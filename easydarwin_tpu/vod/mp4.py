"""MP4/MOV box parser with sample-table walkers.

Covers the box set the reference's ``QTFileLib`` implements as ``QTAtom_*``
classes (stco/stsc/stsd/stss/stsz/stts/tkhd/mdhd/mvhd + co64/ctts/hdlr),
re-designed as flat numpy sample tables instead of per-atom object trees:
one pass builds, per track, arrays of (file offset, size, dts, ctts offset,
sync flag) — the natural layout both for the paced sender and for future
batch staging to the device.

Also parses hint tracks ('hint' handler, 'rtp ' sample description) so
pre-hinted files stream via their own packetization instructions, like
``QTHintTrack``.
"""

from __future__ import annotations

import io
import struct
import mmap
import os
import threading
from dataclasses import dataclass, field

import numpy as np

_CONTAINERS = {b"moov", b"trak", b"mdia", b"minf", b"stbl", b"edts",
               b"udta", b"dinf", b"tref"}


_SHARED_LOCK = threading.Lock()
_DETACHED = object()                   # replaced-on-disk, still-referenced
_SHARED: "dict[str, Mp4File]" = {}     # path -> parsed instance (refs>=0)
_SHARED_IDLE_KEEP = 8                  # parsed files kept warm at 0 refs


def open_shared(path: str) -> "Mp4File":
    """Refcounted shared instance per (path, mtime, size): concurrent
    players of one file share the parse and the mapping; a replaced
    file (changed stat) gets a fresh instance while old readers keep
    their old mapping until release."""
    st = os.stat(path)
    key = (st.st_size, st.st_mtime_ns)
    with _SHARED_LOCK:
        f = _SHARED.get(path)
        if f is not None and f.stat_key == key:
            f._refs += 1
            return f
    fresh = Mp4File(path)              # parse outside the lock
    fresh._shared_key = path
    with _SHARED_LOCK:
        cur = _SHARED.get(path)
        if cur is not None and cur.stat_key == key:
            cur._refs += 1             # raced: adopt the winner
            fresh._shared_key = None
            fresh._close_now()
            return cur
        if cur is not None and cur._refs == 0:
            cur._shared_key = None
            cur._close_now()           # stale, unreferenced: evict now
        elif cur is not None:
            # stale but in use: detach from the by-path table, but KEEP
            # refcounted closing (a bare _shared_key=None would make the
            # FIRST holder's close() unmap under the others' reads)
            cur._shared_key = _DETACHED
        _SHARED[path] = fresh
        fresh._refs = 1
        return fresh


class Mp4Error(ValueError):
    pass


@dataclass
class Box:
    kind: bytes
    start: int           # offset of the box header in the file
    size: int            # total size incl. header
    header: int          # header length (8 or 16)
    children: list["Box"] = field(default_factory=list)

    @property
    def body(self) -> tuple[int, int]:
        return self.start + self.header, self.size - self.header

    def find(self, *path: bytes) -> "Box | None":
        cur: Box | None = self
        for kind in path:
            cur = next((c for c in cur.children if c.kind == kind), None)
            if cur is None:
                return None
        return cur

    def find_all(self, kind: bytes) -> list["Box"]:
        return [c for c in self.children if c.kind == kind]


def _scan(f: io.BufferedReader, start: int, end: int) -> list[Box]:
    boxes = []
    pos = start
    while pos + 8 <= end:
        f.seek(pos)
        hdr = f.read(8)
        if len(hdr) < 8:
            break
        size, kind = struct.unpack(">I4s", hdr)
        header = 8
        if size == 1:
            big = f.read(8)
            size = struct.unpack(">Q", big)[0]
            header = 16
        elif size == 0:
            size = end - pos
        if size < header or pos + size > end:
            break
        box = Box(kind, pos, size, header)
        if kind in _CONTAINERS:
            box.children = _scan(f, pos + header, pos + size)
        boxes.append(box)
        pos += size
    return boxes


@dataclass
class TrackInfo:
    track_id: int = 0
    handler: str = ""            # vide / soun / hint
    timescale: int = 90000
    duration: int = 0
    codec: str = ""              # avc1 / mp4a / ...
    width: int = 0
    height: int = 0
    channels: int = 0
    sample_rate: int = 0
    # codec config
    sps: list[bytes] = field(default_factory=list)
    pps: list[bytes] = field(default_factory=list)
    nal_length_size: int = 4
    audio_config: bytes = b""    # AudioSpecificConfig from esds
    # hint-track linkage
    hint_for: int = 0            # referenced media track id (tref/hint)
    rtp_timescale: int = 0


class Track:
    """One media track: info + flat sample tables."""

    def __init__(self, info: TrackInfo):
        self.info = info
        self.offsets = np.zeros(0, dtype=np.int64)
        self.sizes = np.zeros(0, dtype=np.int64)
        self.dts = np.zeros(0, dtype=np.int64)
        self.ctts = np.zeros(0, dtype=np.int64)
        self.sync = np.zeros(0, dtype=bool)

    @property
    def n_samples(self) -> int:
        return len(self.sizes)

    def duration_sec(self) -> float:
        ts = self.info.timescale or 1
        if self.info.duration:
            return self.info.duration / ts
        if len(self.dts):
            return float(self.dts[-1]) / ts
        return 0.0

    def sample_time_sec(self, i: int) -> float:
        return float(self.dts[i]) / (self.info.timescale or 1)

    def sync_sample_at_or_before(self, i: int) -> int:
        if not self.sync.any():
            return i
        idx = np.nonzero(self.sync[:i + 1])[0]
        return int(idx[-1]) if len(idx) else 0


class Mp4File:
    """Parsed movie + mmap-backed sample reader.

    The reference keeps an FD cache because hundreds of concurrent VOD
    readers hammer buffered file IO (``OSFileSource.cpp:634``); here the
    sample data path is a shared read-only ``mmap`` instead — sample
    reads are stateless slices (no per-reader seek cursor, no per-reader
    buffer), and the parse-time file object is closed right after
    mapping, so N concurrent players of one file cost ONE parse, ONE
    mapping and ONE descriptor (the mapping's own dup).
    ``open_shared``/``close`` refcount one parsed instance per
    (path, mtime, size) — the FD-cache role, modernized."""

    def __init__(self, path: str):
        self.path = path
        self._refs = 0                 # managed by open_shared/close
        self._shared_key = None
        self._f = open(path, "rb")
        try:
            st = os.fstat(self._f.fileno())
            self.stat_key = (st.st_size, st.st_mtime_ns)
            size = st.st_size
            if size == 0:
                raise Mp4Error("empty file")
            self._mm = mmap.mmap(self._f.fileno(), 0,
                                 access=mmap.ACCESS_READ)
            self.boxes = _scan(self._f, 0, size)
            moov = next((b for b in self.boxes if b.kind == b"moov"),
                        None)
            if moov is None:
                raise Mp4Error("no moov box")
            self.timescale, self.duration = self._parse_mvhd(moov)
            self.tracks: list[Track] = []
            for trak in moov.find_all(b"trak"):
                t = self._parse_trak(trak)
                if t is not None:
                    self.tracks.append(t)
        finally:
            self._f.close()            # the mapping keeps the pages alive
            self._f = None

    def close(self):
        # branch on _shared_key ONLY under the lock: open_shared may be
        # detaching this instance concurrently, and an unlocked read
        # could route a detached (replaced-but-referenced) instance down
        # the by-path release path, leaking its mapping forever
        with _SHARED_LOCK:
            key = self._shared_key
            if key is not None:
                self._refs -= 1
                if self._refs > 0:
                    return
                if key is not _DETACHED:
                    # still the by-path entry: keep a few warm for
                    # reopen bursts; evict beyond the cap
                    idle = [p for p, v in _SHARED.items()
                            if v._refs == 0]
                    while len(idle) > _SHARED_IDLE_KEEP:
                        victim = idle.pop(0)
                        v = _SHARED.pop(victim)
                        v._shared_key = None
                        v._close_now()
                    return
                self._shared_key = None   # detached, last holder: unmap
        self._close_now()

    def _close_now(self):
        if self._mm is not None:
            self._mm.close()
            self._mm = None

    # -- readers -----------------------------------------------------------
    def _read_at(self, off: int, n: int) -> bytes:
        if self._f is not None:        # during parse
            self._f.seek(off)
            return self._f.read(n)
        return bytes(self._mm[off:off + n])

    def _full(self, box: Box) -> bytes:
        off, n = box.body
        return self._read_at(off, n)

    def read_sample(self, track: Track, i: int) -> bytes:
        return self._read_at(int(track.offsets[i]), int(track.sizes[i]))

    # -- top-level parses --------------------------------------------------
    def _parse_mvhd(self, moov: Box) -> tuple[int, int]:
        mvhd = moov.find(b"mvhd")
        if mvhd is None:
            return 90000, 0
        b = self._full(mvhd)
        version = b[0]
        if version == 1:
            ts, dur = struct.unpack_from(">IQ", b, 20)
        else:
            ts, dur = struct.unpack_from(">II", b, 12)
        return ts, dur

    def _parse_trak(self, trak: Box) -> Track | None:
        info = TrackInfo()
        tkhd = trak.find(b"tkhd")
        if tkhd is not None:
            b = self._full(tkhd)
            version = b[0]
            info.track_id = struct.unpack_from(
                ">I", b, 20 if version == 1 else 12)[0]
        mdia = trak.find(b"mdia")
        if mdia is None:
            return None
        mdhd = mdia.find(b"mdhd")
        if mdhd is not None:
            b = self._full(mdhd)
            if b[0] == 1:
                info.timescale, info.duration = struct.unpack_from(">IQ", b, 20)
            else:
                info.timescale, info.duration = struct.unpack_from(">II", b, 12)
        hdlr = mdia.find(b"hdlr")
        if hdlr is not None:
            b = self._full(hdlr)
            info.handler = b[8:12].decode("latin-1")
        stbl = mdia.find(b"minf", b"stbl")
        if stbl is None:
            return None
        self._parse_stsd(stbl, info)
        # hint reference
        tref = trak.find(b"tref")
        if tref is not None:
            hint = tref.find(b"hint")
            if hint is not None:
                refs = self._full(hint)
                if len(refs) >= 4:
                    info.hint_for = struct.unpack_from(">I", refs, 0)[0]
        track = Track(info)
        self._build_sample_tables(stbl, track)
        return track

    # -- stsd (codec config) ----------------------------------------------
    def _parse_stsd(self, stbl: Box, info: TrackInfo) -> None:
        stsd = stbl.find(b"stsd")
        if stsd is None:
            return
        b = self._full(stsd)
        n = struct.unpack_from(">I", b, 4)[0]
        off = 8
        for _ in range(n):
            if off + 8 > len(b):
                break
            esize, kind = struct.unpack_from(">I4s", b, off)
            info.codec = kind.decode("latin-1").strip()
            entry = b[off + 8:off + esize]
            if kind == b"avc1" and len(entry) >= 78:
                info.width, info.height = struct.unpack_from(">HH", entry, 24)
                self._parse_avcc(entry[78:], info)
            elif kind == b"mp4a" and len(entry) >= 28:
                info.channels = struct.unpack_from(">H", entry, 16)[0]
                info.sample_rate = struct.unpack_from(">I", entry, 24)[0] >> 16
                self._parse_esds(entry[28:], info)
            elif kind == b"rtp ":
                # hint sample entry: u32 hinttrackversion/highestcompat,
                # then maxpacketsize, then additionaldata boxes (tims = rtp
                # timescale)
                if len(entry) >= 16:
                    pos = 12
                    while pos + 8 <= len(entry):
                        bs, bk = struct.unpack_from(">I4s", entry, pos)
                        if bk == b"tims" and bs >= 12:
                            info.rtp_timescale = struct.unpack_from(
                                ">I", entry, pos + 8)[0]
                        if bs < 8:
                            break
                        pos += bs
            off += max(esize, 8)

    @staticmethod
    def _parse_avcc_bytes(data: bytes, info: TrackInfo) -> None:
        if len(data) < 7:
            return
        info.nal_length_size = (data[4] & 0x03) + 1
        n_sps = data[5] & 0x1F
        pos = 6
        for _ in range(n_sps):
            if pos + 2 > len(data):
                return
            ln = struct.unpack_from(">H", data, pos)[0]
            pos += 2
            info.sps.append(data[pos:pos + ln])
            pos += ln
        if pos >= len(data):
            return
        n_pps = data[pos]
        pos += 1
        for _ in range(n_pps):
            if pos + 2 > len(data):
                return
            ln = struct.unpack_from(">H", data, pos)[0]
            pos += 2
            info.pps.append(data[pos:pos + ln])
            pos += ln

    def _parse_avcc(self, extensions: bytes, info: TrackInfo) -> None:
        pos = 0
        while pos + 8 <= len(extensions):
            size, kind = struct.unpack_from(">I4s", extensions, pos)
            if size < 8:
                break
            if kind == b"avcC":
                self._parse_avcc_bytes(extensions[pos + 8:pos + size], info)
                return
            pos += size

    def _parse_esds(self, extensions: bytes, info: TrackInfo) -> None:
        pos = 0
        while pos + 8 <= len(extensions):
            size, kind = struct.unpack_from(">I4s", extensions, pos)
            if size < 8:
                break
            if kind == b"esds":
                body = extensions[pos + 12:pos + size]   # skip version/flags
                info.audio_config = self._find_decoder_specific(body)
                return
            pos += size

    @staticmethod
    def _find_decoder_specific(body: bytes) -> bytes:
        """Walk the ES descriptor tree for tag 0x05 (DecoderSpecificInfo)."""
        def read_len(b, p):
            ln = 0
            while p < len(b):
                c = b[p]
                p += 1
                ln = (ln << 7) | (c & 0x7F)
                if not c & 0x80:
                    break
            return ln, p

        p = 0
        stack = [(body, 0)]
        while stack:
            b, p = stack.pop()
            while p < len(b):
                tag = b[p]
                ln, q = read_len(b, p + 1)
                payload = b[q:q + ln]
                if tag == 0x05:
                    return payload
                if tag == 0x03:       # ES_Descriptor: skip ES_ID+flags
                    stack.append((payload, 3))
                elif tag == 0x04:     # DecoderConfig: skip 13 fixed bytes
                    stack.append((payload, 13))
                p = q + ln
        return b""

    # -- sample tables -----------------------------------------------------
    def _build_sample_tables(self, stbl: Box, track: Track) -> None:
        def table(kind: bytes) -> bytes | None:
            box = stbl.find(kind)
            return self._full(box) if box else None

        stsz = table(b"stsz")
        if stsz is None:
            return
        uniform, count = struct.unpack_from(">II", stsz, 4)
        if uniform:
            sizes = np.full(count, uniform, dtype=np.int64)
        else:
            sizes = np.frombuffer(stsz, dtype=">u4", count=count,
                                  offset=12).astype(np.int64)
        # chunk offsets
        stco = table(b"stco")
        co64 = table(b"co64")
        if stco is not None:
            n_chunks = struct.unpack_from(">I", stco, 4)[0]
            chunk_off = np.frombuffer(stco, dtype=">u4", count=n_chunks,
                                      offset=8).astype(np.int64)
        elif co64 is not None:
            n_chunks = struct.unpack_from(">I", co64, 4)[0]
            chunk_off = np.frombuffer(co64, dtype=">u8", count=n_chunks,
                                      offset=8).astype(np.int64)
        else:
            return
        # sample→chunk map
        stsc = table(b"stsc")
        offsets = np.zeros(count, dtype=np.int64)
        if stsc is not None:
            n_ent = struct.unpack_from(">I", stsc, 4)[0]
            ent = np.frombuffer(stsc, dtype=">u4", count=n_ent * 3,
                                offset=8).reshape(n_ent, 3).astype(np.int64)
            s = 0
            for e in range(n_ent):
                first_chunk = ent[e, 0] - 1
                per_chunk = ent[e, 1]
                last_chunk = (ent[e + 1, 0] - 1 if e + 1 < n_ent
                              else len(chunk_off))
                for c in range(first_chunk, last_chunk):
                    if s >= count:
                        break
                    off = chunk_off[c]
                    for _ in range(per_chunk):
                        if s >= count:
                            break
                        offsets[s] = off
                        off += sizes[s]
                        s += 1
        # decode timestamps
        stts = table(b"stts")
        dts = np.zeros(count, dtype=np.int64)
        if stts is not None:
            n_ent = struct.unpack_from(">I", stts, 4)[0]
            ent = np.frombuffer(stts, dtype=">u4", count=n_ent * 2,
                                offset=8).reshape(n_ent, 2).astype(np.int64)
            t = 0
            s = 0
            for e in range(n_ent):
                for _ in range(int(ent[e, 0])):
                    if s >= count:
                        break
                    dts[s] = t
                    t += int(ent[e, 1])
                    s += 1
        # composition offsets
        ctts = table(b"ctts")
        cts = np.zeros(count, dtype=np.int64)
        if ctts is not None:
            n_ent = struct.unpack_from(">I", ctts, 4)[0]
            ent = np.frombuffer(ctts, dtype=">i4", count=n_ent * 2,
                                offset=8).reshape(n_ent, 2).astype(np.int64)
            s = 0
            for e in range(n_ent):
                for _ in range(int(ent[e, 0])):
                    if s >= count:
                        break
                    cts[s] = int(ent[e, 1])
                    s += 1
        # sync samples
        stss = table(b"stss")
        sync = np.ones(count, dtype=bool)
        if stss is not None:
            sync[:] = False
            n_ent = struct.unpack_from(">I", stss, 4)[0]
            idx = np.frombuffer(stss, dtype=">u4", count=n_ent,
                                offset=8).astype(np.int64) - 1
            sync[idx[idx < count]] = True
        track.offsets, track.sizes = offsets, sizes
        track.dts, track.ctts, track.sync = dts, cts, sync

    # -- convenience -------------------------------------------------------
    def video_track(self) -> Track | None:
        return next((t for t in self.tracks if t.info.handler == "vide"), None)

    def audio_track(self) -> Track | None:
        return next((t for t in self.tracks if t.info.handler == "soun"), None)

    def hint_tracks(self) -> list[Track]:
        return [t for t in self.tracks if t.info.handler == "hint"]
