"""Sample → RTP packetization + SDP generation for VOD.

Reference parity: ``QTFileLib``'s hint-track packetizer (``QTHintTrack.cpp``
— hint samples carried packetization instructions) and the SDP the
reference's ``DoDescribe`` emits (``QTSSFileModule.cpp:606``).  Modern files
are rarely hinted, so the primary path self-packetizes: H.264 AVCC →
RFC 6184 (single NAL / FU-A, SPS/PPS re-injected before each IDR), AAC →
RFC 3640 mpeg4-generic.  Pre-hinted files use ``HintInterpreter``, which
executes the 'rtp ' constructor programs like ``QTHintTrack``.
"""

from __future__ import annotations

import base64
import struct
from dataclasses import dataclass

from ..protocol import nalu, rtp, sdp
from .mp4 import Mp4File, Track

RTP_CLOCK_VIDEO = 90000


def split_avcc(sample: bytes, nal_length_size: int = 4) -> list[bytes]:
    """Split an AVCC sample (length-prefixed) into NAL units."""
    out = []
    pos = 0
    n = len(sample)
    while pos + nal_length_size <= n:
        ln = int.from_bytes(sample[pos:pos + nal_length_size], "big")
        pos += nal_length_size
        if ln <= 0 or pos + ln > n:
            break
        out.append(sample[pos:pos + ln])
        pos += ln
    return out


@dataclass
class PacketizerState:
    seq: int = 1
    ssrc: int = 0
    payload_type: int = 96


class H264Packetizer:
    """One client's H.264 track packetizer (RFC 6184, mode 1)."""

    def __init__(self, track: Track, *, ssrc: int, seq_start: int = 1,
                 payload_type: int = 96, mtu: int = 1400):
        self.track = track
        self.state = PacketizerState(seq=seq_start, ssrc=ssrc,
                                     payload_type=payload_type)
        self.mtu = mtu

    def rtp_timestamp(self, i: int) -> int:
        info = self.track.info
        t = int(self.track.dts[i]) + int(self.track.ctts[i])
        return int(t * RTP_CLOCK_VIDEO // max(info.timescale, 1)) & 0xFFFFFFFF

    def packetize_sample(self, data: bytes, i: int) -> list[bytes]:
        info = self.track.info
        ts = self.rtp_timestamp(i)
        nals = split_avcc(data, info.nal_length_size)
        if bool(self.track.sync[i]):
            nals = list(info.sps) + list(info.pps) + nals
        pkts: list[bytes] = []
        for k, nal in enumerate(nals):
            last_nal = k == len(nals) - 1
            sub = nalu.packetize_h264(
                nal, seq=self.state.seq, timestamp=ts,
                ssrc=self.state.ssrc, payload_type=self.state.payload_type,
                mtu=self.mtu, marker_on_last=last_nal)
            self.state.seq = (self.state.seq + len(sub)) & 0xFFFF
            pkts.extend(sub)
        return pkts


class AacPacketizer:
    """RFC 3640 mpeg4-generic: one AU per packet, 13/3-bit AU header."""

    def __init__(self, track: Track, *, ssrc: int, seq_start: int = 1,
                 payload_type: int = 97):
        self.track = track
        self.state = PacketizerState(seq=seq_start, ssrc=ssrc,
                                     payload_type=payload_type)

    def rtp_timestamp(self, i: int) -> int:
        return int(self.track.dts[i]) & 0xFFFFFFFF   # clock == sample rate

    def packetize_sample(self, data: bytes, i: int) -> list[bytes]:
        au_header = struct.pack(">HH", 16, (len(data) << 3) & 0xFFFF)
        pkt = rtp.RtpPacket(
            payload_type=self.state.payload_type, seq=self.state.seq,
            timestamp=self.rtp_timestamp(i), ssrc=self.state.ssrc,
            marker=True, payload=au_header + data).to_bytes()
        self.state.seq = (self.state.seq + 1) & 0xFFFF
        return [pkt]


class HintInterpreter:
    """Executes hint-sample constructor programs ('rtp ' tracks).

    Hint sample layout (QTHintTrack's input): u16 packet count, u16
    reserved, then per packet: i32 relative-time, u16 rtp-header-bits,
    u16 seq, u16 flags, u16 constructor count, then 16-byte constructors:
    type 0 noop / 1 immediate / 2 sample-range / 3 sample-description.
    """

    def __init__(self, file: Mp4File, hint_track: Track, media_track: Track,
                 *, ssrc: int, payload_type: int = 96):
        self.file = file
        self.hint = hint_track
        self.media = media_track
        self.ssrc = ssrc
        self.payload_type = payload_type

    def packetize_sample(self, i: int) -> list[bytes]:
        data = self.file.read_sample(self.hint, i)
        if len(data) < 4:
            return []
        n_pkts = struct.unpack_from(">H", data, 0)[0]
        pos = 4
        out = []
        for _ in range(n_pkts):
            if pos + 12 > len(data):
                break
            _rel, hdr_bits, seq, _flags, n_cons = struct.unpack_from(
                ">iHHHH", data, pos)
            pos += 12
            payload = bytearray()
            for _c in range(n_cons):
                if pos + 16 > len(data):
                    break
                ctype = data[pos]
                if ctype == 1:      # immediate
                    ln = data[pos + 1]
                    payload += data[pos + 2:pos + 2 + min(ln, 14)]
                elif ctype == 2:    # sample range from the media track
                    _tref = data[pos + 1]
                    ln, samplenum, off = struct.unpack_from(">HII", data,
                                                            pos + 2)
                    if 1 <= samplenum <= self.media.n_samples:
                        sample = self.file.read_sample(self.media,
                                                       samplenum - 1)
                        payload += sample[off:off + ln]
                pos += 16
            ts_scale = self.hint.info.rtp_timescale or RTP_CLOCK_VIDEO
            ts = int(int(self.hint.dts[i]) * ts_scale
                     // max(self.hint.info.timescale, 1))
            out.append(rtp.RtpPacket(
                payload_type=self.payload_type, seq=seq,
                timestamp=ts & 0xFFFFFFFF, ssrc=self.ssrc,
                marker=bool(hdr_bits & 0x0080),
                payload=bytes(payload)).to_bytes())
        return out


def sdp_for_file(f: Mp4File, *, name: str = "") -> sdp.SessionDescription:
    """Build the DESCRIBE answer for a file (QTSSFileModule::DoDescribe)."""
    sd = sdp.SessionDescription(session_name=name or "vod")
    track_no = 0
    v = f.video_track()
    if v is not None and v.info.codec == "avc1":
        track_no += 1
        info = sdp.StreamInfo(media_type="video", payload_type=96,
                              payload_name="H264/90000", codec="H264",
                              clock_rate=RTP_CLOCK_VIDEO, track_id=track_no)
        fmtp = "96 packetization-mode=1"
        if v.info.sps:
            plid = v.info.sps[0][1:4].hex().upper() if len(v.info.sps[0]) >= 4 \
                else "42001F"
            props = ",".join(base64.b64encode(x).decode()
                             for x in (v.info.sps + v.info.pps))
            fmtp += f";profile-level-id={plid};sprop-parameter-sets={props}"
        info.fmtp = fmtp
        sd.streams.append(info)
    a = f.audio_track()
    if a is not None and a.info.codec == "mp4a":
        track_no += 1
        rate = a.info.sample_rate or a.info.timescale
        ch = a.info.channels or 2
        info = sdp.StreamInfo(media_type="audio", payload_type=97,
                              payload_name=f"MPEG4-GENERIC/{rate}/{ch}",
                              codec="MPEG4-GENERIC", clock_rate=rate,
                              track_id=track_no)
        cfg = a.info.audio_config.hex().upper() or "1190"
        info.fmtp = (f"97 streamtype=5;profile-level-id=1;mode=AAC-hbr;"
                     f"sizelength=13;indexlength=3;indexdeltalength=3;"
                     f"config={cfg}")
        sd.streams.append(info)
    rng = max((t.duration_sec() for t in f.tracks), default=0.0)
    if rng:
        sd.attributes["range"] = f"npt=0-{rng:.3f}"
    return sd
