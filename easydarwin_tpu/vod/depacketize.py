"""RTP → H.264 access units (the packetizer's inverse).

Feeds the recorder (RtspRecordModule flow) and, later, the transcode/HLS
paths.  Handles single NAL units, STAP-A aggregation, and FU-A fragments
(RFC 6184); groups NALs into access units on RTP timestamp change or
marker, and captures SPS/PPS out-of-band for the AVCC config record.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..protocol import nalu, rtp


@dataclass
class AccessUnit:
    timestamp: int                       # RTP timestamp (90 kHz)
    nals: list[bytes] = field(default_factory=list)

    @property
    def is_idr(self) -> bool:
        return any((n[0] & 0x1F) == 5 for n in self.nals if n)

    def to_avcc(self, length_size: int = 4) -> bytes:
        out = bytearray()
        for n in self.nals:
            out += len(n).to_bytes(length_size, "big") + n
        return bytes(out)


class H264Depacketizer:
    """Push RTP packets (in seq order), pop completed access units."""

    def __init__(self):
        self.sps: bytes | None = None
        self.pps: bytes | None = None
        self._current: AccessUnit | None = None
        self._fu_buf: bytearray | None = None
        self._fu_type = 0
        self._done: list[AccessUnit] = []
        self.packets = 0
        self.malformed = 0

    def push(self, packet: bytes) -> None:
        try:
            p = rtp.RtpPacket.parse(packet)
        except rtp.RtpError:
            self.malformed += 1
            return
        self.packets += 1
        if not p.payload:
            return
        if self._current is not None and p.timestamp != self._current.timestamp:
            self._finish()
        if self._current is None:
            self._current = AccessUnit(p.timestamp)
        t = p.payload[0] & 0x1F
        if 1 <= t <= 23:
            self._add_nal(p.payload)
        elif t == nalu.NAL_STAP_A:
            pos = 1
            while pos + 2 <= len(p.payload):
                ln = int.from_bytes(p.payload[pos:pos + 2], "big")
                pos += 2
                if ln == 0 or pos + ln > len(p.payload):
                    self.malformed += 1
                    break
                self._add_nal(p.payload[pos:pos + ln])
                pos += ln
        elif t == nalu.NAL_FU_A and len(p.payload) >= 2:
            ind, hdr = p.payload[0], p.payload[1]
            start, end = hdr & 0x80, hdr & 0x40
            if start:
                self._fu_type = (ind & 0xE0) | (hdr & 0x1F)
                self._fu_buf = bytearray((self._fu_type,))
            if self._fu_buf is not None:
                self._fu_buf += p.payload[2:]
                if end:
                    self._add_nal(bytes(self._fu_buf))
                    self._fu_buf = None
            else:
                self.malformed += 1         # mid-fragment without start
        else:
            self.malformed += 1
        if p.marker:
            self._finish()

    def _add_nal(self, nal: bytes) -> None:
        if not nal:
            return
        t = nal[0] & 0x1F
        if t == nalu.NAL_SPS:
            self.sps = nal
            return                          # config, not sample data
        if t == nalu.NAL_PPS:
            self.pps = nal
            return
        self._current.nals.append(nal)

    def _finish(self) -> None:
        if self._current is not None and self._current.nals:
            self._done.append(self._current)
        self._current = None
        self._fu_buf = None

    def pop_units(self) -> list[AccessUnit]:
        out, self._done = self._done, []
        return out

    def flush(self) -> list[AccessUnit]:
        self._finish()
        return self.pop_units()
