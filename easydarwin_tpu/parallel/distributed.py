"""Multi-host scale-out: the distributed communication backend.

The reference scales across machines with a control plane only — Redis
presence keys plus EasyCMS redirection (``EasyRedisHandler.cpp:177-335``);
each EasyDarwin's data plane is confined to one box.  Here the data plane
itself can span hosts: JAX collectives ride **ICI** inside a slice and
**DCN** across hosts, so a relay fleet can shard sources/subscribers over
a multi-host pod while keeping the same Redis/EasyProtocol control plane
(``cluster/``) for discovery.

Wire-up order on every host of the fleet::

    from easydarwin_tpu.parallel import distributed
    distributed.init_from_env()          # jax.distributed.initialize
    mesh = distributed.make_cluster_mesh(sub=2)   # DCN-aware relay mesh
    step = parallel.mesh.sharded_relay_step(mesh)

Axis placement matters: ``src`` (sources) is the outermost axis and the
only one allowed to cross the DCN boundary — per-source relay math is
embarrassingly parallel, so DCN carries zero steady-state traffic.
``sub``/``win`` collectives (keyframe ``pmax``, fleet ``psum``) stay on
ICI within each host's slice.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import AXES, make_relay_mesh

_initialized = False


def init_from_env(coordinator: str | None = None,
                  num_processes: int | None = None,
                  process_id: int | None = None) -> bool:
    """Initialize ``jax.distributed`` for multi-host operation.

    Arguments fall back to the standard env vars
    (``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID``).  A no-op (returns False) when neither arguments
    nor env describe a fleet — single-host deployments never pay the
    rendezvous.  On cloud TPU pods where the runtime supplies rendezvous
    metadata, set ``JAX_NUM_PROCESSES`` (or pass any argument) to opt in;
    ``jax.distributed.initialize`` then fills the gaps from metadata.
    Idempotent: repeated calls after success return True.
    """
    global _initialized
    if _initialized:
        return True
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    num_str = os.environ.get("JAX_NUM_PROCESSES")
    num_processes = num_processes if num_processes is not None else (
        int(num_str) if num_str else None)
    pid_str = os.environ.get("JAX_PROCESS_ID")
    process_id = process_id if process_id is not None else (
        int(pid_str) if pid_str else None)
    # a process id alone can never describe a fleet — require a coordinator
    # or a process count (argument or env) before paying the rendezvous
    if coordinator is None and num_processes is None:
        return False
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    return True


def make_cluster_mesh(*, sub: int = 1, win: int = 1,
                      devices=None) -> Mesh:
    """Relay mesh for the whole fleet, DCN-aware.

    Devices are laid out host-major: ``src`` is factored as
    ``(num_hosts × local_src)`` so slicing the ``src`` axis never splits a
    host's devices across a DCN boundary, and the ``sub``/``win``
    collectives (pmax/psum) always resolve within one host's ICI domain.
    Requires ``sub·win`` to divide each host's local device count.
    """
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    if n % (sub * win):
        raise ValueError(f"{n} devices not divisible by sub*win={sub * win}")
    # host-major ordering: jax.devices() already groups by process; make it
    # explicit so a reordered backend cannot interleave hosts inside a slice
    devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    # every host must contribute whole (sub × win) tiles, or a src row
    # would straddle hosts and put sub/win collectives on DCN
    per_host: dict[int, int] = {}
    for d in devices:
        per_host[d.process_index] = per_host.get(d.process_index, 0) + 1
    for proc, cnt in per_host.items():
        if cnt % (sub * win):
            raise ValueError(
                f"host {proc} has {cnt} devices, not divisible by "
                f"sub*win={sub * win}; a src row would cross the DCN "
                f"boundary (see module doc)")
    arr = np.array(devices).reshape(n // (sub * win), sub, win)
    return Mesh(arr, AXES)


def process_span(mesh: Mesh) -> dict:
    """Describe how the mesh maps onto processes (for REST getserverinfo
    and logs): total hosts, local device count, and whether any non-src
    axis crosses a process boundary (it never should — see module doc)."""
    devs = mesh.devices
    procs = {d.process_index for d in devs.flat}
    cross = False
    for i in range(devs.shape[0]):
        if len({d.process_index for d in devs[i].flat}) > 1:
            cross = True
    return {"num_processes": len(procs),
            "local_devices": jax.local_device_count(),
            "non_src_axis_crosses_hosts": cross,
            "mesh_shape": dict(zip(AXES, devs.shape))}


def mesh_summary(mesh: Mesh) -> dict[str, str]:
    """``process_span`` flattened to the string-valued dict shape the
    REST ``getserverinfo`` document uses — the live-server surface for
    the mesh→process mapping (previously reachable only from the
    dryrun).  Operators read it to confirm the serving mesh matches the
    deployment: how many hosts, devices per host, the (src, sub, win)
    factorization, and whether any non-src axis crosses a DCN boundary
    (it never should — see the module doc)."""
    span = process_span(mesh)
    shape = span["mesh_shape"]
    return {
        "MeshDevices": str(int(mesh.devices.size)),
        "MeshShape": ",".join(f"{a}={shape[a]}" for a in AXES),
        "MeshNumProcesses": str(span["num_processes"]),
        "MeshLocalDevices": str(span["local_devices"]),
        "MeshNonSrcAxisCrossesHosts":
            "1" if span["non_src_axis_crosses_hosts"] else "0",
    }


__all__ = ["init_from_env", "make_cluster_mesh", "make_relay_mesh",
           "mesh_summary", "process_span"]
