"""Sharded relay step over a (src, sub, win) device mesh.

Sharding layout (all specs in terms of mesh axes ``src``/``sub``/``win``):

====================  ====================  =============================
array                 shape                 PartitionSpec
====================  ====================  =============================
prefix                [N, P, W]             (src, win, None)
length / age          [N, P]                (src, win)
out_state             [N, S, 5]             (src, sub, None)
bucket_of_output      [N, S]                (src, sub)
headers (out)         [N, S, P, 12]         (src, sub, win, None)
mask (out)            [N, S, P]             (src, sub, win)
newest_keyframe (out) [N]                   (src,)  — pmax over win
====================  ====================  =============================

Fan-out math is (sub × win)-local: each chip renders headers for its
subscriber slice over its packet-window slice with zero communication.  The
only cross-chip dependencies are the keyframe scan (max over the ``win``
axis → ``jax.lax.pmax``) and fleet-level counters (``psum``), both tiny
scalars on ICI.  This is the honest mapping of the reference's scale axes
(SURVEY §2.6): session-parallelism → ``src``, bucket fan-out → ``sub``,
the packet/GOP buffer window → ``win``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import fanout as fanout_ops
from ..ops import parse as parse_ops

try:                                    # jax >= 0.4.38 exports it top-level
    _shard_map = jax.shard_map
except AttributeError:                  # older: the experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

AXES = ("src", "sub", "win")


def make_relay_mesh(devices=None, *, src: int | None = None,
                    sub: int | None = None, win: int | None = None) -> Mesh:
    """Build a 3-axis relay mesh over ``devices`` (default: all).

    Unspecified axis sizes are inferred: ``src`` absorbs remaining devices,
    ``sub``/``win`` default to 1 unless given.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    sub = sub or 1
    win = win or 1
    src = src or n // (sub * win)
    if src * sub * win != n:
        raise ValueError(f"mesh {src}x{sub}x{win} != {n} devices")
    return Mesh(devices.reshape(src, sub, win), AXES)


def make_megabatch_mesh(n_devices: int = 0, devices=None) -> Mesh | None:
    """The megabatch scheduler's serving mesh: ``src``-only (streams
    shard over devices; ``sub``/``win`` stay whole because the stacked
    pass is already one fused window per stream).

    ``n_devices``: 0 = every local device, N = the first N local
    devices.  Returns ``None`` when fewer than two devices would
    participate — the caller then keeps the single-device dispatch path
    (a 1-device box degrades to exactly the pre-mesh behavior)."""
    import jax
    devices = list(devices) if devices is not None else jax.local_devices()
    n = len(devices) if n_devices <= 0 else min(n_devices, len(devices))
    if n < 2:
        return None
    return make_relay_mesh(devices[:n], src=n, sub=1, win=1)


def _local_step(prefix, length, age, out_state, buckets, bucket_delay_ms):
    """Per-shard computation: vmap the single-source device step over the
    local source block, then reduce the keyframe scan across ``win``."""

    def one_source(pre, ln, ag, st, bk):
        fields = parse_ops.parse_packets(pre, ln)
        headers = fanout_ops.fanout_headers(pre[:, :2], fields["seq"],
                                            fields["timestamp"], st)
        mask = fanout_ops.eligibility(ag, bk, bucket_delay_ms)
        valid = ln > 0
        kf = fields["keyframe_first"] & valid
        idx = jnp.arange(kf.shape[0], dtype=jnp.int32)
        local_kf = jnp.max(jnp.where(kf, idx, -1))
        return headers, mask & valid[None, :], local_kf

    headers, mask, local_kf = jax.vmap(one_source)(
        prefix, length, age, out_state, buckets)
    # win-axis shards see different window slices: offset local indices by
    # the shard's base, then take the global max over the win axis.
    win_idx = jax.lax.axis_index("win").astype(jnp.int32)
    p_local = prefix.shape[1]
    global_kf = jnp.where(local_kf >= 0, local_kf + win_idx * p_local, -1)
    global_kf = jax.lax.pmax(global_kf, "win")
    # fleet counter: total eligible sends this pass (psum over everything) —
    # feeds the REST getserverinfo load gauge without a host gather.
    eligible = jnp.sum(mask.astype(jnp.int32))
    total_eligible = jax.lax.psum(eligible, AXES)
    return headers, mask, global_kf, total_eligible


def sharded_relay_step(mesh: Mesh, bucket_delay_ms: int = 73):
    """Build the jitted multi-chip relay step for ``mesh``.

    Returns ``fn(prefix, length, age, out_state, buckets)`` →
    ``(headers, mask, newest_keyframe, total_eligible)``.
    """
    in_specs = (P("src", "win", None), P("src", "win"), P("src", "win"),
                P("src", "sub", None), P("src", "sub"))
    out_specs = (P("src", "sub", "win", None), P("src", "sub", "win"),
                 P("src"), P())
    step = _shard_map(
        functools.partial(_local_step, bucket_delay_ms=bucket_delay_ms),
        mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    return jax.jit(step)


def shard_args(mesh: Mesh, prefix, length, age, out_state, buckets):
    """device_put host arrays with the layout sharded_relay_step expects."""
    specs = (P("src", "win", None), P("src", "win"), P("src", "win"),
             P("src", "sub", None), P("src", "sub"))
    return tuple(jax.device_put(a, NamedSharding(mesh, s))
                 for a, s in zip((prefix, length, age, out_state, buckets),
                                 specs))


def example_batch(n_src=4, n_sub=8, n_pkt=32, width=parse_ops.PARSE_PREFIX,
                  seed=0):
    """Synthetic well-formed relay batch (H.264 single-NAL packets with
    periodic IDRs) for compile checks, dry runs and benches."""
    rng = np.random.default_rng(seed)
    prefix = np.zeros((n_src, n_pkt, width), dtype=np.uint8)
    length = np.full((n_src, n_pkt), 200, dtype=np.int32)
    prefix[:, :, 0] = 0x80                      # V=2
    prefix[:, :, 1] = 96                        # PT=96
    seqs = np.arange(n_pkt, dtype=np.uint16)
    prefix[:, :, 2] = (seqs >> 8)[None, :]
    prefix[:, :, 3] = (seqs & 0xFF)[None, :]
    ts = (np.arange(n_pkt, dtype=np.uint32) * 3000)
    for i in range(4):
        prefix[:, :, 4 + i] = ((ts >> (8 * (3 - i))) & 0xFF)[None, :]
    ssrc = rng.integers(0, 2**32, size=n_src, dtype=np.uint32)
    for i in range(4):
        prefix[:, :, 8 + i] = ((ssrc >> (8 * (3 - i))) & 0xFF)[:, None]
    # NAL header: IDR every 16th packet, else non-IDR slice
    nal = np.where(np.arange(n_pkt) % 16 == 0, (3 << 5) | 5, (3 << 5) | 1)
    prefix[:, :, 12] = nal[None, :]
    age = np.full((n_src, n_pkt), 500, dtype=np.int32)
    out_state = np.zeros((n_src, n_sub, fanout_ops.STATE_COLS), dtype=np.uint32)
    out_state[:, :, 0] = rng.integers(0, 2**32, size=(n_src, n_sub))
    out_state[:, :, 3] = rng.integers(0, 2**16, size=(n_src, n_sub))
    buckets = (np.arange(n_sub, dtype=np.int32) // 16)[None, :].repeat(n_src, 0)
    return prefix, length, age, out_state, buckets
