"""Multi-chip scale-out: mesh construction + sharded relay step.

The reference scales across *machines* with Redis presence + EasyCMS
redirection (SURVEY §5, `EasyRedisHandler.cpp:177-335`) and across *cores*
with its task-thread pool.  Within a TPU pod the analogous axes are native
mesh dimensions (SURVEY §2.6 mapping):

* ``src``  — relay sources sharded across chips (the data-parallel axis);
* ``sub``  — subscriber blocks sharded across chips (the tensor/fan-out
  axis: each chip renders headers for its slice of subscribers);
* ``win``  — the packet window sharded across chips (the sequence-parallel
  axis: the GOP/keyframe scan becomes a ``pmax`` collective over ``win``).

All collectives ride ICI inside a pod; the Redis/JSON control plane is kept
unchanged (it is orthogonal to the data path) for multi-host DCN scale-out.
"""

from .mesh import (make_megabatch_mesh, make_relay_mesh,  # noqa: F401
                   sharded_relay_step, example_batch)
from .distributed import (init_from_env, make_cluster_mesh,  # noqa: F401
                          mesh_summary, process_span)
