"""Megabatch-on-mesh throughput harness (ISSUE 7).

Drives the cross-stream megabatch scheduler over REAL relay streams and
real UDP egress in two interleaved modes — bucket dispatch sharded over
a ``(src)``-axis device mesh vs the single-device dispatch — and
reports packets/s for both plus the scaling efficiency of the mesh.
One harness, three callers:

* ``bench.py`` — the ``extra.multichip`` section (in-process when the
  box has devices, via a forced-host-device child otherwise);
* ``__graft_entry__.dryrun_multichip`` — so MULTICHIP_r*.json reports
  packets/s from the mesh, not just "dryrun OK";
* ``tools/soak.py --devices N`` — the sharded multi-source section.

Method: two identical stream sets fed identical bursts, stepped
alternately with the order flipped per wake (the same shared-VM drift
cancellation the bench headline uses).  Every wake pushes a fresh burst
per stream so each mode's scheduler has real windows to stage and a
real stacked pass to dispatch — rewound-bookmark capacity loops would
leave the device idle behind the params cache and measure only egress.
``scaling_efficiency`` = mesh rate / (n_devices × single-device rate):
1.0 = linear.  On the forced-host CPU mesh the "devices" are host
threads sharing the same cores, so efficiency well below 1 is expected
there; the figure is meaningful on real chips.
"""

from __future__ import annotations

import socket
import time

import numpy as np


def _mk_streams(n_streams: int, n_sub: int, addrs, send_fd: int, seed: int):
    from ..protocol import sdp
    from ..relay.fanout import TpuFanoutEngine
    from ..relay.output import CollectingOutput
    from ..relay.stream import RelayStream, StreamSettings

    sdp_txt = ("v=0\r\ns=m\r\nt=0 0\r\nm=video 0 RTP/AVP 96\r\n"
               "a=rtpmap:96 H264/90000\r\na=control:trackID=1\r\n")
    rng = np.random.default_rng(seed)
    streams, engines = [], []
    for s in range(n_streams):
        st = RelayStream(sdp.parse(sdp_txt).streams[0],
                         StreamSettings(bucket_delay_ms=0))
        for i in range(n_sub):
            o = CollectingOutput(ssrc=int(rng.integers(0, 2**32)),
                                 out_seq_start=int(rng.integers(0, 2**16)))
            o.native_addr = addrs[(s * n_sub + i) % len(addrs)]
            st.add_output(o)
        streams.append(st)
        engines.append(TpuFanoutEngine(egress_fd=send_fd))
    return streams, engines


def _precompile(sched, n_streams: int, n_sub: int, burst: int) -> None:
    """Trace the stacked step for the shapes the loop will use BEFORE
    any packet carries an arrival stamp (cold jit must not ride the
    timed window — the PR 3/4 latch discipline)."""
    import jax

    from ..models.relay_pipeline import (megabatch_window_step,
                                         sharded_megabatch_step)
    from ..ops.fanout import STATE_COLS
    from ..ops.staging import ROW_STRIDE, rows_per_shard
    from ..relay.fanout import _pow2
    s_pad = _pow2(n_sub, 8)
    p_pad = _pow2(max(burst, 1), 16)   # one burst staged per wake

    def trace_single(pp: int) -> None:
        b = _pow2(n_streams, 1)
        np.asarray(megabatch_window_step(
            jax.device_put(np.zeros((b, pp, ROW_STRIDE), np.uint8)),
            np.zeros((b, s_pad, STATE_COLS), np.uint32)))

    # the synchronous prime (begin_wake) dispatches the UNSHARDED step
    # over 16-row zero windows in BOTH modes — without this trace a mesh
    # run cold-jits the prime inside the first stamped wake and the
    # compile wall time lands in the ingest→wire histograms the soak's
    # SLO checks read
    trace_single(16)
    if sched.mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        n_dev = len(sched._mesh_devices)
        b_pad = rows_per_shard(n_streams, n_dev) * n_dev
        sharding = NamedSharding(sched.mesh, P("src", None, None))
        win = jax.device_put(np.zeros((b_pad, p_pad, ROW_STRIDE), np.uint8),
                             sharding)
        state = jax.device_put(np.zeros((b_pad, s_pad, STATE_COLS),
                                        np.uint32), sharding)
        np.asarray(sharded_megabatch_step(sched.mesh)(win, state))
    elif p_pad != 16:
        trace_single(p_pad)            # the dispatch shape, if distinct


def device_phase_means() -> dict:
    """Per-device mean milliseconds of the mesh phases recorded so far
    (``megabatch_device_phase_seconds``): {"0": {"h2d": ms, ...}, ...}."""
    from .. import obs
    out: dict[str, dict[str, float]] = {}
    for (device, phase), st in sorted(
            obs.MEGABATCH_DEVICE_PHASE_SECONDS._states.items()):
        if st.count:
            out.setdefault(device, {})[phase] = round(
                st.sum / st.count * 1e3, 4)
    return out


def measure_mesh_throughput(n_devices: int, *, n_streams: int = 16,
                            n_sub: int = 8, burst: int = 24,
                            seconds: float = 4.0, addrs=None) -> dict:
    """Paired mesh-vs-single-device megabatch throughput (module doc).

    Returns the ``extra.multichip`` schema; ``n_devices: 1`` with a
    ``note`` when no mesh could be built (1-device box) — the caller
    still gets valid single-device numbers."""
    from ..relay.megabatch import MegabatchScheduler
    from .mesh import make_megabatch_mesh

    recv = None
    if addrs is None:
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.bind(("127.0.0.1", 0))
        recv.setblocking(False)
        recv.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 1 << 22)
        addrs = [recv.getsockname()]
    send = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    send.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 1 << 22)

    mesh = make_megabatch_mesh(n_devices)
    n_dev = int(mesh.devices.size) if mesh is not None else 1
    sets = {
        "mesh": (_mk_streams(n_streams, n_sub, addrs, send.fileno(), 11),
                 MegabatchScheduler(mesh=mesh)),
        "one": (_mk_streams(n_streams, n_sub, addrs, send.fileno(), 11),
                MegabatchScheduler()),
    }
    pkt = bytes([0x80, 96]) + bytes(10) + bytes(1388)

    def push(streams, seq, t):
        for st in streams:
            for b in range(burst):
                st.push_rtp(pkt[:2] + ((seq + b) & 0xFFFF).to_bytes(2, "big")
                            + pkt[4:], t)
        return seq + burst

    def step(mode, t):
        (streams, engines), sched = sets[mode]
        pairs = list(zip(streams, engines))
        sched.begin_wake(pairs, t)
        for st, eng in pairs:
            eng.step(st, t)
        sched.end_wake(pairs, t)

    def drain_recv():
        if recv is None:
            return
        try:
            while True:
                recv.recv(65536)
        except BlockingIOError:
            pass

    for mode in sets:
        _precompile(sets[mode][1], n_streams, n_sub, burst)
    # prime both modes (GSO probe, rebase latches) outside the timing
    t = int(time.monotonic() * 1000)
    seq = push(sets["mesh"][0][0], 0, t)
    push(sets["one"][0][0], 0, t)
    step("mesh", t)
    step("one", t)
    for _, sched in sets.values():
        sched.drain()
    drain_recv()
    base_sent = {m: sum(e.packets_sent for e in sets[m][0][1])
                 for m in sets}
    elapsed = {m: 0.0 for m in sets}
    wakes = 0
    t_end = time.perf_counter() + seconds
    while time.perf_counter() < t_end:
        t = int(time.monotonic() * 1000)
        seq = push(sets["mesh"][0][0], seq, t)
        push(sets["one"][0][0], seq - burst, t)
        order = ("mesh", "one") if wakes % 2 == 0 else ("one", "mesh")
        for mode in order:
            c0 = time.perf_counter()
            step(mode, t)
            elapsed[mode] += time.perf_counter() - c0
        drain_recv()
        wakes += 1
        if wakes % 16 == 0:
            for m in sets:
                for st in sets[m][0][0]:
                    st.prune(t)
    for _, sched in sets.values():
        sched.drain()
    sent = {m: sum(e.packets_sent for e in sets[m][0][1]) - base_sent[m]
            for m in sets}
    rate = {m: sent[m] / elapsed[m] if elapsed[m] > 0 else 0.0
            for m in sets}
    send.close()
    if recv is not None:
        recv.close()
    sched_mesh = sets["mesh"][1]
    sched_one = sets["one"][1]
    if n_dev <= 1:
        eff = 1.0                      # no mesh: nothing to scale
    elif rate["one"] > 0:
        eff = rate["mesh"] / (n_dev * rate["one"])
    else:
        # a dead single-device baseline must read as BROKEN (0.0 fails
        # bench_gate's positive-finite check), never as linear scaling
        eff = 0.0
    out = {
        "n_devices": n_dev,
        "streams": n_streams,
        "subscribers_per_stream": n_sub,
        "wakes": wakes,
        "packets_per_sec": round(rate["mesh"], 1),
        "packets_per_sec_per_device": round(rate["mesh"] / n_dev, 1),
        "single_device_packets_per_sec": round(rate["one"], 1),
        "scaling_efficiency": round(eff, 4),
        "sharded_passes": sched_mesh.sharded_passes,
        "single_device_passes": sched_one.passes,
        "wire_mismatches": sched_mesh.mismatches + sched_one.mismatches,
        "device_phase_ms": device_phase_means(),
        "method": (
            "Two identical stream sets fed identical bursts, stepped "
            "alternately with per-wake order flip (paired drift "
            "cancellation): one under the mesh-sharded megabatch "
            "scheduler, one under single-device dispatch.  Every wake "
            "pushes a fresh burst so each mode stages and dispatches "
            "real device work; packets/s = subscriber sends / that "
            "mode's summed step wall time.  scaling_efficiency = "
            "mesh rate / (n_devices x single-device rate)."),
    }
    if mesh is None:
        out["note"] = ("no mesh: fewer than 2 devices — single-device "
                       "dispatch on both sides")
    return out


__all__ = ["measure_mesh_throughput", "device_phase_means"]
