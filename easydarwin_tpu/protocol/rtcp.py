"""RTCP parse/build (RFC 3550 §6) — SR/RR/SDES/BYE/APP.

Reference parity: ``RTCPUtilitiesLib`` (``RTCPPacket.cpp`` RR parse,
``RTCPSRPacket.cpp`` SR+SDES+BYE generation, ``RTCPAckPacket.cpp`` the
reliable-UDP "qtak" APP ack, ``RTCPAPPNADUPacket.cpp`` 3GPP NADU) and the
relay's SR rewrite (``RTPSessionOutput.cpp:403-460``), which patches the SSRC
of server-generated compounds so relayed receivers see a consistent source.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

SR, RR, SDES, BYE, APP = 200, 201, 202, 203, 204
#: RFC 4585 transport-layer feedback (RTPFB); the count bits carry FMT
RTPFB = 205
FMT_GENERIC_NACK = 1

NTP_EPOCH_DELTA = 2208988800  # seconds between 1900 (NTP) and 1970 (Unix)


class RtcpError(ValueError):
    pass


@dataclass
class ReportBlock:
    ssrc: int
    fraction_lost: int
    cumulative_lost: int
    highest_seq: int
    jitter: int
    lsr: int
    dlsr: int

    def to_bytes(self) -> bytes:
        # RFC 3550 §6.4.1: cumulative_lost is a SIGNED 24-bit quantity —
        # duplicate packets make received > expected, driving it
        # negative, and it must round-trip as such.  Clamp to the signed
        # range (the RFC's own rule) rather than letting a wild value
        # alias into another report's fraction byte.
        lost = max(-0x800000, min(self.cumulative_lost, 0x7FFFFF)) \
            & 0xFFFFFF
        return struct.pack("!IIIIII", self.ssrc,
                           ((self.fraction_lost & 0xFF) << 24) | lost,
                           self.highest_seq, self.jitter, self.lsr, self.dlsr)

    @classmethod
    def parse(cls, data: bytes, off: int) -> "ReportBlock":
        ssrc, frac_lost, hseq, jit, lsr, dlsr = struct.unpack_from("!IIIIII", data, off)
        # sign-extend the 24-bit field: an unsigned read would report a
        # duplicate-heavy receiver (-1 on the wire) as ~16.7M lost and
        # poison every loss-driven controller downstream
        cum = frac_lost & 0xFFFFFF
        if cum >= 0x800000:
            cum -= 0x1000000
        return cls(ssrc, frac_lost >> 24, cum, hseq, jit, lsr, dlsr)


@dataclass
class SenderReport:
    ssrc: int
    ntp_ts: int          # 64-bit NTP timestamp
    rtp_ts: int
    packet_count: int
    octet_count: int
    reports: list[ReportBlock] = field(default_factory=list)

    def to_bytes(self) -> bytes:
        body = struct.pack("!IQIII", self.ssrc, self.ntp_ts & (2**64 - 1),
                           self.rtp_ts & 0xFFFFFFFF, self.packet_count,
                           self.octet_count)
        for rb in self.reports:
            body += rb.to_bytes()
        return _hdr(SR, len(self.reports), len(body)) + body


@dataclass
class ReceiverReport:
    ssrc: int
    reports: list[ReportBlock] = field(default_factory=list)

    def to_bytes(self) -> bytes:
        body = struct.pack("!I", self.ssrc)
        for rb in self.reports:
            body += rb.to_bytes()
        return _hdr(RR, len(self.reports), len(body)) + body


@dataclass
class SdesChunk:
    ssrc: int
    cname: str = ""

    def to_bytes(self) -> bytes:
        name = self.cname.encode()
        body = struct.pack("!I", self.ssrc) + bytes((1, len(name))) + name + b"\x00"
        pad = (-len(body)) % 4
        return body + b"\x00" * pad


@dataclass
class Sdes:
    chunks: list[SdesChunk] = field(default_factory=list)

    def to_bytes(self) -> bytes:
        body = b"".join(c.to_bytes() for c in self.chunks)
        return _hdr(SDES, len(self.chunks), len(body)) + body


@dataclass
class Bye:
    ssrcs: list[int] = field(default_factory=list)
    reason: str = ""

    def to_bytes(self) -> bytes:
        body = b"".join(struct.pack("!I", s) for s in self.ssrcs)
        if self.reason:
            r = self.reason.encode()
            body += bytes((len(r),)) + r
            body += b"\x00" * ((-len(body)) % 4)
        return _hdr(BYE, len(self.ssrcs), len(body)) + body


@dataclass
class App:
    ssrc: int
    name: str            # 4 chars, e.g. "qtak" (ack), "qtsn"/"PSS0" (NADU)
    subtype: int = 0
    data: bytes = b""

    def to_bytes(self) -> bytes:
        body = struct.pack("!I", self.ssrc) + self.name.encode()[:4].ljust(4) + self.data
        return _hdr(APP, self.subtype, len(body)) + body


@dataclass
class NaduBlock:
    """One per-source block of a 3GPP TS 26.234 NADU APP packet
    (``RTCPAPPNADUPacket.cpp``): receiver buffer feedback driving the
    reference's rate adaptation alongside thinning."""

    ssrc: int
    playout_delay_ms: int = 0xFFFF    # 0xFFFF = not known
    nsn: int = 0                      # next RTP seq to decode
    nun: int = 0                      # next ADU to decode (5 bits)
    free_buffer_64b: int = 0          # free buffer space, 64-byte units

    def to_bytes(self) -> bytes:
        return struct.pack("!IHHBBH", self.ssrc,
                           self.playout_delay_ms & 0xFFFF, self.nsn & 0xFFFF,
                           0, self.nun & 0x1F, self.free_buffer_64b & 0xFFFF)

    @classmethod
    def parse(cls, body: bytes, off: int) -> "NaduBlock":
        ssrc, delay, nsn, _rsvd, nun, fbs = struct.unpack_from(
            "!IHHBBH", body, off)
        return cls(ssrc, delay, nsn, nun & 0x1F, fbs)


@dataclass
class Nadu:
    """NADU APP packet: name "PSS0", one 12-byte block per observed SSRC."""

    ssrc: int                         # sender of the feedback
    blocks: list[NaduBlock] = field(default_factory=list)

    NAME = "PSS0"

    def to_bytes(self) -> bytes:
        return App(self.ssrc, self.NAME, subtype=0,
                   data=b"".join(b.to_bytes() for b in self.blocks)).to_bytes()

    @classmethod
    def from_app(cls, app: "App") -> "Nadu | None":
        if app.name != cls.NAME or len(app.data) % 12:
            return None
        return cls(app.ssrc, [NaduBlock.parse(app.data, i)
                              for i in range(0, len(app.data), 12)])


@dataclass
class GenericNack:
    """RFC 4585 §6.2.1 transport-layer generic NACK: the receiver's
    list of lost MEDIA seqs, each FCI a (PID, BLP) pair — PID the first
    lost seq, BLP a bitmask of the 16 following seqs also lost.  The
    reliability tier (relay/fec.py) resolves these back to live ring
    bookmarks for RTX replay."""

    sender_ssrc: int
    media_ssrc: int
    pairs: list[tuple[int, int]] = field(default_factory=list)

    def to_bytes(self) -> bytes:
        body = struct.pack("!II", self.sender_ssrc & 0xFFFFFFFF,
                           self.media_ssrc & 0xFFFFFFFF)
        for pid, blp in self.pairs:
            body += struct.pack("!HH", pid & 0xFFFF, blp & 0xFFFF)
        return _hdr(RTPFB, FMT_GENERIC_NACK, len(body)) + body

    def lost_seqs(self) -> list[int]:
        out: list[int] = []
        for pid, blp in self.pairs:
            out.append(pid & 0xFFFF)
            for bit in range(16):
                if blp & (1 << bit):
                    out.append((pid + 1 + bit) & 0xFFFF)
        return out

    @classmethod
    def from_seqs(cls, sender_ssrc: int, media_ssrc: int,
                  seqs) -> "GenericNack":
        """Pack lost seqs into minimal (PID, BLP) FCI pairs."""
        pairs: list[tuple[int, int]] = []
        for s in sorted({s & 0xFFFF for s in seqs}):
            if pairs:
                pid, blp = pairs[-1]
                d = (s - pid) & 0xFFFF
                if 1 <= d <= 16:
                    pairs[-1] = (pid, blp | (1 << (d - 1)))
                    continue
            pairs.append((s, 0))
        return cls(sender_ssrc, media_ssrc, pairs)


def _hdr(ptype: int, count: int, body_len: int) -> bytes:
    if body_len % 4:
        raise RtcpError("RTCP body must be 32-bit aligned")
    return struct.pack("!BBH", 0x80 | (count & 0x1F), ptype, body_len // 4)


def parse_compound(data: bytes) -> list[object]:
    """Parse a compound RTCP datagram into typed packets (unknown → App/raw)."""
    out: list[object] = []
    off = 0
    while off + 4 <= len(data):
        b0, ptype, words = struct.unpack_from("!BBH", data, off)
        if b0 >> 6 != 2:
            raise RtcpError(f"bad RTCP version at offset {off}")
        count = b0 & 0x1F
        end = off + 4 + words * 4
        if end > len(data):
            raise RtcpError("truncated RTCP packet")
        body = data[off + 4:end]
        if ptype == SR and len(body) >= 24:
            ssrc, ntp, rtp_ts, pc, oc = struct.unpack_from("!IQIII", body)
            sr = SenderReport(ssrc, ntp, rtp_ts, pc, oc)
            sr.reports = [ReportBlock.parse(body, 24 + i * 24)
                          for i in range(count) if 24 + (i + 1) * 24 <= len(body)]
            out.append(sr)
        elif ptype == RR and len(body) >= 4:
            ssrc = struct.unpack_from("!I", body)[0]
            rr = ReceiverReport(ssrc)
            rr.reports = [ReportBlock.parse(body, 4 + i * 24)
                          for i in range(count) if 4 + (i + 1) * 24 <= len(body)]
            out.append(rr)
        elif ptype == BYE:
            ssrcs = [struct.unpack_from("!I", body, i * 4)[0] for i in range(count)
                     if (i + 1) * 4 <= len(body)]
            bye = Bye(ssrcs)
            roff = count * 4
            if roff < len(body):
                rlen = body[roff]
                bye.reason = body[roff + 1:roff + 1 + rlen].decode("utf-8", "replace")
            out.append(bye)
        elif ptype == RTPFB and count == FMT_GENERIC_NACK \
                and len(body) >= 8 and (len(body) - 8) % 4 == 0:
            sender, media = struct.unpack_from("!II", body)
            nack = GenericNack(sender, media)
            nack.pairs = [struct.unpack_from("!HH", body, 8 + i * 4)
                          for i in range((len(body) - 8) // 4)]
            out.append(nack)
        elif ptype == APP and len(body) >= 8:
            ssrc = struct.unpack_from("!I", body)[0]
            app = App(ssrc, body[4:8].decode("ascii", "replace"),
                      subtype=count, data=body[8:])
            out.append(Nadu.from_app(app) or app)
        elif ptype == SDES:
            sd = Sdes()
            coff = 0
            for _ in range(count):
                if coff + 4 > len(body):
                    break
                ssrc = struct.unpack_from("!I", body, coff)[0]
                coff += 4
                cname = ""
                while coff < len(body) and body[coff] != 0:
                    item, ilen = body[coff], body[coff + 1] if coff + 1 < len(body) else 0
                    val = body[coff + 2:coff + 2 + ilen]
                    if item == 1:
                        cname = val.decode("utf-8", "replace")
                    coff += 2 + ilen
                coff += 1                      # the terminating null
                coff += (-coff) % 4            # chunk padding
                sd.chunks.append(SdesChunk(ssrc, cname))
            out.append(sd)
        else:
            out.append(App(0, "????", subtype=count, data=body))
        off = end
    return out


def ntp_now(unix_time: float) -> int:
    """Unix seconds (float) → 64-bit NTP timestamp."""
    sec = int(unix_time) + NTP_EPOCH_DELTA
    frac = int((unix_time % 1.0) * (1 << 32)) & 0xFFFFFFFF
    return (sec << 32) | frac


def ntp_middle32(ntp_ts: int) -> int:
    """The LSR field: middle 32 bits of a 64-bit NTP timestamp."""
    return (ntp_ts >> 16) & 0xFFFFFFFF


def build_server_compound(ssrc: int, cname: str, *, unix_time: float,
                          rtp_ts: int, packet_count: int,
                          octet_count: int, bye: bool = False) -> bytes:
    """SR + SDES(CNAME) [+ BYE] — what ``RTCPSRPacket`` emits each RR interval
    (``RTPStream.cpp:1300`` SR generation, 5 s cadence)."""
    out = SenderReport(ssrc, ntp_now(unix_time), rtp_ts, packet_count,
                       octet_count).to_bytes()
    out += Sdes([SdesChunk(ssrc, cname)]).to_bytes()
    if bye:
        out += Bye([ssrc]).to_bytes()
    return out


def _walk_compound(data):
    """Yield ``(offset, ptype, words)`` for each top-level packet of a
    compound — the one header walk all the rewrite helpers share."""
    off = 0
    while off + 8 <= len(data):
        b0, ptype, words = struct.unpack_from("!BBH", data, off)
        if b0 >> 6 != 2:
            return
        yield off, ptype, words
        off += 4 + words * 4


def compound_has_sr(data: bytes) -> bool:
    """Cheap top-level scan: does this compound carry a sender report?"""
    return any(ptype == SR for _off, ptype, _w in _walk_compound(data))


def rebase_compound(data: bytes, new_ssrc: int, *, unix_time: float,
                    rtp_ts_now: int, packet_count: int | None = None,
                    octet_count: int | None = None) -> bytes:
    """Relay a pusher's RTCP compound onto one output's timeline.

    The reference's ``RTPSessionOutput::RewriteRTCP``
    (``RTPSessionOutput.cpp:403-460``): every top-level SSRC becomes the
    output's, and each SR additionally gets its NTP timestamp set to NOW
    and its RTP timestamp set to the *output-timeline* RTP time
    corresponding to now (the caller maps it through ``RewriteState`` —
    round 1 forwarded the source-timeline pair, which was wrong for every
    client using it for A/V sync).  ``packet_count``/``octet_count``
    replace the SR's sender stats with the output's own (the reference
    doubles the pusher's counts in place, a hack we do not mirror)."""
    out = bytearray(data)
    for off, ptype, words in _walk_compound(out):
        # only when the packet actually has a leading SSRC word (a BYE
        # with count=0 or an empty SDES is 4 bytes)
        if ptype in (SR, RR, SDES, BYE, APP) and words >= 1:
            struct.pack_into("!I", out, off + 4, new_ssrc & 0xFFFFFFFF)
        if ptype == SR and words >= 6:
            struct.pack_into("!Q", out, off + 8,
                             ntp_now(unix_time) & (2**64 - 1))
            struct.pack_into("!I", out, off + 16, rtp_ts_now & 0xFFFFFFFF)
            if packet_count is not None:
                struct.pack_into("!I", out, off + 20,
                                 packet_count & 0xFFFFFFFF)
            if octet_count is not None:
                struct.pack_into("!I", out, off + 24,
                                 octet_count & 0xFFFFFFFF)
    return bytes(out)


def rewrite_compound_ssrc(data: bytes, new_ssrc: int) -> bytes:
    """Rewrite every top-level sender/source SSRC in a compound to
    ``new_ssrc`` — the relay's SR rewrite (``RTPSessionOutput.cpp:403-460``),
    applied so late-joined receivers see the per-output SSRC rather than the
    pusher's."""
    out = bytearray(data)
    for off, ptype, words in _walk_compound(out):
        # only when the packet actually has a leading SSRC word (a BYE with
        # count=0 or an empty SDES is 4 bytes; off+4 would be the NEXT
        # packet's header)
        if ptype in (SR, RR, SDES, BYE, APP) and words >= 1:
            struct.pack_into("!I", out, off + 4, new_ssrc & 0xFFFFFFFF)
    return bytes(out)
