"""AAC over RTP (RFC 3640 mpeg4-generic, AAC-hbr mode).

The reference relays audio opaquely (SDPSourceInfo keeps the media
section, the reflector forwards packets); this module exists for the
parts OUR pipeline adds on top: the HLS muxer needs access-unit
boundaries and the AudioSpecificConfig to build an fMP4 ``mp4a`` track
(`hls/segmenter.py`), and the test/soak pushers need the inverse.

AAC-hbr framing (the mode every camera/encoder SDP in practice uses):
16-bit AU-headers-length (in BITS), then per-AU headers of
``sizelength`` + ``indexlength``/``indexdeltalength`` bits, then the AU
payloads back to back.  One AU may instead span several packets
(fragmentation); interleaving (non-zero AU-index) is out of scope and
dropped.
"""

from __future__ import annotations

from dataclasses import dataclass

AAC_SAMPLES_PER_FRAME = 1024


def parse_fmtp(fmtp: str) -> dict[str, str]:
    """``"97 sizelength=13; indexlength=3; config=1190"`` → dict."""
    out: dict[str, str] = {}
    body = fmtp.split(" ", 1)[1] if " " in fmtp else fmtp
    for part in body.split(";"):
        if "=" in part:
            k, v = part.split("=", 1)
            out[k.strip().lower()] = v.strip()
    return out


@dataclass
class AacConfig:
    """What the muxer needs from the SDP's fmtp + rtpmap."""

    sample_rate: int = 48000
    channels: int = 2
    asc: bytes = b""                 # AudioSpecificConfig (config=HEX)
    sizelength: int = 13
    indexlength: int = 3
    indexdeltalength: int = 3

    @classmethod
    def from_sdp(cls, fmtp: str, clock_rate: int,
                 channels: int = 2) -> "AacConfig":
        p = parse_fmtp(fmtp)
        asc = b""
        if "config" in p:
            try:
                asc = bytes.fromhex(p["config"])
            except ValueError:
                asc = b""
        cfg = cls(sample_rate=clock_rate or 48000, channels=channels,
                  asc=asc,
                  sizelength=int(p.get("sizelength", 13) or 13),
                  indexlength=int(p.get("indexlength", 3) or 3),
                  indexdeltalength=int(p.get("indexdeltalength", 3) or 3))
        if asc and len(asc) >= 2:
            # trust the AudioSpecificConfig over the rtpmap when present
            freq_idx = ((asc[0] & 0x07) << 1) | (asc[1] >> 7)
            rates = (96000, 88200, 64000, 48000, 44100, 32000, 24000,
                     22050, 16000, 12000, 11025, 8000, 7350)
            if freq_idx < len(rates):
                cfg.sample_rate = rates[freq_idx]
            cfg.channels = (asc[1] >> 3) & 0x0F or channels
        return cfg

    def default_asc(self) -> bytes:
        """AAC-LC AudioSpecificConfig synthesized from rate/channels
        (used when the SDP carries no config=)."""
        rates = (96000, 88200, 64000, 48000, 44100, 32000, 24000,
                 22050, 16000, 12000, 11025, 8000, 7350)
        idx = rates.index(self.sample_rate) if self.sample_rate in rates \
            else 3
        v = (2 << 11) | (idx << 7) | ((self.channels & 0x0F) << 3)
        return bytes(((v >> 8) & 0xFF, v & 0xFF))


def packetize_aac_hbr(au: bytes, *, seq: int, timestamp: int, ssrc: int,
                      payload_type: int = 97, marker: bool = True,
                      cfg: AacConfig | None = None) -> bytes:
    """One AAC AU → one RTP packet (hbr, single AU — the common shape)."""
    import struct
    cfg = cfg or AacConfig()
    hdr_bits = cfg.sizelength + cfg.indexlength
    au_hdr = (len(au) << cfg.indexlength) & ((1 << hdr_bits) - 1)
    nbytes = (hdr_bits + 7) // 8
    payload = struct.pack(">H", hdr_bits) \
        + au_hdr.to_bytes(nbytes, "big") + au
    b1 = (0x80 if marker else 0) | (payload_type & 0x7F)
    return struct.pack("!BBHII", 0x80, b1, seq & 0xFFFF,
                       timestamp & 0xFFFFFFFF, ssrc) + payload


class AacDepacketizer:
    """RTP payloads → (au_bytes, rtp_timestamp) pairs.

    The RTP clock for mpeg4-generic IS the sample rate, so timestamps
    are already in sample units; AUs after the first in one packet
    advance by 1024 samples each (AAC frame length)."""

    def __init__(self, cfg: AacConfig | None = None):
        self.cfg = cfg or AacConfig()
        self._frag: bytearray | None = None
        self._frag_ts = 0
        self._frag_need = 0
        self._last_seq: int | None = None
        self.errors = 0

    def push(self, rtp_packet: bytes) -> list[tuple[bytes, int]]:
        if len(rtp_packet) < 12:
            self.errors += 1
            return []
        seq = int.from_bytes(rtp_packet[2:4], "big")
        if self._frag is not None and self._last_seq is not None \
                and seq != ((self._last_seq + 1) & 0xFFFF):
            # a lost fragment-tail must not swallow the next AU into the
            # stale fragment (corrupt audio at a stale timestamp)
            self._frag = None
            self.errors += 1
        self._last_seq = seq
        ts = int.from_bytes(rtp_packet[4:8], "big")
        marker = bool(rtp_packet[1] & 0x80)
        p = rtp_packet[12:]
        cfg = self.cfg
        if len(p) < 2:
            self.errors += 1
            return []
        hdr_bits_total = (p[0] << 8) | p[1]
        hdr_bits = cfg.sizelength + cfg.indexlength
        if hdr_bits <= 0 or hdr_bits_total < hdr_bits:
            # a zero/short AU-headers-length (or a malicious fmtp with
            # sizelength=0) would make us parse media bytes as a header
            # — and a garbage size can wedge the fragment state into
            # eating subsequent valid AUs
            self.errors += 1
            return []
        n_aus = hdr_bits_total // hdr_bits
        hdr_bytes = (hdr_bits_total + 7) // 8
        if len(p) < 2 + hdr_bytes:
            self.errors += 1
            return []
        sizes = []
        bitpos = 16
        raw = p
        for i in range(n_aus):
            size = 0
            for _ in range(cfg.sizelength):
                size = (size << 1) | ((raw[bitpos >> 3] >>
                                      (7 - (bitpos & 7))) & 1)
                bitpos += 1
            idx = 0
            il = cfg.indexlength if i == 0 else cfg.indexdeltalength
            for _ in range(il):
                idx = (idx << 1) | ((raw[bitpos >> 3] >>
                                    (7 - (bitpos & 7))) & 1)
                bitpos += 1
            if idx != 0:                 # interleaving: out of scope
                self.errors += 1
                return []
            sizes.append(size)
        data = p[2 + hdr_bytes:]
        out: list[tuple[bytes, int]] = []
        if self._frag is not None:
            # continuation of a fragmented AU: hbr repeats the AU header
            take = min(len(data), self._frag_need - len(self._frag))
            self._frag += data[:take]
            if len(self._frag) >= self._frag_need and marker:
                out.append((bytes(self._frag), self._frag_ts))
                self._frag = None
            elif len(self._frag) >= self._frag_need:
                self._frag = None        # desync: drop silently
                self.errors += 1
            return out
        if n_aus == 1 and sizes[0] > len(data):
            # fragmented AU: accumulate until the marker closes it
            self._frag = bytearray(data)
            self._frag_ts = ts
            self._frag_need = sizes[0]
            return []
        off = 0
        for i, size in enumerate(sizes):
            if off + size > len(data):
                self.errors += 1
                break
            out.append((data[off:off + size],
                        (ts + i * AAC_SAMPLES_PER_FRAME) & 0xFFFFFFFF))
            off += size
        return out
