"""Baseline JPEG entropy codec: scan bytes ⇄ quantized DCT coefficients.

This is the host-side half of the config-5 transcode ladder.  RTP/JPEG
(RFC 2435) streams are baseline JFIF scans coded with the *standard*
Huffman tables (the same ``_DC/_AC_CODELENS/SYMBOLS`` tables
``protocol.mjpeg`` writes into reconstructed JFIF headers), so the scan
can be entropy-decoded into ``[n_blocks, 64]`` coefficient-level arrays,
requantized on the TPU (``ops.transform.requantize`` — pure elementwise +
MXU math over all blocks at once), and entropy-re-encoded at each ladder
rung.  Entropy coding itself is inherently serial bit twiddling and stays
on the host in every real system; the batched transform math is the
device's share.

Levels are kept in **zigzag order** end-to-end: the JFIF DQT tables ride
in zigzag order too, so requantization pairs level ``i`` with table entry
``i`` without reordering.

No reference counterpart exists (EasyDarwin ships no transcoder; EasyHLS
was closed-source) — new code, like the HLS tier.
"""

from __future__ import annotations

import numpy as np

from .mjpeg import (_AC_CHROMA_CODELENS, _AC_CHROMA_SYMBOLS, _AC_CODELENS,
                    _AC_SYMBOLS, _DC_CHROMA_CODELENS, _DC_CHROMA_SYMBOLS,
                    _DC_CODELENS, _DC_SYMBOLS)


class JpegEntropyError(ValueError):
    pass


# -- canonical Huffman table construction ------------------------------------

def _build_decode(codelens: bytes, symbols: bytes) -> dict[tuple[int, int], int]:
    """(bit-length, code) → symbol for canonical Huffman tables."""
    table = {}
    code = 0
    k = 0
    for nbits, count in enumerate(codelens, start=1):
        for _ in range(count):
            table[(nbits, code)] = symbols[k]
            code += 1
            k += 1
        code <<= 1
    return table


def _build_encode(codelens: bytes, symbols: bytes) -> dict[int, tuple[int, int]]:
    """symbol → (code, bit-length)."""
    out = {}
    for (nbits, code), sym in _build_decode(codelens, symbols).items():
        out[sym] = (code, nbits)
    return out


_DC_DECODE = _build_decode(_DC_CODELENS, _DC_SYMBOLS)
_AC_DECODE = _build_decode(_AC_CODELENS, _AC_SYMBOLS)
_DC_ENCODE = _build_encode(_DC_CODELENS, _DC_SYMBOLS)
_AC_ENCODE = _build_encode(_AC_CODELENS, _AC_SYMBOLS)
_DC_CHROMA_DECODE = _build_decode(_DC_CHROMA_CODELENS, _DC_CHROMA_SYMBOLS)
_AC_CHROMA_DECODE = _build_decode(_AC_CHROMA_CODELENS, _AC_CHROMA_SYMBOLS)
_DC_CHROMA_ENCODE = _build_encode(_DC_CHROMA_CODELENS, _DC_CHROMA_SYMBOLS)
_AC_CHROMA_ENCODE = _build_encode(_AC_CHROMA_CODELENS, _AC_CHROMA_SYMBOLS)

#: per-component (DC decode, AC decode) — comp 0 luma, comps 1-2 chroma
_DECODE_TABLES = ((_DC_DECODE, _AC_DECODE),
                  (_DC_CHROMA_DECODE, _AC_CHROMA_DECODE),
                  (_DC_CHROMA_DECODE, _AC_CHROMA_DECODE))
_ENCODE_TABLES = ((_DC_ENCODE, _AC_ENCODE),
                  (_DC_CHROMA_ENCODE, _AC_CHROMA_ENCODE),
                  (_DC_CHROMA_ENCODE, _AC_CHROMA_ENCODE))

#: blocks per MCU by RTP/JPEG type & 1 — type 0 = 4:2:2 (Y Y Cb Cr),
#: type 1 = 4:2:0 (Y Y Y Y Cb Cr); component index per block
_MCU_COMPS = {0: (0, 0, 1, 2), 1: (0, 0, 0, 0, 1, 2)}
#: MCU pixel footprint (w, h) per type
_MCU_SIZE = {0: (16, 8), 1: (16, 16)}


def mcu_grid(width: int, height: int, jtype: int) -> tuple[int, int]:
    mw, mh = _MCU_SIZE[jtype & 1]
    return (width + mw - 1) // mw, (height + mh - 1) // mh


class _BitReader:
    """MSB-first reader over an entropy-coded segment with 0xFF00
    unstuffing; stops at markers (restart or EOI)."""

    __slots__ = ("data", "pos", "acc", "nbits")

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0
        self.acc = 0
        self.nbits = 0

    def _fill(self) -> None:
        while self.nbits <= 24:
            if self.pos >= len(self.data):
                # trailing virtual 1s (decoders pad; EOB codes resolve)
                self.acc = (self.acc << 8) | 0xFF
                self.nbits += 8
                continue
            b = self.data[self.pos]
            if b == 0xFF:
                nxt = self.data[self.pos + 1] if self.pos + 1 < len(self.data) else 0xD9
                if nxt == 0x00:
                    self.pos += 2
                elif 0xD0 <= nxt <= 0xD7:   # restart marker: caller resyncs
                    self.acc = (self.acc << 8) | 0xFF
                    self.nbits += 8
                    continue
                else:                        # EOI or foreign marker
                    self.acc = (self.acc << 8) | 0xFF
                    self.nbits += 8
                    continue
            else:
                self.pos += 1
            self.acc = (self.acc << 8) | b
            self.nbits += 8

    def bits(self, n: int) -> int:
        if n == 0:
            return 0
        self._fill()
        v = (self.acc >> (self.nbits - n)) & ((1 << n) - 1)
        self.nbits -= n
        self.acc &= (1 << self.nbits) - 1
        return v

    def huffman(self, table: dict[tuple[int, int], int]) -> int:
        code = 0
        for length in range(1, 17):
            code = (code << 1) | self.bits(1)
            sym = table.get((length, code))
            if sym is not None:
                return sym
        raise JpegEntropyError("invalid Huffman code")

    def align_and_skip_restart(self) -> None:
        """Byte-align and consume an RSTn marker (between DRI intervals)."""
        self.acc = 0
        self.nbits = 0
        d = self.data
        while self.pos + 1 < len(d):
            if d[self.pos] == 0xFF and 0xD0 <= d[self.pos + 1] <= 0xD7:
                self.pos += 2
                return
            self.pos += 1


def _extend(v: int, t: int) -> int:
    return v - ((1 << t) - 1) if v < (1 << (t - 1)) else v


def decode_scan(scan: bytes, width: int, height: int, jtype: int,
                restart_interval: int = 0) -> list[np.ndarray]:
    """Entropy-decode a baseline scan → per-component zigzag level arrays.

    Returns ``[Y, Cb, Cr]`` where Y is ``[4*n_mcus or 2*n_mcus, 64]`` and
    Cb/Cr are ``[n_mcus, 64]`` int16 (type 1 = 4:2:0, type 0 = 4:2:2)."""
    jt = jtype & 1
    comps = _MCU_COMPS[jt]
    gw, gh = mcu_grid(width, height, jt)
    n_mcus = gw * gh
    n_y = comps.count(0)
    out = [np.zeros((n_mcus * n_y, 64), np.int16),
           np.zeros((n_mcus, 64), np.int16),
           np.zeros((n_mcus, 64), np.int16)]
    idx = [0, 0, 0]
    pred = [0, 0, 0]
    r = _BitReader(scan)
    for mcu in range(n_mcus):
        if restart_interval and mcu and mcu % restart_interval == 0:
            r.align_and_skip_restart()
            pred = [0, 0, 0]
        for comp in comps:
            dc_tab, ac_tab = _DECODE_TABLES[comp]
            blk = out[comp][idx[comp]]
            idx[comp] += 1
            t = r.huffman(dc_tab)
            diff = _extend(r.bits(t), t) if t else 0
            pred[comp] += diff
            blk[0] = pred[comp]
            k = 1
            while k < 64:
                rs = r.huffman(ac_tab)
                rl, size = rs >> 4, rs & 0xF
                if rs == 0x00:              # EOB
                    break
                if rs == 0xF0:              # ZRL
                    k += 16
                    continue
                k += rl
                if k > 63:
                    raise JpegEntropyError("AC run past block end")
                blk[k] = _extend(r.bits(size), size)
                k += 1
    return out


class _BitWriter:
    __slots__ = ("out", "acc", "nbits")

    def __init__(self):
        self.out = bytearray()
        self.acc = 0
        self.nbits = 0

    def bits(self, v: int, n: int) -> None:
        if n == 0:
            return
        self.acc = (self.acc << n) | (v & ((1 << n) - 1))
        self.nbits += n
        while self.nbits >= 8:
            b = (self.acc >> (self.nbits - 8)) & 0xFF
            self.out.append(b)
            if b == 0xFF:
                self.out.append(0x00)       # byte stuffing
            self.nbits -= 8
        self.acc &= (1 << self.nbits) - 1

    def flush(self) -> bytes:
        if self.nbits:
            pad = 8 - self.nbits
            self.bits((1 << pad) - 1, pad)  # pad with 1s
        return bytes(self.out)


def _category(v: int) -> int:
    return int(abs(v)).bit_length()


def encode_scan(levels: list[np.ndarray], jtype: int) -> bytes:
    """Per-component zigzag level arrays → entropy-coded scan bytes
    (standard tables, no restart markers)."""
    jt = jtype & 1
    comps = _MCU_COMPS[jt]
    n_mcus = len(levels[1])
    idx = [0, 0, 0]
    pred = [0, 0, 0]
    w = _BitWriter()
    for _mcu in range(n_mcus):
        for comp in comps:
            dc_enc, ac_enc = _ENCODE_TABLES[comp]
            blk = levels[comp][idx[comp]]
            idx[comp] += 1
            dc = int(blk[0])
            diff = dc - pred[comp]
            pred[comp] = dc
            t = _category(diff)
            code, nb = dc_enc[t]
            w.bits(code, nb)
            if t:
                w.bits(diff if diff >= 0 else diff + (1 << t) - 1, t)
            # AC: run-length of zeros + category
            last_nz = 63
            while last_nz > 0 and blk[last_nz] == 0:
                last_nz -= 1
            k = 1
            while k <= last_nz:
                run = 0
                while blk[k] == 0:
                    run += 1
                    k += 1
                while run >= 16:
                    code, nb = ac_enc[0xF0]
                    w.bits(code, nb)        # ZRL
                    run -= 16
                v = int(blk[k])
                s = _category(v)
                code, nb = ac_enc[(run << 4) | s]
                w.bits(code, nb)
                w.bits(v if v >= 0 else v + (1 << s) - 1, s)
                k += 1
            if last_nz < 63:
                code, nb = ac_enc[0x00]
                w.bits(code, nb)            # EOB
    return w.flush()
