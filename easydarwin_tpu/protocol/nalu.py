"""H.264 RTP payload classification (RFC 6184) — the keyframe index oracle.

Reproduces the exact predicates of the reference's ``ReflectorSender``:

* ``is_keyframe_first_packet`` — ``ReflectorStream.cpp:1403-1513``: a packet
  whose (possibly aggregated/fragmented) leading NAL unit is IDR(5), SPS(7) or
  PPS(8).  Header size is computed as ``12 + 4*CC`` (extension ignored), the
  packet must be ≥ 20 bytes, and for FU-A/FU-B only fragments with the start
  bit set count.
* ``is_frame_first_packet`` — ``ReflectorStream.cpp:1515-1557``: any leading
  single/aggregation NAL, or a FU fragment with the start bit.
* ``is_frame_last_packet`` — ``ReflectorStream.cpp:1559-1573``: RTP marker bit.

These run per-packet on the host only as the oracle; the production path is the
vectorized equivalent in ``easydarwin_tpu.ops.parse`` evaluated for a whole
packet ring at once on device.
"""

from __future__ import annotations

from . import rtp

# NAL unit types (H.264 Annex A / RFC 6184 §5.2)
NAL_NON_IDR = 1
NAL_IDR = 5
NAL_SEI = 6
NAL_SPS = 7
NAL_PPS = 8
NAL_AUD = 9
NAL_STAP_A = 24
NAL_STAP_B = 25
NAL_MTAP16 = 26
NAL_MTAP24 = 27
NAL_FU_A = 28
NAL_FU_B = 29

#: minimum packet length the reference requires before classifying
_MIN_CLASSIFY_LEN = 20

#: offset (past the RTP header) of the first aggregated NAL header byte for
#: each aggregation packet type, per ReflectorStream.cpp:1465-1483
_AGG_INNER_OFFSET = {NAL_STAP_A: 3, NAL_STAP_B: 5, NAL_MTAP16: 8, NAL_MTAP24: 9}

KEYFRAME_NAL_TYPES = frozenset({NAL_IDR, NAL_SPS, NAL_PPS})


def effective_nal_type(packet: bytes) -> int | None:
    """The NAL type the reference's classifier ends up testing, or None.

    Resolves aggregation packets (STAP/MTAP) to their first contained NAL and
    FU-A/B to the fragmented NAL *only when the start bit is set* (a non-start
    fragment keeps type 28/29, which is never a keyframe type — mirroring the
    reference, which leaves ``nal_unit_type`` as the outer type in that case).
    """
    if len(packet) < _MIN_CLASSIFY_LEN:
        return None
    hs = rtp.header_size_cc_only(packet)
    if len(packet) <= hs:
        return None
    t = packet[hs] & 0x1F
    if t in _AGG_INNER_OFFSET:
        off = _AGG_INNER_OFFSET[t]
        if len(packet) > hs + off:
            t = packet[hs + off] & 0x1F
    elif t in (NAL_FU_A, NAL_FU_B):
        if len(packet) > hs + 1 and packet[hs + 1] & 0x80:
            t = packet[hs + 1] & 0x1F
    return t


def is_keyframe_first_packet(packet: bytes) -> bool:
    """True iff this RTP packet starts an H.264 keyframe (IDR/SPS/PPS)."""
    return effective_nal_type(packet) in KEYFRAME_NAL_TYPES


def is_frame_first_packet(packet: bytes) -> bool:
    """True iff this packet begins a (any) frame per the reference's test."""
    if len(packet) < _MIN_CLASSIFY_LEN:
        return False
    hs = rtp.header_size_cc_only(packet)
    if len(packet) <= hs:
        return False
    t = packet[hs] & 0x1F
    if 1 <= t <= 27:  # single NAL or aggregation packet
        return True
    if t in (NAL_FU_A, NAL_FU_B):
        return len(packet) > hs + 1 and bool(packet[hs + 1] & 0x80)
    return False


def is_frame_last_packet(packet: bytes) -> bool:
    """True iff the RTP marker bit is set (reference: byte1 & 0x80, len≥20)."""
    return len(packet) >= _MIN_CLASSIFY_LEN and bool(packet[1] & 0x80)


def split_annexb(stream: bytes) -> list[bytes]:
    """Split an Annex-B byte stream into NAL units (without start codes)."""
    out: list[bytes] = []
    i, n = 0, len(stream)
    starts: list[int] = []
    while i < n - 2:
        if stream[i] == 0 and stream[i + 1] == 0:
            if stream[i + 2] == 1:
                starts.append(i + 3)
                i += 3
                continue
            if i < n - 3 and stream[i + 2] == 0 and stream[i + 3] == 1:
                starts.append(i + 4)
                i += 4
                continue
        i += 1
    for k, s in enumerate(starts):
        e = n
        if k + 1 < len(starts):
            e = starts[k + 1]
            while e > s and stream[e - 1] == 0:  # strip next start code prefix
                e -= 1
            if e > s and stream[e - 1] == 1:
                e -= 1
                while e > s and stream[e - 1] == 0:
                    e -= 1
        out.append(stream[s:e])
    return out


def packetize_h264(nal: bytes, *, seq: int, timestamp: int, ssrc: int,
                   payload_type: int = 96, mtu: int = 1400,
                   marker_on_last: bool = True) -> list[bytes]:
    """Packetize one NAL unit into RTP packets (single NAL or FU-A).

    A minimal RFC 6184 packetizer used by the test harness, the loopback
    pusher, and the VOD fallback path for non-hinted H.264 tracks.
    """
    pkts: list[bytes] = []
    if len(nal) <= mtu:
        pkts.append(rtp.RtpPacket(
            payload_type=payload_type, seq=seq, timestamp=timestamp,
            ssrc=ssrc, marker=marker_on_last, payload=nal).to_bytes())
        return pkts
    nri = nal[0] & 0x60
    ntype = nal[0] & 0x1F
    fu_indicator = nri | NAL_FU_A
    body = nal[1:]
    first = True
    while body:
        chunk, body = body[:mtu - 2], body[mtu - 2:]
        fu_header = ntype | (0x80 if first else 0) | (0x40 if not body else 0)
        pkts.append(rtp.RtpPacket(
            payload_type=payload_type, seq=seq, timestamp=timestamp,
            ssrc=ssrc, marker=marker_on_last and not body,
            payload=bytes((fu_indicator, fu_header)) + chunk).to_bytes())
        seq = (seq + 1) & 0xFFFF
        first = False
    return pkts
