"""x-RTP-Meta-Info packet format (DSS legacy, RTPMetaInfoLib parity).

Reference: ``RTPMetaInfoLib/RTPMetaInfoPacket.{h,cpp}`` — meta-info packets
are RTP packets whose payload is a TLV field list appended after the 12-byte
RTP header; the real media payload rides in the ``md`` field.  Two field
encodings exist on the wire:

* uncompressed: 2-byte ASCII field name (be) + 2-byte length (be) + data
* compressed:   1 byte ``0x80 | field_id`` + 1-byte length + data, where the
  id→field mapping was negotiated in the ``x-RTP-Meta-Info`` RTSP header
  (``ConstructFieldIDArrayFromHeader``, RTPMetaInfoPacket.cpp:72-113)

Fields (RTPMetaInfoPacket.h:44-56, length validators cpp:50-59):

====  =====================  =====
name  meaning                bytes
====  =====================  =====
pp    packet position        8
tt    transmit time (ms)     8
ft    frame type             2
pn    packet number          8
sq    original seq number    2
md    media payload          any
====  =====================  =====
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

#: field order matches the reference's FieldIndex enum
FIELDS = ("pp", "tt", "ft", "pn", "sq", "md")

#: required wire lengths; 0 = variable (RTPMetaInfoPacket.cpp:50-59)
FIELD_LENGTHS = {"pp": 8, "tt": 8, "ft": 2, "pn": 8, "sq": 2, "md": 0}

#: frame type field values (RTPMetaInfoPacket.h:84-90)
FRAME_UNKNOWN, FRAME_KEY, FRAME_B, FRAME_P = 0, 1, 2, 3

#: "no compressed id assigned; sent uncompressed" (kUncompressed)
UNCOMPRESSED = -1


def parse_header(value: str) -> dict[str, int]:
    """``x-RTP-Meta-Info`` RTSP header → {field: compressed_id}.

    Header grammar is ``name[=id];name[=id];...`` (e.g. ``tt;ft=1;sq=2;md=3``);
    a field without ``=id`` is sent uncompressed (UNCOMPRESSED sentinel).
    Unknown names are dropped, like the reference's kIllegalField filter."""
    out: dict[str, int] = {}
    for part in value.split(";"):
        part = part.strip()
        if len(part) < 2:
            continue
        name, _, idstr = part.partition("=")
        name = name.strip().lower()
        if name not in FIELDS:
            continue
        if idstr.strip():
            try:
                out[name] = int(idstr)
            except ValueError:
                continue
        else:
            out[name] = UNCOMPRESSED
    return out


def build_header(fields: dict[str, int]) -> str:
    """{field: compressed_id} → ``x-RTP-Meta-Info`` header value."""
    parts = []
    for name in FIELDS:                      # canonical field order
        if name not in fields:
            continue
        fid = fields[name]
        parts.append(name if fid == UNCOMPRESSED else f"{name}={fid}")
    return ";".join(parts)


@dataclass
class MetaInfo:
    """Parsed x-RTP-Meta-Info packet (RTPMetaInfoPacket member parity)."""

    packet_position: int | None = None       # pp
    transmit_time: int | None = None         # tt
    frame_type: int | None = None            # ft
    packet_number: int | None = None         # pn
    seq: int | None = None                   # sq
    media: bytes | None = None               # md
    media_offset: int = 0                    # offset of md data in the packet

    _BY_FIELD = {"pp": "packet_position", "tt": "transmit_time",
                 "ft": "frame_type", "pn": "packet_number", "sq": "seq"}


def parse_packet(data: bytes,
                 field_ids: dict[str, int] | None = None) -> MetaInfo | None:
    """Parse a meta-info packet (after its 12-byte RTP header).

    ``field_ids`` is the negotiated {field: id} map (compressed fields need
    it; pure-uncompressed packets don't).  Returns None on malformed input —
    the reference's false return (``ParsePacket``, cpp:116-222)."""
    if len(data) < 12:
        return None
    id_to_field = {}
    if field_ids:
        id_to_field = {fid: name for name, fid in field_ids.items()
                       if fid >= 0}
    info = MetaInfo()
    pos = 12
    end = len(data)
    while pos + 2 <= end:                     # a field header fits (even a
        first = data[pos]                     # trailing zero-length one)
        if first & 0x80:                      # compressed: id + 1-byte len
            name = id_to_field.get(first & 0x7F)
            flen = data[pos + 1]
            pos += 2
        else:                                 # uncompressed: name16 + len16
            if pos + 4 > end:
                break
            try:
                name = data[pos:pos + 2].decode("ascii").lower()
            except UnicodeDecodeError:
                name = None
            if name not in FIELDS:
                name = None
            flen = struct.unpack_from(">H", data, pos + 2)[0]
            pos += 4
        if name is not None:
            want = FIELD_LENGTHS[name]
            if want and flen != want:
                return None                   # wrong field length: corrupt
        if pos + flen > end:
            return None
        if name == "md":
            info.media = data[pos:pos + flen]
            info.media_offset = pos
        elif name is not None:
            val = int.from_bytes(data[pos:pos + flen], "big")
            setattr(info, MetaInfo._BY_FIELD[name], val)
        pos += flen
    return info


def build_packet(rtp_header: bytes, *, media: bytes,
                 field_ids: dict[str, int] | None = None,
                 packet_position: int | None = None,
                 transmit_time: int | None = None,
                 frame_type: int | None = None,
                 packet_number: int | None = None,
                 seq: int | None = None) -> bytes:
    """Construct a meta-info packet: RTP header + TLV fields (md last).

    Fields with a non-negative id in ``field_ids`` use the compressed
    encoding; everything else goes uncompressed."""
    if len(rtp_header) < 12:
        raise ValueError("need a full 12-byte RTP header")
    field_ids = field_ids or {}

    def tlv(name: str, payload: bytes) -> bytes:
        # md can never be compressed — its payload exceeds a 1-byte length
        # (reference asserts kUncompressed for kMediaDataField,
        # QTHintTrack.cpp:1363, and patches a 16-bit length at :1472)
        fid = UNCOMPRESSED if name == "md" else field_ids.get(name,
                                                              UNCOMPRESSED)
        if fid >= 0:
            if len(payload) > 0xFF:
                raise ValueError(f"{name}: compressed field too long")
            return bytes([0x80 | fid, len(payload)]) + payload
        return name.encode("ascii") + struct.pack(">H", len(payload)) + payload

    out = bytearray(rtp_header[:12])
    for name, val, size in (("pp", packet_position, 8),
                            ("tt", transmit_time, 8),
                            ("ft", frame_type, 2),
                            ("pn", packet_number, 8),
                            ("sq", seq, 2)):
        if val is not None:
            out += tlv(name, int(val).to_bytes(size, "big"))
    out += tlv("md", media)
    return bytes(out)


def strip_to_rtp(data: bytes,
                 field_ids: dict[str, int] | None = None) -> bytes | None:
    """Meta-info packet → plain RTP packet (header ∥ media payload).

    The reference's ``MakeRTPPacket`` (cpp:226-241) does this in place by
    sliding the header down to the media data; an immutable copy is the
    Python idiom for the same operation."""
    info = parse_packet(data, field_ids)
    if info is None or info.media is None:
        return None
    return data[:12] + info.media
