"""RTP packet parse/build (RFC 3550 §5.1).

Reference behavior being reproduced: the reflector treats packets as opaque
byte slots of at most ``kMaxReflectorPacketSize`` (2060 bytes,
``ReflectorStream.h:127``) and reads seq/timestamp/SSRC at fixed offsets; the
keyframe classifier computes the header size as ``12 + 4*CC`` ignoring the
extension bit (``ReflectorStream.cpp:1457-1459``).  This module implements the
full header (incl. extension) for correctness-critical paths and exposes the
reference-compatible ``header_size_cc_only`` for bit-compatible classification.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

RTP_VERSION = 2
FIXED_HEADER_LEN = 12
#: Reference slot size: ReflectorStream.h:127 (kMaxReflectorPacketSize).
MAX_PACKET_SIZE = 2060


class RtpError(ValueError):
    pass


@dataclass
class RtpPacket:
    """A parsed RTP packet. ``payload`` excludes padding."""

    payload_type: int
    seq: int
    timestamp: int
    ssrc: int
    marker: bool = False
    padding: bool = False
    csrcs: tuple[int, ...] = ()
    extension: tuple[int, bytes] | None = None  # (profile id, data)
    payload: bytes = b""
    version: int = RTP_VERSION

    @property
    def header_len(self) -> int:
        n = FIXED_HEADER_LEN + 4 * len(self.csrcs)
        if self.extension is not None:
            n += 4 + len(self.extension[1])
        return n

    def to_bytes(self) -> bytes:
        b0 = (self.version << 6) | (0x20 if self.padding else 0) | (
            0x10 if self.extension is not None else 0) | len(self.csrcs)
        b1 = (0x80 if self.marker else 0) | (self.payload_type & 0x7F)
        out = bytearray(struct.pack(
            "!BBHII", b0, b1, self.seq & 0xFFFF,
            self.timestamp & 0xFFFFFFFF, self.ssrc & 0xFFFFFFFF))
        for c in self.csrcs:
            out += struct.pack("!I", c & 0xFFFFFFFF)
        if self.extension is not None:
            profile, data = self.extension
            if len(data) % 4:
                raise RtpError("extension data must be a multiple of 4 bytes")
            out += struct.pack("!HH", profile & 0xFFFF, len(data) // 4)
            out += data
        out += self.payload
        return bytes(out)

    @classmethod
    def parse(cls, data: bytes) -> "RtpPacket":
        if len(data) < FIXED_HEADER_LEN:
            raise RtpError(f"short RTP packet: {len(data)} bytes")
        b0, b1, seq, ts, ssrc = struct.unpack_from("!BBHII", data)
        version = b0 >> 6
        if version != RTP_VERSION:
            raise RtpError(f"bad RTP version {version}")
        cc = b0 & 0x0F
        off = FIXED_HEADER_LEN + 4 * cc
        if len(data) < off:
            raise RtpError("truncated CSRC list")
        csrcs = struct.unpack_from(f"!{cc}I", data, FIXED_HEADER_LEN) if cc else ()
        ext = None
        if b0 & 0x10:
            if len(data) < off + 4:
                raise RtpError("truncated extension header")
            profile, words = struct.unpack_from("!HH", data, off)
            if len(data) < off + 4 + 4 * words:
                raise RtpError("truncated extension data")
            ext = (profile, data[off + 4:off + 4 + 4 * words])
            off += 4 + 4 * words
        payload = data[off:]
        padding = bool(b0 & 0x20)
        if padding:
            if not payload or payload[-1] == 0 or payload[-1] > len(payload):
                raise RtpError("bad padding")
            payload = payload[:-payload[-1]]
        return cls(payload_type=b1 & 0x7F, seq=seq, timestamp=ts, ssrc=ssrc,
                   marker=bool(b1 & 0x80), padding=padding, csrcs=tuple(csrcs),
                   extension=ext, payload=payload)


def header_size_cc_only(data: bytes) -> int:
    """Header size as the reference computes it: ``12 + 4*CC``, extension bit
    deliberately ignored (``ReflectorStream.cpp:1457-1459``)."""
    return FIXED_HEADER_LEN + 4 * (data[0] & 0x0F)


def peek_seq(data: bytes) -> int:
    return struct.unpack_from("!H", data, 2)[0]


def peek_timestamp(data: bytes) -> int:
    return struct.unpack_from("!I", data, 4)[0]


def peek_ssrc(data: bytes) -> int:
    return struct.unpack_from("!I", data, 8)[0]


def rewrite_header(data: bytes, *, seq: int | None = None,
                   timestamp: int | None = None,
                   ssrc: int | None = None) -> bytes:
    """Return ``data`` with seq/timestamp/SSRC overwritten in place.

    This is the scalar oracle for the device fan-out: the TPU path computes the
    same three fields for every (subscriber, packet) pair in one batched op
    (see ``ops.fanout``), and the egress scatters them over the shared payload.
    """
    out = bytearray(data)
    if seq is not None:
        struct.pack_into("!H", out, 2, seq & 0xFFFF)
    if timestamp is not None:
        struct.pack_into("!I", out, 4, timestamp & 0xFFFFFFFF)
    if ssrc is not None:
        struct.pack_into("!I", out, 8, ssrc & 0xFFFFFFFF)
    return bytes(out)


def seq_delta(a: int, b: int) -> int:
    """Signed distance a-b in 16-bit sequence space (RFC 3550 A.1 style)."""
    d = (a - b) & 0xFFFF
    return d - 0x10000 if d >= 0x8000 else d
