"""RTP/JPEG (RFC 2435) — MJPEG camera streams.

The reference relays MJPEG cameras through the same reflector path as
H.264 (BASELINE config 3 mixes both); its keyframe fast-start machinery
(``ReflectorStream.cpp:1403-1513``) only special-cases H.264, so MJPEG
late-joiners wait for the next frame boundary.  Here MJPEG gets the same
first-class treatment: every JPEG frame is independently decodable, so a
packet with **fragment offset 0 is a keyframe-first packet** and the relay
fast-start / GOP-ring logic works unchanged.

This module is the codec kit around that: RFC 2435 header parse/build, a
packetizer (JPEG scan → RTP fragments) and a depacketizer that
reconstructs a decodable JFIF file from fragments using the RFC's
Appendix A standard quantization/Huffman tables.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from . import rtp


class MjpegError(ValueError):
    pass


# -- RFC 2435 section 3.1: main JPEG header (8 bytes) -----------------------

@dataclass
class JpegHeader:
    type_specific: int = 0
    fragment_offset: int = 0          # 24-bit byte offset into the scan
    type: int = 1                     # 0=4:2:2, 1=4:2:0 (+64 w/ restarts)
    q: int = 255                      # 1..99 scale, 100..127 reserved, >=128 in-band tables
    width: int = 0                    # pixels (wire carries /8)
    height: int = 0
    restart_interval: int = 0         # present when 64 <= type <= 127
    qtables: bytes = b""              # in-band tables (q >= 128, offset 0)
    precision: int = 0

    @property
    def is_frame_start(self) -> bool:
        return self.fragment_offset == 0


def parse_payload(payload: bytes) -> tuple[JpegHeader, bytes]:
    """RTP payload → (header, scan fragment bytes)."""
    if len(payload) < 8:
        raise MjpegError("RTP/JPEG payload shorter than main header")
    h = JpegHeader()
    h.type_specific = payload[0]
    h.fragment_offset = int.from_bytes(payload[1:4], "big")
    h.type = payload[4]
    h.q = payload[5]
    h.width = payload[6] * 8
    h.height = payload[7] * 8
    off = 8
    if 64 <= h.type <= 127:
        if len(payload) < off + 4:
            raise MjpegError("truncated restart marker header")
        h.restart_interval = struct.unpack_from("!H", payload, off)[0]
        off += 4
    if h.q >= 128 and h.fragment_offset == 0:
        if len(payload) < off + 4:
            raise MjpegError("truncated quantization table header")
        _mbz, h.precision, qlen = struct.unpack_from("!BBH", payload, off)
        off += 4
        if len(payload) < off + qlen:
            raise MjpegError("truncated quantization tables")
        h.qtables = payload[off:off + qlen]
        off += qlen
    return h, payload[off:]


def build_payload(header: JpegHeader, fragment: bytes) -> bytes:
    out = bytes([header.type_specific]) + \
        header.fragment_offset.to_bytes(3, "big") + \
        bytes([header.type, header.q, header.width // 8, header.height // 8])
    if 64 <= header.type <= 127:
        out += struct.pack("!HH", header.restart_interval, 0xFFFF)
    if header.q >= 128 and header.fragment_offset == 0:
        out += struct.pack("!BBH", 0, header.precision, len(header.qtables))
        out += header.qtables
    return out + fragment


def is_frame_first_packet(packet: bytes) -> bool:
    """Fragment offset 0 ⇒ start of a JPEG frame ⇒ (M)JPEG "keyframe".

    The MJPEG analogue of ``nalu.is_keyframe_first_packet``; used by the
    packet ring's ingest classification and mirrored on-device by
    ``ops.parse.parse_packets(codec="mjpeg")``."""
    if len(packet) < 12:
        return False
    hs = rtp.header_size_cc_only(packet)
    payload = packet[hs:]
    return len(payload) >= 8 and payload[1:4] == b"\x00\x00\x00"


# -- packetizer --------------------------------------------------------------

def packetize_jpeg(scan: bytes, *, width: int, height: int, seq: int,
                   timestamp: int, ssrc: int, type_: int = 1, q: int = 255,
                   qtables: bytes = b"", payload_type: int = 26,
                   mtu: int = 1400) -> list[bytes]:
    """JPEG entropy-coded scan → RTP packets (marker on the last).

    ``scan`` is the data between SOS and EOI; ``qtables`` (when ``q >=
    128``) rides in-band in the first fragment per RFC 2435 §3.1.8."""
    if width % 8 or height % 8 or width > 2040 or height > 2040:
        raise MjpegError("RFC 2435 dimensions must be multiples of 8, <=2040")
    pkts = []
    off = 0
    first_seq = seq
    while off < len(scan) or not pkts:
        hdr = JpegHeader(fragment_offset=off, type=type_, q=q, width=width,
                         height=height,
                         qtables=qtables if off == 0 else b"")
        head_len = len(build_payload(hdr, b""))
        room = max(mtu - 12 - head_len, 1)
        frag = scan[off:off + room]
        off += len(frag)
        last = off >= len(scan)
        pkts.append(rtp.RtpPacket(
            payload_type=payload_type, seq=(first_seq + len(pkts)) & 0xFFFF,
            timestamp=timestamp & 0xFFFFFFFF, ssrc=ssrc, marker=last,
            payload=build_payload(hdr, frag)).to_bytes())
        if last:
            break
    return pkts


# -- RFC 2435 Appendix A: standard tables & JFIF header synthesis ------------

_LUMA_Q = bytes([
    16, 11, 10, 16, 24, 40, 51, 61, 12, 12, 14, 19, 26, 58, 60, 55,
    14, 13, 16, 24, 40, 57, 69, 56, 14, 17, 22, 29, 51, 87, 80, 62,
    18, 22, 37, 56, 68, 109, 103, 77, 24, 35, 55, 64, 81, 104, 113, 92,
    49, 64, 78, 87, 103, 121, 120, 101, 72, 92, 95, 98, 112, 100, 103, 99])
_CHROMA_Q = bytes([
    17, 18, 24, 47, 99, 99, 99, 99, 18, 21, 26, 66, 99, 99, 99, 99,
    24, 26, 56, 99, 99, 99, 99, 99, 47, 66, 99, 99, 99, 99, 99, 99,
    99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99,
    99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99])

_DC_CODELENS = bytes([0, 1, 5, 1, 1, 1, 1, 1, 1, 0, 0, 0, 0, 0, 0, 0])
_DC_SYMBOLS = bytes(range(12))
# Standard chroma tables (RFC 2435 Appendix B / T.81 Annex K tables K.4/K.6).
# Real RTP/JPEG senders (libjpeg, ffmpeg, cameras) code Cb/Cr with these, not
# the luma set — decoders must select per component.
_DC_CHROMA_CODELENS = bytes([0, 3, 1, 1, 1, 1, 1, 1, 1, 1, 1, 0, 0, 0, 0, 0])
_DC_CHROMA_SYMBOLS = bytes(range(12))
_AC_CHROMA_CODELENS = bytes([0, 2, 1, 2, 4, 4, 3, 4, 7, 5, 4, 4, 0, 1, 2, 0x77])
_AC_CHROMA_SYMBOLS = bytes([
    0x00, 0x01, 0x02, 0x03, 0x11, 0x04, 0x05, 0x21, 0x31, 0x06, 0x12, 0x41,
    0x51, 0x07, 0x61, 0x71, 0x13, 0x22, 0x32, 0x81, 0x08, 0x14, 0x42, 0x91,
    0xa1, 0xb1, 0xc1, 0x09, 0x23, 0x33, 0x52, 0xf0, 0x15, 0x62, 0x72, 0xd1,
    0x0a, 0x16, 0x24, 0x34, 0xe1, 0x25, 0xf1, 0x17, 0x18, 0x19, 0x1a, 0x26,
    0x27, 0x28, 0x29, 0x2a, 0x35, 0x36, 0x37, 0x38, 0x39, 0x3a, 0x43, 0x44,
    0x45, 0x46, 0x47, 0x48, 0x49, 0x4a, 0x53, 0x54, 0x55, 0x56, 0x57, 0x58,
    0x59, 0x5a, 0x63, 0x64, 0x65, 0x66, 0x67, 0x68, 0x69, 0x6a, 0x73, 0x74,
    0x75, 0x76, 0x77, 0x78, 0x79, 0x7a, 0x82, 0x83, 0x84, 0x85, 0x86, 0x87,
    0x88, 0x89, 0x8a, 0x92, 0x93, 0x94, 0x95, 0x96, 0x97, 0x98, 0x99, 0x9a,
    0xa2, 0xa3, 0xa4, 0xa5, 0xa6, 0xa7, 0xa8, 0xa9, 0xaa, 0xb2, 0xb3, 0xb4,
    0xb5, 0xb6, 0xb7, 0xb8, 0xb9, 0xba, 0xc2, 0xc3, 0xc4, 0xc5, 0xc6, 0xc7,
    0xc8, 0xc9, 0xca, 0xd2, 0xd3, 0xd4, 0xd5, 0xd6, 0xd7, 0xd8, 0xd9, 0xda,
    0xe2, 0xe3, 0xe4, 0xe5, 0xe6, 0xe7, 0xe8, 0xe9, 0xea, 0xf2, 0xf3, 0xf4,
    0xf5, 0xf6, 0xf7, 0xf8, 0xf9, 0xfa])
_AC_CODELENS = bytes([0, 2, 1, 3, 3, 2, 4, 3, 5, 5, 4, 4, 0, 0, 1, 0x7D])
_AC_SYMBOLS = bytes([
    0x01, 0x02, 0x03, 0x00, 0x04, 0x11, 0x05, 0x12, 0x21, 0x31, 0x41, 0x06,
    0x13, 0x51, 0x61, 0x07, 0x22, 0x71, 0x14, 0x32, 0x81, 0x91, 0xa1, 0x08,
    0x23, 0x42, 0xb1, 0xc1, 0x15, 0x52, 0xd1, 0xf0, 0x24, 0x33, 0x62, 0x72,
    0x82, 0x09, 0x0a, 0x16, 0x17, 0x18, 0x19, 0x1a, 0x25, 0x26, 0x27, 0x28,
    0x29, 0x2a, 0x34, 0x35, 0x36, 0x37, 0x38, 0x39, 0x3a, 0x43, 0x44, 0x45,
    0x46, 0x47, 0x48, 0x49, 0x4a, 0x53, 0x54, 0x55, 0x56, 0x57, 0x58, 0x59,
    0x5a, 0x63, 0x64, 0x65, 0x66, 0x67, 0x68, 0x69, 0x6a, 0x73, 0x74, 0x75,
    0x76, 0x77, 0x78, 0x79, 0x7a, 0x83, 0x84, 0x85, 0x86, 0x87, 0x88, 0x89,
    0x8a, 0x92, 0x93, 0x94, 0x95, 0x96, 0x97, 0x98, 0x99, 0x9a, 0xa2, 0xa3,
    0xa4, 0xa5, 0xa6, 0xa7, 0xa8, 0xa9, 0xaa, 0xb2, 0xb3, 0xb4, 0xb5, 0xb6,
    0xb7, 0xb8, 0xb9, 0xba, 0xc2, 0xc3, 0xc4, 0xc5, 0xc6, 0xc7, 0xc8, 0xc9,
    0xca, 0xd2, 0xd3, 0xd4, 0xd5, 0xd6, 0xd7, 0xd8, 0xd9, 0xda, 0xe1, 0xe2,
    0xe3, 0xe4, 0xe5, 0xe6, 0xe7, 0xe8, 0xe9, 0xea, 0xf1, 0xf2, 0xf3, 0xf4,
    0xf5, 0xf6, 0xf7, 0xf8, 0xf9, 0xfa])


def make_qtables(q: int) -> bytes:
    """Scale the Appendix A base tables by Q (1..99) → 128 bytes
    (luma ∥ chroma)."""
    q = max(1, min(q, 99))
    factor = 5000 // q if q < 50 else 200 - q * 2
    out = bytearray()
    for base in (_LUMA_Q, _CHROMA_Q):
        for v in base:
            out.append(max(1, min((v * factor + 50) // 100, 255)))
    return bytes(out)


def _marker(code: int, body: bytes) -> bytes:
    return bytes([0xFF, code]) + struct.pack("!H", len(body) + 2) + body


def make_jfif_headers(header: JpegHeader, qtables: bytes) -> bytes:
    """SOI→SOS JFIF prefix per Appendix A ``MakeHeaders`` (standard
    Huffman tables; sampling from the RTP/JPEG type)."""
    if not qtables:
        qtables = make_qtables(header.q if 1 <= header.q <= 99 else 99)
    elif len(qtables) < 128:
        qtables = (qtables + qtables)[:128]   # one in-band table: reuse for chroma
    out = bytearray(b"\xff\xd8")                       # SOI
    out += _marker(0xDB, b"\x00" + qtables[:64])       # DQT luma
    out += _marker(0xDB, b"\x01" + qtables[64:128])    # DQT chroma
    if 64 <= header.type <= 127 and header.restart_interval:
        out += _marker(0xDD, struct.pack("!H", header.restart_interval))
    samp = 0x22 if (header.type & 0x3F) == 1 else 0x21   # 4:2:0 vs 4:2:2
    out += _marker(0xC0, struct.pack(                  # SOF0, 3 components
        "!BHHB", 8, header.height, header.width, 3) +
        bytes([1, samp, 0, 2, 0x11, 1, 3, 0x11, 1]))
    out += _marker(0xC4, b"\x00" + _DC_CODELENS + _DC_SYMBOLS)   # DHT DC luma
    out += _marker(0xC4, b"\x10" + _AC_CODELENS + _AC_SYMBOLS)   # DHT AC luma
    out += _marker(0xC4, b"\x01" + _DC_CHROMA_CODELENS + _DC_CHROMA_SYMBOLS)
    out += _marker(0xC4, b"\x11" + _AC_CHROMA_CODELENS + _AC_CHROMA_SYMBOLS)
    out += _marker(0xDA, b"\x03" +                     # SOS
                   bytes([1, 0x00, 2, 0x11, 3, 0x11]) + b"\x00\x3f\x00")
    return bytes(out)


# -- depacketizer ------------------------------------------------------------

@dataclass
class _Frame:
    timestamp: int
    header: JpegHeader | None = None
    parts: list[tuple[int, bytes]] = field(default_factory=list)
    have_marker: bool = False


class JpegDepacketizer:
    """Reassemble RTP/JPEG fragments into decodable JFIF frames.

    ``push(packet)`` returns complete JPEG file bytes when the packet
    carries the frame's marker bit and all fragments are present, else
    ``None``.  Incomplete frames are dropped when a newer timestamp
    arrives (cameras are lossy; MJPEG has no inter-frame dependencies)."""

    def __init__(self):
        self._cur: _Frame | None = None
        self.frames_out = 0
        self.frames_dropped = 0

    def push(self, packet: bytes) -> bytes | None:
        parts = self.push_parts(packet)
        if parts is None:
            return None
        header, scan, _ts = parts
        jfif = make_jfif_headers(header, header.qtables)
        if not scan.endswith(b"\xff\xd9"):
            scan += b"\xff\xd9"            # EOI
        return jfif + scan

    def push_parts(self, packet: bytes
                   ) -> tuple[JpegHeader, bytes, int] | None:
        """Like push() but returns (header, raw scan, rtp timestamp) —
        the transcode ladder wants the entropy-coded scan, not a JFIF
        container."""
        pkt = rtp.RtpPacket.parse(packet)
        header, frag = parse_payload(pkt.payload)
        if self._cur is None or pkt.timestamp != self._cur.timestamp:
            if self._cur is not None:
                self.frames_dropped += 1
            self._cur = _Frame(pkt.timestamp)
        f = self._cur
        if header.fragment_offset == 0:
            f.header = header
        f.parts.append((header.fragment_offset, frag))
        if pkt.marker:
            f.have_marker = True
        if not f.have_marker or f.header is None:
            return None
        f.parts.sort()
        scan = bytearray()
        for off, part in f.parts:
            if off != len(scan):
                self.frames_dropped += 1    # gap: fragment lost
                self._cur = None
                return None
            scan += part
        self._cur = None
        self.frames_out += 1
        return f.header, bytes(scan), f.timestamp
