"""SDP parse/build (RFC 4566 subset the reference understands).

Reference parity: ``APICommonCode/SDPSourceInfo.cpp`` (SDP →
``SourceInfo::StreamInfo[]``: media type, payload type/name, clock rate,
track control IDs, buffer delay) and ``SDPUtils.cpp`` (line container +
ordering).  Also builds DESCRIBE answers and normalizes pushed ANNOUNCE SDP
the way the reflector's ``DoDescribe``/``DoAnnounce`` do.
"""

from __future__ import annotations

from dataclasses import dataclass, field

#: qtss stream media kinds
VIDEO, AUDIO, OTHER = "video", "audio", "other"


@dataclass
class StreamInfo:
    """Per-media-section info (SDPSourceInfo::StreamInfo equivalent)."""

    media_type: str = OTHER           # "video" | "audio" | "other"
    payload_type: int = 0             # RTP payload type number
    payload_name: str = ""            # e.g. "H264/90000"
    codec: str = ""                   # e.g. "H264"
    clock_rate: int = 90000
    port: int = 0
    track_id: int = 0                 # from a=control:trackID=N (or ordinal)
    control: str = ""                 # raw control attribute value
    buffer_delay: float = 3.0         # a=x-bufferdelay
    fmtp: str = ""
    connection: str = ""              # per-media c= override (multicast relay)
    attributes: dict[str, str] = field(default_factory=dict)

    def dest_address(self, session_connection: str = "") -> str:
        """The ingest destination from the media-level ``c=`` (falling back
        to the session-level one): ``IN IP4 239.1.2.3/127`` → ``239.1.2.3``."""
        conn = self.connection or session_connection
        parts = conn.split()
        return parts[-1].split("/")[0] if parts else ""


@dataclass
class SessionDescription:
    session_name: str = ""
    origin: str = ""
    connection: str = ""
    control: str = "*"
    attributes: dict[str, str] = field(default_factory=dict)
    streams: list[StreamInfo] = field(default_factory=list)
    raw: str = ""

    def video_streams(self) -> list[StreamInfo]:
        return [s for s in self.streams if s.media_type == VIDEO]

    def audio_streams(self) -> list[StreamInfo]:
        return [s for s in self.streams if s.media_type == AUDIO]


def parse(text: str | bytes) -> SessionDescription:
    if isinstance(text, bytes):
        text = text.decode("utf-8", "replace")
    sd = SessionDescription(raw=text)
    cur: StreamInfo | None = None
    ordinal = 0
    for line in text.replace("\r\n", "\n").split("\n"):
        line = line.strip()
        if len(line) < 2 or line[1] != "=":
            continue
        kind, val = line[0], line[2:]
        if kind == "m":
            parts = val.split()
            cur = StreamInfo()
            ordinal += 1
            cur.track_id = ordinal
            if parts:
                cur.media_type = parts[0] if parts[0] in (VIDEO, AUDIO) else OTHER
            if len(parts) >= 2:
                try:
                    cur.port = int(parts[1].split("/")[0])
                except ValueError:
                    pass
            if len(parts) >= 4:
                try:
                    cur.payload_type = int(parts[3])
                except ValueError:
                    pass
            sd.streams.append(cur)
        elif kind == "s":
            sd.session_name = val
        elif kind == "o":
            sd.origin = val
        elif kind == "c":
            if cur is None:
                sd.connection = val
            else:
                cur.connection = val
        elif kind == "a":
            name, _, aval = val.partition(":")
            if cur is None:
                if name == "control":
                    sd.control = aval
                else:
                    sd.attributes[name] = aval
                continue
            if name == "control":
                cur.control = aval
                # accept trackID=N / streamid=N / trailing integer
                low = aval.lower()
                for pref in ("trackid=", "streamid="):
                    if pref in low:
                        try:
                            cur.track_id = int(low.split(pref)[1].split()[0])
                        except ValueError:
                            pass
            elif name == "rtpmap":
                # rtpmap:<pt> <name>/<clock>[/<chans>]
                try:
                    pt, rest = aval.split(None, 1)
                    if int(pt) == cur.payload_type or not cur.payload_name:
                        cur.payload_name = rest
                        cur.codec = rest.split("/")[0].upper()
                        bits = rest.split("/")
                        if len(bits) >= 2:
                            cur.clock_rate = int(bits[1])
                except (ValueError, IndexError):
                    pass
            elif name == "fmtp":
                cur.fmtp = aval
            elif name == "x-bufferdelay":
                try:
                    cur.buffer_delay = float(aval)
                except ValueError:
                    pass
            else:
                cur.attributes[name] = aval
    # default codecs for static payload types
    for s in sd.streams:
        if not s.codec:
            s.codec = {0: "PCMU", 8: "PCMA", 14: "MPA", 26: "JPEG",
                       32: "MPV", 33: "MP2T"}.get(s.payload_type, "")
            if s.payload_type == 26:
                s.clock_rate = 90000
    return sd


def build(sd: SessionDescription, *, server_ip: str = "0.0.0.0",
          session_id: int = 0) -> str:
    """Serialize a DESCRIBE answer in the canonical v/o/s/c/t/a ordering
    enforced by the reference's SDP container (``SDPUtils.cpp`` sort)."""
    lines = [
        "v=0",
        sd.origin and f"o={sd.origin}"
        or f"o=- {session_id} {session_id} IN IP4 {server_ip}",
        f"s={sd.session_name or 'easydarwin_tpu'}",
        f"c={sd.connection or f'IN IP4 {server_ip}'}",
        "t=0 0",
        f"a=control:{sd.control or '*'}",
    ]
    for name, aval in sd.attributes.items():
        lines.append(f"a={name}:{aval}" if aval else f"a={name}")
    for i, s in enumerate(sd.streams, start=1):
        lines.append(f"m={s.media_type} 0 RTP/AVP {s.payload_type}")
        if s.payload_name:
            lines.append(f"a=rtpmap:{s.payload_type} {s.payload_name}")
        if s.fmtp:
            lines.append(f"a=fmtp:{s.fmtp}")
        lines.append(f"a=control:trackID={s.track_id or i}")
        for name, aval in s.attributes.items():
            lines.append(f"a={name}:{aval}" if aval else f"a={name}")
    return "\r\n".join(lines) + "\r\n"


class SdpCache:
    """Path → SDP map for pushed sessions (reference: ``sdpCache.{h,cpp}``,
    a singleton replacing on-disk .sdp files)."""

    def __init__(self):
        self._map: dict[str, str] = {}

    def set(self, path: str, sdp: str) -> None:
        self._map[_norm(path)] = sdp

    def get(self, path: str) -> str | None:
        return self._map.get(_norm(path))

    def pop(self, path: str) -> None:
        self._map.pop(_norm(path), None)

    def paths(self) -> list[str]:
        return sorted(self._map)

    def __len__(self) -> int:
        return len(self._map)


def _norm(path: str) -> str:
    if path.endswith(".sdp"):
        path = path[:-4]
    return path.rstrip("/")
