"""RTSP/1.0 grammar: requests, responses, Transport negotiation, $-framing.

Reference parity: ``RTSPProtocol.cpp`` (method/header/status tables),
``RTSPRequest.cpp`` (request line + Transport header parse),
``RTSPRequestStream.cpp`` (incremental buffered reads + interleaved-data
demux), ``RTSPResponseStream.cpp`` (response writing).

The incremental reader (`RtspWireReader`) is sans-IO: feed bytes, receive a
stream of `RtspRequest` / `InterleavedPacket` events. Both the asyncio server
and the in-process test clients drive it, so the grammar is tested without
sockets.
"""

from __future__ import annotations

from dataclasses import dataclass, field

RTSP_VERSION = "RTSP/1.0"

METHODS = (
    "OPTIONS", "DESCRIBE", "ANNOUNCE", "SETUP", "PLAY", "PAUSE", "TEARDOWN",
    "RECORD", "GET_PARAMETER", "SET_PARAMETER", "REDIRECT",
)

#: HTTP verbs accepted on the RTSP port for RTSP-over-HTTP tunneling and
#: icy/HTTP side-channels (RTSPSession's HTTP-tunnel states,
#: ``RTSPSession.cpp:1339-1459``)
HTTP_METHODS = ("GET", "POST")

#: status code → reason phrase (subset of RTSPProtocol.cpp's table)
STATUS_PHRASES = {
    100: "Continue", 200: "OK", 201: "Created", 250: "Low on Storage Space",
    300: "Multiple Choices", 301: "Moved Permanently", 302: "Found",
    304: "Not Modified", 305: "Use Proxy",
    400: "Bad Request", 401: "Unauthorized", 403: "Forbidden",
    404: "Not Found", 405: "Method Not Allowed", 406: "Not Acceptable",
    407: "Proxy Authentication Required", 408: "Request Timeout",
    410: "Gone", 411: "Length Required", 412: "Precondition Failed",
    413: "Request Entity Too Large", 414: "Request-URI Too Long",
    415: "Unsupported Media Type", 451: "Parameter Not Understood",
    452: "Conference Not Found", 453: "Not Enough Bandwidth",
    454: "Session Not Found", 455: "Method Not Valid in This State",
    456: "Header Field Not Valid for Resource", 457: "Invalid Range",
    458: "Parameter Is Read-Only", 459: "Aggregate Operation Not Allowed",
    460: "Only Aggregate Operation Allowed", 461: "Unsupported Transport",
    462: "Destination Unreachable", 500: "Internal Server Error",
    501: "Not Implemented", 502: "Bad Gateway", 503: "Service Unavailable",
    504: "Gateway Timeout", 505: "RTSP Version Not Supported",
    551: "Option Not Supported",
}


class RtspError(ValueError):
    def __init__(self, status: int, msg: str = ""):
        super().__init__(msg or STATUS_PHRASES.get(status, str(status)))
        self.status = status


@dataclass
class TransportSpec:
    """Parsed Transport header (one transport-spec)."""

    protocol: str = "RTP/AVP"          # RTP/AVP | RTP/AVP/UDP | RTP/AVP/TCP
    is_tcp: bool = False
    unicast: bool = True
    mode: str = "PLAY"                 # PLAY | RECORD (mode=receive treated as RECORD)
    client_port: tuple[int, int] | None = None
    server_port: tuple[int, int] | None = None
    interleaved: tuple[int, int] | None = None
    destination: str | None = None
    source: str | None = None
    ssrc: int | None = None
    ttl: int | None = None

    @classmethod
    def parse(cls, value: str) -> "TransportSpec":
        # Only the first transport-spec is honored (reference behavior).
        spec = value.split(",")[0].strip()
        parts = [p.strip() for p in spec.split(";") if p.strip()]
        if not parts:
            raise RtspError(461, "empty Transport header")
        t = cls(protocol=parts[0].upper())
        t.is_tcp = t.protocol.endswith("/TCP")
        for p in parts[1:]:
            key, _, val = p.partition("=")
            key = key.lower()
            if key == "unicast":
                t.unicast = True
            elif key == "multicast":
                t.unicast = False
            elif key == "mode":
                v = val.strip('"').upper()
                t.mode = "RECORD" if v in ("RECORD", "RECEIVE") else "PLAY"
            elif key in ("client_port", "server_port", "interleaved"):
                lo, _, hi = val.partition("-")
                try:
                    pair = (int(lo), int(hi) if hi else int(lo) + 1)
                except ValueError as e:
                    raise RtspError(461, f"bad {key}: {val!r}") from e
                setattr(t, key, pair)
            elif key == "destination":
                t.destination = val
            elif key == "source":
                t.source = val
            elif key == "ssrc":
                try:
                    t.ssrc = int(val, 16)
                except ValueError:
                    pass
            elif key == "ttl":
                try:
                    t.ttl = int(val)
                except ValueError:
                    pass
        return t

    def to_header(self) -> str:
        parts = [self.protocol]
        parts.append("unicast" if self.unicast else "multicast")
        if self.destination:
            parts.append(f"destination={self.destination}")
        if self.source:
            parts.append(f"source={self.source}")
        if self.client_port:
            parts.append(f"client_port={self.client_port[0]}-{self.client_port[1]}")
        if self.server_port:
            parts.append(f"server_port={self.server_port[0]}-{self.server_port[1]}")
        if self.interleaved:
            parts.append(f"interleaved={self.interleaved[0]}-{self.interleaved[1]}")
        if self.ssrc is not None:
            parts.append(f"ssrc={self.ssrc:08X}")
        if self.mode == "RECORD":
            parts.append('mode=record')
        return ";".join(parts)


@dataclass
class RtspRequest:
    method: str
    uri: str
    headers: dict[str, str]            # keys lower-cased
    body: bytes = b""
    version: str = RTSP_VERSION

    @property
    def cseq(self) -> int:
        try:
            return int(self.headers.get("cseq", "0"))
        except ValueError:
            return 0

    @property
    def session_id(self) -> str | None:
        v = self.headers.get("session")
        return v.split(";")[0].strip() if v else None

    @property
    def transport(self) -> TransportSpec | None:
        v = self.headers.get("transport")
        return TransportSpec.parse(v) if v else None

    def path(self) -> str:
        """URI path without scheme/host: rtsp://h:p/live/a.sdp → /live/a.sdp"""
        uri = self.uri
        if "://" in uri:
            rest = uri.split("://", 1)[1]
            slash = rest.find("/")
            uri = rest[slash:] if slash >= 0 else "/"
        return uri.split("?")[0] or "/"

    def to_bytes(self) -> bytes:
        lines = [f"{self.method} {self.uri} {self.version}"]
        for k, v in self.headers.items():
            lines.append(f"{_canon(k)}: {v}")
        if self.body and "content-length" not in self.headers:
            lines.append(f"Content-Length: {len(self.body)}")
        return ("\r\n".join(lines) + "\r\n\r\n").encode() + self.body


@dataclass
class RtspResponse:
    status: int = 200
    headers: dict[str, str] = field(default_factory=dict)
    body: bytes = b""
    version: str = RTSP_VERSION

    def to_bytes(self) -> bytes:
        phrase = STATUS_PHRASES.get(self.status, "Unknown")
        lines = [f"{self.version} {self.status} {phrase}"]
        for k, v in self.headers.items():
            lines.append(f"{_canon(k)}: {v}")
        if self.body and "content-length" not in {k.lower() for k in self.headers}:
            lines.append(f"Content-Length: {len(self.body)}")
        return ("\r\n".join(lines) + "\r\n\r\n").encode() + self.body

    @classmethod
    def parse(cls, head: bytes, body: bytes = b"") -> "RtspResponse":
        text = head.decode("utf-8", "replace")
        lines = text.split("\r\n")
        first = lines[0].split(None, 2)
        if len(first) < 2 or not first[0].startswith("RTSP/"):
            raise RtspError(400, f"bad status line {lines[0]!r}")
        headers = _parse_headers(lines[1:])
        return cls(status=int(first[1]), headers=headers, body=body,
                   version=first[0])


def _canon(key: str) -> str:
    special = {"cseq": "CSeq", "www-authenticate": "WWW-Authenticate",
               "rtp-info": "RTP-Info", "content-length": "Content-Length",
               "content-type": "Content-Type", "content-base": "Content-Base"}
    return special.get(key.lower()) or "-".join(
        w.capitalize() for w in key.split("-"))


def _parse_headers(lines: list[str]) -> dict[str, str]:
    headers: dict[str, str] = {}
    for line in lines:
        if not line:
            continue
        name, sep, val = line.partition(":")
        if not sep:
            continue
        headers[name.strip().lower()] = val.strip()
    return headers


@dataclass
class InterleavedPacket:
    """One $-framed binary chunk from an RTSP/TCP connection."""

    channel: int
    data: bytes


def frame_interleaved(channel: int, data: bytes) -> bytes:
    """Build a $-framed interleaved chunk (RFC 2326 §10.12)."""
    return b"$" + bytes((channel,)) + len(data).to_bytes(2, "big") + data


class RtspWireReader:
    """Incremental RTSP stream reader with interleaved-data demux.

    Mirrors ``RTSPRequestStream.cpp``: bytes arriving on an RTSP TCP
    connection are either full-text requests (terminated by CRLFCRLF, plus
    Content-Length body) or ``$``-framed binary (RTP/RTCP pushed by a
    RECORD-mode client). ``feed()`` buffers; ``events()`` yields completed
    ``RtspRequest`` / ``InterleavedPacket`` / ``RtspResponse`` objects.
    """

    MAX_HEADER = 64 * 1024
    MAX_BODY = 8 * 1024 * 1024

    def __init__(self, parse_responses: bool = False):
        self._buf = bytearray()
        self._parse_responses = parse_responses

    def feed(self, data: bytes) -> None:
        self._buf += data

    def events(self):
        while True:
            ev = self._next()
            if ev is None:
                return
            yield ev

    def _next(self):
        buf = self._buf
        if not buf:
            return None
        if buf[0] == 0x24:  # '$'
            if len(buf) < 4:
                return None
            length = int.from_bytes(buf[2:4], "big")
            if len(buf) < 4 + length:
                return None
            pkt = InterleavedPacket(buf[1], bytes(buf[4:4 + length]))
            del buf[:4 + length]
            return pkt
        # Tolerate stray CRLF between messages (RFC 2326 allows it) — and
        # re-dispatch afterwards: the next byte may start a '$' binary frame,
        # which must not fall through to text parsing.
        if buf[:2] == b"\r\n":
            while buf[:2] == b"\r\n":
                del buf[:2]
            return self._next()
        end = buf.find(b"\r\n\r\n")
        if end < 0:
            if len(buf) > self.MAX_HEADER:
                raise RtspError(413, "header too large")
            return None
        head = bytes(buf[:end])
        headers = _parse_headers(head.decode("utf-8", "replace").split("\r\n")[1:])
        try:
            clen = int(headers.get("content-length", "0"))
        except ValueError:
            clen = 0
        if clen < 0 or clen > self.MAX_BODY:
            raise RtspError(413, "body too large")
        total = end + 4 + clen
        if len(buf) < total:
            return None
        body = bytes(buf[end + 4:total])
        del buf[:total]
        first = head.split(b"\r\n", 1)[0].decode("utf-8", "replace")
        if self._parse_responses and first.startswith("RTSP/"):
            return RtspResponse.parse(head, body)
        parts = first.split(None, 2)
        if len(parts) != 3:
            raise RtspError(400, f"bad request line {first!r}")
        method, uri, version = parts
        if method not in METHODS and method not in HTTP_METHODS:
            raise RtspError(501, f"unknown method {method!r}")
        return RtspRequest(method=method, uri=uri, headers=headers, body=body,
                           version=version)
