"""Wire-protocol parsers and builders (pure Python — the CPU oracle).

Every format the device tier (``easydarwin_tpu.ops``) accelerates has its
reference implementation here; differential tests assert bit-exact agreement.

Modules
-------
``rtp``   RTP fixed header + extension parse/build (RFC 3550 §5.1).
``rtcp``  RTCP SR/RR/SDES/BYE/APP parse/build (RFC 3550 §6) incl. the
          reliable-UDP Ack/NADU APP formats the reference understands.
``nalu``  H.264 RTP payload classification (RFC 6184): NAL unit type,
          keyframe-first-packet / frame-first / frame-last predicates with the
          exact semantics of the reference's ``ReflectorSender``
          (``ReflectorStream.cpp:1403-1573``).
``rtsp``  RTSP/1.0 request/response grammar + Transport header negotiation
          (reference: ``RTSPRequest.cpp``, ``RTSPProtocol.cpp``).
``sdp``   SDP parse into per-stream ``StreamInfo`` records (reference:
          ``SDPSourceInfo.cpp``) and SDP generation for DESCRIBE answers.
"""

from . import nalu, rtcp, rtp, rtsp, sdp  # noqa: F401
