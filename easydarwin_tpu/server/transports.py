"""RTP/RTCP transports: UDP port-pair pool, UDP & interleaved outputs.

Reference parity: ``UDPSocketPool`` (even-RTP/odd-RTCP port pairs,
``UDPSocketPool.h``), ``RTPStream``'s UDP send (``RTPStream.cpp:1145``) and
TCP interleaved send (``InterleavedWrite``, ``RTPStream.cpp:772``), the
reflector's ingest sockets (``ReflectorSocket``), and the WouldBlock
flow-control contract (``RTPSessionOutput.cpp:610-620``): a stalled client
must never stall the relay — the output reports WOULD_BLOCK and replays from
its bookmark on the next pass.
"""

from __future__ import annotations

import asyncio
from typing import Callable

from ..protocol import rtsp
from ..relay.output import RelayOutput, WriteResult

#: default interleaved write-buffer high water mark: past this the output
#: reports WOULD_BLOCK (the reference gets EAGAIN from a 96 KB SO_SNDBUF,
#: TCPListenerSocket.cpp:189-190)
HIGH_WATER = 256 * 1024


class InterleavedOutput(RelayOutput):
    """$-framed RTP/RTCP egress over the client's RTSP TCP connection."""

    def __init__(self, transport: asyncio.WriteTransport,
                 rtp_channel: int, rtcp_channel: int, **kw):
        super().__init__(**kw)
        self.transport = transport
        self.rtp_channel = rtp_channel
        self.rtcp_channel = rtcp_channel

    def _send(self, channel: int, chunks: tuple[bytes, ...]) -> WriteResult:
        tr = self.transport
        if tr.is_closing():
            return WriteResult.ERROR
        if tr.get_write_buffer_size() > HIGH_WATER:
            return WriteResult.WOULD_BLOCK
        n = sum(len(c) for c in chunks)
        tr.write(b"$" + bytes((channel,)) + n.to_bytes(2, "big"))
        for c in chunks:
            tr.write(c)
        return WriteResult.OK

    def send_bytes(self, data: bytes, *, is_rtcp: bool) -> WriteResult:
        ch = self.rtcp_channel if is_rtcp else self.rtp_channel
        return self._send(ch, (data,))

    def send_rewritten(self, header: bytes, tail: bytes) -> WriteResult:
        if self.meta_field_ids is not None:     # negotiated meta-info wrap
            return self.send_bytes(self._wrap_meta(header, tail),
                                   is_rtcp=False)
        return self._send(self.rtp_channel, (header, tail))


class UdpOutput(RelayOutput):
    """RTP/RTCP egress to a client's UDP port pair."""

    def __init__(self, rtp_transport: asyncio.DatagramTransport,
                 rtcp_transport: asyncio.DatagramTransport | None,
                 client_ip: str, client_rtp_port: int,
                 client_rtcp_port: int, **kw):
        super().__init__(**kw)
        self.rtp_transport = rtp_transport
        self.rtcp_transport = rtcp_transport
        self.rtp_addr = (client_ip, client_rtp_port)
        self.rtcp_addr = (client_ip, client_rtcp_port)

    def send_bytes(self, data: bytes, *, is_rtcp: bool) -> WriteResult:
        tr = self.rtcp_transport if is_rtcp else self.rtp_transport
        if tr is None:
            return WriteResult.OK
        if tr.is_closing():
            return WriteResult.ERROR
        tr.sendto(data, self.rtcp_addr if is_rtcp else self.rtp_addr)
        return WriteResult.OK

    def send_rewritten(self, header: bytes, tail: bytes) -> WriteResult:
        if self.meta_field_ids is not None:     # negotiated meta-info wrap
            return self.send_bytes(self._wrap_meta(header, tail),
                                   is_rtcp=False)
        if self.rtp_transport.is_closing():
            return WriteResult.ERROR
        self.rtp_transport.sendto(header + tail, self.rtp_addr)
        return WriteResult.OK


class _DatagramSink(asyncio.DatagramProtocol):
    def __init__(self, on_packet: Callable[[bytes, tuple], None] | None = None):
        self.on_packet = on_packet
        self.transport: asyncio.DatagramTransport | None = None

    def connection_made(self, transport):
        self.transport = transport

    def datagram_received(self, data, addr):
        if self.on_packet is not None:
            self.on_packet(data, addr)


class UdpPair:
    """One bound even/odd (RTP, RTCP) endpoint pair."""

    def __init__(self, rtp_transport, rtp_proto, rtcp_transport, rtcp_proto,
                 rtp_port: int):
        self.rtp_transport: asyncio.DatagramTransport = rtp_transport
        self.rtp_proto: _DatagramSink = rtp_proto
        self.rtcp_transport: asyncio.DatagramTransport = rtcp_transport
        self.rtcp_proto: _DatagramSink = rtcp_proto
        self.rtp_port = rtp_port

    @property
    def rtcp_port(self) -> int:
        return self.rtp_port + 1

    def close(self) -> None:
        for t in (self.rtp_transport, self.rtcp_transport):
            if t and not t.is_closing():
                t.close()


class UdpPortPool:
    """Allocates even/odd UDP port pairs (``UDPSocketPool`` equivalent)."""

    def __init__(self, bind_ip: str = "0.0.0.0", base_port: int = 6970,
                 max_pairs: int = 4000):
        self.bind_ip = bind_ip
        self.base_port = base_port
        self.max_pairs = max_pairs
        self._next = base_port

    async def allocate(self, on_rtp=None, on_rtcp=None) -> UdpPair:
        loop = asyncio.get_running_loop()
        last_err: Exception | None = None
        for _ in range(self.max_pairs):
            port = self._next
            self._next += 2
            if self._next >= self.base_port + 2 * self.max_pairs:
                self._next = self.base_port
            try:
                rtp_t, rtp_p = await loop.create_datagram_endpoint(
                    lambda: _DatagramSink(on_rtp),
                    local_addr=(self.bind_ip, port))
                try:
                    rtcp_t, rtcp_p = await loop.create_datagram_endpoint(
                        lambda: _DatagramSink(on_rtcp),
                        local_addr=(self.bind_ip, port + 1))
                except OSError as e:
                    rtp_t.close()
                    last_err = e
                    continue
                return UdpPair(rtp_t, rtp_p, rtcp_t, rtcp_p, port)
            except OSError as e:
                last_err = e
                continue
        raise OSError(f"no free UDP port pairs: {last_err}")
