"""RTP/RTCP transports: UDP port-pair pool, UDP & interleaved outputs.

Reference parity: ``UDPSocketPool`` (even-RTP/odd-RTCP port pairs,
``UDPSocketPool.h``), ``RTPStream``'s UDP send (``RTPStream.cpp:1145``) and
TCP interleaved send (``InterleavedWrite``, ``RTPStream.cpp:772``), the
reflector's ingest sockets (``ReflectorSocket``), and the WouldBlock
flow-control contract (``RTPSessionOutput.cpp:610-620``): a stalled client
must never stall the relay — the output reports WOULD_BLOCK and replays from
its bookmark on the next pass.
"""

from __future__ import annotations

import asyncio
from typing import Callable

from ..protocol import rtsp
from ..relay.output import RelayOutput, WriteResult

#: default interleaved write-buffer high water mark: past this the output
#: reports WOULD_BLOCK (the reference gets EAGAIN from a 96 KB SO_SNDBUF,
#: TCPListenerSocket.cpp:189-190)
HIGH_WATER = 256 * 1024


class InterleavedOutput(RelayOutput):
    """$-framed RTP/RTCP egress over the client's RTSP TCP connection.

    Engine fast path (ISSUE 14): the TPU engine recognizes these by
    ``interleave_chan``/``stream_fd`` and frames whole ring spans
    through the native writev/io_uring stream sender — byte-identical
    to the per-packet ``_send`` below, differential-tested over real
    sockets.  Raw fd writes are only legal while the asyncio transport
    buffer is EMPTY (``engine_writable``): bytes queued in the
    transport must never be overtaken mid-stream.  A short native write
    hands the torn packet's remainder to ``push_tail`` (the transport),
    which then owns ordering until the buffer drains."""

    def __init__(self, transport: asyncio.WriteTransport,
                 rtp_channel: int, rtcp_channel: int, **kw):
        super().__init__(**kw)
        self.transport = transport
        self.rtp_channel = rtp_channel
        self.rtcp_channel = rtcp_channel
        sock = None
        try:
            sock = transport.get_extra_info("socket")
        except Exception:
            sock = None
        #: raw stream-socket fd for the native framed sender; -1 when
        #: the transport cannot expose one (TLS/tunnel/test harness) —
        #: such outputs stay on the buffered batch-header rung
        try:
            self.stream_fd = sock.fileno() if sock is not None else -1
        except (OSError, AttributeError):
            self.stream_fd = -1

    @property
    def interleave_chan(self) -> int:
        """The RTP interleave channel byte — the per-output framing
        constant that rides the affine device pass (ops.fanout chan
        column)."""
        return self.rtp_channel

    def engine_writable(self) -> bool:
        """True when raw fd writes cannot reorder around buffered
        bytes: transport open, fd known, and the transport's user-space
        write buffer fully drained."""
        tr = self.transport
        return (self.stream_fd >= 0 and not tr.is_closing()
                and tr.get_write_buffer_size() == 0)

    def push_tail(self, data: bytes) -> bool:
        """Queue a torn packet's remaining bytes through the transport
        (which then owns connection ordering).  False when the
        transport died — the caller accounts the span as errored."""
        tr = self.transport
        if tr.is_closing():
            return False
        tr.write(data)
        return True

    def _send(self, channel: int, chunks: tuple[bytes, ...]) -> WriteResult:
        tr = self.transport
        if tr.is_closing():
            return WriteResult.ERROR
        if tr.get_write_buffer_size() > HIGH_WATER:
            return WriteResult.WOULD_BLOCK
        n = sum(len(c) for c in chunks)
        tr.write(b"$" + bytes((channel,)) + n.to_bytes(2, "big"))
        for c in chunks:
            tr.write(c)
        return WriteResult.OK

    def send_bytes(self, data: bytes, *, is_rtcp: bool) -> WriteResult:
        ch = self.rtcp_channel if is_rtcp else self.rtp_channel
        return self._send(ch, (data,))

    def send_rewritten(self, header: bytes, tail: bytes) -> WriteResult:
        if self.meta_field_ids is not None:     # negotiated meta-info wrap
            return self.send_bytes(self.wrap_meta(header, tail),
                                   is_rtcp=False)
        return self._send(self.rtp_channel, (header, tail))


class UdpOutput(RelayOutput):
    """RTP/RTCP egress to a client's UDP port pair."""

    def __init__(self, rtp_transport: asyncio.DatagramTransport,
                 rtcp_transport: asyncio.DatagramTransport | None,
                 client_ip: str, client_rtp_port: int,
                 client_rtcp_port: int, **kw):
        super().__init__(**kw)
        self.rtp_transport = rtp_transport
        self.rtcp_transport = rtcp_transport
        self.rtp_addr = (client_ip, client_rtp_port)
        self.rtcp_addr = (client_ip, client_rtcp_port)

    def send_bytes(self, data: bytes, *, is_rtcp: bool) -> WriteResult:
        tr = self.rtcp_transport if is_rtcp else self.rtp_transport
        if tr is None:
            return WriteResult.OK
        if tr.is_closing():
            return WriteResult.ERROR
        tr.sendto(data, self.rtcp_addr if is_rtcp else self.rtp_addr)
        return WriteResult.OK

    def send_rewritten(self, header: bytes, tail: bytes) -> WriteResult:
        if self.meta_field_ids is not None:     # negotiated meta-info wrap
            return self.send_bytes(self.wrap_meta(header, tail),
                                   is_rtcp=False)
        if self.rtp_transport.is_closing():
            return WriteResult.ERROR
        self.rtp_transport.sendto(header + tail, self.rtp_addr)
        return WriteResult.OK


class _DatagramSink(asyncio.DatagramProtocol):
    def __init__(self, on_packet: Callable[[bytes, tuple], None] | None = None):
        self.on_packet = on_packet
        self.transport: asyncio.DatagramTransport | None = None

    def connection_made(self, transport):
        self.transport = transport

    def datagram_received(self, data, addr):
        if self.on_packet is not None:
            self.on_packet(data, addr)


class UdpPair:
    """One bound even/odd (RTP, RTCP) endpoint pair."""

    def __init__(self, rtp_transport, rtp_proto, rtcp_transport, rtcp_proto,
                 rtp_port: int):
        self.rtp_transport: asyncio.DatagramTransport = rtp_transport
        self.rtp_proto: _DatagramSink = rtp_proto
        self.rtcp_transport: asyncio.DatagramTransport = rtcp_transport
        self.rtcp_proto: _DatagramSink = rtcp_proto
        self.rtp_port = rtp_port

    @property
    def rtcp_port(self) -> int:
        return self.rtp_port + 1

    def close(self) -> None:
        for t in (self.rtp_transport, self.rtcp_transport):
            if t and not t.is_closing():
                t.close()


class NativeIngestPair(UdpPair):
    """A ``UdpPair`` whose RTP side is a plain non-blocking socket drained
    by a readiness callback in native recvmmsg batches — the reference's
    event-drain role (``EventContext.cpp:190-335`` →
    ``ReflectorSocket::GetIncomingData``) with one syscall per 64
    datagrams instead of one asyncio callback per datagram."""

    def __init__(self, rtp_sock, rtcp_transport, rtcp_proto, rtp_port: int,
                 loop, on_readable, *, uring: bool = False):
        self.rtp_sock = rtp_sock
        self.rtp_transport = None
        self.rtp_proto = None
        self.rtcp_transport = rtcp_transport
        self.rtcp_proto = rtcp_proto
        self.rtp_port = rtp_port
        self._loop = loop
        self._fd = rtp_sock.fileno()
        # multishot io_uring ingest (ISSUE 8): armed/disarmed with the
        # PAIR's lifetime so a recycled fd number can never route a new
        # socket's drain through a stale ring; native.udp_ingest falls
        # back to recvmmsg transparently when arming is refused.  When
        # armed, the event loop watches the RING's pollable fd, not the
        # socket: the multishot arm consumes the socket queue before
        # epoll sees it, so socket readability would never fire and
        # completions would strand until the buffer pool exhausted.
        self._uring_armed = False
        self._watch_fds = [self._fd]
        if uring:
            from .. import native
            ring_fd = native.uring_ingest_arm(self._fd)
            if ring_fd is not None:
                self._uring_armed = True
                # watch BOTH: the ring fires in steady state; the socket
                # only becomes readable again if the ring dies (drain
                # error → disarm), which keeps the recvmmsg fallback
                # reachable instead of stalling a watched-ring-only pair
                self._watch_fds.append(ring_fd)
        # the callback always receives the SOCKET fd: drains are keyed
        # by it (native.udp_ingest routes armed fds through the ring)
        for wfd in self._watch_fds:
            loop.add_reader(wfd, on_readable, self._fd)

    def prune_ring_watch(self) -> None:
        """Drop the ring-fd watch after a native-level fallback disarm.

        ``native.udp_ingest`` closes a failing ring mid-drain (io_uring
        degradation → recvmmsg); the freed fd NUMBER must leave the
        event loop immediately — epoll auto-drops a closed fd but
        asyncio's Python-side key map does not, so the next socket that
        recycles the number inherits a stale registration and dies in
        ``selector.modify`` (FileNotFoundError)."""
        if not self._uring_armed:
            return
        from .. import native
        if native.uring_ingest_armed(self._fd):
            return
        for wfd in self._watch_fds[1:]:
            try:
                self._loop.remove_reader(wfd)
            except Exception:
                pass
        self._watch_fds = [self._fd]
        self._uring_armed = False

    def close(self) -> None:
        if self.rtp_sock is not None:
            for wfd in self._watch_fds:
                try:
                    self._loop.remove_reader(wfd)
                except Exception:
                    pass
            if self._uring_armed:
                from .. import native
                native.uring_ingest_disarm(self._fd)
                self._uring_armed = False
            self.rtp_sock.close()
            self.rtp_sock = None
        if self.rtcp_transport and not self.rtcp_transport.is_closing():
            self.rtcp_transport.close()


class UdpPortPool:
    """Allocates even/odd UDP port pairs (``UDPSocketPool`` equivalent)."""

    def __init__(self, bind_ip: str = "0.0.0.0", base_port: int = 6970,
                 max_pairs: int = 4000):
        self.bind_ip = bind_ip
        self.base_port = base_port
        self.max_pairs = max_pairs
        self._next = base_port

    async def _scan(self, make_rtp, on_rtcp):
        """Shared even/odd port scan: ``make_rtp(loop, port)`` returns
        ``(rtp_obj, close_fn)`` or raises OSError; the odd RTCP endpoint
        binds second with rollback.  Returns (rtp_obj, rtcp_t, rtcp_p,
        port)."""
        loop = asyncio.get_running_loop()
        last_err: Exception | None = None
        for _ in range(self.max_pairs):
            port = self._next
            self._next += 2
            if self._next >= self.base_port + 2 * self.max_pairs:
                self._next = self.base_port
            try:
                rtp_obj, rtp_close = await make_rtp(loop, port)
            except OSError as e:
                last_err = e
                continue
            try:
                rtcp_t, rtcp_p = await loop.create_datagram_endpoint(
                    lambda: _DatagramSink(on_rtcp),
                    local_addr=(self.bind_ip, port + 1))
            except OSError as e:
                rtp_close()
                last_err = e
                continue
            return rtp_obj, rtcp_t, rtcp_p, port
        raise OSError(f"no free UDP port pairs: {last_err}")

    async def allocate(self, on_rtp=None, on_rtcp=None) -> UdpPair:
        async def make_rtp(loop, port):
            rtp_t, rtp_p = await loop.create_datagram_endpoint(
                lambda: _DatagramSink(on_rtp),
                local_addr=(self.bind_ip, port))
            return (rtp_t, rtp_p), rtp_t.close

        (rtp_t, rtp_p), rtcp_t, rtcp_p, port = await self._scan(make_rtp,
                                                                on_rtcp)
        return UdpPair(rtp_t, rtp_p, rtcp_t, rtcp_p, port)

    async def allocate_native(self, on_readable, on_rtcp=None,
                              uring: bool = False) -> NativeIngestPair:
        """Pair whose RTP socket feeds the native recvmmsg drain:
        ``on_readable(fd)`` runs once per readiness edge and drains a
        whole batch, instead of one asyncio callback per datagram.
        ``uring=True`` arms multishot io_uring ingest for the socket
        (capability-gated; the recvmmsg drain stays the fallback)."""
        import socket as socket_mod

        async def make_rtp(loop, port):
            s = socket_mod.socket(socket_mod.AF_INET, socket_mod.SOCK_DGRAM)
            s.setblocking(False)
            try:
                s.bind((self.bind_ip, port))
                s.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_RCVBUF,
                             1 << 21)
            except OSError:
                s.close()
                raise
            return s, s.close

        rtp_sock, rtcp_t, rtcp_p, port = await self._scan(make_rtp, on_rtcp)
        loop = asyncio.get_running_loop()
        return NativeIngestPair(rtp_sock, rtcp_t, rtcp_p, port, loop,
                                on_readable, uring=uring)
