"""Layered server configuration.

The reference layers CLI getopt → XML prefs (``easydarwin.xml``) → a typed
table of ~85 prefs with defaults (``QTSServerPrefs.cpp:190-280``) → SIGHUP /
REST-triggered ``RereadPrefs`` role rebroadcast.  Here: a typed dataclass
with the same key prefs, TOML load/save (stdlib ``tomllib``), and change
listeners that components subscribe to (the RereadPrefs equivalent).
"""

from __future__ import annotations

import dataclasses

try:
    import tomllib
except ModuleNotFoundError:        # Python < 3.11: same API from tomli
    import tomli as tomllib        # type: ignore[no-redef]
from dataclasses import dataclass, field
from typing import Callable


@dataclass
class ServerConfig:
    # --- core ports (QTSServerPrefs: rtsp_port 222, service ports 273-274)
    rtsp_port: int = 10554
    service_port: int = 10008          # REST API (service_lan_port)
    bind_ip: str = "0.0.0.0"
    # --- relay tuning (ReflectorStream.cpp:56-68 + prefs)
    bucket_size: int = 16
    bucket_delay_ms: int = 73
    overbuffer_sec: float = 10.0
    max_packet_age_sec: float = 20.0
    ring_capacity: int = 4096
    reflect_interval_ms: int = 20      # sender wake cadence (ref: 200 ms)
    # --- session management
    rtsp_timeout_sec: int = 120        # idle RTSP session kill
    push_timeout_sec: int = 20         # broadcaster refresh window
    timeout_sweep_sec: int = 15        # TimeoutTask.h:66 granularity
    # --- VOD
    movie_folder: str = "/tmp/movies"
    # --- VOD segment cache (ISSUE 10: vod/cache.py + the group pacer).
    # On: PLAY on a file path is served by the shared group pacer — hot
    # assets' samples are pre-packed into the fixed-slot ring-window
    # format once and every subscriber rides the same megabatch/affine
    # engine as live relay; cache misses stream through the cold mmap
    # path while a background fill packs the window.  Off: every player
    # gets the per-session asyncio FileSession (the pre-ISSUE-10 path,
    # still used for Scale/meta-info/hinted sessions either way).
    vod_cache_enabled: bool = True
    vod_cache_bytes: int = 268_435_456     # LRU byte budget (host + HBM)
    vod_cache_window_samples: int = 64     # samples packed per window
    vod_cache_lookahead_ms: int = 500      # pacer ring-fill horizon
    # keep each packed window's staged rows HBM-resident (uploaded once,
    # shared by every subscriber on that window) so a hot join's affine
    # prime pass costs zero H2D; host-only caching when off
    vod_cache_device: bool = True
    # --- DVR / time-shift (ISSUE 12: dvr/).  On: every pushed live
    # session's completed ring windows spill to
    # <movie_folder>/.dvr/<path>/ already in the fixed-slot packed
    # serving format (pack-at-record-time); live subscribers can PAUSE
    # and PLAY with Range: into the past (served by the VOD pacer from
    # the spill, catch-up rejoining live gapless), and stopping a
    # recording finalizes an instantly-servable <path>.dvr asset.
    # Requires vod_cache_enabled (the spill serves through the segment
    # cache's zero-repack open path).
    dvr_enabled: bool = False
    dvr_window_pkts: int = 64              # packets per spill window
    dvr_retention_bytes: int = 67_108_864  # per-track spill byte budget
    dvr_retention_sec: float = 600.0       # per-track spill duration cap
    # --- erasure-coded fleet storage (ISSUE 20: storage/).  On: every
    # FINALIZED .dvr asset is sharded into k data + m parity window
    # shards (the GF(256) engine's device matmul, host-oracle-checked)
    # striped across the live lease set under fenced Shard: claims; a
    # read missing <= m shards reconstructs transparently through the
    # spill chain's restore hook, scrub re-verifies local shards against
    # manifest crc32s, and a dead holder's shards are re-derived onto
    # ring successors as background math, not byte copies.  Requires
    # dvr_enabled; works single-node (all shards local — still gives
    # crc-scrubbed, reconstruct-on-corruption durability).
    storage_enabled: bool = False
    storage_data_shards: int = 4           # k: data shards per stripe
    storage_parity_shards: int = 2         # m: parity shards (loss budget)
    storage_scrub_interval_sec: float = 30.0
    storage_device: bool = True            # parity on device w/ host oracle
    # --- dynamic modules (QTSServer::LoadModules / module_folder pref)
    module_folder: str = ""            # "" = no dynamic modules
    # --- device tier
    tpu_fanout: bool = False           # batch engine instead of scalar loop
    tpu_min_outputs: int = 8           # below this the scalar loop wins
    # cross-stream megabatch scheduler (relay/megabatch.py): coalesce all
    # engine-eligible streams into one shape-bucketed device pass per pump
    # wake, with double-buffered H2D staging.  Off → every stream pays its
    # own per-wake device dispatch (the pre-ISSUE-4 behavior).
    megabatch_enabled: bool = True
    # below this many engine-eligible streams the coalescing overhead
    # isn't worth a stacked pass; per-stream stepping is used as-is
    megabatch_min_streams: int = 2
    # devices the megabatch serves from (ISSUE 7): 1 = the default
    # single-device dispatch; N > 1 = shard each shape bucket's stream
    # axis over the first N local devices (parallel.mesh src-only mesh);
    # 0 = every local device.  Clamped to what the box actually has —
    # a 1-device box always degrades to the single-device path
    megabatch_devices: int = 1
    # shared UDP egress pair for players (RTPSocketPool/UDPDemuxer shape;
    # required by the native sendmmsg/GSO fan-out). Falls back to per-client
    # port pairs when off or when the native core is unavailable.
    shared_udp_egress: bool = True
    # egress backend ladder (ISSUE 8): "auto" = best rung the boot-time
    # capability probe grants (io_uring with registered buffers/SQPOLL/
    # zerocopy where the kernel has it, the GSO/sendmmsg pair otherwise);
    # "io_uring"/"gso" force a rung (a forced-but-unavailable io_uring
    # degrades to gso with ONE egress.backend_fallback event); "scalar"
    # forces the per-datagram sendto baseline
    egress_backend: str = "auto"
    # first-class TCP/HTTP delivery (ISSUE 14): interleaved-RTSP
    # subscribers ride the engine's framed writev/io_uring stream path
    # (vectorized $-framing in the same affine device pass as the UDP
    # rewrite).  Off → TCP outputs serve from the per-session
    # batch-header rung, the pre-ISSUE-14 behavior (also the bench's
    # honest baseline).
    tcp_engine_enabled: bool = True
    # x-Retransmit (reliable UDP) negotiation in SETUP — the reference's
    # reliable_udp pref (QTSServerPrefs; RTPStream.cpp:448 gate)
    reliable_udp: bool = True
    # --- lossy-WAN reliability tier (ISSUE 11: relay/fec.py).  On: every
    # plain-UDP subscriber gets a closed-loop FEC encoder (overhead 0
    # until its RRs report loss — a clean last mile costs nothing) and
    # the RFC 4585 generic-NACK → ring-bookmark RTX replay rung.  The
    # x-Retransmit reliable-UDP wrap supersedes it per output (its own
    # ack-driven resend window already owns that subscriber's loss).
    fec_enabled: bool = True
    fec_window: int = 16               # media packets per parity window
    fec_max_overhead: float = 0.30     # parity budget ceiling (ratio)
    fec_kind: str = "rs"               # rs | xor (xor caps parity at 1 row)
    fec_payload_type: int = 127        # parity packets' RTP PT
    rtx_payload_type: int = 126        # RTX replays' RTP PT
    rtx_budget_per_sec: float = 64.0   # per-output replay token refill
    rtx_burst: int = 32                # token bucket depth
    # device-side parity (host GF oracle checked per row; a mismatch
    # degrades the stream to host parity).  Off = host parity only.
    fec_device: bool = True
    # UDP push ingest via the native recvmmsg ring drain (one syscall per
    # 64 datagrams) instead of per-datagram asyncio callbacks; falls back
    # automatically when the native core is unavailable
    native_ingest: bool = True
    # --- cluster (EasyRedisModule / EasyCMS prefs)
    cloud_enabled: bool = False
    redis_host: str = "127.0.0.1"
    redis_port: int = 6379
    server_id: str = "easydarwin-tpu-0"
    cms_host: str = "127.0.0.1"
    cms_port: int = 10000
    wan_ip: str = "127.0.0.1"
    # --- fault-tolerant cluster tier (cluster/service.py: Redis leases +
    # fencing, consistent-hash placement, cross-server pull relay,
    # checkpoint-driven live session migration).  Supersedes the passive
    # cloud_enabled presence when on.
    cluster_enabled: bool = False
    cluster_lease_ttl_sec: float = 5.0     # lease TTL = failure-detect time
    cluster_heartbeat_sec: float = 1.0     # service tick cadence
    cluster_vnodes: int = 64               # ring points per node
    cluster_own_ttl_sec: float = 30.0      # Own:{path} record TTL
    cluster_migration_ttl_sec: float = 30.0  # Ckpt:{path} record TTL
    # cross-server pull relay envelope (cluster/pull.py)
    cluster_pull_connect_timeout_sec: float = 5.0
    cluster_pull_read_timeout_sec: float = 5.0   # no packet → stall
    cluster_pull_backoff_ms: float = 200.0       # first retry (doubles)
    cluster_pull_backoff_cap_ms: float = 5000.0
    cluster_pull_jitter_frac: float = 0.25       # ± anti-stampede jitter
    cluster_pull_breaker_failures: int = 5       # consecutive → open
    cluster_pull_breaker_open_sec: float = 10.0
    # --- load-aware control plane (ISSUE 13: cluster/capacity.py + the
    # Rebalancer in cluster/service.py).  Each node publishes a capacity
    # score (boot-time self-bench of the relay fan-out path, in relayed
    # pkts/sec; pin it here with a value > 0 to skip the bench) plus
    # live utilization into its fenced lease record; the hash ring
    # weights vnode counts by capacity, new SETUPs past the admission
    # high-water mark answer 453 or a 305 redirect to the placement-
    # resolved edge, and the rebalancer drains a sustained-burning
    # node's hottest stream to the least-loaded peer.
    cluster_capacity_score: float = 0.0          # 0 = boot self-bench
    cluster_admission_enabled: bool = True
    cluster_admission_high_water: float = 0.85   # util ratio gate
    cluster_rebalance_enabled: bool = True
    cluster_rebalance_high_water: float = 0.9    # sustained-burn level
    cluster_rebalance_low_water: float = 0.5     # target headroom gate
    cluster_rebalance_burn_sec: float = 10.0     # sustained-burn window
    cluster_rebalance_cooldown_sec: float = 30.0  # min gap between moves
    # --- auth / misc
    auth_enabled: bool = False
    rest_username: str = "admin"
    rest_password: str = "admin"
    rtsp_auth_enabled: bool = False
    users_file: str = ""               # qtpasswd-style user:realm:ha1
    auth_scheme: str = "digest"        # digest | basic
    max_connections: int = 20000       # epollEvent.cpp:16 MAX_EPOLL_FD
    # per-IP cap (QTSSSpamDefenseModule num_conns_per_ip; 0 = unlimited,
    # matching the reference's Linux build which omits the module)
    max_connections_per_ip: int = 0
    # --- SLO watchdog (obs/slo.py: multi-window burn-rate budgets over
    # the obs families, evaluated once per pump maintenance tick)
    slo_enabled: bool = True
    slo_latency_objective_ms: float = 50.0   # a good packet hits the wire…
    slo_latency_target: float = 0.99         # …within this for 99% of them
    slo_drop_objective: float = 0.01         # budgeted bad-packet fraction
    slo_fast_window_sec: float = 60.0
    slo_slow_window_sec: float = 600.0
    slo_fast_burn: float = 14.0              # SRE-workbook page-tier rates
    slo_slow_burn: float = 2.0
    slo_min_events: int = 200                # below this a window is noise
    # --- resilience (easydarwin_tpu/resilience/: deterministic fault
    # injection, health-driven degradation ladder, session checkpoint)
    resilience_enabled: bool = True          # degradation ladder active
    # FaultPlan spec armed at startup (chaos testing), e.g.
    # "seed=7,ingest_drop=0.05,egress_enobufs_every=300"; "" = none
    resilience_fault_plan: str = ""
    resilience_recover_sec: float = 10.0     # clean time per rung climbed
    resilience_max_retries: int = 3          # device retries before a drop
    resilience_backoff_ms: float = 250.0     # first retry backoff (doubles)
    # session checkpoint/hot-restore (<log_folder>/ckpt/): off by default
    # — a restore resurrects sessions from the PREVIOUS process, which an
    # operator opts into (the supervisor deployment), not a test run
    # sharing /tmp state
    resilience_checkpoint_enabled: bool = False
    resilience_checkpoint_interval_sec: float = 5.0
    # a checkpoint older than this is ignored at startup (stale files
    # must not resurrect long-dead sessions)
    resilience_checkpoint_max_age_sec: float = 60.0
    # --- status (RunServer.cpp:248-483: -S console + server_status file)
    stats_interval_sec: int = 0        # 0 = console display off
    status_file_path: str = ""         # "" = no status file
    status_file_interval_sec: int = 10
    # --- logging (QTSSRollingLog / AccessLog / ErrorLog prefs)
    log_folder: str = "/tmp/edtpu_logs"
    access_log_enabled: bool = True
    error_log_verbosity: str = "info"  # fatal|warning|info|debug

    _listeners: list[Callable[["ServerConfig"], None]] = field(
        default_factory=list, repr=False, compare=False)

    # -- reread-prefs machinery -------------------------------------------
    def on_change(self, fn: Callable[["ServerConfig"], None]) -> None:
        self._listeners.append(fn)

    def update(self, **kw) -> None:
        """Apply new values and rebroadcast (the RereadPrefs role)."""
        for k, v in kw.items():
            if k.startswith("_") or not hasattr(self, k):
                raise KeyError(f"unknown pref {k!r}")
            cur = getattr(self, k)
            setattr(self, k, type(cur)(v) if cur is not None else v)
        for fn in list(self._listeners):
            fn(self)

    # -- (de)serialization -------------------------------------------------
    def to_dict(self) -> dict:
        return {f.name: getattr(self, f.name)
                for f in dataclasses.fields(self)
                if not f.name.startswith("_")}

    @classmethod
    def from_dict(cls, d: dict) -> "ServerConfig":
        known = {f.name for f in dataclasses.fields(cls) if not f.name.startswith("_")}
        return cls(**{k: v for k, v in d.items() if k in known})

    @classmethod
    def from_toml(cls, path: str) -> "ServerConfig":
        with open(path, "rb") as f:
            return cls.from_dict(tomllib.load(f))

    def to_toml(self) -> str:
        out = []
        for k, v in self.to_dict().items():
            if isinstance(v, bool):
                out.append(f"{k} = {'true' if v else 'false'}")
            elif isinstance(v, (int, float)):
                out.append(f"{k} = {v}")
            else:
                out.append(f'{k} = "{v}"')
        return "\n".join(out) + "\n"

    # -- derived -----------------------------------------------------------
    def egress_backend_choice(self) -> str:
        """The validated ``egress_backend`` pref.  A typo'd backend must
        fail the boot loudly — silently serving from a rung the operator
        didn't pick would void every forced-backend soak."""
        from ..relay.fanout import EGRESS_BACKENDS
        v = self.egress_backend.strip().lower()
        if v not in EGRESS_BACKENDS:
            raise ValueError(
                f"egress_backend {self.egress_backend!r} not one of "
                f"{EGRESS_BACKENDS}")
        return v

    def slo_config(self):
        from ..obs.slo import SloConfig
        return SloConfig(
            latency_objective_ms=self.slo_latency_objective_ms,
            latency_target=self.slo_latency_target,
            drop_objective=self.slo_drop_objective,
            fast_window_s=self.slo_fast_window_sec,
            slow_window_s=self.slo_slow_window_sec,
            fast_burn=self.slo_fast_burn,
            slow_burn=self.slo_slow_burn,
            min_events=self.slo_min_events)

    def cluster_config(self):
        from ..cluster.pull import PullConfig
        from ..cluster.service import ClusterConfig
        return ClusterConfig(
            self.server_id, ip=self.wan_ip,
            lease_ttl_sec=self.cluster_lease_ttl_sec,
            heartbeat_sec=self.cluster_heartbeat_sec,
            vnodes=self.cluster_vnodes,
            own_ttl_sec=self.cluster_own_ttl_sec,
            migration_ttl_sec=self.cluster_migration_ttl_sec,
            rebalance_enabled=self.cluster_rebalance_enabled,
            rebalance_high_water=self.cluster_rebalance_high_water,
            rebalance_low_water=self.cluster_rebalance_low_water,
            rebalance_burn_sec=self.cluster_rebalance_burn_sec,
            rebalance_cooldown_sec=self.cluster_rebalance_cooldown_sec,
            admission_enabled=self.cluster_admission_enabled,
            admission_high_water=self.cluster_admission_high_water,
            pull=PullConfig(
                connect_timeout_sec=self.cluster_pull_connect_timeout_sec,
                read_timeout_sec=self.cluster_pull_read_timeout_sec,
                backoff_ms=self.cluster_pull_backoff_ms,
                backoff_cap_ms=self.cluster_pull_backoff_cap_ms,
                jitter_frac=self.cluster_pull_jitter_frac,
                breaker_failures=self.cluster_pull_breaker_failures,
                breaker_open_sec=self.cluster_pull_breaker_open_sec))

    def fec_config(self):
        """The validated reliability-tier config (raises at boot on a
        bad window/kind — a typo'd tier silently protecting nothing
        would void every lossy soak)."""
        from ..relay.fec import FecConfig
        return FecConfig(
            window=self.fec_window,
            max_overhead=self.fec_max_overhead,
            kind=self.fec_kind,
            payload_type=self.fec_payload_type,
            rtx_payload_type=self.rtx_payload_type,
            rtx_budget_per_sec=self.rtx_budget_per_sec,
            rtx_burst=self.rtx_burst,
            use_device=self.fec_device).validate()

    def ladder_config(self):
        from ..resilience.ladder import LadderConfig
        return LadderConfig(
            recover_sec=self.resilience_recover_sec,
            max_retries=self.resilience_max_retries,
            backoff_ms=self.resilience_backoff_ms)

    def fault_plan(self):
        """The armed FaultPlan, or None when no chaos spec is set.  A
        malformed spec raises at startup — a typo'd plan that silently
        injects nothing would void the chaos run it was meant to drive."""
        if not self.resilience_fault_plan.strip():
            return None
        from ..resilience.inject import FaultPlan
        return FaultPlan.parse(self.resilience_fault_plan)

    def stream_settings(self):
        from ..relay.stream import StreamSettings
        return StreamSettings(
            bucket_size=self.bucket_size,
            bucket_delay_ms=self.bucket_delay_ms,
            overbuffer_ms=int(self.overbuffer_sec * 1000),
            max_age_ms=int(self.max_packet_age_sec * 1000),
            ring_capacity=self.ring_capacity)


# -- reference easydarwin.xml migration --------------------------------------

def _bool(v: str) -> bool:
    """Strict DSS bool: anything but true/false is reported, not coerced
    (a hand-edited 'True'/'1' must not silently become False)."""
    if v == "true":
        return True
    if v == "false":
        return False
    raise ValueError(f"not a DSS bool: {v!r}")


def _verbosity(v: str) -> str:
    i = int(v)
    if not 0 <= i <= 4:                 # DSS levels 0..4; reject garbage
        raise ValueError(f"verbosity {v!r} out of range")
    return ("fatal", "warning", "info", "info", "debug")[i]


#: reference pref name → (our field, converter).  Server-level prefs plus
#: the per-module sections users actually tune (QTSServerPrefs.cpp:190-280,
#: ReflectorStream::Register, EasyRedisModule prefs).
_XML_SERVER_MAP = {
    "rtsp_port": ("rtsp_port", int),                 # LIST-PREF: first value
    "service_lan_port": ("service_port", int),
    # http_service_port is DSS's RTSP-over-HTTP tunneling port, NOT the
    # REST service port — tunneling here rides the RTSP port itself, so
    # the pref is intentionally left unmapped
    "service_wan_ip": ("wan_ip", str),
    "bind_ip_addr": ("bind_ip",
                     lambda v: "0.0.0.0" if v in ("", "0") else v),
    "movie_folder": ("movie_folder", str),
    "maximum_connections": ("max_connections", int),
    "rtsp_session_timeout": ("rtsp_timeout_sec", int),
    "enable_cloud_platform": ("cloud_enabled", _bool),
    "authentication_scheme": ("auth_scheme", str),
    "error_logfile_verbosity": ("error_log_verbosity", _verbosity),
    "monitor_stats_file_name": ("status_file_path", str),
    "monitor_stats_file_interval_seconds": ("status_file_interval_sec", int),
}

_XML_MODULE_MAP = {
    ("QTSSReflectorModule", "reflector_bucket_offset_delay_msec"):
        ("bucket_delay_ms", int),
    ("QTSSReflectorModule", "reflector_buffer_size_sec"):
        ("overbuffer_sec", float),
    ("QTSSReflectorModule", "timeout_broadcaster_session_secs"):
        ("push_timeout_sec", int),
    ("QTSSAccessLogModule", "request_logging"):
        ("access_log_enabled", _bool),
    ("EasyRedisModule", "redis_ip"): ("redis_host", str),
    ("EasyRedisModule", "redis_port"): ("redis_port", int),
    ("EasyCMSModule", "cms_ip"): ("cms_host", str),
    ("EasyCMSModule", "cms_port"): ("cms_port", int),
}


def load_reference_xml(path: str) -> tuple["ServerConfig", list[str]]:
    """Load the reference's ``easydarwin.xml`` (the DSS ``PREF``/``MODULE``
    DTD, ``PrefsSourceLib/XMLPrefsParser.cpp``) into a ``ServerConfig``.

    Returns ``(config, unmapped)`` — ``unmapped`` lists reference pref
    names with no counterpart here (thinning windows, reliable-UDP
    internals, … — tuned automatically in this implementation), so a
    migrating operator can see exactly what was dropped.
    """
    import xml.etree.ElementTree as ET

    root = ET.parse(path).getroot()
    cfg = ServerConfig()
    unmapped: list[str] = []
    monitor_enabled = False

    def pref_value(el, label: str) -> str:
        if el.tag == "LIST-PREF":
            vals = el.findall("VALUE")
            if len(vals) > 1:           # only the first value carries over
                unmapped.append(
                    f"{label} (extra values dropped: "
                    f"{[(v.text or '').strip() for v in vals[1:]]})")
            return (vals[0].text or "").strip() if vals else ""
        return (el.text or "").strip()

    def apply(el, label: str, ent) -> None:
        if ent is None:
            unmapped.append(label)
            return
        field, conv = ent
        raw = pref_value(el, label)
        try:
            setattr(cfg, field, conv(raw))
        except ValueError:              # mapped name, malformed value
            unmapped.append(f"{label} (invalid value {raw!r})")

    server = root.find("SERVER")
    for el in (server if server is not None else []):
        if el.tag not in ("PREF", "LIST-PREF"):
            continue
        name = el.get("NAME", "")
        if name == "enable_monitor_stats_file":
            monitor_enabled = pref_value(el, name) == "true"
            continue
        apply(el, name, _XML_SERVER_MAP.get(name))
    for mod in root.findall("MODULE"):
        mod_name = mod.get("NAME", "")
        for el in mod:
            if el.tag not in ("PREF", "LIST-PREF"):
                continue
            name = el.get("NAME", "")
            apply(el, f"{mod_name}/{name}",
                  _XML_MODULE_MAP.get((mod_name, name)))
    if not monitor_enabled:
        cfg.status_file_path = ""       # file name without the enable flag
    return cfg, unmapped
