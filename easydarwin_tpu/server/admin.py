"""Admin dictionary-tree browse API (QTSSAdminModule parity).

Reference: ``QTSSAdminModule.cpp:365-1073`` + ``AdminQuery.cpp`` +
``AdminElementNode.cpp`` — the legacy ``/modules/admin`` API walks the
server's reflective attribute dictionaries as a filesystem-like tree with
``command=get|set`` queries, ``*`` wildcards and an optional recurse flag.

Here the same browse semantics sit on the JSON REST port: the tree is
assembled on demand from live server state (info, prefs, sessions,
modules), paths are ``/``-separated with a trailing ``*`` to list
children, and ``command=set`` writes a pref through the same validated
``ServerConfig.update`` path the setbaseconfig route uses.  The mongoose
web UI is intentionally superseded by ``/stats`` + this endpoint.
"""

from __future__ import annotations

from typing import Any


#: role hook names on server.modules.Module (QTSSModule.h:126-163 analogue)
ROLE_HOOKS = ("initialize", "shutdown", "reread_prefs", "rtsp_filter",
              "rtsp_route", "authorize", "rtsp_postprocess",
              "session_closing", "incoming_rtp")


def _module_attrs(module) -> dict:
    """Module-added attributes (QTSS_AddStaticAttribute analogue) under
    an ``attrs`` node; a module raising inside its own hook must not
    take the whole tree down."""
    try:
        attrs = module.attributes()
        if attrs:
            import json
            json.dumps(attrs)          # a non-serializable value would
    except Exception as e:             # break every tree query that
        return {"attrs_error": str(e)}  # includes this node, not just
    return {"attrs": attrs} if attrs else {}  # the module's own path


def _roles_of(module) -> list[str]:
    """Roles a module registers for = hooks it overrides (the dispatch
    arrays in QTSServer::BuildModuleRoleArrays, rebuilt by reflection)."""
    from .modules import Module
    return sorted(r for r in ROLE_HOOKS
                  if any(r in klass.__dict__
                         for klass in type(module).__mro__
                         if klass is not Module and klass is not object))


def build_tree(app) -> dict[str, Any]:
    """Assemble the browseable dictionary tree from live server state.

    Mirrors the reference's top-level element list (AdminElementNode
    ``GetElementFromArray``): server attributes, prefs, connected
    sessions, loaded modules."""
    sessions = {}
    for s in app.live_sessions():
        sessions[s["Path"].strip("/").replace("/", "~")] = dict(s)
    cfg = {k: v for k, v in app.config.to_dict().items()
           if k != "rest_password"}
    return {
        "server": {
            "info": dict(app.server_info()),
            "prefs": cfg,
            "sessions": sessions,
            "modules": {m.name: {"roles": _roles_of(m),
                                 **_module_attrs(m)}
                        for m in getattr(app.modules, "modules", [])},
        },
    }


def query(app, path: str, *, recurse: bool = False) -> tuple[int, Any]:
    """``command=get`` — resolve a tree path.

    Returns (status, payload).  A trailing ``*`` lists children one level
    deep (or the whole subtree with ``recurse``); a concrete path returns
    the node value.  Unknown paths → 404, like the reference's
    404-in-body answers (QTSSAdminModule.cpp ReportErr)."""
    tree: Any = build_tree(app)
    parts = [p for p in path.strip("/").split("/") if p]
    wildcard = bool(parts) and parts[-1] == "*"
    if wildcard:
        parts = parts[:-1]
    node = tree
    for part in parts:
        if not isinstance(node, dict) or part not in node:
            return 404, {"error": f"no such path: {path}"}
        node = node[part]
    if wildcard:
        if not isinstance(node, dict):
            return 400, {"error": "wildcard on a leaf"}
        if recurse:
            return 200, node
        return 200, {k: (v if not isinstance(v, dict) else "*container*")
                     for k, v in node.items()}
    return 200, node


def set_pref(app, path: str, value: str) -> tuple[int, Any]:
    """``command=set`` — write one pref (server/prefs/<name> only; the
    reference likewise only honors sets on preference attributes)."""
    parts = [p for p in path.strip("/").split("/") if p]
    if len(parts) != 3 or parts[:2] != ["server", "prefs"]:
        return 400, {"error": "set supports server/prefs/<name> only"}
    name = parts[2]
    current = app.config.to_dict()
    if name not in current:
        return 404, {"error": f"no such pref: {name}"}
    old = current[name]
    # coerce through the current value's type, as GenerateXMLPrefs did
    try:
        if isinstance(old, bool):
            new: Any = value.lower() in ("1", "true", "yes", "on")
        elif isinstance(old, int):
            new = int(value)
        elif isinstance(old, float):
            new = float(value)
        else:
            new = value
        app.config.update(**{name: new})
    except (TypeError, ValueError) as e:
        return 400, {"error": str(e)}
    if name == "rest_password":        # match the read-side redaction
        return 200, {name: "(redacted)"}
    return 200, {name: new, "was": old}
