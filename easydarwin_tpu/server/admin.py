"""Admin dictionary-tree browse API (QTSSAdminModule parity).

Reference: ``QTSSAdminModule.cpp:365-1073`` + ``AdminQuery.cpp`` +
``AdminElementNode.cpp`` — the legacy ``/modules/admin`` API walks the
server's reflective attribute dictionaries as a filesystem-like tree with
``command=get|set`` queries, ``*`` wildcards and an optional recurse flag.

Here the same browse semantics sit on the JSON REST port: the tree is
assembled on demand from live server state (info, prefs, sessions,
modules), paths are ``/``-separated with a trailing ``*`` to list
children, and ``command=set`` writes a pref through the same validated
``ServerConfig.update`` path the setbaseconfig route uses.  The mongoose
web UI is intentionally superseded by ``/stats`` + this endpoint.
"""

from __future__ import annotations

from typing import Any


#: role hook names on server.modules.Module (QTSSModule.h:126-163 analogue)
ROLE_HOOKS = ("initialize", "shutdown", "reread_prefs", "rtsp_filter",
              "rtsp_route", "authorize", "rtsp_postprocess",
              "session_closing", "incoming_rtp")


def _module_attrs(module) -> dict:
    """Module-added attributes (QTSS_AddStaticAttribute analogue) under
    an ``attrs`` node; a module raising inside its own hook must not
    take the whole tree down."""
    try:
        attrs = module.attributes()
        if attrs:
            import json
            json.dumps(attrs)          # a non-serializable value would
    except Exception as e:             # break every tree query that
        return {"attrs_error": str(e)}  # includes this node, not just
    return {"attrs": attrs} if attrs else {}  # the module's own path


def _roles_of(module) -> list[str]:
    """Roles a module registers for = hooks it overrides (the dispatch
    arrays in QTSServer::BuildModuleRoleArrays, rebuilt by reflection)."""
    from .modules import Module
    return sorted(r for r in ROLE_HOOKS
                  if any(r in klass.__dict__
                         for klass in type(module).__mro__
                         if klass is not Module and klass is not object))


def _session_node(app, sess):
    """A session's tree node reads through its (cached) AttrStore and
    per-stream stores — the qtssClientSession/RTPStream dictionaries."""
    from . import dictionary as dct
    store = getattr(sess, "attr_store", None)
    if store is None:
        store = sess.attr_store = dct.session_store(app, sess)
    streams = {}
    for tid in sess.streams:
        skey = f"track{tid}"
        cache = getattr(sess, "_stream_stores", None)
        if cache is None:
            cache = sess._stream_stores = {}
        if tid not in cache:
            cache[tid] = dct.stream_store(sess, tid)
        streams[skey] = cache[tid]
    return {"attrs": store, "streams": streams}


def build_tree(app) -> dict[str, Any]:
    """Assemble the browseable dictionary tree from live server state.

    Mirrors the reference's top-level element list (AdminElementNode
    ``GetElementFromArray``): server attributes, prefs, connected
    sessions, loaded modules.  Nodes are either plain dict containers
    or ``AttrStore`` objects — the reflective dictionaries every query
    and set resolves through (QTSSDictionaryMap)."""
    from . import dictionary as dct
    sstore = getattr(app, "attr_store", None)
    if sstore is None:
        sstore = app.attr_store = dct.server_store(app)
    cstore = getattr(app.config, "attr_store", None)
    if cstore is None:
        cstore = app.config.attr_store = dct.config_store(app.config)
    mstore = getattr(app, "metrics_store", None)
    if mstore is None:
        mstore = app.metrics_store = dct.metrics_store()
    sessions = {}
    for s in app.registry.sessions.values():
        sessions[s.path.strip("/").replace("/", "~")] = \
            _session_node(app, s)
    modules = {}
    for m in getattr(app.modules, "modules", []):
        node: dict[str, Any] = {"roles": _roles_of(m),
                                **_module_attrs(m)}
        mstore = getattr(m, "attr_store", None)
        if mstore is not None and mstore.describe():
            node["instance_attrs"] = mstore
        modules[m.name] = node
    return {
        "server": {
            "info": sstore,
            "prefs": cstore,
            "metrics": mstore,
            "sessions": sessions,
            "modules": modules,
        },
    }


def _materialize(node: Any) -> Any:
    from .dictionary import AttrStore
    if isinstance(node, AttrStore):
        return node.as_dict()
    if isinstance(node, dict):
        return {k: _materialize(v) for k, v in node.items()}
    return node


def query(app, path: str, *, recurse: bool = False) -> tuple[int, Any]:
    """``command=get`` — resolve a tree path.

    Returns (status, payload).  A trailing ``*`` lists children one
    level deep (or the whole subtree with ``recurse``); a concrete path
    returns the node value.  Inside an ``AttrStore`` node, a segment is
    an attribute name or ``@<id>`` (get-by-id), and the reserved
    segment ``parameters`` returns the attribute metadata (id, type,
    access) like the reference's ?parameters view.  Unknown paths →
    404 (QTSSAdminModule.cpp ReportErr)."""
    from .dictionary import AttrStore
    tree: Any = build_tree(app)
    parts = [p for p in path.strip("/").split("/") if p]
    wildcard = bool(parts) and parts[-1] == "*"
    if wildcard:
        parts = parts[:-1]
    node = tree
    for part in parts:
        if isinstance(node, AttrStore):
            if part == "parameters":
                node = node.describe()
                continue
            try:
                node = node.get(part)
            except KeyError:
                return 404, {"error": f"no such path: {path}"}
            continue
        if not isinstance(node, dict) or part not in node:
            return 404, {"error": f"no such path: {path}"}
        node = node[part]
    if isinstance(node, AttrStore) and not wildcard:
        return 200, node.as_dict()
    if wildcard:
        if isinstance(node, AttrStore):
            return 200, node.as_dict()
        if not isinstance(node, dict):
            return 400, {"error": "wildcard on a leaf"}
        if recurse:
            return 200, _materialize(node)
        return 200, {k: (v if not isinstance(v, (dict, AttrStore))
                         else "*container*")
                     for k, v in node.items()}
    return 200, _materialize(node)


def flight_query(app, session_id: str) -> tuple[int, Any]:
    """``command=flight&session=<id>`` — the session's black box.

    A LIVE session answers with its current event ring + correlated span
    summaries (no dump side effects, ``"live": true``); an abnormally
    torn-down one answers with its stored flight dump.  Without
    ``session=``, lists what is retrievable (live rings + kept dumps)."""
    from ..obs import FLIGHT
    if not session_id:
        return 200, {"live": FLIGHT.live_sessions(),
                     "dumps": sorted(FLIGHT.dumps)}
    doc = FLIGHT.lookup(session_id)
    if doc is None:
        return 404, {"error": f"no flight data for session {session_id}"}
    return 200, doc


def profile_snapshot(app) -> dict:
    """``command=top`` / ``GET /api/v1/profile`` — the live attribution
    document: per-phase latency summaries, top sessions by wire bytes
    and by p99 contribution (obs/profile.py), plus the SLO watchdog's
    budget status when the server carries one (the raw profiler shape is
    preserved so operators' jq pipelines survive a headless profiler)."""
    from ..obs import PROFILER
    doc = PROFILER.snapshot()
    slo = getattr(app, "slo", None)
    if slo is not None:
        doc["slo"] = slo.status()
    return doc


def ledger_snapshot(app) -> dict:
    """``GET /api/v1/ledger`` — the wake-loop ledger's live document
    (ISSUE 16): per-work-class wait/service aggregates, deferred/shed
    counts, the worst wait's trace correlation, and the cluster tick's
    Redis roundtrip sub-accounting.  The node id rides along so a
    multi-node capture (blame_report, soak post-mortem) stays
    attributable after aggregation."""
    from ..obs import LEDGER, events
    doc = LEDGER.snapshot()
    doc["node"] = events.NODE.get("id") or ""
    return doc


def blame_snapshot(app) -> dict:
    """``command=blame`` — the "why is p99 high" table: the ledger
    snapshot ranked by wait-p99 blame through obs.ledger.blame_doc,
    with the live ingest→wire p50/p99 as the measured figures the
    attribution must conserve against (the same estimator bench's
    composed round pins at ≥ 90 %)."""
    from ..obs import LEDGER, RELAY_INGEST_TO_WIRE, blame_doc
    p50 = RELAY_INGEST_TO_WIRE.quantile(0.50) * 1e3
    p99 = RELAY_INGEST_TO_WIRE.quantile(0.99) * 1e3
    snap = ledger_snapshot(app)
    doc = blame_doc(snap, measured_p99_ms=p99 or None,
                    baseline_p50_ms=p50)
    doc["node"] = snap.get("node", "")
    doc["ledger"] = snap
    # audience suspect source (ISSUE 18): viewer impact joins the
    # cause — stall storms / collapsed QoE p10 become suspect lines
    # alongside the ledger's, and the rollup rides the doc so
    # tools/blame_report.py can re-derive them from a capture
    from ..obs import AUDIENCE
    from ..obs import audience as audience_mod
    roll = AUDIENCE.rollup()
    doc["audience"] = roll
    doc["suspects"] = list(doc.get("suspects") or []) \
        + audience_mod.suspect_flags(roll)
    return doc


def audience_snapshot(app, worst_n: int = 5) -> dict:
    """``GET /api/v1/audience`` / ``command=audience`` — the columnar
    per-subscriber QoE store's drill-down doc (ISSUE 18): per-stream
    rollup (QoE p50/p10, drops/late/RTX/FEC totals, stall figures,
    storm latches) + the worst-N subscribers of each stream.  The node
    id rides along so multi-node captures stay attributable."""
    from ..obs import AUDIENCE, events
    doc = AUDIENCE.snapshot(worst_n=worst_n)
    doc["node"] = events.NODE.get("id") or ""
    return doc


def set_pref(app, path: str, value: str) -> tuple[int, Any]:
    """``command=set`` — write one pref through the prefs AttrStore
    (``server/prefs/<name>`` or ``server/prefs/@<id>``; the reference
    likewise only honors sets on preference attributes, and read-only
    attributes refuse with the QTSS_ReadOnly analogue)."""
    from . import dictionary as dct
    parts = [p for p in path.strip("/").split("/") if p]
    if len(parts) != 3 or parts[:2] != ["server", "prefs"]:
        return 400, {"error": "set supports server/prefs/<name> only"}
    cstore = getattr(app.config, "attr_store", None)
    if cstore is None:
        cstore = app.config.attr_store = dct.config_store(app.config)
    try:
        spec = cstore.spec(parts[2])
    except KeyError:
        return 404, {"error": f"no such pref: {parts[2]}"}
    old = cstore.get(spec.attr_id)
    try:
        new = cstore.set(spec.attr_id, value)
    except PermissionError as e:
        return 400, {"error": str(e)}
    except (TypeError, ValueError) as e:
        return 400, {"error": str(e)}
    if spec.name == "rest_password":   # match the read-side redaction
        return 200, {spec.name: "(redacted)"}
    return 200, {spec.name: new, "was": old}
