"""Server assembly + supervision — the ``RunServer.cpp`` equivalent.

Boot order mirrors ``StartServer`` (``RunServer.cpp:65-215``): config →
session registry → listeners (RTSP + REST service port) → relay pump
(the ReflectorSocket/IdleTask send loop, here one asyncio task, woken by
ingest and ticking at ``reflect_interval_ms``) → timeout sweeper (15 s
granularity, ``TimeoutTask.h:66``) → optional cluster presence task.

The pump chooses per stream between the scalar CPU fan-out and the TPU
batch engine (``relay.fanout.TpuFanoutEngine``) based on config and the
subscriber count — the "module loaded / unloaded with CPU fallback"
behavior the north star requires.
"""

from __future__ import annotations

import asyncio
import time

from ..relay.fanout import TpuFanoutEngine
from ..relay.session import SessionRegistry, now_ms
from .config import ServerConfig
from .rest import RestApi
from .rtsp import RtspServer


class _RestoredSubscriber:
    """Connection stand-in for a checkpoint-restored UDP subscriber.

    The real RTSP connection died with the previous process; this
    adapter duck-types what ``RtspServer.on_client_rtcp`` needs
    (``player_tracks``/``relay``/``path``/``stats``/``last_activity``)
    so the restored output's receiver reports keep driving quality
    adaptation AND proving liveness — and the sweep reaps the output
    after ``rtsp_timeout_sec`` of RTCP silence, so a player that never
    came back cannot be relayed to forever."""

    is_pusher = False

    def __init__(self, sess, track_id: int, stream, output):
        import types
        self.relay = sess
        self.path = sess.path
        self.stream = stream
        self.output = output
        self.player_tracks = {track_id: types.SimpleNamespace(
            output=output)}
        self.stats: dict = {}
        self.last_activity = time.monotonic()


class StreamingServer:
    def __init__(self, config: ServerConfig | None = None, *,
                 describe_fallback=None, redis_client=None):
        self.config = config or ServerConfig()
        self.registry = SessionRegistry(self.config.stream_settings())
        from ..vod.session import VodService
        self.vod = VodService(self.config.movie_folder)
        self.auth = None
        if self.config.rtsp_auth_enabled:
            from .auth import AuthService, AccessRules, UsersFile
            rules = AccessRules()
            rules.protect("/", [])          # valid-user everywhere by default
            self.auth = AuthService(
                UsersFile(self.config.users_file or None),
                rules, scheme=self.config.auth_scheme)
        self.access_log = None
        self.error_log = None
        if self.config.access_log_enabled:
            import os
            from ..utils.logs import AccessLog, ErrorLog
            self.access_log = AccessLog(
                os.path.join(self.config.log_folder, "access.log"))
            self.error_log = ErrorLog(
                os.path.join(self.config.log_folder, "error.log"),
                verbosity=self.config.error_log_verbosity)
        self.rtsp = RtspServer(self.config, self.registry,
                               describe_fallback=describe_fallback,
                               on_pump_wake=self._wake, vod=self.vod,
                               auth=self.auth, access_log=self.access_log)
        from ..relay.source import SdpFileRelaySource
        self.relay_source = SdpFileRelaySource(
            self.config.movie_folder, self.registry,
            on_ingest=lambda _path: self._wake())
        self.rtsp.relay_source = self.relay_source
        from ..relay.pull import PullRelayManager
        self.pulls = PullRelayManager(self.registry,
                                      on_packet=lambda _path: self._wake())
        self.rest = RestApi(self.config, self)
        from ..vod.record import RecordingManager
        from ..hls import HlsService
        from .mp3 import Mp3Service
        self.recordings = RecordingManager()
        self.hls = HlsService(self.registry,
                              requant_on_device=self.config.tpu_fanout)
        from ..models.mjpeg_ladder import MjpegTranscodeService
        self.transcodes = MjpegTranscodeService(
            self.registry, on_frame=lambda _path: self._wake())
        self.mp3 = Mp3Service(self.config.movie_folder)
        self.rtsp.http_get_handler = self._rtsp_port_http_get
        self._pump_event = asyncio.Event()
        #: first un-serviced wake's perf stamp — the wake→pass queueing
        #: delay phase (obs/profile.py); None = no wake pending
        self._wake_ns: int | None = None
        #: SLO watchdog over the obs families; the pump's 1 Hz
        #: maintenance block ticks it, violations flag flight recorders
        from ..obs import PROFILER, SloWatchdog
        self.slo = SloWatchdog(self.config.slo_config(),
                               offender=PROFILER.top_offender)
        #: degradation ladder (resilience/ladder.py): per-stream rung
        #: megabatch → per-stream device → CPU oracle → shed, consulted
        #: by the pump per wake and ticked by the 1 Hz maintenance block
        self.ladder = None
        if self.config.resilience_enabled:
            from ..resilience import DegradationLadder
            self.ladder = DegradationLadder(self.config.ladder_config())
            # RTX budget exhaustion (relay/fec.py) is charged to the
            # ladder: a black-holed client's NACK storm sheds load
            # through the same machinery as any other overload
            self.rtsp.on_rtx_giveup = (
                lambda path: self.ladder.note_device_error(
                    path, reason="rtx_giveup"))
        #: session checkpoint/hot-restore (resilience/checkpoint.py) —
        #: built in start() once log_folder is final
        self.checkpoint = None
        #: adapters owning hot-restored subscribers (RTCP demux +
        #: silence reaping); swept alongside the RTSP timeout sweep
        self._restored_subs: list[_RestoredSubscriber] = []
        #: parked interleaved-TCP checkpoint records (ISSUE 14):
        #: (path, track_id, session_id) → (record, parked_monotonic).
        #: Claimed by the rtsp SETUP re-attach hook; unclaimed entries
        #: age out via the sweep as counted ckpt.tcp_orphan events.
        self._pending_tcp: dict = {}
        self._armed_faults = False
        self._tasks: list[asyncio.Task] = []
        self._running = False
        self._restart_requested = False
        self.restart_event = asyncio.Event()
        self._engines: dict[int, TpuFanoutEngine] = {}
        #: io_uring egress ring over the shared UDP pair (ISSUE 8);
        #: built in start() by the probe ladder, None = GSO/scalar rung
        self.uring_egress = None
        #: the rung the probe ladder landed on ("io_uring"/"gso"/
        #: "scalar") — mirrored into egress_backend_info{backend}
        self.egress_backend_effective = "gso"
        #: pusher RTP sockets get multishot io_uring ingest when True
        self.uring_ingest_enabled = False
        #: cross-stream megabatch scheduler (relay/megabatch.py) — built
        #: lazily on the first wake with enough engine-eligible streams
        self.megabatch = None
        #: the megabatch serving mesh (megabatch_devices > 1), built in
        #: start() so a bad device config fails loudly at boot, not on
        #: the first busy wake; None = single-device dispatch
        self.megabatch_mesh = None
        #: VOD segment cache + shared group pacer (ISSUE 10): hot file
        #: sessions become megabatch-eligible relay streams the pump
        #: steps alongside live; built in start() (engines need the
        #: egress probe's verdict), None = every player runs the cold
        #: per-session FileSession
        self.vod_cache = None
        self.vod_pacer = None
        #: DVR / time-shift tier (ISSUE 12: dvr/): window spill off the
        #: live rings + pause/rewind/catch-up served by the VOD pacer;
        #: built in start() after the cache/pacer exist, None = off
        self.dvr = None
        #: async peer-fill plumbing: (path, track, win) -> Future of the
        #: helper-thread HTTP fetch (see _dvr_peer_fetch)
        self._dvr_fetches: dict = {}
        self._dvr_fetch_pool = None
        #: erasure-coded storage tier (ISSUE 20: storage/): finalized
        #: DVR assets sharded k+m across the fleet, reads reconstruct
        #: from any k survivors; built in start() after the DVR tier,
        #: None = off
        self.storage = None
        #: in-flight erasure restores: (path, track, win) -> Future of
        #: the helper-thread reconstruct (see _storage_restore)
        self._storage_fetches: dict = {}
        self._storage_scrub_due = 0.0
        self.started_at = time.time()
        from .status import StatusMonitor
        self.status = StatusMonitor(self)
        self.presence = None
        #: fault-tolerant cluster tier (cluster/service.py) — built in
        #: start() once the listener ports are known
        self.cluster = None
        #: load-aware control plane (ISSUE 13): capacity score + live
        #: utilization tracker, built in start() under cluster mode
        #: (the boot self-bench only runs when a cluster will read it)
        self.load_tracker = None
        #: remote DVR assets bootstrapped via /api/v1/dvrmeta:
        #: path -> (host, http_port, {track: [win_lo, win_hi]}) —
        #: consulted by _dvr_peer_fetch when the armed-asset Own:
        #: advertisement (cluster.dvr_peers) has no entry (a finalized
        #: asset's advert died with its live claim)
        self._dvr_meta_peers: dict = {}
        #: paths whose all-peer meta sweep found nothing: path ->
        #: monotonic retry-after.  Without this a repeat DESCRIBE of the
        #: same bogus .dvr path re-runs the full (N-1)-peer HTTP sweep
        #: every time — the path-scan amplification the live describe()
        #: gate exists to prevent
        self._dvr_meta_misses: dict = {}
        self._user_describe_fallback = describe_fallback
        self._redis_client = redis_client
        self.config.on_change(self._on_config_change)

    # ------------------------------------------------------------- control
    @property
    def modules(self):
        return self.rtsp.modules

    def register_module(self, module) -> None:
        """QTSS_Register + AddModule equivalent."""
        self.rtsp.modules.register(module)

    async def start(self) -> None:
        self._running = True
        # crash flight dumps land next to this server's rolling logs
        # (written only when a dump happens; write failures swallowed).
        # The recorder — like REGISTRY/TRACER/EVENTS — is process-global,
        # so only a server actually STARTING claims the directory; a
        # merely-constructed instance never redirects a running one's
        import os
        from ..obs import FLIGHT, set_node
        FLIGHT.dump_dir = os.path.join(self.config.log_folder, "flight")
        # claim the process-wide node identity for event/flight
        # attribution (ISSUE 15) — same starting-server-wins rule as the
        # dump dir; the cluster heartbeat refreshes the fence token
        set_node(self.config.server_id)
        # plugins register before the listeners accept anything, so their
        # filter/authorize hooks cover every request (the reference loads
        # modules before CreateListeners' ports go live too)
        if self.config.module_folder:
            from .modules import load_modules_from
            for m in load_modules_from(
                    self.config.module_folder,
                    on_error=lambda f, e: self.error_log
                    and self.error_log.warning(f"module {f} failed: {e}")):
                self.register_module(m)
        if self.config.fec_enabled:
            self.config.fec_config()    # raises at boot on a bad window/kind
        # chaos plan (resilience/inject.py): armed before anything serves
        # so the very first pass already runs under the fault schedule
        plan = self.config.fault_plan()
        if plan is not None:
            from ..resilience import INJECTOR
            INJECTOR.arm(plan)
            self._armed_faults = True
        await self.rtsp.start()
        self._init_egress_backend()
        await self.rest.start()
        if self.config.resilience_checkpoint_enabled:
            # hot-restore AFTER the egress pair exists (restored UDP
            # subscribers send through it) and BEFORE the pump starts
            from ..resilience import CheckpointManager
            self.checkpoint = CheckpointManager(
                os.path.join(self.config.log_folder, "ckpt"),
                interval_sec=self.config.resilience_checkpoint_interval_sec,
                max_age_sec=self.config.resilience_checkpoint_max_age_sec)
            self.rtsp.tcp_restore = self.claim_tcp_restore
            try:
                n_sess, n_out = self.checkpoint.restore(
                    self.registry, output_factory=self._restored_output,
                    tcp_sink=self._park_tcp_record)
                if n_out:
                    self._adopt_restored_outputs()
                if n_sess and self.error_log:
                    self.error_log.info(
                        f"checkpoint: restored {n_sess} sessions / "
                        f"{n_out} subscribers")
            except Exception as e:
                if self.error_log:
                    self.error_log.warning(f"checkpoint restore: {e!r}")
        self.rtsp.modules.run_initialize(self)
        if (self.config.tpu_fanout and self.config.megabatch_enabled
                and self.config.megabatch_devices != 1):
            # the megabatch serving mesh (ISSUE 7): built before the
            # pump's first wake; any failure here (bad device count, no
            # backend) degrades to single-device dispatch with a logged
            # warning rather than a dead pump
            try:
                from ..parallel.mesh import make_megabatch_mesh
                self.megabatch_mesh = make_megabatch_mesh(
                    self.config.megabatch_devices)
                if self.megabatch_mesh is not None and self.error_log:
                    from ..parallel.distributed import process_span
                    self.error_log.info(
                        "megabatch mesh: "
                        f"{process_span(self.megabatch_mesh)}")
            except Exception as e:
                self.megabatch_mesh = None
                if self.error_log:
                    self.error_log.warning(
                        f"megabatch mesh unavailable, serving "
                        f"single-device: {e!r}")
        if self.config.vod_cache_enabled:
            from ..vod.cache import SegmentCache
            from ..vod.session import VodPacerGroup
            self.vod_cache = SegmentCache(
                budget_bytes=self.config.vod_cache_bytes,
                window_samples=self.config.vod_cache_window_samples,
                device=self.config.vod_cache_device)
            self.vod_pacer = VodPacerGroup(
                self.vod_cache,
                engine_for=self._engine_for,
                engine_drop=lambda s: self._engines.pop(id(s), None),
                scheduler=lambda: self.megabatch,
                settings=self.config.stream_settings(),
                lookahead_ms=self.config.vod_cache_lookahead_ms,
                device_prime=(self.config.vod_cache_device
                              and self.config.tpu_fanout))
            self.rtsp.vod_pacer = self.vod_pacer
            if self.checkpoint is not None:
                # re-warm the previous process's hot set (PR 5 shape:
                # metadata only — windows re-pack in the background on
                # each asset's first open)
                import json
                self._vod_ckpt_path = os.path.join(
                    self.config.log_folder, "ckpt", "vod_cache.json")
                try:
                    with open(self._vod_ckpt_path,
                              encoding="utf-8") as fh:
                        n = self.vod_cache.restore(json.load(fh))
                    if n and self.error_log:
                        self.error_log.info(
                            f"vod cache: re-warming {n} windows")
                except (OSError, ValueError):
                    pass
        if self.config.dvr_enabled:
            if self.vod_pacer is None:
                if self.error_log:
                    self.error_log.warning(
                        "dvr_enabled needs vod_cache_enabled (the spill "
                        "serves through the segment cache); DVR is OFF")
            else:
                from ..dvr import DvrManager
                self.dvr = DvrManager(
                    os.path.join(self.config.movie_folder, ".dvr"),
                    self.vod_cache, self.vod_pacer, self.registry,
                    window_pkts=self.config.dvr_window_pkts,
                    retention_bytes=self.config.dvr_retention_bytes,
                    retention_sec=self.config.dvr_retention_sec,
                    error_log=self.error_log)
                self.rtsp.dvr = self.dvr
        if self.config.storage_enabled:
            if self.dvr is None:
                if self.error_log:
                    self.error_log.warning(
                        "storage_enabled needs dvr_enabled (only "
                        "finalized DVR assets are sharded); storage is "
                        "OFF")
            else:
                from ..storage import StorageService
                self.storage = StorageService(
                    os.path.join(self.config.movie_folder, ".shards"),
                    self.config.server_id,
                    k=self.config.storage_data_shards,
                    m=self.config.storage_parity_shards,
                    use_device=self.config.storage_device,
                    error_log=self.error_log)
                self.dvr.on_finalize = self._storage_on_finalize
                self.dvr.restorer = self._storage_restore
        # crash-safe recorder orphan sweep (vod/record.py): leftover
        # <file>.mp4.tmp means a recorder died mid-write — report it
        from ..vod.record import sweep_orphans
        try:
            sweep_orphans(self.config.movie_folder)
        except OSError:
            pass
        self._tasks = [
            asyncio.create_task(self._pump_loop(), name="relay-pump"),
            asyncio.create_task(self._sweep_loop(), name="timeout-sweep"),
        ]
        if self.config.stats_interval_sec or self.config.status_file_path:
            self._tasks.append(
                asyncio.create_task(self._status_loop(), name="status"))
        if self.config.cluster_enabled:
            # the fault-tolerant tier: lease + placement + pull relay +
            # migration.  It subsumes the passive presence records, so
            # cloud_enabled presence is skipped when it runs.
            from ..cluster.redis_client import AsyncRedis
            from ..cluster.service import ClusterService
            redis = self._redis_client or AsyncRedis(
                self.config.redis_host, self.config.redis_port)
            ccfg = self.config.cluster_config()
            ccfg.rtsp_port = self.rtsp.port or self.config.rtsp_port
            ccfg.http_port = self.rest.port or self.config.service_port
            self.cluster = ClusterService(
                redis, ccfg, registry=self.registry,
                pull_manager=self.pulls,
                restore_doc=self._cluster_restore,
                on_pull_failure=self._on_pull_failure,
                on_fence_lost=self._cluster_fence_lost,
                error_log=self.error_log)
            if self.dvr is not None:
                # spilled-window spans ride this node's fenced Own:
                # records; cold DVR windows another node recorded
                # peer-fill through its spill files, not origin
                self.cluster.dvr_advertise = self.dvr.advertise
                self.dvr.fetcher = self._dvr_peer_fetch
                # fully-remote asset bootstrap (ISSUE 13 satellite):
                # a .dvr DESCRIBE on a node that never saw the stream
                # syncs the recording node's meta/index documents first
                self.dvr.meta_sync = self._dvr_meta_sync
            if self.storage is not None:
                # the erasure tier rides the cluster: shards place on
                # the capacity-weighted ring, claims write through the
                # tick as fenced Shard: records, and repair watches the
                # live lease set for dead holders (ISSUE 20)
                self.storage.node_id = ccfg.node_id
                self.storage.peer_nodes = \
                    lambda: dict(self.cluster.last_nodes) \
                    if self.cluster is not None else {}
                self.storage.ring_for = self.cluster.placement.ring
                self.storage.push_shard = self._storage_push_blocking
                self.storage.fetch_shard = self._storage_fetch_blocking
                self.storage.fetch_manifest = \
                    self._storage_manifest_blocking
                self.cluster.storage_claims = \
                    self.storage.pending_claims
                self.cluster.storage_repair = self.storage.repair_scan
            # load-aware control plane (ISSUE 13): capacity published
            # into the lease each heartbeat, admission gate on new
            # SETUPs.  The self-bench is cached per boot; an operator-
            # pinned cluster_capacity_score skips it entirely.
            from ..cluster.capacity import LoadTracker, self_bench
            cap = self.config.cluster_capacity_score or self_bench()
            self.load_tracker = LoadTracker(
                cap,
                slo=self.slo if self.config.slo_enabled else None,
                subscribers=lambda: sum(
                    s.num_outputs
                    for s in self.registry.sessions.values()))
            self.cluster.load_status = self.load_tracker.sample
            if ccfg.admission_enabled:
                self.rtsp.admission = self._admission_verdict
            # fleet federation (ISSUE 15): the rollup published into
            # Fleet:{node} each heartbeat, and the gate that lets live
            # peers' pulls thread their trace ids into this node
            from ..obs import fleet as fleet_mod
            self.cluster.fleet_status = \
                lambda: fleet_mod.build_rollup(self)
            self.rtsp.peer_trace_gate = self._peer_trace_gate
            await self.cluster.start()
            self.rtsp.describe_fallback = self._cluster_describe
        elif self.config.cloud_enabled:
            from ..cluster.presence import PresenceService
            from ..cluster.redis_client import AsyncRedis
            redis = self._redis_client or AsyncRedis(
                self.config.redis_host, self.config.redis_port)
            self.presence = PresenceService(
                redis, self.config.server_id, ip=self.config.wan_ip,
                rtsp_port=self.rtsp.port or self.config.rtsp_port,
                http_port=self.rest.port or self.config.service_port)
            try:
                await self.presence.start()
            except Exception:
                self.presence = None       # redis unreachable: run standalone

    async def stop(self) -> None:
        self._running = False
        if self.checkpoint is not None:
            # final snapshot while the registry is still intact, so a
            # supervisor relaunch (EXIT_RESTART) resumes from the very
            # last state, not the last periodic interval
            try:
                self.checkpoint.write(self.registry)
                if self.vod_cache is not None:
                    self._write_vod_cache_meta()
            except Exception:
                pass
        if self._armed_faults:
            from ..resilience import INJECTOR
            INJECTOR.disarm()
            self._armed_faults = False
        self.rtsp.modules.run_shutdown(self)
        if self.cluster is not None:
            # planned drain: fresh checkpoints published + lease released
            # while the registry is still intact, so peers adopt within
            # one tick instead of a TTL wait
            try:
                await self.cluster.stop(drain=True)
            except Exception:
                pass
            self.cluster = None
            self.rtsp.admission = None
            self.load_tracker = None
        if self.presence is not None:
            await self.presence.stop()
            self.presence = None
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        # drain the recorder tier while sessions still exist: every
        # in-flight MP4 finalizes (tmp→rename, playable moov) and every
        # armed DVR asset flips complete — instant stream-to-VOD instead
        # of an orphan sweep at next boot
        try:
            self.recordings.stop_all()
        except Exception:
            pass
        if self.dvr is not None:
            try:
                self.dvr.close()
            except Exception:
                pass
            self.rtsp.dvr = None
            self.dvr = None
        if self.storage is not None:
            try:
                self.storage.close()
            except Exception:
                pass
            self.storage = None
            self._storage_fetches.clear()
        if self._dvr_fetch_pool is not None:
            self._dvr_fetch_pool.shutdown(wait=False, cancel_futures=True)
            self._dvr_fetch_pool = None
            self._dvr_fetches.clear()
        if self.vod_pacer is not None:
            self.rtsp.vod_pacer = None
            try:
                self.vod_pacer.close()
                self.vod_cache.close()
            except Exception:
                pass
            self.vod_pacer = None
            self.vod_cache = None
        self.relay_source.close_all()
        self.transcodes.stop_all()
        await self.pulls.stop_all()
        await self.rtsp.stop()
        if self.uring_egress is not None:
            self.uring_egress.close()
            self.uring_egress = None
        if self.uring_ingest_enabled:
            from .. import native
            native.uring_ingest_disarm()
            self.uring_ingest_enabled = False
        await self.rest.stop()

    def request_restart(self) -> None:
        """REST /restart: under the supervisor (server.supervisor) the main
        loop exits with EXIT_RESTART and the watchdog relaunches."""
        self._restart_requested = True
        self.restart_event.set()

    def _on_config_change(self, cfg: ServerConfig) -> None:
        self.registry.settings = cfg.stream_settings()
        self.rtsp.modules.run_reread_prefs(cfg)

    def _wake(self) -> None:
        if self._wake_ns is None:
            self._wake_ns = time.perf_counter_ns()
        self._pump_event.set()

    def _restored_output(self, rec: dict):
        """Checkpoint output factory: rebuild a UDP subscriber on the
        shared egress pair (the address pair IS the transport — the
        client never learns the server restarted).  Interleaved/TCP
        outputs died with their connections and are skipped."""
        if rec.get("kind") != "udp" or not rec.get("rtp_addr"):
            return None
        egress = self.rtsp.shared_egress
        if egress is None or not egress.active:
            return None
        from .egress import NativeUdpOutput
        ip, rtp_port = rec["rtp_addr"]
        rtcp = rec.get("rtcp_addr") or (ip, int(rtp_port) + 1)
        out = NativeUdpOutput(egress, ip, int(rtp_port), int(rtcp[1]))
        # the RTCP destination may live on a DIFFERENT host than the RTP
        # one (RTSP Transport destination semantics) — restore it whole
        out.rtcp_addr = (rtcp[0], int(rtcp[1]))
        return out

    def _adopt_restored_outputs(self, paths=None, exclude_ids=()) -> None:
        """Give every just-restored UDP output a connection stand-in:
        register it with the shared-egress RTCP demux (quality feedback
        + liveness proof flow again) and track it for the silence sweep.
        At startup every output in the registry IS a restored one; a
        mid-run migration restore passes ``paths`` (the restored
        sessions) and ``exclude_ids`` (outputs that existed before the
        restore) so live subscribers are never double-registered."""
        egress = self.rtsp.shared_egress
        if egress is None:
            return
        exclude = set(exclude_ids)
        for sess in self.registry.sessions.values():
            if paths is not None and sess.path not in paths:
                continue
            for tid, stream in sess.streams.items():
                for out in stream.outputs:
                    if getattr(out, "native_addr", None) is None \
                            or id(out) in exclude:
                        continue
                    sub = _RestoredSubscriber(sess, tid, stream, out)
                    self._restored_subs.append(sub)
                    egress.register(out, sub)

    def _cluster_restore(self, doc: dict) -> tuple[int, int]:
        """Cluster migration hook: rebuild the adopted stream's sessions
        + UDP subscribers from its Redis-published checkpoint.  The
        subscribers' address pairs ARE their transport, so the players
        are re-pointed at this node without re-SETUP.  Interleaved-TCP
        subscribers park for the re-attach path (their connection died
        with the old owner; the player reconnects and presents its old
        Session id — ISSUE 14 migration parity)."""
        from ..resilience.checkpoint import restore_registry
        if self.rtsp.tcp_restore is None:
            self.rtsp.tcp_restore = self.claim_tcp_restore
        paths = {s.get("path") for s in doc.get("sessions", ())}
        pre = {id(o)
               for p in paths if p
               for sess in (self.registry.find(p),) if sess is not None
               for st in sess.streams.values() for o in st.outputs}
        n_sess, n_out = restore_registry(
            self.registry, doc, output_factory=self._restored_output,
            tcp_sink=self._park_tcp_record)
        # trace lineage (ISSUE 15): the adopted streams now live HERE —
        # extend their node lineage so a stitched trace names both the
        # dead owner and this adopter under the one preserved trace id
        for p in paths:
            sess = self.registry.find(p) if p else None
            if sess is not None and (not sess.trace_nodes
                                     or sess.trace_nodes[-1]
                                     != self.config.server_id):
                sess.trace_nodes.append(self.config.server_id)
        if n_out:
            self._adopt_restored_outputs(paths=paths, exclude_ids=pre)
        self._wake()
        return n_sess, n_out

    def _on_pull_failure(self, path: str) -> None:
        """Cluster pull envelope → ladder coupling: an upstream pull
        failure degrades the stream's rung, never kills the session."""
        if self.ladder is not None:
            self.ladder.note_device_error(path, reason="pull_errors")

    def _cluster_fence_lost(self, path: str) -> None:
        """A NEWER owner fenced us out of ``path``: stop serving it on
        THIS node.  Dropping only the Redis claim would leave a zombie
        data plane — two nodes transmitting the same ssrc to the same
        subscribers.  The local source connection is closed (the device
        re-registers and re-pushes to the new owner — the reference
        recovery protocol), restored stand-ins are unregistered and the
        session removed."""
        sess = self.registry.find(path)
        if sess is None:
            return
        egress = self.rtsp.shared_egress
        for sub in [s for s in self._restored_subs if s.path == path]:
            self._restored_subs.remove(sub)
            if egress is not None:
                egress.unregister(sub.output, sub)
        from ..relay.pull import _spawn_cleanup
        for conn in [c for c in list(self.rtsp.connections)
                     if c.is_pusher and c.path == path]:
            if conn.writer is not None:
                try:
                    conn.writer.close()
                except Exception:
                    pass
            _spawn_cleanup(conn.close())
        if self.registry.find(path) is sess:
            self.registry.remove(path)

    async def _cluster_describe(self, path: str):
        """DESCRIBE fallback under cluster mode: a path another node
        owns is served locally through the pull envelope; any
        user-supplied fallback still gets the last word."""
        text = None
        if self.cluster is not None:
            try:
                text = await self.cluster.describe(path)
            except Exception as e:
                if self.error_log:
                    self.error_log.warning(f"cluster describe: {e!r}")
        if text is None and self._user_describe_fallback is not None:
            text = await self._user_describe_fallback(path)
        return text

    def _peer_trace_gate(self, node_id: str, client_ip: str) -> bool:
        """X-Trace-Id acceptance (ISSUE 15): the request must name a
        LIVE-leased cluster node in X-Cluster-Node AND arrive from that
        node's registered lease address — node ids are public (the
        fleet endpoint lists them), so the name alone is forgeable; the
        source address binds the claim to the peer's actual socket.
        (Co-located nodes sharing one address — the test topology —
        still cannot be forged from off-box.)"""
        if not node_id or self.cluster is None:
            return False
        meta = self.cluster.last_nodes.get(node_id)
        return isinstance(meta, dict) and meta.get("ip") == client_ip

    def _admission_verdict(self, path: str, client_key: str
                           ) -> tuple[str, str | None] | None:
        """Overload admission (ISSUE 13): None = admit; otherwise
        ``("redirect", url)`` — a placement-resolved edge exists, send
        RTSP 305 — or ``("refuse", None)`` — RTSP 453.  Synchronous by
        design: it reads the LAST heartbeat's load sample and node
        snapshot (a SETUP must never wait on Redis); the
        ``overload_spoof`` fault site forces the verdict for chaos
        runs.  Shedding before burning: every refusal is counted and
        evented."""
        lt = self.load_tracker
        if lt is None:
            return None
        from .. import obs
        from ..resilience import INJECTOR
        hw = self.config.cluster_admission_high_water
        over = lt.last_util >= hw
        if not over and INJECTOR.active:
            over = INJECTOR.overload_spoof()
        if not over:
            return None
        target = None
        url = None
        cl = self.cluster
        if cl is not None and cl.last_nodes:
            target = cl.placement.edge_for(
                path, cl.last_nodes, client_key=client_key,
                exclude=(cl.config.node_id,), high_water=hw)
            if target is not None:
                meta = cl.last_nodes.get(target) or {}
                ip, port = meta.get("ip"), meta.get("rtsp")
                if ip and port:
                    p = path if path.startswith("/") else "/" + path
                    url = f"rtsp://{ip}:{int(port)}{p}"
        action = "redirect" if url else "refuse"
        obs.CLUSTER_ADMISSION_REFUSED.inc(action=action)
        from ..obs import EVENTS
        EVENTS.emit("cluster.refuse", level="warn", stream=path,
                    action=action, util=round(lt.last_util, 3),
                    target=target)
        return (action, url)

    #: in-flight DVR peer fetches we will still collect (bound: a slow
    #: peer must not accumulate unbounded queued HTTP work)
    _DVR_FETCH_INFLIGHT_MAX = 32
    #: seconds an all-peer /api/v1/dvrmeta miss stays cached (a newly
    #: finalized recording becomes peer-fillable within this bound)
    _DVR_META_MISS_SEC = 10.0

    def _dvr_peer_fetch(self, path: str, track_id: int,
                        win: int) -> bytes | None:
        """Cluster peer-fill: fetch one spilled window blob from the
        node whose fenced ``Own:`` record advertises it (the recording
        node serves it over REST ``/api/v1/dvrwindow``).  The caller is
        the segment cache's packed-fill path, INLINE ON THE PUMP — so
        the HTTP round-trip runs on a helper thread and this returns
        ``b""`` (fetch pending: retry next tick, the time-shift cursor
        HOLDS) until the result lands; ``None`` means definitively
        unavailable (no peer / outside the advertised span / fetch
        failed) and the cursor hops the window."""
        cluster = self.cluster
        if cluster is None:
            return None
        from ..protocol.sdp import _norm
        peer = cluster.dvr_peers.get(_norm(path)) \
            or self._dvr_meta_peers.get(_norm(path))
        if peer is None:
            return None
        host, port, spans = peer
        span = spans.get(str(track_id))
        if span is not None and not span[0] <= int(win) <= span[1]:
            return None                 # advertised range excludes it
        key = (_norm(path), int(track_id), int(win))
        fut = self._dvr_fetches.get(key)
        if fut is None:
            if len(self._dvr_fetches) >= self._DVR_FETCH_INFLIGHT_MAX:
                # reap done-but-unclaimed entries first: a session torn
                # down mid-fetch never re-polls its key, and abandoned
                # results must not pin the cap shut forever
                for k in [k for k, f in self._dvr_fetches.items()
                          if f.done()]:
                    del self._dvr_fetches[k]
                if len(self._dvr_fetches) >= self._DVR_FETCH_INFLIGHT_MAX:
                    return None
            self._dvr_fetches[key] = self._ensure_dvr_fetch_pool().submit(
                self._dvr_fetch_blocking, host, int(port), path,
                int(track_id), int(win))
            return b""
        if not fut.done():
            return b""
        del self._dvr_fetches[key]
        try:
            return fut.result()
        except Exception:
            return None

    def _ensure_dvr_fetch_pool(self):
        if self._dvr_fetch_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._dvr_fetch_pool = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="dvr-fetch")
        return self._dvr_fetch_pool

    def _peer_http_get(self, host: str, port: int,
                       target: str) -> bytes | None:
        """One peer REST GET — helper-thread only.  Sends this node's
        REST credentials: on an auth-enabled cluster the peer's DVR
        endpoints sit behind the same shared config.  None on any
        non-200 / network failure."""
        import base64
        import http.client
        headers = {}
        if self.config.auth_enabled:
            cred = (f"{self.config.rest_username}:"
                    f"{self.config.rest_password}").encode()
            headers["Authorization"] = \
                "Basic " + base64.b64encode(cred).decode()
        try:
            conn = http.client.HTTPConnection(host, port, timeout=2.0)
            try:
                conn.request("GET", target, headers=headers)
                resp = conn.getresponse()
                if resp.status != 200:
                    return None
                return resp.read()
            finally:
                conn.close()
        except OSError:
            return None

    def _dvr_fetch_blocking(self, host: str, port: int, path: str,
                            track_id: int, win: int) -> bytes | None:
        from urllib.parse import quote
        return self._peer_http_get(
            host, port, f"/api/v1/dvrwindow?path={quote(path)}"
                        f"&track={track_id}&win={win}")

    async def _dvr_meta_sync(self, path: str) -> bool:
        """Bootstrap a fully-remote ``.dvr`` asset (ISSUE 13 satellite,
        closing the PR 12 open item): ask each live peer's REST
        ``/api/v1/dvrmeta`` for the asset's meta + per-track index
        documents, materialize them locally (index records + EMPTY spill
        file, so every window read degrades to the peer fetcher), and
        remember which peer answered so ``_dvr_peer_fetch`` can route
        window fills there even without an armed-asset advertisement."""
        cluster, dvr = self.cluster, self.dvr
        if cluster is None or dvr is None:
            return False
        from ..protocol.sdp import _norm
        # negative cache: a path no peer knew stays a miss for a while —
        # one cheap scanning client must not turn every repeat DESCRIBE
        # into a fresh cluster-wide HTTP sweep
        now = time.monotonic()
        until = self._dvr_meta_misses.get(_norm(path))
        if until is not None:
            if now < until:
                return False
            del self._dvr_meta_misses[_norm(path)]
        nodes = dict(cluster.last_nodes)
        if not nodes:
            try:
                nodes = await cluster.placement.live_nodes()
            except Exception:
                return False
        loop = asyncio.get_running_loop()
        for node, meta in nodes.items():
            if node == cluster.config.node_id:
                continue
            host, port = meta.get("ip"), meta.get("http")
            if not host or not port:
                continue
            doc = await loop.run_in_executor(
                self._ensure_dvr_fetch_pool(), self._dvr_meta_blocking,
                str(host), int(port), path)
            if not doc or not dvr.materialize(path, doc):
                continue
            spans = {}
            for tid, idx in (doc.get("tracks") or {}).items():
                wins = [int(r["win"]) for r in idx.get("windows", ())
                        if isinstance(r, dict) and "win" in r]
                if wins:
                    spans[str(tid)] = [min(wins), max(wins)]
            self._dvr_meta_peers[_norm(path)] = (str(host), int(port),
                                                 spans)
            return True
        if len(self._dvr_meta_misses) >= 512:     # bound scanner abuse
            self._dvr_meta_misses.clear()
        self._dvr_meta_misses[_norm(path)] = now + self._DVR_META_MISS_SEC
        return False

    def _dvr_meta_blocking(self, host: str, port: int,
                           path: str) -> dict | None:
        """HTTP GET of a peer's /api/v1/dvrmeta — helper-thread only."""
        import json
        from urllib.parse import quote
        raw = self._peer_http_get(
            host, port, f"/api/v1/dvrmeta?path={quote(path)}")
        if raw is None:
            return None
        try:
            doc = json.loads(raw.decode("utf-8", "replace"))
        except ValueError:
            return None
        return doc if isinstance(doc, dict) else None

    # -- erasure storage plumbing (ISSUE 20) -------------------------------
    #: in-flight restore cap — same bound and reasoning as the DVR
    #: peer-fill cap above
    _STORAGE_RESTORE_INFLIGHT_MAX = 32

    def _storage_on_finalize(self, result: dict) -> None:
        """DvrManager finalize hook: shard the finished asset on a
        storage worker thread (parity matmuls + peer pushes are
        blocking; finalize runs on the event loop)."""
        if self.storage is not None and self.dvr is not None:
            self.storage.store_async(result["path"], self.dvr)

    def _storage_restore(self, path: str, track_id: int,
                         win: int) -> bytes | None:
        """The spill chain's last resort, INLINE ON THE PUMP: kick the
        blocking shard-gather + GF reconstruct onto a storage worker and
        speak the fetch-pending protocol — ``b""`` while the future
        runs (the time-shift cursor HOLDS), the reconstructed blob when
        it lands, ``None`` when the stripe is beyond the parity budget."""
        st = self.storage
        if st is None:
            return None
        from ..protocol.sdp import _norm
        key = (_norm(path), int(track_id), int(win))
        fut = self._storage_fetches.get(key)
        if fut is None:
            if len(self._storage_fetches) >= \
                    self._STORAGE_RESTORE_INFLIGHT_MAX:
                for k in [k for k, f in self._storage_fetches.items()
                          if f.done()]:
                    del self._storage_fetches[k]
                if len(self._storage_fetches) >= \
                        self._STORAGE_RESTORE_INFLIGHT_MAX:
                    return None
            self._storage_fetches[key] = st.restore_async(
                path, int(track_id), int(win))
            return b""
        if not fut.done():
            return b""
        del self._storage_fetches[key]
        try:
            return fut.result()
        except Exception:
            return None

    def _peer_http_post(self, host: str, port: int, target: str,
                        body: bytes) -> bool:
        """One peer REST POST — helper-thread only, same auth rules as
        :meth:`_peer_http_get`."""
        import base64
        import http.client
        headers = {"Content-Type": "application/octet-stream"}
        if self.config.auth_enabled:
            cred = (f"{self.config.rest_username}:"
                    f"{self.config.rest_password}").encode()
            headers["Authorization"] = \
                "Basic " + base64.b64encode(cred).decode()
        try:
            conn = http.client.HTTPConnection(host, port, timeout=2.0)
            try:
                conn.request("POST", target, body=body, headers=headers)
                return conn.getresponse().status == 200
            finally:
                conn.close()
        except OSError:
            return False

    def _storage_push_blocking(self, node_meta: dict, asset: str,
                               name: str, payload: bytes,
                               manifest_json: str) -> bool:
        from urllib.parse import quote
        host, port = node_meta.get("ip"), node_meta.get("http")
        if not host or not port:
            return False
        return self._peer_http_post(
            str(host), int(port),
            f"/api/v1/shardpush?path={quote(asset)}&name={quote(name)}",
            manifest_json.encode() + b"\n\n" + payload)

    def _storage_fetch_blocking(self, node_meta: dict, asset: str,
                                name: str) -> bytes | None:
        from urllib.parse import quote
        host, port = node_meta.get("ip"), node_meta.get("http")
        if not host or not port:
            return None
        return self._peer_http_get(
            str(host), int(port),
            f"/api/v1/shard?path={quote(asset)}&name={quote(name)}")

    def _storage_manifest_blocking(self, node_meta: dict,
                                   asset: str) -> dict | None:
        import json
        from urllib.parse import quote
        host, port = node_meta.get("ip"), node_meta.get("http")
        if not host or not port:
            return None
        raw = self._peer_http_get(
            str(host), int(port),
            f"/api/v1/shardmeta?path={quote(asset)}")
        if raw is None:
            return None
        try:
            doc = json.loads(raw.decode("utf-8", "replace"))
        except ValueError:
            return None
        return doc if isinstance(doc, dict) else None

    def _write_vod_cache_meta(self) -> None:
        """Atomic write of the segment cache's hot-set metadata next to
        the relay checkpoint (same cadence, same tmp+rename rule)."""
        import json
        import os
        path = getattr(self, "_vod_ckpt_path", None)
        if path is None:
            return
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(self.vod_cache.snapshot(), fh,
                          separators=(",", ":"))
            os.replace(tmp, path)
        except OSError:
            pass

    def _park_tcp_record(self, path: str, track_id, rec: dict) -> None:
        """Checkpoint restore sink for ``kind=tcp`` records: park until
        the player re-attaches.  Records with no session id can never
        be matched — counted orphan immediately instead of rotting."""
        from .. import obs
        sid = rec.get("session_id")
        if not sid:
            obs.RESILIENCE_CKPT_TCP_ORPHANS.inc()
            obs.EVENTS.emit("ckpt.tcp_orphan", stream=path or "?",
                            reason="no_session_id")
            return
        self._pending_tcp[(path, track_id, sid)] = (rec, time.monotonic())

    def claim_tcp_restore(self, path: str, track_id, sid: str):
        """The rtsp SETUP re-attach hook: pop-and-return the parked
        record for (path, track, old Session id), or None."""
        ent = self._pending_tcp.pop((path, track_id, sid), None)
        return ent[0] if ent is not None else None

    def _sweep_pending_tcp(self) -> None:
        """Discard parked TCP records no player reclaimed within the
        RTSP timeout — stale-connection records must not adopt into a
        much later, unrelated subscriber."""
        if not self._pending_tcp:
            return
        from .. import obs
        now = time.monotonic()
        for key in [k for k, (_r, t0) in self._pending_tcp.items()
                    if now - t0 > self.config.rtsp_timeout_sec]:
            del self._pending_tcp[key]
            obs.RESILIENCE_CKPT_TCP_ORPHANS.inc()
            obs.EVENTS.emit("ckpt.tcp_orphan", stream=key[0],
                            reason="timeout", track=key[1])

    def _sweep_restored(self) -> None:
        """Reap restored subscribers whose player never proved itself:
        no ownership-proven RTCP for ``rtsp_timeout_sec`` (the same
        clock a live UDP player's connection is held to) removes the
        output — a vanished player cannot be relayed to forever."""
        self._sweep_pending_tcp()
        if not self._restored_subs:
            return
        now = time.monotonic()
        egress = self.rtsp.shared_egress
        for sub in list(self._restored_subs):
            stale = (now - sub.last_activity
                     > self.config.rtsp_timeout_sec)
            gone = self.registry.find(sub.path) is not sub.relay
            if not (stale or gone):
                continue
            self._restored_subs.remove(sub)
            if not gone:
                sub.stream.remove_output(sub.output)
            if egress is not None:
                egress.unregister(sub.output, sub)

    # ------------------------------------------------- egress backend probe
    def _init_egress_backend(self) -> None:
        """The boot-time probe ladder (ISSUE 8): resolve the configured
        ``egress_backend`` against what this kernel actually grants.

        Every probe failure — ENOSYS (pre-5.1), seccomp EPERM,
        RLIMIT_MEMLOCK too small for the registered arena — lands on the
        GSO rung with ONE structured ``egress.backend_fallback`` event
        and a fallback counter tick, never a counted hard_error (the
        same fix shape as the PR 4 GSO EINVAL probe)."""
        from .. import native, obs
        choice = self.config.egress_backend_choice()  # raises on a typo
        # engines must see the SAME normalized choice the ladder used —
        # handing them the raw pref ("Auto", "IO_URING ") would make
        # metrics claim one rung while every pass serves another
        self._egress_backend_choice = choice
        egress = self.rtsp.shared_egress
        effective = "scalar" if choice == "scalar" else "gso"
        if (choice in ("auto", "io_uring") and egress is not None
                and egress.active and native.available()):
            caps = native.uring_probe()
            if caps >= 0:
                try:
                    from ..relay.ring import SLOT_SIZE
                    self.uring_egress = native.UringEgress(
                        egress.fileno(), max_pkt=SLOT_SIZE)
                    effective = "io_uring"
                    self.uring_ingest_enabled = bool(
                        self.config.native_ingest
                        and caps & native.URING_CAP_RECV_MULTI)
                    self.rtsp.uring_ingest_enabled = \
                        self.uring_ingest_enabled
                except OSError as e:
                    caps = -(e.errno or 38)
            if caps < 0:
                import errno as errno_mod
                reason = errno_mod.errorcode.get(-caps, str(-caps))
                obs.EGRESS_BACKEND_FALLBACKS.inc(backend="io_uring")
                obs.EVENTS.emit(
                    "egress.backend_fallback",
                    level="warn" if choice == "io_uring" else "info",
                    backend="io_uring", fallback="gso", reason=reason)
                if self.error_log:
                    self.error_log.info(
                        f"egress backend: io_uring unavailable "
                        f"({reason}), serving from the GSO rung")
        self.egress_backend_effective = effective
        # info-style gauge: exactly one backend child reads 1 so a
        # forced-backend soak can assert what serves the wire
        for b in ("io_uring", "gso", "scalar"):
            obs.EGRESS_BACKEND_INFO.set(1 if b == effective else 0,
                                        backend=b)
        if self.error_log and effective != "gso":
            self.error_log.info(f"egress backend: {effective}"
                                + (f" (caps={self.uring_egress.caps})"
                                   if self.uring_egress else ""))

    # ---------------------------------------------------------- pump loop
    def _engine_for(self, stream) -> TpuFanoutEngine:
        eng = self._engines.get(id(stream))
        if eng is None:
            eng = self._engines[id(stream)] = TpuFanoutEngine(
                egress_backend=getattr(self, "_egress_backend_choice",
                                       None) or "auto",
                uring=self.uring_egress)
        egress = self.rtsp.shared_egress
        eng.egress_fd = egress.fileno() if egress is not None else None
        eng.uring = self.uring_egress
        eng.tcp_fast_enabled = self.config.tcp_engine_enabled
        return eng

    def _reflect_all(self) -> int:
        t = now_ms()
        wake_ns, self._wake_ns = self._wake_ns, None
        from ..obs import LEDGER
        if wake_ns is not None:
            # wake→pass queueing delay: ingest set the event at wake_ns,
            # the loop got scheduled and reached the pass now — event-loop
            # lag the per-pass phases cannot see but players feel
            from ..obs import PROFILER
            PROFILER.observe("wake_to_pass", "pump",
                             time.perf_counter_ns() - wake_ns)
        # wake ledger (ISSUE 16): one record per wake, every unit below
        # tagged with its work class.  The record stays open through the
        # 1 Hz maintenance block in _pump_loop (end_wake there); direct
        # callers (tests, bench) are covered by begin_wake folding any
        # unclosed predecessor.
        LEDGER.begin_wake(wake_ns)
        led_on = LEDGER.enabled
        sent = 0
        use_tpu = self.config.tpu_fanout
        # megabatch: coalesce every engine-eligible stream's device work
        # into one shape-bucketed stacked pass per wake (ISSUE 4).  The
        # scheduler harvests the previous wake's in-flight pass here,
        # the per-stream steps below consume the installed params, and
        # end_wake stages+dispatches the next pass after the loop.  Any
        # scheduler failure degrades to per-stream stepping, never to a
        # halted pump.
        # VOD group pacer (ISSUE 10): fill every hot session's rings up
        # to the lookahead horizon and collect its (stream, engine)
        # pairs — paced VOD subscribers are first-class relay streams
        # the pump steps below and the megabatch scheduler coalesces
        # with live streams.  Any pacer failure degrades THIS wake's
        # VOD service, never the pump.
        vod_pairs = []
        if self.vod_pacer is not None and self.vod_pacer.sessions:
            _u = LEDGER.unit_start()
            try:
                vod_pairs = self.vod_pacer.tick(t)
            except Exception as e:
                vod_pairs = []
                if self.error_log:
                    self.error_log.warning(f"vod pacer: {e!r}")
            LEDGER.unit_end(_u, "vod_fill", items=max(len(vod_pairs), 1))
        # DVR window spill (ISSUE 12): snapshot any live ring window the
        # head completed since the last wake (an integer compare per
        # armed stream when nothing did).  Runs BEFORE the reflect pass
        # so a time-shift cursor parked at the spill/ring seam sees the
        # freshest cold tail.  Failures degrade recording, not relaying.
        if self.dvr is not None and self.dvr._armed:
            _u = LEDGER.unit_start()
            try:
                self.dvr.tick(t)
            except Exception as e:
                if self.error_log:
                    self.error_log.warning(f"dvr spill: {e!r}")
            LEDGER.unit_end(_u, "dvr_spill")
        mega_pairs = []
        lad = self.ladder
        if use_tpu and self.config.megabatch_enabled:
            for sess in list(self.registry.sessions.values()):
                for stream in sess.streams.values():
                    if (stream.num_outputs >= self.config.tpu_min_outputs
                            and (lad is None
                                 or lad.allows_megabatch(sess.path))):
                        mega_pairs.append((stream,
                                           self._engine_for(stream)))
            # paced VOD streams are always megabatch-eligible when the
            # engine tier is on: the affine rewrite is content-
            # independent, and a 1-subscriber VOD stream costs one
            # bucket row, not a device pass
            mega_pairs.extend(vod_pairs)
            if len(mega_pairs) >= self.config.megabatch_min_streams:
                if self.megabatch is None:
                    from ..relay.megabatch import MegabatchScheduler
                    self.megabatch = MegabatchScheduler(
                        mesh=self.megabatch_mesh)
                _u = LEDGER.unit_start()
                try:
                    self.megabatch.begin_wake(mega_pairs, t)
                except Exception as e:
                    if lad is not None:
                        lad.note_scheduler_error(
                            [s.session_path for s, _ in mega_pairs])
                    mega_pairs = []
                    if self.error_log:
                        self.error_log.warning(f"megabatch harvest: {e!r}")
                LEDGER.unit_end(_u, "megabatch",
                                items=max(len(mega_pairs), 1))
            else:
                mega_pairs = []
        if not mega_pairs and self.megabatch is not None:
            # scheduler built but not engaged this wake (mass teardown,
            # megabatch disabled): keep harvesting in-flight passes so
            # they can't pin torn-down streams and staging buffers
            _u = LEDGER.unit_start()
            try:
                self.megabatch.idle_wake()
            except Exception as e:
                if self.error_log:
                    self.error_log.warning(f"megabatch idle: {e!r}")
            LEDGER.unit_end(_u, "megabatch")
        mega_ids = {id(s) for s, _ in mega_pairs}
        # live relay pass: ONE ledger unit covering every live stream's
        # step/reflect; the slowest stream's trace_id rides the record
        # (the critical-path correlation a p99 sample decomposes by)
        _lu = LEDGER.unit_start()
        _n_live = 0
        _worst_ns, _worst_trace = -1, None
        for sess in list(self.registry.sessions.values()):
            for stream in sess.streams.values():
                _s0 = time.perf_counter_ns() if led_on else 0
                _n_live += 1
                # per-stream guard: one bad output (broken socket, buggy
                # transcoder tap) must never halt fan-out for the rest
                pre_stalls = stream.stats.stalls
                # ladder rung (resilience/ladder.py): ≤1 keeps the
                # device engine (0 = megabatch-coalesced); ≥2 — or a
                # retry-backoff window — serves via the CPU oracle,
                # the mandatory fallback the north star requires
                mode = 0 if lad is None else lad.engine_mode(sess.path)
                device = (use_tpu and stream.num_outputs
                          >= self.config.tpu_min_outputs and mode <= 1)
                try:
                    if device:
                        eng = self._engine_for(stream)
                        eng.megabatch_owned = id(stream) in mega_ids
                        sent += eng.step(stream, t)
                        if lad is not None:
                            lad.note_device_ok(sess.path)
                    else:
                        sent += stream.reflect(t)
                except Exception as e:
                    if device and lad is not None:
                        # the DEVICE path failed: bounded retry with
                        # backoff first, rung change only past the
                        # budget.  Oracle-path failures (one broken
                        # output) are logged only — they are not device
                        # health and must not move the ladder
                        lad.note_device_error(sess.path)
                    if self.error_log:
                        self.error_log.warning(
                            f"reflect error on {sess.path}: {e!r}")
                try:
                    for out in stream.tickable_outputs:
                        # reliable-UDP retransmit sweep (RTO-expired
                        # packets; RTPPacketResender resend-on-interval)
                        sent += out.tick(t)
                except Exception as e:
                    # one buggy output's sweep must neither halt fan-out
                    # nor masquerade as a device error
                    if self.error_log:
                        self.error_log.warning(
                            f"tick error on {sess.path}: {e!r}")
                # wheel hint: a due-but-held bucket release on a
                # NON-stalled stream just matured mid-pass and may be
                # armed immediately; a stalled stream must not be (a
                # time wake cannot unblock a full socket)
                stream._last_pass_stalled = \
                    stream.stats.stalls > pre_stalls
                if led_on:
                    _el = time.perf_counter_ns() - _s0
                    if _el > _worst_ns:
                        _worst_ns, _worst_trace = _el, stream.trace_id
        LEDGER.unit_end(_lu, "live_relay", items=max(_n_live, 1),
                        trace_id=_worst_trace)
        # paced VOD streams: same per-stream guard discipline as live.
        # The device gate ignores tpu_min_outputs — a VOD subscriber is
        # one output by construction, and its device cost is a bucket
        # row in the stacked pass, not a per-stream dispatch
        _vu = LEDGER.unit_start() if vod_pairs else None
        for stream, eng in vod_pairs:
            pre_stalls = stream.stats.stalls
            try:
                if use_tpu and eng is not None:
                    eng.megabatch_owned = id(stream) in mega_ids
                    sent += eng.step(stream, t)
                else:
                    sent += stream.reflect(t)
            except Exception as e:
                if self.error_log:
                    self.error_log.warning(
                        f"vod reflect error on {stream.session_path}: "
                        f"{e!r}")
            try:
                for out in stream.tickable_outputs:
                    sent += out.tick(t)
            except Exception as e:
                if self.error_log:
                    self.error_log.warning(
                        f"vod tick error on {stream.session_path}: {e!r}")
            stream._last_pass_stalled = \
                stream.stats.stalls > pre_stalls
        if _vu is not None:
            LEDGER.unit_end(_vu, "vod_fill", items=len(vod_pairs))
        if mega_pairs:
            _u = LEDGER.unit_start()
            try:
                self.megabatch.end_wake(mega_pairs, t)
            except Exception as e:
                if lad is not None:
                    lad.note_scheduler_error(
                        [s.session_path for s, _ in mega_pairs])
                if self.error_log:
                    self.error_log.warning(f"megabatch stage: {e!r}")
            LEDGER.unit_end(_u, "megabatch", items=len(mega_pairs))
        return sent

    def _make_pump_wheel(self):
        """1 ms native timer wheel pacing the pump below the fixed tick
        (``csrc ed_wheel``; the reference's scheduler has a 10 ms floor,
        ``Task.cpp:334-335``).  Streams post their earliest bucket-delay
        release / reliable-UDP RTO here; the pump sleeps until the wheel's
        next deadline instead of a full reflect interval."""
        from .. import native
        if not native.available():
            return None
        try:
            return native.TimerWheel(now_ms())
        except RuntimeError:
            return None

    def _schedule_stream_deadlines(self, wheel, t: int) -> None:
        """``t`` must be the time the wheel was last advanced to, so
        relative deadlines land on the right tick."""
        for sess in self.registry.sessions.values():
            for stream in sess.streams.values():
                allow_due = not getattr(stream, "_last_pass_stalled", False)
                d = stream.next_deadline_ms(t, allow_due=allow_due)
                key = id(stream)
                cur = self._wheel_sched.get(key)
                if d < 0:
                    continue
                due = t + d
                if cur is not None and cur[1] <= due and cur[1] >= t:
                    continue            # an earlier-or-equal timer pends
                if cur is not None:
                    wheel.cancel(cur[0])
                self._wheel_sched[key] = (wheel.schedule(d, key), due)

    async def _pump_loop(self) -> None:
        interval = self.config.reflect_interval_ms / 1000.0
        last_prune = 0.0
        wheel = self._make_pump_wheel()
        self._wheel_sched: dict[int, tuple[int, int]] = {}
        while self._running:
            timeout = interval
            if wheel is not None and wheel.pending:
                nd = wheel.next_deadline(now_ms())
                if nd >= 0:
                    timeout = min(interval, max(nd, 1) / 1000.0)
            try:
                await asyncio.wait_for(self._pump_event.wait(), timeout)
            except asyncio.TimeoutError:
                pass
            self._pump_event.clear()
            self._reflect_all()
            if wheel is not None:
                # advance and schedule against the SAME clock sample, or
                # timers fire early by the reflect-pass duration
                t = now_ms()
                for key in wheel.advance(t):
                    self._wheel_sched.pop(key, None)
                self._schedule_stream_deadlines(wheel, t)
            now = time.monotonic()
            if now - last_prune >= 1.0:
                last_prune = now
                t = now_ms()
                for sess in list(self.registry.sessions.values()):
                    sess.prune(t)
                    for st in sess.streams.values():
                        st.send_upstream_rr(t)  # 5 s pusher liveness RRs
                if self.config.slo_enabled:
                    try:
                        self.slo.tick()
                    except Exception as e:
                        if self.error_log:
                            self.error_log.warning(f"slo tick: {e!r}")
                try:
                    # per-stream end-to-end freshness (ISSUE 15): one
                    # observation per actively-relaying stream per
                    # second, hop count from the freshness chain
                    from ..obs import fleet as fleet_mod
                    fleet_mod.observe_freshness(self)
                except Exception as e:
                    if self.error_log:
                        self.error_log.warning(f"freshness: {e!r}")
                try:
                    # audience observatory (ISSUE 18): derive stalls /
                    # QoE / storm latches from the columnar store —
                    # array passes per stream block, never per packet
                    from ..obs import AUDIENCE
                    AUDIENCE.tick()
                except Exception as e:
                    if self.error_log:
                        self.error_log.warning(f"audience tick: {e!r}")
                if self.ladder is not None:
                    try:
                        self._ladder_maintenance()
                    except Exception as e:
                        if self.error_log:
                            self.error_log.warning(f"ladder tick: {e!r}")
                if self.checkpoint is not None:
                    from ..obs import LEDGER
                    _u = LEDGER.unit_start()
                    try:
                        wrote = self.checkpoint.maybe_write(self.registry)
                        if wrote and self.vod_cache is not None:
                            self._write_vod_cache_meta()
                    except Exception as e:
                        if self.error_log:
                            self.error_log.warning(f"checkpoint: {e!r}")
                    LEDGER.unit_end(_u, "checkpoint")
                if self.presence is not None:
                    self.presence.set_load(sum(
                        s.num_outputs
                        for s in self.registry.sessions.values()))
                    try:
                        await self.presence.sync_streams(self.registry.paths())
                    except Exception:
                        pass
            # close this wake's ledger record AFTER the maintenance
            # block: the 1 Hz duties ran on the same wake's thread time,
            # so their service belongs to the record a queued packet's
            # wait decomposes against
            from ..obs import LEDGER
            LEDGER.end_wake()

    def _ladder_maintenance(self) -> None:
        """1 Hz ladder duties: evaluate recovery/SLO pressure, then shed
        the newest subscriber of any rung-3 stream (one per session per
        tick — shedding is a pressure valve, not an eviction sweep)."""
        from .. import obs
        from ..resilience import LEVEL_SHED
        stalls = {
            sess.path: sum(st.stats.stalls
                           for st in sess.streams.values())
            for sess in self.registry.sessions.values()}
        slo_status = None
        offender = None
        if self.config.slo_enabled:
            slo_status = self.slo.status()
            from ..obs import PROFILER
            offender = PROFILER.top_offender()
        self.ladder.tick(stalls, slo_status=slo_status, offender=offender)
        for sess in list(self.registry.sessions.values()):
            if self.ladder.level(sess.path) < LEVEL_SHED:
                continue
            for stream in sess.streams.values():
                out = self.ladder.shed_candidate(stream)
                if out is not None and stream.remove_output(out):
                    obs.RESILIENCE_SHED_OUTPUTS.inc()
                    obs.EVENTS.emit(
                        "ladder.shed", level="warn", stream=sess.path,
                        trace_id=sess.trace_id,
                        outputs=stream.num_outputs)
                    break

    async def _status_loop(self) -> None:
        """The 1 Hz supervisor's status duties (RunServer.cpp:620-719):
        console columns every ``stats_interval_sec``, status file every
        ``status_file_interval_sec``."""
        import sys
        last_file = 0.0
        # tick fast enough for BOTH outputs: -S 60 must not stretch a 10 s
        # file cadence to 60 s
        enabled = [i for i in (self.config.stats_interval_sec,
                               self.config.status_file_interval_sec
                               if self.config.status_file_path else 0) if i]
        interval = min(enabled) if enabled else 1
        last_console = 0.0
        while self._running:
            await asyncio.sleep(interval)
            snap = self.status.tick()       # the ONE baseline advance per
            # tick; console and file read the returned snapshot (and any
            # concurrent REST reader uses the pure snapshot())
            now = time.monotonic()
            if (self.config.stats_interval_sec and now - last_console
                    >= self.config.stats_interval_sec - interval / 2):
                last_console = now
                if self.status.needs_header():
                    print(self.status.header_line(), file=sys.stderr)
                print(self.status.console_line(snap), file=sys.stderr,
                      flush=True)
            if (self.config.status_file_path
                    and now - last_file
                    >= self.config.status_file_interval_sec - interval / 2):
                last_file = now
                try:
                    self.status.write_file(self.config.status_file_path,
                                           snap)
                except OSError:
                    pass

    async def _sweep_loop(self) -> None:
        while self._running:
            await asyncio.sleep(self.config.timeout_sweep_sec)
            self.rtsp.sweep_timeouts()
            self._sweep_restored()
            self.relay_source.sweep()
            self.transcodes.sweep()
            self.hls.sweep()
            await self.pulls.sweep()
            # background scrub (ISSUE 20): a bounded batch of local
            # shard crc32 / parity-oracle verifications per interval,
            # off the event loop — corruption is found BEFORE a reader
            # needs the shard
            if self.storage is not None:
                now = time.monotonic()
                if now >= self._storage_scrub_due:
                    self._storage_scrub_due = (
                        now + self.config.storage_scrub_interval_sec)
                    st = self.storage
                    st._executor().submit(st.scrub_tick)

    async def _rtsp_port_http_get(self, conn, target: str,
                                  headers: dict) -> bool:
        """Plain HTTP GET on the RTSP port: icy MP3 streams + stats page."""
        path = target.split("?")[0]
        if path.lower().endswith(".mp3"):
            await self.mp3.stream(conn.writer, path, headers)
            return True
        if path.lower().endswith(".m3u"):
            # directory scan + per-file ID3 probes are blocking IO —
            # keep them off the shared event loop
            pl = await asyncio.to_thread(self.mp3.playlist, path)
            if pl is not None:
                body = pl.encode()
                conn.writer.write(
                    b"HTTP/1.0 200 OK\r\n"
                    b"Content-Type: audio/x-mpegurl\r\n"
                    b"Content-Length: " + str(len(body)).encode()
                    + b"\r\n\r\n" + body)
                return True
        if path in ("/", "/stats"):
            html = self.rest._webstats_html().encode()
            conn.writer.write(
                b"HTTP/1.0 200 OK\r\nContent-Type: text/html\r\n"
                b"Content-Length: " + str(len(html)).encode() + b"\r\n\r\n"
                + html)
            return True
        return False

    # ------------------------------------------------------------- queries
    def server_info(self) -> dict:
        # pure snapshot(): REST readers share the status loop's tick()
        # baseline instead of racing it (the old sample()-everywhere
        # design zeroed whichever reader came second in a tick)
        d = self.status.snapshot()
        mesh_info = {}
        if self.megabatch_mesh is not None:
            # the mesh→process mapping, live (previously only the
            # multichip dryrun could see process_span)
            try:
                from ..parallel.distributed import mesh_summary
                mesh_info = mesh_summary(self.megabatch_mesh)
                if self.megabatch is not None:
                    mesh_info["MeshShardedPasses"] = str(
                        self.megabatch.sharded_passes)
            except Exception:
                mesh_info = {}
        return {
            **mesh_info,
            "ServerName": "easydarwin-tpu",
            "Version": "0.1.0",
            "UpTimeSec": str(d["uptime_sec"]),
            "RTSPPort": str(self.rtsp.port or self.config.rtsp_port),
            "ServicePort": str(self.rest.port or self.config.service_port),
            "Connections": str(d["rtsp_connections"]),
            "PushSessions": str(d["push_sessions"]),
            "Requests": str(d["requests"]),
            "PacketsIn": str(d["packets_in"]),
            "PacketsOut": str(d["packets_out"]),
            "InRatePps": str(d["in_rate"]),
            "OutRatePps": str(d["out_rate"]),
            "IngestToWireP99Ms": str(d["ingest_to_wire_p99_ms"]),
            "TpuFanout": "1" if self.config.tpu_fanout else "0",
            # wake-ledger summary (ISSUE 16): the console's "is the pump
            # starving" answer without a /metrics scrape
            "LedgerTopWaitClass": str(d.get("ledger_top_wait_class", "")),
            "LedgerLastWakeMs": str(d.get("ledger_last_wake_ms", 0.0)),
        }

    def live_sessions(self) -> list[dict]:
        out = []
        for sess in self.registry.sessions.values():
            st = sess.stats()
            out.append({
                "Path": sess.path,
                "Url": f"rtsp://{self.config.wan_ip}:"
                       f"{self.rtsp.port or self.config.rtsp_port}{sess.path}",
                "Outputs": str(sess.num_outputs),
                "AgeSec": str((now_ms() - sess.created_ms) // 1000),
                "Streams": st["streams"],
            })
        return out

    def device_stream_url(self, device: str) -> str | None:
        path = f"/{device.strip('/')}"
        for cand in (path, f"/live/{device.strip('/')}"):
            if self.registry.find(cand) is not None:
                return (f"rtsp://{self.config.wan_ip}:"
                        f"{self.rtsp.port or self.config.rtsp_port}{cand}")
        return None
