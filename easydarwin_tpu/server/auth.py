"""RTSP authentication: Basic + Digest, users file, per-path access rules.

Reference parity: ``QTSSAccessModule`` (``QTSSAccessModule.cpp:117-523`` +
``AccessChecker.cpp``): a qtpasswd-style users file holding
``user: MD5(user:realm:password)`` digests and qtaccess-style per-path
rules (``require user a b`` / ``require valid-user`` / open).  Digest auth
follows RFC 2617 MD5 with server nonces; Basic decodes and hashes through
the same table.
"""

from __future__ import annotations

import base64
import hashlib
import os
import secrets
import time


def ha1(user: str, realm: str, password: str) -> str:
    return hashlib.md5(f"{user}:{realm}:{password}".encode()).hexdigest()


class UsersFile:
    """``user:realm:ha1`` lines (what qtpasswd produces)."""

    def __init__(self, path: str | None = None, realm: str = "easydarwin-tpu"):
        self.path = path
        self.realm = realm
        self.users: dict[str, str] = {}        # user -> ha1
        if path and os.path.exists(path):
            self.load()

    def load(self) -> None:
        self.users.clear()
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(":")
                if len(parts) == 3:
                    user, realm, digest = parts
                    self.users[user] = digest
                    self.realm = realm

    def add(self, user: str, password: str) -> None:
        self.users[user] = ha1(user, self.realm, password)

    def check_password(self, user: str, password: str) -> bool:
        want = self.users.get(user)
        return want is not None and want == ha1(user, self.realm, password)


class AccessRules:
    """Longest-prefix path rules: None = open, [] = any valid user,
    [names] = listed users only (qtaccess 'require')."""

    def __init__(self):
        self._rules: dict[str, list[str] | None] = {}

    def protect(self, prefix: str, users: list[str] | None = None) -> None:
        self._rules[prefix.rstrip("/") or "/"] = (
            list(users) if users is not None else [])

    def open_path(self, prefix: str) -> None:
        self._rules[prefix.rstrip("/") or "/"] = None

    def required_users(self, path: str) -> list[str] | None:
        best, rule = -1, None
        for prefix, users in self._rules.items():
            if (path == prefix or path.startswith(prefix + "/")
                    or prefix == "/"):
                if len(prefix) > best:
                    best, rule = len(prefix), users
        return rule


class AuthService:
    NONCE_TTL = 300.0

    def __init__(self, users: UsersFile, rules: AccessRules | None = None,
                 *, scheme: str = "digest"):
        self.users = users
        self.rules = rules or AccessRules()
        self.scheme = scheme
        self._nonces: dict[str, float] = {}

    # -- challenge ---------------------------------------------------------
    def challenge(self) -> str:
        if self.scheme == "basic":
            return f'Basic realm="{self.users.realm}"'
        nonce = secrets.token_hex(16)
        self._nonces[nonce] = time.time()
        return (f'Digest realm="{self.users.realm}", nonce="{nonce}", '
                f'algorithm=MD5')

    def _nonce_ok(self, nonce: str) -> bool:
        t = self._nonces.get(nonce)
        if t is None or time.time() - t > self.NONCE_TTL:
            self._nonces.pop(nonce, None)
            return False
        return True

    # -- verification ------------------------------------------------------
    def authorize(self, path: str, method: str,
                  authorization: str | None) -> tuple[bool, str | None]:
        """(allowed, authenticated user). Paths with no rule are open."""
        required = self.rules.required_users(path)
        if required is None:
            return True, None
        user = self._authenticate(method, authorization)
        if user is None:
            return False, None
        if required and user not in required:
            return False, user
        return True, user

    def _authenticate(self, method: str, header: str | None) -> str | None:
        if not header:
            return None
        scheme, _, rest = header.partition(" ")
        scheme = scheme.lower()
        if scheme == "basic":
            try:
                user, _, pw = base64.b64decode(rest).decode().partition(":")
            except (ValueError, UnicodeDecodeError):
                return None
            return user if self.users.check_password(user, pw) else None
        if scheme == "digest":
            fields = {}
            for part in rest.split(","):
                k, _, v = part.strip().partition("=")
                fields[k.lower()] = v.strip('"')
            user = fields.get("username", "")
            nonce = fields.get("nonce", "")
            uri = fields.get("uri", "")
            resp = fields.get("response", "")
            if not self._nonce_ok(nonce):
                return None
            h1 = self.users.users.get(user)
            if h1 is None:
                return None
            h2 = hashlib.md5(f"{method}:{uri}".encode()).hexdigest()
            want = hashlib.md5(f"{h1}:{nonce}:{h2}".encode()).hexdigest()
            return user if secrets.compare_digest(want, resp) else None
        return None


def digest_response(user: str, password: str, realm: str, method: str,
                    uri: str, nonce: str) -> str:
    """Client-side helper (tests / RtspClient)."""
    h1 = ha1(user, realm, password)
    h2 = hashlib.md5(f"{method}:{uri}".encode()).hexdigest()
    resp = hashlib.md5(f"{h1}:{nonce}:{h2}".encode()).hexdigest()
    return (f'Digest username="{user}", realm="{realm}", nonce="{nonce}", '
            f'uri="{uri}", response="{resp}"')
