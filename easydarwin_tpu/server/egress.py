"""Shared UDP egress pair + native scatter sender for player outputs.

The reference serves every RTP client from a *shared* UDP socket pair per
NIC (``QTSServer::SetupUDPSockets`` → ``RTPSocketPool``,
``QTSServer.cpp:668,1259-1290``), demultiplexing inbound RTCP by source
address (``UDPDemuxer.h``).  Round 1 of this build allocated one socket
pair per player instead, which made per-packet asyncio ``sendto`` the only
egress path.  This module restores the reference's shared-pair shape and
uses it as the doorway to the native batched egress: every UDP player's
packets leave through ONE unconnected socket via ``csrc``'s
sendmmsg/UDP-GSO scatter (``native.fanout_send_multi``), so the TPU
engine's affine rewrite params drive the wire directly — no per-packet
Python, no per-subscriber payload copies.

RTCP still rides asyncio (low rate): one shared socket receives player
receiver reports and demuxes them to the owning connection by source
address, exactly the UDPDemuxer role.
"""

from __future__ import annotations

import asyncio
import socket

from ..relay.output import RelayOutput, WriteResult


class NativeUdpOutput(RelayOutput):
    """One subscriber × one track on the shared egress pair.

    The TPU engine recognizes these by ``native_addr`` and routes their
    packets through the native scatter sender; the scalar oracle path
    still works (``send_bytes`` below) so differential tests and the
    CPU fallback see identical behavior."""

    def __init__(self, egress: "SharedUdpEgress", client_ip: str,
                 client_rtp_port: int, client_rtcp_port: int, **kw):
        super().__init__(**kw)
        self.egress = egress
        self.rtp_addr = (client_ip, client_rtp_port)
        self.rtcp_addr = (client_ip, client_rtcp_port)

    @property
    def native_addr(self) -> tuple[str, int]:
        return self.rtp_addr

    def send_bytes(self, data: bytes, *, is_rtcp: bool) -> WriteResult:
        if is_rtcp:
            return self.egress.send_rtcp(data, self.rtcp_addr)
        return self.egress.send_rtp(data, self.rtp_addr)

class _RtcpProtocol(asyncio.DatagramProtocol):
    def __init__(self, egress: "SharedUdpEgress"):
        self.egress = egress
        self.transport = None

    def connection_made(self, transport):
        self.transport = transport

    def datagram_received(self, data, addr):
        self.egress._on_rtcp(data, addr)


class SharedUdpEgress:
    """The server's shared (RTP, RTCP) UDP pair.

    * RTP: a plain non-blocking socket.  The engine's native path writes
      it with sendmmsg/GSO; the scalar path with ``sendto`` (WouldBlock
      surfaces as a bookmark replay, same contract as the reference's
      ``RTPStream::Write``).
    * RTCP: an asyncio endpoint; inbound receiver reports demux by source
      address to the registered connection (UDPDemuxer equivalent).
    """

    def __init__(self, bind_ip: str = "0.0.0.0"):
        self.bind_ip = bind_ip
        self.rtp_sock: socket.socket | None = None
        self.rtcp_transport = None
        self.rtp_port = 0
        self.rtcp_port = 0
        #: (ip, port) → (conn, handler) exact-address demux
        self._demux: dict[tuple[str, int], object] = {}
        #: ip → set of registered conns (fallback when the client sends
        #: RTCP from an ephemeral port, which NATs and stacks often do)
        self._by_ip: dict[str, list] = {}
        self.on_rtcp = None             # set by the server: (conn, data) -> None
        self.rtcp_in = 0
        self.send_errors = 0

    @property
    def active(self) -> bool:
        return self.rtp_sock is not None

    async def start(self) -> None:
        self.rtp_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.rtp_sock.setblocking(False)
        self.rtp_sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 1 << 22)
        self.rtp_sock.bind((self.bind_ip, 0))
        self.rtp_port = self.rtp_sock.getsockname()[1]
        loop = asyncio.get_running_loop()
        self.rtcp_transport, _ = await loop.create_datagram_endpoint(
            lambda: _RtcpProtocol(self), local_addr=(self.bind_ip, 0))
        self.rtcp_port = self.rtcp_transport.get_extra_info("sockname")[1]

    def close(self) -> None:
        if self.rtp_sock is not None:
            self.rtp_sock.close()
            self.rtp_sock = None
        if self.rtcp_transport is not None:
            self.rtcp_transport.close()
            self.rtcp_transport = None
        self._demux.clear()
        self._by_ip.clear()

    # -- registration (UDPDemuxer) ----------------------------------------
    def register(self, out: NativeUdpOutput, conn) -> None:
        prev = self._demux.get(out.rtcp_addr)
        self._demux[out.rtcp_addr] = conn
        conns = self._by_ip.setdefault(out.rtcp_addr[0], [])
        if prev is conn:
            return                  # re-SETUP of the same addr: idempotent
        if prev is not None and prev in conns:
            conns.remove(prev)      # addr re-claimed by a new connection
        conns.append(conn)

    def unregister(self, out: NativeUdpOutput, conn) -> None:
        if self._demux.get(out.rtcp_addr) is conn:
            del self._demux[out.rtcp_addr]
        conns = self._by_ip.get(out.rtcp_addr[0])
        if conns and conn in conns:
            conns.remove(conn)
            if not conns:
                del self._by_ip[out.rtcp_addr[0]]

    def _on_rtcp(self, data: bytes, addr) -> None:
        self.rtcp_in += 1
        conn = self._demux.get((addr[0], addr[1]))
        if conn is None:
            # ephemeral source port: fall back to ip when unambiguous
            conns = self._by_ip.get(addr[0])
            if not conns:
                return
            if len(set(map(id, conns))) == 1:
                conn = conns[0]
            else:
                # several connections behind one IP (NAT): match the RR's
                # report-block SSRCs against each candidate's output SSRCs
                # instead of dropping the feedback (ADVICE r2)
                conn = self._match_by_ssrc(conns, data)
            if conn is None:
                return
        if self.on_rtcp is not None:
            self.on_rtcp(conn, data, addr)

    @staticmethod
    def _match_by_ssrc(conns, data: bytes):
        """The connection whose outputs own an SSRC this compound reports
        on — None when zero or several match (still ambiguous)."""
        from ..protocol import rtcp as rtcp_mod
        try:
            pkts = rtcp_mod.parse_compound(data)
        except rtcp_mod.RtcpError:
            return None
        reported = {rb.ssrc for p in pkts
                    if isinstance(p, rtcp_mod.ReceiverReport)
                    for rb in p.reports}
        if not reported:
            return None
        matches = []
        for conn in conns:
            tracks = getattr(conn, "player_tracks", None) or {}
            owned = {pt.output.rewrite.ssrc for pt in tracks.values()}
            if owned & reported:
                matches.append(conn)
        return matches[0] if len(matches) == 1 else None

    # -- scalar sends ------------------------------------------------------
    def send_rtp(self, data: bytes, addr) -> WriteResult:
        if self.rtp_sock is None:
            return WriteResult.ERROR
        try:
            self.rtp_sock.sendto(data, addr)
        except BlockingIOError:
            return WriteResult.WOULD_BLOCK
        except OSError:
            self.send_errors += 1
            return WriteResult.ERROR
        return WriteResult.OK

    def send_rtcp(self, data: bytes, addr) -> WriteResult:
        if self.rtcp_transport is None or self.rtcp_transport.is_closing():
            return WriteResult.ERROR
        self.rtcp_transport.sendto(data, addr)
        return WriteResult.OK

    def fileno(self) -> int:
        return self.rtp_sock.fileno() if self.rtp_sock is not None else -1
