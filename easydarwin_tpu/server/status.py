"""Runtime status: operator console columns + interval status file.

Reference parity: the ``-S n`` stats console (``RunServer.cpp:397-483`` —
1 Hz column printout of RTP conns/packet rates/late/quality the operators
eyeballed as their "test suite") and the ``server_status`` plist written on
an interval (``RunServer.cpp:248-388``).  The plist format is Apple legacy;
the idiomatic carrier today is a JSON snapshot with the same fields, which
also feeds the REST ``getserverinfo`` answer.

Read model: ``tick()`` advances the rate baseline exactly once per status
tick; ``snapshot()`` is a PURE read that combines live counters with the
rates computed by the last tick.  Any number of readers (console, status
file, REST ``getserverinfo``) can snapshot inside one tick without zeroing
each other's rates — the footgun the old single ``sample()`` had, where
the second caller in a tick saw dt≈0 and rates pinned to ~0 forever.
"""

from __future__ import annotations

import json
import os
import time

from .. import obs

#: console column layout (name, width) — RunServer.cpp:427-446 equivalents
COLUMNS = (("RTSP", 6), ("Push", 6), ("Play", 6), ("PktsIn", 10),
           ("PktsOut", 10), ("InRate/s", 10), ("OutRate/s", 10),
           ("Queue", 7), ("UpMin", 7))


class StatusMonitor:
    """Reads server counters, derives rates, renders console lines and
    JSON snapshots.  Pure (no I/O of its own) except ``write_file``."""

    def __init__(self, app):
        self.app = app
        self._last_t: float | None = None
        self._last_in = 0
        self._last_out = 0
        self._in_rate = 0.0
        self._out_rate = 0.0
        self._lines_printed = 0

    # -- sampling ----------------------------------------------------------
    def _counters(self) -> dict:
        app = self.app
        s = app.rtsp.stats
        pkts_out = sum(st.stats.packets_out
                       for sess in app.registry.sessions.values()
                       for st in sess.streams.values())
        queued = sum(len(st.rtp_ring)
                     for sess in app.registry.sessions.values()
                     for st in sess.streams.values())
        players = sum(sess.num_outputs
                      for sess in app.registry.sessions.values())
        return {
            "rtsp_connections": len(app.rtsp.connections),
            "push_sessions": len(app.registry.sessions),
            "players": players,
            "packets_in": s["packets_in"],
            "packets_out": pkts_out,
            "queued_packets": queued,
            "uptime_sec": int(time.time() - app.started_at),
            "requests": s["requests"],
        }

    def tick(self) -> dict:
        """Advance the rate baseline ONCE and return a snapshot.  Call
        exactly once per status tick; every other reader in the same tick
        uses ``snapshot()`` (or the dict this returns)."""
        c = self._counters()
        now = time.monotonic()
        if self._last_t is not None and now > self._last_t:
            dt = now - self._last_t
            self._in_rate = (c["packets_in"] - self._last_in) / dt
            self._out_rate = (c["packets_out"] - self._last_out) / dt
        self._last_t = now
        self._last_in = c["packets_in"]
        self._last_out = c["packets_out"]
        return self._render(c)

    def snapshot(self) -> dict:
        """PURE read: live counters + rates from the last ``tick()``.
        Never moves the baseline, so console, status file and REST can
        all call it inside one tick."""
        return self._render(self._counters())

    #: kept as an alias so older callers/tests keep working; semantics are
    #: tick() — it DOES advance the baseline
    sample = tick

    def _render(self, c: dict) -> dict:
        snap = dict(c)
        snap["in_rate"] = round(self._in_rate, 1)
        snap["out_rate"] = round(self._out_rate, 1)
        # key obs families mirrored into the operator surface: the real
        # in-server ingest→wire latency and the native bytes-to-wire the
        # console/plist never had (the whole point of the obs layer)
        obs.REGISTRY.collect()
        lat = obs.RELAY_INGEST_TO_WIRE
        snap["ingest_to_wire_count"] = lat.total_count()
        snap["ingest_to_wire_p50_ms"] = round(lat.quantile(0.5) * 1e3, 3)
        snap["ingest_to_wire_p99_ms"] = round(lat.quantile(0.99) * 1e3, 3)
        snap["wire_bytes"] = int(obs.EGRESS_BYTES.value())
        snap["tpu_passes"] = int(obs.TPU_PASSES.value())
        # wake-ledger summary (ISSUE 16): "is the pump starving" answered
        # from the console/getserverinfo without a scrape — the class
        # that waited longest in the latest wake and that wake's duration
        led = obs.LEDGER
        snap["ledger_top_wait_class"] = led.last_top_class
        snap["ledger_last_wake_ms"] = round(led.last_wake_ms, 3)
        snap["ledger_wakes"] = led.wakes
        # audience summary (ISSUE 18): "how are the viewers doing"
        # answered from the console surface without a scrape
        aud = obs.AUDIENCE.rollup()
        snap["audience_subscribers"] = aud["subscribers"]
        snap["audience_qoe_p50"] = aud["qoe_p50"]
        snap["audience_qoe_p10"] = aud["qoe_p10"]
        snap["audience_stalled_now"] = aud["stalled_now"]
        snap["audience_stall_storms"] = aud["stall_storms"]
        return snap

    # -- console (the -S display) -----------------------------------------
    def console_line(self, sample: dict | None = None) -> str:
        d = self.snapshot() if sample is None else sample
        vals = (d["rtsp_connections"], d["push_sessions"], d["players"],
                d["packets_in"], d["packets_out"], d["in_rate"],
                d["out_rate"], d["queued_packets"], d["uptime_sec"] // 60)
        line = "".join(str(v).rjust(w) for (_, w), v in zip(COLUMNS, vals))
        self._lines_printed += 1
        return line

    def header_line(self) -> str:
        return "".join(name.rjust(w) for name, w in COLUMNS)

    def needs_header(self, every: int = 20) -> bool:
        """Reprint the header every N lines, as the reference console does."""
        return self._lines_printed % every == 0

    # -- status file (the server_status plist) -----------------------------
    def write_file(self, path: str, sample: dict | None = None) -> None:
        """Defaults to the pure ``snapshot()`` — safe to combine with a
        console print in the same tick (the loop calls ``tick()`` once and
        hands the dict to both)."""
        snap = dict(self.snapshot() if sample is None else sample,
                    written_at=int(time.time()), server="easydarwin-tpu")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snap, f, indent=1)
        os.replace(tmp, path)           # atomic: readers never see a torn file
