"""Runtime status: operator console columns + interval status file.

Reference parity: the ``-S n`` stats console (``RunServer.cpp:397-483`` —
1 Hz column printout of RTP conns/packet rates/late/quality the operators
eyeballed as their "test suite") and the ``server_status`` plist written on
an interval (``RunServer.cpp:248-388``).  The plist format is Apple legacy;
the idiomatic carrier today is a JSON snapshot with the same fields, which
also feeds the REST ``getserverinfo`` answer.
"""

from __future__ import annotations

import json
import os
import time

#: console column layout (name, width) — RunServer.cpp:427-446 equivalents
COLUMNS = (("RTSP", 6), ("Push", 6), ("Play", 6), ("PktsIn", 10),
           ("PktsOut", 10), ("InRate/s", 10), ("OutRate/s", 10),
           ("Queue", 7), ("UpMin", 7))


class StatusMonitor:
    """Samples server counters, derives rates, renders console lines and
    JSON snapshots.  Pure (no I/O of its own) except ``write_file``."""

    def __init__(self, app):
        self.app = app
        self._last_t: float | None = None
        self._last_in = 0
        self._last_out = 0
        self._lines_printed = 0

    # -- sampling ----------------------------------------------------------
    def sample(self) -> dict:
        app = self.app
        s = app.rtsp.stats
        pkts_out = sum(st.stats.packets_out
                       for sess in app.registry.sessions.values()
                       for st in sess.streams.values())
        queued = sum(len(st.rtp_ring)
                     for sess in app.registry.sessions.values()
                     for st in sess.streams.values())
        players = sum(sess.num_outputs
                      for sess in app.registry.sessions.values())
        now = time.monotonic()
        in_rate = out_rate = 0.0
        if self._last_t is not None and now > self._last_t:
            dt = now - self._last_t
            in_rate = (s["packets_in"] - self._last_in) / dt
            out_rate = (pkts_out - self._last_out) / dt
        self._last_t = now
        self._last_in = s["packets_in"]
        self._last_out = pkts_out
        return {
            "rtsp_connections": len(app.rtsp.connections),
            "push_sessions": len(app.registry.sessions),
            "players": players,
            "packets_in": s["packets_in"],
            "packets_out": pkts_out,
            "in_rate": round(in_rate, 1),
            "out_rate": round(out_rate, 1),
            "queued_packets": queued,
            "uptime_sec": int(time.time() - app.started_at),
            "requests": s["requests"],
        }

    # -- console (the -S display) -----------------------------------------
    def console_line(self, sample: dict | None = None) -> str:
        d = self.sample() if sample is None else sample
        vals = (d["rtsp_connections"], d["push_sessions"], d["players"],
                d["packets_in"], d["packets_out"], d["in_rate"],
                d["out_rate"], d["queued_packets"], d["uptime_sec"] // 60)
        line = "".join(str(v).rjust(w) for (_, w), v in zip(COLUMNS, vals))
        self._lines_printed += 1
        return line

    def header_line(self) -> str:
        return "".join(name.rjust(w) for name, w in COLUMNS)

    def needs_header(self, every: int = 20) -> bool:
        """Reprint the header every N lines, as the reference console does."""
        return self._lines_printed % every == 0

    # -- status file (the server_status plist) -----------------------------
    def write_file(self, path: str, sample: dict | None = None) -> None:
        """``sample`` lets one tick share a single sample() with the console
        — sample() moves the rate baseline, so calling it twice per tick
        would make the second reader's rates ~0 forever."""
        snap = dict(self.sample() if sample is None else sample,
                    written_at=int(time.time()), server="easydarwin-tpu")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snap, f, indent=1)
        os.replace(tmp, path)           # atomic: readers never see a torn file
