"""Host-tier servers: RTSP protocol sessions + JSON REST management API.

Reference parity:

* ``config.py``      — the layered pref system (``QTSServerPrefs.cpp:190-280``
  typed table, SIGHUP/REST ``RereadPrefs`` rebroadcast) as a dataclass +
  TOML file + change hooks.
* ``rtsp.py``        — ``RTSPSession``'s per-request role pipeline
  (``RTSPSession.cpp:216`` state machine) as an asyncio connection handler
  speaking OPTIONS/DESCRIBE/ANNOUNCE/SETUP/PLAY/PAUSE/RECORD/TEARDOWN/
  GET_PARAMETER/SET_PARAMETER with interleaved-TCP and UDP transports.
* ``transports.py``  — ``RTPStream``'s send paths (UDP ``RTPStream.cpp:1145``,
  interleaved ``cpp:772``) + the RTP/RTCP port-pair pool
  (``UDPSocketPool.h``) on asyncio datagram endpoints, with real
  WouldBlock semantics from transport write-buffer high-water marks.
* ``rest.py``        — the ``HTTPSession`` JSON API (routes
  ``HTTPSession.cpp:365-405``) on the service port.
* ``app.py``         — ``RunServer.cpp`` boot/supervision: wires config,
  session registry, relay pump, timeout sweeps, REST; graceful shutdown.
"""

from .config import ServerConfig  # noqa: F401
from .app import StreamingServer  # noqa: F401
