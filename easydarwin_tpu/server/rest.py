"""JSON REST management API on the service port.

Reference parity: ``HTTPSession.cpp:318-732`` — routes at 365-405:
``/api/v1/{login, logout, getserverinfo, getbaseconfig, setbaseconfig,
restart, getrtsplivesessions, getdevicestream, livedevicestream}``, answers
wrapped in the EasyProtocol envelope (``HTTPSession.cpp:655-732``).

A deliberately tiny HTTP/1.1 server (no framework): parse request line +
headers + optional body, route, answer JSON, close or keep-alive.
"""

from __future__ import annotations

import asyncio
import base64
import json
import re
import secrets
import time
from urllib.parse import parse_qs, urlparse

from ..cluster import protocol as ep
from .config import ServerConfig

SERVER_NAME = "easydarwin-tpu/0.1"

#: /api/v1/sessions/<rtsp-session-id>/trace (ids are token_hex, so the
#: route()-level lowercasing is lossless)
_SESSION_TRACE_RE = re.compile(r"^sessions/([0-9a-f]+)/trace$")


class RestApi:
    def __init__(self, config: ServerConfig, app):
        self.config = config
        self.app = app                      # StreamingServer
        self.tokens: set[str] = set()
        # per-process CSRF token for the /admin HTML set form: a
        # cross-site POST rides cached Basic credentials but cannot READ
        # the admin page to learn this value (same-origin policy)
        self._admin_csrf = secrets.token_urlsafe(16)
        self._server: asyncio.AbstractServer | None = None
        self.port: int | None = None
        self.started_at = time.time()
        #: HLS serving health (ISSUE 14): 304 revalidations served and
        #: body sends per egress rung — the regression tests pin the
        #: zero-per-request-copy hot path on these
        self.hls_not_modified = 0
        self.hls_rungs = {"io_uring": 0, "writev": 0, "buffered": 0}

    def _stream_body(self, writer: asyncio.StreamWriter, head: bytes,
                     data) -> str:
        """Write one HLS response through the stream-egress rung ladder
        (io_uring → writev → buffered).  The header rides the transport
        (tiny, flushes immediately); when the transport buffer is empty
        the body goes straight to the socket through the native sender —
        no per-request copy of the segment bytes, no per-chunk Python.
        Any shortfall (EAGAIN, no raw socket, buffered header) hands the
        REMAINDER to the transport, which owns ordering from then on."""
        from .. import native, obs
        tr = writer.transport
        writer.write(head)
        mv = data if isinstance(data, memoryview) else memoryview(data)
        rung = "buffered"
        sent = 0
        try:
            sock = tr.get_extra_info("socket")
        except Exception:
            sock = None
        if (sock is not None and not tr.is_closing()
                and tr.get_write_buffer_size() == 0
                and native.loaded()):
            fd = sock.fileno()
            uring = getattr(self.app, "uring_egress", None)
            if uring is not None and getattr(uring, "active", False):
                rung = "io_uring"
                sent = uring.stream_write(fd, mv)
            else:
                rung = "writev"
                sent = native.stream_write(fd, mv)
            if sent < 0:
                rung, sent = "buffered", 0
        if sent < len(mv):
            # memoryview slice: the transport queues a VIEW of the same
            # immutable bytes — still zero copies of the segment body
            tr.write(mv[sent:])
        self.hls_rungs[rung] = self.hls_rungs.get(rung, 0) + 1
        obs.HLS_SEGMENT_EGRESS_BYTES.inc(len(mv), rung=rung)
        return rung

    #: content types the scrape-compression satellite covers: the
    #: Prometheus exposition and the NDJSON event feeds (big, highly
    #: repetitive, fetched every few seconds by federating scrapers).
    #: HLS bodies must NOT be here (the zero-copy stream-egress path
    #: sends them verbatim) and the pprof endpoint is already gzipped.
    _GZIP_CTYPES = ("text/plain", "application/x-ndjson")
    #: below this a gzip header costs more than it saves
    _GZIP_MIN_BYTES = 256

    def _maybe_gzip(self, headers: dict, status: int, ctype: str,
                    data: bytes) -> tuple[bytes, dict | None]:
        """Compress a /metrics or NDJSON response body when the client
        asked for it (``Accept-Encoding: gzip``).  Returns the (possibly
        compressed) body + the extra response headers; identity when
        compression would not help or does not apply."""
        if (status != 200 or not data or len(data) < self._GZIP_MIN_BYTES
                or not (ctype or "").startswith(self._GZIP_CTYPES)):
            return data, None
        accept = headers.get("accept-encoding", "")
        if "gzip" not in accept.lower():
            return data, None
        import gzip
        # mtime=0: deterministic bytes, so scrape-cost tests can pin size
        packed = gzip.compress(data, 6, mtime=0)
        if len(packed) >= len(data):
            return data, None
        return packed, {"Content-Encoding": "gzip",
                        "Vary": "Accept-Encoding"}

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connection, self.config.bind_ip,
            self.config.service_port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                head = await reader.readuntil(b"\r\n\r\n")
                lines = head.decode("latin-1").split("\r\n")
                try:
                    method, target, _version = lines[0].split(None, 2)
                except ValueError:
                    break
                headers = {}
                for ln in lines[1:]:
                    k, _, v = ln.partition(":")
                    if _:
                        headers[k.strip().lower()] = v.strip()
                body = b""
                clen = int(headers.get("content-length", "0") or 0)
                if clen:
                    body = await reader.readexactly(clen)
                res = await self.route(method, target, headers, body)
                status, payload = res[0], res[1]
                ctype = res[2] if len(res) > 2 else None
                extra = res[3] if len(res) > 3 else None
                data = payload.encode() if isinstance(payload, str) else payload
                if ctype is None:
                    ctype = ("text/html" if data[:2] in (b"<!", b"<h")
                             else "application/json")
                data, enc_hdrs = self._maybe_gzip(headers, status, ctype,
                                                  data)
                extra = {**(extra or {}), **enc_hdrs} if enc_hdrs else extra
                reason = {200: "OK", 304: "Not Modified"}.get(status,
                                                              "Error")
                head = (
                    f"HTTP/1.1 {status} {reason}\r\n"
                    f"Server: {SERVER_NAME}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(data)}\r\n"
                    + "".join(f"{k}: {v}\r\n"
                              for k, v in (extra or {}).items())
                    + "Connection: keep-alive\r\n\r\n").encode()
                if (status == 200 and data
                        and target.split("?")[0].lower()
                        .startswith("/hls/")):
                    # HLS bodies ride the stream-egress rung ladder
                    # (ISSUE 14): header + body written separately so
                    # the segment bytes are never concatenated into a
                    # per-request copy
                    self._stream_body(writer, head, data)
                else:
                    writer.write(head)
                    if data:
                        writer.write(data)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.LimitOverrunError):
            pass
        finally:
            writer.close()

    # ---------------------------------------------------------------- auth
    def _authorized(self, headers: dict, params: dict) -> bool:
        if not self.config.auth_enabled:
            return True
        token = (params.get("token", [None])[0]
                 or headers.get("x-token"))
        if token in self.tokens:
            return True
        auth = headers.get("authorization", "")
        if auth.lower().startswith("basic "):
            try:
                user, _, pw = base64.b64decode(auth[6:]).decode().partition(":")
                return (user == self.config.rest_username
                        and pw == self.config.rest_password)
            except Exception:
                return False
        return False

    # --------------------------------------------------------------- route
    async def route(self, method: str, target: str, headers: dict,
                    body: bytes) -> tuple[int, str]:
        url = urlparse(target)
        path = url.path.rstrip("/").lower()
        params = parse_qs(url.query)
        if path == "/stats":
            return 200, self._webstats_html()
        if path == "/metrics":
            # Prometheus scrape: unauthenticated read-only exposition,
            # same trust level as /stats
            from .. import obs
            return (200, obs.REGISTRY.expose(),
                    "text/plain; version=0.0.4; charset=utf-8")
        if path == "/debug/profile":
            # span-ring flamegraph as a gzipped pprof Profile proto
            # (`go tool pprof http://host:port/debug/profile` /
            # speedscope); aggregation happens at request time, same
            # read-only trust level as /metrics
            from ..obs import build_pprof
            return 200, build_pprof(), "application/octet-stream"
        if path == "/admin":
            if not self._authorized(headers, params):
                return 401, "<h1>401</h1>"
            if method == "POST" and body:
                params = {**params, **parse_qs(body.decode("utf-8",
                                                           "replace"))}
            return self._admin_html(params, method, headers)
        if path.startswith("/hls/") and self.app.hls is not None:
            served = self.app.hls.serve(url.path)
            if served is None:
                return 404, json.dumps({"error": "not found"})
            ctype, data, etag = served
            if etag is not None:
                if headers.get("if-none-match") == etag:
                    # revalidation short-circuit: a player polling the
                    # playlist (or re-fetching an immutable segment)
                    # costs a header round-trip, zero body bytes
                    self.hls_not_modified += 1
                    return 304, b"", ctype, {"ETag": etag}
                return 200, data, ctype, {"ETag": etag}
            return 200, data, ctype
        if not path.startswith("/api/v1/"):
            return 404, json.dumps({"error": "not found"})
        cmd = path[len("/api/v1/"):]
        if "x-token" in headers and "token" not in params:
            params["token"] = [headers["x-token"]]
        if cmd == "login":
            return self._login(params, headers)
        if not self._authorized(headers, params):
            return 401, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_UNAUTHORIZED)
        # per-session trace retrieval: GET /api/v1/sessions/<id>/trace
        # (the flight recorder's REST face; raw JSON, not the envelope,
        # so operators can pipe it straight to jq / a file).  Under
        # cluster mode the document is STITCHED (ISSUE 15): the local
        # hop plus every upstream hop of the stream's relay tree,
        # fetched through the peers' /api/v1/streamtrace endpoints —
        # ``local=1`` skips the stitch (the inter-node fetch uses it).
        m = _SESSION_TRACE_RE.match(cmd)
        if m is not None:
            from . import admin
            status, doc = admin.flight_query(self.app, m.group(1))
            if status == 200 and params.get("local", ["0"])[0] \
                    not in ("1", "true"):
                from ..obs import fleet
                try:
                    doc = await fleet.stitch_trace(self.app, doc)
                except Exception:
                    pass            # the local document still answers
            return status, json.dumps(doc, default=str), "application/json"
        if self.config.auth_enabled and self._mutates(cmd, params) \
                and headers.get("x-token") not in self.tokens:
            # CSRF altitude guard on the STATE CHANGE itself, not just
            # the HTML form: cached Basic creds (or a leaked query-string
            # token) ride any cross-site GET/POST, but a custom header
            # cannot cross origins without a CORS preflight this server
            # never grants.  Mutating commands therefore demand a login
            # token sent via the X-Token HEADER.
            return 403, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_UNAUTHORIZED,
                               body={"Detail":
                                     "mutating API calls need the X-Token "
                                     "header (see /api/v1/login)"})
        fn = getattr(self, f"_cmd_{cmd}", None)
        if fn is None:
            return 404, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_NOT_FOUND)
        return await fn(params, body) if asyncio.iscoroutinefunction(fn) \
            else fn(params, body)

    #: API commands that change server state (everything not a pure read)
    _MUTATING = frozenset((
        "setbaseconfig", "restart", "startrecord", "stoprecord",
        "startpullrelay", "stoppullrelay", "starttranscode",
        "stoptranscode", "starthls", "stophls", "logout"))

    def _mutates(self, cmd: str, params: dict) -> bool:
        if cmd in self._MUTATING:
            return True
        return (cmd == "admin"
                and params.get("command", ["get"])[0].lower() == "set")

    def _login(self, params: dict, headers: dict) -> tuple[int, str]:
        user = params.get("username", [""])[0]
        pw = params.get("password", [""])[0]
        if (self.config.auth_enabled
                and (user != self.config.rest_username
                     or pw != self.config.rest_password)):
            return 401, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_UNAUTHORIZED)
        token = secrets.token_hex(16)
        self.tokens.add(token)
        return 200, ep.ack(ep.MSG_SC_SERVER_INFO_ACK,
                           body={"Token": token})

    def _cmd_logout(self, params: dict, body: bytes) -> tuple[int, str]:
        # route() folds an X-Token header into params["token"], so a
        # header-only logout (the convention the mutation guard demands)
        # revokes that token rather than silently discarding nothing
        token = params.get("token", [""])[0]
        self.tokens.discard(token)
        return 200, ep.ack(ep.MSG_SC_SERVER_INFO_ACK)

    def _cmd_profile(self, params: dict, body: bytes) -> tuple[int, str, str]:
        """GET /api/v1/profile — the phase profiler's live snapshot
        (same document as admin command=top; raw JSON, not the
        envelope, so it pipes straight to jq)."""
        from . import admin
        return (200, json.dumps(admin.profile_snapshot(self.app),
                                default=str), "application/json")

    def _cmd_ledger(self, params: dict,
                    body: bytes) -> tuple[int, str, str]:
        """GET /api/v1/ledger — the wake-loop ledger's live snapshot
        (ISSUE 16): per-work-class wait/service aggregates, deferred
        counts, worst-wait trace correlation, and the cluster tick's
        Redis roundtrip sub-accounting.  Raw JSON (same pipe-to-jq
        convention as /api/v1/profile); ``tools/blame_report.py`` and
        the soak post-mortems read exactly this document."""
        from . import admin
        return (200, json.dumps(admin.ledger_snapshot(self.app),
                                default=str), "application/json")

    def _cmd_audience(self, params: dict,
                      body: bytes) -> tuple[int, str, str]:
        """GET /api/v1/audience — the columnar per-subscriber QoE
        store's drill-down (ISSUE 18): per-stream rollup + worst-N
        subscribers (``?n=`` overrides the default 5).  Raw JSON for
        jq pipelines; the composed soak's viewer-experience gate and
        ``tools/blame_report.py`` read exactly this document."""
        from . import admin
        try:
            n = int(params.get("n", ["5"])[0])
        except ValueError:
            n = 5
        return (200, json.dumps(
            admin.audience_snapshot(self.app, worst_n=max(0, min(n, 100))),
            default=str), "application/json")

    def _cmd_fleet(self, params: dict,
                   body: bytes) -> tuple[int, str, str]:
        """GET /api/v1/fleet — the aggregated cluster topology (ISSUE
        15): every node's latest rollup with liveness/staleness
        verdicts, served from the cluster tick's cache (a read never
        waits on Redis).  Standalone servers answer a single-node
        fleet of the same shape.  Raw JSON for jq pipelines."""
        from ..obs import fleet
        return (200, json.dumps(fleet.fleet_snapshot(self.app),
                                default=str), "application/json")

    def _cmd_streamtrace(self, params: dict,
                         body: bytes) -> tuple[int, str, str] | tuple[int, str]:
        """GET /api/v1/streamtrace?path= — this node's single hop of a
        stream's stitched trace (trace id, lineage, freshness chain,
        trace-tagged spans/events, the upstream node when pulled).
        This is the inter-node stitching wire the sessions/<id>/trace
        endpoint follows hop by hop."""
        from ..obs import fleet
        path = params.get("path", [""])[0]
        if not path:
            return 400, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_BAD_REQUEST)
        doc = fleet.local_hop_doc(self.app, path)
        status = 404 if doc.get("error") else 200
        return status, json.dumps(doc, default=str), "application/json"

    @staticmethod
    def _page_params(params: dict) -> tuple[int, int | None]:
        """The ONE parser for the event log's (n, since) paging query —
        /api/v1/events and admin command=events must never drift on
        cursor semantics."""
        try:
            n = int(params.get("n", ["256"])[0])
        except ValueError:
            n = 256
        since = None
        try:
            if "since" in params:
                since = int(params["since"][0])
        except ValueError:
            since = None
        return n, since

    def _cmd_events(self, params: dict,
                    body: bytes) -> tuple[int, str, str]:
        """GET /api/v1/events?n=&since= — the structured event log as
        NDJSON.  Every record carries a monotonic per-process ``seq``;
        a federating scraper pages with ``since=<last seq seen>``
        (oldest-first pages, so a scraper far behind catches up through
        the ring) and COUNTS gaps from the seq jumps (plus
        events_dropped_total) instead of silently missing ring
        evictions."""
        from ..obs import EVENTS
        n, since = self._page_params(params)
        lines = EVENTS.dump_lines(n, since)
        return (200, "\n".join(lines) + ("\n" if lines else ""),
                "application/x-ndjson")

    def _cmd_getserverinfo(self, params: dict, body: bytes) -> tuple[int, str]:
        st = self.app.server_info()
        return 200, ep.ack(ep.MSG_SC_SERVER_INFO_ACK, body=st)

    def _cmd_getrtsplivesessions(self, params: dict,
                                 body: bytes) -> tuple[int, str]:
        sessions = self.app.live_sessions()
        return 200, ep.ack(ep.MSG_SC_RTSP_LIVE_SESSIONS_ACK, body={
            "SessionCount": str(len(sessions)), "Sessions": sessions})

    def _cmd_getbaseconfig(self, params: dict, body: bytes) -> tuple[int, str]:
        cfg = {k: v for k, v in self.config.to_dict().items()
               if k != "rest_password"}
        return 200, ep.ack(ep.MSG_SC_BASE_CONFIG_ACK, body={"Config": cfg})

    def _cmd_setbaseconfig(self, params: dict, body: bytes) -> tuple[int, str]:
        try:
            doc = json.loads(body or b"{}")
            changes = doc.get("Config", doc) if isinstance(doc, dict) else {}
            self.config.update(**changes)
        except (json.JSONDecodeError, KeyError, TypeError, ValueError) as e:
            return 400, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_BAD_REQUEST,
                               body={"Detail": str(e)})
        return 200, ep.ack(ep.MSG_SC_BASE_CONFIG_ACK)

    def _cmd_restart(self, params: dict, body: bytes) -> tuple[int, str]:
        self.app.request_restart()
        return 200, ep.ack(ep.MSG_SC_SERVER_INFO_ACK, body={"Restarting": "1"})

    def _cmd_getdevicestream(self, params: dict,
                             body: bytes) -> tuple[int, str]:
        """Start/locate a device stream (cloud mode: asks CMS; standalone:
        answers the local RTSP url if the path is live)."""
        device = params.get("device", params.get("serial", [""]))[0]
        if not device:
            return 400, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_BAD_REQUEST)
        url = self.app.device_stream_url(device)
        if url is None:
            return 404, ep.ack(ep.MSG_SC_EXCEPTION,
                               error=ep.ERR_DEVICE_OFFLINE)
        return 200, ep.ack(ep.MSG_SC_GET_STREAM_ACK, body={"URL": url})

    _cmd_livedevicestream = _cmd_getdevicestream

    def _cmd_startrecord(self, params: dict, body: bytes) -> tuple[int, str]:
        """Attach an MP4 recorder to a live session (RtspRecordModule);
        with the DVR tier on, also arm the window spiller (ISSUE 12) so
        stop leaves BOTH an MP4 and an instantly-servable packed asset."""
        path = params.get("path", [""])[0]
        sess = self.app.registry.find(path) if path else None
        if sess is None:
            return 404, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_NOT_FOUND)
        import os
        from ..utils.paths import confined_subpath
        fname = params.get("file", [""])[0] or (
            sess.path.strip("/").replace("/", "_")
            + time.strftime("_%Y%m%d%H%M%S") + ".mp4")
        root = self.config.movie_folder
        os.makedirs(root, exist_ok=True)
        # confinement is commonpath-over-realpaths (utils/paths), the
        # one test that rejects ALL the escape classes: `..` traversal,
        # a sibling folder sharing the prefix string, and a symlink
        # inside movie_folder pointing outside it
        full = confined_subpath(root, fname)
        if full is None:
            return 400, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_BAD_REQUEST,
                               body={"Detail": "file escapes movie_folder"})
        os.makedirs(os.path.dirname(full), exist_ok=True)
        try:
            self.app.recordings.start(sess, full)
        except ValueError as e:
            return 400, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_BAD_REQUEST,
                               body={"Detail": str(e)})
        dvr_armed = False
        if self.app.dvr is not None:
            sdp = self.app.registry.sdp_cache.get(sess.path) or ""
            dvr_armed = self.app.dvr.arm(sess, sdp) or \
                self.app.dvr.armed(sess.path)
        return 200, ep.ack(ep.MSG_SC_SERVER_INFO_ACK,
                           body={"Recording": sess.path, "File": full,
                                 "Dvr": "1" if dvr_armed else "0"})

    def _cmd_stoprecord(self, params: dict, body: bytes) -> tuple[int, str]:
        path = params.get("path", [""])[0]
        dvr_res = (self.app.dvr.finalize(path)
                   if self.app.dvr is not None else None)
        try:
            res = self.app.recordings.stop(path)
        except KeyError:
            if dvr_res is None:
                return 404, ep.ack(ep.MSG_SC_EXCEPTION,
                                   error=ep.ERR_NOT_FOUND)
            # DVR-only recording (armed at RECORD time, no MP4 sink)
            return 200, ep.ack(ep.MSG_SC_SERVER_INFO_ACK, body={
                "DvrWindows": str(dvr_res["windows"])})
        extra = ({"DvrWindows": str(dvr_res["windows"])}
                 if dvr_res is not None else {})
        return 200, ep.ack(ep.MSG_SC_SERVER_INFO_ACK, body={
            "File": res["path"], "Samples": str(res["samples"]), **extra})

    def _cmd_dvrwindow(self, params: dict,
                       body: bytes) -> tuple[int, object, str] | tuple[int, str]:
        """GET /api/v1/dvrwindow?path=&track=&win= — one spilled window's
        raw blob bytes, exactly as the spill file stores them.  This is
        the cluster peer-fill wire: node B time-shifting a stream node A
        recorded block-fills from A's spill files through here instead
        of hitting origin (the fetch side is ``app._dvr_peer_fetch``)."""
        if self.app.dvr is None:
            return 404, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_NOT_FOUND)
        path = params.get("path", [""])[0]
        try:
            track = int(params.get("track", [""])[0])
            win = int(params.get("win", [""])[0])
        except ValueError:
            return 400, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_BAD_REQUEST)
        blob = self.app.dvr.window_blob(path, track, win)
        if blob is None:
            return 404, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_NOT_FOUND)
        return 200, blob, "application/octet-stream"

    def _cmd_dvrmeta(self, params: dict,
                     body: bytes) -> tuple[int, str] | tuple[int, str, str]:
        """GET /api/v1/dvrmeta?path= — an asset's meta + per-track spill
        index documents (ISSUE 13 satellite).  This is the bootstrap
        half of cluster peer-fill: a node that never saw the stream
        materializes these documents locally (``DvrManager.materialize``)
        and then block-fills every window through ``/api/v1/dvrwindow``
        — a fully-remote ``.dvr`` asset replays anywhere the cluster
        routes a subscriber."""
        if self.app.dvr is None:
            return 404, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_NOT_FOUND)
        path = params.get("path", [""])[0]
        doc = self.app.dvr.meta_doc(path) if path else None
        if doc is None and path \
                and getattr(self.app, "storage", None) is not None:
            # erasure-tier fallback (ISSUE 20): the recording node is
            # gone, but the asset's DVR documents ride every shard
            # manifest — ANY surviving shard holder answers the
            # bootstrap sweep, so a fully-remote asset replays even
            # with its owner dead
            doc = self.app.storage.meta_doc(path)
        if doc is None:
            return 404, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_NOT_FOUND)
        return 200, json.dumps(doc, separators=(",", ":")), \
            "application/json"

    # -- erasure storage wire (ISSUE 20) -----------------------------------
    def _cmd_shard(self, params: dict,
                   body: bytes) -> tuple[int, object, str] | tuple[int, str]:
        """GET /api/v1/shard?path=&name= — one local erasure shard's
        payload (crc-verified against the manifest before it ships; a
        corrupt local copy 404s and self-queues repair)."""
        st = getattr(self.app, "storage", None)
        if st is None:
            return 404, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_NOT_FOUND)
        path = params.get("path", [""])[0]
        name = params.get("name", [""])[0]
        if not path or not name:
            return 400, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_BAD_REQUEST)
        payload = st.serve_shard(path, name)
        if payload is None:
            return 404, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_NOT_FOUND)
        return 200, payload, "application/octet-stream"

    def _cmd_shardmeta(self, params: dict,
                       body: bytes) -> tuple[int, str] | tuple[int, str, str]:
        """GET /api/v1/shardmeta?path= — the asset's shard manifest
        (stripe geometry, per-shard crc32s, holder map, embedded DVR
        documents)."""
        st = getattr(self.app, "storage", None)
        if st is None:
            return 404, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_NOT_FOUND)
        path = params.get("path", [""])[0]
        man = st.manifest(path) if path else None
        if man is None:
            return 404, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_NOT_FOUND)
        return 200, json.dumps(man, separators=(",", ":")), \
            "application/json"

    def _cmd_shardpush(self, params: dict,
                       body: bytes) -> tuple[int, str]:
        """POST /api/v1/shardpush?path=&name= — a peer placing one shard
        here at store/repair time; the body is ``manifest-json\\n\\n``
        followed by the raw payload.  Not in _MUTATING: the push rides
        Basic auth like every peer call, and the payload is fenced by
        the manifest crc32 — a corrupt or cross-gen push is refused, so
        the CSRF login-token dance would only couple node bring-up
        order."""
        st = getattr(self.app, "storage", None)
        if st is None:
            return 404, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_NOT_FOUND)
        path = params.get("path", [""])[0]
        name = params.get("name", [""])[0]
        sep = body.find(b"\n\n")
        if not path or not name or sep < 0:
            return 400, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_BAD_REQUEST)
        try:
            man = json.loads(body[:sep]) if sep > 0 else None
        except ValueError:
            return 400, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_BAD_REQUEST)
        if not st.receive_shard(path, name, body[sep + 2:], man):
            return 400, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_BAD_REQUEST,
                               body={"Detail": "shard refused (crc/gen)"})
        return 200, ep.ack(ep.MSG_SC_SERVER_INFO_ACK, body={
            "Shard": name})

    def _cmd_storagestats(self, params: dict,
                          body: bytes) -> tuple[int, str, str]:
        """GET /api/v1/storagestats — the storage tier's counters plus
        the zero-repack witness (``vod.cache.pack_window.calls``): the
        cluster soak reads this on every survivor after the holder
        kill to assert shards reconstructed with no repacketization
        and no scrub errors."""
        from ..vod.cache import pack_window
        st = getattr(self.app, "storage", None)
        doc: dict = {"enabled": st is not None,
                     "pack_window_calls": int(pack_window.calls)}
        if st is not None:
            doc.update(st.stats())
        return 200, json.dumps(doc, separators=(",", ":")), \
            "application/json"

    async def _cmd_startpullrelay(self, params: dict,
                                  body: bytes) -> tuple[int, str]:
        """Pull a remote rtsp:// stream into a local path (EasyRelaySession
        direction: server chains act as players toward upstreams)."""
        from ..relay.pull import PullError
        url = params.get("url", [""])[0]
        path = params.get("path", [""])[0]
        if not url or not path:
            return 400, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_BAD_REQUEST,
                               body={"Detail": "need url= and path="})
        try:
            pull = await self.app.pulls.start_pull(path, url)
        except PullError as e:
            return 502, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_BAD_REQUEST,
                               body={"Detail": str(e)})
        return 200, ep.ack(ep.MSG_SC_SERVER_INFO_ACK, body={
            "Pull": pull.local_path, "Url": pull.url})

    async def _cmd_stoppullrelay(self, params: dict,
                                 body: bytes) -> tuple[int, str]:
        path = params.get("path", [""])[0]
        try:
            st = await self.app.pulls.stop_pull(path)
        except KeyError:
            return 404, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_NOT_FOUND)
        return 200, ep.ack(ep.MSG_SC_SERVER_INFO_ACK, body={
            "Pull": st["path"], "Packets": str(st["packets"])})

    def _cmd_getpullrelays(self, params: dict, body: bytes) -> tuple[int, str]:
        return 200, ep.ack(ep.MSG_SC_SERVER_INFO_ACK, body={
            "Pulls": self.app.pulls.list_pulls()})

    def _cmd_starttranscode(self, params: dict,
                            body: bytes) -> tuple[int, str]:
        """Start an on-TPU MJPEG bitrate ladder on a live path; the rungs
        appear as {path}@q{Q} live streams."""
        path = params.get("path", [""])[0]
        rungs = tuple(q for q in
                      params.get("rungs", ["40,20"])[0].split(",") if q)
        try:
            out = self.app.transcodes.start(path, rungs)
        except KeyError:
            return 404, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_NOT_FOUND)
        except ValueError as e:
            return 400, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_BAD_REQUEST,
                               body={"Detail": str(e)})
        return 200, ep.ack(ep.MSG_SC_SERVER_INFO_ACK, body={
            "Transcode": out.source_path,
            "Rungs": [r.session.path for r in out.rungs]})

    def _cmd_stoptranscode(self, params: dict,
                           body: bytes) -> tuple[int, str]:
        path = params.get("path", [""])[0]
        try:
            st = self.app.transcodes.stop(path)
        except KeyError:
            return 404, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_NOT_FOUND)
        return 200, ep.ack(ep.MSG_SC_SERVER_INFO_ACK, body={
            "Transcode": st["path"], "FramesIn": str(st["frames_in"])})

    def _cmd_gettranscodes(self, params: dict,
                           body: bytes) -> tuple[int, str]:
        return 200, ep.ack(ep.MSG_SC_SERVER_INFO_ACK, body={
            "Transcodes": self.app.transcodes.list_ladders()})

    def _cmd_starthls(self, params: dict, body: bytes) -> tuple[int, str]:
        """Publish a live path over HLS with a temporal rendition ladder
        (config-5 mux): one call → multi-rendition master.m3u8."""
        from ..hls.segmenter import DEFAULT_RUNGS
        from ..protocol.sdp import _norm
        path = params.get("path", [""])[0]
        rungs_raw = params.get("rungs", [""])[0]
        try:
            rungs = (tuple(r if r.startswith("q") else int(r)
                           for r in rungs_raw.split(",") if r)
                     if rungs_raw else DEFAULT_RUNGS)
            self.app.hls.start(path, rungs)
        except KeyError:
            return 404, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_NOT_FOUND)
        except ValueError as e:
            return 400, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_BAD_REQUEST,
                               error_string=str(e))
        key = _norm(path)
        return 200, ep.ack(ep.MSG_SC_SERVER_INFO_ACK, body={
            "Master": f"/hls{key}/master.m3u8",
            "Renditions": ["index.m3u8"]
            + [(f"{r}/index.m3u8" if isinstance(r, str)
                else f"r{int(r)}/index.m3u8") for r in rungs]})

    def _cmd_stophls(self, params: dict, body: bytes) -> tuple[int, str]:
        from ..protocol.sdp import _norm
        path = params.get("path", [""])[0]
        key = _norm(path)
        if key not in self.app.hls.outputs:
            return 404, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_NOT_FOUND)
        self.app.hls.stop(path)
        return 200, ep.ack(ep.MSG_SC_SERVER_INFO_ACK, body={"Hls": key})

    def _cmd_gethlsstreams(self, params: dict,
                           body: bytes) -> tuple[int, str]:
        return 200, ep.ack(ep.MSG_SC_SERVER_INFO_ACK, body={
            "Streams": self.app.hls.list_streams()})

    def _cmd_admin(self, params: dict, body: bytes) -> tuple[int, str]:
        """Dictionary-tree browse (QTSSAdminModule's /modules/admin API):
        ``?path=server/prefs/*&command=get[&recurse=1]`` or
        ``?path=server/prefs/<name>&command=set&value=...``."""
        from . import admin
        path = params.get("path", ["server/*"])[0]
        command = params.get("command", ["get"])[0].lower()
        if command == "trace":
            # span-ring dump: the raw Chrome trace-event document (NOT
            # envelope-wrapped) so chrome://tracing / Perfetto load the
            # response body directly
            from ..obs import TRACER
            return 200, json.dumps(TRACER.dump()), "application/json"
        if command == "flight":
            # per-session black box (live ring or stored dump) — raw
            # JSON for the same pipe-to-jq reason as command=trace
            status, doc = admin.flight_query(
                self.app, params.get("session", [""])[0])
            return status, json.dumps(doc, default=str), "application/json"
        if command == "events":
            # structured event log as JSON lines; since=<seq> pages
            # from a cursor exactly like /api/v1/events (one parser)
            from ..obs import EVENTS
            n, since = self._page_params(params)
            lines = EVENTS.dump_lines(n, since)
            return (200, "\n".join(lines) + ("\n" if lines else ""),
                    "application/x-ndjson")
        if command == "fleet":
            # aggregated cluster topology (ISSUE 15) — raw JSON for the
            # same pipe-to-jq reason as command=trace
            from ..obs import fleet
            return (200, json.dumps(fleet.fleet_snapshot(self.app),
                                    default=str), "application/json")
        if command == "top":
            # live phase/session attribution snapshot (raw JSON for the
            # same pipe-to-jq reason as command=trace)
            return (200, json.dumps(admin.profile_snapshot(self.app),
                                    default=str), "application/json")
        if command == "blame":
            # the wake ledger's "why is p99 high" decomposition (ISSUE
            # 16): per-class wait/service attribution ranked by blame,
            # with cross-node suspect flags — raw JSON for jq
            return (200, json.dumps(admin.blame_snapshot(self.app),
                                    default=str), "application/json")
        if command == "audience":
            # the audience observatory's per-subscriber QoE drill-down
            # (ISSUE 18) — raw JSON for the same pipe-to-jq reason;
            # honors the same ?n= worst-N clamp as /api/v1/audience
            return self._cmd_audience(params, b"")
        if command == "set":
            status, payload = admin.set_pref(
                self.app, path, params.get("value", [""])[0])
        elif command == "get":
            recurse = params.get("recurse", ["0"])[0] in ("1", "true")
            status, payload = admin.query(self.app, path, recurse=recurse)
        else:
            return 400, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_BAD_REQUEST,
                               body={"Detail": f"unknown command {command}"})
        if status != 200:
            return status, ep.ack(ep.MSG_SC_EXCEPTION, error=ep.ERR_NOT_FOUND
                                  if status == 404 else ep.ERR_BAD_REQUEST,
                                  body=payload)
        return 200, ep.ack(ep.MSG_SC_SERVER_INFO_ACK,
                           body={"Path": path, "Value": payload})

    def _admin_html(self, params: dict, method: str = "GET",
                    headers: dict | None = None) -> tuple[int, str, str]:
        """HTML front-end over the admin dictionary tree — the mongoose
        web-admin role (``QTSSAdminModule.cpp:365`` served HTML over the
        same get/set query API): navigable containers, leaf values, and
        an inline set form for ``server/prefs/*``."""
        import html as _html
        from urllib.parse import quote

        from . import admin
        path = params.get("path", ["server/*"])[0]
        msg = ""
        if params.get("command", [""])[0].lower() == "set":
            if method != "POST":
                # a state-changing set must not ride an idempotent GET
                # (link prefetchers, refresh, cross-site <img> CSRF)
                msg = "<p class=err>set requires POST</p>"
            elif (not secrets.compare_digest(
                        params.get("csrf", [""])[0].encode("utf-8"),
                        self._admin_csrf.encode("ascii"))
                    and (headers or {}).get("x-token") not in self.tokens):
                # bytes, not str: compare_digest raises on non-ASCII str
                # input, and the csrf field is attacker-supplied
                # cross-site form POSTs ride cached Basic creds; demand
                # proof the caller read this page (embedded token) or
                # holds an API token sent via a header a form can't set
                msg = "<p class=err>set requires the page CSRF token</p>"
            else:
                st, payload = admin.set_pref(self.app, path.rstrip("/*"),
                                             params.get("value", [""])[0])
                msg = ("<p class=ok>set ok</p>" if st == 200 else
                       f"<p class=err>{_html.escape(str(payload))}</p>")
            path = "server/prefs/*"
        status, payload = admin.query(self.app, path)
        crumbs = []
        acc = []
        for part in [p for p in path.strip("/").split("/") if p != "*"]:
            acc.append(part)
            href = quote("/".join(acc), safe="/") + "/*"
            crumbs.append(f'<a href="/admin?path={quote(href, safe="/*")}"'
                          f">{_html.escape(part)}</a>")
        rows = []
        if status != 200:
            rows.append(f"<tr><td colspan=2 class=err>"
                        f"{_html.escape(str(payload))}</td></tr>")
        elif isinstance(payload, dict):
            base = path.strip("/").rstrip("*").rstrip("/")
            for k in sorted(payload):
                v = payload[k]
                if isinstance(v, dict) or v == "*container*":
                    href = quote(f"{base}/{k}", safe="/") + "/*"
                    rows.append(
                        f'<tr><td><a href="/admin?path='
                        f'{quote(href, safe="/*")}">'
                        f"{_html.escape(str(k))}/</a></td><td></td></tr>")
                else:
                    cell = _html.escape(str(v))
                    if base == "server/prefs":
                        cell += (f'<form method=post action=/admin '
                                 f'style="display:inline">'
                                 f'<input type=hidden name=path value='
                                 f'"server/prefs/{_html.escape(str(k))}">'
                                 f'<input type=hidden name=command '
                                 f'value=set>'
                                 f'<input type=hidden name=csrf value='
                                 f'"{self._admin_csrf}">'
                                 f'<input name=value size=12> '
                                 f'<input type=submit value=set></form>')
                    rows.append(f"<tr><td>{_html.escape(str(k))}</td>"
                                f"<td>{cell}</td></tr>")
        else:
            rows.append(f"<tr><td>{_html.escape(path)}</td>"
                        f"<td>{_html.escape(str(payload))}</td></tr>")
        body = ("<!doctype html><html><head><title>easydarwin-tpu admin"
                "</title><style>body{font-family:monospace;margin:2em}"
                "table{border-collapse:collapse}td{border:1px solid #ccc;"
                "padding:2px 8px}.err{color:#b00}.ok{color:#080}"
                "</style></head><body>"
                f"<h2><a href=\"/admin?path=server/*\">admin</a> "
                f"{' / '.join(crumbs)}</h2>{msg}"
                f"<table>{''.join(rows)}</table>"
                "<p><a href=/stats>stats</a></p></body></html>")
        return 200, body, "text/html"

    def _webstats_html(self) -> str:
        """HTML stats page (QTSSWebStatsModule.cpp:86-992 equivalent,
        served from the service port instead of RTSP-port HTTP GET)."""
        info = self.app.server_info()
        sessions = self.app.live_sessions()
        rows = "".join(
            f"<tr><td>{s['Path']}</td><td>{s['Outputs']}</td>"
            f"<td>{s['AgeSec']}s</td><td><code>{s['Url']}</code></td></tr>"
            for s in sessions)
        infos = "".join(f"<tr><td>{k}</td><td>{v}</td></tr>"
                        for k, v in info.items())
        return (
            "<!doctype html><html><head><title>easydarwin-tpu stats"
            "</title><style>body{font-family:monospace;margin:2em}"
            "table{border-collapse:collapse;margin:1em 0}"
            "td,th{border:1px solid #999;padding:4px 10px}</style></head>"
            f"<body><h1>easydarwin-tpu</h1><h2>Server</h2>"
            f"<table>{infos}</table>"
            f"<h2>Live sessions ({len(sessions)})</h2>"
            f"<table><tr><th>Path</th><th>Outputs</th><th>Age</th>"
            f"<th>URL</th></tr>{rows}</table></body></html>")
