"""MP3 / Shoutcast-style icy HTTP streaming.

Reference parity: ``QTSSMP3StreamingModule.cpp`` (2.9K LoC): HTTP GET of an
.mp3 path on the RTSP port answers an icy (Shoutcast) stream — paced at the
file's bitrate, with ``icy-metaint`` StreamTitle metadata blocks when the
client sent ``Icy-MetaData: 1``.  StreamTitle comes from the file's ID3v2
TIT2/TPE1 frames when present (``Artist - Title``, the module's
ParseId3Tags role); a GET of a directory (or ``<dir>.m3u``) answers an
``audio/x-mpegurl`` listing of its .mp3 files — the playlist-brokering
half of the module.
"""

from __future__ import annotations

import asyncio
import os

#: MPEG1 Layer III bitrate table (kbps), index 1..14
_BITRATES = (0, 32, 40, 48, 56, 64, 80, 96, 112, 128, 160, 192, 224, 256,
             320, 0)
_SAMPLE_RATES = (44100, 48000, 32000, 0)

META_INT = 8192


def parse_id3_title(data: bytes) -> str | None:
    """ID3v2.3/2.4 TIT2 (+TPE1) → ``Artist - Title`` (None = no tag).

    Handles the common encodings (latin-1, utf-16 w/BOM, utf-8) and
    syncsafe v2.4 frame sizes; anything malformed degrades to None and
    the caller falls back to the filename."""
    if len(data) < 10 or data[:3] != b"ID3":
        return None
    ver = data[3]
    tag_size = ((data[6] & 0x7F) << 21) | ((data[7] & 0x7F) << 14) | \
        ((data[8] & 0x7F) << 7) | (data[9] & 0x7F)
    end = min(10 + tag_size, len(data))
    pos = 10
    fields: dict[str, str] = {}
    while pos + 10 <= end:
        fid = data[pos:pos + 4]
        if not fid.strip(b"\x00"):
            break
        raw = data[pos + 4:pos + 8]
        if ver >= 4:                     # v2.4: syncsafe frame size
            fsize = ((raw[0] & 0x7F) << 21) | ((raw[1] & 0x7F) << 14) | \
                ((raw[2] & 0x7F) << 7) | (raw[3] & 0x7F)
        else:
            fsize = int.from_bytes(raw, "big")
        body = data[pos + 10:pos + 10 + fsize]
        pos += 10 + fsize
        if fid not in (b"TIT2", b"TPE1") or not body:
            continue
        enc, text = body[0], body[1:]
        try:
            if enc == 0:
                val = text.decode("latin-1")
            elif enc == 1:
                val = text.decode("utf-16")
            elif enc == 2:
                val = text.decode("utf-16-be")
            else:
                val = text.decode("utf-8")
        except UnicodeDecodeError:
            continue
        fields[fid.decode()] = val.rstrip("\x00").strip()
    title = fields.get("TIT2")
    if not title:
        return None
    artist = fields.get("TPE1")
    return f"{artist} - {title}" if artist else title


def parse_mp3_bitrate(data: bytes) -> int:
    """Find the first MPEG1-L3 frame header; returns kbps (default 128)."""
    for i in range(len(data) - 4):
        b0, b1, b2 = data[i], data[i + 1], data[i + 2]
        if b0 == 0xFF and (b1 & 0xE0) == 0xE0:
            version = (b1 >> 3) & 0x03
            layer = (b1 >> 1) & 0x03
            if version == 3 and layer == 1:          # MPEG1 Layer III
                bi = (b2 >> 4) & 0x0F
                sr = _SAMPLE_RATES[(b2 >> 2) & 0x03]
                if 0 < bi < 15 and sr:
                    return _BITRATES[bi]
    return 128


class Mp3Service:
    def __init__(self, movie_folder: str):
        self.movie_folder = movie_folder
        self.streams_served = 0

    def playlist(self, path: str) -> str | None:
        """``/dir`` or ``/dir.m3u`` → an m3u listing of the directory's
        .mp3 files (the module's playlist-brokering half); None = not a
        listable directory."""
        rel = path.lstrip("/")
        if rel.lower().endswith(".m3u"):
            rel = rel[:-4]
        cand = os.path.normpath(os.path.join(self.movie_folder, rel))
        root = os.path.normpath(self.movie_folder)
        # commonpath-over-realpaths containment (utils/paths, the same
        # guard VodService.resolve uses): also catches symlinks inside
        # the root pointing outside it, which prefix checks cannot
        from ..utils.paths import under_root
        if not os.path.isdir(cand) or not under_root(self.movie_folder,
                                                     cand):
            return None
        names = sorted(n for n in os.listdir(cand)
                       if n.lower().endswith(".mp3"))
        base = "/" + os.path.relpath(cand, root).replace(os.sep, "/")
        if base == "/.":
            base = ""
        lines = ["#EXTM3U"]
        for n in names:
            with open(os.path.join(cand, n), "rb") as f:
                title = parse_id3_title(f.read(128 * 1024)) \
                    or os.path.splitext(n)[0]
            lines.append(f"#EXTINF:-1,{title}")
            lines.append(f"{base}/{n}")
        return "\n".join(lines) + "\n"

    def resolve(self, path: str) -> str | None:
        if not path.lower().endswith(".mp3"):
            return None
        cand = os.path.normpath(
            os.path.join(self.movie_folder, path.lstrip("/")))
        from ..utils.paths import under_root
        if not os.path.isfile(cand) \
                or not under_root(self.movie_folder, cand):
            return None
        return cand

    async def stream(self, writer: asyncio.StreamWriter, path: str,
                     headers: dict, *, loop: bool = False,
                     pace: bool = True) -> None:
        """Write the icy response + paced MP3 bytes until EOF/disconnect."""
        fp = self.resolve(path)
        if fp is None:
            writer.write(b"HTTP/1.0 404 Not Found\r\n\r\n")
            return
        want_meta = headers.get("icy-metadata", "0").strip() == "1"
        with open(fp, "rb") as probe:
            head_bytes = probe.read(128 * 1024)
        title = parse_id3_title(head_bytes) \
            or os.path.splitext(os.path.basename(fp))[0]
        head = ["ICY 200 OK", "icy-name: easydarwin-tpu",
                "Content-Type: audio/mpeg", "icy-pub: 0"]
        if want_meta:
            head.append(f"icy-metaint:{META_INT}")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode())
        self.streams_served += 1

        with open(fp, "rb") as f:
            first = f.read(4096)
            kbps = parse_mp3_bitrate(first)
            f.seek(0)
            bytes_per_sec = kbps * 1000 // 8
            meta = _meta_block(title) if want_meta else b""
            sent_since_meta = 0
            while True:
                chunk = f.read(4096)
                if not chunk:
                    if loop:
                        f.seek(0)
                        continue
                    break
                if want_meta:
                    out = bytearray()
                    for b in chunk:
                        out.append(b)
                        sent_since_meta += 1
                        if sent_since_meta == META_INT:
                            out += meta
                            sent_since_meta = 0
                    writer.write(bytes(out))
                else:
                    writer.write(chunk)
                try:
                    await writer.drain()
                except ConnectionError:
                    return
                if pace:
                    await asyncio.sleep(len(chunk) / bytes_per_sec)


def _meta_block(title: str) -> bytes:
    text = f"StreamTitle='{title}';".encode()
    pad = (-len(text)) % 16
    blocks = (len(text) + pad) // 16
    return bytes((blocks,)) + text + b"\x00" * pad
