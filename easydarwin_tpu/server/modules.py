"""Module/role system — the QTSS plugin architecture, re-designed.

Reference parity: ``APIStubLib/QTSS.h`` roles + ``QTSSModule`` registration
+ ``QTSServer::BuildModuleRoleArrays`` dispatch (``QTSServer.cpp:285``).
The reference's reflective attribute dictionaries (``QTSSDictionary``) exist
to let C plugins poke server state without headers; in Python the natural
equivalent is plain objects + typed hook points, so the role pipeline is
kept and the dictionary indirection is dropped.

Roles (named after their QTSS counterparts):

* ``initialize(server)`` / ``shutdown(server)``
* ``reread_prefs(config)``                    — QTSS_RereadPrefs_Role
* ``rtsp_filter(conn, req) -> RtspResponse|None``   — QTSS_RTSPFilter_Role:
  may answer the request outright (used by web-stats-style modules)
* ``rtsp_route(conn, req) -> None``           — QTSS_RTSPRoute_Role
* ``authorize(conn, req) -> bool|None``       — QTSS_RTSPAuthorize_Role:
  False forbids, True allows, None = no opinion
* ``rtsp_postprocess(conn, req, resp)``       — QTSS_RTSPPostProcessor_Role
* ``session_closing(conn)``                   — QTSS_ClientSessionClosing_Role
* ``incoming_rtp(session, track_id, packet)`` — QTSS_RTSPIncomingData_Role

Modules are registered in priority order; filter/authorize short-circuit
like the reference's role arrays.
"""

from __future__ import annotations

from ..protocol import rtsp


class Module:
    """Subclass and override the roles you register for."""

    name = "module"

    def attributes(self) -> dict:
        """Module-added attributes for the admin dictionary tree — the
        static half of the QTSS dictionary system
        (``QTSS_AddStaticAttribute``; modules exposed live counters and
        state through it, browseable under ``modules/<name>``).  Return
        a flat or nested dict of JSON-able values."""
        return {}

    def add_instance_attr(self, name: str, getter, *, type: str = "str",
                          writable: bool = False, setter=None) -> int:
        """The ``QTSS_AddInstanceAttribute`` analogue: attach a typed
        attribute to THIS module instance at runtime.  It appears in
        the admin tree under ``modules/<name>/instance_attrs`` on the
        next query, with get/set-by-id via the reflective store."""
        from .dictionary import AttrStore
        if not hasattr(self, "attr_store"):
            self.attr_store = AttrStore(f"module:{self.name}")
        return self.attr_store.add_instance_attr(
            name, getter, type=type, writable=writable, setter=setter)

    def initialize(self, server) -> None:
        pass

    def shutdown(self, server) -> None:
        pass

    def reread_prefs(self, config) -> None:
        pass

    def rtsp_filter(self, conn, req: rtsp.RtspRequest):
        return None

    def rtsp_route(self, conn, req: rtsp.RtspRequest) -> None:
        return None

    def authorize(self, conn, req: rtsp.RtspRequest):
        return None

    def rtsp_postprocess(self, conn, req: rtsp.RtspRequest,
                         resp: rtsp.RtspResponse) -> None:
        return None

    def session_closing(self, conn) -> None:
        return None

    def incoming_rtp(self, session, track_id: int, packet: bytes) -> None:
        return None


class ModuleRegistry:
    def __init__(self):
        self.modules: list[Module] = []

    def register(self, module: Module) -> None:
        self.modules.append(module)

    def unregister(self, module: Module) -> None:
        if module in self.modules:
            self.modules.remove(module)

    # -- dispatch (role arrays) -------------------------------------------
    def run_initialize(self, server) -> None:
        for m in self.modules:
            m.initialize(server)

    def run_shutdown(self, server) -> None:
        for m in self.modules:
            m.shutdown(server)

    def run_reread_prefs(self, config) -> None:
        for m in self.modules:
            m.reread_prefs(config)

    def run_filter(self, conn, req):
        """First module answering wins (QTSSModule kRTSPFilter semantics)."""
        for m in self.modules:
            resp = m.rtsp_filter(conn, req)
            if resp is not None:
                return resp
        return None

    def run_route(self, conn, req) -> None:
        for m in self.modules:
            m.rtsp_route(conn, req)

    def run_authorize(self, conn, req) -> bool:
        """False if any module forbids (all abstaining → allowed)."""
        for m in self.modules:
            v = m.authorize(conn, req)
            if v is False:
                return False
            if v is True:
                return True
        return True

    def run_postprocess(self, conn, req, resp) -> None:
        for m in self.modules:
            m.rtsp_postprocess(conn, req, resp)

    def run_session_closing(self, conn) -> None:
        for m in self.modules:
            m.session_closing(conn)

    def run_incoming_rtp(self, session, track_id, packet) -> None:
        for m in self.modules:
            m.incoming_rtp(session, track_id, packet)


# -- dynamic loading (QTSServer::LoadModules / OSCodeFragment parity) --------

def load_modules_from(folder: str, *, on_error=None) -> list[Module]:
    """Scan ``folder`` for ``*.py`` plugin files and instantiate their
    modules, the way ``QTSServer::LoadModules`` (``QTSServer.cpp:283``)
    dlopens every file in ``module_folder`` via ``OSCodeFragment``.

    A plugin file may provide, in order of precedence:

    * ``EDTPU_MODULES`` — a list of ``Module`` instances or classes;
    * ``register() -> Module | list[Module]`` — a factory;
    * top-level ``Module`` subclasses (each is instantiated).

    A broken plugin is skipped (the reference logs and continues too);
    ``on_error(filename, exc)`` observes failures.
    """
    import importlib.util
    import os
    import sys

    loaded: list[Module] = []
    if not folder or not os.path.isdir(folder):
        return loaded
    for fname in sorted(os.listdir(folder)):
        if not fname.endswith(".py") or fname.startswith("_"):
            continue
        path = os.path.join(folder, fname)
        name = "edtpu_plugin_" + fname[:-3]
        try:
            spec = importlib.util.spec_from_file_location(name, path)
            py = importlib.util.module_from_spec(spec)
            sys.modules[name] = py          # importlib recipe: before exec
            try:
                spec.loader.exec_module(py)
            except BaseException:
                sys.modules.pop(name, None)
                raise
            loaded.extend(_modules_in(py))
        except Exception as e:              # plugin bugs must not kill boot
            if on_error is not None:
                on_error(fname, e)
    return loaded


def _modules_in(py) -> list[Module]:
    def inst(x):
        return x() if isinstance(x, type) else x

    if hasattr(py, "EDTPU_MODULES"):
        return [inst(m) for m in py.EDTPU_MODULES]
    if hasattr(py, "register") and callable(py.register):
        out = py.register()
        return [inst(m) for m in (out if isinstance(out, list) else [out])]
    # fallback: leaf Module subclasses *defined in this file* — imported
    # classes and intermediate bases must not be double-registered
    cands = [cls for cls in vars(py).values()
             if isinstance(cls, type) and issubclass(cls, Module)
             and cls is not Module and cls.__module__ == py.__name__]
    return [cls() for cls in cands
            if not any(cls is not o and issubclass(o, cls) for o in cands)]
