"""Reflective typed attribute store — the QTSS dictionary system.

Reference: every server object in the reference is a typed reflective
dictionary (``Server.tproj/QTSSDictionary.cpp:59`` ff.,
``QTSSDictionaryMap``): attributes carry a numeric id, a name, a
declared type and an access flag; modules and the admin module read and
write objects exclusively through get/set-by-id.  That indirection is
what made the reference's admin tree, module API and stats web UI
uniform.

This port keeps the shape but drops the C boilerplate: an
``AttrStore`` holds specs (id, name, type, writable) plus GETTERS into
live object state — values are never copied into the store, so every
read reflects the object as it is now.  ``get``/``set`` accept either
the attribute name or ``@<id>``; sets validate writability and coerce
through the declared type.  ``add_instance_attr`` is the
``QTSS_AddInstanceAttribute`` analogue: modules (or anything else) can
attach new attributes to a live object at runtime, and the admin tree
picks them up on the next query.

The admin tree (``server/admin.py``) and ``/stats`` read through these
stores, which flips SURVEY row 16 from hand-built dicts to the
reference's reflective design.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable


@dataclass(frozen=True)
class AttrSpec:
    attr_id: int
    name: str
    type: str                           # str | int | bool | float | json
    writable: bool = False


_COERCE: dict[str, Callable[[str], Any]] = {
    "int": int,
    "float": float,
    "bool": lambda v: str(v).lower() in ("1", "true", "yes", "on"),
    "str": str,
    "json": lambda v: v,
}


class AttrStore:
    """One object's typed attribute dictionary."""

    def __init__(self, kind: str):
        self.kind = kind                # qtssServerObjectType analogue
        self._specs: dict[int, AttrSpec] = {}
        self._by_name: dict[str, int] = {}
        self._getters: dict[int, Callable[[], Any]] = {}
        self._setters: dict[int, Callable[[Any], None]] = {}
        self._next_id = 0

    # -- registration ------------------------------------------------
    def add_attr(self, name: str, getter: Callable[[], Any], *,
                 type: str = "str", writable: bool = False,
                 setter: Callable[[Any], None] | None = None) -> int:
        """Register an attribute; returns its id (stable for the
        object's lifetime, assigned in registration order like the
        reference's qtssAttrId enums)."""
        if name in self._by_name:
            raise ValueError(f"attribute exists: {name}")
        if type not in _COERCE:
            raise ValueError(f"unknown attr type: {type}")
        if writable and setter is None:
            raise ValueError("writable attribute needs a setter")
        attr_id = self._next_id
        self._next_id += 1
        self._specs[attr_id] = AttrSpec(attr_id, name, type, writable)
        self._by_name[name] = attr_id
        self._getters[attr_id] = getter
        if setter is not None:
            self._setters[attr_id] = setter
        return attr_id

    # the QTSS_AddInstanceAttribute analogue: same mechanics, kept as a
    # separate name so module code reads like the reference API
    add_instance_attr = add_attr

    # -- access ------------------------------------------------------
    def _resolve(self, id_or_name: "int | str") -> int:
        if isinstance(id_or_name, int):
            if id_or_name not in self._specs:
                raise KeyError(f"{self.kind}: no attr id {id_or_name}")
            return id_or_name
        s = str(id_or_name)
        if s.startswith("@"):           # "@3" — set/get-by-id paths
            try:
                return self._resolve(int(s[1:]))
            except ValueError:
                raise KeyError(f"{self.kind}: bad attr ref {s}") from None
        if s not in self._by_name:
            raise KeyError(f"{self.kind}: no attr {s}")
        return self._by_name[s]

    def spec(self, id_or_name: "int | str") -> AttrSpec:
        return self._specs[self._resolve(id_or_name)]

    def get(self, id_or_name: "int | str") -> Any:
        return self._getters[self._resolve(id_or_name)]()

    def set(self, id_or_name: "int | str", value: Any) -> Any:
        """Type-coerced write; refuses read-only attributes (the
        reference returned QTSS_ReadOnly)."""
        attr_id = self._resolve(id_or_name)
        spec = self._specs[attr_id]
        if not spec.writable:
            raise PermissionError(f"{self.kind}.{spec.name} is read-only")
        coerced = _COERCE[spec.type](value) if isinstance(value, str) \
            else value
        self._setters[attr_id](coerced)
        return coerced

    def describe(self) -> list[dict]:
        """Attribute metadata (the admin tree's ?parameters view)."""
        return [{"id": s.attr_id, "name": s.name, "type": s.type,
                 "access": "rw" if s.writable else "r"}
                for s in self._specs.values()]

    def as_dict(self) -> dict[str, Any]:
        out = {}
        for attr_id, spec in self._specs.items():
            try:
                out[spec.name] = self._getters[attr_id]()
            except Exception as e:      # a live getter must not take the
                out[spec.name] = f"(error: {e})"   # whole tree down
        return out


# ---------------------------------------------------------------- factories

def server_store(app) -> AttrStore:
    """qtssServerObjectType: live server attributes (RTSPPort, uptime,
    session counts — the qtssSvr* set the stats module reads)."""
    st = AttrStore("server")
    info = app.server_info                     # live call, not snapshot
    for key in ("ServerName", "Version", "UpTimeSec", "RTSPPort",
                "ServicePort", "Connections", "PushSessions",
                "Requests", "PacketsIn", "TpuFanout"):
        st.add_attr(key, (lambda k=key: info().get(k)))
    return st


def config_store(config) -> AttrStore:
    """qtssPrefsObjectType: every pref writable through the validated
    ``ServerConfig.update`` path (RereadPrefs semantics)."""
    st = AttrStore("prefs")
    for name, value in config.to_dict().items():
        typ = ("bool" if isinstance(value, bool) else
               "int" if isinstance(value, int) else
               "float" if isinstance(value, float) else "str")
        st.add_attr(
            name,
            (lambda n=name: "(redacted)" if n == "rest_password"
             else config.to_dict().get(n)),
            type=typ, writable=True,
            setter=lambda v, n=name: config.update(**{n: v}))
    return st


def session_store(app, sess) -> AttrStore:
    """qtssClientSessionObjectType: one relay session's live state."""
    st = AttrStore("session")
    st.add_attr("Path", lambda: sess.path)
    st.add_attr("Url", lambda: (
        f"rtsp://{app.config.wan_ip}:"
        f"{app.rtsp.port or app.config.rtsp_port}{sess.path}"))
    st.add_attr("Outputs", lambda: sess.num_outputs, type="int")
    st.add_attr("AgeSec", lambda: _age_sec(sess), type="int")
    st.add_attr("Streams", lambda: sess.stats()["streams"], type="json")
    return st


def _age_sec(sess) -> int:
    from ..relay.session import now_ms
    return int((now_ms() - sess.created_ms) // 1000)


def metrics_store() -> AttrStore:
    """Reflective view over the obs metric registry: one attribute per
    registered family, getters read the LIVE family value (counters as
    numbers, histograms as {count,sum,p50,p99}, labelled families as
    name→value maps).  ``server/metrics/<family>`` and ``@<id>`` admin
    queries therefore see exactly what a ``/metrics`` scrape sees."""
    import time as _time

    from .. import obs
    st = AttrStore("metrics")
    last_collect = [0.0]

    def _live(fam):
        # refresh external sources (ed_stats) at most once per 50 ms: an
        # as_dict() tree sweep reads ~26 getters back-to-back and must
        # not re-snapshot the native counters for every one of them
        now = _time.monotonic()
        if now - last_collect[0] > 0.05:
            last_collect[0] = now
            obs.REGISTRY.collect()
        return fam.as_value()

    for fam in obs.REGISTRY.families():
        st.add_attr(fam.name, (lambda f=fam: _live(f)), type="json")
    return st


def stream_store(sess, track_id: int) -> AttrStore:
    """qtssRTPStreamObjectType: per-track live counters (the per-stream
    set the RTPStream dictionary exposed)."""
    st = AttrStore("stream")
    st.add_attr("TrackID", lambda: track_id, type="int")

    def _live(key):
        return sess.stats()["streams"].get(track_id, {}).get(key)

    for key in ("media", "codec", "packets_in", "bytes_in",
                "packets_out", "keyframes", "queue", "oversize_dropped"):
        st.add_attr(key, (lambda k=key: _live(k)), type="json")
    return st
