"""The RTSP session layer: per-connection request pipeline + media wiring.

Reference parity: ``RTSPSession.cpp:216`` (state machine over parsed
requests), ``QTSSReflectorModule.cpp`` request handling (``DoAnnounce`` 898,
``DoDescribe`` 1176, ``DoSetup`` 1597, ``DoPlay`` 1867, teardown), and the
interleaved ingest path ``QTSS_RTSPIncomingData_Role`` → ``ProcessRTPData``
(``QTSSReflectorModule.cpp:604``).  One asyncio task per connection replaces
the Task-thread state machine; WouldBlock backpressure is carried by the
transport write-buffer (see ``transports``).

A connection can be a *player* (DESCRIBE/SETUP/PLAY of a live path or VOD
file), a *pusher* (ANNOUNCE/SETUP mode=record/RECORD — the EasyPusher flow),
or a plain control connection.
"""

from __future__ import annotations

import asyncio
import secrets
import time
from dataclasses import dataclass, field

from ..obs import EVENTS, FLIGHT, TRACER
from ..protocol import rtsp, sdp
from ..relay.session import RelaySession, SessionRegistry, now_ms
from .config import ServerConfig
from .transports import (InterleavedOutput, UdpOutput, UdpPair, UdpPortPool)

SERVER_NAME = "easydarwin-tpu/0.1"
ALLOWED = ("OPTIONS, DESCRIBE, ANNOUNCE, SETUP, PLAY, PAUSE, RECORD, "
           "TEARDOWN, GET_PARAMETER, SET_PARAMETER")


def _extract_track(uri_path: str) -> tuple[str, int | None]:
    """Split '/live/cam1/trackID=2' → ('/live/cam1', 2).

    The track component must be EXACTLY ``track<id>``/``trackID=<id>``/
    ``streamid=<id>`` — a path like ``/live/track5cam`` is a stream
    named track5cam, not track 5 of /live (a parser must not guess;
    VERDICT r3 weak 7)."""
    low = uri_path.lower()
    for marker in ("trackid=", "streamid=", "track"):
        pos = low.rfind("/" + marker)
        if pos >= 0:
            tail = uri_path[pos + 1 + len(marker):]
            if tail.isdigit():
                return uri_path[:pos], int(tail)
    return uri_path, None


@dataclass
class _PlayerTrack:
    track_id: int
    output: object                      # RelayOutput
    udp_pair: UdpPair | None = None


@dataclass
class _PusherTrack:
    track_id: int
    udp_pair: UdpPair | None = None


class RtspConnection:
    """One RTSP TCP connection (player, pusher, or control)."""

    def __init__(self, server: "RtspServer", reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.wire = rtsp.RtspWireReader()
        self.uri = ""
        self.session_id: str | None = None
        self.path: str | None = None
        self.relay: RelaySession | None = None
        self.vod_file = None                 # Mp4File when playing VOD
        self.vod_session = None              # FileSession
        #: the ``<live>.dvr`` asset path when SETUP landed on a spilled
        #: DVR asset (pure replay through the time-shift tier)
        self.dvr_path: str | None = None
        #: per-track absolute resume cursors latched by a PAUSE under an
        #: armed spiller: the next PLAY re-enters the past exactly here
        #: (cleared by a successful resume or an explicit Range seek)
        self.pause_ids: dict[int, int] | None = None
        self.is_pusher = False
        self.playing = False
        self.player_tracks: dict[int, _PlayerTrack] = {}
        self.pusher_tracks: dict[int, _PusherTrack] = {}
        #: interleaved channel → (track_id, is_rtcp) for push ingest
        self.channel_map: dict[int, tuple[int, bool]] = {}
        self.last_activity = time.monotonic()
        self.closed = False
        self.auth_user: str | None = None
        self.user_agent = ""
        self.created_at = time.monotonic()
        peer = writer.get_extra_info("peername") or ("?", 0)
        self.client_ip = peer[0]
        #: ip:port — the admission redirect's edge-spread key: thousands
        #: of viewers behind one CGNAT ip must still fan across edges,
        #: so the spread hashes the full 5-tuple-ish identity, not the ip
        self.client_key = f"{peer[0]}:{peer[1]}"
        #: correlation id threaded through every span/event/flight record
        #: this connection produces (and stamped onto its relay session /
        #: outputs, so engine-pass and native-egress spans carry it too)
        self.trace_id = secrets.token_hex(8)
        #: why this connection died, when not a clean TEARDOWN/EOF —
        #: set by the timeout sweep or the uncaught-exception catch;
        #: non-None at close() triggers the flight-recorder dump
        self.abnormal_reason: str | None = None

    # ------------------------------------------------------------------ io
    async def run(self) -> None:
        try:
            first = await self.reader.read(16384)
            if not first:
                await self.close()
                return
            if first.startswith(b"GET ") or first.startswith(b"POST"):
                # HTTP on the RTSP port: RTSP-over-HTTP tunnel, icy MP3, or
                # the stats page (RTSPSession.cpp:1339-1459 tunnel states;
                # MP3StreamingModule; WebStatsModule RTSP-port GET)
                await self._run_http(first)
                return
            self._feed(first)
            await self._drain_events()
            while not self.closed:
                data = await self.reader.read(16384)
                if not data:
                    break
                self._feed(data)
                await self._drain_events()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except rtsp.RtspError as e:
            self._reply(rtsp.RtspResponse(e.status), cseq=0)
            self.abnormal_reason = f"protocol: {e.status}"
        except Exception as e:
            # crash flight recorder: an uncaught handler exception must
            # leave a black box — including the stack frames asyncio
            # would have printed, or the crash is undiagnosable
            import traceback
            self.abnormal_reason = (f"exception: {type(e).__name__}: "
                                    f"{e}"[:200])
            EVENTS.emit("rtsp.exception", level="error",
                        session_id=self.session_id, stream=self.path,
                        trace_id=self.trace_id,
                        error=f"{type(e).__name__}: {e}"[:200],
                        tb=traceback.format_exc(limit=12)[-2000:])
        finally:
            await self.close()

    def _feed(self, data: bytes) -> None:
        self.last_activity = time.monotonic()
        self.wire.feed(data)

    async def _drain_events(self) -> None:
        for ev in self.wire.events():
            if isinstance(ev, rtsp.InterleavedPacket):
                self._on_interleaved(ev)
            else:
                await self._dispatch(ev)

    # ------------------------------------------------ HTTP on the RTSP port
    async def _run_http(self, first: bytes) -> None:
        buf = bytearray(first)
        while b"\r\n\r\n" not in buf:
            data = await self.reader.read(16384)
            if not data:
                return
            buf += data
        head_end = buf.index(b"\r\n\r\n")
        lines = bytes(buf[:head_end]).decode("latin-1").split("\r\n")
        rest = bytes(buf[head_end + 4:])
        try:
            method, target, _ver = lines[0].split(None, 2)
        except ValueError:
            return
        headers = {}
        for ln in lines[1:]:
            k, sep, v = ln.partition(":")
            if sep:
                headers[k.strip().lower()] = v.strip()
        cookie = headers.get("x-sessioncookie")
        if method == "GET" and cookie:
            await self._tunnel_get(cookie)
        elif method == "POST" and cookie:
            await self._tunnel_post(cookie, rest)
        elif method == "GET":
            await self.server.handle_http_get(self, target, headers)

    async def _tunnel_get(self, cookie: str) -> None:
        """The data half of an RTSP-over-HTTP tunnel: hold the connection,
        answer the tunnel preamble; all RTSP replies/media flow here."""
        self.writer.write(
            b"HTTP/1.0 200 OK\r\nServer: " + SERVER_NAME.encode() +
            b"\r\nConnection: close\r\nCache-Control: no-store\r\n"
            b"Pragma: no-cache\r\n"
            b"Content-Type: application/x-rtsp-tunnelled\r\n\r\n")
        self.server.tunnels[cookie] = self
        try:
            while not self.closed:        # hold open; client sends nothing
                data = await self.reader.read(4096)
                if not data:
                    break
        finally:
            self.server.tunnels.pop(cookie, None)

    async def _tunnel_post(self, cookie: str, initial: bytes) -> None:
        """The command half: base64-encoded RTSP arrives here; decode and
        execute against the GET-side connection (replies go to its writer)."""
        import base64
        target = self.server.tunnels.get(cookie)
        if target is None:
            self.writer.write(b"HTTP/1.0 404 Not Found\r\n\r\n")
            return
        b64 = bytearray()

        async def feed(raw: bytes) -> None:
            b64.extend(c for c in raw if c not in b" \r\n\t")
            n = len(b64) // 4 * 4
            if n:
                decoded = base64.b64decode(bytes(b64[:n]))
                del b64[:n]
                target.wire.feed(decoded)
                await target._drain_events()

        await feed(initial)
        while not self.closed and not target.closed:
            data = await self.reader.read(16384)
            if not data:
                break
            self.last_activity = time.monotonic()
            await feed(data)

    def _reply(self, resp: rtsp.RtspResponse, cseq: int | None = None) -> None:
        resp.headers.setdefault("CSeq", str(cseq) if cseq is not None else "0")
        resp.headers.setdefault("Server", SERVER_NAME)
        if self.session_id:
            resp.headers.setdefault("Session", self.session_id)
        self._last_response = resp
        self.writer.write(resp.to_bytes())

    # ----------------------------------------------------------- dispatch
    def _adopt_peer_trace(self, req: rtsp.RtspRequest) -> None:
        """Cross-node trace propagation (ISSUE 15): a cluster peer's
        pull carries the stream's trace id upstream as ``X-Trace-Id``;
        this connection adopts it so its spans/events/flight box stitch
        into the same multi-hop trace.  Accepted ONLY from cluster
        peers: the request must name a live-leased node in
        ``X-Cluster-Node`` AND arrive from that node's registered lease
        address (node ids are public, so the name alone would be
        forgeable — see app._peer_trace_gate)."""
        from ..utils.client import hexish
        tid = req.headers.get("x-trace-id", "").strip()
        if not tid or tid == self.trace_id:
            return
        gate = getattr(self.server, "peer_trace_gate", None)
        if gate is None or not gate(req.headers.get("x-cluster-node", ""),
                                    self.client_ip):
            return
        if not hexish(tid):
            return
        self.trace_id = tid

    async def _dispatch(self, req: rtsp.RtspRequest) -> None:
        self.server.stats["requests"] += 1
        self._adopt_peer_trace(req)
        handler = getattr(self, f"_do_{req.method.lower()}", None)
        if handler is None:
            self._reply(rtsp.RtspResponse(501), req.cseq)
            return
        if ua := req.headers.get("user-agent"):
            self.user_agent = ua
        if req.uri != "*":
            self.uri = req.uri
        mods = self.server.modules
        # Filter role: a module may answer the request outright
        filtered = mods.run_filter(self, req)
        if filtered is not None:
            self._reply(filtered, req.cseq)
            return
        mods.run_route(self, req)
        auth = self.server.auth
        if (auth is not None
                and req.method in ("DESCRIBE", "SETUP", "ANNOUNCE", "PLAY",
                                   "RECORD")):
            allowed, user = auth.authorize(
                req.path(), req.method, req.headers.get("authorization"))
            if not allowed:
                self._reply(rtsp.RtspResponse(401, {
                    "WWW-Authenticate": auth.challenge()}), req.cseq)
                return
            self.auth_user = user
        if not mods.run_authorize(self, req):
            self._reply(rtsp.RtspResponse(403), req.cseq)
            return
        self._last_response = None
        t0 = TRACER.begin()
        errored = False
        try:
            await handler(req)
        except rtsp.RtspError as e:
            errored = True
            self._reply(rtsp.RtspResponse(e.status), req.cseq)
            EVENTS.emit("rtsp.error", level="warn",
                        session_id=self.session_id, stream=self.path,
                        trace_id=self.trace_id, method=req.method,
                        status=e.status)
        finally:
            TRACER.end(f"rtsp.{req.method.lower()}", t0, cat="rtsp",
                       trace_id=self.trace_id)
        if (not errored and req.method in self._EVENT_METHODS
                and self._last_response is not None):
            EVENTS.emit(f"rtsp.{req.method.lower()}",
                        session_id=self.session_id, stream=self.path,
                        trace_id=self.trace_id,
                        status=self._last_response.status)
        if self._last_response is not None:
            mods.run_postprocess(self, req, self._last_response)

    #: media lifecycle methods that emit a generic status event from the
    #: dispatcher (SETUP emits its richer event inside _do_setup)
    _EVENT_METHODS = frozenset(("ANNOUNCE", "PLAY", "RECORD", "PAUSE",
                                "TEARDOWN"))

    async def _do_options(self, req: rtsp.RtspRequest) -> None:
        self._reply(rtsp.RtspResponse(200, {"Public": ALLOWED}), req.cseq)

    async def _do_get_parameter(self, req: rtsp.RtspRequest) -> None:
        body = (req.body or b"").decode("utf-8", "replace").lower()
        if "x-freshness" in body:
            # the freshness-chain hop transport (ISSUE 15): answer this
            # stream's chain (origin hop first) so a downstream relay-
            # tree edge can append its own stamp — no media-wire change
            import json as json_mod
            from ..protocol.sdp import _norm
            path = self.path or _norm(req.path())
            sess = self.server.registry.find(path)
            if sess is not None:
                from ..obs import fleet
                chain = fleet.freshness_chain(
                    sess, self.server.config.server_id)
                self._reply(rtsp.RtspResponse(
                    200, {"Content-Type": "application/json"},
                    json_mod.dumps(chain).encode()), req.cseq)
                return
        self._reply(rtsp.RtspResponse(200), req.cseq)

    async def _do_set_parameter(self, req: rtsp.RtspRequest) -> None:
        self._reply(rtsp.RtspResponse(200), req.cseq)

    async def _do_describe(self, req: rtsp.RtspRequest) -> None:
        path = req.path()
        text = await self.server.describe(path)
        if text is None:
            self._reply(rtsp.RtspResponse(404), req.cseq)
            return
        self.path = sdp._norm(path)
        extra = {}
        sess = self.server.registry.find(self.path)
        if sess is not None:
            # downstream trace propagation (ISSUE 15): the reply names
            # the stream's trace id so a pulling edge serves its local
            # replica under the SAME id — informational for everyone
            # else (an id grants nothing; acceptance upstream is gated)
            extra["X-Trace-Id"] = sess.trace_id
        self._reply(rtsp.RtspResponse(200, {
            "Content-Type": "application/sdp",
            "Content-Base": req.uri.rstrip("/") + "/",
            **extra,
        }, text.encode()), req.cseq)

    async def _do_announce(self, req: rtsp.RtspRequest) -> None:
        if not req.body:
            raise rtsp.RtspError(400, "ANNOUNCE without SDP")
        path = req.path()
        existing = self.server.registry.find(sdp._norm(path))
        self.relay = self.server.registry.find_or_create(
            path, req.body.decode("utf-8", "replace"))
        self.relay.owner = self         # ANNOUNCE takes ownership (adoption)
        if existing is self.relay:
            # adopting a live session (re-ANNOUNCE after a migration /
            # restart / pull supersede): the STREAM's trace id is minted
            # once and survives feeder changes — the connection adopts
            # it, so a stitched trace spans the handover instead of
            # breaking at it (ISSUE 15 lineage)
            self.trace_id = self.relay.trace_id
        else:
            # fresh session: ownership carries the trace — engine-pass /
            # native-egress spans for this broadcast correlate to THIS
            # pusher connection
            self.relay.set_trace(self.trace_id)
        self.path = self.relay.path
        self.is_pusher = True
        self.server.stats["pushers"] += 1
        self._reply(rtsp.RtspResponse(200), req.cseq)

    # -- SETUP -------------------------------------------------------------
    async def _do_setup(self, req: rtsp.RtspRequest) -> None:
        t = req.transport
        if t is None:
            raise rtsp.RtspError(461)
        base, track_id = _extract_track(req.path())
        if self.session_id is None:
            self.session_id = secrets.token_hex(8)
            FLIGHT.register(self.session_id, trace_id=self.trace_id,
                            client_ip=self.client_ip, path=base)
        mode = "record" if (t.mode == "RECORD" or self.is_pusher) else "play"
        if mode == "record":
            await self._setup_record(req, base, track_id, t)
        else:
            await self._setup_play(req, base, track_id, t)
        EVENTS.emit("rtsp.setup", session_id=self.session_id,
                    stream=self.path or base, trace_id=self.trace_id,
                    status=self._last_response.status
                    if self._last_response else 0,
                    track=track_id, mode=mode)

    async def _setup_record(self, req, base, track_id, t) -> None:
        if self.relay is None:
            raise rtsp.RtspError(455, "SETUP record before ANNOUNCE")
        if track_id is None or track_id not in self.relay.streams:
            raise rtsp.RtspError(404, f"unknown track {track_id}")
        resp_t = rtsp.TransportSpec(protocol=t.protocol, mode="RECORD",
                                    is_tcp=t.is_tcp)
        if t.is_tcp:
            ch = t.interleaved or (2 * (len(self.pusher_tracks)),
                                   2 * len(self.pusher_tracks) + 1)
            self.channel_map[ch[0]] = (track_id, False)
            self.channel_map[ch[1]] = (track_id, True)
            self.pusher_tracks[track_id] = _PusherTrack(track_id)
            resp_t.interleaved = ch
            # receiver reports ride back on the RTCP channel
            # (ReflectorStream.h:341 kRRInterval liveness to the pusher)
            st = self.relay.streams.get(track_id)
            if st is not None:
                st.upstream_rtcp = (
                    lambda d, c=ch[1]: self.send_interleaved(c, d))
                st.upstream_rtcp_owner = self
        else:
            tid = track_id
            from .. import native
            if self.server.config.native_ingest and native.available():
                # recvmmsg batch drain straight into the ring — no
                # per-datagram Python on the push ingest path
                pair = await self.server.udp_pool.allocate_native(
                    on_readable=lambda fd, tid=tid:
                        self._native_rtp_drain(tid, fd),
                    on_rtcp=lambda d, a, tid=tid: self._udp_ingest(
                        tid, d, True, addr=a),
                    uring=getattr(self.server, "uring_ingest_enabled",
                                  False))
            else:
                pair = await self.server.udp_pool.allocate(
                    on_rtp=lambda d, a, tid=tid: self._udp_ingest(
                        tid, d, False),
                    on_rtcp=lambda d, a, tid=tid: self._udp_ingest(
                        tid, d, True, addr=a))
            self.pusher_tracks[track_id] = _PusherTrack(track_id, pair)
            resp_t.server_port = (pair.rtp_port, pair.rtcp_port)
            resp_t.client_port = t.client_port
        self._reply(rtsp.RtspResponse(200, {"Transport": resp_t.to_header()}),
                    req.cseq)

    async def _setup_play(self, req, base, track_id, t) -> None:
        # overload admission (ISSUE 13): past the utilization high-water
        # mark a node sheds NEW subscribers before it burns — 305 to the
        # placement-resolved edge when one has headroom, 453 otherwise.
        # Only the session's FIRST track gates: a half-set-up player
        # must complete or tear down, never strand mid-session.  Plain
        # local-file VOD is exempt: no peer can serve this node's movie
        # folder (live relays migrate, .dvr assets bootstrap — files
        # don't), so a redirect would turn overload into a hard 404.
        adm = self.server.admission
        vod = self.server.vod
        is_dvr = (self.server.dvr is not None
                  and self.server.dvr.is_dvr_path(base))
        local_file = (not is_dvr and vod is not None
                      and vod.resolve(base) is not None)
        if adm is not None and not self.player_tracks and not local_file:
            verdict = adm(base, self.client_key)
            if verdict is not None:
                action, url = verdict
                if action == "redirect" and url:
                    self._reply(rtsp.RtspResponse(
                        305, {"Location": url}), req.cseq)
                else:
                    raise rtsp.RtspError(453)
                return
        dvr = self.server.dvr
        if (dvr is not None and dvr.is_dvr_path(base)
                and self.vod_file is None):
            await self._setup_play_dvr(req, base, track_id, t)
            return
        relay = await self.server.open_for_play(base)
        if relay is None:
            await self._setup_play_vod(req, base, track_id, t)
            return
        self.relay = relay
        self.path = relay.path
        if track_id is None:
            track_id = sorted(set(relay.streams) - set(self.player_tracks))[0] \
                if set(relay.streams) - set(self.player_tracks) else None
        if track_id is None or track_id not in relay.streams:
            raise rtsp.RtspError(404, f"unknown track {track_id}")
        out, resp_t, pair = await self._make_output(t)
        if t.is_tcp:
            self._maybe_readopt_tcp(req, relay.path, track_id, out, resp_t)
        extra = self._negotiate_meta_info(req, out)
        out, rel_extra = self._negotiate_retransmit(req, out, t)
        extra.update(rel_extra)
        extra.update(self._attach_fec(req, out, t))
        self._install_player_track(track_id, out, pair)
        self._reply(rtsp.RtspResponse(200, {
            "Transport": resp_t.to_header(), **extra}), req.cseq)

    def _maybe_readopt_tcp(self, req, path, track_id, out, resp_t) -> None:
        """Checkpoint/migration parity for interleaved TCP (ISSUE 14):
        a player re-connecting after a restart/migration presents its
        old ``Session`` id; if a ``kind=tcp`` checkpoint record matches
        (path, track, session), its set-once rewrite state is adopted —
        same ssrc, framed seq continuing exactly where the dead
        process's wire stopped.  No match = a fresh subscriber (stale
        records age out counted as ``ckpt.tcp_orphan``)."""
        hook = self.server.tcp_restore
        sid = (req.headers.get("session") or "").strip()
        if hook is None or not sid:
            return
        rec = hook(path, track_id, sid)
        if rec is None:
            return
        rw = rec.get("rewrite") or [0, -1, -1, 0, 0]
        out.rewrite.ssrc = int(rw[0])
        out.rewrite.base_src_seq = int(rw[1])
        out.rewrite.base_src_ts = int(rw[2])
        out.rewrite.out_seq_start = int(rw[3])
        out.rewrite.out_ts_start = int(rw[4])
        out.packets_sent = int(rec.get("packets_sent", 0))
        out.bytes_sent = int(rec.get("bytes_sent", 0))
        out.payload_octets = int(rec.get("payload_octets", 0))
        resp_t.ssrc = out.rewrite.ssrc      # Transport echoes the OLD ssrc
        EVENTS.emit("ckpt.tcp_reattach", session_id=self.session_id,
                    stream=path, trace_id=self.trace_id, track=track_id)

    def _negotiate_retransmit(self, req, out, t):
        """Reliable-UDP negotiation: a UDP SETUP carrying
        ``x-Retransmit: our-retransmit[;window=KB]`` gets its output
        wrapped in the resend window and the header echoed back
        (``RTSPRequest::ParseRetransmitHeader`` RTSPRequest.cpp:530-560;
        ``RTPStream::SendSetupResponse`` RTPStream.cpp:616 echo).  TCP
        transports never downgrade (reference: only UDP upgrades)."""
        hdr = req.headers.get("x-retransmit", "")
        if (t.is_tcp or not self.server.config.reliable_udp
                or "our-retransmit" not in hdr.lower()):
            return out, {}
        window_kb = None
        for part in hdr.split(";"):
            k, _, v = part.partition("=")
            if k.strip().lower() == "window":
                try:
                    window_kb = int(v.strip())
                except ValueError:
                    pass
        from ..relay.reliable import ReliableUdpOutput
        return (ReliableUdpOutput(out, window_kb=window_kb),
                {"x-Retransmit": hdr})

    def _attach_fec(self, req, out, t) -> dict:
        """Arm the lossy-WAN reliability tier for one plain-UDP output
        (ISSUE 11): a closed-loop FEC encoder (overhead 0 until the
        subscriber's RRs report loss) + the NACK→RTX replay budget.

        OPT-IN, negotiated like x-Retransmit: the SETUP must carry
        ``x-FEC: parity`` and the grant is echoed back with the parity/
        RTX payload types.  Parity and RTX packets ride the media SSRC
        with their OWN seq spaces, which a non-FEC-aware RFC 3550
        receiver would fold into one per-SSRC seq tracker — garbage
        fraction_lost feeding back into the thinning controller — so
        un-negotiated emission is never allowed.  TCP transports don't
        lose packets; the reliable-UDP wrap owns its subscriber's loss
        already; meta-info wrapping changes the wire format parity
        would have to describe."""
        hdr = req.headers.get("x-fec", "")
        if (not self.server.config.fec_enabled or t.is_tcp
                or "parity" not in hdr.lower()
                or hasattr(out, "resender")
                or out.meta_field_ids is not None):
            return {}
        from ..relay.fec import FecOutputState
        cfg = self.server.config.fec_config()
        out.fec = FecOutputState(cfg)
        return {"x-FEC": f"parity;pt={cfg.payload_type}"
                         f";rtx-pt={cfg.rtx_payload_type}"}

    def _install_player_track(self, track_id, out, pair) -> None:
        """Land a SETUP'd output, releasing any replaced track's transport
        and registering native outputs for RTCP demux only AFTER every
        fallible step succeeded (no leak on a failed SETUP)."""
        egress = self.server.shared_egress
        old = self.player_tracks.get(track_id)
        if old is not None:
            if old.udp_pair:
                old.udp_pair.close()
            elif egress is not None and hasattr(old.output, "rtcp_addr"):
                egress.unregister(old.output, self)
        # correlate this output's retransmit/QoS events back to the
        # player's session (reliable-UDP emits through these)
        out.trace_id = self.trace_id
        out.session_id = self.session_id
        self.player_tracks[track_id] = _PlayerTrack(track_id, out, pair)
        if egress is not None and pair is None and hasattr(out, "rtcp_addr"):
            egress.register(out, self)

    #: x-RTP-Meta-Info fields fillable on the LIVE relay path (tt
    #: transmit-time, sq sequence, md media); VOD adds ft/pn from its
    #: sample tables (META_SUPPORTED_VOD)
    META_SUPPORTED = ("tt", "sq", "md")
    META_SUPPORTED_VOD = ("pp", "tt", "ft", "pn", "sq", "md")

    def _negotiate_meta_info(self, req, out, supported=None) -> dict:
        """DSS QT-client extension: a SETUP carrying ``x-RTP-Meta-Info``
        lists wanted fields; the answer assigns compressed ids and the
        output wraps packets in the meta-info format
        (``RTPMetaInfoLib``; ``RTPStream`` send path)."""
        from ..protocol import rtp_meta
        want = req.headers.get("x-rtp-meta-info", "")
        if not want:
            return {}
        requested = rtp_meta.parse_header(want)
        supported = supported or self.META_SUPPORTED
        granted = {f: i for i, f in enumerate(
            f for f in supported if f in requested)}
        if "md" not in granted:
            return {}                   # md is mandatory for a media stream
        granted["md"] = rtp_meta.UNCOMPRESSED   # md is never compressed
        out.meta_field_ids = granted
        return {"x-RTP-Meta-Info": rtp_meta.build_header(granted)}

    async def _make_output(self, t: rtsp.TransportSpec):
        """Create the egress output for one SETUP'd track (shared between
        live-relay and VOD play paths)."""
        ssrc = secrets.randbits(32)
        seq0 = secrets.randbits(16)
        resp_t = rtsp.TransportSpec(protocol=t.protocol, is_tcp=t.is_tcp)
        resp_t.ssrc = ssrc
        pair = None
        if t.is_tcp:
            ch = t.interleaved or (2 * len(self.player_tracks),
                                   2 * len(self.player_tracks) + 1)
            out = InterleavedOutput(self.writer.transport, ch[0], ch[1],
                                    ssrc=ssrc, out_seq_start=seq0)
            resp_t.interleaved = ch
        else:
            if not t.client_port:
                raise rtsp.RtspError(461, "UDP SETUP without client_port")
            egress = self.server.shared_egress
            if egress is not None and egress.active:
                # shared-pair egress (RTPSocketPool shape): the native
                # batched fan-out path serves this output
                from .egress import NativeUdpOutput
                out = NativeUdpOutput(egress, self.client_ip,
                                      t.client_port[0], t.client_port[1],
                                      ssrc=ssrc, out_seq_start=seq0)
                resp_t.server_port = (egress.rtp_port, egress.rtcp_port)
            else:
                pair = await self.server.udp_pool.allocate(
                    on_rtcp=lambda d, a: self.server.on_client_rtcp(self, d, a))
                out = UdpOutput(pair.rtp_transport, pair.rtcp_transport,
                                self.client_ip, t.client_port[0],
                                t.client_port[1], ssrc=ssrc,
                                out_seq_start=seq0)
                resp_t.server_port = (pair.rtp_port, pair.rtcp_port)
            resp_t.client_port = t.client_port
        return out, resp_t, pair

    async def _setup_play_vod(self, req, base, track_id, t) -> None:
        """SETUP on a file path (QTSSFileModule DoSetup equivalent)."""
        if self.vod_file is None:
            vod = self.server.vod
            f = vod.open(base) if vod is not None else None
            if f is None:
                raise rtsp.RtspError(404)
            self.vod_file = f
            self.path = base
        n_tracks = sum(1 for tr in (self.vod_file.video_track(),
                                    self.vod_file.audio_track())
                       if tr is not None)
        if track_id is None:
            track_id = len(self.player_tracks) + 1
        if not 1 <= track_id <= n_tracks:
            raise rtsp.RtspError(404, f"unknown track {track_id}")
        out, resp_t, pair = await self._make_output(t)
        meta_extra = self._negotiate_meta_info(
            req, out, supported=self.META_SUPPORTED_VOD)
        out, rel_extra = self._negotiate_retransmit(req, out, t)
        # x-FEC is NOT offered on VOD: the NACK handler resolves through
        # conn.relay (None for file sessions) and the cold FileSession
        # never registers with a RelayStream — granting a capability the
        # server cannot honor would leave the client waiting on it
        # (reliable-UDP is the VOD loss story, as in the reference)
        self._install_player_track(track_id, out, pair)
        self._reply(rtsp.RtspResponse(200, {
            "Transport": resp_t.to_header(), **rel_extra, **meta_extra}),
            req.cseq)

    async def _setup_play_dvr(self, req, base, track_id, t) -> None:
        """SETUP on a ``<live>.dvr`` asset path: the spilled per-track
        indexes name the tracks; outputs are ordinary player outputs
        the time-shift session block-fills at PLAY.  x-RTP-Meta-Info
        and x-FEC are not offered here — ft/pn need mp4 sample tables
        and FEC needs a live RelayStream, neither of which a spilled
        asset has (reliable-UDP remains the replay loss story)."""
        dvr = self.server.dvr
        asset = dvr.open_asset(base)
        if asset is None:
            raise rtsp.RtspError(404)
        try:
            track_ids = sorted(asset.tracks)
        finally:
            asset.close()
        if track_id is None:
            avail = [i for i in track_ids if i not in self.player_tracks]
            track_id = avail[0] if avail else None
        if track_id is None or track_id not in track_ids:
            raise rtsp.RtspError(404, f"unknown track {track_id}")
        self.dvr_path = sdp._norm(base)
        self.path = self.dvr_path
        out, resp_t, pair = await self._make_output(t)
        out, rel_extra = self._negotiate_retransmit(req, out, t)
        self._install_player_track(track_id, out, pair)
        self._reply(rtsp.RtspResponse(200, {
            "Transport": resp_t.to_header(), **rel_extra}), req.cseq)

    async def _do_record(self, req: rtsp.RtspRequest) -> None:
        if not self.is_pusher or self.relay is None:
            raise rtsp.RtspError(455)
        self.relay.pusher_alive = True
        if self.server.dvr is not None:
            # dvr_enabled: every pushed broadcast records — completed
            # ring windows spill to the packed-window store from the
            # first full window on (idempotent re-arm on re-RECORD)
            self.server.dvr.arm(
                self.relay,
                self.server.registry.sdp_cache.get(self.relay.path) or "")
        self._reply(rtsp.RtspResponse(200), req.cseq)

    @staticmethod
    def _range_npt(req: rtsp.RtspRequest) -> float | None:
        """The numeric start of a ``Range: npt=…`` header, or None for
        a missing/``now`` range (``npt=now-`` means the live edge, RFC
        2326 §3.6 — only an explicit number asks for the past)."""
        rng = req.headers.get("range", "")
        if not rng.startswith("npt="):
            return None
        start = rng[4:].split("-")[0].strip()
        if not start or start == "now":
            return None
        try:
            return max(float(start), 0.0)
        except ValueError:
            return None

    @staticmethod
    def _parse_speed(req: rtsp.RtspRequest) -> tuple[float, dict]:
        """RFC 2326 §12.35 Speed on a time-shift PLAY: the catch-up
        accelerator (delivery-rate factor; >1 is how a shifted viewer
        reaches the live head and rejoins).  Out-of-range plays at 1×
        and the response says so."""
        v = req.headers.get("speed", "")
        if not v:
            return 1.0, {}
        try:
            f = float(v)
        except ValueError:
            f = None
        if f is None or not 0.01 <= f <= 8.0:
            return 1.0, {"Speed": "1"}
        return f, {"Speed": f"{f:g}"}

    async def _do_play(self, req: rtsp.RtspRequest) -> None:
        if self.vod_file is not None:
            await self._do_play_vod(req)
            return
        if self.dvr_path is not None:
            await self._do_play_dvr(req)
            return
        if self.relay is None or not self.player_tracks:
            raise rtsp.RtspError(455)
        # live path under an armed spiller: an explicit numeric Range
        # (rewind) or a latched PAUSE bookmark re-enters through the
        # time-shift tier; ``npt=now-`` / no Range joins the live edge
        dvr = self.server.dvr
        start_npt = self._range_npt(req)
        if (dvr is not None
                and (start_npt is not None or self.pause_ids)
                and self._play_timeshift(req, start_npt)):
            return
        infos = []
        for tid, pt in self.player_tracks.items():
            if pt.output not in self.relay.streams[tid].outputs:
                self.relay.add_output(tid, pt.output)
            infos.append(f"url={req.uri.rstrip('/')}/trackID={tid}"
                         f";seq={pt.output.rewrite.out_seq_start}")
        self.playing = True
        self.server.stats["players"] += 1
        self.server.wake_pump()
        self._reply(rtsp.RtspResponse(200, {
            "Range": "npt=now-", "RTP-Info": ",".join(infos)}), req.cseq)

    async def _do_play_vod(self, req: rtsp.RtspRequest) -> None:
        from ..vod.session import FileSession
        if not self.player_tracks:
            raise rtsp.RtspError(455)
        start_npt = 0.0
        rng = req.headers.get("range", "")
        if rng.startswith("npt="):
            try:
                start_npt = float(rng[4:].split("-")[0] or 0.0)
            except ValueError:
                start_npt = 0.0
        if self.vod_session is not None:
            self.vod_session.stop()
        # Speed (RFC 2326 §12.35): delivery-rate factor, timestamps
        # untouched.  Scale (§12.34): viewing-rate factor — delivery is
        # paced faster AND RTP timestamps are compressed by the factor so
        # a compliant client actually renders fast-forward.  Reverse play
        # (negative Scale) is unsupported and ignored, not silently
        # converted to forward.
        extra = {}
        speed = 1.0
        ts_scale = 1.0
        for hdr in ("scale", "speed"):
            v = req.headers.get(hdr, "")
            if not v:
                continue
            try:
                f = float(v)
            except ValueError:
                f = None
            if f is None or not 0.01 <= f <= 8.0:
                # RFC 2326 §12.34: the response carries the value actually
                # used — a rejected request plays at 1x and must say so
                extra[hdr.capitalize()] = "1"
                continue
            speed *= f
            if hdr == "scale":
                ts_scale = f
            extra[hdr.capitalize()] = f"{f:g}"
        outputs = {tid: pt.output for tid, pt in self.player_tracks.items()}
        # hot vs cold: the group pacer serves plain-RTP sessions through
        # the cache + live engine tier (ISSUE 10); Scale (timestamp
        # compression is not an affine offset) and x-RTP-Meta-Info
        # sessions (ft/pn/pp come from the sample tables mid-send) keep
        # the per-session FileSession
        pacer = getattr(self.server, "vod_pacer", None)
        hot = (pacer is not None and ts_scale == 1.0
               and all(o.meta_field_ids is None for o in outputs.values()))
        if hot:
            self.vod_session = pacer.open(
                self.vod_file, outputs, start_npt=start_npt,
                speed=speed, path=self.path or req.uri)
            self.server.wake_pump()
        else:
            self.vod_session = FileSession(self.vod_file, outputs,
                                           start_npt=start_npt,
                                           speed=speed,
                                           ts_scale=ts_scale)
            self.vod_session.start()
        self.playing = True
        self.server.stats["players"] += 1
        infos = ",".join(
            f"url={req.uri.rstrip('/')}/trackID={tid}"
            f";seq={pt.output.rewrite.out_seq_start}"
            for tid, pt in self.player_tracks.items())
        self._reply(rtsp.RtspResponse(200, {
            "Range": f"npt={start_npt:.3f}-", "RTP-Info": infos,
            **extra}), req.cseq)

    def _play_timeshift(self, req, start_npt: float | None) -> bool:
        """PLAY into the past on a LIVE subscription: detach from the
        live fan-out and hand the outputs (rewrite state intact — same
        ssrc, contiguous seq across the shift and the eventual catch-up
        join) to a pacer-driven TimeShiftSession over the spilled
        windows.  An explicit Range wins over a pause bookmark; returns
        False (caller joins live) when the asset has nothing yet."""
        speed, extra = self._parse_speed(req)
        outputs = {tid: pt.output
                   for tid, pt in self.player_tracks.items()}
        start_ids = None if start_npt is not None else self.pause_ids
        self._detach_outputs()
        if self.vod_session is not None:
            self.vod_session.stop()
            self.vod_session = None
        sess = self.server.dvr.open_timeshift(
            self.path, outputs, start_npt=start_npt,
            start_ids=start_ids, speed=speed)
        if sess is None:
            return False
        self.vod_session = sess
        self.pause_ids = None
        self.playing = True
        self.server.stats["players"] += 1
        self.server.wake_pump()
        infos = ",".join(
            f"url={req.uri.rstrip('/')}/trackID={tid}"
            f";seq={pt.output.rewrite.out_seq_start}"
            for tid, pt in self.player_tracks.items())
        self._reply(rtsp.RtspResponse(200, {
            "Range": f"npt={sess.position_npt() or sess.start_npt:.3f}-",
            "RTP-Info": infos, **extra}), req.cseq)
        return True

    async def _do_play_dvr(self, req: rtsp.RtspRequest) -> None:
        """PLAY a spilled ``.dvr`` asset: pure replay under the shared
        VOD pacer (instant stream-to-VOD — nothing was re-muxed; live
        pause/rewind uses ``_play_timeshift`` on the live path)."""
        if not self.player_tracks:
            raise rtsp.RtspError(455)
        start_npt = self._range_npt(req)
        # no explicit Range + a latched PAUSE bookmark = resume exactly
        # there (the same contract as the live _play_timeshift path);
        # an explicit Range always wins and discards the bookmark
        start_ids = None if start_npt is not None else self.pause_ids
        speed, extra = self._parse_speed(req)
        if self.vod_session is not None:
            self.vod_session.stop()
            self.vod_session = None
        outputs = {tid: pt.output
                   for tid, pt in self.player_tracks.items()}
        sess = self.server.dvr.open_timeshift(
            self.dvr_path, outputs, start_npt=start_npt,
            start_ids=start_ids, speed=speed)
        if sess is None:
            raise rtsp.RtspError(404)
        self.vod_session = sess
        self.pause_ids = None
        self.playing = True
        self.server.stats["players"] += 1
        self.server.wake_pump()
        infos = ",".join(
            f"url={req.uri.rstrip('/')}/trackID={tid}"
            f";seq={pt.output.rewrite.out_seq_start}"
            for tid, pt in self.player_tracks.items())
        self._reply(rtsp.RtspResponse(200, {
            "Range": f"npt={sess.position_npt() or sess.start_npt:.3f}-",
            "RTP-Info": infos, **extra}), req.cseq)

    async def _do_pause(self, req: rtsp.RtspRequest) -> None:
        sess = self.vod_session
        if sess is not None and hasattr(sess, "pause_ids"):
            # pausing a time-shift session: latch the exact resume
            # cursors (next id the PLAYER has not received)
            self.pause_ids = sess.pause_ids()
        elif (self.relay is not None and self.playing
                and self.server.dvr is not None
                and self.server.dvr.armed(self.path)):
            # live pause under an armed spiller: each output's ring
            # bookmark is the next unsent absolute id, and the spill
            # shares the ring's id space — the bookmark IS the resume
            # cursor (a resume before the first reflect just re-joins)
            ids = {tid: int(pt.output.bookmark)
                   for tid, pt in self.player_tracks.items()
                   if pt.output.bookmark is not None}
            self.pause_ids = ids or None
        if sess is not None:
            sess.stop()
            self.vod_session = None
        self._detach_outputs()
        self.playing = False
        self._reply(rtsp.RtspResponse(200), req.cseq)

    async def _do_teardown(self, req: rtsp.RtspRequest) -> None:
        self._reply(rtsp.RtspResponse(200), req.cseq)
        await self.close()

    # -------------------------------------------------------- media paths
    def _on_interleaved(self, pkt: rtsp.InterleavedPacket) -> None:
        """Pushed media (RECORD mode) or player RTCP feedback."""
        m = self.channel_map.get(pkt.channel)
        if m is not None and self.relay is not None:
            track_id, is_rtcp = m
            if not is_rtcp:
                self.server.modules.run_incoming_rtp(self.relay, track_id,
                                                     pkt.data)
            self.relay.push(track_id, pkt.data, is_rtcp=is_rtcp)
            self.server.stats["packets_in"] += 1
            self.server.wake_pump()
            return
        if self.player_tracks and pkt.channel % 2 == 1:
            self.server.on_client_rtcp(self, pkt.data)

    def send_interleaved(self, channel: int, data: bytes) -> None:
        """Write one $-framed packet on this connection (server→client)."""
        if not self.writer.is_closing():
            self.writer.write(b"$" + bytes([channel])
                              + len(data).to_bytes(2, "big") + data)

    def _native_rtp_drain(self, track_id: int, fd: int) -> None:
        """Readiness-edge callback for a pusher's native-ingest RTP
        socket: one call drains the whole pending batch into the ring."""
        if self.relay is None:
            return
        try:
            n = self.relay.drain_native(track_id, fd)
        except OSError:
            # hard recv error (or a close race on the fd): stop the
            # readiness callback so a permanently-readable dead socket
            # cannot spin the loop; the timeout sweep reaps the track
            try:
                asyncio.get_event_loop().remove_reader(fd)
            except (OSError, ValueError):
                pass
            return
        # the drain may have disarmed a failing io_uring ring (native
        # fallback to recvmmsg): its now-closed ring fd must stop being
        # watched before another socket recycles the number
        pt = self.pusher_tracks.get(track_id)
        pair = pt.udp_pair if pt is not None else None
        if pair is not None and getattr(pair, "_uring_armed", False):
            pair.prune_ring_watch()
        if n:
            self.last_activity = time.monotonic()
            self.server.stats["packets_in"] += n
            self.server.wake_pump()

    def _udp_ingest(self, track_id: int, data: bytes, is_rtcp: bool,
                    addr=None) -> None:
        if self.relay is not None:
            self.relay.push(track_id, data, is_rtcp=is_rtcp)
            self.server.stats["packets_in"] += 1
            self.server.wake_pump()
            if is_rtcp and addr is not None:
                # learn the pusher's RTCP address once → upstream RRs
                st = self.relay.streams.get(track_id)
                pt = self.pusher_tracks.get(track_id)
                if (st is not None and st.upstream_rtcp is None
                        and pt is not None and pt.udp_pair is not None):
                    tr = pt.udp_pair.rtcp_transport
                    st.upstream_rtcp = (
                        lambda d, t=tr, a=addr: t.sendto(d, a))
                    st.upstream_rtcp_owner = self

    # ----------------------------------------------------------- teardown
    def _detach_outputs(self) -> None:
        if self.relay is None:
            return
        for tid, pt in self.player_tracks.items():
            st = self.relay.streams.get(tid)
            if st is not None:
                st.remove_output(pt.output)

    async def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        if self.session_id is not None:
            EVENTS.emit("rtsp.close", session_id=self.session_id,
                        stream=self.path, trace_id=self.trace_id,
                        level="warn" if self.abnormal_reason else "info",
                        reason=self.abnormal_reason or "eof")
            if self.abnormal_reason and (self.player_tracks
                                         or self.is_pusher):
                # abnormal media-session death → freeze the black box
                FLIGHT.dump(self.session_id, reason=self.abnormal_reason)
            else:
                FLIGHT.discard(self.session_id)
        self.server.modules.run_session_closing(self)
        self.server.on_session_closed(self)
        if self.vod_session is not None:
            self.vod_session.stop()
            self.vod_session = None
        if self.vod_file is not None:
            self.vod_file.close()
            self.vod_file = None
        self._detach_outputs()
        if self.player_tracks:
            # a departed player's QoS gauges must not linger in /metrics
            # (a surviving subscriber's next RR re-creates them)
            from ..relay import quality as quality_mod
            from ..relay import fec as fec_mod
            for tid in self.player_tracks:
                quality_mod.drop_qos(self.path, tid)
                fec_mod.drop_overhead_gauge(self.path, tid)
        egress = self.server.shared_egress
        for pt in self.player_tracks.values():
            if pt.udp_pair:
                pt.udp_pair.close()
            elif egress is not None and hasattr(pt.output, "rtcp_addr"):
                egress.unregister(pt.output, self)
        for pt in self.pusher_tracks.values():
            if pt.udp_pair:
                pt.udp_pair.close()
        if self.is_pusher and self.relay is not None:
            # our upstream-RR closures reference this (dying) connection —
            # clear them so an adopted session re-learns the new pusher's
            # RTCP path instead of writing into a closed transport forever
            for st in self.relay.streams.values():
                if st.upstream_rtcp_owner is self:
                    st.upstream_rtcp = None
                    st.upstream_rtcp_owner = None
            # pusher gone → tear down the relay session (the reference frees
            # the ReflectorSession when the broadcast stops) — but only if
            # still OURS: a re-ANNOUNCE adopts the session (owner re-stamped)
            # and that live broadcast must survive our disconnect
            if (self.server.registry.find(self.relay.path) is self.relay
                    and self.relay.owner is self):
                self.server.registry.remove(self.relay.path)
            self.relay = None
        if self in self.server.connections:
            self.server.connections.discard(self)
            self.server.on_ip_disconnect(self.client_ip)
        try:
            self.writer.close()
        except Exception:
            pass


class RtspServer:
    """Listener + connection registry (QTSServer::CreateListeners analog)."""

    def __init__(self, config: ServerConfig, registry: SessionRegistry,
                 *, describe_fallback=None, on_pump_wake=None, vod=None,
                 auth=None, access_log=None):
        self.config = config
        self.registry = registry
        self.vod = vod                       # VodService or None
        #: VodPacerGroup (ISSUE 10) — set by the app once the engine
        #: tier is probed; None = every PLAY gets the cold FileSession
        self.vod_pacer = None
        #: DvrManager (ISSUE 12) — set by the app when dvr_enabled; None
        #: = PAUSE detaches (classic), ``.dvr`` paths 404, RECORD never
        #: arms a spiller
        self.dvr = None
        self.auth = auth                     # AuthService or None
        self.access_log = access_log         # AccessLog or None
        #: overload admission hook (ISSUE 13) — set by the app under
        #: cluster mode: ``(path, client_key) -> None | (action, url)``;
        #: None = every SETUP admitted (standalone behavior)
        self.admission = None
        #: cross-node trace acceptance gate (ISSUE 15) — set by the app
        #: under cluster mode: ``(x_cluster_node_header) -> bool``;
        #: None = X-Trace-Id headers are never adopted (standalone)
        self.peer_trace_gate = None
        #: interleaved-TCP checkpoint re-attach hook (ISSUE 14) — set by
        #: the app when checkpointing is on: ``(path, track_id,
        #: session_id) -> record | None``.  A re-connecting player that
        #: presents its old Session id on an interleaved SETUP adopts
        #: the recorded rewrite state, so the framed seq space continues
        #: gapless across a restart/migration.
        self.tcp_restore = None
        from .modules import ModuleRegistry
        self.modules = ModuleRegistry()
        #: RTSP-over-HTTP tunnels: x-sessioncookie → GET-side connection
        self.tunnels: dict[str, RtspConnection] = {}
        #: hook for plain HTTP GET on the RTSP port (mp3/stats); set by app
        self.http_get_handler = None
        self.udp_pool = UdpPortPool(bind_ip="0.0.0.0")
        #: shared (RTP, RTCP) egress pair for UDP players — the reference's
        #: RTPSocketPool shared-pair + UDPDemuxer design; doorway to the
        #: native batched egress (server/egress.py). None until start().
        self.shared_egress = None
        #: set by the app's egress-backend probe: pusher RTP sockets get
        #: multishot io_uring ingest (transports.NativeIngestPair arms
        #: per pair; the recvmmsg drain stays the fallback)
        self.uring_ingest_enabled = False
        #: SdpFileRelaySource for .sdp-described UDP/multicast broadcasts
        self.relay_source = None
        self.connections: set[RtspConnection] = set()
        #: live connection count per client IP (O(1) SpamDefense check)
        self._per_ip: dict[str, int] = {}
        self.stats = {"requests": 0, "pushers": 0, "players": 0,
                      "packets_in": 0}
        self._server: asyncio.AbstractServer | None = None
        #: hook for VOD / other describe sources: async (path) -> sdp | None
        self.describe_fallback = describe_fallback
        self._on_pump_wake = on_pump_wake
        self.port: int | None = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connection, self.config.bind_ip, self.config.rtsp_port)
        self.port = self._server.sockets[0].getsockname()[1]
        if self.config.shared_udp_egress:
            from .egress import SharedUdpEgress
            self.shared_egress = SharedUdpEgress(self.config.bind_ip)
            await self.shared_egress.start()
            self.shared_egress.on_rtcp = self.on_client_rtcp

    async def stop(self) -> None:
        for conn in list(self.connections):
            await conn.close()
        if self.shared_egress is not None:
            self.shared_egress.close()
            self.shared_egress = None
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _on_connection(self, reader, writer) -> None:
        if len(self.connections) >= self.config.max_connections:
            writer.close()
            return
        # per-IP cap (QTSSSpamDefenseModule): refuse before spending a task
        per_ip = self.config.max_connections_per_ip
        peer = writer.get_extra_info("peername")
        ip = peer[0] if peer else "?"       # same fallback as client_ip
        if per_ip and self._per_ip.get(ip, 0) >= per_ip:
            writer.close()
            return
        conn = RtspConnection(self, reader, writer)
        self.connections.add(conn)
        self._per_ip[ip] = self._per_ip.get(ip, 0) + 1
        await conn.run()

    def on_ip_disconnect(self, ip: str) -> None:
        n = self._per_ip.get(ip, 0) - 1
        if n > 0:
            self._per_ip[ip] = n
        else:
            self._per_ip.pop(ip, None)

    # -- hooks -------------------------------------------------------------
    async def describe(self, path: str) -> str | None:
        # live sessions (pushed or already-opened broadcasts) win over
        # on-disk .sdp files, which win over VOD assets
        text = self.registry.sdp_cache.get(path)
        if text is None and self.relay_source is not None:
            text = await self.relay_source.describe(path)
        if text is None and self.vod is not None:
            text = await self.vod.describe(path)
        if text is None and self.dvr is not None:
            # <live path>.dvr: the spilled asset's stored push SDP
            text = await self.dvr.describe(path)
        if text is None and self.describe_fallback is not None:
            text = await self.describe_fallback(path)
        return text

    async def open_for_play(self, path: str) -> RelaySession | None:
        sess = self.registry.find(path)
        if sess is None and self.relay_source is not None:
            sess = await self.relay_source.open(path)
        return sess

    async def handle_http_get(self, conn: RtspConnection, target: str,
                              headers: dict) -> None:
        if self.http_get_handler is not None:
            handled = await self.http_get_handler(conn, target, headers)
            if handled:
                return
        conn.writer.write(b"HTTP/1.0 404 Not Found\r\n\r\n")

    def on_session_closed(self, conn: RtspConnection) -> None:
        """ClientSessionClosing → access-log record (AccessLogModule role)."""
        if self.access_log is None or (not conn.player_tracks
                                       and not conn.is_pusher):
            return
        from ..utils.logs import AccessRecord
        sent = sum(pt.output.packets_sent
                   for pt in conn.player_tracks.values())
        nbytes = sum(pt.output.bytes_sent
                     for pt in conn.player_tracks.values())
        any_udp = any(pt.udp_pair for pt in conn.player_tracks.values())
        self.access_log.record(AccessRecord(
            client_ip=conn.client_ip, uri=conn.uri or conn.path or "-",
            method="RECORD" if conn.is_pusher else "PLAY",
            duration_sec=time.monotonic() - conn.created_at,
            bytes_sent=nbytes, packets_sent=sent,
            user_agent=conn.user_agent,
            transport="UDP" if any_udp else "TCP"))

    def on_client_rtcp(self, conn: RtspConnection, data: bytes,
                       addr=None) -> None:
        """Receiver reports from players → per-output quality adaptation
        (the QTSS_RTCPProcess_Role → FlowControlModule pipeline), and
        'qtak' acks → the reliable-UDP resend window.

        Valid RTCP from a player proves the session is alive: refresh its
        idle clock, or the sweep kills an actively-watching UDP player at
        rtsp_timeout (its RTSP TCP connection is legitimately silent
        during playback).  The refresh requires PROOF of ownership — the
        datagram's source is a registered track's RTCP address, or the
        compound references an SSRC this connection's outputs own — so a
        forged-but-parseable empty RR cannot keep a dead session
        allocated forever.  Reference: ``RTPStream::
        ProcessIncomingRTCPPacket`` → ``RefreshTimeout`` via RTCPTask."""
        from ..protocol import rtcp as rtcp_mod
        self.stats.setdefault("rtcp_in", 0)
        self.stats["rtcp_in"] += 1
        try:
            pkts = rtcp_mod.parse_compound(data)
        except rtcp_mod.RtcpError:
            return
        outputs = {pt.output.rewrite.ssrc: pt.output
                   for pt in conn.player_tracks.values()}
        track_of = {pt.output.rewrite.ssrc: tid
                    for tid, pt in conn.player_tracks.items()}
        # the RTCP source address names the track (each SETUP registers its
        # own client rtcp port) — required for acks, whose 16-bit seq
        # spaces collide across tracks (a video ack must never pop an
        # audio packet from its resend window)
        addr_out = None
        if addr is not None:
            for pt in conn.player_tracks.values():
                if getattr(pt.output, "rtcp_addr", None) == tuple(addr):
                    addr_out = pt.output
                    break
        proven = addr_out is not None
        from ..resilience.inject import INJECTOR
        for p in pkts:
            if isinstance(p, rtcp_mod.ReceiverReport):
                for rb in p.reports:
                    out = outputs.get(rb.ssrc)
                    if out is not None:
                        proven = True
                        frac = rb.fraction_lost / 256.0
                        if INJECTOR.active:
                            # chaos site (ISSUE 11): drive the loss-fed
                            # controllers without a lossy wire
                            spoof = INJECTOR.rr_loss_spoof()
                            if spoof is not None:
                                frac = spoof
                        out.on_receiver_report(frac)
                        fec = getattr(out, "fec", None)
                        if fec is not None:
                            # closed-loop FEC overhead rides the SAME
                            # RR stream the thinning controller reads
                            fec.controller.on_receiver_report(frac)
                        # fold loss/jitter into the scrapeable per-stream
                        # QoS gauges (obs registry)
                        from ..relay import quality as quality_mod
                        tid = track_of.get(rb.ssrc)
                        rate = None
                        if conn.relay is not None and tid in conn.relay.streams:
                            rate = conn.relay.streams[tid].info.clock_rate
                        quality_mod.record_rr_qos(
                            conn.path, tid, frac, rb.jitter, rate)
            elif isinstance(p, rtcp_mod.Nadu):
                # 3GPP NADU buffer state → per-output rate adaptation;
                # each block names the media sender SSRC it reports on
                for blk in p.blocks:
                    out = outputs.get(blk.ssrc)
                    if out is not None:
                        proven = True
                        out.on_nadu(blk.playout_delay_ms,
                                    blk.free_buffer_64b)
                        fec = getattr(out, "fec", None)
                        if fec is not None:
                            # buffer distress shifts the NACK-vs-FEC
                            # split toward RTX (parity is bitrate)
                            fec.controller.on_nadu(blk.playout_delay_ms,
                                                   blk.free_buffer_64b)
            elif isinstance(p, rtcp_mod.GenericNack):
                # RFC 4585 generic NACK → ring-bookmark RTX replay
                # (relay/fec.py): the ring IS the retransmission buffer
                out = outputs.get(p.media_ssrc)
                if out is None and addr_out is not None \
                        and getattr(addr_out, "fec", None) is not None:
                    out = addr_out       # source-addr routed fallback
                if out is not None and self._handle_nack(conn, out, p):
                    proven = True
            elif isinstance(p, rtcp_mod.App):
                # RTCPAckPacket → RTPPacketResender::AckPacket path.
                # Route: exact track by RTCP source addr, else by the
                # App's SSRC, else (single reliable track only) fall back
                # to it — never broadcast across colliding seq spaces
                routed = addr_out is not None or p.ssrc in outputs
                if addr_out is not None:
                    targets = [addr_out]
                elif p.ssrc in outputs:
                    targets = [outputs[p.ssrc]]
                else:
                    targets = [o for o in outputs.values()
                               if hasattr(o, "on_rtcp_app")]
                    if len(targets) != 1:
                        continue
                for out in targets:
                    ack_fn = getattr(out, "on_rtcp_app", None)
                    if ack_fn is not None:
                        matched = ack_fn(p)
                        # Ownership proof: a source-addr/SSRC-routed
                        # track, or — in the single-track fallback,
                        # where neither matched — an ack seq that
                        # actually popped a packet from the resend
                        # window.  A forged-but-parseable App with an
                        # arbitrary SSRC proves nothing and must not
                        # refresh the idle clock.
                        if routed or matched:
                            proven = True
        if proven:
            conn.last_activity = time.monotonic()

    def _handle_nack(self, conn: RtspConnection, out, nack) -> bool:
        """Resolve one generic NACK's lost OUTPUT seqs to live ring
        bookmarks and replay them as RTX (ISSUE 11).  Returns True when
        the NACK matched a FEC-armed output (ownership proof — a
        forged NACK for an unknown SSRC proves nothing)."""
        if getattr(out, "fec", None) is None or conn.relay is None:
            return False
        tid = next((t for t, pt in conn.player_tracks.items()
                    if pt.output is out), None)
        stream = conn.relay.streams.get(tid) if tid is not None else None
        if stream is None or stream.fec is None:
            return False
        stream.fec.replay_nacked(out, nack.lost_seqs(), now_ms(),
                                 on_giveup=self.on_rtx_giveup)
        return True

    #: set by the app: a path whose RTX budget was exhausted is charged
    #: to the PR 5 degradation ladder (a black-holed client must shed
    #: load, never amplify)
    on_rtx_giveup = None

    def wake_pump(self) -> None:
        if self._on_pump_wake is not None:
            self._on_pump_wake()

    def sweep_timeouts(self) -> int:
        """Close idle connections (TimeoutTask 15 s sweep equivalent)."""
        now = time.monotonic()
        killed = 0
        for conn in list(self.connections):
            idle = now - conn.last_activity
            limit = (self.config.push_timeout_sec if conn.is_pusher
                     else self.config.rtsp_timeout_sec)
            if conn.is_pusher and self.relay_active(conn):
                limit = max(limit, self.config.push_timeout_sec)
            if idle > limit:
                conn.abnormal_reason = (conn.abnormal_reason
                                        or f"timeout: idle {idle:.1f}s "
                                           f"> {limit}s")
                asyncio.get_event_loop().create_task(conn.close())
                killed += 1
        return killed

    @staticmethod
    def relay_active(conn: RtspConnection) -> bool:
        return (conn.relay is not None
                and now_ms() - conn.relay.last_ingest_ms < 5_000)
