"""Process watchdog — the fork-based auto-restart loop.

Reference parity: ``main.cpp:492-558`` — the parent forks the server child,
waits, and restarts it on crash or on the deliberate restart exit code
(exit −2 → restart, ``RunServer.cpp:711-717``), honoring the
``auto_restart`` pref and rate-limiting runaway crash loops.
"""

from __future__ import annotations

import subprocess
import sys
import time

#: child exit code meaning "restart me" (REST /restart, SIGHUP-style)
EXIT_RESTART = 2
#: give up if the child dies this many times within WINDOW_SEC
MAX_CRASHES = 5
WINDOW_SEC = 60.0


def run_supervised(child_argv: list[str], *, auto_restart: bool = True,
                   spawn=None, sleep=time.sleep,
                   log=lambda m: print(m, file=sys.stderr, flush=True)) -> int:
    """Run the child command under supervision; returns the final exit code.

    ``spawn``/``sleep``/``log`` are injectable for tests.
    """
    spawn = spawn or (lambda argv: subprocess.call(argv))
    crashes: list[float] = []
    while True:
        code = spawn(child_argv)
        if code == 0:
            return 0
        if code == EXIT_RESTART:
            log("supervisor: restart requested, relaunching")
            continue
        if not auto_restart:
            return code
        now = time.monotonic()
        crashes = [t for t in crashes if now - t < WINDOW_SEC] + [now]
        if len(crashes) >= MAX_CRASHES:
            log(f"supervisor: {len(crashes)} crashes in {WINDOW_SEC:.0f}s, "
                "giving up")
            return code
        delay = min(2.0 ** len(crashes), 15.0)
        log(f"supervisor: child exited {code}, restarting in {delay:.0f}s")
        sleep(delay)
