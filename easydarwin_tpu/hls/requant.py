"""HLS bitrate rendition via transform-domain H.264 requantization.

``RequantHlsOutput`` is an ``HlsOutput`` whose access units pass through
``codecs.h264_requant.SliceRequantizer`` before muxing: a TRUE
lower-bitrate rendition at the SAME frame rate, next to the temporal
(frame-thinning) rungs (VERDICT r2 item 4).  The split mirrors the MJPEG
ladder: CAVLC entropy recode on the host, the per-level integer requant
batched on the device (``ops.transform.h264_requant``), differential-
tested bit-exact against the scalar oracle.

Honest scope notes (also in ``codecs.h264_requant``): CAVLC baseline
intra slices only (I_4x4 + I_16x16, luma AND 4:2:0 chroma residuals);
anything else passes through unchanged and is counted, so the rendition
degrades toward the source bitrate rather than corrupting.  Requant is
open loop: drift is spatial-only and resets at every IDR — for
all-intra camera streams, every frame."""

from __future__ import annotations

import asyncio
from concurrent.futures import ThreadPoolExecutor

from ..codecs.h264_requant import (SliceRequantizer, device_batch,
                                   device_batch_chroma)
from ..vod.depacketize import AccessUnit
from .segmenter import HlsOutput

#: one shared worker for ALL requant renditions: the host-side CAVLC
#: recode is pure Python (~0.5 ms per macroblock) and must never run on
#: the event loop — a single FIFO worker also preserves per-stream AU
#: order without locks
_worker: ThreadPoolExecutor | None = None


def _get_worker() -> ThreadPoolExecutor:
    global _worker
    if _worker is None:
        _worker = ThreadPoolExecutor(max_workers=1,
                                     thread_name_prefix="hls-requant")
    return _worker


class RequantHlsOutput(HlsOutput):
    def __init__(self, delta_qp: int, *, use_device: bool = True, **kw):
        super().__init__(**kw)
        from .. import native as native_mod
        if native_mod.available():
            # the native CAVLC walk (~100x the Python path) is the
            # production engine; it embeds the same exact level shift
            # and the chroma identity/shift/round-trip dispatch
            fn = cfn = None
        else:
            fn = device_batch if use_device else None
            cfn = device_batch_chroma if use_device else None
        self.requant = SliceRequantizer(delta_qp, requant_fn=fn,
                                        chroma_fn=cfn)
        self.delta_qp = delta_qp
        self._ps_fed: tuple[bytes | None, bytes | None] = (None, None)
        #: AUs dropped because the requant worker was too far behind —
        #: real-time-ness depends on picture size (pure-Python CAVLC);
        #: shedding keeps the rendition live instead of ever-later
        self.shed = 0
        self._inflight = 0

    def _transform(self, au: AccessUnit,
                   ps: tuple[bytes | None, bytes | None]) -> AccessUnit:
        # the depacketizer latches SPS/PPS out of band (they are config,
        # not sample data) — feed them to the requantizer when they change
        if ps != self._ps_fed:
            self._ps_fed = ps
            for n in ps:
                if n:
                    self.requant.transform_nal(n)
        return AccessUnit(au.timestamp,
                          [self.requant.transform_nal(n) for n in au.nals])

    def _on_unit(self, au: AccessUnit) -> None:
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        # parameter sets are captured at ENQUEUE time (loop thread): a
        # queued AU must be requantized against the PPS it was coded
        # with, not whatever a later packet latched
        ps = (self.depack.sps, self.depack.pps)
        if loop is None:
            # synchronous caller (tests, offline tools): transform inline
            super()._on_unit(self._transform(au, ps))
            return
        if self._inflight >= 8:
            self.shed += 1                 # backlogged: shed, stay live
            return
        self._inflight += 1

        def work():
            try:
                out = self._transform(au, ps)
            except Exception:
                # never let a worker error strand _inflight (that would
                # shed every future AU forever); pass the unit through
                out = au
            loop.call_soon_threadsafe(self._emit, out)

        _get_worker().submit(work)

    def _emit(self, au: AccessUnit) -> None:
        self._inflight -= 1
        super()._on_unit(au)
