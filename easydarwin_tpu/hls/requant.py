"""HLS bitrate rendition via transform-domain H.264 requantization.

``RequantHlsOutput`` is an ``HlsOutput`` whose access units pass through
``codecs.h264_requant.SliceRequantizer`` before muxing: a TRUE
lower-bitrate rendition at the SAME frame rate, next to the temporal
(frame-thinning) rungs (VERDICT r2 item 4).  The split mirrors the MJPEG
ladder: CAVLC entropy recode on the host, the per-level integer requant
batched on the device (``ops.transform.h264_requant``), differential-
tested bit-exact against the scalar oracle.

Parallel harness (VERDICT r3 item 1): ALL requant renditions share one
``ThreadPoolExecutor`` sized to the host's cores — the native CAVLC walk
is a ctypes call, so the GIL is released for its whole duration and
pictures genuinely run in parallel.  Order is preserved per rendition
without serializing it: consecutive AUs of the same rung pipeline
through different workers (each against snapshot parameter sets) and a
reorder buffer emits them in submission order — so ONE 1080p30 rung
scales across cores, not just many rungs across cores.  The reference
analogue is the short/blocking task-thread split
(``Task.cpp:120-146``); here the "blocking pool" is per-picture jobs.

Honest scope notes (also in ``codecs.h264_requant``): CAVLC baseline
intra slices only (I_4x4 + I_16x16, luma AND 4:2:0 chroma residuals);
anything else passes through unchanged and is counted, so the rendition
degrades toward the source bitrate rather than corrupting.  Requant is
open loop: drift is spatial-only and resets at every IDR — for
all-intra camera streams, every frame."""

from __future__ import annotations

import asyncio
import os
import threading
from concurrent.futures import ThreadPoolExecutor

from ..codecs.h264_requant import (SliceRequantizer, device_batch,
                                   device_batch_chroma)
from ..vod.depacketize import AccessUnit
from .segmenter import HlsOutput

#: one shared pool for ALL requant renditions, sized to the cores the
#: process may use: the native walk releases the GIL (ctypes), so jobs
#: from one OR many renditions run truly concurrently; the pure-Python
#: fallback path still benefits from staying off the event loop
_pool: ThreadPoolExecutor | None = None
_sizing_cache: dict | None = None


def widen_affinity() -> None:
    """Undo a ONE-CORE pin on the calling thread.  The TPU runtime
    plugin pins the thread that initializes it (on the bench/server box:
    the main thread, at interpreter start via sitecustomize) to a single
    core; threads spawned afterwards inherit that one-core mask, which
    is how a 2-core host ran the whole requant pool on one CPU
    (``workers=1``, ``parallel == serial`` in bench r04/r05).

    Deliberately narrow: only the exact one-core signature is widened,
    so an operator's multi-core confinement (``taskset -c 0,1``) is
    preserved; the kernel intersects the widened mask with the cpuset,
    so a cpuset quota is never escaped either.  What this CANNOT see is
    a pure bandwidth quota (cgroup ``cpu.max`` on a big node) — size the
    pool explicitly with ``EDTPU_REQUANT_WORKERS`` there (the override
    also disables widening entirely)."""
    if os.environ.get("EDTPU_REQUANT_WORKERS"):
        return
    try:
        if len(os.sched_getaffinity(0)) == 1 and (os.cpu_count() or 1) > 1:
            os.sched_setaffinity(0, range(os.cpu_count() or 1))
    except (AttributeError, OSError, ValueError):
        pass


def _own_cgroup_path(proc_cgroup: str, controller: str | None) -> str:
    """This process's cgroup path for ``controller`` (None = the v2
    unified hierarchy) from ``/proc/self/cgroup`` — the effective quota
    lives in OUR cgroup, not the root (a systemd CPUQuota= service sits
    in system.slice/<svc> where the root's cpu.max reads 'max')."""
    try:
        with open(proc_cgroup, encoding="ascii") as f:
            for ln in f:
                parts = ln.strip().split(":", 2)
                if len(parts) != 3:
                    continue
                if controller is None and parts[0] == "0":
                    return parts[2]
                if controller is not None and \
                        controller in parts[1].split(","):
                    return parts[2]
    except OSError:
        pass
    return ""


def _cgroup_quota_cpus(proc_cgroup: str = "/proc/self/cgroup",
                       fs_root: str = "/sys/fs/cgroup") -> float | None:
    """CPU-equivalents allowed by the cgroup's *bandwidth* quota (the
    signal affinity masks cannot see): cgroup v2 ``cpu.max`` or v1
    ``cpu.cfs_quota_us``/``cpu.cfs_period_us``, read from THIS
    process's cgroup and every ancestor up to the root — the effective
    limit is the minimum along the chain.  None = no quota anywhere
    (or not on Linux/cgroups)."""
    best: float | None = None

    def note(v: float) -> None:
        nonlocal best
        best = v if best is None else min(best, v)

    def walk(root: str, rel: str, read) -> None:
        node = root + rel if rel and rel != "/" else root
        while True:
            v = read(node)
            if v is not None:
                note(v)
            if node == root or not node.startswith(root):
                break
            node = os.path.dirname(node)

    def read_v2(node: str) -> float | None:
        try:
            with open(node + "/cpu.max", encoding="ascii") as f:
                quota, _, period = f.read().strip().partition(" ")
            if quota != "max" and float(period) > 0:
                return float(quota) / float(period)
        except (OSError, ValueError):
            pass
        return None

    def read_v1(node: str) -> float | None:
        try:
            with open(node + "/cpu.cfs_quota_us", encoding="ascii") as f:
                quota = float(f.read().strip())
            with open(node + "/cpu.cfs_period_us", encoding="ascii") as f:
                period = float(f.read().strip())
            if quota > 0 and period > 0:
                return quota / period
        except (OSError, ValueError):
            pass
        return None

    walk(fs_root, _own_cgroup_path(proc_cgroup, None), read_v2)
    walk(fs_root + "/cpu", _own_cgroup_path(proc_cgroup, "cpu"), read_v1)
    return best


def _probe_affinity() -> int:
    """CPUs visible to a fresh thread that first widens its own affinity
    (un-inheriting the TPU runtime's one-core main-thread pin)."""
    box: list[int] = []

    def probe() -> None:
        widen_affinity()
        try:
            box.append(len(os.sched_getaffinity(0)))
        except (AttributeError, OSError):
            box.append(os.cpu_count() or 1)

    t = threading.Thread(target=probe, name="hls-requant-probe")
    t.start()
    t.join()
    return max(1, box[0] if box else 1)


def pool_sizing(*, affinity: int | None = None,
                quota: float | None = None,
                cpu_count: int | None = None,
                env: str | None = None) -> dict:
    """Worker count for the shared requant pool PLUS the rationale —
    which signal won and what every signal read — surfaced into the
    bench JSON ``extra`` so a wrong sizing is diagnosable from the
    trajectory alone (BENCH_r05 shipped ``workers: 1`` with nothing to
    say why).

    Signals, in precedence order:

    * ``EDTPU_REQUANT_WORKERS`` — explicit operator override;
    * the **affinity probe** (widened throwaway thread) — the CPUs the
      scheduler will actually run our threads on;
    * the **cgroup bandwidth quota** (``cpu.max`` / cfs_quota) — the
      signal the affinity mask cannot see.  Two regressions it fixes:
      the bench-box case where the probe collapses to 1 (the runtime's
      one-core pin survives because ``sched_setaffinity`` is denied in
      the container) while the quota provisions several CPUs — trust
      the quota, the per-worker initializer still retries the widen;
      and the big-node case where affinity says 96 but ``cpu.max``
      caps at 2 — sizing to 96 just trades throughput for preemption
      thrash, so the quota caps the pool.

    Keyword arguments override the probed signals (tests); the no-
    argument call is memoized — none of these signals move at runtime."""
    global _sizing_cache
    injected = (affinity is not None or quota is not None
                or cpu_count is not None or env is not None)
    if not injected and _sizing_cache is not None:
        return _sizing_cache
    env = os.environ.get("EDTPU_REQUANT_WORKERS") if env is None else env
    if env:
        try:
            sizing = {"workers": max(1, int(env)), "source": "env",
                      "affinity_cpus": None, "quota_cpus": None,
                      "cpu_count": os.cpu_count() or 1}
            if not injected:
                _sizing_cache = sizing
            return sizing
        except ValueError:
            pass
    ncpu = cpu_count if cpu_count is not None else (os.cpu_count() or 1)
    aff = affinity if affinity is not None else _probe_affinity()
    q = quota if quota is not None else _cgroup_quota_cpus()
    q_cpus = max(1, int(q)) if q is not None and q >= 1 else \
        (1 if q is not None else None)
    if aff <= 1 and q_cpus is not None and q_cpus > 1:
        workers, source = min(q_cpus, ncpu), "cpu_max_quota"
    elif q_cpus is not None and q_cpus < aff:
        workers, source = q_cpus, "cpu_max_cap"
    else:
        workers, source = aff, "affinity"
    sizing = {"workers": max(1, workers), "source": source,
              "affinity_cpus": aff,
              "quota_cpus": round(q, 2) if q is not None else None,
              "cpu_count": ncpu}
    if not injected:
        _sizing_cache = sizing
    return sizing


def pool_workers() -> int:
    """Worker count for the shared requant pool (see ``pool_sizing``
    for the decision rationale)."""
    return pool_sizing()["workers"]


def _get_pool() -> ThreadPoolExecutor:
    global _pool
    if _pool is None:
        # initializer: each worker un-inherits the importing thread's
        # one-core pin, or the sized pool still stacks on a single CPU
        _pool = ThreadPoolExecutor(max_workers=pool_workers(),
                                   thread_name_prefix="hls-requant",
                                   initializer=widen_affinity)
    return _pool


class RequantHlsOutput(HlsOutput):
    def __init__(self, delta_qp: int, *, use_device: bool = True, **kw):
        super().__init__(**kw)
        from .. import native as native_mod
        if native_mod.available():
            # the native CAVLC walk (~100x the Python path) is the
            # production engine; it embeds the same exact level shift
            # and the chroma identity/shift/round-trip dispatch
            fn = cfn = None
        else:
            fn = device_batch if use_device else None
            cfn = device_batch_chroma if use_device else None
        self.requant = SliceRequantizer(delta_qp, requant_fn=fn,
                                        chroma_fn=cfn)
        self.delta_qp = delta_qp
        self._ps_fed: tuple[bytes | None, bytes | None] = (None, None)
        #: AUs dropped because the pipeline was too far behind — shedding
        #: keeps the rendition live instead of ever-later.  Depth 2x the
        #: pool keeps every core fed while bounding added latency to
        #: ~2 pictures' work
        self.shed = 0
        self._max_pending = max(4, 2 * pool_workers())
        # per-rendition reorder buffer: workers complete out of order,
        # fMP4 fragments must not
        self._next_submit = 0
        self._next_emit = 0
        self._ready: dict[int, AccessUnit] = {}

    def _transform(self, au: AccessUnit,
                   ps: tuple[bytes | None, bytes | None]) -> AccessUnit:
        # the depacketizer latches SPS/PPS out of band (they are config,
        # not sample data) — feed them to the requantizer when they change
        if ps != self._ps_fed:
            self._ps_fed = ps
            for n in ps:
                if n:
                    self.requant.transform_nal(n)
        return AccessUnit(au.timestamp,
                          [self.requant.transform_nal(n) for n in au.nals])

    def _on_unit(self, au: AccessUnit) -> None:
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        # parameter sets are captured at ENQUEUE time (loop thread): a
        # queued AU must be requantized against the PPS it was coded
        # with, not whatever a later packet latched
        ps = (self.depack.sps, self.depack.pps)
        if loop is None:
            # synchronous caller (tests, offline tools): transform inline
            super()._on_unit(self._transform(au, ps))
            return
        # gate on SUBMITTED-minus-EMITTED, not worker completions: a
        # straggler AU must stall admission too, or fast successors pile
        # up unboundedly in the reorder buffer behind it (added latency
        # then grows with the straggler, breaking the "degrade in frame
        # rate, never in latency" contract)
        if self.pending >= self._max_pending:
            self.shed += 1                 # backlogged: shed, stay live
            return
        # latch the sets on the loop thread and snapshot the PARSED
        # objects for the worker (requant_with is stateless)
        if ps != self._ps_fed:
            self._ps_fed = ps
            for n in ps:
                if n:
                    self.requant.transform_nal(n)
        sps, pps = self.requant.sps, self.requant.pps
        seq = self._next_submit
        self._next_submit += 1

        def work():
            try:
                deltas = []
                nals = []
                for n in au.nals:
                    out, d = self.requant.requant_with(n, sps, pps)
                    nals.append(out)
                    deltas.append(d)
                out_au = AccessUnit(au.timestamp, nals)
            except Exception:
                # never let a worker error strand the reorder slot (that
                # would shed every future AU forever); pass the unit
                # through — and none of its stats: partially-counted
                # work whose output was discarded must not drift
                # bytes_out away from emitted bytes
                out_au = au
                deltas = []
            loop.call_soon_threadsafe(self._emit, seq, out_au, deltas)

        _get_pool().submit(work)

    @property
    def pending(self) -> int:
        """Submitted-but-not-yet-emitted AUs (in workers OR waiting in
        the reorder buffer) — the admission gate and test barrier."""
        return self._next_submit - self._next_emit

    def _emit(self, seq: int, au: AccessUnit, deltas) -> None:
        for d in deltas:
            self.requant.stats.merge(d)
        self._ready[seq] = au
        while self._next_emit in self._ready:
            super()._on_unit(self._ready.pop(self._next_emit))
            self._next_emit += 1
