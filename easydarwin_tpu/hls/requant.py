"""HLS bitrate rendition via transform-domain H.264 requantization.

``RequantHlsOutput`` is an ``HlsOutput`` whose access units pass through
``codecs.h264_requant.SliceRequantizer`` before muxing: a TRUE
lower-bitrate rendition at the SAME frame rate, next to the temporal
(frame-thinning) rungs (VERDICT r2 item 4).  The split mirrors the MJPEG
ladder: CAVLC entropy recode on the host, the per-level integer requant
batched on the device (``ops.transform.h264_requant``), differential-
tested bit-exact against the scalar oracle.

Parallel harness (VERDICT r3 item 1): ALL requant renditions share one
``ThreadPoolExecutor`` sized to the host's cores — the native CAVLC walk
is a ctypes call, so the GIL is released for its whole duration and
pictures genuinely run in parallel.  Order is preserved per rendition
without serializing it: consecutive AUs of the same rung pipeline
through different workers (each against snapshot parameter sets) and a
reorder buffer emits them in submission order — so ONE 1080p30 rung
scales across cores, not just many rungs across cores.  The reference
analogue is the short/blocking task-thread split
(``Task.cpp:120-146``); here the "blocking pool" is per-picture jobs.

Honest scope notes (also in ``codecs.h264_requant``): CAVLC baseline
intra slices only (I_4x4 + I_16x16, luma AND 4:2:0 chroma residuals);
anything else passes through unchanged and is counted, so the rendition
degrades toward the source bitrate rather than corrupting.  Requant is
open loop: drift is spatial-only and resets at every IDR — for
all-intra camera streams, every frame."""

from __future__ import annotations

import asyncio
import os
import threading
from concurrent.futures import ThreadPoolExecutor

from ..codecs.h264_requant import (SliceRequantizer, device_batch,
                                   device_batch_chroma)
from ..vod.depacketize import AccessUnit
from .segmenter import HlsOutput

#: one shared pool for ALL requant renditions, sized to the cores the
#: process may use: the native walk releases the GIL (ctypes), so jobs
#: from one OR many renditions run truly concurrently; the pure-Python
#: fallback path still benefits from staying off the event loop
_pool: ThreadPoolExecutor | None = None
_workers_cache: int | None = None


def widen_affinity() -> None:
    """Undo a ONE-CORE pin on the calling thread.  The TPU runtime
    plugin pins the thread that initializes it (on the bench/server box:
    the main thread, at interpreter start via sitecustomize) to a single
    core; threads spawned afterwards inherit that one-core mask, which
    is how a 2-core host ran the whole requant pool on one CPU
    (``workers=1``, ``parallel == serial`` in bench r04/r05).

    Deliberately narrow: only the exact one-core signature is widened,
    so an operator's multi-core confinement (``taskset -c 0,1``) is
    preserved; the kernel intersects the widened mask with the cpuset,
    so a cpuset quota is never escaped either.  What this CANNOT see is
    a pure bandwidth quota (cgroup ``cpu.max`` on a big node) — size the
    pool explicitly with ``EDTPU_REQUANT_WORKERS`` there (the override
    also disables widening entirely)."""
    if os.environ.get("EDTPU_REQUANT_WORKERS"):
        return
    try:
        if len(os.sched_getaffinity(0)) == 1 and (os.cpu_count() or 1) > 1:
            os.sched_setaffinity(0, range(os.cpu_count() or 1))
    except (AttributeError, OSError, ValueError):
        pass


def pool_workers() -> int:
    """Worker count for the shared requant pool: the number of CPUs the
    cgroup actually allows, measured from a throwaway thread that first
    widens its own affinity — so a runtime-pinned importing thread can
    no longer collapse the pool to 1.  ``EDTPU_REQUANT_WORKERS``
    overrides (sizing experiments / CI determinism).  Memoized: the
    cgroup quota doesn't move at runtime."""
    global _workers_cache
    env = os.environ.get("EDTPU_REQUANT_WORKERS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    if _workers_cache is not None:
        return _workers_cache
    box: list[int] = []

    def probe() -> None:
        widen_affinity()
        try:
            box.append(len(os.sched_getaffinity(0)))
        except (AttributeError, OSError):
            box.append(os.cpu_count() or 1)

    t = threading.Thread(target=probe, name="hls-requant-probe")
    t.start()
    t.join()
    _workers_cache = max(1, box[0] if box else 1)
    return _workers_cache


def _get_pool() -> ThreadPoolExecutor:
    global _pool
    if _pool is None:
        # initializer: each worker un-inherits the importing thread's
        # one-core pin, or the sized pool still stacks on a single CPU
        _pool = ThreadPoolExecutor(max_workers=pool_workers(),
                                   thread_name_prefix="hls-requant",
                                   initializer=widen_affinity)
    return _pool


class RequantHlsOutput(HlsOutput):
    def __init__(self, delta_qp: int, *, use_device: bool = True, **kw):
        super().__init__(**kw)
        from .. import native as native_mod
        if native_mod.available():
            # the native CAVLC walk (~100x the Python path) is the
            # production engine; it embeds the same exact level shift
            # and the chroma identity/shift/round-trip dispatch
            fn = cfn = None
        else:
            fn = device_batch if use_device else None
            cfn = device_batch_chroma if use_device else None
        self.requant = SliceRequantizer(delta_qp, requant_fn=fn,
                                        chroma_fn=cfn)
        self.delta_qp = delta_qp
        self._ps_fed: tuple[bytes | None, bytes | None] = (None, None)
        #: AUs dropped because the pipeline was too far behind — shedding
        #: keeps the rendition live instead of ever-later.  Depth 2x the
        #: pool keeps every core fed while bounding added latency to
        #: ~2 pictures' work
        self.shed = 0
        self._max_pending = max(4, 2 * pool_workers())
        # per-rendition reorder buffer: workers complete out of order,
        # fMP4 fragments must not
        self._next_submit = 0
        self._next_emit = 0
        self._ready: dict[int, AccessUnit] = {}

    def _transform(self, au: AccessUnit,
                   ps: tuple[bytes | None, bytes | None]) -> AccessUnit:
        # the depacketizer latches SPS/PPS out of band (they are config,
        # not sample data) — feed them to the requantizer when they change
        if ps != self._ps_fed:
            self._ps_fed = ps
            for n in ps:
                if n:
                    self.requant.transform_nal(n)
        return AccessUnit(au.timestamp,
                          [self.requant.transform_nal(n) for n in au.nals])

    def _on_unit(self, au: AccessUnit) -> None:
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        # parameter sets are captured at ENQUEUE time (loop thread): a
        # queued AU must be requantized against the PPS it was coded
        # with, not whatever a later packet latched
        ps = (self.depack.sps, self.depack.pps)
        if loop is None:
            # synchronous caller (tests, offline tools): transform inline
            super()._on_unit(self._transform(au, ps))
            return
        # gate on SUBMITTED-minus-EMITTED, not worker completions: a
        # straggler AU must stall admission too, or fast successors pile
        # up unboundedly in the reorder buffer behind it (added latency
        # then grows with the straggler, breaking the "degrade in frame
        # rate, never in latency" contract)
        if self.pending >= self._max_pending:
            self.shed += 1                 # backlogged: shed, stay live
            return
        # latch the sets on the loop thread and snapshot the PARSED
        # objects for the worker (requant_with is stateless)
        if ps != self._ps_fed:
            self._ps_fed = ps
            for n in ps:
                if n:
                    self.requant.transform_nal(n)
        sps, pps = self.requant.sps, self.requant.pps
        seq = self._next_submit
        self._next_submit += 1

        def work():
            try:
                deltas = []
                nals = []
                for n in au.nals:
                    out, d = self.requant.requant_with(n, sps, pps)
                    nals.append(out)
                    deltas.append(d)
                out_au = AccessUnit(au.timestamp, nals)
            except Exception:
                # never let a worker error strand the reorder slot (that
                # would shed every future AU forever); pass the unit
                # through — and none of its stats: partially-counted
                # work whose output was discarded must not drift
                # bytes_out away from emitted bytes
                out_au = au
                deltas = []
            loop.call_soon_threadsafe(self._emit, seq, out_au, deltas)

        _get_pool().submit(work)

    @property
    def pending(self) -> int:
        """Submitted-but-not-yet-emitted AUs (in workers OR waiting in
        the reorder buffer) — the admission gate and test barrier."""
        return self._next_submit - self._next_emit

    def _emit(self, seq: int, au: AccessUnit, deltas) -> None:
        for d in deltas:
            self.requant.stats.merge(d)
        self._ready[seq] = au
        while self._next_emit in self._ready:
            super()._on_unit(self._ready.pop(self._next_emit))
            self._next_emit += 1
