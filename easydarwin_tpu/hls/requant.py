"""HLS bitrate rendition via transform-domain H.264 requantization.

``RequantHlsOutput`` is an ``HlsOutput`` whose access units pass through
``codecs.h264_requant.SliceRequantizer`` before muxing: a TRUE
lower-bitrate rendition at the SAME frame rate, next to the temporal
(frame-thinning) rungs (VERDICT r2 item 4).  The split mirrors the MJPEG
ladder: CAVLC entropy recode on the host, the per-level integer requant
batched on the device (``ops.transform.h264_requant``), differential-
tested bit-exact against the scalar oracle.

Parallel harness (VERDICT r3 item 1): ALL requant renditions share one
``ThreadPoolExecutor`` sized to the host's cores — the native CAVLC walk
is a ctypes call, so the GIL is released for its whole duration and
pictures genuinely run in parallel.  Order is preserved per rendition
without serializing it: consecutive AUs of the same rung pipeline
through different workers (each against snapshot parameter sets) and a
reorder buffer emits them in submission order — so ONE 1080p30 rung
scales across cores, not just many rungs across cores.  The reference
analogue is the short/blocking task-thread split
(``Task.cpp:120-146``); here the "blocking pool" is per-picture jobs.

Honest scope notes (also in ``codecs.h264_requant``): CAVLC baseline
intra slices only (I_4x4 + I_16x16, luma AND 4:2:0 chroma residuals);
anything else passes through unchanged and is counted, so the rendition
degrades toward the source bitrate rather than corrupting.  Requant is
open loop: drift is spatial-only and resets at every IDR — for
all-intra camera streams, every frame."""

from __future__ import annotations

import asyncio
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from ..codecs.h264_requant import (FusedRequantDispatch, RequantStats,
                                   SliceRequantizer, device_batch,
                                   device_batch_chroma, gather_slice,
                                   parse_slice_nal, recode_parsed)
from ..obs import (REQUANT_AUS, REQUANT_REASSEMBLY_MISMATCH,
                   REQUANT_RENDITIONS, REQUANT_SHED, REQUANT_SLICES,
                   REQUANT_STAGE_SECONDS)
from ..relay.output import RelayOutput, WriteResult
from ..vod.depacketize import AccessUnit
from .segmenter import HlsOutput

#: the CLOSED requant-pipeline stage vocabulary behind
#: ``requant_stage_seconds{stage}`` (tools/metrics_lint.py rejects any
#: observed child outside it): ``parse`` = shared entropy decode of a
#: slice, ``entropy`` = the fused native walk (serial CAVLC/CABAC state
#: machines, decode+recode in one pass), ``transform_device`` = the
#: fused device requant dispatch + harvest for every (slice, rendition)
#: of an AU, ``recode`` = one rendition's serial entropy re-encode over
#: the shared parse, ``reassemble`` = the ordered per-AU emit.
REQUANT_STAGES = ("parse", "entropy", "transform_device", "recode",
                  "reassemble")


def _stage(stage: str, t0: float) -> None:
    REQUANT_STAGE_SECONDS.observe(time.perf_counter() - t0, stage=stage)

#: one shared pool for ALL requant renditions, sized to the cores the
#: process may use: the native walk releases the GIL (ctypes), so jobs
#: from one OR many renditions run truly concurrently; the pure-Python
#: fallback path still benefits from staying off the event loop
_pool: ThreadPoolExecutor | None = None
_sizing_cache: dict | None = None


def widen_affinity() -> None:
    """Undo a ONE-CORE pin on the calling thread.  The TPU runtime
    plugin pins the thread that initializes it (on the bench/server box:
    the main thread, at interpreter start via sitecustomize) to a single
    core; threads spawned afterwards inherit that one-core mask, which
    is how a 2-core host ran the whole requant pool on one CPU
    (``workers=1``, ``parallel == serial`` in bench r04/r05).

    Deliberately narrow: only the exact one-core signature is widened,
    so an operator's multi-core confinement (``taskset -c 0,1``) is
    preserved; the kernel intersects the widened mask with the cpuset,
    so a cpuset quota is never escaped either.  What this CANNOT see is
    a pure bandwidth quota (cgroup ``cpu.max`` on a big node) — size the
    pool explicitly with ``EDTPU_REQUANT_WORKERS`` there (the override
    also disables widening entirely)."""
    if os.environ.get("EDTPU_REQUANT_WORKERS"):
        return
    try:
        if len(os.sched_getaffinity(0)) == 1 and (os.cpu_count() or 1) > 1:
            os.sched_setaffinity(0, range(os.cpu_count() or 1))
    except (AttributeError, OSError, ValueError):
        pass


def _own_cgroup_path(proc_cgroup: str, controller: str | None) -> str:
    """This process's cgroup path for ``controller`` (None = the v2
    unified hierarchy) from ``/proc/self/cgroup`` — the effective quota
    lives in OUR cgroup, not the root (a systemd CPUQuota= service sits
    in system.slice/<svc> where the root's cpu.max reads 'max')."""
    try:
        with open(proc_cgroup, encoding="ascii") as f:
            for ln in f:
                parts = ln.strip().split(":", 2)
                if len(parts) != 3:
                    continue
                if controller is None and parts[0] == "0":
                    return parts[2]
                if controller is not None and \
                        controller in parts[1].split(","):
                    return parts[2]
    except OSError:
        pass
    return ""


def _cgroup_quota_cpus(proc_cgroup: str = "/proc/self/cgroup",
                       fs_root: str = "/sys/fs/cgroup") -> float | None:
    """CPU-equivalents allowed by the cgroup's *bandwidth* quota (the
    signal affinity masks cannot see): cgroup v2 ``cpu.max`` or v1
    ``cpu.cfs_quota_us``/``cpu.cfs_period_us``, read from THIS
    process's cgroup and every ancestor up to the root — the effective
    limit is the minimum along the chain.  None = no quota anywhere
    (or not on Linux/cgroups)."""
    best: float | None = None

    def note(v: float) -> None:
        nonlocal best
        best = v if best is None else min(best, v)

    def walk(root: str, rel: str, read) -> None:
        node = root + rel if rel and rel != "/" else root
        while True:
            v = read(node)
            if v is not None:
                note(v)
            if node == root or not node.startswith(root):
                break
            node = os.path.dirname(node)

    def read_v2(node: str) -> float | None:
        try:
            with open(node + "/cpu.max", encoding="ascii") as f:
                quota, _, period = f.read().strip().partition(" ")
            if quota != "max" and float(period) > 0:
                return float(quota) / float(period)
        except (OSError, ValueError):
            pass
        return None

    def read_v1(node: str) -> float | None:
        try:
            with open(node + "/cpu.cfs_quota_us", encoding="ascii") as f:
                quota = float(f.read().strip())
            with open(node + "/cpu.cfs_period_us", encoding="ascii") as f:
                period = float(f.read().strip())
            if quota > 0 and period > 0:
                return quota / period
        except (OSError, ValueError):
            pass
        return None

    walk(fs_root, _own_cgroup_path(proc_cgroup, None), read_v2)
    walk(fs_root + "/cpu", _own_cgroup_path(proc_cgroup, "cpu"), read_v1)
    return best


def _probe_affinity() -> int:
    """CPUs visible to a fresh thread that first widens its own affinity
    (un-inheriting the TPU runtime's one-core main-thread pin)."""
    box: list[int] = []

    def probe() -> None:
        widen_affinity()
        try:
            box.append(len(os.sched_getaffinity(0)))
        except (AttributeError, OSError):
            box.append(os.cpu_count() or 1)

    t = threading.Thread(target=probe, name="hls-requant-probe")
    t.start()
    t.join()
    return max(1, box[0] if box else 1)


def pool_sizing(*, affinity: int | None = None,
                quota: float | None = None,
                cpu_count: int | None = None,
                env: str | None = None) -> dict:
    """Worker count for the shared requant pool PLUS the rationale —
    which signal won and what every signal read — surfaced into the
    bench JSON ``extra`` so a wrong sizing is diagnosable from the
    trajectory alone (BENCH_r05 shipped ``workers: 1`` with nothing to
    say why).

    Signals, in precedence order:

    * ``EDTPU_REQUANT_WORKERS`` — explicit operator override;
    * the **affinity probe** (widened throwaway thread) — the CPUs the
      scheduler will actually run our threads on;
    * the **cgroup bandwidth quota** (``cpu.max`` / cfs_quota) — the
      signal the affinity mask cannot see.  Two regressions it fixes:
      the bench-box case where the probe collapses to 1 (the runtime's
      one-core pin survives because ``sched_setaffinity`` is denied in
      the container) while the quota provisions several CPUs — trust
      the quota, the per-worker initializer still retries the widen;
      and the big-node case where affinity says 96 but ``cpu.max``
      caps at 2 — sizing to 96 just trades throughput for preemption
      thrash, so the quota caps the pool.

    Keyword arguments override the probed signals (tests); the no-
    argument call is memoized — none of these signals move at runtime."""
    global _sizing_cache
    injected = (affinity is not None or quota is not None
                or cpu_count is not None or env is not None)
    if not injected and _sizing_cache is not None:
        return _sizing_cache
    env = os.environ.get("EDTPU_REQUANT_WORKERS") if env is None else env
    if env:
        try:
            sizing = {"workers": max(1, int(env)), "source": "env",
                      "affinity_cpus": None, "quota_cpus": None,
                      "cpu_count": os.cpu_count() or 1}
            if not injected:
                _sizing_cache = sizing
            return sizing
        except ValueError:
            pass
    ncpu = cpu_count if cpu_count is not None else (os.cpu_count() or 1)
    aff = affinity if affinity is not None else _probe_affinity()
    q = quota if quota is not None else _cgroup_quota_cpus()
    q_cpus = max(1, int(q)) if q is not None and q >= 1 else \
        (1 if q is not None else None)
    if aff <= 1 and q_cpus is not None and q_cpus > 1:
        workers, source = min(q_cpus, ncpu), "cpu_max_quota"
    elif q_cpus is not None and q_cpus < aff:
        workers, source = q_cpus, "cpu_max_cap"
    else:
        workers, source = aff, "affinity"
    sizing = {"workers": max(1, workers), "source": source,
              "affinity_cpus": aff,
              "quota_cpus": round(q, 2) if q is not None else None,
              "cpu_count": ncpu}
    if not injected:
        _sizing_cache = sizing
    return sizing


def pool_workers() -> int:
    """Worker count for the shared requant pool (see ``pool_sizing``
    for the decision rationale)."""
    return pool_sizing()["workers"]


def _get_pool() -> ThreadPoolExecutor:
    global _pool
    if _pool is None:
        # initializer: each worker un-inherits the importing thread's
        # one-core pin, or the sized pool still stacks on a single CPU
        _pool = ThreadPoolExecutor(max_workers=pool_workers(),
                                   thread_name_prefix="hls-requant",
                                   initializer=widen_affinity)
    return _pool


class RequantHlsOutput(HlsOutput):
    def __init__(self, delta_qp: int, *, use_device: bool = True, **kw):
        super().__init__(**kw)
        from .. import native as native_mod
        if native_mod.available():
            # the native CAVLC walk (~100x the Python path) is the
            # production engine; it embeds the same exact level shift
            # and the chroma identity/shift/round-trip dispatch
            fn = cfn = None
        else:
            fn = device_batch if use_device else None
            cfn = device_batch_chroma if use_device else None
        self.requant = SliceRequantizer(delta_qp, requant_fn=fn,
                                        chroma_fn=cfn)
        self.delta_qp = delta_qp
        self._ps_fed: tuple[bytes | None, bytes | None] = (None, None)
        #: AUs dropped because the pipeline was too far behind — shedding
        #: keeps the rendition live instead of ever-later.  Depth 2x the
        #: pool keeps every core fed while bounding added latency to
        #: ~2 pictures' work
        self.shed = 0
        self._max_pending = max(4, 2 * pool_workers())
        # per-rendition reorder buffer: workers complete out of order,
        # fMP4 fragments must not
        self._next_submit = 0
        self._next_emit = 0
        self._ready: dict[int, AccessUnit] = {}

    def _transform(self, au: AccessUnit,
                   ps: tuple[bytes | None, bytes | None]) -> AccessUnit:
        # the depacketizer latches SPS/PPS out of band (they are config,
        # not sample data) — feed them to the requantizer when they change
        if ps != self._ps_fed:
            self._ps_fed = ps
            for n in ps:
                if n:
                    self.requant.transform_nal(n)
        return AccessUnit(au.timestamp,
                          [self.requant.transform_nal(n) for n in au.nals])

    def _on_unit(self, au: AccessUnit) -> None:
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        # parameter sets are captured at ENQUEUE time (loop thread): a
        # queued AU must be requantized against the PPS it was coded
        # with, not whatever a later packet latched
        ps = (self.depack.sps, self.depack.pps)
        if loop is None:
            # synchronous caller (tests, offline tools): transform inline
            super()._on_unit(self._transform(au, ps))
            return
        # gate on SUBMITTED-minus-EMITTED, not worker completions: a
        # straggler AU must stall admission too, or fast successors pile
        # up unboundedly in the reorder buffer behind it (added latency
        # then grows with the straggler, breaking the "degrade in frame
        # rate, never in latency" contract)
        if self.pending >= self._max_pending:
            self.shed += 1                 # backlogged: shed, stay live
            from ..obs.ledger import LEDGER
            LEDGER.defer("hls_requant")
            return
        # latch the sets on the loop thread and snapshot the PARSED
        # objects for the worker (requant_with is stateless)
        if ps != self._ps_fed:
            self._ps_fed = ps
            for n in ps:
                if n:
                    self.requant.transform_nal(n)
        sps, pps = self.requant.sps, self.requant.pps
        seq = self._next_submit
        self._next_submit += 1

        def work():
            try:
                deltas = []
                nals = []
                for n in au.nals:
                    out, d = self.requant.requant_with(n, sps, pps)
                    nals.append(out)
                    deltas.append(d)
                out_au = AccessUnit(au.timestamp, nals)
            except Exception:
                # never let a worker error strand the reorder slot (that
                # would shed every future AU forever); pass the unit
                # through — and none of its stats: partially-counted
                # work whose output was discarded must not drift
                # bytes_out away from emitted bytes
                out_au = au
                deltas = []
            loop.call_soon_threadsafe(self._emit, seq, out_au, deltas)

        _get_pool().submit(work)

    @property
    def pending(self) -> int:
        """Submitted-but-not-yet-emitted AUs (in workers OR waiting in
        the reorder buffer) — the admission gate and test barrier."""
        return self._next_submit - self._next_emit

    def _emit(self, seq: int, au: AccessUnit, deltas) -> None:
        for d in deltas:
            self.requant.stats.merge(d)
        self._ready[seq] = au
        while self._next_emit in self._ready:
            super()._on_unit(self._ready.pop(self._next_emit))
            self._next_emit += 1


# ========================================================== the ABR ladder
# ISSUE 9 tentpole: one shared-parse, slice-parallel, device-overlapped
# pipeline feeding EVERY q-rung rendition of a source.
#
#   AU ──► slice NALs ──► [parse ×S across the pool]          (Python path)
#            │                    │
#            │                    └► ONE FusedRequantDispatch (S slices ×
#            │                       N renditions, async device) ──►
#            │                       [recode ×S×N across the pool]
#            │
#            └──────────► [native walk ×S×N across the pool]  (native path)
#                                 │
#                    ordered per-AU reassembly ──► rendition muxers
#
# The native engine keeps its fused decode+requant+recode walk (two
# orders faster than the Python slice walk, so N independent walks beat
# one shared Python parse at any ladder width) — its ladder lever is the
# slice × rendition fan-out across the pool.  The Python engines (device
# or scalar transform) parse each slice ONCE and recode N times, with
# all (slice, rendition) transform rows batched into a single device
# dispatch per AU, double-buffered: the JAX dispatch is asynchronous and
# admission allows ~2×workers AUs in flight, so the device computes AU
# k's rows while the pool entropy-decodes AU k+1 (the PR 4 staging
# pattern).  A single-slice, single-rendition AU degenerates to exactly
# the serial ``SliceRequantizer`` path — bit-identity is pinned by
# tests/test_requant_ladder.py.


class LadderRendition(HlsOutput):
    """One rung's CMAF muxer: fed already-requantized AUs by its ladder
    (never raw packets — ``send_bytes`` on a rendition is a wiring bug).
    Keeps the ``.requant`` / ``.shed`` surface the admin/soak layers
    read on q-rung outputs."""

    def __init__(self, ladder: "RequantLadder", delta_qp: int,
                 engine: SliceRequantizer, **kw):
        super().__init__(**kw)
        self._ladder = ladder
        self.delta_qp = delta_qp
        #: the per-rendition stats container (and serial engine config);
        #: worker deltas merge into ``requant.stats`` once per AU
        self.requant = engine
        #: share the ladder's depacketizer so the init segment sees the
        #: source SPS/PPS (requant never rewrites parameter sets)
        self.depack = ladder.depack

    def send_bytes(self, data: bytes, *, is_rtcp: bool):
        raise RuntimeError("ladder renditions are fed AUs by the "
                           "ladder, not packets")

    @property
    def shed(self) -> int:
        """AUs shed at ladder admission (sheds apply to every rendition
        of the ladder together — degrade in frame rate, never latency)."""
        return self._ladder.shed

    @property
    def pending(self) -> int:
        return self._ladder.pending


class _AuJob:
    """Bookkeeping for one AU in flight through the ladder pool: per-
    rendition output slots (slice-ordered), per-worker stats deltas, and
    the outstanding-unit counter that triggers reassembly."""

    __slots__ = ("seq", "au", "deltas", "sps", "pps", "slice_idx",
                 "outs", "stats", "remaining", "lock", "parsed",
                 "mismatch")

    def __init__(self, seq: int, au: AccessUnit, deltas, sps, pps):
        self.seq = seq
        self.au = au
        self.deltas = deltas
        self.sps = sps
        self.pps = pps
        self.slice_idx = [i for i, n in enumerate(au.nals)
                          if n and (n[0] & 0x1F) in (1, 5)
                          and sps is not None and pps is not None]
        # non-slice NALs ride through in place; slice slots start EMPTY
        # so the reassembly check catches a genuinely lost unit instead
        # of silently emitting the source slice
        slice_set = set(self.slice_idx)
        self.outs = {d: [None if i in slice_set else n
                         for i, n in enumerate(au.nals)]
                     for d in deltas}
        self.stats = {d: [] for d in deltas}
        self.remaining = 0
        self.lock = threading.Lock()
        self.parsed = {}                # slice pos -> (ParsedSlice, gather)
        self.mismatch = False


class RequantLadder(RelayOutput):
    """The multi-rendition transform-domain requant pipeline: ONE relay
    sink per published path that depacketizes once, requantizes each AU
    to every rung of its ladder through the shared worker pool, and
    feeds the per-rendition muxers in source order."""

    def __init__(self, *, use_device: bool = True,
                 target_duration: float = 2.0, window: int = 6,
                 audio=None):
        super().__init__(ssrc=0x415)
        # identity rewrite, same as HlsOutput: every rendition keeps the
        # SOURCE timestamps so ABR switching never jumps in time
        self.rewrite.base_src_seq = 0
        self.rewrite.base_src_ts = 0
        self.rewrite.out_seq_start = 0
        self.rewrite.out_ts_start = 0
        from ..vod.depacketize import H264Depacketizer
        self.depack = H264Depacketizer()
        self.target_duration = target_duration
        self.window = window
        self.audio = audio
        from .. import native as native_mod
        self._use_native = native_mod.available()
        self._use_device = bool(use_device) and not self._use_native
        self._fn = None if self._use_native else \
            (device_batch if use_device else None)
        self._cfn = None if self._use_native else \
            (device_batch_chroma if use_device else None)
        self.renditions: dict[int, LadderRendition] = {}
        self._sps = None
        self._pps = None
        self._sps_raw: bytes | None = None
        self._pps_raw: bytes | None = None
        self.shed = 0
        self._max_pending = max(4, 2 * pool_workers())
        self._next_submit = 0
        self._next_emit = 0
        self._ready: dict[int, _AuJob] = {}

    # -- ladder membership -------------------------------------------------
    def add_rendition(self, delta_qp: int) -> LadderRendition:
        """Get-or-create the rung at ``delta_qp`` (multiples of 6, the
        exact-shift window — SliceRequantizer validates)."""
        out = self.renditions.get(delta_qp)
        if out is None:
            engine = SliceRequantizer(delta_qp, requant_fn=self._fn,
                                      chroma_fn=self._cfn)
            out = LadderRendition(self, delta_qp, engine,
                                  target_duration=self.target_duration,
                                  window=self.window, audio=self.audio)
            self.renditions[delta_qp] = out
        return out

    @property
    def pending(self) -> int:
        """Submitted-but-not-yet-emitted AUs (in workers OR waiting in
        the reorder buffer) — the admission gate and test barrier."""
        return self._next_submit - self._next_emit

    # -- ingest ------------------------------------------------------------
    def send_bytes(self, data: bytes, *, is_rtcp: bool) -> WriteResult:
        if is_rtcp:
            return WriteResult.OK
        self.depack.push(data)
        units = self.depack.pop_units()
        if not units:
            return WriteResult.OK
        # wake-ledger unit (ISSUE 16): AU admission runs nested inside
        # the pump's live-relay pass — bracketing it here (per completed
        # AU, never per packet) lets the ledger subtract it from
        # live_relay and charge the requant class with its own service
        from ..obs.ledger import LEDGER
        tok = LEDGER.unit_start()
        for au in units:
            self._on_unit(au)
        LEDGER.unit_end(tok, "hls_requant", items=len(units))
        return WriteResult.OK

    def _latch_ps(self, au: AccessUnit) -> None:
        """Latch SPS/PPS at AU granularity on the ingest thread: the
        depacketizer's out-of-band sets plus any in-band sets riding the
        AU (parameter sets are config, not sample data — conformant
        senders place them before the slices they govern)."""
        from ..codecs.h264_intra import Pps, Sps
        cands = [self.depack.sps, self.depack.pps]
        cands += [n for n in au.nals if n and (n[0] & 0x1F) in (7, 8)]
        for n in cands:
            if not n:
                continue
            t = n[0] & 0x1F
            try:
                if t == 7 and n != self._sps_raw:
                    self._sps, self._sps_raw = Sps.parse(n), n
                elif t == 8 and n != self._pps_raw:
                    self._pps, self._pps_raw = Pps.parse(n), n
            except (ValueError, EOFError, IndexError):
                if t == 7:
                    self._sps = self._sps_raw = None
                else:
                    self._pps = self._pps_raw = None

    def _on_unit(self, au: AccessUnit) -> None:
        if not self.renditions:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        self._latch_ps(au)
        deltas = tuple(sorted(self.renditions))
        job = None
        if loop is None:
            # synchronous caller (tests, offline tools): run the SAME
            # pipeline inline — sync and pooled output are byte-identical
            job = _AuJob(self._next_submit, au, deltas, self._sps,
                         self._pps)
            self._next_submit += 1
            self._run_job_inline(job)
            self._emit(job)
            return
        if self.pending >= self._max_pending:
            self.shed += 1               # backlogged: shed, stay live
            REQUANT_SHED.inc()
            from ..obs.ledger import LEDGER
            LEDGER.defer("hls_requant")
            return
        job = _AuJob(self._next_submit, au, deltas, self._sps, self._pps)
        self._next_submit += 1
        if not job.slice_idx:
            self._emit(job)              # SEI/PS-only AU: nothing to do,
            return                       # but it keeps its emit slot
        pool = _get_pool()
        if self._use_native:
            # unit granularity adapts to the pool: when the SLICES alone
            # already saturate the workers, one unit per slice (looping
            # the renditions) avoids paying submit/lock overhead for
            # parallelism the pool cannot add; a few-slice AU on a wide
            # pool keeps the full (slice x rendition) fan-out so every
            # worker engages
            if len(job.slice_idx) >= pool_workers():
                job.remaining = len(job.slice_idx)
                for pos in job.slice_idx:
                    pool.submit(self._native_unit, loop, job, pos,
                                deltas)
            else:
                job.remaining = len(job.slice_idx) * len(deltas)
                for pos in job.slice_idx:
                    for d in deltas:
                        pool.submit(self._native_unit, loop, job, pos,
                                    (d,))
        else:
            job.remaining = len(job.slice_idx)
            for pos in job.slice_idx:
                pool.submit(self._parse_unit, loop, job, pos)

    # -- worker units ------------------------------------------------------
    # Every unit takes ``loop``: the pooled path passes the event loop
    # (completion notifies it thread-safely); the synchronous inline
    # path passes None and the caller emits after the last unit — ONE
    # implementation, so sync and pooled can never drift apart.
    def _complete_unit(self, loop, job: _AuJob) -> None:
        with job.lock:
            job.remaining -= 1
            done = job.remaining == 0
        if done and loop is not None:
            loop.call_soon_threadsafe(self._emit, job)

    def _native_unit(self, loop, job: _AuJob, pos: int,
                     unit_deltas: "tuple[int, ...]") -> None:
        """One slice through the fused native walk (the serial entropy
        state machines, decode+requant+recode in one pass) for one or
        more renditions — the slice × rendition fan-out IS the native
        ladder lever."""
        nal = job.au.nals[pos]
        for delta in unit_deltas:
            engine = self.renditions[delta].requant
            try:
                t0 = time.perf_counter()
                out, d = engine.requant_with(nal, job.sps, job.pps)
                _stage("entropy", t0)
            except Exception:
                out = nal                # never strand the slot — and
                d = RequantStats()       # count the pass-through, or
                d.bytes_in += len(nal)   # bytes_out drifts away from
                d.bytes_out += len(nal)  # the bytes actually emitted
                d.slices_passed_through += 1
            with job.lock:
                job.outs[delta][pos] = out
                job.stats[delta].append(d)
        REQUANT_SLICES.inc(len(unit_deltas))
        self._complete_unit(loop, job)

    def _parse_unit(self, loop, job: _AuJob, pos: int) -> None:
        """Shared parse of one slice (Python engines): entropy-decode
        ONCE for the whole rendition ladder.  The worker that finishes
        the AU's last parse runs the fused dispatch inline and fans the
        per-(slice, rendition) recodes back across the pool."""
        nal = job.au.nals[pos]
        parsed = None
        try:
            t0 = time.perf_counter()
            p = parse_slice_nal(nal, job.sps, job.pps)
            parsed = (p, gather_slice(p))
            _stage("parse", t0)
        except Exception:
            parsed = None                # out of scope: pass through
        with job.lock:
            if parsed is not None:
                job.parsed[pos] = parsed
            job.remaining -= 1
            last = job.remaining == 0    # this was the AU's final parse
        if last:
            self._dispatch_unit(loop, job)

    def _dispatch_unit(self, loop, job: _AuJob) -> None:
        """The AU's single fused transform dispatch (slices × renditions
        in one call; asynchronous on the device path, so device time
        hides behind the NEXT AU's parses on other workers), then the
        recode fan-out."""
        order = sorted(job.parsed)
        failed = [pos for pos in job.slice_idx if pos not in job.parsed]
        dispatch = None
        if order:
            try:
                t0 = time.perf_counter()
                dispatch = FusedRequantDispatch(
                    [job.parsed[pos][1] for pos in order],
                    job.deltas, requant_fn=self._fn, chroma_fn=self._cfn,
                    chroma_qp_offset=job.pps.chroma_qp_offset,
                    use_device=self._use_device)
                dispatch._harvested()    # device wait lands here, not in
                _stage("transform_device", t0)   # a recode bracket
            except Exception:
                dispatch = None
                failed = list(job.slice_idx)
                order = []
        for pos in failed:
            d = RequantStats()
            d.bytes_in += len(job.au.nals[pos])
            d.slices_passed_through += 1
            d.bytes_out += len(job.au.nals[pos])
            with job.lock:
                for delta in job.deltas:
                    job.outs[delta][pos] = job.au.nals[pos]
                    job.stats[delta].append(
                        d if delta == job.deltas[0] else _copy_delta(d))
        REQUANT_SLICES.inc(len(failed) * len(job.deltas))
        if not order:
            if loop is not None:
                loop.call_soon_threadsafe(self._emit, job)
            return
        with job.lock:
            # swap the exhausted parse budget for the recode budget: one
            # unit per (slice, rendition)
            job.remaining = len(order) * len(job.deltas)
        if loop is None:
            for s_i, pos in enumerate(order):
                for d_i, delta in enumerate(job.deltas):
                    self._recode_unit(None, job, dispatch, s_i, pos,
                                      d_i, delta)
            return
        pool = _get_pool()
        for s_i, pos in enumerate(order):
            for d_i, delta in enumerate(job.deltas):
                pool.submit(self._recode_unit, loop, job, dispatch,
                            s_i, pos, d_i, delta)

    def _recode_unit(self, loop, job: _AuJob, dispatch, s_i: int,
                     pos: int, d_i: int, delta: int) -> None:
        """One rendition's serial entropy re-encode of one slice over
        the shared parse."""
        nal = job.au.nals[pos]
        parsed, gather = job.parsed[pos]
        d = RequantStats()
        d.bytes_in += len(nal)
        try:
            t0 = time.perf_counter()
            out, n_blocks = recode_parsed(parsed, gather, dispatch,
                                          s_i, d_i)
            _stage("recode", t0)
            d.slices_requantized += 1
            d.blocks += n_blocks
        except Exception:
            out = nal
            d.slices_passed_through += 1
        d.bytes_out += len(out)
        with job.lock:
            job.outs[delta][pos] = out
            job.stats[delta].append(d)
        REQUANT_SLICES.inc()
        self._complete_unit(loop, job)

    # -- synchronous path --------------------------------------------------
    def _run_job_inline(self, job: _AuJob) -> None:
        """The pooled pipeline, single-threaded (no loop running): same
        primitives, same order, same bytes."""
        if not job.slice_idx:
            return
        if self._use_native:
            job.remaining = len(job.slice_idx)
            for pos in job.slice_idx:
                self._native_unit(None, job, pos, job.deltas)
            return
        job.remaining = len(job.slice_idx)
        for pos in job.slice_idx:
            self._parse_unit(None, job, pos)

    # -- reassembly --------------------------------------------------------
    def _emit(self, job: _AuJob) -> None:
        """Ordered per-AU reassembly (loop/caller thread): verify every
        slice slot, merge each rendition's worker deltas into its stats
        ONCE, and feed the muxers in source order."""
        t0 = time.perf_counter()
        for delta in job.deltas:
            if any(n is None for n in job.outs[delta]):
                # a pipeline bookkeeping bug, never silent corruption:
                # count it, pass the source AU through for this rung,
                # and drop its stats (output was discarded)
                job.mismatch = True
                job.outs[delta] = list(job.au.nals)
                job.stats[delta] = []
        if job.mismatch:
            REQUANT_REASSEMBLY_MISMATCH.inc()
        self._ready[job.seq] = job
        while self._next_emit in self._ready:
            j = self._ready.pop(self._next_emit)
            self._next_emit += 1
            REQUANT_AUS.inc()
            REQUANT_RENDITIONS.inc(len(j.deltas))
            for delta in j.deltas:
                out = self.renditions.get(delta)
                if out is None:
                    continue
                au_delta = RequantStats()
                for d in j.stats[delta]:
                    au_delta.merge(d)
                out.requant.stats.merge(au_delta)
                out._on_unit(AccessUnit(j.au.timestamp,
                                        j.outs[delta]))
        _stage("reassemble", t0)


def _copy_delta(d: RequantStats) -> RequantStats:
    c = RequantStats()
    c.merge(d)
    return c
