"""HLS output tier.

The reference only *referenced* an HLS module — EasyHLS was a closed
commercial SDK and no source ships (SURVEY §2.3) — so this is new code:
live relay → fMP4 (CMAF) segments + m3u8 playlists, attached to a relay
session as a ``RelayOutput`` sink (like the recorder) and served from the
service port (``/hls/<path>/index.m3u8``).
"""

from .segmenter import HlsOutput, HlsService  # noqa: F401
