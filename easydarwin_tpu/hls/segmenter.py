"""Live fMP4 HLS segmenter.

One ``HlsOutput`` per published stream: depacketizes the relayed H.264,
cuts segments on IDR boundaries near the target duration, and keeps a
sliding window of in-memory CMAF fragments:

* init segment — ``ftyp`` + ``moov`` (with ``mvex/trex``: sample tables
  live in the fragments),
* media segments — ``styp`` + ``moof`` (mfhd/tfhd/tfdt/trun) + ``mdat``,
* playlist — live sliding-window ``#EXT-X-MAP`` m3u8.

The transcode ladder (ops.transform) will fan one ingest into N
``HlsOutput``s at different rungs; this module is the mux/serve half.
"""

from __future__ import annotations

import struct
from collections import deque
from dataclasses import dataclass, field

from ..protocol.aac import AAC_SAMPLES_PER_FRAME, AacConfig
from ..relay.output import RelayOutput, WriteResult
from ..vod.depacketize import AccessUnit, H264Depacketizer
from ..vod.mp4_writer import box, full_box

VIDEO_CLOCK = 90000


def _esds(cfg: AacConfig) -> bytes:
    """MP4 elementary-stream descriptor for AAC-LC: ES_Descriptor →
    DecoderConfig (objectType 0x40 audio/ISO 14496-3, streamType 5) →
    DecoderSpecificInfo = AudioSpecificConfig from the SDP (or
    synthesized from rate/channels)."""
    asc = cfg.asc or cfg.default_asc()
    dsi = bytes((0x05, len(asc))) + asc
    dcd = bytes((0x04, 13 + len(dsi), 0x40, 0x15, 0, 0, 0)) + \
        struct.pack(">II", 128000, 128000) + dsi
    sl = bytes((0x06, 1, 0x02))
    es = bytes((0x03, 3 + len(dcd) + len(sl))) + \
        struct.pack(">HB", 2, 0) + dcd + sl
    return full_box(b"esds", 0, 0, es)


def _audio_trak(cfg: AacConfig) -> bytes:
    esds = _esds(cfg)
    entry = struct.pack(">I4s", 36 + len(esds), b"mp4a") + bytes(6) + \
        struct.pack(">H", 1) + bytes(8) + \
        struct.pack(">HHI", cfg.channels, 16, 0) + \
        struct.pack(">I", cfg.sample_rate << 16) + esds
    stsd = full_box(b"stsd", 0, 0, struct.pack(">I", 1), entry)
    stbl = box(b"stbl", stsd,
               full_box(b"stts", 0, 0, bytes(4)),
               full_box(b"stsc", 0, 0, bytes(4)),
               full_box(b"stsz", 0, 0, bytes(8)),
               full_box(b"stco", 0, 0, bytes(4)))
    url = full_box(b"url ", 0, 1)
    dinf = box(b"dinf", full_box(b"dref", 0, 0, struct.pack(">I", 1), url))
    minf = box(b"minf", full_box(b"smhd", 0, 0, bytes(4)), dinf, stbl)
    mdhd = full_box(b"mdhd", 0, 0,
                    struct.pack(">IIII", 0, 0, cfg.sample_rate, 0),
                    struct.pack(">HH", 0x55C4, 0))
    hdlr = full_box(b"hdlr", 0, 0, bytes(4), b"soun", bytes(12),
                    b"easydarwin-tpu\x00")
    mdia = box(b"mdia", mdhd, hdlr, minf)
    tkhd = full_box(b"tkhd", 0, 7, struct.pack(">IIIII", 0, 0, 2, 0, 0),
                    bytes(8), struct.pack(">hhhH", 0, 0, 0x0100, 0),
                    struct.pack(">9I", 0x10000, 0, 0, 0, 0x10000, 0, 0, 0,
                                0x40000000),
                    struct.pack(">II", 0, 0))
    return box(b"trak", tkhd, mdia)


def _init_segment(sps: bytes, pps: bytes,
                  audio: AacConfig | None = None) -> bytes:
    avcc = box(b"avcC",
               bytes((1, sps[1] if len(sps) > 1 else 66,
                      sps[2] if len(sps) > 2 else 0,
                      sps[3] if len(sps) > 3 else 30, 0xFF, 0xE1)),
               struct.pack(">H", len(sps)), sps, bytes((1,)),
               struct.pack(">H", len(pps)), pps)
    entry = struct.pack(">I4s", 86 + len(avcc), b"avc1") + bytes(6) + \
        struct.pack(">H", 1) + bytes(16) + struct.pack(">HH", 0, 0) + \
        struct.pack(">II", 0x00480000, 0x00480000) + bytes(4) + \
        struct.pack(">H", 1) + bytes(32) + struct.pack(">Hh", 0x18, -1) + avcc
    stsd = full_box(b"stsd", 0, 0, struct.pack(">I", 1), entry)
    stbl = box(b"stbl", stsd,
               full_box(b"stts", 0, 0, bytes(4)),
               full_box(b"stsc", 0, 0, bytes(4)),
               full_box(b"stsz", 0, 0, bytes(8)),
               full_box(b"stco", 0, 0, bytes(4)))
    url = full_box(b"url ", 0, 1)
    dinf = box(b"dinf", full_box(b"dref", 0, 0, struct.pack(">I", 1), url))
    minf = box(b"minf", full_box(b"vmhd", 0, 1, bytes(8)), dinf, stbl)
    mdhd = full_box(b"mdhd", 0, 0,
                    struct.pack(">IIII", 0, 0, VIDEO_CLOCK, 0),
                    struct.pack(">HH", 0x55C4, 0))
    hdlr = full_box(b"hdlr", 0, 0, bytes(4), b"vide", bytes(12),
                    b"easydarwin-tpu\x00")
    mdia = box(b"mdia", mdhd, hdlr, minf)
    tkhd = full_box(b"tkhd", 0, 7, struct.pack(">IIIII", 0, 0, 1, 0, 0),
                    bytes(8), struct.pack(">hhhH", 0, 0, 0, 0),
                    struct.pack(">9I", 0x10000, 0, 0, 0, 0x10000, 0, 0, 0,
                                0x40000000),
                    struct.pack(">II", 0, 0))
    trak = box(b"trak", tkhd, mdia)
    trexes = [full_box(b"trex", 0, 0,
                       struct.pack(">IIIII", 1, 1, 0, 0, 0))]
    traks = [trak]
    if audio is not None:
        traks.append(_audio_trak(audio))
        trexes.append(full_box(b"trex", 0, 0,
                               struct.pack(">IIIII", 2, 1, 0, 0, 0)))
    mvex = box(b"mvex", *trexes)
    mvhd = full_box(b"mvhd", 0, 0,
                    struct.pack(">IIII", 0, 0, VIDEO_CLOCK, 0),
                    struct.pack(">IH", 0x00010000, 0x0100), bytes(10),
                    struct.pack(">9I", 0x10000, 0, 0, 0, 0x10000, 0, 0, 0,
                                0x40000000), bytes(24),
                    struct.pack(">I", 3))
    return box(b"ftyp", b"iso6", struct.pack(">I", 0), b"iso6cmfc") + \
        box(b"moov", mvhd, *traks, mvex)


def _traf(track_id: int, base_dts: int,
          samples: list[tuple[bytes, int, bool]], data_offset: int
          ) -> bytes:
    tfhd = full_box(b"tfhd", 0, 0x020000,      # default-base-is-moof
                    struct.pack(">I", track_id))
    tfdt = full_box(b"tfdt", 1, 0, struct.pack(">Q", base_dts))
    flags = 0x000001 | 0x000100 | 0x000200 | 0x000400
    rows = b""
    for data, dur, sync in samples:
        sflags = 0x02000000 if sync else 0x01010000
        rows += struct.pack(">III", dur, len(data), sflags)
    trun = full_box(b"trun", 0, flags,
                    struct.pack(">Ii", len(samples), data_offset), rows)
    return box(b"traf", tfhd, tfdt, trun)


def _traf_len(n_samples: int) -> int:
    return 8 + 16 + 20 + (8 + 4 + 4 + 4 + 12 * n_samples)


def _media_segment(seq: int, base_dts: int,
                   samples: list[tuple[bytes, int, bool]],
                   audio_samples: list[tuple[bytes, int, bool]] = (),
                   audio_base_dts: int = 0) -> bytes:
    """samples: [(avcc_data, duration, is_sync)]; audio rides as a
    second traf (track 2) sharing the mdat, video bytes first."""
    video_bytes = b"".join(s[0] for s in samples)
    audio_bytes = b"".join(s[0] for s in audio_samples)
    mfhd = full_box(b"mfhd", 0, 0, struct.pack(">I", seq))
    moof_len = 8 + len(mfhd) + _traf_len(len(samples)) + \
        (_traf_len(len(audio_samples)) if audio_samples else 0)
    v_off = moof_len + 8
    trafs = [_traf(1, base_dts, samples, v_off)]
    if audio_samples:
        trafs.append(_traf(2, audio_base_dts, list(audio_samples),
                           v_off + len(video_bytes)))
    moof = box(b"moof", mfhd, *trafs)
    assert len(moof) == moof_len
    return box(b"styp", b"msdh", struct.pack(">I", 0), b"msdhmsix") + \
        moof + box(b"mdat", video_bytes + audio_bytes)


@dataclass
class Segment:
    seq: int
    duration_sec: float
    data: bytes


class HlsOutput(RelayOutput):
    """Relay sink producing a sliding window of CMAF segments."""

    def __init__(self, *, target_duration: float = 2.0, window: int = 6,
                 audio: AacConfig | None = None):
        super().__init__(ssrc=0x415)
        # identity rewrite: every rendition of one path keeps the SOURCE
        # timestamps, so variant timelines (tfdt) stay aligned and ABR
        # switching between rungs never jumps in presentation time
        self.rewrite.base_src_seq = 0
        self.rewrite.base_src_ts = 0
        self.rewrite.out_seq_start = 0
        self.rewrite.out_ts_start = 0
        self.target_duration = target_duration
        self.window = window
        self.depack = H264Depacketizer()
        self.init_segment: bytes | None = None
        self.segments: list[Segment] = []
        self.media_seq = 0            # seq of segments[0]
        self._pending: list[AccessUnit] = []
        self._seg_start_ts: int | None = None
        self._last_ts: int | None = None
        #: AAC track (None = video-only, the pre-round-4 shape).  Audio
        #: AUs ride UNCHANGED through every rendition — thinning and
        #: requant are video-axis transforms (VERDICT r3 item 4)
        self.audio = audio
        # deque: overflow shedding pops from the FRONT per AU, and
        # list.pop(0) is O(P) per shed (the VOD pacer deque fix shape)
        self._audio_pending: deque[tuple[bytes, int]] = deque()
        self._audio_dts = 0           # running tfdt, audio timescale
        self._audio_last_dur = AAC_SAMPLES_PER_FRAME
        self._audio_prev_ts: int | None = None
        self.audio_samples_muxed = 0
        self.audio_dropped = 0
        # rolling bitrate observation for the master playlist
        self._obs_bytes = 0
        self._obs_sec = 0.0
        # serving-side caches (ISSUE 14): the playlist text is rebuilt
        # only when a segment is cut/evicted (keyed by window identity),
        # and segment bodies are served by reference — the counters pin
        # the zero-per-request-copy property in the regression tests
        self._playlist_cache: tuple | None = None  # (key, base, text)
        self.playlist_builds = 0
        #: per-OUTPUT generation token baked into every ETag: media_seq
        #: and segment numbering restart from 0 on a server restart or
        #: stream re-publish, so counter-only tags would let a surviving
        #: player revalidate stale bytes with a false 304
        import secrets as _secrets
        self.etag_gen = _secrets.token_hex(4)

    def send_bytes(self, data: bytes, *, is_rtcp: bool) -> WriteResult:
        if is_rtcp:
            return WriteResult.OK
        self.depack.push(data)
        for au in self.depack.pop_units():
            self._on_unit(au)
        return WriteResult.OK

    def _on_unit(self, au: AccessUnit) -> None:
        if self.init_segment is None:
            if not (self.depack.sps and self.depack.pps and au.is_idr):
                return
            self.init_segment = _init_segment(self.depack.sps,
                                              self.depack.pps, self.audio)
        if self._seg_start_ts is None:
            if not au.is_idr:
                return                    # segments must start on IDR
            self._seg_start_ts = au.timestamp
        elapsed = ((au.timestamp - self._seg_start_ts) & 0xFFFFFFFF) / VIDEO_CLOCK
        if au.is_idr and self._pending and elapsed >= self.target_duration:
            self._cut()
            self._seg_start_ts = au.timestamp
        self._pending.append(au)
        self._last_ts = au.timestamp

    def on_audio(self, data: bytes, ts: int) -> None:
        """One AAC AU from the session's audio track (RTP ts = sample
        units).  Buffered until the video-driven cut; audio received
        before the first video segment opens is dropped (nothing to
        sync it against yet)."""
        if self.audio is None or self._seg_start_ts is None:
            return
        if self._audio_prev_ts is None and not self._audio_pending \
                and self._audio_dts == 0:
            # anchor the audio tfdt timeline to the video position NOW,
            # mapped into the audio timescale: video tfdt carries raw
            # source RTP timestamps (random origin per RFC 3550), so a
            # zero-based audio track would present up to 2^32/90k sec
            # away from it.  First-AU arrival jitter bounds the residual
            # offset to ~a frame; an SR-correlated mapping can tighten
            # it later.
            ref = self._last_ts if self._last_ts is not None \
                else self._seg_start_ts
            self._audio_dts = ref * self.audio.sample_rate // VIDEO_CLOCK
        self._audio_pending.append((data, ts))
        # bounded like every other buffer here: cuts are video-driven,
        # so a stalled video track must shed audio, not hoard it
        max_aus = 2 + int((self.window + 2) * self.target_duration
                          * self.audio.sample_rate
                          // AAC_SAMPLES_PER_FRAME)
        while len(self._audio_pending) > max_aus:
            self._audio_pending.popleft()
            self.audio_dropped += 1

    def _drain_audio(self) -> tuple[list, int]:
        """All buffered AUs → (samples, base_dts).  The audio timeline is
        self-paced from AU timestamp deltas (RTP clock == sample rate),
        zero-based at the first segment — sync error vs video is bounded
        by one audio frame + ingest jitter, and both tracks' tfdt then
        advance in lockstep."""
        if not self._audio_pending:
            return [], self._audio_dts
        aus = list(self._audio_pending)
        self._audio_pending.clear()
        if self._audio_prev_ts is not None:
            # the previous batch's final AU got a GUESSED duration; the
            # real one is this batch's first ts minus its ts — reconcile
            # so a gap straddling a cut cannot drift the tfdt timeline
            gap = (aus[0][1] - self._audio_prev_ts) & 0xFFFFFFFF
            if 0 < gap <= self.audio.sample_rate * 10:
                self._audio_dts += gap - self._audio_last_dur
        base = self._audio_dts
        samples = []
        for i, (data, ts) in enumerate(aus):
            if i + 1 < len(aus):
                dur = (aus[i + 1][1] - ts) & 0xFFFFFFFF
                if not 0 < dur <= self.audio.sample_rate * 10:
                    dur = self._audio_last_dur
            else:
                dur = self._audio_last_dur
            self._audio_last_dur = dur if 0 < dur <= \
                self.audio.sample_rate * 10 else AAC_SAMPLES_PER_FRAME
            samples.append((data, dur, True))    # every AAC frame syncs
            self._audio_dts += dur
        self._audio_prev_ts = aus[-1][1]
        self.audio_samples_muxed += len(samples)
        return samples, base

    def _cut(self) -> None:
        if not self._pending:
            return
        base = self._pending[0].timestamp
        samples = []
        for i, au in enumerate(self._pending):
            if i + 1 < len(self._pending):
                dur = (self._pending[i + 1].timestamp - au.timestamp) \
                    & 0xFFFFFFFF
            else:
                dur = VIDEO_CLOCK // 30
            if not 0 < dur < VIDEO_CLOCK * 10:
                dur = VIDEO_CLOCK // 30
            samples.append((au.to_avcc(), dur, au.is_idr))
        total = sum(d for _, d, _ in samples) / VIDEO_CLOCK
        seq = self.media_seq + len(self.segments)
        audio_samples, audio_base = self._drain_audio()
        seg = Segment(seq, total, _media_segment(seq, base, samples,
                                                 audio_samples,
                                                 audio_base))
        self.segments.append(seg)
        self._obs_bytes += len(seg.data)
        self._obs_sec += total
        self._pending = []
        while len(self.segments) > self.window:
            self.segments.pop(0)
            self.media_seq += 1

    # -- serving -----------------------------------------------------------
    def playlist_key(self) -> tuple:
        """Identity of the current sliding window — the playlist text
        (and its ETag) is a pure function of this."""
        return (self.media_seq, len(self.segments),
                self.segments[-1].seq if self.segments else -1)

    def playlist(self, base_url: str = "") -> str:
        """The live m3u8 — rebuilt only when the window changed (a
        per-request rebuild was O(window) string work on every GET of
        every player; the cache returns the SAME str object, which the
        regression tests pin)."""
        key = (self.playlist_key(), base_url)
        cached = self._playlist_cache
        if cached is not None and cached[0] == key:
            return cached[1]
        lines = ["#EXTM3U", "#EXT-X-VERSION:7",
                 f"#EXT-X-TARGETDURATION:{int(self.target_duration + 1)}",
                 f"#EXT-X-MEDIA-SEQUENCE:{self.media_seq}",
                 f'#EXT-X-MAP:URI="{base_url}init.mp4"']
        for s in self.segments:
            lines.append(f"#EXTINF:{s.duration_sec:.3f},")
            lines.append(f"{base_url}seg{s.seq}.m4s")
        text = "\n".join(lines) + "\n"
        self._playlist_cache = (key, text)
        self.playlist_builds += 1
        return text

    def get_segment(self, seq: int) -> bytes | None:
        """Served BY REFERENCE — a cut segment is immutable, so every
        GET shares the one bytes object (zero per-request copies)."""
        for s in self.segments:
            if s.seq == seq:
                return s.data
        return None

    def codec_string(self) -> str:
        """RFC 6381 codec tags from the SPS bytes (+ AAC-LC when the
        entry carries audio)."""
        sps = self.depack.sps
        video = f"avc1.{sps[1]:02X}{sps[2]:02X}{sps[3]:02X}" \
            if sps and len(sps) >= 4 else "avc1.42E01E"
        return video + ",mp4a.40.2" if self.audio is not None else video

    def observed_bandwidth(self) -> int:
        """Peak-ish bits/s over the segments produced so far (0 = none)."""
        if self._obs_sec <= 0:
            return 0
        return int(self._obs_bytes * 8 / self._obs_sec)


class HlsAudioTap(RelayOutput):
    """RelayOutput on the session's AUDIO track: depacketizes RFC 3640
    AAC and fans each AU into every rendition of the entry (renditions
    added later see audio immediately — the dict reference is live)."""

    def __init__(self, cfg: AacConfig, renditions: dict):
        super().__init__(ssrc=0x416)
        self.rewrite.base_src_seq = 0
        self.rewrite.base_src_ts = 0
        self.rewrite.out_seq_start = 0
        self.rewrite.out_ts_start = 0
        from ..protocol.aac import AacDepacketizer
        self.depack = AacDepacketizer(cfg)
        self.renditions = renditions

    def send_bytes(self, data: bytes, *, is_rtcp: bool) -> WriteResult:
        if is_rtcp:
            return WriteResult.OK
        for au, ts in self.depack.push(data):
            for out in self.renditions.values():
                out.on_audio(au, ts)
        return WriteResult.OK


#: SDP codec names this HLS muxer can carry as an fMP4 audio track
_AAC_CODECS = ("MPEG4-GENERIC",)


class _HlsEntry:
    """One published path: the full-rate rendition plus temporal rungs."""

    def __init__(self, sess, track_id: int,
                 audio_track: int | None = None,
                 audio_cfg: AacConfig | None = None):
        self.sess = sess
        self.track_id = track_id
        self.audio_track = audio_track
        self.audio_cfg = audio_cfg
        #: rendition name → HlsOutput; "" = source frame rate, "rN" =
        #: thinning level N (1 = half rate, 2 = keyframes only), "qN" =
        #: requant rung (a LadderRendition fed by ``requant_ladder``)
        self.renditions: dict[str, HlsOutput] = {}
        self.audio_tap: HlsAudioTap | None = None
        #: ONE RequantLadder serves every q-rung of the entry: the AU is
        #: depacketized and entropy-decoded once, slices fan across the
        #: shared pool, and all renditions ride one fused transform
        #: dispatch (hls/requant.py, ISSUE 9)
        self.requant_ladder = None


#: default ladder for master.m3u8: temporal rungs only (frame-granular
#: thinning, NO re-encode: level 1 halves the frame rate, level 2 keeps
#: GOP heads only — matching the reference's own thinning behavior,
#: RTPStream.h:144-174).  The transform-domain REQUANT rung "qN" (same
#: frame rate, truly lower bitrate — hls/requant.py) is OPT-IN via
#: starthls rungs=q6 or an explicit /q6/ URL: its host-side CAVLC recode
#: costs ~0.5 ms per macroblock, so auto-advertising it on every
#: master.m3u8 GET would stall large pictures and publish a bogus
#: variant for out-of-scope (CABAC/inter) sources.
DEFAULT_RUNGS = (1, 2)
MAX_RUNG_LEVEL = 2
MAX_REQUANT_DELTA = 18
#: BANDWIDTH fallbacks per rendition before any segment is observed
_NOMINAL_BW = {"": 2_000_000, "r1": 1_200_000, "r2": 400_000,
               "q6": 1_000_000, "q12": 500_000}


class HlsService:
    """Manages per-path HLS entries (full rendition + temporal rungs) and
    serves master/rendition playlists + segments.

    BASELINE config-5's mux half: one live H.264 push → multi-rendition
    ``master.m3u8``.  The rungs reuse the relay's frame-granular thinning
    (``relay.quality.ThinningFilter``) pinned at a fixed level, so every
    rendition is a valid lower-frame-rate H.264 stream with zero
    re-encoding (the MJPEG requant ladder is the transcode half; H.264
    entropy re-coding is a serial-decoder problem with no TPU win)."""

    def __init__(self, registry, *, target_duration: float = 2.0,
                 window: int = 6, requant_on_device: bool = False):
        self.registry = registry
        self.target_duration = target_duration
        self.window = window
        #: device-batch the q-rung requant (bit-exact either way).  OFF by
        #: default on the live path: first-touch JAX init (slow compile,
        #: or a wedged tunneled lease) must never stall the rendition
        #: worker; the server enables it when its TPU fan-out is on.
        self.requant_on_device = requant_on_device
        self.outputs: dict[str, _HlsEntry] = {}

    def _rendition(self, entry: _HlsEntry, name: str) -> HlsOutput:
        out = entry.renditions.get(name)
        if out is None:
            if name.startswith("q"):
                # every q-rung of a path shares ONE RequantLadder (the
                # session output): one depacketize + one entropy decode
                # per AU no matter how wide the ladder is
                from .requant import RequantLadder
                if entry.requant_ladder is None:
                    entry.requant_ladder = RequantLadder(
                        use_device=self.requant_on_device,
                        target_duration=self.target_duration,
                        window=self.window, audio=entry.audio_cfg)
                    entry.sess.add_output(entry.track_id,
                                          entry.requant_ladder)
                out = entry.requant_ladder.add_rendition(int(name[1:]))
            else:
                out = HlsOutput(target_duration=self.target_duration,
                                window=self.window, audio=entry.audio_cfg)
                if name:
                    out.thinning.controller.level = int(name[1:])
                entry.sess.add_output(entry.track_id, out)
            entry.renditions[name] = out
            if entry.audio_track is not None and entry.audio_tap is None:
                entry.audio_tap = HlsAudioTap(entry.audio_cfg,
                                              entry.renditions)
                entry.sess.add_output(entry.audio_track, entry.audio_tap)
        return out

    def _retire(self, key: str, entry: _HlsEntry) -> None:
        from .requant import LadderRendition
        for out in entry.renditions.values():
            if not isinstance(out, LadderRendition):
                entry.sess.remove_output(entry.track_id, out)
        if entry.requant_ladder is not None:
            entry.sess.remove_output(entry.track_id, entry.requant_ladder)
        if entry.audio_tap is not None and entry.audio_track is not None:
            entry.sess.remove_output(entry.audio_track, entry.audio_tap)

    def _fresh_entry(self, key: str) -> _HlsEntry | None:
        """Current entry for ``key`` — retiring it first if the source
        session was replaced (publisher reconnect) so viewers never get a
        frozen playlist bound to a dead session."""
        entry = self.outputs.get(key)
        if entry is not None and self.registry.find(key) is not entry.sess:
            self.outputs.pop(key)
            self._retire(key, entry)
            entry = None
        return entry

    def start(self, path: str, rungs: tuple[int, ...] = (),
              *, include_source: bool = True) -> HlsOutput | None:
        """Publish ``path`` over HLS; returns the full-rate rendition (or
        None with ``include_source=False``).  ``rungs`` adds temporal
        renditions (thinning levels 1..MAX_RUNG_LEVEL); out-of-range
        levels raise ValueError rather than advertising a dead variant."""
        from ..protocol.sdp import _norm
        key = _norm(path)
        names = []
        for r in rungs:
            if isinstance(r, str) and r.startswith("q"):
                delta = int(r[1:])
                if not (6 <= delta <= MAX_REQUANT_DELTA and delta % 6 == 0):
                    raise ValueError(
                        f"requant rungs must be q6..q{MAX_REQUANT_DELTA} "
                        "in steps of 6")
                names.append(f"q{delta}")
            else:
                level = int(r)
                if not 1 <= level <= MAX_RUNG_LEVEL:
                    raise ValueError(
                        f"rung levels must be 1..{MAX_RUNG_LEVEL}")
                names.append(f"r{level}")
        entry = self._fresh_entry(key)
        if entry is None:
            sess = self.registry.find(key)
            if sess is None:
                raise KeyError(key)
            vids = [tid for tid, st in sess.streams.items()
                    if st.info.media_type == "video"]
            if not vids:
                raise ValueError("no video track")
            audio_tid = audio_cfg = None
            for tid, st in sess.streams.items():
                if st.info.media_type == "audio" \
                        and st.info.codec in _AAC_CODECS:
                    audio_tid = tid
                    chans = 2
                    bits = st.info.payload_name.split("/")
                    if len(bits) >= 3 and bits[2].isdigit():
                        chans = int(bits[2])
                    audio_cfg = AacConfig.from_sdp(
                        st.info.fmtp, st.info.clock_rate, chans)
                    break
            entry = self.outputs[key] = _HlsEntry(sess, vids[0],
                                                  audio_tid, audio_cfg)
        out = self._rendition(entry, "") if include_source else None
        for name in names:
            self._rendition(entry, name)
        return out

    def stop(self, path: str) -> None:
        from ..protocol.sdp import _norm
        key = _norm(path)
        entry = self.outputs.pop(key, None)
        if entry is not None:
            self._retire(key, entry)

    def sweep(self) -> int:
        """Retire entries whose source session is gone or was replaced."""
        dead = [k for k, e in self.outputs.items()
                if self.registry.find(k) is not e.sess]
        for k in dead:
            self._retire(k, self.outputs.pop(k))
        return len(dead)

    def list_streams(self) -> list[dict]:
        def info(name, out):
            d = {
                "name": name or "source",
                "uri": (f"{name}/index.m3u8" if name else "index.m3u8"),
                "segments": len(out.segments),
                "bandwidth": out.observed_bandwidth(),
            }
            rq = getattr(out, "requant", None)
            if rq is not None:          # requant rung: surface honesty
                d["requantized_slices"] = rq.stats.slices_requantized
                d["passed_through_slices"] = rq.stats.slices_passed_through
                d["shed_units"] = out.shed
            return d
        return [{
            "path": key,
            "renditions": [info(n, o)
                           for n, o in sorted(entry.renditions.items())],
        } for key, entry in self.outputs.items()]

    def master_playlist(self, entry: _HlsEntry) -> str:
        lines = ["#EXTM3U", "#EXT-X-VERSION:7"]
        for name in sorted(entry.renditions, key=lambda n: (n != "", n)):
            out = entry.renditions[name]
            bw = out.observed_bandwidth() or _NOMINAL_BW.get(name, 800_000)
            lines.append(f"#EXT-X-STREAM-INF:BANDWIDTH={bw},"
                         f'CODECS="{out.codec_string()}"')
            lines.append(f"{name}/index.m3u8" if name else "index.m3u8")
        return "\n".join(lines) + "\n"

    def serve(self, url_path: str
              ) -> tuple[str, bytes | str, str | None] | None:
        """Resolve ``/hls/<stream-path>[/rN]/<file>`` → (content_type,
        body, etag).  ``master.m3u8`` auto-starts the default temporal
        ladder; a rendition playlist auto-starts just that rendition.
        ``etag`` (None = uncacheable) lets the REST layer short-circuit
        repeat GETs with 304 — playlists carry a weak window-identity
        tag, segments a strong one (a cut segment is immutable)."""
        if not url_path.startswith("/hls/"):
            return None
        rest = url_path[5:]
        if "/" not in rest:
            return None
        stream_path, fname = rest.rsplit("/", 1)
        rendition = ""
        parts = stream_path.rsplit("/", 1)
        from ..protocol.sdp import _norm as _n
        if (len(parts) == 2 and len(parts[1]) >= 2
                and parts[1][0] in "rq" and parts[1][1:].isdigit()
                # a stream genuinely PUBLISHED at .../r2 or .../q6 keeps
                # its full path; the suffix is a rendition only when no
                # such session exists
                and self.registry.find(_n("/" + stream_path.strip("/")))
                is None):
            stream_path, rendition = parts
        from ..protocol.sdp import _norm
        key = _norm("/" + stream_path.strip("/"))
        try:
            if fname == "master.m3u8":
                # idempotent: upgrades an existing single-variant entry
                # to the default ladder too
                self.start(key, DEFAULT_RUNGS)
            elif rendition and (self._fresh_entry(key) is None
                                or rendition not in
                                self.outputs[key].renditions):
                rung = rendition if rendition[0] == "q" \
                    else int(rendition[1:])
                self.start(key, (rung,), include_source=False)
            elif self._fresh_entry(key) is None:
                self.start(key)
        except (KeyError, ValueError):
            return None
        entry = self.outputs.get(key)
        if entry is None:
            return None
        if fname == "master.m3u8":
            return ("application/vnd.apple.mpegurl",
                    self.master_playlist(entry), None)
        out = entry.renditions.get(rendition)
        if out is None:
            return None
        gen = out.etag_gen
        if fname in ("index.m3u8", "playlist.m3u8"):
            pk = out.playlist_key()
            return ("application/vnd.apple.mpegurl", out.playlist(),
                    f'W/"pl-{gen}-{pk[0]}-{pk[1]}-{pk[2]}"')
        if fname == "init.mp4":
            if out.init_segment is None:
                return None
            return ("video/mp4", out.init_segment,
                    f'"init-{gen}-{len(out.init_segment)}"')
        if fname.startswith("seg") and fname.endswith(".m4s"):
            try:
                seq = int(fname[3:-4])
            except ValueError:
                return None
            data = out.get_segment(seq)
            if data is None:
                return None
            return ("video/iso.segment", data,
                    f'"seg-{gen}-{seq}-{len(data)}"')
        return None
