"""Live fMP4 HLS segmenter.

One ``HlsOutput`` per published stream: depacketizes the relayed H.264,
cuts segments on IDR boundaries near the target duration, and keeps a
sliding window of in-memory CMAF fragments:

* init segment — ``ftyp`` + ``moov`` (with ``mvex/trex``: sample tables
  live in the fragments),
* media segments — ``styp`` + ``moof`` (mfhd/tfhd/tfdt/trun) + ``mdat``,
* playlist — live sliding-window ``#EXT-X-MAP`` m3u8.

The transcode ladder (ops.transform) will fan one ingest into N
``HlsOutput``s at different rungs; this module is the mux/serve half.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from ..relay.output import RelayOutput, WriteResult
from ..vod.depacketize import AccessUnit, H264Depacketizer
from ..vod.mp4_writer import box, full_box

VIDEO_CLOCK = 90000


def _init_segment(sps: bytes, pps: bytes) -> bytes:
    avcc = box(b"avcC",
               bytes((1, sps[1] if len(sps) > 1 else 66,
                      sps[2] if len(sps) > 2 else 0,
                      sps[3] if len(sps) > 3 else 30, 0xFF, 0xE1)),
               struct.pack(">H", len(sps)), sps, bytes((1,)),
               struct.pack(">H", len(pps)), pps)
    entry = struct.pack(">I4s", 86 + len(avcc), b"avc1") + bytes(6) + \
        struct.pack(">H", 1) + bytes(16) + struct.pack(">HH", 0, 0) + \
        struct.pack(">II", 0x00480000, 0x00480000) + bytes(4) + \
        struct.pack(">H", 1) + bytes(32) + struct.pack(">Hh", 0x18, -1) + avcc
    stsd = full_box(b"stsd", 0, 0, struct.pack(">I", 1), entry)
    stbl = box(b"stbl", stsd,
               full_box(b"stts", 0, 0, bytes(4)),
               full_box(b"stsc", 0, 0, bytes(4)),
               full_box(b"stsz", 0, 0, bytes(8)),
               full_box(b"stco", 0, 0, bytes(4)))
    url = full_box(b"url ", 0, 1)
    dinf = box(b"dinf", full_box(b"dref", 0, 0, struct.pack(">I", 1), url))
    minf = box(b"minf", full_box(b"vmhd", 0, 1, bytes(8)), dinf, stbl)
    mdhd = full_box(b"mdhd", 0, 0,
                    struct.pack(">IIII", 0, 0, VIDEO_CLOCK, 0),
                    struct.pack(">HH", 0x55C4, 0))
    hdlr = full_box(b"hdlr", 0, 0, bytes(4), b"vide", bytes(12),
                    b"easydarwin-tpu\x00")
    mdia = box(b"mdia", mdhd, hdlr, minf)
    tkhd = full_box(b"tkhd", 0, 7, struct.pack(">IIIII", 0, 0, 1, 0, 0),
                    bytes(8), struct.pack(">hhhH", 0, 0, 0, 0), bytes(2),
                    struct.pack(">9I", 0x10000, 0, 0, 0, 0x10000, 0, 0, 0,
                                0x40000000),
                    struct.pack(">II", 0, 0))
    trak = box(b"trak", tkhd, mdia)
    trex = full_box(b"trex", 0, 0, struct.pack(">IIIII", 1, 1, 0, 0, 0))
    mvex = box(b"mvex", trex)
    mvhd = full_box(b"mvhd", 0, 0,
                    struct.pack(">IIII", 0, 0, VIDEO_CLOCK, 0),
                    struct.pack(">IH", 0x00010000, 0x0100), bytes(10),
                    struct.pack(">9I", 0x10000, 0, 0, 0, 0x10000, 0, 0, 0,
                                0x40000000), bytes(24),
                    struct.pack(">I", 2))
    return box(b"ftyp", b"iso6", struct.pack(">I", 0), b"iso6cmfc") + \
        box(b"moov", mvhd, trak, mvex)


def _media_segment(seq: int, base_dts: int,
                   samples: list[tuple[bytes, int, bool]]) -> bytes:
    """samples: [(avcc_data, duration, is_sync)]"""
    mdat_payload = b"".join(s[0] for s in samples)
    mfhd = full_box(b"mfhd", 0, 0, struct.pack(">I", seq))
    # tfhd: default-base-is-moof | track id
    tfhd = full_box(b"tfhd", 0, 0x020000, struct.pack(">I", 1))
    tfdt = full_box(b"tfdt", 1, 0, struct.pack(">Q", base_dts))
    # trun: data-offset | sample-duration | sample-size | sample-flags
    flags = 0x000001 | 0x000100 | 0x000200 | 0x000400
    rows = b""
    for data, dur, sync in samples:
        sflags = 0x02000000 if sync else 0x01010000
        rows += struct.pack(">III", dur, len(data), sflags)
    trun_len = 8 + 4 + 4 + 4 + 12 * len(samples)
    moof_len = 8 + len(mfhd) + 8 + len(tfhd) + len(tfdt) + trun_len
    data_offset = moof_len + 8
    trun = full_box(b"trun", 0, flags,
                    struct.pack(">Ii", len(samples), data_offset), rows)
    traf = box(b"traf", tfhd, tfdt, trun)
    moof = box(b"moof", mfhd, traf)
    return box(b"styp", b"msdh", struct.pack(">I", 0), b"msdhmsix") + \
        moof + box(b"mdat", mdat_payload)


@dataclass
class Segment:
    seq: int
    duration_sec: float
    data: bytes


class HlsOutput(RelayOutput):
    """Relay sink producing a sliding window of CMAF segments."""

    def __init__(self, *, target_duration: float = 2.0, window: int = 6):
        super().__init__(ssrc=0x415)
        self.target_duration = target_duration
        self.window = window
        self.depack = H264Depacketizer()
        self.init_segment: bytes | None = None
        self.segments: list[Segment] = []
        self.media_seq = 0            # seq of segments[0]
        self._pending: list[AccessUnit] = []
        self._seg_start_ts: int | None = None
        self._last_ts: int | None = None

    def send_bytes(self, data: bytes, *, is_rtcp: bool) -> WriteResult:
        if is_rtcp:
            return WriteResult.OK
        self.depack.push(data)
        for au in self.depack.pop_units():
            self._on_unit(au)
        return WriteResult.OK

    def _on_unit(self, au: AccessUnit) -> None:
        if self.init_segment is None:
            if not (self.depack.sps and self.depack.pps and au.is_idr):
                return
            self.init_segment = _init_segment(self.depack.sps,
                                              self.depack.pps)
        if self._seg_start_ts is None:
            if not au.is_idr:
                return                    # segments must start on IDR
            self._seg_start_ts = au.timestamp
        elapsed = ((au.timestamp - self._seg_start_ts) & 0xFFFFFFFF) / VIDEO_CLOCK
        if au.is_idr and self._pending and elapsed >= self.target_duration:
            self._cut()
            self._seg_start_ts = au.timestamp
        self._pending.append(au)
        self._last_ts = au.timestamp

    def _cut(self) -> None:
        if not self._pending:
            return
        base = self._pending[0].timestamp
        samples = []
        for i, au in enumerate(self._pending):
            if i + 1 < len(self._pending):
                dur = (self._pending[i + 1].timestamp - au.timestamp) \
                    & 0xFFFFFFFF
            else:
                dur = VIDEO_CLOCK // 30
            if not 0 < dur < VIDEO_CLOCK * 10:
                dur = VIDEO_CLOCK // 30
            samples.append((au.to_avcc(), dur, au.is_idr))
        total = sum(d for _, d, _ in samples) / VIDEO_CLOCK
        seq = self.media_seq + len(self.segments)
        self.segments.append(Segment(seq, total,
                                     _media_segment(seq, base, samples)))
        self._pending = []
        while len(self.segments) > self.window:
            self.segments.pop(0)
            self.media_seq += 1

    # -- serving -----------------------------------------------------------
    def playlist(self, base_url: str = "") -> str:
        lines = ["#EXTM3U", "#EXT-X-VERSION:7",
                 f"#EXT-X-TARGETDURATION:{int(self.target_duration + 1)}",
                 f"#EXT-X-MEDIA-SEQUENCE:{self.media_seq}",
                 f'#EXT-X-MAP:URI="{base_url}init.mp4"']
        for s in self.segments:
            lines.append(f"#EXTINF:{s.duration_sec:.3f},")
            lines.append(f"{base_url}seg{s.seq}.m4s")
        return "\n".join(lines) + "\n"

    def get_segment(self, seq: int) -> bytes | None:
        for s in self.segments:
            if s.seq == seq:
                return s.data
        return None


class HlsService:
    """Manages HlsOutputs per live path + serves playlist/segments."""

    def __init__(self, registry, *, target_duration: float = 2.0,
                 window: int = 6):
        self.registry = registry
        self.target_duration = target_duration
        self.window = window
        self.outputs: dict[str, tuple[object, int, HlsOutput]] = {}

    def start(self, path: str) -> HlsOutput:
        from ..protocol.sdp import _norm
        key = _norm(path)
        if key in self.outputs:
            return self.outputs[key][2]
        sess = self.registry.find(key)
        if sess is None:
            raise KeyError(key)
        vids = [tid for tid, st in sess.streams.items()
                if st.info.media_type == "video"]
        if not vids:
            raise ValueError("no video track")
        out = HlsOutput(target_duration=self.target_duration,
                        window=self.window)
        sess.add_output(vids[0], out)
        self.outputs[key] = (sess, vids[0], out)
        return out

    def stop(self, path: str) -> None:
        from ..protocol.sdp import _norm
        key = _norm(path)
        if key in self.outputs:
            sess, tid, out = self.outputs.pop(key)
            sess.remove_output(tid, out)

    def serve(self, url_path: str) -> tuple[str, bytes | str] | None:
        """Resolve /hls/<stream-path>/<file> → (content_type, body)."""
        if not url_path.startswith("/hls/"):
            return None
        rest = url_path[5:]
        if "/" not in rest:
            return None
        stream_path, fname = rest.rsplit("/", 1)
        key = "/" + stream_path.strip("/")
        entry = self.outputs.get(key)
        if entry is None:
            try:
                self.start(key)
            except (KeyError, ValueError):
                return None
            entry = self.outputs[key]
        out = entry[2]
        if fname in ("index.m3u8", "playlist.m3u8"):
            return ("application/vnd.apple.mpegurl", out.playlist())
        if fname == "init.mp4":
            if out.init_segment is None:
                return None
            return ("video/mp4", out.init_segment)
        if fname.startswith("seg") and fname.endswith(".m4s"):
            try:
                seq = int(fname[3:-4])
            except ValueError:
                return None
            data = out.get_segment(seq)
            return ("video/iso.segment", data) if data is not None else None
        return None
