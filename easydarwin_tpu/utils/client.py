"""Programmatic RTSP client: pusher + player flows for tests and load-gen.

Reference parity: ``RTSPClientLib/ClientSession.{h,cpp}`` (programmatic
DESCRIBE/SETUP/PLAY state machine used by the old StreamingLoadTool) and
``PlayerSimulator.h`` (client-side loss/late tracking) — rebuilt on asyncio
as a usable harness instead of the reference's bit-rotted copy.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field

from ..protocol import rtp, rtsp, sdp


@dataclass
class ReceiverStats:
    """PlayerSimulator-style accounting."""

    packets: int = 0
    bytes: int = 0
    lost: int = 0
    duplicates: int = 0
    out_of_order: int = 0
    _last_seq: int | None = None
    _seen: set = field(default_factory=set)

    def on_packet(self, data: bytes) -> None:
        self.packets += 1
        self.bytes += len(data)
        try:
            seq = rtp.peek_seq(data)
        except Exception:
            return
        if seq in self._seen:
            self.duplicates += 1
            return
        self._seen.add(seq)
        if self._last_seq is not None:
            d = rtp.seq_delta(seq, self._last_seq)
            if d > 1:
                self.lost += d - 1
            elif d < 0:
                self.out_of_order += 1
        if self._last_seq is None or rtp.seq_delta(seq, self._last_seq) > 0:
            self._last_seq = seq


def hexish(s: str) -> bool:
    """A plausible trace id: 8-64 lowercase hex chars (token_hex shape).
    Anything else must not become a correlation key."""
    return 8 <= len(s) <= 64 and all(c in "0123456789abcdef" for c in s)


class RtspClient:
    def __init__(self):
        self.reader: asyncio.StreamReader | None = None
        self.writer: asyncio.StreamWriter | None = None
        self.wire = rtsp.RtspWireReader(parse_responses=True)
        self.cseq = 0
        self.session_id: str | None = None
        #: headers merged into EVERY request (overridable per call) —
        #: the pull-relay envelope sets the cluster-peer correlation
        #: pair here (X-Trace-Id / X-Cluster-Node, ISSUE 15)
        self.default_headers: dict = {}
        #: the last DESCRIBE response (play_start) — carries the
        #: upstream stream's X-Trace-Id for downstream trace adoption
        self.describe_response: rtsp.RtspResponse | None = None
        self._responses: asyncio.Queue = asyncio.Queue()
        #: interleaved channel → asyncio.Queue of payload bytes
        self.channels: dict[int, asyncio.Queue] = {}
        #: set by enable_any_queue(): single (channel, data) stream instead
        #: of per-channel queues (pull-relay forwarding wants arrival order)
        self.any_queue: asyncio.Queue | None = None
        self.stats = ReceiverStats()
        self._reader_task: asyncio.Task | None = None

    async def connect(self, host: str, port: int) -> None:
        self.reader, self.writer = await asyncio.open_connection(host, port)
        self._reader_task = asyncio.create_task(self._read_loop())

    async def close(self) -> None:
        if self._reader_task:
            self._reader_task.cancel()
            try:
                await self._reader_task
            except (asyncio.CancelledError, Exception):
                pass
        if self.writer:
            self.writer.close()

    async def _read_loop(self) -> None:
        try:
            await self._read_loop_inner()
        finally:
            if self.any_queue is not None:      # EOF sentinel for recv_any
                self.any_queue.put_nowait((-1, b""))

    async def _read_loop_inner(self) -> None:
        while True:
            data = await self.reader.read(16384)
            if not data:
                break
            self.wire.feed(data)
            for ev in self.wire.events():
                if isinstance(ev, rtsp.InterleavedPacket):
                    if ev.channel % 2 == 0:
                        self.stats.on_packet(ev.data)
                    if self.any_queue is not None:
                        self.any_queue.put_nowait((ev.channel, ev.data))
                    else:
                        q = self.channels.setdefault(ev.channel,
                                                     asyncio.Queue())
                        q.put_nowait(ev.data)
                else:
                    self._responses.put_nowait(ev)

    # ------------------------------------------------------------ requests
    async def request(self, method: str, uri: str, headers=None,
                      body: bytes = b"", timeout: float = 5.0
                      ) -> rtsp.RtspResponse:
        self.cseq += 1
        want = self.cseq
        hdrs = {"cseq": str(want)}
        if self.session_id:
            hdrs["session"] = self.session_id
        hdrs.update(self.default_headers)
        hdrs.update(headers or {})
        req = rtsp.RtspRequest(method, uri, hdrs, body)
        self.writer.write(req.to_bytes())
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            left = deadline - asyncio.get_running_loop().time()
            resp = await asyncio.wait_for(self._responses.get(),
                                          max(left, 0.001))
            # CSeq matching: a previously timed-out request's late reply
            # must not pair with THIS request (the queue is FIFO; one
            # desync would shift every later pairing) — drop stale ones
            rc = resp.headers.get("cseq")
            try:
                if rc is not None and int(rc) < want:
                    continue
            except ValueError:
                pass
            break
        if sid := resp.headers.get("session"):
            self.session_id = sid.split(";")[0].strip()
        return resp

    def send_interleaved(self, channel: int, data: bytes) -> None:
        self.writer.write(rtsp.frame_interleaved(channel, data))

    async def recv_interleaved(self, channel: int,
                               timeout: float = 5.0) -> bytes:
        q = self.channels.setdefault(channel, asyncio.Queue())
        return await asyncio.wait_for(q.get(), timeout)

    def enable_any_queue(self) -> None:
        """Switch to arrival-order (channel, data) delivery via recv_any."""
        self.any_queue = asyncio.Queue()

    async def recv_any(self) -> tuple[int, bytes]:
        """Next (channel, data) in arrival order; (-1, b"") on EOF."""
        if self.any_queue is None:
            self.enable_any_queue()
        return await self.any_queue.get()

    # ---------------------------------------------------------- push flow
    async def push_start(self, uri: str, sdp_text: str,
                         tcp: bool = True) -> None:
        """ANNOUNCE + SETUP(record) each track + RECORD (EasyPusher flow)."""
        r = await self.request("ANNOUNCE", uri, {
            "content-type": "application/sdp"}, sdp_text.encode())
        assert r.status == 200, r.status
        sd = sdp.parse(sdp_text)
        self.push_transports = []
        for i, st in enumerate(sd.streams):
            t = (f"RTP/AVP/TCP;unicast;interleaved={2*i}-{2*i+1};mode=record"
                 if tcp else "RTP/AVP;unicast;client_port=0-1;mode=record")
            r = await self.request("SETUP", f"{uri}/trackID={st.track_id}",
                                   {"transport": t})
            assert r.status == 200, r.status
            self.push_transports.append(rtsp.TransportSpec.parse(
                r.headers.get("transport", "RTP/AVP")))
        r = await self.request("RECORD", uri)
        assert r.status == 200, r.status

    def push_packet(self, track_index: int, data: bytes,
                    is_rtcp: bool = False) -> None:
        self.send_interleaved(2 * track_index + (1 if is_rtcp else 0), data)

    # ---------------------------------------------------------- play flow
    async def play_start(self, uri: str, *, tcp: bool = True,
                         client_ports: list[tuple[int, int]] | None = None,
                         setup_headers: dict | None = None
                         ) -> sdp.SessionDescription:
        r = await self.request("DESCRIBE", uri, {"accept": "application/sdp"})
        assert r.status == 200, r.status
        self.describe_response = r
        up_trace = r.headers.get("x-trace-id", "").strip()
        if "x-trace-id" in self.default_headers and hexish(up_trace):
            # trace-propagating caller (the pull-relay envelope): adopt
            # the upstream STREAM's trace before the SETUPs go out, so
            # the serving connection upstream is tagged with the same id
            # this edge will serve under (ISSUE 15)
            self.default_headers["x-trace-id"] = up_trace
        sd = sdp.parse(r.body)
        self.transports = []
        self.setup_responses = []
        for i, st in enumerate(sd.streams):
            if tcp:
                t = f"RTP/AVP/TCP;unicast;interleaved={2*i}-{2*i+1}"
            else:
                cp = client_ports[i]
                t = f"RTP/AVP;unicast;client_port={cp[0]}-{cp[1]}"
            r = await self.request("SETUP", f"{uri}/trackID={st.track_id}",
                                   {"transport": t, **(setup_headers or {})})
            assert r.status == 200, r.status
            self.setup_responses.append(r)
            self.transports.append(rtsp.TransportSpec.parse(
                r.headers.get("transport", "RTP/AVP")))
        r = await self.request("PLAY", uri)
        assert r.status == 200, r.status
        return sd

    async def teardown(self, uri: str) -> None:
        try:
            await self.request("TEARDOWN", uri, timeout=2.0)
        except (asyncio.TimeoutError, ConnectionError):
            pass
