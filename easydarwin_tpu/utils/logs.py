"""Rolling logs: error log + W3C streaming access log.

Reference parity: ``QTSSRollingLog`` (task-driven size/time rolled logs,
``QTSSRollingLog.cpp``), the ErrorLog module's level filter
(``QTSSErrorLogModule.cpp``) and the AccessLog module's W3C-extended field
set (``QTSSAccessLogModule.cpp:153-1022``).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

from .. import obs
from .http_misc import parse_user_agent


class RollingLog:
    """Append-only log rolled by size and/or age; files get .N suffixes."""

    def __init__(self, path: str, *, max_bytes: int = 10_000_000,
                 max_age_sec: float = 7 * 86400, keep: int = 5,
                 name: str | None = None):
        self.path = path
        self.name = name or os.path.splitext(os.path.basename(path))[0]
        self.max_bytes = max_bytes
        self.max_age_sec = max_age_sec
        self.keep = keep
        self._f = None
        self._opened_at = 0.0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def _open(self):
        if self._f is None:
            self._f = open(self.path, "a", buffering=1)
            self._opened_at = time.time()

    def write_line(self, line: str) -> None:
        self._open()
        if (self._f.tell() >= self.max_bytes
                or time.time() - self._opened_at >= self.max_age_sec):
            self.roll()
        self._f.write(line.rstrip("\n") + "\n")
        if self._f.tell() >= self.max_bytes:
            # roll AFTER a crossing write too: one oversized line must not
            # leave the file permanently over the cap (the pre-write check
            # alone only notices at the NEXT write, which may never come)
            self.roll()

    def roll(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
        for i in range(self.keep - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        if os.path.exists(self.path):
            os.replace(self.path, f"{self.path}.1")
        obs.LOG_ROLLS.inc(log=self.name)
        self._open()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class ErrorLog:
    """Level-filtered rolling error log (fatal/warning/info/debug)."""

    LEVELS = {"fatal": 0, "warning": 1, "info": 2, "debug": 3}

    def __init__(self, path: str, *, verbosity: str = "info", **kw):
        self.log = RollingLog(path, **kw)
        self.verbosity = self.LEVELS.get(verbosity, 2)

    def write(self, level: str, message: str) -> None:
        if self.LEVELS.get(level, 3) <= self.verbosity:
            ts = time.strftime("%Y-%m-%d %H:%M:%S")
            self.log.write_line(f"{ts} [{level.upper()}] {message}")
            obs.LOG_LINES.inc(log=self.log.name, level=level)

    def fatal(self, m):
        self.write("fatal", m)

    def warning(self, m):
        self.write("warning", m)

    def info(self, m):
        self.write("info", m)

    def debug(self, m):
        self.write("debug", m)


@dataclass
class AccessRecord:
    """One finished client session (the AccessLog module logs on
    ClientSessionClosing)."""

    client_ip: str = "-"
    uri: str = "-"
    method: str = "-"                  # PLAY / RECORD
    status: int = 200
    duration_sec: float = 0.0
    bytes_sent: int = 0
    packets_sent: int = 0
    packets_lost: int = 0
    user_agent: str = "-"
    transport: str = "-"               # UDP / TCP


W3C_FIELDS = ("c-ip date time cs-uri cs-method sc-status x-duration "
              "sc-bytes sc-packets x-packets-lost cs(User-Agent) "
              "x-transport c-playerid c-playerversion c-os c-osversion "
              "c-cpu")


class AccessLog:
    def __init__(self, path: str, **kw):
        self.log = RollingLog(path, **kw)
        self._wrote_header = False

    def record(self, r: AccessRecord) -> None:
        if not self._wrote_header:
            self._wrote_header = True
            self.log.write_line("#Version: 1.0")
            self.log.write_line("#Software: easydarwin-tpu/0.1")
            self.log.write_line(f"#Fields: {W3C_FIELDS}")
        now = time.gmtime()
        ua = (r.user_agent or "-").replace(" ", "_")
        # c-playerid/... columns from the DSS User-Agent grammar
        # (UserAgentParser parity; "-" when the client doesn't send them)
        att = parse_user_agent(r.user_agent or "")
        cols = " ".join((att.get(k) or "-").replace(" ", "_")
                        for k in ("qtid", "qtver", "os", "osver", "cpu"))
        self.log.write_line(
            f"{r.client_ip} {time.strftime('%Y-%m-%d', now)} "
            f"{time.strftime('%H:%M:%S', now)} {r.uri} {r.method} "
            f"{r.status} {r.duration_sec:.1f} {r.bytes_sent} "
            f"{r.packets_sent} {r.packets_lost} {ua} {r.transport} {cols}")
        obs.LOG_LINES.inc(log=self.log.name, level="access")
