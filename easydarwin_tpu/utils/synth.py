"""Shared synthetic test pattern for benches, soak and codec tests.

One definition so the bench probe, the integration soak and the codec
test suite all exercise the SAME content (a change to the pattern's
coefficient ranges must not silently diverge between them)."""

from __future__ import annotations

import numpy as np


def synth_luma(n: int = 96, f: float = 0.0) -> np.ndarray:
    """uint8 [n, n] plane of drifting sinusoids; ``f`` animates (frame
    index) for soak-style moving content, 0 gives the static pattern."""
    x = np.arange(n)[None, :].repeat(n, 0).astype(np.float64)
    y = np.arange(n)[:, None].repeat(n, 1).astype(np.float64)
    return (128 + 50 * np.sin(x / 9.0 + f / 3) + 40 * np.cos(y / 7.0 - f / 5)
            + 20 * np.sin((x + y) / 5.0)).clip(0, 255).astype(np.uint8)
