"""Support utilities: the loopback RTSP client / load generator and misc
helpers.  The client revives the concept of the reference's
``RTSPClientLib/ClientSession`` + ``PlayerSimulator`` (which no longer built
there — SURVEY §4) as the framework's end-to-end test harness."""
