"""Filesystem path-confinement helpers shared by the media roots.

The VOD/MP3 tiers map request paths under a configured folder.  A
prefix ``startswith`` test over ``normpath`` output accepts two whole
classes of escapes: sibling directories sharing the prefix string
(``/srv/movies2`` passes a ``/srv/movies`` root) and symlinks inside
the root pointing outside it.  The one correct test is
``os.path.commonpath`` over ``realpath``-resolved paths.
"""

from __future__ import annotations

import os


def under_root(root: str, candidate: str) -> bool:
    """True iff ``candidate`` resolves to a path inside ``root``
    (symlinks followed on both sides; the root itself counts)."""
    root_r = os.path.realpath(root)
    cand_r = os.path.realpath(candidate)
    try:
        return os.path.commonpath([cand_r, root_r]) == root_r
    except ValueError:                  # different drives / mixed abs-rel
        return False


def confined_subpath(root: str, relative: str) -> str | None:
    """Join an untrusted ``relative`` under ``root`` and confine it:
    the normalized path, or None when it escapes (``..`` traversal,
    symlink, sibling-prefix) or resolves to the root itself.  The one
    guard shared by every surface that maps request strings to files
    (``startrecord`` targets, DVR asset directories)."""
    cand = os.path.normpath(os.path.join(root, relative.lstrip("/\\")))
    if not under_root(root, cand) \
            or os.path.realpath(cand) == os.path.realpath(root):
        return None
    return cand


__all__ = ["under_root", "confined_subpath"]
