"""Small request-metadata parsers (CommonUtilitiesLib misc parity).

* ``parse_user_agent`` — ``UserAgentParser.{h,cpp}``: streaming-client
  User-Agent strings of the form ``QTS (qtid=...;qtver=...;os=...)`` →
  the six DSS attributes (qtid/qtver/lang/os/osver/cpu), used by the
  access log's c-playerid/c-playerversion/c-os/c-osversion/c-cpu columns.
* ``QueryParamList`` — ``QueryParamList.cpp``: ordered, case-insensitive
  URL query parameter list (the admin API's ``command=...&parameters`` ).
* ``rfc1123_date`` / ``parse_rfc1123`` — ``DateTranslator.cpp``: the HTTP
  Date header format the reference renders into responses.
"""

from __future__ import annotations

import email.utils
import re
import time
from urllib.parse import parse_qsl, unquote

#: the six attributes DSS understands (UserAgentParser.h:62-70)
UA_ATTRIBUTES = ("qtid", "qtver", "lang", "os", "osver", "cpu")


def parse_user_agent(value: str) -> dict[str, str]:
    """User-Agent → {attribute: value} for the known DSS attributes.

    Grammar (UserAgentParser.cpp Parse): everything inside the first
    parenthesized group is ``name=value;`` pairs; values may themselves be
    parenthesized (e.g. ``os=Mac%20OS%20X``); unknown names are ignored."""
    out: dict[str, str] = {}
    start = value.find("(")
    end = value.rfind(")")
    body = value[start + 1:end] if 0 <= start < end else value
    for part in body.split(";"):
        name, sep, val = part.partition("=")
        if not sep:
            continue
        name = name.strip().lower()
        if name not in UA_ATTRIBUTES:
            continue
        val = unquote(val.strip()).strip('"')
        if val.startswith("(") and val.endswith(")"):
            val = val[1:-1]
        if name not in out:                  # first occurrence wins
            out[name] = val
    return out


class QueryParamList:
    """Ordered multi-value query parameter list, case-insensitive names.

    The reference walks the raw query string into a queue of name/value
    pairs and answers ``DoFindCGIValueForParam`` lookups; both ``&`` and
    ``;`` separate pairs (QueryParamList.cpp ParseNextParameter)."""

    def __init__(self, query: str):
        # split on BOTH separators (mixed "a=1&b=2;c=3" is legal to the
        # reference's parser), then decode each pair
        self._pairs: list[tuple[str, str]] = []
        for part in re.split("[&;]", query.lstrip("?")):
            if not part:
                continue
            for name, val in parse_qsl(part, keep_blank_values=True):
                self._pairs.append((name.lower(), val))

    def get(self, name: str, default: str | None = None) -> str | None:
        name = name.lower()
        for n, v in self._pairs:
            if n == name:
                return v
        return default

    def get_all(self, name: str) -> list[str]:
        name = name.lower()
        return [v for n, v in self._pairs if n == name]

    def __len__(self) -> int:
        return len(self._pairs)

    def __iter__(self):
        return iter(self._pairs)


def rfc1123_date(ts: float | None = None) -> str:
    """Unix time → ``Sun, 06 Nov 1994 08:49:37 GMT`` (DateTranslator's
    UpdateDateBuffer format, also the HTTP Date header)."""
    return email.utils.formatdate(
        time.time() if ts is None else ts, usegmt=True)


def parse_rfc1123(value: str) -> float | None:
    """Inverse of ``rfc1123_date``; honors the timezone field; None on
    unparseable input."""
    parsed = email.utils.parsedate_tz(value)
    if parsed is None:
        return None
    return float(email.utils.mktime_tz(parsed))
