"""Health-driven degradation ladder — per-stream graceful fallback.

Every relay stream sits on one rung of a four-rung ladder:

====  ===========  ====================================================
rung  name         what serves the stream
====  ===========  ====================================================
0     megabatch    the cross-stream stacked device pass (full service)
1     device       per-stream TPU engine (no coalescing)
2     cpu          the scalar CPU oracle (``RelayStream.reflect``)
3     shed         CPU oracle + the newest subscribers are shed one per
                   maintenance tick until the stream keeps up
====  ===========  ====================================================

Rung 0/1/2 already exist as code paths (the engine fallback discipline
the north star requires); this module adds the *state machine* that
moves streams between them:

* **Down** — a device error (real or injected) first gets **bounded
  retry-with-backoff**: the stream serves via the CPU oracle for an
  exponentially growing backoff window, then retries its device path.
  Only ``max_retries`` consecutive failures change the rung.  A
  megabatch-scheduler failure degrades every engaged rung-0 stream to
  rung 1 (per-stream stepping is the scheduler's own fallback).  At
  rung 2, sustained stall growth (slow subscribers) degrades to rung 3,
  where the server sheds the newest subscriber per tick — the reference
  would simply let everyone lag.
* **Up** — time hysteresis: one rung per maintenance tick, only after
  ``recover_sec`` with no errors and no rung change (so a flapping
  device cannot oscillate the ladder at tick rate).
* **SLO coupling** — on an SLO violation rising edge the watchdog's
  worst-offender stream is degraded one rung (the quality analogue of a
  device error).

Every transition updates ``resilience_ladder_level{stream}``, counts
``resilience_transitions_total{direction}`` and emits one latched
``ladder.degrade`` / ``ladder.recover`` event (per transition, never per
tick).  ``tools/soak.py --chaos`` fails on any stream still below rung 0
at exit or any degrade without a matching recover.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from .. import obs

#: rung names, index == level (the ``resilience_ladder_level`` value)
RUNGS = ("megabatch", "device", "cpu", "shed")
LEVEL_FULL, LEVEL_DEVICE, LEVEL_CPU, LEVEL_SHED = range(4)


@dataclass(frozen=True)
class LadderConfig:
    """Mirrored 1:1 from the ``resilience_*`` ServerConfig keys."""

    recover_sec: float = 10.0        # clean time before climbing one rung
    max_retries: int = 3             # device errors retried before a drop
    backoff_ms: float = 250.0        # first retry backoff (doubles, capped)
    backoff_cap_ms: float = 4000.0
    shed_stall_growth: int = 50      # stalls/tick at rung 2 → rung 3


class _Health:
    __slots__ = ("level", "retries", "backoff_until", "last_error",
                 "last_change", "prev_stalls")

    def __init__(self):
        self.level = LEVEL_FULL
        self.retries = 0
        self.backoff_until = 0.0     # monotonic; retrying while < now
        self.last_error = 0.0
        self.last_change = 0.0
        self.prev_stalls: int | None = None


class DegradationLadder:
    """One per server; the pump consults ``engine_mode`` per stream per
    wake and the 1 Hz maintenance block drives ``tick``."""

    def __init__(self, config: LadderConfig | None = None, *,
                 clock=time.monotonic, events=None, gauge=None,
                 transitions=None, retries=None):
        self.config = config or LadderConfig()
        self._clock = clock
        self._events = events if events is not None else obs.EVENTS
        self._gauge = gauge if gauge is not None \
            else obs.RESILIENCE_LADDER_LEVEL
        self._transitions = transitions if transitions is not None \
            else obs.RESILIENCE_TRANSITIONS
        self._retries = retries if retries is not None \
            else obs.RESILIENCE_RETRIES
        self._streams: dict[str, _Health] = {}
        self._slo_was_violating = False
        self.degrades = 0
        self.recovers = 0

    # -- read side --------------------------------------------------------
    def _h(self, path: str) -> _Health:
        h = self._streams.get(path)
        if h is None:
            h = self._streams[path] = _Health()
        return h

    def level(self, path: str | None) -> int:
        h = self._streams.get(path or "")
        return h.level if h is not None else LEVEL_FULL

    def engine_mode(self, path: str | None, now: float | None = None) -> int:
        """Effective rung for THIS wake: the stream's level, except that
        a device-retry backoff window serves via the CPU oracle without
        a rung change — the bounded-retry half of the contract."""
        h = self._streams.get(path or "")
        if h is None:
            return LEVEL_FULL
        if h.level < LEVEL_CPU and h.backoff_until:
            if (self._clock() if now is None else now) < h.backoff_until:
                return LEVEL_CPU
        return h.level

    def allows_megabatch(self, path: str | None) -> bool:
        return self.engine_mode(path) == LEVEL_FULL

    def worst_level(self) -> int:
        return max((h.level for h in self._streams.values()), default=0)

    def status(self) -> dict:
        return {path: {"level": h.level, "rung": RUNGS[h.level],
                       "retries": h.retries}
                for path, h in sorted(self._streams.items())}

    # -- error inputs -----------------------------------------------------
    def note_device_error(self, path: str | None,
                          now: float | None = None, *,
                          reason: str = "device_errors") -> None:
        """A device-path failure (dispatch exception, injected fault) on
        one stream: retry with exponential backoff; past ``max_retries``
        consecutive failures, drop one rung (0→1 or 1→2).  The cluster
        pull envelope charges upstream-pull failures through the same
        machinery with ``reason="pull_errors"`` — a broken pull degrades
        the stream's rung, it never kills the session."""
        if path is None:
            return
        now = self._clock() if now is None else now
        h = self._h(path)
        if h.level >= LEVEL_CPU:
            # no device work left to fail; crucially, do NOT refresh
            # last_error — a non-device exception leaking in here must
            # not hold the clean-window clock and pin the stream on the
            # CPU oracle forever
            return
        h.last_error = now
        h.retries += 1
        if h.retries <= self.config.max_retries:
            backoff = min(self.config.backoff_ms
                          * (2 ** (h.retries - 1)),
                          self.config.backoff_cap_ms) / 1000.0
            h.backoff_until = now + backoff
            self._retries.inc()
        else:
            self._degrade(path, h, now, reason=reason)

    def note_device_ok(self, path: str | None,
                       now: float | None = None) -> None:
        """A successful device pass with retries pending.  The budget
        resets only after a SUSTAINED clean stretch (``recover_sec``):
        a fault every few seconds with successes in between is a sick
        device, not a string of independent transients — interleaved
        successes must not hold the rung forever."""
        h = self._streams.get(path or "")
        if h is None or not h.retries:
            return
        now = self._clock() if now is None else now
        if now - h.last_error >= self.config.recover_sec:
            h.retries = 0
            h.backoff_until = 0.0

    def note_scheduler_error(self, paths, now: float | None = None) -> None:
        """A megabatch-scheduler failure (the pump already degraded the
        WAKE to per-stream stepping): charge every engaged rung-0 stream
        a device error, so persistent scheduler faults latch those
        streams onto rung 1 instead of re-failing every wake."""
        now = self._clock() if now is None else now
        for path in paths:
            if path is not None and self.level(path) == LEVEL_FULL:
                self.note_device_error(path, now)

    # -- the tick ---------------------------------------------------------
    def tick(self, stalls: dict[str, int] | None = None, *,
             slo_status: dict | None = None, offender: str | None = None,
             now: float | None = None) -> None:
        """Once per 1 Hz maintenance block.  ``stalls`` maps live stream
        paths to their cumulative stall counters (drives rung 2→3 and
        prunes dead paths); ``slo_status``/``offender`` couple the SLO
        watchdog's burn signal in."""
        now = self._clock() if now is None else now
        cfg = self.config
        if stalls is not None:
            for path in [p for p in self._streams if p not in stalls]:
                del self._streams[path]
                self._gauge.remove(stream=path)
        # SLO burn rising edge: the worst-p99 session pays one rung
        if slo_status is not None:
            violating = any(o.get("in_violation")
                            for o in (slo_status.get("objectives")
                                      or {}).values())
            if violating and not self._slo_was_violating and offender:
                h = self._h(offender)
                h.last_error = now
                if h.level < LEVEL_SHED:
                    self._degrade(offender, h, now, reason="slo_burn")
            self._slo_was_violating = violating
        for path, h in self._streams.items():
            cur = stalls.get(path) if stalls is not None else None
            if cur is not None:
                growth = cur - (h.prev_stalls
                                if h.prev_stalls is not None else cur)
                h.prev_stalls = cur
                if (h.level == LEVEL_CPU
                        and growth >= cfg.shed_stall_growth):
                    h.last_error = now
                    self._degrade(path, h, now, reason="stall_growth")
                    continue
            if (h.level > LEVEL_FULL
                    and now - h.last_error >= cfg.recover_sec
                    and now - h.last_change >= cfg.recover_sec):
                self._recover(path, h, now)

    def shed_candidate(self, stream):
        """The newest subscriber of ``stream`` (last output of the last
        bucket) — what rung 3 sheds, one per tick, never the last one
        (an empty stream would instantly 'recover')."""
        if stream.num_outputs <= 1:
            return None
        for bucket in reversed(stream.buckets):
            if bucket:
                return bucket[-1]
        return None

    # -- transitions ------------------------------------------------------
    def _degrade(self, path: str, h: _Health, now: float,
                 reason: str) -> None:
        frm = h.level
        h.level = min(h.level + 1, LEVEL_SHED)
        if h.level == frm:
            return
        h.retries = 0
        h.backoff_until = 0.0
        h.last_change = now
        self.degrades += 1
        self._gauge.set(h.level, stream=path)
        self._transitions.inc(direction="down")
        self._events.emit("ladder.degrade", level="warn", stream=path,
                          rung=RUNGS[h.level], from_rung=RUNGS[frm],
                          reason=reason)

    def _recover(self, path: str, h: _Health, now: float) -> None:
        frm = h.level
        h.level -= 1
        # NOT last_error: after one clean window the stream climbs one
        # rung per tick, so a deep degradation recovers in seconds, not
        # rungs × recover_sec (the 30 s post-clearance budget)
        h.last_change = 0.0
        self.recovers += 1
        self._gauge.set(h.level, stream=path)
        self._transitions.inc(direction="up")
        self._events.emit("ladder.recover", stream=path,
                          rung=RUNGS[h.level], from_rung=RUNGS[frm])
