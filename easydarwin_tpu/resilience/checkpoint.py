"""Session checkpoint / hot-restore — relay state that survives a crash.

ARCHITECTURE §1 made every piece of relay bookkeeping a plain integer
(absolute ring ids, affine rewrite 5-tuples, RR accounting), exactly so
it could be shipped anywhere — including to disk.  This module
serializes that bookkeeping for every live relay session to
``<log_folder>/ckpt/relay.json`` (atomic tmp+rename, one compact JSON
document) and restores it on startup, so a supervisor-restarted server
resumes live relays **without re-SETUP**:

* **ring cursors** — ``head`` is restored (``tail = head``: the packet
  *bytes* died with the process, but absolute ids keep counting, so
  every bookmark/keyframe invariant survives);
* **subscriber rewrite state** — the affine 5-tuple per output plus the
  sent counters.  The rewrite is a pure function of that state, so the
  first packet after restore carries exactly the seq/ts/ssrc an
  uninterrupted run would have produced — byte-identical, and
  differential-tested that way (``tests/test_resilience.py``);
* **RR accounting + reporter identity** — upstream receiver reports
  continue on the same extended-seq timeline;
* **keyframe index** — restored as an id; ``ring.valid()`` guards the
  (gone) bytes, so late joiners simply fast-start from the next GOP.

UDP subscribers restore transparently (``kind="udp"``: the shared-
egress address pair is the whole transport — the client never learns
the server died).  Interleaved-TCP subscribers (``kind="tcp"``, ISSUE
14) record their channel ids + RTSP session id; their connections died
with the process, so the records PARK on the server and are adopted
when the same player re-attaches (an interleaved SETUP carrying the old
``Session`` id) — same ssrc, framed seq continuing gapless.  Records no
player reclaims within the RTSP timeout are discarded, counted as
``resilience_checkpoint_tcp_orphans_total`` with a ``ckpt.tcp_orphan``
event.  Time-domain fields (arrival clocks,
SR cadence, wall anchors) are deliberately NOT restored — the monotonic
clock restarts with the process, so they re-latch on first use.

Versioned (``CKPT_VERSION``); a version mismatch or a checkpoint older
than ``max_age_sec`` is ignored (a stale file must not resurrect last
week's sessions).  Families: ``resilience_checkpoint_writes_total``,
``…_bytes_total``, ``…_restores_total``, ``…_errors_total``; events
``ckpt.save`` / ``ckpt.restore``.
"""

from __future__ import annotations

import json
import math
import os
import time

from .. import obs

#: checkpoint document format version; readers reject anything else
CKPT_VERSION = 1
#: file name inside the ``ckpt/`` directory
CKPT_FILE = "relay.json"


# -- snapshot ------------------------------------------------------------
def _snapshot_output(out, bucket_idx: int) -> dict:
    rw = out.rewrite
    if getattr(out, "native_addr", None) is not None:
        kind = "udp"
    elif getattr(out, "interleave_chan", None) is not None:
        kind = "tcp"
    else:
        kind = "opaque"
    rec = {
        "kind": kind,
        "bucket": bucket_idx,
        "rewrite": [rw.ssrc, rw.base_src_seq, rw.base_src_ts,
                    rw.out_seq_start, rw.out_ts_start],
        "packets_sent": out.packets_sent,
        "bytes_sent": out.bytes_sent,
        "payload_octets": out.payload_octets,
    }
    if kind == "udp":
        rec["rtp_addr"] = list(out.native_addr)
        rtcp = getattr(out, "rtcp_addr", None)
        rec["rtcp_addr"] = list(rtcp) if rtcp else None
    elif kind == "tcp":
        # interleaved outputs CAN restore (ISSUE 14): the rewrite state
        # is set-once ints, so when the same player re-attaches (its
        # old Session id on a fresh interleaved SETUP) the framed seq
        # space continues gapless.  The connection itself died with the
        # process — the record parks until the re-attach or the orphan
        # sweep.
        rec["channels"] = [out.rtp_channel, out.rtcp_channel]
        rec["session_id"] = getattr(out, "session_id", None)
    return rec


def _snapshot_stream(st) -> dict:
    return {
        "track": st.info.track_id,
        "head": st.rtp_ring.head,
        "keyframe_id": st.keyframe_id,
        "reporter_ssrc": st.reporter_ssrc,
        "rr": [st._rr_base_seq if st._rr_base_seq is not None else -1,
               st._rr_max_seq, st._rr_cycles, st._rr_received,
               st._rr_prev_expected, st._rr_prev_received],
        "packets_in": st.stats.packets_in,
        "packets_out": st.stats.packets_out,
        "outputs": [_snapshot_output(o, b)
                    for b, bucket in enumerate(st.buckets)
                    for o in bucket],
    }


def snapshot_session(registry, path: str, *,
                     node_id: str | None = None) -> dict | None:
    """One session's serializable record (the cluster tier publishes
    these per-stream to Redis for migration); None when the session is
    missing or not restorable (no cached SDP).

    Trace lineage (ISSUE 15): the record carries the stream's trace id
    and the node ids it has lived on (``node_id`` appended when given),
    so an adoption/hot-restore keeps correlating under the SAME trace —
    a stitched multi-hop trace spans the migration instead of breaking
    at it."""
    sess = registry.find(path)
    if sess is None:
        return None
    sdp = registry.sdp_cache.get(sess.path)
    if sdp is None:
        return None
    lineage = list(getattr(sess, "trace_nodes", ()) or ())
    if node_id is None:
        node_id = obs.NODE["id"]
    if node_id and (not lineage or lineage[-1] != node_id):
        lineage.append(str(node_id))
    return {
        "path": sess.path,
        "sdp": sdp,
        "trace": sess.trace_id,
        "trace_nodes": lineage,
        "streams": [_snapshot_stream(st) for st in sess.streams.values()],
    }


def snapshot_registry(registry) -> dict:
    """One serializable document for every live relay session (pure
    reads — safe from the pump's maintenance block)."""
    sessions = [doc for sess in registry.sessions.values()
                if (doc := snapshot_session(registry, sess.path))
                is not None]
    # truncate, never round: round() can stamp up to 0.5 ms in the
    # FUTURE, and a load() inside that window computes a negative age
    # and rejects the checkpoint it just wrote
    return {"version": CKPT_VERSION,
            "saved_wall": math.floor(time.time() * 1000) / 1000.0,
            "sessions": sessions}


# -- restore -------------------------------------------------------------
def _restore_stream(st, rec: dict, output_factory, *, path: str = "",
                    tcp_sink=None) -> int:
    ring = st.rtp_ring
    head = int(rec.get("head", 0))
    # the bytes are gone; the id space continues — every bookmark and
    # eviction invariant holds with an empty [head, head) window
    ring.head = ring.tail = head
    # merging into a LIVE session (cluster migration onto a node that
    # was pull-serving this path): pre-existing subscribers' bookmarks
    # live in the old local id space — one ahead of the restored head
    # would stall silently until new ids caught up.  Resume them at the
    # next ingested packet, exactly like the restored outputs below.
    for out in st.outputs:
        if out.bookmark is not None and out.bookmark > head:
            out.bookmark = head
    kf = rec.get("keyframe_id")
    st.keyframe_id = int(kf) if kf is not None else None
    st.reporter_ssrc = int(rec.get("reporter_ssrc", st.reporter_ssrc))
    rr = rec.get("rr") or [-1, 0, 0, 0, 0, 0]
    st._rr_base_seq = None if rr[0] < 0 else int(rr[0])
    st._rr_max_seq, st._rr_cycles, st._rr_received = \
        int(rr[1]), int(rr[2]), int(rr[3])
    st._rr_prev_expected, st._rr_prev_received = int(rr[4]), int(rr[5])
    st.stats.packets_in = int(rec.get("packets_in", 0))
    st.stats.packets_out = int(rec.get("packets_out", 0))
    restored = 0
    for orec in rec.get("outputs", ()):
        if orec.get("kind") == "tcp":
            # the connection died with the process; park the record for
            # the re-attach path (rtsp SETUP with the old Session id)
            # instead of dropping it — the long-standing "recorded but
            # skipped" gap, closed (ISSUE 14)
            if tcp_sink is not None:
                tcp_sink(path, rec.get("track"), orec)
            continue
        out = output_factory(orec) if output_factory is not None else None
        if out is None:
            continue
        rw = orec.get("rewrite") or [0, -1, -1, 0, 0]
        out.rewrite.ssrc = int(rw[0])
        out.rewrite.base_src_seq = int(rw[1])
        out.rewrite.base_src_ts = int(rw[2])
        out.rewrite.out_seq_start = int(rw[3])
        out.rewrite.out_ts_start = int(rw[4])
        out.packets_sent = int(orec.get("packets_sent", 0))
        out.bytes_sent = int(orec.get("bytes_sent", 0))
        out.payload_octets = int(orec.get("payload_octets", 0))
        # resume at the next ingested packet: everything earlier either
        # reached the wire before the crash or died with the ring
        out.bookmark = head
        # the recorded bucket index pins the delay-stagger tier the
        # subscriber was serving in (first-fit would repack over holes)
        st.add_output(out, bucket=int(orec.get("bucket", 0)))
        restored += 1
    return restored


def restore_registry(registry, doc: dict, *, output_factory=None,
                     tcp_sink=None) -> tuple[int, int]:
    """Rebuild sessions/streams/outputs from a checkpoint document into
    ``registry``.  ``output_factory(record) -> RelayOutput | None``
    builds the transport for each recorded output (None skips it — the
    default, since only the server knows its egress).
    ``tcp_sink(path, track_id, record)`` receives each ``kind=tcp``
    record — interleaved outputs have no transport until their player
    re-connects, so the server parks them for the SETUP re-attach path.
    Returns ``(sessions, outputs)`` restored (parked TCP records are
    not counted until they re-attach)."""
    n_out = 0
    n_sess = 0
    for srec in doc.get("sessions", ()):
        path, sdp = srec.get("path"), srec.get("sdp")
        if not path or not sdp:
            continue
        try:
            sess = registry.find_or_create(path, sdp)
        except Exception:
            obs.RESILIENCE_CKPT_ERRORS.inc()
            continue
        n_sess += 1
        # trace lineage survives the restore: the stream keeps the trace
        # id it was born with, so spans/events recorded on the previous
        # owner and on this node stitch under ONE id (ISSUE 15)
        trace = srec.get("trace")
        if trace:
            sess.set_trace(str(trace))
            sess.trace_nodes = [str(n) for n in
                                (srec.get("trace_nodes") or ())]
        by_track = {s.get("track"): s for s in srec.get("streams", ())}
        for tid, st in sess.streams.items():
            rec = by_track.get(tid)
            if rec is not None:
                n_out += _restore_stream(st, rec, output_factory,
                                         path=path, tcp_sink=tcp_sink)
    return n_sess, n_out


class CheckpointManager:
    """Periodic writer + startup restorer for one server's relay state."""

    def __init__(self, ckpt_dir: str, *, interval_sec: float = 5.0,
                 max_age_sec: float = 60.0, clock=time.monotonic):
        self.ckpt_dir = ckpt_dir
        self.path = os.path.join(ckpt_dir, CKPT_FILE)
        self.interval_sec = interval_sec
        self.max_age_sec = max_age_sec
        self._clock = clock
        self._last_write: float | None = None  # None = write immediately
        self.writes = 0
        self.restores = 0

    # -- write side -------------------------------------------------------
    def maybe_write(self, registry, now: float | None = None) -> bool:
        now = self._clock() if now is None else now
        if (self._last_write is not None
                and now - self._last_write < self.interval_sec):
            return False
        self._last_write = now
        return self.write(registry)

    def write(self, registry) -> bool:
        """Atomic snapshot write; failures count, never raise — a full
        disk must not take the pump down."""
        doc = snapshot_registry(registry)
        blob = json.dumps(doc, separators=(",", ":"))
        try:
            os.makedirs(self.ckpt_dir, exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(blob)
            os.replace(tmp, self.path)
        except OSError:
            obs.RESILIENCE_CKPT_ERRORS.inc()
            return False
        self.writes += 1
        obs.RESILIENCE_CKPT_WRITES.inc()
        obs.RESILIENCE_CKPT_BYTES.inc(len(blob))
        obs.EVENTS.emit("ckpt.save", level="debug",
                        sessions=len(doc["sessions"]), bytes=len(blob))
        return True

    # -- restore side -----------------------------------------------------
    def load(self) -> dict | None:
        """The checkpoint document, or None when missing, unreadable,
        version-mismatched or older than ``max_age_sec`` (stale files
        must not resurrect long-dead sessions)."""
        try:
            with open(self.path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(doc, dict) or doc.get("version") != CKPT_VERSION:
            obs.RESILIENCE_CKPT_ERRORS.inc()
            return None
        age = time.time() - float(doc.get("saved_wall", 0))
        # -1 s tolerance: a small NTP step between write and load must
        # not make a just-written checkpoint look future-dated; a file
        # from a genuinely wrong clock is still rejected
        if not -1.0 <= age <= self.max_age_sec:
            return None
        return doc

    def restore(self, registry, *, output_factory=None,
                tcp_sink=None) -> tuple[int, int]:
        """Load + rebuild; returns ``(sessions, outputs)`` restored
        (``(0, 0)`` when there is nothing usable)."""
        doc = self.load()
        if doc is None:
            return (0, 0)
        n_sess, n_out = restore_registry(registry, doc,
                                         output_factory=output_factory,
                                         tcp_sink=tcp_sink)
        if n_sess:
            self.restores += 1
            obs.RESILIENCE_CKPT_RESTORES.inc()
            obs.EVENTS.emit("ckpt.restore", sessions=n_sess,
                            outputs=n_out)
        return n_sess, n_out
