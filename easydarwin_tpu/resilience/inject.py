"""Deterministic fault injection — chaos as a regression test.

A :class:`FaultPlan` is a small, seeded description of *which* faults to
provoke and *how often*; the process-wide :data:`INJECTOR` executes it at
the sites the relay hot path exposes:

==================  ====================================================
site                where it bites
==================  ====================================================
``ingest_drop``     ``RelayStream.push_rtp`` discards the packet
``ingest_reorder``  push_rtp holds one packet and releases it after the
                    next (adjacent swap — the classic UDP reorder)
``ingest_corrupt``  one payload byte (never the 12-byte header) flipped
``egress_native``   ``csrc`` ``ed_fault_*`` knobs: every Nth native send
                    call fails EAGAIN / ENOBUFS, or sleeps a latency
                    spike before the syscall
``device_dispatch`` the engine/megabatch device query raises
                    :class:`InjectedFault` (a transient device error)
``stale_params``    the engine's cached affine params / megabatch
                    override are invalidated, forcing the slow path
``slow_subscriber`` a Python-path output write reports WOULD_BLOCK
                    (bookmark replay backpressure)
==================  ====================================================

**Determinism.**  Probability sites draw from per-site
``random.Random(seed ^ crc32(site))`` streams, so the decision sequence
for one site depends only on the plan seed and that site's call count —
never on how calls to *other* sites interleave.  Every-N sites are plain
counters.  ``tests/test_resilience.py`` pins same-seed → same-schedule.

**Observability.**  Every injection counts into
``fault_injected_total{site}`` and emits a rate-limited ``fault.injected``
event (one per site per second, carrying the count accumulated since the
last emit) — so a flight-recorder dump shows the cause next to the
effect without the event ring drowning in per-packet records.  The
native-egress injections are counted by the C side into
``ed_stats.fault_injections`` and mirrored by the scrape collector.
"""

from __future__ import annotations

import random
import time
import zlib
from dataclasses import dataclass, fields, replace

from .. import obs

#: the closed injection-site vocabulary (the ``site`` label of
#: ``fault_injected_total``; ``egress_native`` is counted by csrc).
#: The cluster sites (ISSUE 6): ``lease_loss`` deletes this node's own
#: Redis lease mid-heartbeat (a simulated TTL expiry — peers adopt its
#: streams), ``redis_partition`` makes a cluster tick's Redis access
#: time out, ``pull_stall`` freezes a cross-server pull's read loop so
#: the retry/backoff envelope must recover it.
#: The receiver-side sites (ISSUE 11): ``egress_drop`` silently loses a
#: Python-path delivered packet AFTER the send accounting (the wire ate
#: it — the reliability tier must notice via RR/NACK, never the
#: sender's counters), and ``rr_loss_spoof`` replaces the
#: ``fraction_lost`` of every inbound receiver report so the closed-
#: loop FEC controller can be driven without a lossy wire.
#: The control-plane sites (ISSUE 13): ``capacity_spoof`` replaces the
#: capacity score a node believes in and publishes (lie low → the node
#: over-reports utilization, burns, and the rebalancer/admission paths
#: fire; lie high → it hoards keyspace on the weighted ring), and
#: ``overload_spoof`` forces an admission check to read past the
#: high-water mark (seeded probability stream) so the 453/redirect
#: paths are chaos-testable without real load.
SITES = ("ingest_drop", "ingest_reorder", "ingest_corrupt",
         "egress_native", "device_dispatch", "stale_params",
         "slow_subscriber", "lease_loss", "redis_partition",
         "pull_stall", "egress_drop", "rr_loss_spoof",
         "capacity_spoof", "overload_spoof")

#: minimum seconds between ``fault.injected`` events per site
EMIT_INTERVAL_S = 1.0


class InjectedFault(RuntimeError):
    """A deliberately provoked transient failure (device dispatch)."""


@dataclass(frozen=True)
class FaultPlan:
    """Seeded, config-driven fault schedule.

    Zero means "site disabled".  Parse from the ``resilience_fault_plan``
    config key / ``--chaos`` spec with :meth:`parse` (``k=v`` pairs,
    comma-separated): ``"seed=7,ingest_drop=0.05,egress_enobufs_every=300"``.
    """

    seed: int = 0
    # -- ingest (probability per packet) ---------------------------------
    ingest_drop: float = 0.0
    ingest_reorder: float = 0.0
    ingest_corrupt: float = 0.0
    # -- native egress (deterministic every-N send calls; csrc knobs) ----
    egress_eagain_every: int = 0
    egress_enobufs_every: int = 0
    egress_latency_every: int = 0
    egress_latency_us: int = 0
    # -- device tier -----------------------------------------------------
    device_error_every: int = 0        # every Nth device dispatch raises
    device_error_period_s: float = 0.0  # … or at most one per period
    stale_params_every: int = 0
    # -- subscriber backpressure (deterministic: every Nth python-path
    # write reports WOULD_BLOCK; 0.05 is NOT a probability — it coerces
    # to 0 and disables the site) ----------------------------------------
    slow_sub_every: int = 0
    # -- cluster tier (deterministic every-N; see SITES above) -----------
    lease_loss_every: int = 0          # Nth heartbeat finds the lease gone
    redis_partition_every: int = 0     # Nth cluster tick's Redis times out
    pull_stall_every: int = 0          # Nth pull liveness probe stalls
    # -- receiver-side loss (ISSUE 11): probability a delivered Python-
    # path packet is silently lost after send accounting; the spoofed
    # fraction_lost (0..1) stamped onto every inbound RR while armed ---
    egress_drop: float = 0.0
    rr_loss_spoof: float = 0.0
    # -- control plane (ISSUE 13): the capacity score this node believes
    # in and publishes is REPLACED by this value when > 0 (deterministic
    # — the skewed soak forces a heterogeneous cluster with it); the
    # probability an admission check reads "past the high-water mark"
    # regardless of real utilization -------------------------------------
    capacity_spoof: float = 0.0
    overload_spoof: float = 0.0

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """``k=v,k=v`` → FaultPlan; unknown keys raise (a typo'd chaos
        plan that silently injects nothing is worse than an error)."""
        plan = cls()
        if not spec.strip():
            return plan
        types = {f.name: f.type for f in fields(cls)}
        kw = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            k, _, v = part.partition("=")
            k = k.strip()
            if k not in types:
                raise ValueError(f"unknown fault-plan key {k!r} "
                                 f"(known: {sorted(types)})")
            kw[k] = float(v) if types[k] == "float" else int(float(v))
        return replace(plan, **kw)

    def to_spec(self) -> str:
        out = []
        for f in fields(self):
            v = getattr(self, f.name)
            if v:
                out.append(f"{f.name}={v}")
        return ",".join(out)

    def any_active(self) -> bool:
        return any(getattr(self, f.name) for f in fields(self)
                   if f.name != "seed")


class FaultInjector:
    """Executes one :class:`FaultPlan`; disabled (``active=False``) by
    default so the hot-path hooks cost one attribute check."""

    def __init__(self, *, events=None, counter=None, clock=time.monotonic):
        self.plan: FaultPlan | None = None
        self.active = False
        self._clock = clock
        self._events = events if events is not None else obs.EVENTS
        self._counter = counter if counter is not None \
            else obs.FAULT_INJECTED
        self._rng: dict[str, random.Random] = {}
        self._count: dict[str, int] = {}
        self._last_emit: dict[str, float] = {}
        self._pending: dict[str, int] = {}     # injections since last emit
        #: None = the period timer starts EXPIRED (the first dispatch
        #: after arming fires, then one per period — "one failure per
        #: minute" means the minute starts with one, not after one)
        self._last_device_error: float | None = None

    # -- lifecycle --------------------------------------------------------
    def arm(self, plan: FaultPlan) -> None:
        """Install a plan and reset every deterministic stream — arming
        the same seed twice replays the identical schedule."""
        self.plan = plan
        self._rng = {s: random.Random((plan.seed << 16)
                                      ^ zlib.crc32(s.encode()))
                     for s in SITES}
        self._count = {s: 0 for s in SITES}
        self._pending = {}
        self._last_emit = {}
        self._last_device_error = None
        self._push_native(plan)
        self.active = plan.any_active()

    def disarm(self) -> None:
        self.plan = None
        self.active = False
        self._push_native(None)

    @staticmethod
    def _push_native(plan: FaultPlan | None) -> None:
        """Mirror the egress knobs into csrc.  A plan that actually uses
        them FORCE-LOADS the library (arming chaos is an explicit
        operator action, and the server arms before anything else has
        touched native — a loaded()-only check would silently leave the
        egress fault-free for the whole run); plans without egress knobs
        and disarms never trigger a load/build."""
        from .. import native
        if plan is not None and (plan.egress_eagain_every
                                 or plan.egress_enobufs_every
                                 or plan.egress_latency_every):
            if not native.available():
                return                 # no native core: knobs can't bite
            native.fault_set(plan.egress_eagain_every,
                             plan.egress_enobufs_every,
                             plan.egress_latency_every,
                             plan.egress_latency_us)
            return
        if native.loaded():
            native.fault_clear()

    # -- accounting -------------------------------------------------------
    def _note(self, site: str, n: int = 1) -> None:
        self._count[site] = self._count.get(site, 0) + n
        self._counter.inc(n, site=site)
        self._pending[site] = self._pending.get(site, 0) + n
        now = self._clock()
        if now - self._last_emit.get(site, 0.0) >= EMIT_INTERVAL_S:
            self._last_emit[site] = now
            self._events.emit("fault.injected", site=site,
                              count=self._pending.pop(site, 0))

    def counts(self) -> dict[str, int]:
        """Injections per site (the ``_<site>_calls`` attempt counters
        the every-N streams keep are internal and excluded)."""
        return {k: v for k, v in self._count.items()
                if not k.startswith("_")}

    # -- decision streams -------------------------------------------------
    def _fire(self, site: str, prob: float) -> bool:
        if prob <= 0.0:
            return False
        return self._rng[site].random() < prob

    def _every(self, site: str, n: int) -> bool:
        if n <= 0:
            return False
        c = self._count.get(f"_{site}_calls", 0) + 1
        self._count[f"_{site}_calls"] = c
        return c % n == 0

    # -- sites ------------------------------------------------------------
    def ingest(self, packet: bytes, hold: list) -> list[bytes]:
        """The ingest gauntlet: returns the packets to actually push
        (possibly empty for a drop/hold, possibly two for a release).

        ``hold`` is the CALLER-owned one-slot reorder buffer (the stream
        passes its own) — a held packet must die with its stream, never
        sit in an injector-side map where a recycled ``id()`` could
        release it into an unrelated stream's ring (the same id-reuse
        hazard the megabatch cursor pruning guards against)."""
        p = self.plan
        if p is None:
            return [packet]
        if self._fire("ingest_drop", p.ingest_drop):
            self._note("ingest_drop")
            return []
        if p.ingest_corrupt and len(packet) > 12 \
                and self._fire("ingest_corrupt", p.ingest_corrupt):
            rng = self._rng["ingest_corrupt"]
            off = 12 + rng.randrange(len(packet) - 12)
            mut = bytearray(packet)
            mut[off] ^= 0xFF
            self._note("ingest_corrupt")
            packet = bytes(mut)
        if p.ingest_reorder:
            if hold:
                return [packet, hold.pop()]    # adjacent swap completes
            if self._fire("ingest_reorder", p.ingest_reorder):
                self._note("ingest_reorder")
                hold.append(packet)            # held for the next push
                return []
        return [packet]

    def ingest_ring(self, ring, start: int, stop: int) -> None:
        """The ingest gauntlet for natively-drained packets (recvmmsg
        lands them straight in ring slots, so faults mutate in place):
        a drop zeroes the slot's length+flags — downstream treats it as
        a runt and never relays it; corruption flips one payload byte.
        Reorder only exists on the push path (slots are already
        sequenced by the time the drain returns).  Draws from the SAME
        per-site streams as the push path."""
        p = self.plan
        if p is None or not (p.ingest_drop or p.ingest_corrupt):
            return
        for pid in range(start, stop):
            s = ring.slot(pid)
            if self._fire("ingest_drop", p.ingest_drop):
                ring.length[s] = 0
                ring.flags[s] = 0
                self._note("ingest_drop")
                continue
            n = int(ring.length[s])
            if n > 12 and self._fire("ingest_corrupt", p.ingest_corrupt):
                off = 12 + self._rng["ingest_corrupt"].randrange(n - 12)
                ring.data[s, off] ^= 0xFF
                self._note("ingest_corrupt")

    def device_dispatch(self, where: str) -> None:
        """Raises :class:`InjectedFault` when a device-dispatch failure
        is due (count-based ``device_error_every`` OR at most one per
        ``device_error_period_s``)."""
        p = self.plan
        if p is None:
            return
        due = self._every("device_dispatch", p.device_error_every)
        if not due and p.device_error_period_s > 0:
            now = self._clock()
            if (self._last_device_error is None
                    or now - self._last_device_error
                    >= p.device_error_period_s):
                self._last_device_error = now
                due = True
        if due:
            self._note("device_dispatch")
            raise InjectedFault(f"injected device-dispatch failure "
                                f"at {where}")

    def stale_params(self) -> bool:
        p = self.plan
        if p is None or not self._every("stale_params",
                                        p.stale_params_every):
            return False
        self._note("stale_params")
        return True

    def slow_subscriber(self) -> bool:
        p = self.plan
        if p is None or not self._every("slow_subscriber",
                                        p.slow_sub_every):
            return False
        self._note("slow_subscriber")
        return True

    def egress_drop(self) -> bool:
        """True when a delivered Python-path packet should be silently
        lost (receiver-side loss synthesized without touching the wire;
        the seeded per-site stream makes one seed = one loss schedule).
        Consumed by ``RelayOutput.write_rtp``/``send_rewritten`` AND by
        harness-side receivers (the lossy soak player) — each caller
        owns its own armed injector, so schedules never interleave."""
        p = self.plan
        if p is None or not self._fire("egress_drop", p.egress_drop):
            return False
        self._note("egress_drop")
        return True

    def rr_loss_spoof(self) -> float | None:
        """The spoofed ``fraction_lost`` (0..1) to stamp onto an inbound
        receiver report, or None when the site is disarmed — drives the
        closed-loop FEC controller without a lossy wire."""
        p = self.plan
        if p is None or p.rr_loss_spoof <= 0.0:
            return None
        self._note("rr_loss_spoof")
        return min(p.rr_loss_spoof, 1.0)

    def capacity_spoof(self) -> float | None:
        """The lying capacity score (pps) to believe in and publish, or
        None when the site is disarmed.  Counted once per application
        (one per load sample — the heartbeat cadence)."""
        p = self.plan
        if p is None or p.capacity_spoof <= 0.0:
            return None
        self._note("capacity_spoof")
        return float(p.capacity_spoof)

    def overload_spoof(self) -> bool:
        """True when this admission check should read the node as past
        its high-water mark (seeded per-site probability stream — one
        seed = one refusal schedule)."""
        p = self.plan
        if p is None or not self._fire("overload_spoof", p.overload_spoof):
            return False
        self._note("overload_spoof")
        return True

    # -- cluster sites ----------------------------------------------------
    def lease_loss(self) -> bool:
        """True when this heartbeat should find its lease gone (the
        caller deletes its own lease key — indistinguishable from a TTL
        expiry to every peer)."""
        p = self.plan
        if p is None or not self._every("lease_loss", p.lease_loss_every):
            return False
        self._note("lease_loss")
        return True

    def redis_partition(self) -> bool:
        """True when this cluster tick's Redis access should time out."""
        p = self.plan
        if p is None or not self._every("redis_partition",
                                        p.redis_partition_every):
            return False
        self._note("redis_partition")
        return True

    def pull_stall(self) -> bool:
        """True when a cross-server pull's liveness probe should treat
        the upstream as stalled (forcing the retry envelope)."""
        p = self.plan
        if p is None or not self._every("pull_stall", p.pull_stall_every):
            return False
        self._note("pull_stall")
        return True


#: process-wide injector; ``active`` stays False until a plan is armed,
#: so the relay hot-path hooks cost one attribute check per call
INJECTOR = FaultInjector()
