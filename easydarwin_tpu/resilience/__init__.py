"""Resilience subsystem: fault injection, degradation ladder, checkpoint.

The observability layers (PR 1-3) can *describe* a failure; this package
makes the server survive one — and makes failure reproducible enough to
test that claim continuously:

* ``resilience.inject`` — a seeded, config-driven :class:`FaultPlan`
  executed by the process-wide :data:`INJECTOR`: packet drop / reorder /
  corruption at ingest, EAGAIN / ENOBUFS / latency spikes at the native
  egress (``csrc`` ``ed_fault_*`` knobs), device-dispatch exceptions and
  artificial stale params in the relay engines, and slow-subscriber
  backpressure.  Same seed → same injection schedule, so a chaos run is
  a regression test, not a dice roll.
* ``resilience.ladder`` — :class:`DegradationLadder`: a per-stream state
  machine megabatch → per-stream device → CPU oracle → shed-newest-
  subscribers with bounded retry-with-backoff before any rung change and
  time-hysteresis on the way back up, driven by device errors, SLO burn
  and injected-fault pressure.
* ``resilience.checkpoint`` — :class:`CheckpointManager`: periodic
  serialization of the relay bookkeeping (ring cursors, subscriber
  rewrite state, RR accounting — all plain integers by ARCHITECTURE §1)
  to ``<log_folder>/ckpt/``, restored on startup so a supervisor-
  restarted server resumes live relays without re-SETUP.

See ARCHITECTURE.md "Resilience".
"""

from .inject import (  # noqa: F401
    INJECTOR, FaultInjector, FaultPlan, InjectedFault)
from .ladder import (  # noqa: F401
    LEVEL_CPU, LEVEL_DEVICE, LEVEL_FULL, LEVEL_SHED, RUNGS,
    DegradationLadder, LadderConfig)
from .checkpoint import (  # noqa: F401
    CKPT_VERSION, CheckpointManager, snapshot_registry)
