"""Pallas kernel for the packet parse/classify hot op.

Same contract as ``parse.parse_packets`` (the jnp reference), fused into a
single VMEM pass per tile of packets.  TPU-friendly formulation: the only
data-dependent indices are the header-size-relative byte peeks
(``hs = 12 + 4·CC``), and CC has just 16 possible values — so each needed
byte is computed as a sum of 16 *static* column slices masked by
``CC == k``, avoiding per-row dynamic gathers entirely (Mosaic lowers the
whole kernel to vector selects).

Outputs are packed as two arrays to keep the out_specs simple:
``words  [P, 4] uint32``  — seq, timestamp, ssrc, payload_start
``flagsv [P, 5] int32``   — nal_type, keyframe_first, frame_first,
frame_last, marker
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .parse import PARSE_PREFIX, _AGG_OFFSETS, _KEYFRAME_TYPES, \
    _MIN_CLASSIFY_LEN

TILE = 256


def _byte_at_hs_plus(x: jnp.ndarray, cc: jnp.ndarray, delta: int
                     ) -> jnp.ndarray:
    """x[p, 12 + 4*cc[p] + delta] via 16 masked static slices."""
    out = jnp.zeros(x.shape[0], dtype=jnp.int32)
    for k in range(16):
        col = 12 + 4 * k + delta
        if col < x.shape[1]:
            out = jnp.where(cc == k, x[:, col], out)
    return out


def _parse_tile(x: jnp.ndarray, length: jnp.ndarray):
    b0, b1 = x[:, 0], x[:, 1]
    cc = b0 & 0x0F
    hs = 12 + 4 * cc
    seq = ((x[:, 2] << 8) | x[:, 3]).astype(jnp.uint32)
    ts = ((x[:, 4] << 24) | (x[:, 5] << 16) | (x[:, 6] << 8) | x[:, 7]
          ).astype(jnp.uint32)
    ssrc = ((x[:, 8] << 24) | (x[:, 9] << 16) | (x[:, 10] << 8) | x[:, 11]
            ).astype(jnp.uint32)
    marker = (b1 & 0x80) != 0
    classifiable = (length >= _MIN_CLASSIFY_LEN) & (length > hs)
    nal0 = _byte_at_hs_plus(x, cc, 0) & 0x1F
    eff = nal0
    for agg_type, off in _AGG_OFFSETS:
        inner = _byte_at_hs_plus(x, cc, off) & 0x1F
        eff = jnp.where((nal0 == agg_type) & (length > hs + off), inner, eff)
    fu_hdr = _byte_at_hs_plus(x, cc, 1)
    is_fu = (nal0 == 28) | (nal0 == 29)
    fu_start = is_fu & (length > hs + 1) & ((fu_hdr & 0x80) != 0)
    eff = jnp.where(fu_start, fu_hdr & 0x1F, eff)
    eff = jnp.where(classifiable, eff, -1)
    kf = jnp.zeros_like(eff, dtype=bool)
    for t in _KEYFRAME_TYPES:
        kf |= eff == t
    kf &= classifiable
    frame_first = classifiable & (((nal0 >= 1) & (nal0 <= 27)) | fu_start)
    frame_last = (length >= _MIN_CLASSIFY_LEN) & marker
    words = jnp.stack([seq, ts, ssrc, hs.astype(jnp.uint32)], axis=-1)
    flagsv = jnp.stack([eff, kf.astype(jnp.int32),
                        frame_first.astype(jnp.int32),
                        frame_last.astype(jnp.int32),
                        marker.astype(jnp.int32)], axis=-1)
    return words, flagsv


def _kernel(prefix_ref, length_ref, words_ref, flags_ref):
    x = prefix_ref[:].astype(jnp.int32)
    length = length_ref[:].astype(jnp.int32)
    words, flagsv = _parse_tile(x, length)
    words_ref[:] = words
    flags_ref[:] = flagsv


def parse_packets_pallas(prefix: jnp.ndarray, length: jnp.ndarray,
                         interpret: bool | None = None
                         ) -> dict[str, jnp.ndarray]:
    """Pallas-fused parse; same results as ``parse.parse_packets``.

    ``interpret`` defaults to True on the CPU backend (tests/fallback) and
    False on TPU.  Not jitted itself — callers jit the surrounding step.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.default_backend() == "cpu"

    n = prefix.shape[0]
    pad = (-n) % TILE
    if pad:
        prefix = jnp.concatenate(
            [prefix, jnp.zeros((pad, prefix.shape[1]), prefix.dtype)])
        length = jnp.concatenate([length, jnp.zeros(pad, length.dtype)])
    grid = prefix.shape[0] // TILE
    words, flagsv = pl.pallas_call(
        _kernel,
        out_shape=(jax.ShapeDtypeStruct((prefix.shape[0], 4), jnp.uint32),
                   jax.ShapeDtypeStruct((prefix.shape[0], 5), jnp.int32)),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((TILE, prefix.shape[1]), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE,), lambda i: (i,), memory_space=pltpu.VMEM),
        ],
        out_specs=(pl.BlockSpec((TILE, 4), lambda i: (i, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((TILE, 5), lambda i: (i, 0),
                                memory_space=pltpu.VMEM)),
        interpret=interpret,
    )(prefix, length.astype(jnp.int32))
    words, flagsv = words[:n], flagsv[:n]
    return {
        "seq": words[:, 0], "timestamp": words[:, 1], "ssrc": words[:, 2],
        "payload_start": words[:, 3].astype(jnp.int32),
        "nal_type": flagsv[:, 0],
        "keyframe_first": flagsv[:, 1].astype(bool),
        "frame_first": flagsv[:, 2].astype(bool),
        "frame_last": flagsv[:, 3].astype(bool),
        "marker": flagsv[:, 4].astype(bool),
    }
