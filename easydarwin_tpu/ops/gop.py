"""Device-side GOP bookkeeping — the keyframe index and ring window ops.

The reference maintains ``fKeyFrameStartPacketElementPointer`` (newest
IDR-start packet) by checking each packet on ingest and walking pointers
(``ReflectorStream.cpp:1292-1397``) plus a byte-oriented GOP cache
(``CKeyFrameCache``, 2 MB cap, ``keyframecache.cpp``).  On device both
collapse into masked reductions over the packet window.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def newest_keyframe(keyframe_first: jnp.ndarray,
                    valid: jnp.ndarray) -> jnp.ndarray:
    """Index of the newest valid keyframe-first packet, or -1.

    keyframe_first/valid: [P] bool → int32 scalar.
    """
    idx = jnp.arange(keyframe_first.shape[0], dtype=jnp.int32)
    cand = jnp.where(keyframe_first & valid, idx, -1)
    return jnp.max(cand)


@jax.jit
def gop_window_mask(keyframe_first: jnp.ndarray, valid: jnp.ndarray,
                    frame_last: jnp.ndarray) -> jnp.ndarray:
    """[P] bool mask of the current (newest) GOP: every packet from the
    newest keyframe-first onward.  The device equivalent of replaying
    ``CKeyFrameCache`` to a late joiner (``keyframecache.cpp:6-118`` resets
    the cache on each SPS and appends until the next)."""
    start = newest_keyframe(keyframe_first, valid)
    idx = jnp.arange(keyframe_first.shape[0], dtype=jnp.int32)
    return valid & (start >= 0) & (idx >= start)


@jax.jit
def fast_start_indices(keyframe_first: jnp.ndarray, valid: jnp.ndarray,
                       age_ms: jnp.ndarray, overbuffer_ms) -> jnp.ndarray:
    """First packet a brand-new output should receive (scalar int32):
    the newest in-window keyframe if one exists, else the oldest packet
    younger than the over-buffer window, else the newest valid packet —
    ``GetNewestKeyFrameFirstPacket`` + ``GetClientBufferStartPacketOffset``
    semantics (``ReflectorStream.cpp:1196-1240, 1310-1397``).
    ``age_ms`` is ``now − arrival`` per packet (int32)."""
    P = keyframe_first.shape[0]
    idx = jnp.arange(P, dtype=jnp.int32)
    age_ok = valid & (age_ms.astype(jnp.int32)
                      <= jnp.asarray(overbuffer_ms, jnp.int32))
    kf = newest_keyframe(keyframe_first & age_ok, valid)
    oldest_young = jnp.min(jnp.where(age_ok, idx, P))
    newest_valid = jnp.max(jnp.where(valid, idx, -1))
    fallback = jnp.where(oldest_young < P, oldest_young, newest_valid)
    return jnp.where(kf >= 0, kf, fallback).astype(jnp.int32)
