"""Device-tier ops (JAX/XLA/Pallas) — the TPU replacement for the
reference's per-packet × per-subscriber reflector loop.

Dataflow (north star, BASELINE config 4):

    host ring ──[P,96] byte prefixes + lengths + arrivals──▶ device
        parse.parse_packets      batched RTP header parse + H.264
                                 keyframe/frame classification
        gop.newest_keyframe      IDR bookmark scan
        fanout.fanout_headers    vmap over subscribers: seq/ts rebase +
                                 SSRC rewrite → [S,P,12] header bytes
        fanout.eligibility       per-bucket delay stagger mask
    device ──[S,P,12] headers + [S,P] mask──▶ host vectored egress

Only rewritten 12-byte headers cross back; payload bytes never leave host
memory (they are shared across all S subscribers and scattered with
``sendmsg`` iovecs).  The reference instead memcpy's every packet once per
subscriber (``ReflectorStream.cpp:1138 SendPacketsToOutput``).

``transform`` holds the MXU-path kernels (8×8 DCT/IDCT/quant as batched
matmuls) backing the config-5 transcode ladder.
"""

from . import fanout, gop, parse  # noqa: F401
