"""Batched RTP parse + H.264 classification on device.

Vectorized (fixed-shape, branch-free) equivalent of the host oracle in
``protocol.rtp`` / ``protocol.nalu`` — one fused XLA computation classifies a
whole packet window at once instead of the reference's per-packet calls
(``ReflectorSender::IsKeyFrameFirstPacket``, ``ReflectorStream.cpp:1403``).

Inputs are ``[P, W]`` uint8 byte *prefixes* plus ``[P]`` total lengths; W
must be ≥ ``PARSE_PREFIX`` (96): the deepest legal peek is CC=15 CSRCs +
the MTAP24 inner-NAL offset = byte 81, and ``_byte_at`` clamps
out-of-range column indices (a narrower buffer would silently classify
from the wrong byte rather than error).  All outputs are int32/bool
``[P]`` vectors.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

#: bytes of each packet staged to the device for parsing.  12 (fixed header)
#: + 60 (max CSRC) + 10 (deepest aggregation peek, MTAP24 offset 9) → 96
#: covers the worst legal case with headroom and keeps lanes aligned.
PARSE_PREFIX = 96

_KEYFRAME_TYPES = (5, 7, 8)
#: aggregation-type → inner-NAL peek offset (ReflectorStream.cpp:1465-1483)
_AGG_OFFSETS = ((24, 3), (25, 5), (26, 8), (27, 9))
_MIN_CLASSIFY_LEN = 20


def _byte_at(x: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """x: [P, W] int32, idx: [P] → x[p, idx[p]] with clamping."""
    idx = jnp.clip(idx, 0, x.shape[1] - 1)
    return jnp.take_along_axis(x, idx[:, None], axis=1)[:, 0]


def normalize_codec(codec: str) -> str:
    """Map SDP / user codec spellings onto the two classifier families.

    "H264"/"AVC" → "h264"; "JPEG"/"MJPEG" (RFC 2435) → "mjpeg".  Unknown
    names raise — silently falling through to the NALU walk would
    mis-classify every packet of a non-H.264 stream."""
    c = codec.strip().lower()
    if c in ("h264", "avc", "avc1", ""):
        return "h264"
    if c in ("mjpeg", "jpeg", "mjpg"):
        return "mjpeg"
    raise ValueError(f"unsupported video codec for device classify: {codec!r}")


def _fixed_header_fields(x: jnp.ndarray):
    """Shared RTP fixed-header extraction: (b0, b1, cc, hs, seq, ts, ssrc,
    marker) — the one place that knows the wire byte offsets."""
    b0, b1 = x[:, 0], x[:, 1]
    cc = b0 & 0x0F
    hs = 12 + 4 * cc
    seq = (x[:, 2] << 8) | x[:, 3]
    ts = ((x[:, 4] << 24) | (x[:, 5] << 16) | (x[:, 6] << 8) | x[:, 7]
          ).astype(jnp.uint32)
    ssrc = ((x[:, 8] << 24) | (x[:, 9] << 16) | (x[:, 10] << 8) | x[:, 11]
            ).astype(jnp.uint32)
    marker = (b1 & 0x80) != 0
    return b0, b1, cc, hs, seq, ts, ssrc, marker


@functools.partial(jax.jit, static_argnames=("is_video", "codec"))
def parse_packets(prefix: jnp.ndarray, length: jnp.ndarray,
                  is_video: bool = True, codec: str = "h264"
                  ) -> dict[str, jnp.ndarray]:
    """Parse a ``[P, W]`` uint8 prefix batch.

    Returns dict of ``[P]`` vectors: ``seq``, ``timestamp`` (uint32),
    ``ssrc`` (uint32), ``marker``, ``payload_start`` (12+4·CC, the
    reference's extension-blind header size), ``nal_type`` (effective, per
    the oracle's aggregation/FU resolution), ``keyframe_first``,
    ``frame_first``, ``frame_last`` (bool).

    ``codec`` selects the classifier (static — one compiled program per
    stream codec): "h264" walks NALU types; "mjpeg" (RFC 2435) marks
    fragment-offset-0 packets keyframe-first, mirroring
    ``protocol.mjpeg.is_frame_first_packet``.
    """
    if normalize_codec(codec) == "mjpeg":
        return _parse_packets_mjpeg(prefix, length, is_video)
    x = prefix.astype(jnp.int32)
    length = length.astype(jnp.int32)
    b0, b1, cc, hs, seq, ts, ssrc, marker = _fixed_header_fields(x)

    classifiable = (length >= _MIN_CLASSIFY_LEN) & (length > hs)
    nal0 = _byte_at(x, hs) & 0x1F

    eff = nal0
    for agg_type, off in _AGG_OFFSETS:
        inner = _byte_at(x, hs + off) & 0x1F
        eff = jnp.where((nal0 == agg_type) & (length > hs + off), inner, eff)
    fu_hdr = _byte_at(x, hs + 1)
    is_fu = (nal0 == 28) | (nal0 == 29)
    fu_ok = is_fu & (length > hs + 1)
    fu_start = fu_ok & ((fu_hdr & 0x80) != 0)
    eff = jnp.where(fu_start, fu_hdr & 0x1F, eff)
    eff = jnp.where(classifiable, eff, -1)

    kf = jnp.zeros_like(eff, dtype=bool)
    for t in _KEYFRAME_TYPES:
        kf |= eff == t
    if not is_video:
        kf = jnp.zeros_like(kf)

    frame_first = classifiable & (((nal0 >= 1) & (nal0 <= 27)) | fu_start)
    frame_last = (length >= _MIN_CLASSIFY_LEN) & marker

    return {
        "seq": seq, "timestamp": ts, "ssrc": ssrc, "marker": marker,
        "payload_start": hs, "nal_type": eff,
        "keyframe_first": kf & classifiable,
        "frame_first": frame_first, "frame_last": frame_last,
    }


def _parse_packets_mjpeg(prefix: jnp.ndarray, length: jnp.ndarray,
                         is_video: bool) -> dict[str, jnp.ndarray]:
    """RFC 2435 classification: frame start ⇔ 24-bit fragment offset 0.

    The offset lives at payload bytes 1-3 (after the 8-byte main JPEG
    header begins at ``hs``); every frame start is a keyframe because JPEG
    frames are independently decodable."""
    x = prefix.astype(jnp.int32)
    length = length.astype(jnp.int32)
    _b0, _b1, _cc, hs, seq, ts, ssrc, marker = _fixed_header_fields(x)
    classifiable = length >= hs + 8           # full RFC 2435 main header
    frag_off = ((_byte_at(x, hs + 1) << 16) | (_byte_at(x, hs + 2) << 8)
                | _byte_at(x, hs + 3))
    frame_first = classifiable & (frag_off == 0)
    kf = frame_first if is_video else jnp.zeros_like(frame_first)
    return {
        "seq": seq, "timestamp": ts, "ssrc": ssrc, "marker": marker,
        "payload_start": hs, "nal_type": jnp.full_like(seq, -1),
        "keyframe_first": kf,
        "frame_first": frame_first,
        "frame_last": classifiable & marker,
    }
