"""Batched RTP parse + H.264 classification on device.

Vectorized (fixed-shape, branch-free) equivalent of the host oracle in
``protocol.rtp`` / ``protocol.nalu`` — one fused XLA computation classifies a
whole packet window at once instead of the reference's per-packet calls
(``ReflectorSender::IsKeyFrameFirstPacket``, ``ReflectorStream.cpp:1403``).

Inputs are ``[P, W]`` uint8 byte *prefixes* plus ``[P]`` total lengths; W
must be ≥ ``PARSE_PREFIX`` (96): the deepest legal peek is CC=15 CSRCs +
the MTAP24 inner-NAL offset = byte 81, and ``_byte_at`` clamps
out-of-range column indices (a narrower buffer would silently classify
from the wrong byte rather than error).  All outputs are int32/bool
``[P]`` vectors.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

#: bytes of each packet staged to the device for parsing.  12 (fixed header)
#: + 60 (max CSRC) + 10 (deepest aggregation peek, MTAP24 offset 9) → 96
#: covers the worst legal case with headroom and keeps lanes aligned.
PARSE_PREFIX = 96

_KEYFRAME_TYPES = (5, 7, 8)
#: aggregation-type → inner-NAL peek offset (ReflectorStream.cpp:1465-1483)
_AGG_OFFSETS = ((24, 3), (25, 5), (26, 8), (27, 9))
_MIN_CLASSIFY_LEN = 20


def _byte_at(x: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """x: [P, W] int32, idx: [P] → x[p, idx[p]] with clamping."""
    idx = jnp.clip(idx, 0, x.shape[1] - 1)
    return jnp.take_along_axis(x, idx[:, None], axis=1)[:, 0]


@functools.partial(jax.jit, static_argnames=("is_video",))
def parse_packets(prefix: jnp.ndarray, length: jnp.ndarray,
                  is_video: bool = True) -> dict[str, jnp.ndarray]:
    """Parse a ``[P, W]`` uint8 prefix batch.

    Returns dict of ``[P]`` vectors: ``seq``, ``timestamp`` (uint32),
    ``ssrc`` (uint32), ``marker``, ``payload_start`` (12+4·CC, the
    reference's extension-blind header size), ``nal_type`` (effective, per
    the oracle's aggregation/FU resolution), ``keyframe_first``,
    ``frame_first``, ``frame_last`` (bool).
    """
    x = prefix.astype(jnp.int32)
    length = length.astype(jnp.int32)
    b0, b1 = x[:, 0], x[:, 1]
    cc = b0 & 0x0F
    hs = 12 + 4 * cc
    seq = (x[:, 2] << 8) | x[:, 3]
    ts = ((x[:, 4] << 24) | (x[:, 5] << 16) | (x[:, 6] << 8) | x[:, 7]
          ).astype(jnp.uint32)
    ssrc = ((x[:, 8] << 24) | (x[:, 9] << 16) | (x[:, 10] << 8) | x[:, 11]
            ).astype(jnp.uint32)
    marker = (b1 & 0x80) != 0

    classifiable = (length >= _MIN_CLASSIFY_LEN) & (length > hs)
    nal0 = _byte_at(x, hs) & 0x1F

    eff = nal0
    for agg_type, off in _AGG_OFFSETS:
        inner = _byte_at(x, hs + off) & 0x1F
        eff = jnp.where((nal0 == agg_type) & (length > hs + off), inner, eff)
    fu_hdr = _byte_at(x, hs + 1)
    is_fu = (nal0 == 28) | (nal0 == 29)
    fu_ok = is_fu & (length > hs + 1)
    fu_start = fu_ok & ((fu_hdr & 0x80) != 0)
    eff = jnp.where(fu_start, fu_hdr & 0x1F, eff)
    eff = jnp.where(classifiable, eff, -1)

    kf = jnp.zeros_like(eff, dtype=bool)
    for t in _KEYFRAME_TYPES:
        kf |= eff == t
    if not is_video:
        kf = jnp.zeros_like(kf)

    frame_first = classifiable & (((nal0 >= 1) & (nal0 <= 27)) | fu_start)
    frame_last = (length >= _MIN_CLASSIFY_LEN) & marker

    return {
        "seq": seq, "timestamp": ts, "ssrc": ssrc, "marker": marker,
        "payload_start": hs, "nal_type": eff,
        "keyframe_first": kf & classifiable,
        "frame_first": frame_first, "frame_last": frame_last,
    }
