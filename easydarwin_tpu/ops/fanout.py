"""Per-subscriber fan-out as one batched device computation.

Replaces the reference's hot double loop (``ReflectorSender::ReflectPackets``
→ ``SendPacketsToOutput`` → per-output ``WritePacket`` memcpy,
``ReflectorStream.cpp:1024-1185``) with a single ``[S, P]`` broadcast:

* seq rebase   ``(src_seq − base_src_seq + out_seq_start) mod 2¹⁶``
* ts rebase    ``(src_ts − base_src_ts + out_ts_start) mod 2³²``
* SSRC swap    per-output SSRC
* eligibility  ``arrival + bucket(s)·bucket_delay ≤ now`` — the reference's
  staggered-bucket send waves (cpp:1088-1119) as a mask instead of a loop.

The rendered result is ``[S, P, 12]`` big-endian header bytes; byte 0/1
(V/P/X/CC, M/PT) are taken verbatim from the source packet, so
``header ∥ packet[12:]`` is bit-identical to the CPU oracle's
``rtp.rewrite_header`` output.  vmap over the subscriber axis keeps the
kernel readable; XLA fuses the whole thing into one elementwise pass.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: columns of the per-output state matrix: ssrc, base_src_seq,
#: base_src_ts, out_seq_start, out_ts_start, chan (the RTSP-interleave
#: channel byte for TCP outputs; CHAN_NONE for datagram subscribers).
#: The channel rides the SAME device pass as the UDP rewrite params —
#: the 4-byte ``$``-framing header is affine in (len, channel), so one
#: stacked pass emits every subscriber's egress params, TCP included
#: (ISSUE 14).
STATE_COLS = 6
#: chan column sentinel for outputs with no interleave framing
CHAN_NONE = 0xFFFFFFFF


def pack_output_state(outputs) -> jnp.ndarray:
    """Host helper: RelayOutput list → [S, STATE_COLS] uint32 state."""
    import numpy as np
    st = np.zeros((len(outputs), STATE_COLS), dtype=np.uint32)
    for i, o in enumerate(outputs):
        rw = o.rewrite
        ch = getattr(o, "interleave_chan", None)
        st[i] = (rw.ssrc, max(rw.base_src_seq, 0), max(rw.base_src_ts, 0),
                 rw.out_seq_start, rw.out_ts_start,
                 CHAN_NONE if ch is None else (ch & 0xFF))
    return st


def _rewrite_one(state: jnp.ndarray, seq: jnp.ndarray, ts: jnp.ndarray):
    """One subscriber: state [STATE_COLS] uint32, seq/ts [P] →
    (seq', ts', ssrc) [P]."""
    ssrc, base_seq, base_ts, seq0, ts0 = (state[i] for i in range(5))
    new_seq = (seq - base_seq + seq0) & jnp.uint32(0xFFFF)
    new_ts = ts - base_ts + ts0          # uint32 wraps naturally
    return new_seq, new_ts, jnp.broadcast_to(ssrc, seq.shape)


@jax.jit
def fanout_headers(b01: jnp.ndarray, seq: jnp.ndarray, ts: jnp.ndarray,
                   out_state: jnp.ndarray) -> jnp.ndarray:
    """Render rewritten headers.

    b01: [P, 2] uint8 (source bytes 0-1) · seq: [P] uint32 · ts: [P] uint32 ·
    out_state: [S, 5] uint32 → [S, P, 12] uint8.
    """
    seq = seq.astype(jnp.uint32)
    ts = ts.astype(jnp.uint32)
    new_seq, new_ts, ssrc = jax.vmap(_rewrite_one, in_axes=(0, None, None))(
        out_state.astype(jnp.uint32), seq, ts)
    S, P = new_seq.shape

    def be_bytes(v: jnp.ndarray, n: int) -> list[jnp.ndarray]:
        return [((v >> (8 * (n - 1 - i))) & 0xFF).astype(jnp.uint8)
                for i in range(n)]

    cols = ([jnp.broadcast_to(b01[None, :, 0], (S, P)),
             jnp.broadcast_to(b01[None, :, 1], (S, P))]
            + be_bytes(new_seq, 2) + be_bytes(new_ts, 4) + be_bytes(ssrc, 4))
    return jnp.stack(cols, axis=-1)


@jax.jit
def eligibility(age_ms: jnp.ndarray, bucket_of_output: jnp.ndarray,
                bucket_delay_ms) -> jnp.ndarray:
    """[S, P] bool: packet p may be sent to output s this pass
    (per-bucket delay stagger, ``ReflectorStream.cpp:1088-1119``).

    ``age_ms`` is ``now − arrival`` per packet (int32 — relative times keep
    the device step free of int64)."""
    min_age = (bucket_of_output.astype(jnp.int32) *
               jnp.asarray(bucket_delay_ms, jnp.int32))
    return age_ms[None, :].astype(jnp.int32) >= min_age[:, None]


def affine_params(out_state: jnp.ndarray):
    """[S, STATE_COLS] state → per-output (seq_off, ts_off, ssrc, chan).

    The single definition of the affine rewrite in terms of the state
    layout; every consumer (device step, flagship pipeline) goes through
    here so the column meanings live in one place.  ``chan`` is the
    interleave-framing channel byte (CHAN_NONE for UDP outputs) — a
    pure passthrough on the device, but riding the pass means the host
    oracle check covers the byte that frames the TCP wire."""
    st = out_state.astype(jnp.uint32)
    return ((st[:, 3] - st[:, 1]) & jnp.uint32(0xFFFF),
            st[:, 4] - st[:, 2],
            st[:, 0],
            st[:, 5])


@jax.jit
def relay_affine_step(prefix: jnp.ndarray, length: jnp.ndarray,
                      out_state: jnp.ndarray) -> dict[str, jnp.ndarray]:
    """Bandwidth-lean device step: O(S+P) results instead of O(S·P).

    The per-subscriber rewrite is *affine*: ``seq' = seq + (out_seq_start −
    base_src_seq)``, ``ts' = ts + (out_ts_start − base_src_ts)``, SSRC
    constant per output.  So the device returns per-packet parsed fields and
    per-output offset triples; the egress path (native sender or the
    vectorized host renderer in ``relay.fanout``) applies the patch while
    scattering — at memory bandwidth, with no per-unit host *compute*.
    D2H shrinks from ``S·P·12`` bytes to ``4·(2P + 3S)``, which matters both
    on PCIe and (drastically) on tunneled devices.
    """
    from .gop import newest_keyframe
    from .parse import parse_packets

    fields = parse_packets(prefix, length)
    valid = length > 0
    kf = fields["keyframe_first"] & valid
    seq_off, ts_off, ssrc, chan = affine_params(out_state)
    return {
        "seq": fields["seq"].astype(jnp.uint32),
        "timestamp": fields["timestamp"],
        "keyframe_first": kf,
        "frame_first": fields["frame_first"],
        "frame_last": fields["frame_last"],
        "newest_keyframe": newest_keyframe(kf, valid),
        "seq_off": seq_off,
        "ts_off": ts_off,
        "ssrc": ssrc,
        "chan": chan,
    }


@jax.jit
def relay_affine_step_packed(prefix: jnp.ndarray, length: jnp.ndarray,
                             out_state: jnp.ndarray) -> jnp.ndarray:
    """``relay_affine_step`` over a leading source axis, with the egress
    params packed into ONE uint32 array ``[N_SRC, 4·S + 1]``:
    ``seq_off[S] ∥ ts_off[S] ∥ ssrc[S] ∥ chan[S] ∥ newest_keyframe``.

    One array means one D2H transfer.  On a tunneled device each fetch is a
    separate RPC with fixed ~latency, so 5 fetches → 1 fetch is a direct
    5× cut in per-window latency; combined with ``copy_to_host_async`` the
    whole fetch hides behind the previous window's egress."""
    out = jax.vmap(relay_affine_step)(prefix, length, out_state)
    kf = out["newest_keyframe"].astype(jnp.uint32)[:, None]
    return jnp.concatenate(
        [out["seq_off"], out["ts_off"], out["ssrc"], out["chan"], kf],
        axis=-1)


#: bytes appended to each packet prefix to carry its length (le32)
WINDOW_EXTRA = 4


def pack_window(prefix, length):
    """Host helper: [..., P, 96] prefixes + [..., P] lengths → ONE uint8
    array [..., P, 100] (length rides as 4 trailing le bytes).

    A tunneled device pays a fixed RPC cost per transfer; fusing the two
    H2D arrays halves the upload round-trips per window."""
    import numpy as np
    prefix = np.asarray(prefix, np.uint8)
    length = np.ascontiguousarray(length, "<u4")  # le bytes match the decode
    lb = length[..., None].view(np.uint8)
    return np.concatenate([prefix, lb], axis=-1)


@jax.jit
def relay_affine_step_window(window: jnp.ndarray,
                             out_state: jnp.ndarray) -> jnp.ndarray:
    """``relay_affine_step_packed`` taking the fused ``pack_window`` layout.

    ``window``: [N_SRC, P, 96+4] uint8 — the only per-pass H2D transfer;
    ``out_state``: [N_SRC, S, STATE_COLS] uint32 — subscriber state, kept
    device-resident by the caller (it changes on subscribe/unsubscribe, not
    per window, so it should never ride the per-window upload)."""
    prefix = window[:, :, :96]
    lb = window[:, :, 96:].astype(jnp.uint32)
    length = (lb[..., 0] | (lb[..., 1] << 8) | (lb[..., 2] << 16)
              | (lb[..., 3] << 24)).astype(jnp.int32)
    return relay_affine_step_packed(prefix, length, out_state)


def unpack_affine(packed, n_sub: int):
    """Host-side views into the packed egress params:
    ``(seq_off, ts_off, ssrc, chan, newest_keyframe)``.

    The newest-keyframe column is re-cast to int32 so the -1 "no keyframe
    in window" sentinel survives the uint32 wire format (it rides as
    0xFFFFFFFF and wraps back here)."""
    return (packed[:, :n_sub], packed[:, n_sub:2 * n_sub],
            packed[:, 2 * n_sub:3 * n_sub],
            packed[:, 3 * n_sub:4 * n_sub],
            packed[:, 4 * n_sub].astype("int32"))


@jax.jit
def relay_batch_step(prefix: jnp.ndarray, length: jnp.ndarray,
                     age_ms: jnp.ndarray, out_state: jnp.ndarray,
                     bucket_of_output: jnp.ndarray,
                     bucket_delay_ms) -> dict[str, jnp.ndarray]:
    """The full device step for one source: parse → keyframe scan → fan-out.

    This is the unit the driver compile-checks (``__graft_entry__.entry``) and
    that ``parallel.mesh`` shards over (sources × subscriber-shards).
    """
    from .gop import newest_keyframe
    from .parse import parse_packets

    fields = parse_packets(prefix, length)
    headers = fanout_headers(prefix[:, :2], fields["seq"], fields["timestamp"],
                             out_state)
    mask = eligibility(age_ms, bucket_of_output, bucket_delay_ms)
    valid = (length > 0)
    sendable = (length >= 12)      # runts are never relayed (skipped host-side)
    return {
        "headers": headers,
        "mask": mask & sendable[None, :],
        "keyframe_first": fields["keyframe_first"],
        "newest_keyframe": newest_keyframe(fields["keyframe_first"], valid),
        "frame_last": fields["frame_last"],
    }
