"""Batched window extraction for the megabatch scheduler's H2D staging.

One stream's contribution to a stacked device pass is a run of ring
packets packed into the fused ``pack_window`` layout (``ops.fanout``):
``[prefix_width bytes | le32 length]`` per row, pow2-padded.  The gather
runs through ``csrc ed_stage_gather`` when the native core is loaded
(one memcpy walk, counted into ``stage_gather_busy_seconds_total``) and
falls back to the numpy fancy-index copy otherwise — same bytes either
way, so the device step never sees which host packed its input.

The staging buffers themselves are owned by the scheduler
(``relay.megabatch``), double-buffered per shape bucket: while the
device/DMA reads the buffer dispatched at wake N, the host gathers wake
N+1 into the alternate.  Under a serving mesh (ISSUE 7) the bucket's
rows are split into PER-DEVICE buffers — one independent C-contiguous
array per mesh shard, sized by ``rows_per_shard`` — so each device's
H2D transfer is a single contiguous copy from host memory that only
that device reads (a global buffer sliced per shard would couple every
device's upload to one allocation's lifetime and defeat the per-shard
double buffer).
"""

from __future__ import annotations

import numpy as np

from .fanout import WINDOW_EXTRA
from .parse import PARSE_PREFIX

#: bytes per fused staging row (prefix + trailing le32 length)
ROW_STRIDE = PARSE_PREFIX + WINDOW_EXTRA


def pow2(n: int, lo: int) -> int:
    """Smallest power-of-two multiple of ``lo`` that is >= ``n`` (with
    ``lo`` itself a power of two) — THE bucket-shape rounding rule every
    staging path shares (per-stream pads, megabatch buckets, per-shard
    blocks), so jit specializations latch on one shape family."""
    p = lo
    while p < n:
        p <<= 1
    return p


def rows_per_shard(n_rows: int, n_shards: int) -> int:
    """Stream rows each mesh shard stages for a bucket of ``n_rows``
    real streams over ``n_shards`` devices: the pow2-padded per-shard
    block (min 1), so the GLOBAL leading axis is ``n_shards * rows_per``
    — divisible by the mesh's ``src`` axis for any device count, while
    jit specializations stay latched per pow2 bucket shape exactly as
    on the single-device path.  Uneven stream counts leave the tail
    shard(s) with zero-filled pad rows (the dryrun's pad-mask rule:
    zero windows + zero state stage nothing and install nothing)."""
    return pow2((max(n_rows, 1) + n_shards - 1) // n_shards, 1)


def pack_rows(data: np.ndarray, length: np.ndarray,
              out_rows: np.ndarray | None = None,
              prefix_width: int = PARSE_PREFIX) -> np.ndarray:
    """Vectorized pack of ``[N, slot]`` packet bytes + lengths into fused
    staging rows (``[N(+pad), ROW_STRIDE]``: prefix ∥ le32 length).

    The VOD segment cache (``vod/cache.py``) pre-packs every window's
    rows ONCE at fill time with this, so a megabatch gather over a
    cache-fed ring is a plain row memcpy — the per-row length packing
    is paid per asset window, not per (subscriber, wake)."""
    n = len(length)
    if out_rows is None:
        out_rows = np.zeros((n, prefix_width + WINDOW_EXTRA), np.uint8)
    w = min(prefix_width, data.shape[1])
    out_rows[:n, :w] = data[:, :w]
    lens = np.ascontiguousarray(length, "<u4")
    out_rows[:n, prefix_width:prefix_width + 4] = \
        lens[:, None].view(np.uint8)
    out_rows[:n, prefix_width + 4:] = 0
    out_rows[n:] = 0
    return out_rows


def gather_window(ring, start: int, count: int, out_rows: np.ndarray,
                  prefix_width: int = PARSE_PREFIX) -> int:
    """Pack ``count`` packets from absolute id ``start`` of ``ring`` (a
    ``relay.ring.PacketRing``) into ``out_rows`` ([rows, stride] uint8,
    C-contiguous, rows >= count) in the fused window layout; zero-fills
    the padding rows.  Returns the number of live rows staged (clamped to
    the ring's live window)."""
    start = max(start, ring.tail)
    stop = min(start + count, ring.head)
    n = max(stop - start, 0)
    if n > out_rows.shape[0]:
        raise ValueError(f"staging buffer too small: {n} > "
                         f"{out_rows.shape[0]} rows")
    if n == 0:
        out_rows[:] = 0
        return 0
    slots = (np.arange(start, stop) % ring.capacity).astype(np.int32)
    staged = getattr(ring, "staged", None)
    if staged is not None and prefix_width == PARSE_PREFIX:
        # pre-staged ring (VOD cache fill keeps a parallel fused-row
        # array current): one fancy-index row copy, no length packing
        out_rows[:n] = staged[slots]
        out_rows[n:] = 0
        return n
    from .. import native
    if native.loaded():
        r = native.stage_gather(ring.data, ring.length, slots,
                                prefix_width, out_rows)
        if r == n:
            return n
        # bad-argument fall-through: the numpy path below is always safe
    out_rows[:n, :prefix_width] = ring.data[slots, :prefix_width]
    lens = np.ascontiguousarray(ring.length[slots], "<u4")
    out_rows[:n, prefix_width:prefix_width + 4] = \
        lens[:, None].view(np.uint8)
    out_rows[:n, prefix_width + 4:] = 0
    out_rows[n:] = 0
    return n
