"""Batched window extraction for the megabatch scheduler's H2D staging.

One stream's contribution to a stacked device pass is a run of ring
packets packed into the fused ``pack_window`` layout (``ops.fanout``):
``[prefix_width bytes | le32 length]`` per row, pow2-padded.  The gather
runs through ``csrc ed_stage_gather`` when the native core is loaded
(one memcpy walk, counted into ``stage_gather_busy_seconds_total``) and
falls back to the numpy fancy-index copy otherwise — same bytes either
way, so the device step never sees which host packed its input.

The staging buffers themselves are owned by the scheduler
(``relay.megabatch``), double-buffered per shape bucket: while the
device/DMA reads the buffer dispatched at wake N, the host gathers wake
N+1 into the alternate.
"""

from __future__ import annotations

import numpy as np

from .fanout import WINDOW_EXTRA
from .parse import PARSE_PREFIX

#: bytes per fused staging row (prefix + trailing le32 length)
ROW_STRIDE = PARSE_PREFIX + WINDOW_EXTRA


def gather_window(ring, start: int, count: int, out_rows: np.ndarray,
                  prefix_width: int = PARSE_PREFIX) -> int:
    """Pack ``count`` packets from absolute id ``start`` of ``ring`` (a
    ``relay.ring.PacketRing``) into ``out_rows`` ([rows, stride] uint8,
    C-contiguous, rows >= count) in the fused window layout; zero-fills
    the padding rows.  Returns the number of live rows staged (clamped to
    the ring's live window)."""
    start = max(start, ring.tail)
    stop = min(start + count, ring.head)
    n = max(stop - start, 0)
    if n > out_rows.shape[0]:
        raise ValueError(f"staging buffer too small: {n} > "
                         f"{out_rows.shape[0]} rows")
    if n == 0:
        out_rows[:] = 0
        return 0
    slots = (np.arange(start, stop) % ring.capacity).astype(np.int32)
    from .. import native
    if native.loaded():
        r = native.stage_gather(ring.data, ring.length, slots,
                                prefix_width, out_rows)
        if r == n:
            return n
        # bad-argument fall-through: the numpy path below is always safe
    out_rows[:n, :prefix_width] = ring.data[slots, :prefix_width]
    lens = np.ascontiguousarray(ring.length[slots], "<u4")
    out_rows[:n, prefix_width:prefix_width + 4] = \
        lens[:, None].view(np.uint8)
    out_rows[:n, prefix_width + 4:] = 0
    out_rows[n:] = 0
    return n
