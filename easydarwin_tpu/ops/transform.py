"""Transform-domain ops for the transcode ladder (BASELINE config 5).

The reference has no transcoder (EasyHLS was closed-source — SURVEY §2.3);
this is new, TPU-first machinery: 8×8 DCT/IDCT expressed as ONE batched
``[N, 64] @ [64, 64]`` matmul via the Kronecker identity
``vec(Cᵀ·X·C) = (Cᵀ ⊗ Cᵀ)·vec(X)`` — MXU-shaped (the per-block 8×8 matmul
form would waste the 128×128 systolic array), arbitrary batch, bf16-friendly.
Quantization follows the JPEG/H.263 convention (base table × quality scale).

Scope note: bitstream entropy (CAVLC/CABAC) decode/encode stays on the host
(native tier); the device owns the dense transform/quant math, which is
where the FLOPs are.  ``decode_blocks_pallas`` fuses dequant → IDCT →
+128 level shift → clip in one VMEM pass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# ----------------------------------------------------------------- DCT bases

def dct_matrix() -> np.ndarray:
    """Orthonormal 8-point DCT-II matrix C: y = C @ x."""
    C = np.zeros((8, 8), dtype=np.float64)
    for k in range(8):
        a = np.sqrt(1 / 8) if k == 0 else np.sqrt(2 / 8)
        for n in range(8):
            C[k, n] = a * np.cos(np.pi * (2 * n + 1) * k / 16)
    return C


@functools.lru_cache(maxsize=None)
def _kron_mats() -> tuple[np.ndarray, np.ndarray]:
    """(forward, inverse) 64×64 operators on row-major vec'd blocks.

    forward: vec(C X Cᵀ) = (C ⊗ C) vec(X)   (2-D DCT of spatial block X)
    inverse: vec(Cᵀ Y C) = (Cᵀ ⊗ Cᵀ) vec(Y)
    """
    C = dct_matrix()
    fwd = np.kron(C, C)
    inv = np.kron(C.T, C.T)
    return (fwd.astype(np.float32), inv.astype(np.float32))


def dct_blocks(x: jnp.ndarray) -> jnp.ndarray:
    """[N, 64] spatial → [N, 64] coefficients (row-major 8×8 blocks)."""
    fwd, _ = _kron_mats()
    return x @ jnp.asarray(fwd).T


def idct_blocks(y: jnp.ndarray) -> jnp.ndarray:
    """[N, 64] coefficients → [N, 64] spatial."""
    _, inv = _kron_mats()
    return y @ jnp.asarray(inv).T


# -------------------------------------------------------------- quantization

#: JPEG Annex K luminance base table, row-major (the de-facto baseline the
#: reference-era tooling used for intra quant).
JPEG_LUMA_QT = np.array([
    16, 11, 10, 16, 24, 40, 51, 61,
    12, 12, 14, 19, 26, 58, 60, 55,
    14, 13, 16, 24, 40, 57, 69, 56,
    14, 17, 22, 29, 51, 87, 80, 62,
    18, 22, 37, 56, 68, 109, 103, 77,
    24, 35, 55, 64, 81, 104, 113, 92,
    49, 64, 78, 87, 103, 121, 120, 101,
    72, 92, 95, 98, 112, 100, 103, 99], dtype=np.float32)


def quality_table(quality: int) -> np.ndarray:
    """JPEG quality (1-100) → effective quant table [64]."""
    quality = int(np.clip(quality, 1, 100))
    scale = 5000 / quality if quality < 50 else 200 - 2 * quality
    qt = np.floor((JPEG_LUMA_QT * scale + 50) / 100)
    return np.clip(qt, 1, 255).astype(np.float32)


@jax.jit
def quantize(coef: jnp.ndarray, qtable: jnp.ndarray) -> jnp.ndarray:
    """[N,64] float coefficients → int32 levels (round-half-away)."""
    return jnp.round(coef / qtable[None, :]).astype(jnp.int32)


@jax.jit
def dequantize(levels: jnp.ndarray, qtable: jnp.ndarray) -> jnp.ndarray:
    return levels.astype(jnp.float32) * qtable[None, :]


# ------------------------------------------------------------------- zigzag

@functools.lru_cache(maxsize=None)
def zigzag_order() -> np.ndarray:
    """[64] indices mapping raster order → zigzag scan order."""
    # odd diagonals run down-left (i ascending), even ones up-right
    order = sorted(((i + j, i if (i + j) % 2 else j, i, j)
                    for i in range(8) for j in range(8)))
    return np.array([i * 8 + j for (_, _, i, j) in order], dtype=np.int32)


def to_zigzag(levels: jnp.ndarray) -> jnp.ndarray:
    return levels[:, jnp.asarray(zigzag_order())]


def from_zigzag(z: jnp.ndarray) -> jnp.ndarray:
    inv = np.argsort(zigzag_order())
    return z[:, jnp.asarray(inv)]


def to_zigzag_np(natural: np.ndarray) -> np.ndarray:
    """Host-side ``to_zigzag`` ([..., 64] natural → zigzag) — the entropy
    codec and ladder reorder on the host, off the device round-trip."""
    return natural[..., zigzag_order()]


def from_zigzag_np(z: np.ndarray) -> np.ndarray:
    out = np.empty_like(z)
    out[..., zigzag_order()] = z
    return out


# ----------------------------------------------------- encode / decode paths

@jax.jit
def encode_blocks(pixels: jnp.ndarray, qtable: jnp.ndarray) -> jnp.ndarray:
    """uint8 [N,64] spatial blocks → int32 quantized coefficient levels."""
    x = pixels.astype(jnp.float32) - 128.0
    return quantize(dct_blocks(x), qtable)


@jax.jit
def decode_blocks(levels: jnp.ndarray, qtable: jnp.ndarray) -> jnp.ndarray:
    """int32 levels → uint8 [N,64] spatial blocks (dequant+IDCT+shift+clip)."""
    x = idct_blocks(dequantize(levels, qtable)) + 128.0
    return jnp.clip(jnp.round(x), 0, 255).astype(jnp.uint8)


@jax.jit
def requantize(levels: jnp.ndarray, qtable_in: jnp.ndarray,
               qtable_out: jnp.ndarray) -> jnp.ndarray:
    """Transform-domain bitrate step-down: dequant with the source table,
    requant with a coarser one — the inner op of the transcode ladder
    (no IDCT round-trip needed for same-resolution rungs)."""
    return quantize(dequantize(levels, qtable_in), qtable_out)


def transcode_ladder(levels: jnp.ndarray, qtable_in: jnp.ndarray,
                     qualities: tuple[int, ...]) -> list[jnp.ndarray]:
    """One decode-side coefficient block set → N ladder rungs."""
    return [requantize(levels, qtable_in, jnp.asarray(quality_table(q)))
            for q in qualities]


# ------------------------------------------------------------ pallas kernel

TILE = 256     # blocks per grid step ([256, 64] f32 tiles in VMEM)


def _decode_kernel(levels_ref, qt_ref, inv_ref, out_ref):
    x = levels_ref[:].astype(jnp.float32) * qt_ref[:]      # dequant (bcast)
    y = jax.lax.dot_general(x, inv_ref[:],
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    out_ref[:] = jnp.clip(jnp.round(y + 128.0), 0, 255).astype(jnp.uint8)


def decode_blocks_pallas(levels: jnp.ndarray, qtable: jnp.ndarray,
                         *, interpret: bool = False) -> jnp.ndarray:
    """Fused dequant→IDCT→shift→clip as one Pallas kernel.

    levels [N,64] int32 (N a multiple of TILE — pad with zero blocks),
    qtable [1,64] f32.  The 64×64 inverse operator rides along in VMEM and
    hits the MXU via dot_general.  ``interpret=True`` runs on CPU for tests.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = levels.shape[0]
    pad = (-n) % TILE
    if pad:
        levels = jnp.concatenate(
            [levels, jnp.zeros((pad, 64), levels.dtype)], axis=0)
    _, inv = _kron_mats()
    grid = levels.shape[0] // TILE
    out = pl.pallas_call(
        _decode_kernel,
        out_shape=jax.ShapeDtypeStruct((levels.shape[0], 64), jnp.uint8),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((TILE, 64), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 64), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((64, 64), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((TILE, 64), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(levels, qtable.reshape(1, 64).astype(jnp.float32),
      jnp.asarray(inv))          # contraction ((1,),(1,)) ≡ x @ inv.T
    return out[:n]


# ------------------------------------------------- DCT-domain 2x downscale

@functools.lru_cache(maxsize=None)
def downscale2x_operator() -> np.ndarray:
    """[256, 64] linear map: a 2×2 quad of dequantized 8×8 DCT blocks →
    the 8×8 DCT block of the half-resolution tile.

    Built numerically as DCT ∘ avgpool2 ∘ IDCT over the 16×16 tile the
    quad reconstructs; being a fixed linear operator it turns resolution
    downscaling into ONE ``[N, 256] @ [256, 64]`` matmul on the MXU — no
    pixel round-trip ever materializes.  Quad layout is row-major:
    [top-left, top-right, bottom-left, bottom-right], each block vec'd
    row-major (natural order, not zigzag)."""
    _, inv = _kron_mats()                      # [64, 64] coeff → spatial
    eye = np.eye(256, dtype=np.float64)
    quads = eye.reshape(256, 2, 2, 8, 8)       # [in, qy, qx, 8, 8]
    # IDCT each 8×8 block of each basis vector
    blocks = quads.reshape(256, 4, 64) @ inv.astype(np.float64).T
    blocks = blocks.reshape(256, 2, 2, 8, 8)
    # assemble 16×16 tiles
    tile = np.zeros((256, 16, 16))
    for qy in range(2):
        for qx in range(2):
            tile[:, qy * 8:qy * 8 + 8, qx * 8:qx * 8 + 8] = \
                blocks[:, qy, qx]
    # 2×2 average pool → 8×8
    pooled = tile.reshape(256, 8, 2, 8, 2).mean(axis=(2, 4))
    # forward DCT of the pooled tile
    fwd, _ = _kron_mats()
    out = pooled.reshape(256, 64) @ fwd.astype(np.float64).T
    return out.astype(np.float32)              # [256, 64]


@jax.jit
def downscale2x_blocks(quads: jnp.ndarray) -> jnp.ndarray:
    """[N, 256] dequantized coefficient quads → [N, 64] half-res
    coefficients (natural order)."""
    M = jnp.asarray(downscale2x_operator())
    return jnp.matmul(quads, M, precision="highest")


@jax.jit
def requantize_downscale2x(quads: jnp.ndarray, qtable_in: jnp.ndarray,
                           qtable_out: jnp.ndarray) -> jnp.ndarray:
    """Quantized quad levels → quantized half-res levels: dequant (input
    table broadcast over the 4 blocks), one MXU matmul, requant."""
    deq = quads.reshape(-1, 4, 64) * qtable_in[None, None, :]
    out = jnp.matmul(deq.reshape(-1, 256),
                     jnp.asarray(downscale2x_operator()),
                     precision="highest")
    return jnp.round(out / qtable_out[None, :]).astype(jnp.int32)


# ------------------------------------------------- H.264 4x4 requant (int32)

@jax.jit
def h264_requant(levels: jnp.ndarray, qp_in: jnp.ndarray,
                 qp_out: jnp.ndarray) -> jnp.ndarray:
    """H.264 4×4 transform-domain requant, BIT-EXACT against
    ``codecs.h264_transform.requant_levels_scalar``: a +6k QP step is
    exactly a rounded k-bit right shift of each level (Qstep doubles
    every 6 QP with identical qp%6 multiplier rows):

      l' = sign(l)·((|l| + 2^k/3) >> k),  k = (qp_out − qp_in) // 6.

    levels: int32 [N, 16] block levels (any scan order — the op is
    elementwise).  qp_in: [N] per-block source QP (per-MB qp_delta
    support); qp_out: [N] or scalar target QP, qp_out ≡ qp_in (mod 6).
    The entropy recode around this stays on the host
    (``codecs.h264_requant``) — the same host⇄device split as the MJPEG
    ladder.  The clip bound is the shared overflow contract
    (``codecs.h264_transform.LEVEL_CLIP``)."""
    from ..codecs.h264_transform import LEVEL_CLIP
    lev = jnp.clip(levels.astype(jnp.int32), -LEVEL_CLIP, LEVEL_CLIP)
    k = ((qp_out - qp_in.astype(jnp.int32)) // 6)[:, None]
    f = (jnp.int32(1) << k) // 3
    out = jnp.sign(lev) * ((jnp.abs(lev) + f) >> k)
    return out.astype(jnp.int32)


# --------------------------------------------- H.264 chroma requant (int32)

def _h2x2(v: jnp.ndarray) -> jnp.ndarray:
    """Elementwise 2×2 Hadamard (H2·c·H2) of [..., 4] raster quads."""
    a, b, c, d = v[..., 0], v[..., 1], v[..., 2], v[..., 3]
    return jnp.stack([a + b + c + d, a - b + c - d,
                      a + b - c - d, a - b - c + d], axis=-1)


def _inv_core_1d(a, b, c, d):
    e0, e1 = a + c, a - c
    e2, e3 = (b >> 1) - d, b + (d >> 1)
    return e0 + e3, e1 + e2, e1 - e2, e0 - e3


def _fwd_core_1d(x0, x1, x2, x3):
    t0, t1, t2, t3 = x0 + x3, x1 + x2, x1 - x2, x0 - x3
    return t0 + t1, 2 * t3 + t2, t0 - t1, t3 - 2 * t2


def _rows_cols(w: jnp.ndarray, fn) -> jnp.ndarray:
    """Apply a 4-point butterfly over rows then columns of [..., 4, 4]."""
    r = jnp.stack(fn(*(w[..., i] for i in range(4))), axis=-1)
    return jnp.stack(fn(*(r[..., i, :] for i in range(4))), axis=-2)


@jax.jit
def h264_requant_chroma(dc: jnp.ndarray, ac: jnp.ndarray,
                        qpc_in: jnp.ndarray, qpc_out: jnp.ndarray
                        ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Batched chroma requant, BIT-EXACT against
    ``codecs.h264_transform.requant_chroma_scalar`` (same clips, same
    integer ops, int32 throughout — the scalar module documents why the
    clips make int32 sufficient).

    dc: int32 [N, 4] chroma DC levels (2×2 raster) per MB component;
    ac: int32 [N, 4, 15] per-block zigzag AC tails; qpc_in/qpc_out: [N].
    Per-row three-way dispatch (identity / exact +6k shift / open-loop
    integer round trip) computed dense and selected — branchless, so one
    trace serves every mix of Table 8-15 deltas."""
    from ..codecs.h264_transform import (LEVEL_CLIP, MF, RES_CLIP, V,
                                         W_CLIP, ZIGZAG4, _CLS)
    n = dc.shape[0]
    dc = jnp.clip(dc.astype(jnp.int32), -LEVEL_CLIP, LEVEL_CLIP)
    ac = jnp.clip(ac.astype(jnp.int32), -LEVEL_CLIP, LEVEL_CLIP)
    qi = qpc_in.astype(jnp.int32)
    qo = qpc_out.astype(jnp.int32)
    delta = qo - qi

    # --- exact-shift arm (delta ≡ 0 mod 6; k=0 degenerates to identity)
    k = jnp.maximum(delta // 6, 0)
    f6 = (jnp.int32(1) << k) // 3

    def shift(x, kk, ff):
        return jnp.sign(x) * ((jnp.abs(x) + ff) >> kk)

    dc_shift = shift(dc, k[:, None], f6[:, None])
    ac_shift = shift(ac, k[:, None, None], f6[:, None, None])

    # --- general arm: dequant (8.5.11 DC + 8.5.12 AC) → inverse core →
    #     forward core → requant at qpc_out
    vpos = jnp.asarray(np.stack([V[m][_CLS] for m in range(6)]),
                       dtype=jnp.int32)                       # [6, 16]
    mfpos = jnp.asarray(np.stack([MF[m][_CLS] for m in range(6)]),
                        dtype=jnp.int32)
    v0 = jnp.asarray(V[:, 0], dtype=jnp.int32)
    mf0 = jnp.asarray(MF[:, 0], dtype=jnp.int32)
    si, so = qi // 6, qo // 6
    mi, mo = qi % 6, qo % 6

    dcc = ((_h2x2(dc) * v0[mi][:, None]) << si[:, None]) >> 1
    lev = jnp.zeros((n, 4, 16), jnp.int32)
    lev = lev.at[:, :, jnp.asarray(ZIGZAG4[1:])].set(ac)
    w = (lev * vpos[mi][:, None, :]) << si[:, None, None]
    w = w.at[:, :, 0].set(dcc)
    x = _rows_cols(w.reshape(n, 4, 4, 4), _inv_core_1d)
    x = jnp.clip((x + 32) >> 6, -RES_CLIP, RES_CLIP)
    big = jnp.clip(_rows_cols(x, _fwd_core_1d),
                   -W_CLIP, W_CLIP).reshape(n, 4, 16)
    qbits = 15 + so
    off = (jnp.int32(1) << qbits) // 3
    q = jnp.sign(big) * ((jnp.abs(big) * mfpos[mo][:, None, :]
                          + off[:, None, None]) >> qbits[:, None, None])
    q = jnp.clip(q, -LEVEL_CLIP, LEVEL_CLIP)
    ac_gen = q[:, :, jnp.asarray(ZIGZAG4[1:])]
    f2 = jnp.clip(_h2x2(jnp.clip(big[:, :, 0], -W_CLIP, W_CLIP)),
                  -W_CLIP, W_CLIP)
    dc_gen = jnp.sign(f2) * ((jnp.abs(f2) * mf0[mo][:, None]
                              + 2 * off[:, None]) >> (qbits + 1)[:, None])
    dc_gen = jnp.clip(dc_gen, -LEVEL_CLIP, LEVEL_CLIP)

    use_shift = (delta % 6 == 0)
    dc_out = jnp.where(use_shift[:, None], dc_shift, dc_gen)
    ac_out = jnp.where(use_shift[:, None, None], ac_shift, ac_gen)
    return dc_out.astype(jnp.int32), ac_out.astype(jnp.int32)
