"""Device-resident GOP ring — HBM-resident packet window state.

The reference's GOP retention is host-side: the reflector queue +
``CKeyFrameCache`` byte cache (2 MB cap, ``keyframecache.h:45-72``; SURVEY
§5 maps it to "a fixed-shape device-resident GOP ring buffer").  Here the
classification window lives in HBM: ingest appends only the *new* packets'
prefixes each pass (``jax.lax.dynamic_update_slice`` under donation, so XLA
updates in place), and the query step runs over the resident window without
re-staging it.  H2D per pass is O(new packets), not O(window).

State arrays (all device-resident):
  prefix  [C, W] uint8 · length [C] int32 · age base [C] int32 · head scalar
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .parse import PARSE_PREFIX


class RingState(NamedTuple):
    prefix: jnp.ndarray        # [C, W] uint8
    length: jnp.ndarray        # [C] int32
    arrival: jnp.ndarray       # [C] int32 (ms, relative epoch)
    head: jnp.ndarray          # scalar int32: total packets ever appended


def init_ring(capacity: int, width: int = PARSE_PREFIX) -> RingState:
    return RingState(
        prefix=jnp.zeros((capacity, width), dtype=jnp.uint8),
        length=jnp.zeros(capacity, dtype=jnp.int32),
        arrival=jnp.zeros(capacity, dtype=jnp.int32),
        head=jnp.zeros((), dtype=jnp.int32))


@functools.partial(jax.jit, donate_argnums=(0,))
def append(state: RingState, new_prefix: jnp.ndarray,
           new_length: jnp.ndarray, new_arrival: jnp.ndarray,
           n_new: jnp.ndarray) -> RingState:
    """Append up to ``new_prefix.shape[0]`` packets (first ``n_new`` valid).

    The batch is written at ``head % C`` with wraparound handled by a double
    dynamic_update_slice (split at the seam).  Donated: XLA reuses the HBM
    buffers in place.
    """
    C = state.prefix.shape[0]
    B = new_prefix.shape[0]
    pos = state.head % C
    idx = (pos + jnp.arange(B, dtype=jnp.int32)) % C
    keep = jnp.arange(B, dtype=jnp.int32) < n_new
    # scatter rows (B is small; scatter handles the seam uniformly)
    prefix = state.prefix.at[idx].set(
        jnp.where(keep[:, None], new_prefix, state.prefix[idx]))
    length = state.length.at[idx].set(
        jnp.where(keep, new_length, state.length[idx]))
    arrival = state.arrival.at[idx].set(
        jnp.where(keep, new_arrival, state.arrival[idx]))
    return RingState(prefix, length, arrival, state.head + n_new)


@jax.jit
def query(state: RingState, out_state: jnp.ndarray,
          now_ms: jnp.ndarray) -> dict:
    """Run the affine relay step over the resident window.

    Returns the ``relay_affine_step`` outputs plus the newest keyframe as an
    *absolute* packet id (-1 if none in window) — device-side equivalent of
    the host ring's keyframe bookmark.
    """
    from .fanout import relay_affine_step

    C = state.prefix.shape[0]
    res = relay_affine_step(state.prefix, state.length, out_state)
    # slot index → absolute id: ids in [head-C, head); slot s holds id
    # head - ((head - s - 1) % C) - 1
    slots = jnp.arange(C, dtype=jnp.int32)
    abs_id = state.head - ((state.head - slots - 1) % C) - 1
    valid = (abs_id >= 0) & (abs_id < state.head) & (state.length > 0)
    kf = res["keyframe_first"] & valid
    newest_kf_abs = jnp.max(jnp.where(kf, abs_id, -1))
    age = jnp.asarray(now_ms, jnp.int32) - state.arrival
    return {**res, "abs_id": abs_id, "valid": valid,
            "newest_keyframe_abs": newest_kf_abs, "age_ms": age}
