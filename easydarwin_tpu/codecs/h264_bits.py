"""H.264 bitstream primitives: MSB-first bit I/O, Exp-Golomb codes, and
RBSP ⇄ NAL emulation-prevention (03) handling.

Reference context: the reference server treats H.264 as opaque payload
(`ReflectorStream.cpp:1403` only peeks NAL types); this module exists for
the transcode tier, which the reference never had (EasyHLS was
closed-source, SURVEY §2.3)."""

from __future__ import annotations


class BitReader:
    """MSB-first reader over bytes (RBSP payload, no emulation bytes)."""

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0                    # bit position
        self._stop_bit: int | None = None   # cached rbsp_stop_one_bit pos

    @property
    def bits_left(self) -> int:
        return len(self.data) * 8 - self.pos

    def read_bit(self) -> int:
        if self.pos >= len(self.data) * 8:
            raise EOFError("past end of RBSP")
        byte = self.data[self.pos >> 3]
        bit = (byte >> (7 - (self.pos & 7))) & 1
        self.pos += 1
        return bit

    def read_bits(self, n: int) -> int:
        v = 0
        for _ in range(n):
            v = (v << 1) | self.read_bit()
        return v

    def peek_bits(self, n: int) -> int:
        """Up to ``n`` bits without consuming; short reads near the end
        are zero-padded (VLC peek convenience)."""
        save = self.pos
        v = 0
        got = 0
        try:
            for _ in range(n):
                v = (v << 1) | self.read_bit()
                got += 1
        except EOFError:
            v <<= (n - got)
        self.pos = save
        return v

    def skip(self, n: int) -> None:
        self.pos += n

    def ue(self) -> int:
        """Unsigned Exp-Golomb."""
        zeros = 0
        while self.read_bit() == 0:
            zeros += 1
            if zeros > 31:
                raise ValueError("bad ue(v)")
        return (1 << zeros) - 1 + (self.read_bits(zeros) if zeros else 0)

    def se(self) -> int:
        """Signed Exp-Golomb."""
        k = self.ue()
        return (k + 1) // 2 if k % 2 else -(k // 2)

    def byte_aligned(self) -> bool:
        return self.pos % 8 == 0

    def more_rbsp_data(self) -> bool:
        """True while data before the rbsp_stop_one_bit remains (the stop
        bit is the LAST set bit of the RBSP; its position is found once
        and cached — the multi-slice MB walk queries this per MB)."""
        if self._stop_bit is None:
            self._stop_bit = -1
            for i in range(len(self.data) - 1, -1, -1):
                b = self.data[i]
                if b:
                    low = b & -b                     # lowest set bit
                    self._stop_bit = i * 8 + 7 - low.bit_length() + 1
                    break
        return self.pos < self._stop_bit


class BitWriter:
    """MSB-first writer."""

    def __init__(self):
        self._bytes = bytearray()
        self._cur = 0
        self._nbits = 0

    @property
    def bit_length(self) -> int:
        return len(self._bytes) * 8 + self._nbits

    def write_bit(self, b: int) -> None:
        self._cur = (self._cur << 1) | (b & 1)
        self._nbits += 1
        if self._nbits == 8:
            self._bytes.append(self._cur)
            self._cur = 0
            self._nbits = 0

    def write_bits(self, v: int, n: int) -> None:
        for i in range(n - 1, -1, -1):
            self.write_bit((v >> i) & 1)

    def ue(self, v: int) -> None:
        if v < 0:
            raise ValueError("ue(v) needs v >= 0")
        k = v + 1
        n = k.bit_length()
        self.write_bits(0, n - 1)
        self.write_bits(k, n)

    def se(self, v: int) -> None:
        self.ue(2 * v - 1 if v > 0 else -2 * v)

    def rbsp_trailing(self) -> None:
        """rbsp_stop_one_bit + alignment zeros."""
        self.write_bit(1)
        while self._nbits:
            self.write_bit(0)

    def to_bytes(self) -> bytes:
        if self._nbits:
            raise ValueError("unaligned bitstream (call rbsp_trailing)")
        return bytes(self._bytes)


def nal_to_rbsp(nal_payload: bytes) -> bytes:
    """Strip emulation-prevention bytes (00 00 03 xx → 00 00 xx)."""
    out = bytearray()
    zeros = 0
    i = 0
    n = len(nal_payload)
    while i < n:
        b = nal_payload[i]
        if zeros >= 2 and b == 0x03 and i + 1 < n \
                and nal_payload[i + 1] <= 0x03:
            zeros = 0
            i += 1
            continue
        out.append(b)
        zeros = zeros + 1 if b == 0 else 0
        i += 1
    return bytes(out)


def rbsp_to_nal(rbsp: bytes) -> bytes:
    """Insert emulation-prevention bytes where 00 00 0[0-3] occurs."""
    out = bytearray()
    zeros = 0
    for b in rbsp:
        if zeros >= 2 and b <= 0x03:
            out.append(0x03)
            zeros = 0
        out.append(b)
        zeros = zeros + 1 if b == 0 else 0
    return bytes(out)
