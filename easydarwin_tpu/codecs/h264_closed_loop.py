"""Closed-loop intra requantization: reconstruct while requantizing so
spatial drift stops compounding (VERDICT r4 item 3 measured −12.9 dB of
open-loop drift at +6 on the DC-only probe).

Open-loop transform-domain requant shifts each block's levels and lets
every downstream intra prediction read slightly-wrong neighbors; the
error cascades across the picture.  The closed loop instead walks MBs
in decode order keeping TWO reconstructions — the original stream's
(the target) and the output stream's — and for every block re-derives
the residual against prediction from the OUTPUT reconstruction before
quantizing at the new QP:

    target  = dec(orig levels, qp_in)  + pred(recon_orig)
    levels' = Q(target − pred(recon_out), qp_out)
    recon_out ← pred(recon_out) + dec(levels', qp_out)

Full 8.3 intra prediction (``h264_pred``) covers every mode a real
encoder emits; the MB model is the shared one, so CAVLC and CABAC
slices both close the loop.  Scope: I slices (IDR pictures), 4:2:0,
MB-row-aligned multi-slice; P slices stay open-loop (their prediction
is temporal — closing it would need full motion compensation).

Verification: the full-mode decoder half is pixel-exact vs libavcodec
on x264 streams; closed-loop outputs decode bit-clean through the
err_detect=explode oracle and land within a few dB of a ground-up
re-encode at the target QP (tests/test_closed_loop.py).
"""

from __future__ import annotations

import numpy as np

from .h264_intra import BLK_XY, MacroblockI4x4, MacroblockI16x16
from .h264_pred import derive_i4x4_modes, pred4x4, pred16x16, pred_chroma
from .h264_transform import (LEVEL_CLIP, MF, V, ZIGZAG4, _CF, chroma_dc_dequant,
                             chroma_dc_quant, chroma_qp, dequant_inverse,
                             forward_transform_quant, inverse_core,
                             mf_position, v_position)

_INV_ZZ = np.argsort(ZIGZAG4)
_H4 = np.array([[1, 1, 1, 1], [1, 1, -1, -1],
                [1, -1, -1, 1], [1, -1, 1, -1]], dtype=np.int64)


def luma_dc_dequant(dc_zz: np.ndarray, qp: int) -> np.ndarray:
    """[16] zigzag I_16x16 DC levels → [4,4] dcY (8.5.10; exact shift
    form, valid for QPY ≥ 12 — the requant rung's documented window)."""
    if qp < 12:
        raise ValueError("I_16x16 DC dequant window is QPY >= 12")
    c = np.clip(dc_zz.astype(np.int64), -LEVEL_CLIP,
                LEVEL_CLIP)[_INV_ZZ].reshape(4, 4)
    f = _H4 @ c @ _H4
    return (f * int(V[qp % 6][0])) << (qp // 6 - 2)


def luma_dc_quant(w00: np.ndarray, qp: int) -> np.ndarray:
    """[4,4] per-block DC coefficients → [16] zigzag quantized DC
    levels (JM forward: 4x4 Hadamard with /2, MF with doubled deadzone
    and qbits+1 — the exact inverse pairing of ``luma_dc_dequant``)."""
    f = (_H4 @ w00.astype(np.int64) @ _H4) >> 1
    qbits = 15 + qp // 6
    off = (1 << qbits) // 3
    lev = np.sign(f) * ((np.abs(f) * int(MF[qp % 6][0]) + 2 * off)
                        >> (qbits + 1))
    return np.clip(lev.reshape(16), -LEVEL_CLIP, LEVEL_CLIP)[ZIGZAG4]


class PictureRecon:
    """One picture's reconstruction planes (Y, Cb, Cr)."""

    def __init__(self, width_mbs: int, height_mbs: int):
        h, w = height_mbs * 16, width_mbs * 16
        self.y = np.zeros((h, w), dtype=np.int64)
        self.c = np.zeros((2, h // 2, w // 2), dtype=np.int64)
        # per-4x4 actual intra mode (−1 = not intra-4x4): feeds 8.3.1.1
        self.blk_modes = np.full((height_mbs * 4, width_mbs * 4), -1,
                                 dtype=np.int32)


def _recon_i16_luma(recon: np.ndarray, pred: np.ndarray, mb: int,
                    w_mbs: int, dc_zz: np.ndarray, ac: np.ndarray,
                    qp: int) -> None:
    """I_16x16 luma reconstruction at ``qp`` (8.5.10 DC chain + AC)."""
    mbx, mby = (mb % w_mbs) * 16, (mb // w_mbs) * 16
    dcy = luma_dc_dequant(dc_zz, qp)
    vq = v_position(qp)
    for b in range(16):
        x4, y4 = BLK_XY[b]
        w = np.zeros(16, dtype=np.int64)
        w[ZIGZAG4[1:]] = np.clip(ac[b], -LEVEL_CLIP, LEVEL_CLIP)
        w *= vq
        w <<= qp // 6
        w[0] = dcy[y4, x4]
        res = inverse_core(w.reshape(4, 4))
        ys, xs = mby + y4 * 4, mbx + x4 * 4
        recon[ys:ys + 4, xs:xs + 4] = np.clip(
            pred[y4 * 4:y4 * 4 + 4, x4 * 4:x4 * 4 + 4] + res, 0, 255)


def _recon_chroma(recon_c: np.ndarray, pred: np.ndarray, mb: int,
                  w_mbs: int, comp: int, cdc: np.ndarray,
                  cac: np.ndarray, qpc: int) -> None:
    mbx, mby = (mb % w_mbs) * 8, (mb // w_mbs) * 8
    dcc = chroma_dc_dequant(cdc, qpc)
    vq = v_position(qpc)
    for b in range(4):
        bx, by = b & 1, b >> 1
        w = np.zeros(16, dtype=np.int64)
        w[ZIGZAG4[1:]] = np.clip(cac[b], -LEVEL_CLIP, LEVEL_CLIP)
        w = (w * vq) << (qpc // 6)
        w[0] = dcc[b]
        res = inverse_core(w.reshape(4, 4))
        recon_c[comp, mby + by * 4:mby + by * 4 + 4,
                mbx + bx * 4:mbx + bx * 4 + 4] = np.clip(
            pred[by * 4:by * 4 + 4, bx * 4:bx * 4 + 4] + res, 0, 255)


def decode_mb(pic: PictureRecon, sps, pps, mb_idx: int, mb,
              first_mb: int) -> None:
    """Reconstruct one parsed intra MB into ``pic`` (any pred mode)."""
    w_mbs = sps.width_mbs
    mbx, mby = mb_idx % w_mbs, mb_idx // w_mbs
    first_row = first_mb // w_mbs
    qpc = chroma_qp(mb.qp, pps.chroma_qp_offset)
    if getattr(mb, "transform_8x8", False):
        raise ValueError("closed loop covers 4x4-transform intra only")
    if isinstance(mb, MacroblockI4x4):
        modes = derive_i4x4_modes(mb.pred_modes, pic.blk_modes, mb_idx,
                                  w_mbs, first_mb)
        for b in range(16):
            x4, y4 = BLK_XY[b]
            gx, gy = mbx * 4 + x4, mby * 4 + y4
            pred = pred4x4(modes[b], pic.y, gx, gy, first_row * 4)
            res = dequant_inverse(mb.levels[b][_INV_ZZ], mb.qp)
            pic.y[gy * 4:gy * 4 + 4, gx * 4:gx * 4 + 4] = np.clip(
                pred + res, 0, 255)
    else:
        # 8.3.1.1: an AVAILABLE intra MB that is not I_4x4 contributes
        # mode 2 (DC) to Min(A, B) — only truly unavailable neighbors
        # force the DC-predicted flag
        pic.blk_modes[mby * 4:mby * 4 + 4, mbx * 4:mbx * 4 + 4] = 2
        pred = pred16x16(mb.pred_mode, pic.y, mbx, mby, first_row)
        _recon_i16_luma(pic.y, pred, mb_idx, w_mbs, mb.dc_levels,
                        mb.ac_levels, mb.qp)
    for comp in range(2):
        predc = pred_chroma(mb.chroma_mode, pic.c[comp], mbx, mby,
                            first_row)
        _recon_chroma(pic.c, predc, mb_idx, w_mbs, comp,
                      mb.chroma_dc[comp], mb.chroma_ac[comp], qpc)


def requant_mb_closed(orig: PictureRecon, out: PictureRecon, sps, pps,
                      mb_idx: int, mb, first_mb: int,
                      delta_qp: int) -> None:
    """Closed-loop requant of one intra MB: decode into ``orig`` at the
    source QP, then re-derive residuals against ``out``'s
    reconstruction and quantize at QP+delta, updating ``mb``'s levels
    and ``out`` in place.  CBP/luma15 recompute stays with the caller
    (shared with the open-loop writers)."""
    w_mbs = sps.width_mbs
    mbx, mby = mb_idx % w_mbs, mb_idx // w_mbs
    first_row = first_mb // w_mbs
    qp_out = mb.qp + delta_qp
    decode_mb(orig, sps, pps, mb_idx, mb, first_mb)   # target pixels
    qpc_out = chroma_qp(qp_out, pps.chroma_qp_offset)
    if isinstance(mb, MacroblockI4x4):
        modes = derive_i4x4_modes(mb.pred_modes, out.blk_modes, mb_idx,
                                  w_mbs, first_mb)
        for b in range(16):
            x4, y4 = BLK_XY[b]
            gx, gy = mbx * 4 + x4, mby * 4 + y4
            target = orig.y[gy * 4:gy * 4 + 4, gx * 4:gx * 4 + 4]
            pred = pred4x4(modes[b], out.y, gx, gy, first_row * 4)
            lev_raster = forward_transform_quant(
                target.astype(np.int64) - pred, qp_out)
            mb.levels[b] = lev_raster[ZIGZAG4]
            res = dequant_inverse(lev_raster, qp_out)
            out.y[gy * 4:gy * 4 + 4, gx * 4:gx * 4 + 4] = np.clip(
                pred + res, 0, 255)
    else:
        out.blk_modes[mby * 4:mby * 4 + 4, mbx * 4:mbx * 4 + 4] = 2
        pred = pred16x16(mb.pred_mode, out.y, mbx, mby, first_row)
        target = orig.y[mby * 16:mby * 16 + 16, mbx * 16:mbx * 16 + 16]
        res = target.astype(np.int64) - pred
        w00 = np.empty((4, 4), dtype=np.int64)
        mf = mf_position(qp_out)
        qbits = 15 + qp_out // 6
        f_off = (1 << qbits) // 3
        for b in range(16):
            x4, y4 = BLK_XY[b]
            blk = res[y4 * 4:y4 * 4 + 4, x4 * 4:x4 * 4 + 4]
            w = _CF @ blk @ _CF.T
            w00[y4, x4] = w[0, 0]
            lev = np.sign(w) * ((np.abs(w) * mf.reshape(4, 4) + f_off)
                                >> qbits)
            lev = np.clip(lev.reshape(16), -LEVEL_CLIP, LEVEL_CLIP)
            mb.ac_levels[b] = lev[ZIGZAG4[1:]]
        mb.dc_levels = luma_dc_quant(w00, qp_out)
        _recon_i16_luma(out.y, pred, mb_idx, w_mbs, mb.dc_levels,
                        mb.ac_levels, qp_out)
    for comp in range(2):
        target = orig.c[comp, mby * 8:mby * 8 + 8, mbx * 8:mbx * 8 + 8]
        predc = pred_chroma(mb.chroma_mode, out.c[comp], mbx, mby,
                            first_row)
        res = target.astype(np.int64) - predc
        w00 = np.empty(4, dtype=np.int64)
        ac = np.zeros((4, 15), dtype=np.int64)
        for b in range(4):
            bx, by = b & 1, b >> 1
            blk = res[by * 4:by * 4 + 4, bx * 4:bx * 4 + 4]
            w00[b] = (_CF @ blk @ _CF.T)[0, 0]
            ac[b] = forward_transform_quant(blk, qpc_out)[ZIGZAG4[1:]]
        mb.chroma_dc[comp] = chroma_dc_quant(w00, qpc_out)
        mb.chroma_ac[comp] = ac
        _recon_chroma(out.c, predc, mb_idx, w_mbs, comp,
                      mb.chroma_dc[comp], mb.chroma_ac[comp], qpc_out)


def decode_intra_picture(sps, pps, parsed_slices
                         ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Full-mode intra decoder over parsed (hdr, mbs) slices → uint8
    (Y, Cb, Cr).  The libavcodec-verified half of the closed loop."""
    pic = PictureRecon(sps.width_mbs, sps.height_mbs)
    for hdr, mbs in parsed_slices:
        if hdr.first_mb % sps.width_mbs:
            raise ValueError("closed-loop scope is MB-row-aligned slices")
        for i, mb in enumerate(mbs, start=hdr.first_mb):
            decode_mb(pic, sps, pps, i, mb, hdr.first_mb)
    return (pic.y.astype(np.uint8), pic.c[0].astype(np.uint8),
            pic.c[1].astype(np.uint8))
