"""Minimal H.264 baseline INTRA codec (CAVLC, I_4x4, 4:2:0).

Purpose: (a) generate REAL CAVLC-coded H.264 for the HLS transcode tests
and benches (the image ships no ffmpeg — SURVEY §4 note on building the
test pyramid from scratch), and (b) provide the slice/macroblock walk the
transform-domain requant rung (``h264_requant``) shares.

Scope (documented, test-enforced): I slices of I_4x4 and I_16x16
macroblocks, DC-mode prediction, CAVLC — including full 4:2:0 chroma
residuals (chroma DC 2×2 Hadamard + AC blocks, Table 9-5 nC=−1 coding,
8.3.4.1 mode-0 chroma prediction).  CABAC and inter prediction are out
of scope; the requant rung passes streams it cannot parse through
unchanged and says so in its stats.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from . import h264_cavlc as cavlc
from .h264_bits import BitReader, BitWriter, nal_to_rbsp, rbsp_to_nal
from .h264_transform import (_CF, ZIGZAG4, chroma_dc_dequant,
                             chroma_dc_quant, chroma_qp, dequant_inverse,
                             forward_transform_quant, inverse_core,
                             v_position)

#: Table 9-4 codeNum → coded_block_pattern for Intra_4x4 (ue-mapped CBP).
CBP_INTRA_FROM_CODE = [
    47, 31, 15, 0, 23, 27, 29, 30, 7, 11, 13, 14, 39, 43, 45, 46,
    16, 3, 5, 10, 12, 19, 21, 26, 28, 35, 37, 42, 44, 1, 2, 4,
    8, 17, 18, 20, 24, 6, 9, 22, 25, 32, 33, 34, 36, 40, 38, 41]
CBP_INTRA_TO_CODE = {cbp: i for i, cbp in enumerate(CBP_INTRA_FROM_CODE)}

#: Table 9-4 codeNum → coded_block_pattern, INTER column (cross-checked
#: against libavcodec's ff_h264_golomb_to_inter_cbp rodata).
CBP_INTER_FROM_CODE = [
    0, 16, 1, 2, 4, 8, 32, 3, 5, 10, 12, 15, 47, 7, 11, 13,
    14, 6, 9, 31, 35, 37, 42, 44, 33, 34, 36, 40, 39, 43, 45, 46,
    17, 18, 20, 24, 19, 21, 26, 28, 23, 27, 29, 30, 22, 25, 38, 41]
CBP_INTER_TO_CODE = {cbp: i for i, cbp in enumerate(CBP_INTER_FROM_CODE)}

#: P macroblock partitioning (Table 7-13): mb_type → number of
#: partitions whose ref_idx/mvd ride in mb_pred (P_8x8* handled apart).
P_MB_PARTS = {0: 1, 1: 2, 2: 2}
#: P sub_mb_type → number of sub-partition mvds (Table 7-17).
P_SUB_PARTS = (1, 2, 2, 4)

#: profile_idc values whose SPS carries the chroma_format / bit-depth /
#: scaling-matrix fields (7.3.2.1.1's "if( profile_idc == 100 || ... )"
#: list): High, High 10, High 4:2:2, High 4:4:4 Predictive, CAVLC 4:4:4,
#: Scalable (83/86), Multiview (118/128/138), and the MFC/stereo codes.
_HIGH_FAMILY = frozenset(
    (100, 110, 122, 244, 44, 83, 86, 118, 128, 138, 139, 134, 135))

#: luma4x4BlkIdx → (x4, y4) inside the macroblock (spec 6.4.3 scan)
BLK_XY = [(2 * ((i >> 2) & 1) + (i & 1), 2 * ((i >> 3) & 1)
           + ((i >> 1) & 1)) for i in range(16)]


@dataclass
class Sps:
    width_mbs: int
    height_mbs: int
    sps_id: int = 0
    log2_max_frame_num: int = 4
    poc_type: int = 2
    log2_max_poc_lsb: int = 4           # meaningful for poc_type 0 only
    profile_idc: int = 66               # 66 CAVLC baseline / 77 CABAC:
                                        # A.2.1 forbids CABAC in baseline

    def build(self) -> bytes:
        bw = BitWriter()
        bw.write_bits(self.profile_idc, 8)
        # baseline asserts constraint_set0/1; Main asserts set1 only
        bw.write_bits(0xC0 if self.profile_idc == 66 else 0x40, 8)
        bw.write_bits(30, 8)            # level_idc 3.0
        bw.ue(self.sps_id)
        bw.ue(self.log2_max_frame_num - 4)
        bw.ue(self.poc_type)
        if self.poc_type == 0:
            bw.ue(self.log2_max_poc_lsb - 4)
        bw.ue(1)                        # max_num_ref_frames
        bw.write_bit(0)                 # gaps_in_frame_num
        bw.ue(self.width_mbs - 1)
        bw.ue(self.height_mbs - 1)
        bw.write_bit(1)                 # frame_mbs_only
        bw.write_bit(1)                 # direct_8x8_inference
        bw.write_bit(0)                 # frame_cropping
        bw.write_bit(0)                 # vui_parameters_present
        bw.rbsp_trailing()
        return b"\x67" + rbsp_to_nal(bw.to_bytes())

    @classmethod
    def parse(cls, nal: bytes) -> "Sps":
        br = BitReader(nal_to_rbsp(nal[1:]))
        profile = br.read_bits(8)
        # 7.3.2.1.1: every profile in _HIGH_FAMILY carries the
        # chroma_format/bit_depth/scaling fields after sps_id — not just
        # 100.  Gating on the full set keeps e.g. a High-10 SPS from
        # being silently misparsed (its chroma_format read as
        # log2_max_frame_num) instead of cleanly rejected.
        if profile not in (66, 77, 88) and profile not in _HIGH_FAMILY:
            raise ValueError(f"unsupported profile {profile}")
        br.read_bits(8)                 # constraint flags
        br.read_bits(8)                 # level
        sps_id = br.ue()
        if profile in _HIGH_FAMILY:
            # the High family is in scope as long as it stays 4:2:0
            # 8-bit with FLAT scaling (non-flat matrices change the
            # requant math; reject → the rung passes the stream through)
            if br.ue() != 1:
                raise ValueError("chroma_format != 4:2:0")
            if br.ue() != 0 or br.ue() != 0:
                raise ValueError("bit depth > 8")
            if br.read_bit():
                raise ValueError("transform bypass unsupported")
            if br.read_bit():
                raise ValueError("scaling matrices unsupported")
        log2_mfn = br.ue() + 4
        poc_type = br.ue()
        log2_poc = 4
        if poc_type == 0:
            log2_poc = br.ue() + 4
        elif poc_type == 1:
            raise ValueError("poc_type 1 unsupported")
        br.ue()                         # max_num_ref_frames
        br.read_bit()
        w = br.ue() + 1
        h = br.ue() + 1
        fmo = br.read_bit()             # frame_mbs_only
        if not fmo:
            raise ValueError("interlace unsupported")
        return cls(w, h, sps_id, log2_mfn, poc_type, log2_poc)


@dataclass
class Pps:
    pps_id: int = 0
    sps_id: int = 0
    pic_init_qp: int = 26
    deblocking_control: bool = True
    bottom_field_poc: bool = False
    chroma_qp_offset: int = 0           # chroma_qp_index_offset (7.4.2.2)
    entropy_cabac: bool = False         # entropy_coding_mode_flag
    num_ref_l0_default: int = 0         # num_ref_idx_l0_default_active_minus1
    num_ref_l1_default: int = 0
    weighted_pred: bool = False         # P-slice explicit weighting
    transform_8x8_mode: bool = False    # High-profile 8x8 transform

    def build(self) -> bytes:
        bw = BitWriter()
        bw.ue(self.pps_id)
        bw.ue(self.sps_id)
        bw.write_bit(1 if self.entropy_cabac else 0)
        bw.write_bit(0)                 # bottom_field_pic_order
        bw.ue(0)                        # num_slice_groups_minus1
        bw.ue(self.num_ref_l0_default)
        bw.ue(self.num_ref_l1_default)
        bw.write_bit(1 if self.weighted_pred else 0)
        bw.write_bits(0, 2)             # weighted_bipred_idc
        bw.se(self.pic_init_qp - 26)
        bw.se(0)                        # pic_init_qs
        bw.se(self.chroma_qp_offset)
        bw.write_bit(1 if self.deblocking_control else 0)
        bw.write_bit(0)                 # constrained_intra_pred
        bw.write_bit(0)                 # redundant_pic_cnt_present
        if self.transform_8x8_mode:     # High-profile extension
            bw.write_bit(1)
            bw.write_bit(0)             # no scaling matrices
            bw.se(self.chroma_qp_offset)
        bw.rbsp_trailing()
        return b"\x68" + rbsp_to_nal(bw.to_bytes())

    @classmethod
    def parse(cls, nal: bytes) -> "Pps":
        br = BitReader(nal_to_rbsp(nal[1:]))
        pps_id = br.ue()
        sps_id = br.ue()
        cabac = bool(br.read_bit())     # entropy_coding_mode_flag
        bottom_poc = bool(br.read_bit())
        if br.ue() != 0:
            raise ValueError("slice groups unsupported")
        nref0 = br.ue()                 # num_ref_idx_l*_default_active_minus1
        nref1 = br.ue()
        wpred = bool(br.read_bit())     # weighted_pred (P requant rejects
        br.read_bits(2)                 # explicit weight tables at slice
        qp = br.se() + 26               # parse — pass-through)
        br.se()
        chroma_off = br.se()
        deblock = bool(br.read_bit())
        br.read_bit()                   # constrained_intra_pred
        if br.read_bit():               # redundant_pic_cnt_present: the
            # P slice header would carry redundant_pic_cnt — reject so
            # the rung passes such streams through instead of misparsing
            raise ValueError("redundant_pic_cnt unsupported")
        t8 = False
        if br.more_rbsp_data():         # High-profile PPS extension
            t8 = bool(br.read_bit())    # transform_8x8_mode_flag
            if br.read_bit():
                raise ValueError("scaling matrices unsupported")
            if br.se() != chroma_off:   # second_chroma_qp_index_offset:
                # the requant maps both components through ONE offset
                raise ValueError("split Cb/Cr qp offsets unsupported")
        return cls(pps_id, sps_id, qp, deblock, bottom_poc, chroma_off,
                   cabac, nref0, nref1, wpred, t8)


@dataclass
class SliceHeader:
    """Round-trippable I/P-slice header fields (subset of 7.3.3)."""

    nal_type: int = 5
    nal_ref_idc: int = 3
    slice_type: int = 7
    first_mb: int = 0                   # first_mb_in_slice (multi-slice)
    frame_num: int = 0
    idr_pic_id: int = 0
    poc_lsb: int = 0
    no_output_prior: int = 0
    long_term_ref: int = 0
    qp: int = 26
    deblock_idc: int = 1
    deblock_alpha: int = 0
    deblock_beta: int = 0
    # -- P-slice fields (7.3.3 + 7.3.3.1/7.3.3.3), round-tripped raw --
    num_ref_override: bool = False      # num_ref_idx_active_override_flag
    num_ref_l0_minus1: int = 0          # valid when num_ref_override
    ref_list_mod: "list[tuple[int, int]] | None" = None   # l0 (idc, val)
    adaptive_marking: "list[tuple[int, tuple[int, ...]]] | None" = None
    cabac_init_idc: int = 0

    @property
    def is_p(self) -> bool:
        return self.slice_type % 5 == 0

    def num_ref_l0(self, pps: "Pps") -> int:
        """Active l0 reference count for this slice."""
        return (self.num_ref_l0_minus1 if self.num_ref_override
                else pps.num_ref_l0_default) + 1


def _zero_chroma() -> tuple[np.ndarray, np.ndarray]:
    return (np.zeros((2, 4), dtype=np.int64),
            np.zeros((2, 4, 15), dtype=np.int64))


@dataclass
class MacroblockI4x4:
    """Parsed I_NxN macroblock: everything needed to re-encode.  With
    ``transform_8x8`` (High profile), ``pred_modes`` holds FOUR intra8x8
    mode pairs and the residual lives in ``levels8`` ([4, 64] 8x8-zigzag
    levels) instead of ``levels``."""

    pred_modes: list[tuple[int, int]]   # (use_predicted, rem_mode) × 16/4
    chroma_mode: int
    cbp: int                            # FULL 6-bit CBP (luma | chroma<<4)
    qp: int                             # ABSOLUTE QPY of this MB (spec
    levels: np.ndarray                  # 7.4.5: mb_qp_delta accumulates
                                        # across MBs; the writer re-derives
                                        # deltas) · [16, 16] zigzag levels
    chroma_dc: np.ndarray = field(default_factory=lambda: _zero_chroma()[0])
    chroma_ac: np.ndarray = field(default_factory=lambda: _zero_chroma()[1])
    transform_8x8: bool = False
    levels8: "np.ndarray | None" = None

    @property
    def chroma_cbp(self) -> int:
        return self.cbp >> 4


@dataclass
class MacroblockI16x16:
    """Parsed I_16x16 macroblock (mb_type 1..24): DC Hadamard block +
    optional 15-coeff AC blocks, plus 4:2:0 chroma residuals."""

    pred_mode: int                      # intra16x16 pred mode 0..3
    chroma_mode: int
    luma_cbp15: bool                    # True = AC blocks coded (CBP 15)
    qp: int
    dc_levels: np.ndarray               # [16] zigzag DC levels
    ac_levels: np.ndarray               # [16, 15] zigzag AC levels
    chroma_cbp: int = 0                 # 0 none / 1 DC only / 2 DC+AC
    chroma_dc: np.ndarray = field(default_factory=lambda: _zero_chroma()[0])
    chroma_ac: np.ndarray = field(default_factory=lambda: _zero_chroma()[1])

    @property
    def mb_type(self) -> int:
        return (1 + self.pred_mode + 4 * self.chroma_cbp
                + (12 if self.luma_cbp15 else 0))


class MacroblockPSkip:
    """P_Skip marker: occupies an MB position with no syntax of its own
    (CAVLC folds runs of these into mb_skip_run; CABAC codes one
    mb_skip_flag each).  The requant rung never touches skipped MBs."""

    __slots__ = ()
    qp = None                           # no QP chain participation
    chroma_cbp = 0


@dataclass
class MacroblockInter:
    """Parsed P macroblock (mb_type 0..4): motion syntax is carried
    VERBATIM (the transform-domain rung never re-derives prediction),
    residual levels are the requant surface.

    ``refs``/``mvds`` are in exact bitstream order (mb_pred /
    sub_mb_pred 7.3.5.1-2): all ref_idx_l0 first, then every mvd pair;
    ``sub_types`` is None unless mb_type is P_8x8 / P_8x8ref0."""

    mb_type: int                        # 0..4 (Table 7-13)
    sub_types: "list[int] | None"       # 4 × sub_mb_type for P_8x8*
    refs: list[int]                     # ref_idx_l0 per partition
    mvds: "list[tuple[int, int]]"       # (mvd_x, mvd_y) per (sub)partition
    cbp: int                            # FULL 6-bit CBP
    qp: int                             # ABSOLUTE QPY (7.4.5 chain)
    levels: np.ndarray                  # [16, 16] zigzag luma levels
    chroma_dc: np.ndarray = field(default_factory=lambda: _zero_chroma()[0])
    chroma_ac: np.ndarray = field(default_factory=lambda: _zero_chroma()[1])
    transform_8x8: bool = False
    levels8: "np.ndarray | None" = None

    @property
    def chroma_cbp(self) -> int:
        return self.cbp >> 4

    @property
    def allows_8x8(self) -> bool:
        """7.3.5's noSubMbPartSizeLessThan8x8Flag for P types."""
        return self.mb_type <= 2 or all(t == 0 for t in self.sub_types)


class SliceCodec:
    """Shared slice walk: parse ⇄ serialize I slices of I_4x4 and
    I_16x16 macroblocks."""

    def __init__(self, sps: Sps, pps: Pps):
        self.sps = sps
        self.pps = pps

    # -- slice header ------------------------------------------------------
    def parse_slice_header(self, br: BitReader, nal_byte: int
                           ) -> "SliceHeader":
        """Parses the full I/P-slice header (H.264 7.3.3) so the requant
        writer can ROUND-TRIP every field — frame_num, idr_pic_id, POC
        lsb, ref-list modifications, dec_ref_pic_marking — not just the
        QP.  Leaves ``br`` at the first MB."""
        nal_type = nal_byte & 0x1F
        nal_ref_idc = (nal_byte >> 5) & 3
        h = SliceHeader(nal_type=nal_type, nal_ref_idc=nal_ref_idc)
        h.first_mb = br.ue()
        if h.first_mb >= self.sps.width_mbs * self.sps.height_mbs:
            raise ValueError("first_mb_in_slice beyond the picture")
        h.slice_type = br.ue()
        if h.slice_type % 5 not in (0, 2):
            raise ValueError(
                f"slice type {h.slice_type} unsupported (I/P scope)")
        br.ue()                          # pps id (ours)
        h.frame_num = br.read_bits(self.sps.log2_max_frame_num)
        if nal_type == 5:
            h.idr_pic_id = br.ue()
        if self.sps.poc_type == 0:
            if self.pps.bottom_field_poc:
                raise ValueError("bottom-field POC unsupported")
            h.poc_lsb = br.read_bits(self.sps.log2_max_poc_lsb)
        if h.is_p:
            if self.pps.weighted_pred:
                # explicit pred_weight_table in the header — out of the
                # requant scope, pass the stream through
                raise ValueError("weighted prediction unsupported")
            h.num_ref_override = bool(br.read_bit())
            if h.num_ref_override:
                h.num_ref_l0_minus1 = br.ue()
            if br.read_bit():            # ref_pic_list_modification_flag
                h.ref_list_mod = []
                while True:
                    idc = br.ue()
                    if idc == 3:
                        break
                    if idc > 3:
                        raise ValueError("bad modification idc")
                    h.ref_list_mod.append((idc, br.ue()))
        if nal_ref_idc != 0:             # dec_ref_pic_marking (7.3.3.3)
            if nal_type == 5:
                h.no_output_prior = br.read_bit()
                h.long_term_ref = br.read_bit()
            elif br.read_bit():          # adaptive marking: MMCO loop,
                h.adaptive_marking = []  # round-tripped raw (7.4.3.3)
                while True:
                    op = br.ue()
                    if op == 0:
                        break
                    if op in (1, 2, 4, 6):
                        h.adaptive_marking.append((op, (br.ue(),)))
                    elif op == 3:
                        h.adaptive_marking.append((op, (br.ue(), br.ue())))
                    elif op == 5:
                        h.adaptive_marking.append((op, ()))
                    else:
                        raise ValueError("bad MMCO op")
        if self.pps.entropy_cabac and h.is_p:
            h.cabac_init_idc = br.ue()
            if h.cabac_init_idc > 2:
                raise ValueError("cabac_init_idc out of range")
        h.qp = self.pps.pic_init_qp + br.se()        # + slice_qp_delta
        if self.pps.deblocking_control:
            idc = br.ue()
            h.deblock_idc = idc
            if idc != 1:
                h.deblock_alpha = br.se()
                h.deblock_beta = br.se()
        return h

    def write_slice_header(self, bw: BitWriter, h: "SliceHeader",
                           qp: int) -> None:
        bw.ue(h.first_mb)                # first_mb_in_slice
        bw.ue(h.slice_type)
        bw.ue(self.pps.pps_id)
        bw.write_bits(h.frame_num, self.sps.log2_max_frame_num)
        if h.nal_type == 5:
            bw.ue(h.idr_pic_id)
        if self.sps.poc_type == 0:
            bw.write_bits(h.poc_lsb, self.sps.log2_max_poc_lsb)
        if h.is_p:
            bw.write_bit(1 if h.num_ref_override else 0)
            if h.num_ref_override:
                bw.ue(h.num_ref_l0_minus1)
            bw.write_bit(1 if h.ref_list_mod is not None else 0)
            if h.ref_list_mod is not None:
                for idc, val in h.ref_list_mod:
                    bw.ue(idc)
                    bw.ue(val)
                bw.ue(3)
        if h.nal_ref_idc != 0:           # dec_ref_pic_marking
            if h.nal_type == 5:
                bw.write_bit(h.no_output_prior)
                bw.write_bit(h.long_term_ref)
            else:
                bw.write_bit(1 if h.adaptive_marking is not None else 0)
                if h.adaptive_marking is not None:
                    for op, args in h.adaptive_marking:
                        bw.ue(op)
                        for a in args:
                            bw.ue(a)
                    bw.ue(0)
        if self.pps.entropy_cabac and h.is_p:
            bw.ue(h.cabac_init_idc)
        bw.se(qp - self.pps.pic_init_qp)
        if self.pps.deblocking_control:
            bw.ue(h.deblock_idc)
            if h.deblock_idc != 1:
                bw.se(h.deblock_alpha)
                bw.se(h.deblock_beta)

    # -- macroblock layer --------------------------------------------------
    def _fresh_totals(self):
        """(luma, chroma) nC context grids for one slice walk: per-4x4
        TotalCoeff, −1 = unavailable.  Chroma grid is [2, h2, w2] (Cb,
        Cr planes of 2×2 blocks per MB)."""
        w4 = self.sps.width_mbs * 4
        h4 = self.sps.height_mbs * 4
        luma = np.full((h4, w4), -1, dtype=np.int32)
        chroma = np.full((2, self.sps.height_mbs * 2,
                          self.sps.width_mbs * 2), -1, dtype=np.int32)
        return luma, chroma

    def _mark_skip_nc(self, mb_idx: int, totals: np.ndarray,
                      tot_c: np.ndarray) -> None:
        """A P_Skip MB's blocks count TotalCoeff 0 in 9.2.1 neighbor
        contexts (available, no residual)."""
        mb_x = (mb_idx % self.sps.width_mbs) * 4
        mb_y = (mb_idx // self.sps.width_mbs) * 4
        totals[mb_y:mb_y + 4, mb_x:mb_x + 4] = 0
        cx, cy = (mb_idx % self.sps.width_mbs) * 2, \
            (mb_idx // self.sps.width_mbs) * 2
        tot_c[:, cy:cy + 2, cx:cx + 2] = 0

    def _read_ref(self, br: BitReader, n_ref: int) -> int:
        if n_ref == 1:
            return 0                    # not coded, inferred (7.4.5.1)
        if n_ref == 2:
            return 1 - br.read_bit()    # te(v) with cMax 1: inverted bit
        return br.ue()

    def _write_ref(self, bw: BitWriter, ref: int, n_ref: int) -> None:
        if n_ref == 1:
            return
        if n_ref == 2:
            bw.write_bit(1 - ref)
        else:
            bw.ue(ref)

    def _parse_inter_mb(self, br: BitReader, mb_type: int, mb_idx: int,
                        cur_qp: int, n_ref: int, totals: np.ndarray,
                        tot_c: np.ndarray
                        ) -> "tuple[MacroblockInter, int]":
        """mb_pred/sub_mb_pred (7.3.5.1-2) for P types 0..4, then the
        shared residual walk.  Motion syntax is carried verbatim."""
        sub_types = None
        refs: list[int] = []
        mvds: list[tuple[int, int]] = []
        if mb_type in (0, 1, 2):
            nparts = P_MB_PARTS[mb_type]
            for _ in range(nparts):
                refs.append(self._read_ref(br, n_ref))
            for _ in range(nparts):
                mvds.append((br.se(), br.se()))
        elif mb_type in (3, 4):
            sub_types = [br.ue() for _ in range(4)]
            if any(t > 3 for t in sub_types):
                raise ValueError("bad P sub_mb_type")
            if mb_type == 3:
                for _ in range(4):
                    refs.append(self._read_ref(br, n_ref))
            for st in sub_types:        # P_8x8ref0: refs inferred 0
                for _ in range(P_SUB_PARTS[st]):
                    mvds.append((br.se(), br.se()))
        else:
            raise ValueError(f"P mb_type {mb_type} unsupported")
        cbp = CBP_INTER_FROM_CODE[br.ue()]
        mb = MacroblockInter(mb_type, sub_types, refs, mvds, cbp, cur_qp,
                             np.zeros((16, 16), dtype=np.int64))
        if (cbp & 15) and self.pps.transform_8x8_mode and mb.allows_8x8:
            mb.transform_8x8 = bool(br.read_bit())
        if cbp:
            cur_qp += br.se()           # mb_qp_delta accumulates (7.4.5)
            if not 0 <= cur_qp <= 51:
                raise ValueError("QPY out of range")
            mb.qp = cur_qp
        if mb.transform_8x8:
            mb.levels8 = np.zeros((4, 64), dtype=np.int64)
            self._residuals8(br, mb_idx, cbp & 15, mb.levels8, totals,
                             decode=True)
        else:
            self._residuals(br, mb_idx, cbp & 15, mb.levels, totals,
                            decode=True)
        self._residuals_chroma(br, mb_idx, cbp >> 4, mb.chroma_dc,
                               mb.chroma_ac, tot_c, decode=True)
        return mb, cur_qp

    def _write_inter_mb(self, bw: BitWriter, mb: "MacroblockInter",
                        mb_idx: int, prev_qp: int, n_ref: int,
                        totals: np.ndarray, tot_c: np.ndarray) -> None:
        bw.ue(mb.mb_type)
        if mb.mb_type in (0, 1, 2):
            for r in mb.refs:
                self._write_ref(bw, r, n_ref)
        else:
            for st in mb.sub_types:
                bw.ue(st)
            if mb.mb_type == 3:
                for r in mb.refs:
                    self._write_ref(bw, r, n_ref)
        for mx, my in mb.mvds:
            bw.se(mx)
            bw.se(my)
        bw.ue(CBP_INTER_TO_CODE[mb.cbp])
        if (mb.cbp & 15) and self.pps.transform_8x8_mode \
                and mb.allows_8x8:
            bw.write_bit(1 if mb.transform_8x8 else 0)
        if mb.cbp:
            delta = mb.qp - prev_qp
            if not -26 <= delta <= 25:
                raise ValueError("mb_qp_delta out of range")
            bw.se(delta)
        if mb.transform_8x8 and mb.levels8 is not None:
            self._residuals8(bw, mb_idx, mb.cbp & 15, mb.levels8,
                             totals, decode=False)
        else:
            self._residuals(bw, mb_idx, mb.cbp & 15, mb.levels, totals,
                            decode=False)
        self._residuals_chroma(bw, mb_idx, mb.cbp >> 4, mb.chroma_dc,
                               mb.chroma_ac, tot_c, decode=False)

    def parse_mbs(self, br: BitReader, slice_qp: int, first_mb: int = 0,
                  hdr: "SliceHeader | None" = None) -> "list":
        """Walk the slice's MBs from ``first_mb`` until the RBSP stop bit
        (7.3.4 moreDataFlag for CAVLC).  nC contexts start fresh — MBs of
        other slices are unavailable neighbors (6.4.9), which the grids'
        untouched −1 cells encode exactly.  With a P ``hdr``, each
        iteration consumes the leading mb_skip_run and inter MB types;
        intra mb_types arrive offset by 5 (Table 7-13)."""
        n_mbs = self.sps.width_mbs * self.sps.height_mbs
        totals, tot_c = self._fresh_totals()
        mbs = []
        cur_qp = slice_qp
        is_p = hdr is not None and hdr.is_p
        n_ref = hdr.num_ref_l0(self.pps) if is_p else 1
        mb_idx = first_mb
        while mb_idx < n_mbs:
            if mbs and not br.more_rbsp_data():
                break                   # end of this slice's MB data
            if is_p:
                run = br.ue()           # mb_skip_run
                if mb_idx + run > n_mbs:
                    raise ValueError("skip run overruns picture")
                for _ in range(run):
                    self._mark_skip_nc(mb_idx, totals, tot_c)
                    mbs.append(MacroblockPSkip())
                    mb_idx += 1
                if not br.more_rbsp_data():
                    break               # slice ends on a skip run
                if mb_idx >= n_mbs:
                    raise ValueError("MB data past picture end")
            mb_type = br.ue()
            if is_p and mb_type < 5:
                mb, cur_qp = self._parse_inter_mb(
                    br, mb_type, mb_idx, cur_qp, n_ref, totals, tot_c)
                mbs.append(mb)
                mb_idx += 1
                continue
            if is_p:
                mb_type -= 5            # intra types ride offset by 5
            if mb_type == 0:
                t8 = bool(self.pps.transform_8x8_mode and br.read_bit())
                modes = []
                for _ in range(4 if t8 else 16):
                    flag = br.read_bit()
                    rem = 0 if flag else br.read_bits(3)
                    modes.append((flag, rem))
                chroma_mode = br.ue()
                cbp = CBP_INTRA_FROM_CODE[br.ue()]
                if cbp:
                    cur_qp += br.se()   # mb_qp_delta ACCUMULATES (7.4.5)
                    if not 0 <= cur_qp <= 51:
                        raise ValueError("QPY out of range")
                levels = np.zeros((16, 16), dtype=np.int64)
                mb = MacroblockI4x4(modes, chroma_mode, cbp, cur_qp,
                                    levels, transform_8x8=t8)
                if t8:
                    mb.levels8 = np.zeros((4, 64), dtype=np.int64)
                    self._residuals8(br, mb_idx, cbp, mb.levels8,
                                     totals, decode=True)
                else:
                    self._residuals(br, mb_idx, cbp, levels, totals,
                                    decode=True)
                self._residuals_chroma(br, mb_idx, cbp >> 4,
                                       mb.chroma_dc, mb.chroma_ac,
                                       tot_c, decode=True)
                mbs.append(mb)
            elif 1 <= mb_type <= 24:
                pred = (mb_type - 1) % 4
                chroma_cbp = ((mb_type - 1) // 4) % 3
                luma15 = mb_type >= 13
                chroma_mode = br.ue()
                cur_qp += br.se()       # always coded for I_16x16
                if not 12 <= cur_qp <= 51:
                    # <12: DC dequant uses a rounding form that breaks the
                    # exact +6k shift argument — pass through
                    raise ValueError("QPY out of I_16x16 requant range")
                mb16 = MacroblockI16x16(
                    pred, chroma_mode, luma15, cur_qp,
                    np.zeros(16, dtype=np.int64),
                    np.zeros((16, 15), dtype=np.int64), chroma_cbp)
                self._residuals16(br, mb_idx, mb16, totals, decode=True)
                self._residuals_chroma(br, mb_idx, chroma_cbp,
                                       mb16.chroma_dc, mb16.chroma_ac,
                                       tot_c, decode=True)
                mbs.append(mb16)
            else:
                raise ValueError(
                    f"mb_type {mb_type} unsupported (I/P scope)")
            mb_idx += 1
        return mbs

    def write_mbs(self, bw: BitWriter, mbs: "list", slice_qp: int,
                  first_mb: int = 0,
                  hdr: "SliceHeader | None" = None) -> None:
        totals, tot_c = self._fresh_totals()
        prev_qp = slice_qp               # deltas are vs the PREVIOUS MB's
        is_p = hdr is not None and hdr.is_p  # QP (7.4.5), not slice QP
        n_ref = hdr.num_ref_l0(self.pps) if is_p else 1
        run = 0
        for mb_idx, mb in enumerate(mbs, start=first_mb):
            if isinstance(mb, MacroblockPSkip):
                self._mark_skip_nc(mb_idx, totals, tot_c)
                run += 1
                continue
            if is_p:
                bw.ue(run)               # mb_skip_run before every coded
                run = 0                  # MB of a P slice (7.3.4)
            if isinstance(mb, MacroblockInter):
                self._write_inter_mb(bw, mb, mb_idx, prev_qp, n_ref,
                                     totals, tot_c)
                if mb.cbp:
                    prev_qp = mb.qp
                continue
            if isinstance(mb, MacroblockI16x16):
                bw.ue(mb.mb_type + (5 if is_p else 0))
                bw.ue(mb.chroma_mode)
                delta = mb.qp - prev_qp
                if not -26 <= delta <= 25:
                    raise ValueError("mb_qp_delta out of range")
                bw.se(delta)             # always coded for I_16x16
                prev_qp = mb.qp
                self._residuals16(bw, mb_idx, mb, totals, decode=False)
                self._residuals_chroma(bw, mb_idx, mb.chroma_cbp,
                                       mb.chroma_dc, mb.chroma_ac,
                                       tot_c, decode=False)
                continue
            bw.ue(5 if is_p else 0)      # mb_type I_NxN
            if self.pps.transform_8x8_mode:
                bw.write_bit(1 if mb.transform_8x8 else 0)
            for flag, rem in mb.pred_modes:
                bw.write_bit(flag)
                if not flag:
                    bw.write_bits(rem, 3)
            bw.ue(mb.chroma_mode)
            bw.ue(CBP_INTRA_TO_CODE[mb.cbp])
            if mb.cbp:
                delta = mb.qp - prev_qp
                if not -26 <= delta <= 25:
                    raise ValueError("mb_qp_delta out of range")
                bw.se(delta)
                prev_qp = mb.qp
            # cbp == 0: no qp_delta syntax — the MB has no residual so its
            # QP is irrelevant; prev_qp carries to the next coded MB
            if mb.transform_8x8 and mb.levels8 is not None:
                self._residuals8(bw, mb_idx, mb.cbp & 15, mb.levels8,
                                 totals, decode=False)
            else:
                self._residuals(bw, mb_idx, mb.cbp, mb.levels, totals,
                                decode=False)
            self._residuals_chroma(bw, mb_idx, mb.cbp >> 4,
                                   mb.chroma_dc, mb.chroma_ac,
                                   tot_c, decode=False)
        if is_p and run:
            bw.ue(run)                   # slice ends on a skip run

    def _nc_at(self, totals: np.ndarray, gx: int, gy: int) -> int:
        w4 = totals.shape[1]
        nA = totals[gy, gx - 1] if gx > 0 else -1
        nB = totals[gy - 1, gx] if gy > 0 else -1
        if nA >= 0 and nB >= 0:
            return int(nA + nB + 1) >> 1
        if nA >= 0:
            return int(nA)
        if nB >= 0:
            return int(nB)
        return 0

    def _residuals16(self, bio, mb_idx: int, mb: "MacroblockI16x16",
                     totals: np.ndarray, *, decode: bool) -> None:
        """I_16x16 residual walk: one 16-coeff DC block (nC from the
        luma4x4BlkIdx-0 neighbors), then — when luma CBP is 15 — sixteen
        15-coeff AC blocks.  Per-4x4 context totals store the AC
        TotalCoeff (DC excluded), matching 9.2.1's nN derivation."""
        mb_x = (mb_idx % self.sps.width_mbs) * 4
        mb_y = (mb_idx // self.sps.width_mbs) * 4
        nC = self._nc_at(totals, mb_x, mb_y)
        if decode:
            mb.dc_levels[:] = cavlc.decode_residual(bio, nC, 16)
        else:
            cavlc.encode_residual(bio, [int(v) for v in mb.dc_levels], nC,
                                  16)
        for blk in range(16):
            x4, y4 = BLK_XY[blk]
            gx, gy = mb_x + x4, mb_y + y4
            if not mb.luma_cbp15:
                totals[gy, gx] = 0
                if decode:
                    mb.ac_levels[blk] = 0
                continue
            nC = self._nc_at(totals, gx, gy)
            if decode:
                mb.ac_levels[blk] = cavlc.decode_residual(bio, nC, 15)
                totals[gy, gx] = int(np.count_nonzero(mb.ac_levels[blk]))
            else:
                cavlc.encode_residual(
                    bio, [int(v) for v in mb.ac_levels[blk]], nC, 15)
                totals[gy, gx] = int(np.count_nonzero(mb.ac_levels[blk]))

    def _residuals(self, bio, mb_idx: int, cbp: int, levels: np.ndarray,
                   totals: np.ndarray, *, decode: bool) -> None:
        """Walk the 16 luma blocks in spec order, maintaining the nC
        context grid; decode into ``levels`` or encode from it."""
        mb_x = (mb_idx % self.sps.width_mbs) * 4
        mb_y = (mb_idx // self.sps.width_mbs) * 4
        for blk in range(16):
            x4, y4 = BLK_XY[blk]
            gx, gy = mb_x + x4, mb_y + y4
            if not (cbp >> (blk >> 2)) & 1:
                totals[gy, gx] = 0
                levels[blk] = 0
                continue
            nA = totals[gy, gx - 1] if gx > 0 else -1
            nB = totals[gy - 1, gx] if gy > 0 else -1
            if nA >= 0 and nB >= 0:
                nC = (nA + nB + 1) >> 1
            elif nA >= 0:
                nC = int(nA)
            elif nB >= 0:
                nC = int(nB)
            else:
                nC = 0
            if decode:
                lv = cavlc.decode_residual(bio, nC)
                levels[blk] = lv
                totals[gy, gx] = sum(1 for v in lv if v)
            else:
                lv = [int(v) for v in levels[blk]]
                cavlc.encode_residual(bio, lv, nC)
                totals[gy, gx] = sum(1 for v in lv if v)

    def _residuals8(self, bio, mb_idx: int, cbp: int,
                    levels8: np.ndarray, totals: np.ndarray, *,
                    decode: bool) -> None:
        """8x8-transform luma residuals, CAVLC style (7.3.5.3.2): each
        coded 8x8 block rides as FOUR interleaved 4x4 blocks — sub j
        carries 8x8-zigzag positions j, j+4, ... — with the ordinary
        per-4x4 nC context grid."""
        mb_x = (mb_idx % self.sps.width_mbs) * 4
        mb_y = (mb_idx // self.sps.width_mbs) * 4
        for blk in range(16):
            i8, j = blk >> 2, blk & 3
            x4, y4 = BLK_XY[blk]
            gx, gy = mb_x + x4, mb_y + y4
            if not (cbp >> i8) & 1:
                totals[gy, gx] = 0
                if decode:
                    levels8[i8, j::4] = 0
                continue
            nC = self._nc_at(totals, gx, gy)
            if decode:
                lv = cavlc.decode_residual(bio, nC)
                levels8[i8, j::4] = lv
                totals[gy, gx] = sum(1 for v in lv if v)
            else:
                lv = [int(v) for v in levels8[i8, j::4]]
                cavlc.encode_residual(bio, lv, nC)
                totals[gy, gx] = sum(1 for v in lv if v)

    def _residuals_chroma(self, bio, mb_idx: int, chroma_cbp: int,
                          cdc: np.ndarray, cac: np.ndarray,
                          tot_c: np.ndarray, *, decode: bool) -> None:
        """4:2:0 chroma residual walk (7.3.5.3.3 order): both components'
        DC blocks first (nC = −1, 4 coeffs), then per component the four
        AC blocks when chroma CBP is 2.  ``tot_c[comp]`` keeps each
        chroma AC block's TotalCoeff for 9.2.1 neighbor contexts (an
        uncoded block counts 0, off-picture is unavailable)."""
        mb_x = (mb_idx % self.sps.width_mbs) * 2
        mb_y = (mb_idx // self.sps.width_mbs) * 2
        if chroma_cbp:
            for comp in range(2):
                if decode:
                    cdc[comp] = cavlc.decode_residual(bio, -1, 4)
                else:
                    cavlc.encode_residual(
                        bio, [int(v) for v in cdc[comp]], -1, 4)
        elif decode:
            cdc[:] = 0
        for comp in range(2):
            grid = tot_c[comp]
            for blk in range(4):
                gx, gy = mb_x + (blk & 1), mb_y + (blk >> 1)
                if chroma_cbp != 2:
                    grid[gy, gx] = 0
                    if decode:
                        cac[comp, blk] = 0
                    continue
                nA = grid[gy, gx - 1] if gx > 0 else -1
                nB = grid[gy - 1, gx] if gy > 0 else -1
                if nA >= 0 and nB >= 0:
                    nC = int(nA + nB + 1) >> 1
                elif nA >= 0:
                    nC = int(nA)
                elif nB >= 0:
                    nC = int(nB)
                else:
                    nC = 0
                if decode:
                    cac[comp, blk] = cavlc.decode_residual(bio, nC, 15)
                else:
                    cavlc.encode_residual(
                        bio, [int(v) for v in cac[comp, blk]], nC, 15)
                grid[gy, gx] = int(np.count_nonzero(cac[comp, blk]))


# ----------------------------------------------------------------- encoder

def _dc_pred(recon: np.ndarray, gx: int, gy: int, gy_min: int = 0) -> int:
    """4×4 DC prediction from reconstructed neighbors (mode 2).
    ``gy_min`` is the slice's first 4×4-block row: neighbors above it
    belong to another slice and are unavailable (6.4.9); slices split on
    MB-row boundaries, so left neighbors are always same-slice."""
    x0, y0 = gx * 4, gy * 4
    left = recon[y0:y0 + 4, x0 - 1] if x0 > 0 else None
    top = recon[y0 - 1, x0:x0 + 4] if gy > gy_min else None
    if left is not None and top is not None:
        return int((int(left.sum()) + int(top.sum()) + 4) >> 3)
    if left is not None:
        return int((int(left.sum()) + 2) >> 2)
    if top is not None:
        return int((int(top.sum()) + 2) >> 2)
    return 128


def _chroma_dc_pred_mb(recon: np.ndarray, mbx: int, mby: int,
                       mby_min: int = 0) -> np.ndarray:
    """[8,8] mode-0 (DC) chroma prediction for one MB per 8.3.4.1: each
    4×4 sub-block predicts from the MB-adjacent row above / column left
    at its own offsets, with the corner blocks averaging both and the
    off-diagonal blocks preferring top (x>0) or left (y>0).  ``mby_min``
    is the slice's first MB row (rows above are another slice)."""
    x0, y0 = mbx * 8, mby * 8
    pred = np.empty((8, 8), dtype=np.int64)
    for by in range(2):
        for bx in range(2):
            top = (recon[y0 - 1, x0 + bx * 4:x0 + bx * 4 + 4]
                   if mby > mby_min else None)
            left = (recon[y0 + by * 4:y0 + by * 4 + 4, x0 - 1]
                    if mbx > 0 else None)
            if (bx, by) == (1, 0):        # top-right block prefers top
                one = top if top is not None else left
                val = 128 if one is None else (int(one.sum()) + 2) >> 2
            elif (bx, by) == (0, 1):      # bottom-left prefers left
                one = left if left is not None else top
                val = 128 if one is None else (int(one.sum()) + 2) >> 2
            elif top is not None and left is not None:   # corners: both
                val = (int(top.sum()) + int(left.sum()) + 4) >> 3
            elif left is not None:
                val = (int(left.sum()) + 2) >> 2
            elif top is not None:
                val = (int(top.sum()) + 2) >> 2
            else:
                val = 128
            pred[by * 4:by * 4 + 4, bx * 4:bx * 4 + 4] = val
    return pred


def _encode_chroma_comp(plane: np.ndarray, recon: np.ndarray, mbx: int,
                        mby: int, qpc: int, mby_min: int = 0
                        ) -> tuple[np.ndarray, np.ndarray]:
    """Quantize one MB's chroma component: ([4] DC levels, [4,15] AC)."""
    pred = _chroma_dc_pred_mb(recon, mbx, mby, mby_min)
    x0, y0 = mbx * 8, mby * 8
    res = plane[y0:y0 + 8, x0:x0 + 8].astype(np.int64) - pred
    w00 = np.empty(4, dtype=np.int64)
    ac = np.zeros((4, 15), dtype=np.int64)
    for b in range(4):
        bx, by = b & 1, b >> 1
        blk = res[by * 4:by * 4 + 4, bx * 4:bx * 4 + 4]
        w00[b] = (_CF @ blk @ _CF.T)[0, 0]
        ac[b] = forward_transform_quant(blk, qpc)[ZIGZAG4[1:]]
    return chroma_dc_quant(w00, qpc), ac


def _recon_chroma_comp(recon: np.ndarray, mbx: int, mby: int,
                       dc: np.ndarray, ac: np.ndarray, qpc: int,
                       mby_min: int = 0) -> None:
    """Reconstruct one MB's chroma component exactly as a decoder does
    (8.5.11 DC chain + 8.5.12 AC dequant + inverse core transform)."""
    pred = _chroma_dc_pred_mb(recon, mbx, mby, mby_min)
    if not (np.any(dc) or np.any(ac)):   # no residual: pure prediction
        x0, y0 = mbx * 8, mby * 8
        recon[y0:y0 + 8, x0:x0 + 8] = pred
        return
    dcc = chroma_dc_dequant(dc, qpc)
    vq = v_position(qpc)
    x0, y0 = mbx * 8, mby * 8
    for b in range(4):
        bx, by = b & 1, b >> 1
        lev = np.zeros(16, dtype=np.int64)
        lev[ZIGZAG4[1:]] = ac[b]
        w = (lev * vq) << (qpc // 6)
        w[0] = dcc[b]
        res = inverse_core(w.reshape(4, 4))
        recon[y0 + by * 4:y0 + by * 4 + 4,
              x0 + bx * 4:x0 + bx * 4 + 4] = np.clip(
            pred[by * 4:by * 4 + 4, bx * 4:bx * 4 + 4] + res, 0, 255)


def encode_iframe(luma: np.ndarray, qp: int, *, frame_num: int = 0,
                  idr_pic_id: int = 0, cb: np.ndarray | None = None,
                  cr: np.ndarray | None = None,
                  sps: Sps | None = None, pps: Pps | None = None,
                  include_ps: bool = True, slices: int = 1,
                  entropy: str = "cavlc") -> list[bytes]:
    """uint8 [H, W] luma (H, W multiples of 16) → NAL payloads
    ([SPS, PPS,] IDR slice(s)), DC-predicted I_4x4 with a real
    reconstruction loop (prediction always from reconstructed samples,
    as a conformant decoder will see them).  Optional ``cb``/``cr``
    [H/2, W/2] planes get real 4:2:0 chroma residuals (mode-0 predicted,
    DC+AC coded); omitted planes keep chroma CBP 0.  ``slices`` splits
    the picture into that many MB-row-aligned slices (the low-latency
    encoder shape), each with slice-scoped prediction and nC contexts."""
    h, w = luma.shape
    if h % 16 or w % 16:
        raise ValueError("dimensions must be multiples of 16")
    sps = sps or Sps(w // 16, h // 16,
                     profile_idc=77 if entropy == "cabac" else 66)
    pps = pps or Pps(pic_init_qp=qp, entropy_cabac=(entropy == "cabac"))
    if not 1 <= slices <= sps.height_mbs:
        raise ValueError("slices must be in 1..height_mbs")
    codec = SliceCodec(sps, pps)
    recon = np.zeros((h, w), dtype=np.int64)
    do_chroma = cb is not None and cr is not None
    qpc = chroma_qp(qp, pps.chroma_qp_offset)
    recon_c = np.zeros((2, h // 2, w // 2), dtype=np.int64)
    zz = ZIGZAG4
    slice_rows = np.array_split(np.arange(sps.height_mbs), slices)
    out_nals: list[bytes] = []
    for rows in slice_rows:
        first_row = int(rows[0])
        first_mb = first_row * sps.width_mbs
        gy_min = first_row * 4           # slice boundary for prediction
        mbs: list[MacroblockI4x4] = []
        for mb_idx in range(first_mb,
                            (int(rows[-1]) + 1) * sps.width_mbs):
            mb_x = (mb_idx % sps.width_mbs) * 4
            mb_y = (mb_idx // sps.width_mbs) * 4
            levels = np.zeros((16, 16), dtype=np.int64)
            nz_blocks = np.zeros(16, dtype=bool)
            for blk in range(16):
                x4, y4 = BLK_XY[blk]
                gx, gy = mb_x + x4, mb_y + y4
                pred = _dc_pred(recon, gx, gy, gy_min)
                src = luma[gy * 4:gy * 4 + 4,
                           gx * 4:gx * 4 + 4].astype(np.int64)
                res = src - pred
                lv_raster = forward_transform_quant(res, qp)
                levels[blk] = lv_raster[zz]
                nz_blocks[blk] = bool(np.any(lv_raster))
                rec_res = dequant_inverse(lv_raster, qp)
                recon[gy * 4:gy * 4 + 4, gx * 4:gx * 4 + 4] = np.clip(
                    pred + rec_res, 0, 255)
            cbp = 0
            for g in range(4):
                if nz_blocks[4 * g:4 * g + 4].any():
                    cbp |= 1 << g
            # CBP-cleared blocks carry no residual: the decoder
            # reconstructs them as pure prediction, so mirror that here
            for blk in range(16):
                if not (cbp >> (blk >> 2)) & 1 and nz_blocks[blk]:
                    levels[blk] = 0
            mb = MacroblockI4x4([(1, 0)] * 16, 0, cbp, qp, levels)
            if do_chroma:
                mbx = mb_idx % sps.width_mbs
                mby = mb_idx // sps.width_mbs
                for comp, plane in enumerate((cb, cr)):
                    mb.chroma_dc[comp], mb.chroma_ac[comp] = \
                        _encode_chroma_comp(plane, recon_c[comp], mbx,
                                            mby, qpc, first_row)
                ccbp = (2 if np.any(mb.chroma_ac) else
                        1 if np.any(mb.chroma_dc) else 0)
                mb.cbp = cbp | (ccbp << 4)
                for comp in range(2):
                    _recon_chroma_comp(recon_c[comp], mbx, mby,
                                       mb.chroma_dc[comp],
                                       mb.chroma_ac[comp], qpc, first_row)
            mbs.append(mb)
        hdr = SliceHeader(frame_num=frame_num, idr_pic_id=idr_pic_id,
                          qp=qp, first_mb=first_mb)
        if pps.entropy_cabac:
            from .h264_cabac import CabacSliceCodec
            out_nals.append(CabacSliceCodec(sps, pps).write_slice(
                hdr, first_mb, mbs, qp))
        else:
            bw = BitWriter()
            codec.write_slice_header(bw, hdr, qp)
            codec.write_mbs(bw, mbs, qp, first_mb)
            bw.rbsp_trailing()
            out_nals.append(bytes([0x65]) + rbsp_to_nal(bw.to_bytes()))
    if include_ps:
        return [sps.build(), pps.build()] + out_nals
    return out_nals


# ----------------------------------------------------------------- decoder

def decode_iframe_yuv(nals: list[bytes]
                      ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """NAL payloads → uint8 (Y [H,W], Cb, Cr [H/2,W/2]) planes (DC-mode
    I_4x4 scope, full 4:2:0 chroma, MB-row-aligned multi-slice)."""
    sps = pps = None
    slice_nals = []
    for nal in nals:
        t = nal[0] & 0x1F
        if t == 7:
            sps = Sps.parse(nal)
        elif t == 8:
            pps = Pps.parse(nal)
        elif t in (1, 5):
            slice_nals.append(nal)
    if sps is None or pps is None or not slice_nals:
        raise ValueError("need SPS+PPS+slice")
    codec = SliceCodec(sps, pps)
    h, w = sps.height_mbs * 16, sps.width_mbs * 16
    recon = np.zeros((h, w), dtype=np.int64)
    recon_c = np.zeros((2, h // 2, w // 2), dtype=np.int64)
    inv_zz = np.argsort(ZIGZAG4)
    for slice_nal in slice_nals:
        if pps.entropy_cabac:
            from .h264_cabac import CabacSliceCodec
            hdr, _first, mbs, _qps = CabacSliceCodec(
                sps, pps).parse_slice(slice_nal)
        else:
            br = BitReader(nal_to_rbsp(slice_nal[1:]))
            hdr = codec.parse_slice_header(br, slice_nal[0])
            mbs = codec.parse_mbs(br, hdr.qp, hdr.first_mb)
        if hdr.first_mb % sps.width_mbs:
            raise ValueError("decoder scope is MB-row-aligned slices")
        first_row = hdr.first_mb // sps.width_mbs
        for mb_idx, mb in enumerate(mbs, start=hdr.first_mb):
            if isinstance(mb, MacroblockI16x16):
                raise ValueError("decoder scope is I_4x4 only")
            mb_x = (mb_idx % sps.width_mbs) * 4
            mb_y = (mb_idx // sps.width_mbs) * 4
            cur_qp = mb.qp
            for blk in range(16):
                flag, _rem = mb.pred_modes[blk]
                if not flag:
                    # an explicit rem mode can never be DC when every
                    # context mode is DC (rem skips the predicted mode)
                    raise ValueError("non-DC intra mode out of scope")
                x4, y4 = BLK_XY[blk]
                gx, gy = mb_x + x4, mb_y + y4
                pred = _dc_pred(recon, gx, gy, first_row * 4)
                lv = mb.levels[blk][inv_zz]
                res = dequant_inverse(lv, cur_qp)
                recon[gy * 4:gy * 4 + 4, gx * 4:gx * 4 + 4] = np.clip(
                    pred + res, 0, 255)
            if mb.chroma_mode != 0:
                raise ValueError("non-DC chroma mode out of scope")
            qpc = chroma_qp(cur_qp, pps.chroma_qp_offset)
            for comp in range(2):
                _recon_chroma_comp(recon_c[comp], mb_idx % sps.width_mbs,
                                   mb_idx // sps.width_mbs,
                                   mb.chroma_dc[comp], mb.chroma_ac[comp],
                                   qpc, first_row)
    return (recon.astype(np.uint8), recon_c[0].astype(np.uint8),
            recon_c[1].astype(np.uint8))


def decode_iframe(nals: list[bytes]) -> np.ndarray:
    """NAL payloads → uint8 [H, W] luma (DC-mode I_4x4 scope)."""
    return decode_iframe_yuv(nals)[0]


def psnr(a: np.ndarray, b: np.ndarray) -> float:
    mse = float(np.mean((a.astype(np.float64) - b.astype(np.float64)) ** 2))
    if mse == 0:
        return 99.0
    return 10.0 * np.log10(255.0 * 255.0 / mse)
