"""H.264 4×4 integer transform + QP quantization (host reference).

The spec's core transform (8.5.12) and the JM-convention forward
quantizer: all integer, so the device port (``ops.transform.
h264_requant``) can be BIT-EXACT against ``requant_levels_scalar`` — the
differential the HLS requant rung is tested on.

Position classes for the 4×4 MF/V multipliers:
  A = {(0,0),(0,2),(2,0),(2,2)}, B = {(1,1),(1,3),(3,1),(3,3)}, C = rest.
"""

from __future__ import annotations

import numpy as np

#: forward quant multipliers MF[qp % 6][class] (class order A, B, C)
MF = np.array([
    [13107, 5243, 8066],
    [11916, 4660, 7490],
    [10082, 4194, 6554],
    [9362, 3647, 5825],
    [8192, 3355, 5243],
    [7282, 2893, 4559]], dtype=np.int64)

#: dequant multipliers V[qp % 6][class]
V = np.array([
    [10, 16, 13],
    [11, 18, 14],
    [13, 20, 16],
    [14, 23, 18],
    [16, 25, 20],
    [18, 29, 23]], dtype=np.int64)

#: position → class index (row-major 4×4)
_CLS = np.array([
    0, 2, 0, 2,
    2, 1, 2, 1,
    0, 2, 0, 2,
    2, 1, 2, 1], dtype=np.int64)

#: 4×4 zigzag scan (raster index per scan position)
ZIGZAG4 = np.array([0, 1, 4, 8, 5, 2, 3, 6, 9, 12, 13, 10, 7, 11, 14, 15],
                   dtype=np.int64)

_CF = np.array([[1, 1, 1, 1],
                [2, 1, -1, -2],
                [1, -1, -1, 1],
                [1, -2, 2, -1]], dtype=np.int64)

#: max |level| the requant paths accept — keeps the int32 device math
#: overflow-free (|l|·V·MF ≤ 2047·29·13107 < 2^31)
LEVEL_CLIP = 2047

#: Table 8-15: QPc as a function of qPI (identity below 30, then the
#: compressing tail).  This non-linearity is WHY chroma needs a general
#: requant: a luma +6k step maps to a chroma delta that is usually not
#: a multiple of 6, so the exact-shift argument does not apply.
CHROMA_QP = np.array(
    list(range(30)) + [29, 30, 31, 32, 32, 33, 34, 34, 35, 35, 36, 36,
                       37, 37, 37, 38, 38, 38, 39, 39, 39, 39],
    dtype=np.int64)

#: clips shared with the device / native chroma paths so int64 (numpy),
#: int32 (XLA) and int32 (C++) stay bit-exact: residuals after the
#: inverse transform clip to ±RES_CLIP (⇒ |W| ≤ 36·4095), forward
#: coefficients to ±W_CLIP (131071·13107 + 2·2^23 < 2^31).  Real
#: residuals are within ±255, so the clips never bind on real streams.
RES_CLIP = 4095
W_CLIP = 131071

_H2 = np.array([[1, 1], [1, -1]], dtype=np.int64)


def chroma_qp(qp_y: int, offset: int = 0) -> int:
    """QPc for a macroblock: Table 8-15 over clip3(0, 51, QPY + offset)."""
    return int(CHROMA_QP[int(np.clip(qp_y + offset, 0, 51))])


def mf_position(qp: int) -> np.ndarray:
    """[16] per-position forward multiplier for ``qp``."""
    return MF[qp % 6][_CLS]


def v_position(qp: int) -> np.ndarray:
    """[16] per-position dequant multiplier for ``qp``."""
    return V[qp % 6][_CLS]


def forward_transform_quant(residual: np.ndarray, qp: int) -> np.ndarray:
    """[4,4] int residual → [16] quantized levels (raster order).

    W = Cf·X·Cfᵀ; level = sign(W)·((|W|·MF + f) >> (15 + qp//6)) with the
    intra rounding offset f = 2^(15+qp//6)/3 (JM convention)."""
    x = residual.astype(np.int64)
    w = _CF @ x @ _CF.T
    qbits = 15 + qp // 6
    f = (1 << qbits) // 3
    mf = mf_position(qp).reshape(4, 4)
    lev = np.sign(w) * ((np.abs(w) * mf + f) >> qbits)
    return np.clip(lev.reshape(16), -LEVEL_CLIP, LEVEL_CLIP)


def inverse_core(w: np.ndarray) -> np.ndarray:
    """[4,4] dequantized coefficients → [4,4] residual (8.5.12's inverse
    core transform with the final +32 >> 6)."""
    def ih(row):
        a, b, c, d = row
        e0 = a + c
        e1 = a - c
        e2 = (b >> 1) - d
        e3 = b + (d >> 1)
        return np.array([e0 + e3, e1 + e2, e1 - e2, e0 - e3], dtype=np.int64)

    tmp = np.stack([ih(w[i]) for i in range(4)])
    cols = np.stack([ih(tmp[:, j]) for j in range(4)], axis=1)
    return ((cols + 32) >> 6).astype(np.int64)


def dequant_inverse(levels: np.ndarray, qp: int) -> np.ndarray:
    """[16] levels (raster) → [4,4] int residual (spec 8.5.12 rounding)."""
    lev = levels.astype(np.int64).reshape(4, 4)
    w = lev * v_position(qp).reshape(4, 4)
    w = w << (qp // 6)
    return inverse_core(w)


def requant_levels_scalar(levels: np.ndarray, qp_in: int, qp_out: int
                          ) -> np.ndarray:
    """Transform-domain requant, THE scalar oracle: [..., 16] levels at
    ``qp_in`` → levels at ``qp_out = qp_in + 6k``.

    Qstep doubles every 6 QP with identical ``qp % 6`` multiplier rows,
    so a +6k requant is EXACTLY a rounded k-bit right shift of each
    level — no transform-normalization terms enter at all (MF and V bake
    in different forward/inverse scalings, so a V·MF product form is
    wrong; this form is exact by the table periodicity).  The intra
    deadzone bias 2^k/3 mirrors the forward quantizer's f offset:
      l' = sign(l)·((|l| + 2^k/3) >> k).
    """
    k = (qp_out - qp_in) // 6
    if qp_out - qp_in != 6 * k or k <= 0:
        raise ValueError("requant ladder steps must be +6 QP multiples")
    lev = np.clip(np.asarray(levels, dtype=np.int64),
                  -LEVEL_CLIP, LEVEL_CLIP)
    f = (1 << k) // 3
    out = np.sign(lev) * ((np.abs(lev) + f) >> k)
    return out.astype(np.int64)


# ------------------------------------------------------------------- chroma

def chroma_dc_dequant(dc_levels: np.ndarray, qpc: int) -> np.ndarray:
    """[4] parsed 2×2 chroma DC levels (raster) → [4] dcC per 8.5.11:
    dcC = ((H2·c·H2) · LevelScale(QPc%6,0,0)) << (QPc/6) >> 5 — the spec's
    LevelScale carries a ×16, so in this module's V convention the net
    shift is >> 1 (exact for every QPc, both forms being 2-adic)."""
    c = np.clip(dc_levels.astype(np.int64), -LEVEL_CLIP,
                LEVEL_CLIP).reshape(2, 2)
    f = _H2 @ c @ _H2
    return (((f * V[qpc % 6][0]) << (qpc // 6)) >> 1).reshape(4)


def chroma_dc_quant(w00: np.ndarray, qpc: int) -> np.ndarray:
    """[4] forward-transform DC coefficients (raster 2×2 of the MB
    component's blocks) → [4] quantized chroma DC levels (JM forward:
    2×2 Hadamard, then MF with doubled deadzone and qbits+1 shift)."""
    f2 = _H2 @ np.clip(w00.astype(np.int64), -W_CLIP,
                       W_CLIP).reshape(2, 2) @ _H2
    f2 = np.clip(f2, -W_CLIP, W_CLIP)
    qbits = 15 + qpc // 6
    off = (1 << qbits) // 3
    lev = np.sign(f2) * ((np.abs(f2) * MF[qpc % 6][0] + 2 * off)
                         >> (qbits + 1))
    return np.clip(lev, -LEVEL_CLIP, LEVEL_CLIP).reshape(4)


def requant_chroma_scalar(dc: np.ndarray, ac: np.ndarray, qpc_in: int,
                          qpc_out: int) -> tuple[np.ndarray, np.ndarray]:
    """Chroma requant for ONE macroblock component, the scalar oracle for
    ``ops.transform.h264_requant_chroma`` (bit-exact, same clips).

    dc: [4] chroma DC levels (2×2 raster); ac: [4, 15] per-block zigzag
    AC tails.  Three-way per-MB dispatch on delta = qpc_out − qpc_in:

    * 0 — identity (Table 8-15 saturation; the levels still decode right
      because QPc is unchanged).
    * +6k — the same exact level shift as luma (the DC chain also scales
      by exactly 2 per +6: same %6 row, one more left shift).
    * otherwise — open-loop integer round trip, each block reconstructed
      exactly as a decoder would (8.5.11 DC + 8.5.12 AC dequant, inverse
      core transform) and re-encoded with the JM forward quantizer at
      qpc_out.  Valid for ANY delta, which chroma needs (module note on
      CHROMA_QP)."""
    dc = np.clip(np.asarray(dc, dtype=np.int64), -LEVEL_CLIP, LEVEL_CLIP)
    ac = np.clip(np.asarray(ac, dtype=np.int64), -LEVEL_CLIP, LEVEL_CLIP)
    delta = qpc_out - qpc_in
    if delta < 0:
        raise ValueError("chroma requant only steps down (qpc_out >= in)")
    if delta == 0:
        return dc.copy(), ac.copy()
    if delta % 6 == 0:
        k = delta // 6
        f = (1 << k) // 3
        sh = lambda x: np.sign(x) * ((np.abs(x) + f) >> k)  # noqa: E731
        return sh(dc), sh(ac)
    dcc = chroma_dc_dequant(dc, qpc_in)
    vq = v_position(qpc_in)
    mfq = mf_position(qpc_out)
    qbits = 15 + qpc_out // 6
    off = (1 << qbits) // 3
    out_ac = np.empty_like(ac)
    w00 = np.empty(4, dtype=np.int64)
    for b in range(4):
        lev = np.zeros(16, dtype=np.int64)
        lev[ZIGZAG4[1:]] = ac[b]
        w = (lev * vq) << (qpc_in // 6)
        w[0] = dcc[b]
        x = np.clip(inverse_core(w.reshape(4, 4)), -RES_CLIP, RES_CLIP)
        big_w = np.clip(_CF @ x @ _CF.T, -W_CLIP, W_CLIP).reshape(16)
        w00[b] = big_w[0]
        q = np.sign(big_w) * ((np.abs(big_w) * mfq + off) >> qbits)
        out_ac[b] = np.clip(q, -LEVEL_CLIP, LEVEL_CLIP)[ZIGZAG4[1:]]
    return chroma_dc_quant(w00, qpc_out), out_ac
