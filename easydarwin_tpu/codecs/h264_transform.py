"""H.264 4×4 integer transform + QP quantization (host reference).

The spec's core transform (8.5.12) and the JM-convention forward
quantizer: all integer, so the device port (``ops.transform.
h264_requant``) can be BIT-EXACT against ``requant_levels_scalar`` — the
differential the HLS requant rung is tested on.

Position classes for the 4×4 MF/V multipliers:
  A = {(0,0),(0,2),(2,0),(2,2)}, B = {(1,1),(1,3),(3,1),(3,3)}, C = rest.
"""

from __future__ import annotations

import numpy as np

#: forward quant multipliers MF[qp % 6][class] (class order A, B, C)
MF = np.array([
    [13107, 5243, 8066],
    [11916, 4660, 7490],
    [10082, 4194, 6554],
    [9362, 3647, 5825],
    [8192, 3355, 5243],
    [7282, 2893, 4559]], dtype=np.int64)

#: dequant multipliers V[qp % 6][class]
V = np.array([
    [10, 16, 13],
    [11, 18, 14],
    [13, 20, 16],
    [14, 23, 18],
    [16, 25, 20],
    [18, 29, 23]], dtype=np.int64)

#: position → class index (row-major 4×4)
_CLS = np.array([
    0, 2, 0, 2,
    2, 1, 2, 1,
    0, 2, 0, 2,
    2, 1, 2, 1], dtype=np.int64)

#: 4×4 zigzag scan (raster index per scan position)
ZIGZAG4 = np.array([0, 1, 4, 8, 5, 2, 3, 6, 9, 12, 13, 10, 7, 11, 14, 15],
                   dtype=np.int64)

_CF = np.array([[1, 1, 1, 1],
                [2, 1, -1, -2],
                [1, -1, -1, 1],
                [1, -2, 2, -1]], dtype=np.int64)

#: max |level| the requant paths accept — keeps the int32 device math
#: overflow-free (|l|·V·MF ≤ 2047·29·13107 < 2^31)
LEVEL_CLIP = 2047


def mf_position(qp: int) -> np.ndarray:
    """[16] per-position forward multiplier for ``qp``."""
    return MF[qp % 6][_CLS]


def v_position(qp: int) -> np.ndarray:
    """[16] per-position dequant multiplier for ``qp``."""
    return V[qp % 6][_CLS]


def forward_transform_quant(residual: np.ndarray, qp: int) -> np.ndarray:
    """[4,4] int residual → [16] quantized levels (raster order).

    W = Cf·X·Cfᵀ; level = sign(W)·((|W|·MF + f) >> (15 + qp//6)) with the
    intra rounding offset f = 2^(15+qp//6)/3 (JM convention)."""
    x = residual.astype(np.int64)
    w = _CF @ x @ _CF.T
    qbits = 15 + qp // 6
    f = (1 << qbits) // 3
    mf = mf_position(qp).reshape(4, 4)
    lev = np.sign(w) * ((np.abs(w) * mf + f) >> qbits)
    return np.clip(lev.reshape(16), -LEVEL_CLIP, LEVEL_CLIP)


def dequant_inverse(levels: np.ndarray, qp: int) -> np.ndarray:
    """[16] levels (raster) → [4,4] int residual (spec 8.5.12 rounding)."""
    lev = levels.astype(np.int64).reshape(4, 4)
    w = lev * v_position(qp).reshape(4, 4)
    w = w << (qp // 6)
    # inverse core transform with >>6 rounding at the end
    def ih(row):
        a, b, c, d = row
        e0 = a + c
        e1 = a - c
        e2 = (b >> 1) - d
        e3 = b + (d >> 1)
        return np.array([e0 + e3, e1 + e2, e1 - e2, e0 - e3], dtype=np.int64)

    tmp = np.stack([ih(w[i]) for i in range(4)])
    cols = np.stack([ih(tmp[:, j]) for j in range(4)], axis=1)
    return ((cols + 32) >> 6).astype(np.int64)


def requant_levels_scalar(levels: np.ndarray, qp_in: int, qp_out: int
                          ) -> np.ndarray:
    """Transform-domain requant, THE scalar oracle: [..., 16] levels at
    ``qp_in`` → levels at ``qp_out = qp_in + 6k``.

    Qstep doubles every 6 QP with identical ``qp % 6`` multiplier rows,
    so a +6k requant is EXACTLY a rounded k-bit right shift of each
    level — no transform-normalization terms enter at all (MF and V bake
    in different forward/inverse scalings, so a V·MF product form is
    wrong; this form is exact by the table periodicity).  The intra
    deadzone bias 2^k/3 mirrors the forward quantizer's f offset:
      l' = sign(l)·((|l| + 2^k/3) >> k).
    """
    k = (qp_out - qp_in) // 6
    if qp_out - qp_in != 6 * k or k <= 0:
        raise ValueError("requant ladder steps must be +6 QP multiples")
    lev = np.clip(np.asarray(levels, dtype=np.int64),
                  -LEVEL_CLIP, LEVEL_CLIP)
    f = (1 << k) // 3
    out = np.sign(lev) * ((np.abs(lev) + f) >> k)
    return out.astype(np.int64)
