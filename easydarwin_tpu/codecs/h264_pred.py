"""Full H.264 intra prediction (spec 8.3): all nine 4x4 luma modes,
the four 16x16 luma modes, and the four 8x8 chroma modes, over
reconstructed sample planes.

This is the piece that turns the transform-domain requant rung into a
CLOSED-LOOP transcoder for intra slices: prediction runs from the
OUTPUT-side reconstruction, so requantization error stops compounding
across prediction chains (VERDICT r4 item 3 measured −12.9 dB of
open-loop drift at +6).  The same functions drive the full-mode intra
DECODER used to obtain the target pixels — verified pixel-exact against
libavcodec on x264 streams in tests/test_closed_loop.py.

Availability follows 6.4.9 with slice-scoped neighbors; the decode-order
rule for top-right samples uses the macroblock raster × 8.3.1
luma4x4BlkIdx order.  Scope: frame MBs, MB-row-aligned slices.
"""

from __future__ import annotations

import numpy as np

from .h264_intra import BLK_XY

#: (x4, y4) inside the MB → luma4x4BlkIdx (inverse of BLK_XY)
_BLK_ORDER = {xy: i for i, xy in enumerate(BLK_XY)}


def block_decode_order(gx: int, gy: int, w4: int) -> int:
    """Global decode-order index of the 4x4 block at (gx, gy)."""
    mb = (gy // 4) * (w4 // 4) + gx // 4
    return mb * 16 + _BLK_ORDER[(gx % 4, gy % 4)]


def _topright4(recon: np.ndarray, gx: int, gy: int, gy_min: int,
               w4: int) -> np.ndarray:
    """p[4..7, -1] for the 4x4 block at (gx, gy): real samples when the
    top-right block is available AND earlier in decode order, else the
    8.3.1.2 substitution p[3, -1] repeated."""
    top = recon[gy * 4 - 1, gx * 4:gx * 4 + 4]
    if (gx + 1 < w4 and gy > gy_min
            and block_decode_order(gx + 1, gy - 1, w4)
            < block_decode_order(gx, gy, w4)):
        return recon[gy * 4 - 1, gx * 4 + 4:gx * 4 + 8]
    return np.full(4, top[3], dtype=recon.dtype)


def pred4x4(mode: int, recon: np.ndarray, gx: int, gy: int,
            gy_min: int) -> np.ndarray:
    """[4,4] prediction for one luma 4x4 block (modes 0-8, 8.3.1.2).
    ``gy_min`` = the slice's first 4x4 row (above it: unavailable)."""
    w4 = recon.shape[1] // 4
    x0, y0 = gx * 4, gy * 4
    avail_l = gx > 0
    avail_t = gy > gy_min
    left = recon[y0:y0 + 4, x0 - 1].astype(np.int64) if avail_l else None
    top = recon[y0 - 1, x0:x0 + 4].astype(np.int64) if avail_t else None
    if mode == 2:                        # DC
        if avail_l and avail_t:
            v = (int(left.sum()) + int(top.sum()) + 4) >> 3
        elif avail_l:
            v = (int(left.sum()) + 2) >> 2
        elif avail_t:
            v = (int(top.sum()) + 2) >> 2
        else:
            v = 128
        return np.full((4, 4), v, dtype=np.int64)
    if mode == 0:                        # Vertical
        if not avail_t:
            raise ValueError("V prediction without top")
        return np.tile(top, (4, 1))
    if mode == 1:                        # Horizontal
        if not avail_l:
            raise ValueError("H prediction without left")
        return np.tile(left.reshape(4, 1), (1, 4))
    if mode == 3:                        # Diagonal-Down-Left
        if not avail_t:
            raise ValueError("DDL without top")
        tr = _topright4(recon, gx, gy, gy_min, w4).astype(np.int64)
        p = np.concatenate([top, tr])    # p[0..7, -1]
        out = np.empty((4, 4), dtype=np.int64)
        for y in range(4):
            for x in range(4):
                if x == 3 and y == 3:
                    out[y, x] = (p[6] + 3 * p[7] + 2) >> 2
                else:
                    i = x + y
                    out[y, x] = (p[i] + 2 * p[i + 1] + p[i + 2] + 2) >> 2
        return out
    # modes 4-8 need the corner sample p[-1,-1]
    if mode in (4, 5, 6) and not (avail_l and avail_t):
        raise ValueError("diagonal prediction without both neighbors")
    corner = int(recon[y0 - 1, x0 - 1]) if (avail_l and avail_t) else 0
    if mode == 4:                        # Diagonal-Down-Right
        out = np.empty((4, 4), dtype=np.int64)
        for y in range(4):
            for x in range(4):
                if x > y:
                    i = x - y
                    a = top[i - 2] if i >= 2 else corner
                    b = top[i - 1] if i >= 1 else corner
                    c = top[i]
                    out[y, x] = (a + 2 * b + c + 2) >> 2
                elif x < y:
                    i = y - x
                    a = left[i - 2] if i >= 2 else corner
                    b = left[i - 1] if i >= 1 else corner
                    c = left[i]
                    out[y, x] = (a + 2 * b + c + 2) >> 2
                else:
                    out[y, x] = (top[0] + 2 * corner + left[0] + 2) >> 2
        return out
    if mode == 5:                        # Vertical-Right (8.3.1.2.5)
        out = np.empty((4, 4), dtype=np.int64)
        for y in range(4):
            for x in range(4):
                z = 2 * x - y
                i = x - (y >> 1)
                if z >= 0 and z % 2 == 0:
                    out[y, x] = ((top[i - 1] if i >= 1 else corner)
                                 + top[i] + 1) >> 1
                elif z >= 0:
                    out[y, x] = ((top[i - 2] if i >= 2 else corner)
                                 + 2 * (top[i - 1] if i >= 1 else corner)
                                 + top[i] + 2) >> 2
                elif z == -1:
                    out[y, x] = (left[0] + 2 * corner + top[0] + 2) >> 2
                else:                    # zVR ≤ −2: left column upward
                    j = y - 2 * x - 1
                    out[y, x] = (left[j]
                                 + 2 * (left[j - 1] if j >= 1 else corner)
                                 + (left[j - 2] if j >= 2 else corner)
                                 + 2) >> 2
        return out
    if mode == 6:                        # Horizontal-Down
        out = np.empty((4, 4), dtype=np.int64)
        for y in range(4):
            for x in range(4):
                z = 2 * y - x
                if z >= 0 and z % 2 == 0:
                    i = y - (x >> 1)
                    out[y, x] = ((left[i - 1] if i >= 1 else corner)
                                 + left[i] + 1) >> 1
                elif z >= 0:
                    i = y - (x >> 1)
                    out[y, x] = ((left[i - 2] if i >= 2 else corner)
                                 + 2 * (left[i - 1] if i >= 1 else corner)
                                 + left[i] + 2) >> 2
                elif z == -1:
                    out[y, x] = (top[0] + 2 * corner + left[0] + 2) >> 2
                else:                    # zHD ≤ −2: top row leftward
                    j = x - 2 * y - 1
                    out[y, x] = (top[j]
                                 + 2 * (top[j - 1] if j >= 1 else corner)
                                 + (top[j - 2] if j >= 2 else corner)
                                 + 2) >> 2
        return out
    if mode == 7:                        # Vertical-Left
        if not avail_t:
            raise ValueError("VL without top")
        tr = _topright4(recon, gx, gy, gy_min, w4).astype(np.int64)
        p = np.concatenate([top, tr])
        out = np.empty((4, 4), dtype=np.int64)
        for y in range(4):
            for x in range(4):
                i = x + (y >> 1)
                if y % 2 == 0:
                    out[y, x] = (p[i] + p[i + 1] + 1) >> 1
                else:
                    out[y, x] = (p[i] + 2 * p[i + 1] + p[i + 2] + 2) >> 2
        return out
    if mode == 8:                        # Horizontal-Up
        if not avail_l:
            raise ValueError("HU without left")
        out = np.empty((4, 4), dtype=np.int64)
        for y in range(4):
            for x in range(4):
                z = x + 2 * y
                if z < 5 and z % 2 == 0:
                    i = y + (x >> 1)
                    out[y, x] = (left[i] + left[i + 1] + 1) >> 1
                elif z < 5:
                    i = y + (x >> 1)
                    out[y, x] = (left[i] + 2 * left[i + 1]
                                 + left[i + 2] + 2) >> 2
                elif z == 5:
                    out[y, x] = (left[2] + 3 * left[3] + 2) >> 2
                else:
                    out[y, x] = left[3]
        return out
    raise ValueError(f"intra4x4 mode {mode} out of range")


def pred16x16(mode: int, recon: np.ndarray, mbx: int, mby: int,
              mby_min: int) -> np.ndarray:
    """[16,16] I_16x16 prediction (8.3.3): 0 V, 1 H, 2 DC, 3 Plane."""
    x0, y0 = mbx * 16, mby * 16
    avail_l = mbx > 0
    avail_t = mby > mby_min
    left = (recon[y0:y0 + 16, x0 - 1].astype(np.int64)
            if avail_l else None)
    top = (recon[y0 - 1, x0:x0 + 16].astype(np.int64)
           if avail_t else None)
    if mode == 0:
        if not avail_t:
            raise ValueError("I16 V without top")
        return np.tile(top, (16, 1))
    if mode == 1:
        if not avail_l:
            raise ValueError("I16 H without left")
        return np.tile(left.reshape(16, 1), (1, 16))
    if mode == 2:
        if avail_l and avail_t:
            v = (int(left.sum()) + int(top.sum()) + 16) >> 5
        elif avail_l:
            v = (int(left.sum()) + 8) >> 4
        elif avail_t:
            v = (int(top.sum()) + 8) >> 4
        else:
            v = 128
        return np.full((16, 16), v, dtype=np.int64)
    if mode == 3:                        # Plane (8.3.3.4)
        if not (avail_l and avail_t):
            raise ValueError("I16 plane without both neighbors")
        corner = int(recon[y0 - 1, x0 - 1])
        hsrc = np.concatenate([[corner], top]).astype(np.int64)
        vsrc = np.concatenate([[corner], left]).astype(np.int64)
        hsum = sum((x + 1) * (int(hsrc[9 + x]) - int(hsrc[7 - x]))
                   for x in range(8))
        vsum = sum((y + 1) * (int(vsrc[9 + y]) - int(vsrc[7 - y]))
                   for y in range(8))
        b = (5 * hsum + 32) >> 6
        c = (5 * vsum + 32) >> 6
        a = 16 * (int(left[15]) + int(top[15]))
        yy, xx = np.mgrid[0:16, 0:16]
        return np.clip((a + b * (xx - 7) + c * (yy - 7) + 16) >> 5,
                       0, 255).astype(np.int64)
    raise ValueError(f"intra16x16 mode {mode} out of range")


def pred_chroma(mode: int, recon: np.ndarray, mbx: int, mby: int,
                mby_min: int) -> np.ndarray:
    """[8,8] chroma prediction (8.3.4): 0 DC, 1 H, 2 V, 3 Plane."""
    x0, y0 = mbx * 8, mby * 8
    avail_l = mbx > 0
    avail_t = mby > mby_min
    if mode == 0:                        # DC, per 4x4 sub-block rules
        from .h264_intra import _chroma_dc_pred_mb
        return _chroma_dc_pred_mb(recon, mbx, mby, mby_min)
    left = recon[y0:y0 + 8, x0 - 1].astype(np.int64) if avail_l else None
    top = recon[y0 - 1, x0:x0 + 8].astype(np.int64) if avail_t else None
    if mode == 1:
        if not avail_l:
            raise ValueError("chroma H without left")
        return np.tile(left.reshape(8, 1), (1, 8))
    if mode == 2:
        if not avail_t:
            raise ValueError("chroma V without top")
        return np.tile(top, (8, 1))
    if mode == 3:                        # Plane (8.3.4.4)
        if not (avail_l and avail_t):
            raise ValueError("chroma plane without both neighbors")
        corner = int(recon[y0 - 1, x0 - 1])
        hsrc = np.concatenate([[corner], top]).astype(np.int64)
        vsrc = np.concatenate([[corner], left]).astype(np.int64)
        hsum = sum((x + 1) * (int(hsrc[5 + x]) - int(hsrc[3 - x]))
                   for x in range(4))
        vsum = sum((y + 1) * (int(vsrc[5 + y]) - int(vsrc[3 - y]))
                   for y in range(4))
        b = (17 * hsum + 16) >> 5
        c = (17 * vsum + 16) >> 5
        a = 16 * (int(left[7]) + int(top[7]))
        yy, xx = np.mgrid[0:8, 0:8]
        return np.clip((a + b * (xx - 3) + c * (yy - 3) + 16) >> 5,
                       0, 255).astype(np.int64)
    raise ValueError(f"chroma mode {mode} out of range")


def derive_i4x4_modes(mb_modes, blk_modes: np.ndarray, mb_idx: int,
                      w_mbs: int, first_mb: int) -> list[int]:
    """Resolve one I_4x4 MB's coded (prev_flag, rem) pairs into actual
    modes (8.3.1.1 most-probable-mode), updating ``blk_modes`` — the
    per-4x4 global mode grid (−1 = unavailable/not-intra-4x4; I_16x16
    and inter MBs read as DC=2 via the availability rule)."""
    mbx, mby = (mb_idx % w_mbs) * 4, (mb_idx // w_mbs) * 4
    first_row4 = (first_mb // w_mbs) * 4
    out = []
    for b in range(16):
        x4, y4 = BLK_XY[b]
        gx, gy = mbx + x4, mby + y4
        ma = blk_modes[gy, gx - 1] if gx > 0 else -1
        mb_ = blk_modes[gy - 1, gx] if gy > first_row4 else -1
        if ma < 0 or mb_ < 0:
            pred = 2                     # dcPredModePredictedFlag
        else:
            pred = min(int(ma), int(mb_))
        flag, rem = mb_modes[b]
        mode = pred if flag else (rem if rem < pred else rem + 1)
        blk_modes[gy, gx] = mode
        out.append(mode)
    return out
