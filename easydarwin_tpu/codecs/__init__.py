"""Host-side codec tier: bitstream entropy work the device cannot do.

The MJPEG ladder split (host entropy ⇄ device transform math,
``models/mjpeg_ladder.py``) applied to H.264: CAVLC baseline intra
parse/re-encode on the host, integer requantization batched on the
device (``ops.transform.h264_requant``).  CABAC and inter prediction are
out of scope (SURVEY §7 step 8 scope note)."""

from .h264_bits import BitReader, BitWriter, rbsp_to_nal, nal_to_rbsp
from .h264_transform import (forward_transform_quant, dequant_inverse,
                             requant_levels_scalar)

__all__ = ["BitReader", "BitWriter", "rbsp_to_nal", "nal_to_rbsp",
           "forward_transform_quant", "dequant_inverse",
           "requant_levels_scalar"]
