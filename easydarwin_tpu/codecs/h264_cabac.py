"""CABAC entropy layer for the H.264 requant rung (I and P slices,
4:2:0).

Real 1080p camera streams are overwhelmingly CABAC (Main/High profile)
and IPPP; without this layer the bitrate ladder is inert on them
(VERDICT r3 item 3, r4 item 1).  This module implements the spec's
arithmetic coding engine (9.3.3.2 decode, 9.3.4 encode) and the I/P
slice syntax layer — mb_type / skip flags / pred modes / ref_idx / mvd
(UEG3) / CBP / mb_qp_delta / residual_block_cabac for ctxBlockCat
0-4 — over the SAME macroblock model as the CAVLC path
(``h264_intra.MacroblockI4x4 / I16x16 / Inter / PSkip``), so the +6k
requant shift and the CBP/QP-chain recompute are shared byte for byte.

Scope (mirrors the CAVLC rung; outside → caller passes through): frame
I and P slices, 4:2:0 8-bit, flat scaling, no I_PCM, no MBAFF, no B
slices, no weighted prediction.  High-profile 8x8 transform is decoded
(cat-5 residuals, ctx 399 flags); dense streams round-trip byte-exact
vs x264, but a sparse-content margin case is still open, so the
requant gate refuses any 8x8 slice whose parse ends before the picture
(pass-through, never truncation — see tests/test_h264_high.py).
Constants in ``h264_cabac_tables`` are the spec's Tables 9-44/9-45 and
the (m,n) init columns — intra plus the three cabac_init_idc inter
tables — provenance in ``tools/gen_cabac_tables.py``.

Correctness levers: encode⇄decode round-trips in-tree; slices encoded
here decode bit-for-bit through the system libavcodec
(``tests/test_h264_cabac.py``); and REAL x264 bitstreams — whose
syntax shapes our own encoder never produces — round-trip and requant
through the libavcodec err_detect=explode oracle
(``tests/test_h264_inter.py``; that path caught a chroma-pred context
bug in-tree round-trips could never see).  Reference spot:
``/root/reference`` has no codec layer at all; nearest anchor is the
NALU classification in ``QTSSReflectorModule/ReflectorStream.cpp``.
"""

from __future__ import annotations

import numpy as np

from .h264_bits import BitReader, BitWriter, nal_to_rbsp, rbsp_to_nal
from .h264_cabac_tables import (CTX_INIT_I, CTX_INIT_P0, CTX_INIT_P1,
                                CTX_INIT_P2, LAST_MAP_8X8, RANGE_LPS,
                                SIG_MAP_8X8, TRANS_IDX_LPS,
                                TRANS_IDX_MPS)
from .h264_intra import (BLK_XY, MacroblockI16x16, MacroblockI4x4,
                         MacroblockInter, MacroblockPSkip, Pps,
                         SliceCodec, SliceHeader, Sps)

#: init table per slice type: I column, or inter column by cabac_init_idc
CTX_INIT_P = (CTX_INIT_P0, CTX_INIT_P1, CTX_INIT_P2)

#: P partition geometry in 8x8 units: mb_type → (x8, y8, w8, h8) rows
_P_PARTS8 = {0: ((0, 0, 2, 2),),
             1: ((0, 0, 2, 1), (0, 1, 2, 1)),
             2: ((0, 0, 1, 2), (1, 0, 1, 2))}
#: P sub-partition geometry in 4x4 units RELATIVE to the 8x8:
#: sub_mb_type → (x4, y4, w4, h4) rows
_P_SUB4 = {0: ((0, 0, 2, 2),),
           1: ((0, 0, 2, 1), (0, 1, 2, 1)),
           2: ((0, 0, 1, 2), (1, 0, 1, 2)),
           3: ((0, 0, 1, 1), (1, 0, 1, 1), (0, 1, 1, 1), (1, 1, 1, 1))}

# ctxIdx bases (frame coding; verified against the system libavcodec's
# compiled offset tables — see tools/gen_cabac_tables.py)
_CBF_BASE = 85           # + 4*ctxBlockCat + inc
_SIG_BASE = (105, 120, 134, 149, 152)      # significant_coeff_flag
_LAST_BASE = (166, 181, 195, 210, 213)     # last_significant_coeff_flag
_ABS_BASE = (227, 237, 247, 257, 266)      # coeff_abs_level_minus1
_TERMINATE = 276                           # end_of_slice / I_PCM bin
_T8_BASE = 399                             # transform_size_8x8_flag
_SIG8 = 402                                # cat 5 (8x8 luma) residual
_LAST8 = 417
_ABS8 = 426


def _init_states(slice_qp: int, table=CTX_INIT_I) -> np.ndarray:
    """pStateIdx/valMPS per ctxIdx from the (m, n) pairs (9.3.1.1);
    ``table`` is the intra column or CTX_INIT_P[cabac_init_idc]."""
    qp = min(max(slice_qp, 0), 51)
    mn = np.asarray(table, dtype=np.int64).reshape(1024, 2)
    pre = np.clip(((mn[:, 0] * qp) >> 4) + mn[:, 1], 1, 126)
    st = np.where(pre <= 63, (63 - pre) << 1, ((pre - 64) << 1) | 1)
    return st.astype(np.uint8)


class CabacDecoder:
    """9.3.3.2 arithmetic decoding engine over an RBSP byte buffer."""

    def __init__(self, rbsp: bytes, bitpos: int, slice_qp: int,
                 table=CTX_INIT_I):
        # cabac_alignment_one_bit: slice_data starts byte-aligned
        while bitpos & 7:
            bitpos += 1
        self.d = rbsp
        self.pos = bitpos
        self.nbits = len(rbsp) * 8
        self.state = _init_states(slice_qp, table)
        self.range = 510
        self.offset = 0
        self.overrun = 0
        for _ in range(9):
            self.offset = (self.offset << 1) | self._bit()
        if self.offset >= 510:
            raise ValueError("invalid CABAC init offset")

    def _bit(self) -> int:
        if self.pos >= self.nbits:
            # reads past the RBSP are 0 by rule; a bounded overrun is
            # normal (renorm looks ahead), unbounded means corruption
            self.overrun += 1
            if self.overrun > 64:
                raise ValueError("CABAC read far past slice end")
            return 0
        b = (self.d[self.pos >> 3] >> (7 - (self.pos & 7))) & 1
        self.pos += 1
        return b

    def decision(self, ctx: int) -> int:
        s = self.state[ctx]
        p = s >> 1
        mps = s & 1
        lps = RANGE_LPS[4 * p + ((self.range >> 6) & 3)]
        self.range -= lps
        if self.offset >= self.range:
            binv = mps ^ 1
            self.offset -= self.range
            self.range = lps
            if p == 0:
                mps ^= 1
            self.state[ctx] = (TRANS_IDX_LPS[p] << 1) | mps
        else:
            binv = mps
            self.state[ctx] = (TRANS_IDX_MPS[p] << 1) | mps
        while self.range < 256:
            self.range <<= 1
            self.offset = (self.offset << 1) | self._bit()
        return binv

    def bypass(self) -> int:
        self.offset = (self.offset << 1) | self._bit()
        if self.offset >= self.range:
            self.offset -= self.range
            return 1
        return 0

    def terminate(self) -> int:
        self.range -= 2
        if self.offset >= self.range:
            return 1
        while self.range < 256:
            self.range <<= 1
            self.offset = (self.offset << 1) | self._bit()
        return 0


class CabacEncoder:
    """9.3.4 arithmetic encoding engine producing RBSP bits."""

    def __init__(self, slice_qp: int, table=CTX_INIT_I):
        self.state = _init_states(slice_qp, table)
        self.low = 0
        self.range = 510
        self.first = True
        self.outstanding = 0
        self.bits: list[int] = []

    def _put(self, b: int) -> None:
        if self.first:
            self.first = False      # 9.3.4.1: leading bit not written
        else:
            self.bits.append(b)
        while self.outstanding:
            self.bits.append(1 - b)
            self.outstanding -= 1

    def _renorm(self) -> None:
        while self.range < 256:
            if self.low >= 512:
                self._put(1)
                self.low -= 512
            elif self.low < 256:
                self._put(0)
            else:
                self.outstanding += 1
                self.low -= 256
            self.low <<= 1
            self.range <<= 1

    def decision(self, ctx: int, binv: int) -> None:
        s = self.state[ctx]
        p = s >> 1
        mps = s & 1
        lps = RANGE_LPS[4 * p + ((self.range >> 6) & 3)]
        self.range -= lps
        if binv != mps:
            self.low += self.range
            self.range = lps
            if p == 0:
                mps ^= 1
            self.state[ctx] = (TRANS_IDX_LPS[p] << 1) | mps
        else:
            self.state[ctx] = (TRANS_IDX_MPS[p] << 1) | mps
        self._renorm()

    def bypass(self, binv: int) -> None:
        self.low <<= 1
        if binv:
            self.low += self.range
        if self.low >= 1024:
            self._put(1)
            self.low -= 1024
        elif self.low < 512:
            self._put(0)
        else:
            self.outstanding += 1
            self.low -= 512

    def terminate(self, binv: int) -> None:
        self.range -= 2
        if binv:
            self.low += self.range
            self.range = 2
            self._renorm()
            # EncodeFlush (9.3.4.6): the final written bit doubles as
            # rbsp_stop_one_bit
            self._put((self.low >> 9) & 1)
            self.bits.append((self.low >> 8) & 1)
            self.bits.append(1)
        else:
            self._renorm()


# ------------------------------------------------------------ syntax layer


class _NeighborState:
    """Per-slice context grids for neighbor-dependent ctxIdxInc (all
    derivations are slice-scoped: outside → mbAddrN unavailable, and for
    intra coding an unavailable coded_block_flag neighbor counts as 1)."""

    def __init__(self, width_mbs: int, height_mbs: int):
        self.w, self.h = width_mbs, height_mbs
        self.mb_seen = np.zeros(width_mbs * height_mbs, dtype=bool)
        self.is_i4x4 = np.zeros(width_mbs * height_mbs, dtype=bool)
        self.chroma_mode = np.zeros(width_mbs * height_mbs, dtype=np.int32)
        self.cbp_luma = np.zeros(width_mbs * height_mbs, dtype=np.int32)
        self.cbp_chroma = np.zeros(width_mbs * height_mbs, dtype=np.int32)
        self.dc_cbf = np.zeros(width_mbs * height_mbs, dtype=np.int8)
        # cbf grids start at -1 = "no block of THIS slice here": a top
        # neighbor inside another slice must read as unavailable (intra
        # default 1), not as an all-zero coded block — zero-init here
        # desynced every slice after the first against libavcodec
        self.luma_cbf = np.full((4 * height_mbs, 4 * width_mbs), -1,
                                dtype=np.int8)
        self.chroma_cbf = np.full((2, 2 * height_mbs, 2 * width_mbs), -1,
                                  dtype=np.int8)
        self.cdc_cbf = np.zeros((2, width_mbs * height_mbs),
                                dtype=np.int8)
        self.last_dqp_nz = False
        # -- P-slice caches --
        self.skip = np.zeros(width_mbs * height_mbs, dtype=bool)
        # per-8x8: 1 iff an inter partition with refIdx>0 covers it
        # (intra/skip/unavailable contribute 0 to the ref ctx, 9.3.3.1.1.6)
        self.refgt0 = np.zeros((2 * height_mbs, 2 * width_mbs),
                               dtype=np.int8)
        # per-4x4 |mvd| by component (intra/skip cells stay 0)
        self.absmvd = np.zeros((2, 4 * height_mbs, 4 * width_mbs),
                               dtype=np.int32)
        # per-MB transform_size_8x8_flag (ctx 399 neighbors)
        self.t8 = np.zeros(width_mbs * height_mbs, dtype=np.int8)

    def _mb_ok(self, mb: int, dx: int, dy: int) -> int:
        x, y = mb % self.w + dx, mb // self.w + dy
        if x < 0 or y < 0 or x >= self.w or y >= self.h:
            return -1
        n = y * self.w + x
        return n if self.mb_seen[n] else -1

    def mb_type_inc(self, mb: int) -> int:
        inc = 0
        for dx, dy in ((-1, 0), (0, -1)):
            n = self._mb_ok(mb, dx, dy)
            if n >= 0 and not self.is_i4x4[n]:
                inc += 1
        return inc

    def chroma_pred_inc(self, mb: int) -> int:
        # 9.3.3.1.1.8: ctxIdxInc = condTermFlagA + condTermFlagB — BOTH
        # neighbors contribute 1 (unlike the A + 2B pattern of cbf/cbp).
        # The A+2B form here decoded our own streams fine (our encoder
        # only emits chroma mode 0) but silently truncated x264 slices
        # at the first MB with two nonzero-mode neighbors.
        inc = 0
        for dx, dy in ((-1, 0), (0, -1)):
            n = self._mb_ok(mb, dx, dy)
            if n >= 0 and self.chroma_mode[n] != 0:
                inc += 1
        return inc

    def cbp_luma_inc(self, mb: int, b8: int, cur_bits: int) -> int:
        """9.3.3.1.1.4: inc = a + 2*b, condTerm = (neighbor 8x8's CBP
        bit == 0); the left/top neighbor of an edge 8x8 lives in the
        adjacent MB, inner ones in the current (partially-built) CBP."""
        x8, y8 = b8 & 1, b8 >> 1
        a = b = 1        # unavailable neighbor → bit treated as CODED (0)
        if x8 == 1:
            a = 0 if (cur_bits >> (b8 - 1)) & 1 else 1
        else:
            n = self._mb_ok(mb, -1, 0)
            if n >= 0:
                a = 0 if (self.cbp_luma[n] >> (b8 + 1)) & 1 else 1
            else:
                a = 0
        if y8 == 1:
            b = 0 if (cur_bits >> (b8 - 2)) & 1 else 1
        else:
            n = self._mb_ok(mb, 0, -1)
            if n >= 0:
                b = 0 if (self.cbp_luma[n] >> (b8 + 2)) & 1 else 1
            else:
                b = 0
        return a + 2 * b

    def cbp_chroma_inc(self, mb: int, binidx: int) -> int:
        inc = 0
        for i, (dx, dy) in enumerate(((-1, 0), (0, -1))):
            n = self._mb_ok(mb, dx, dy)
            if n < 0:
                continue
            v = self.cbp_chroma[n]
            cond = (v != 0) if binidx == 0 else (v == 2)
            if cond:
                inc += 1 if i == 0 else 2
        return inc

    def dqp_inc(self) -> int:
        return 1 if self.last_dqp_nz else 0

    def t8_inc(self, mb: int) -> int:
        """9.3.3.1.1.10: neighbors' transform_size_8x8_flag values."""
        inc = 0
        for dx, dy in ((-1, 0), (0, -1)):
            n = self._mb_ok(mb, dx, dy)
            if n >= 0 and self.t8[n]:
                inc += 1
        return inc

    def _cbf_at(self, grid, y: int, x: int, h: int, w: int,
                dflt: int) -> int:
        # outside the slice/picture: default 1 when the CURRENT MB is
        # intra, 0 when inter (9.3.3.1.1.9)
        if x < 0 or y < 0 or x >= w or y >= h:
            return dflt
        v = grid[y, x]
        return dflt if v < 0 else int(v)

    def luma_cbf_inc(self, gx: int, gy: int, intra: bool = True) -> int:
        h, w = self.luma_cbf.shape
        d = 1 if intra else 0
        return (self._cbf_at(self.luma_cbf, gy, gx - 1, h, w, d)
                + 2 * self._cbf_at(self.luma_cbf, gy - 1, gx, h, w, d))

    def chroma_cbf_inc(self, comp: int, gx: int, gy: int,
                       intra: bool = True) -> int:
        h, w = self.chroma_cbf.shape[1:]
        g = self.chroma_cbf[comp]
        d = 1 if intra else 0
        return (self._cbf_at(g, gy, gx - 1, h, w, d)
                + 2 * self._cbf_at(g, gy - 1, gx, h, w, d))

    # -- P-slice ctxIdxInc helpers ------------------------------------
    def skip_inc(self, mb: int) -> int:
        """9.3.3.1.1.1: condTermFlagN = mbN available and NOT skipped."""
        inc = 0
        for dx, dy in ((-1, 0), (0, -1)):
            n = self._mb_ok(mb, dx, dy)
            if n >= 0 and not self.skip[n]:
                inc += 1
        return inc

    def ref_inc(self, bx: int, by: int) -> int:
        """9.3.3.1.1.6 over the per-8x8 refIdx>0 cache."""
        h, w = self.refgt0.shape
        a = self.refgt0[by, bx - 1] if bx > 0 else 0
        b = self.refgt0[by - 1, bx] if by > 0 else 0
        return int(a) + 2 * int(b)

    def mvd_inc(self, comp: int, x4: int, y4: int) -> int:
        """9.3.3.1.1.7: bin0 ctx from |mvdA| + |mvdB| of the component."""
        h, w = self.absmvd.shape[1:]
        a = self.absmvd[comp, y4, x4 - 1] if x4 > 0 else 0
        b = self.absmvd[comp, y4 - 1, x4] if y4 > 0 else 0
        s = int(a) + int(b)
        return (1 if s > 2 else 0) + (1 if s > 32 else 0)

    def mark_skip(self, mb: int) -> None:
        """P_Skip: available neighbor with zero residual, refIdx 0 and
        no mvd; resets the dqp chain (7.4.5 prevMbSkipped)."""
        w = self.w
        mbx, mby = (mb % w) * 4, (mb // w) * 4
        self.mb_seen[mb] = True
        self.skip[mb] = True
        self.is_i4x4[mb] = False
        self.t8[mb] = 0
        self.chroma_mode[mb] = 0
        self.cbp_luma[mb] = 0
        self.cbp_chroma[mb] = 0
        self.dc_cbf[mb] = 0
        self.cdc_cbf[:, mb] = 0
        self.luma_cbf[mby:mby + 4, mbx:mbx + 4] = 0
        cx, cy = (mb % w) * 2, (mb // w) * 2
        self.chroma_cbf[:, cy:cy + 2, cx:cx + 2] = 0
        self.refgt0[cy:cy + 2, cx:cx + 2] = 0
        self.absmvd[:, mby:mby + 4, mbx:mbx + 4] = 0
        self.last_dqp_nz = False

    def dc_cbf_inc(self, mb: int) -> int:
        inc = 0
        for i, (dx, dy) in enumerate(((-1, 0), (0, -1))):
            n = self._mb_ok(mb, dx, dy)
            v = 1 if n < 0 else int(self.dc_cbf[n])
            if v:
                inc += 1 if i == 0 else 2
        return inc


class CabacSliceCodec:
    """Parse ⇄ serialize CABAC I slices into the shared MB model."""

    def __init__(self, sps: Sps, pps: Pps):
        self.sps = sps
        self.pps = pps
        self.inner = SliceCodec(sps, pps)   # header round-trip reuse

    # ------------------------------------------------------------ parse
    def parse_slice(self, nal: bytes
                    ) -> tuple[SliceHeader, int, list, np.ndarray]:
        """→ (header, first_mb, mbs, per-mb qp).  Raises ValueError on
        anything outside the supported profile subset."""
        rbsp = nal_to_rbsp(nal[1:])
        br = BitReader(rbsp)
        hdr = self.inner.parse_slice_header(br, nal[0])
        is_p = hdr.is_p
        table = CTX_INIT_P[hdr.cabac_init_idc] if is_p else CTX_INIT_I
        dec = CabacDecoder(rbsp, br.pos, hdr.qp, table)
        w = self.sps.width_mbs
        n_mbs = w * self.sps.height_mbs
        n_ref = hdr.num_ref_l0(self.pps) if is_p else 1
        nb = _NeighborState(w, self.sps.height_mbs)
        mbs: list = []
        qps: list[int] = []
        cur_qp = hdr.qp
        mb = hdr.first_mb
        if mb >= n_mbs:
            raise ValueError("first_mb out of range")
        while True:
            if mb >= n_mbs:
                raise ValueError("slice overruns picture")
            if is_p and dec.decision(11 + nb.skip_inc(mb)):
                nb.mark_skip(mb)
                mbs.append(MacroblockPSkip())
                qps.append(cur_qp)
            else:
                cur_qp, parsed = self._parse_mb(dec, nb, mb, cur_qp,
                                                is_p, n_ref)
                mbs.append(parsed)
                qps.append(cur_qp)
            mb += 1
            if dec.terminate():
                break
        return hdr, hdr.first_mb, mbs, np.asarray(qps)

    def _parse_mb(self, dec: CabacDecoder, nb: _NeighborState, mb: int,
                  cur_qp: int, is_p: bool = False, n_ref: int = 1):
        if is_p:
            # Table 9-34 P prefix (layout mirrored from the libavcodec
            # decode we differential-test against): bin@14 == 0 → inter,
            # == 1 → intra mb_type rides ctx 17-20 with no neighbor inc
            if dec.decision(14) == 0:
                if dec.decision(15) == 0:
                    mb_type = 3 * dec.decision(16)       # 16x16 / 8x8
                else:
                    mb_type = 2 - dec.decision(17)       # 8x16 / 16x8
                return self._parse_inter(dec, nb, mb, cur_qp, mb_type,
                                         n_ref)
            if dec.decision(17) == 0:
                return self._parse_i4x4(dec, nb, mb, cur_qp)
            if dec.terminate():
                raise ValueError("I_PCM unsupported")
            return self._parse_i16(dec, nb, mb, cur_qp,
                                   (18, 19, 19, 20, 20))
        if dec.decision(3 + nb.mb_type_inc(mb)) == 0:
            return self._parse_i4x4(dec, nb, mb, cur_qp)
        if dec.terminate():
            raise ValueError("I_PCM unsupported")
        return self._parse_i16(dec, nb, mb, cur_qp, (6, 7, 8, 9, 10))

    def _parse_i16(self, dec: CabacDecoder, nb: _NeighborState, mb: int,
                   cur_qp: int, ctxs: tuple):
        """I_16x16 tail after the mb_type prefix bins; ``ctxs`` =
        (luma15, chroma!=0, chroma==2, pred hi, pred lo) ctxIdx — the
        two slice families share bin structure but not contexts."""
        w = self.sps.width_mbs
        mbx, mby = (mb % w) * 4, (mb // w) * 4
        luma15 = dec.decision(ctxs[0])
        chroma_cbp = 0
        if dec.decision(ctxs[1]):
            chroma_cbp = 2 if dec.decision(ctxs[2]) else 1
        pred = (dec.decision(ctxs[3]) << 1) | dec.decision(ctxs[4])

        nb.mb_seen[mb] = True
        nb.is_i4x4[mb] = False
        nb.t8[mb] = 0
        nb.cbp_luma[mb] = 15 if luma15 else 0
        nb.cbp_chroma[mb] = chroma_cbp

        chroma_mode = self._parse_chroma_mode(dec, nb, mb)
        delta = self._parse_dqp(dec, nb)
        cur_qp += delta
        if not 12 <= cur_qp <= 51:
            # <12: DC dequant uses a rounding form that breaks the exact
            # +6k shift argument — pass through (same rule as CAVLC)
            raise ValueError("QPY out of I_16x16 requant range")

        dc = np.zeros(16, dtype=np.int64)
        cbf = dec.decision(_CBF_BASE + 0 + nb.dc_cbf_inc(mb))
        nb.dc_cbf[mb] = cbf
        if cbf:
            self._residual(dec, 0, dc, 16)
        ac = np.zeros((16, 15), dtype=np.int64)
        for b in range(16):
            x4, y4 = BLK_XY[b]
            gx, gy = mbx + x4, mby + y4
            if luma15:
                cbf = dec.decision(_CBF_BASE + 4 + nb.luma_cbf_inc(gx, gy))
                nb.luma_cbf[gy, gx] = cbf
                if cbf:
                    self._residual(dec, 1, ac[b], 15)
            else:
                nb.luma_cbf[gy, gx] = 0
        cdc, cac = self._parse_chroma(dec, nb, mb, chroma_cbp)
        out = MacroblockI16x16(pred, chroma_mode, bool(luma15), cur_qp,
                               dc, ac, chroma_cbp, cdc, cac)
        return cur_qp, out

    def _parse_i4x4(self, dec: CabacDecoder, nb: _NeighborState, mb: int,
                    cur_qp: int):
        w = self.sps.width_mbs
        mbx, mby = (mb % w) * 4, (mb // w) * 4
        t8 = False
        if self.pps.transform_8x8_mode:
            t8 = bool(dec.decision(_T8_BASE + nb.t8_inc(mb)))
        nb.t8[mb] = 1 if t8 else 0
        modes = []
        for _ in range(4 if t8 else 16):
            if dec.decision(68):
                modes.append((1, 0))
            else:
                rem = (dec.decision(69) | (dec.decision(69) << 1)
                       | (dec.decision(69) << 2))
                modes.append((0, rem))
        nb.mb_seen[mb] = True
        nb.is_i4x4[mb] = True
        chroma_mode = self._parse_chroma_mode(dec, nb, mb)

        cbp = 0
        for b8 in range(4):
            if dec.decision(73 + nb.cbp_luma_inc(mb, b8, cbp)):
                cbp |= 1 << b8
        chroma_cbp = 0
        if dec.decision(77 + nb.cbp_chroma_inc(mb, 0)):
            chroma_cbp = 2 if dec.decision(81 + nb.cbp_chroma_inc(mb, 1)) \
                else 1
        nb.cbp_luma[mb] = cbp
        nb.cbp_chroma[mb] = chroma_cbp

        if cbp or chroma_cbp:
            delta = self._parse_dqp(dec, nb)
            cur_qp += delta
            if not 0 <= cur_qp <= 51:
                raise ValueError("qp out of range")
        else:
            nb.last_dqp_nz = False
        nb.dc_cbf[mb] = 0

        levels = np.zeros((16, 16), dtype=np.int64)
        levels8 = None
        if t8:
            # 8x8 luma residual (cat 5): no per-block cbf — the CBP bit
            # is the coded flag, and neighbor cbf cells inherit it
            levels8 = np.zeros((4, 64), dtype=np.int64)
            for b8 in range(4):
                x8, y8 = (b8 & 1) * 2, (b8 >> 1) * 2
                bit = (cbp >> b8) & 1
                if bit:
                    self._residual(dec, 5, levels8[b8], 64)
                nb.luma_cbf[mby + y8:mby + y8 + 2,
                            mbx + x8:mbx + x8 + 2] = bit
        else:
            for b in range(16):
                x4, y4 = BLK_XY[b]
                gx, gy = mbx + x4, mby + y4
                if (cbp >> (b >> 2)) & 1:
                    cbf = dec.decision(
                        _CBF_BASE + 8 + nb.luma_cbf_inc(gx, gy))
                    nb.luma_cbf[gy, gx] = cbf
                    if cbf:
                        self._residual(dec, 2, levels[b], 16)
                else:
                    nb.luma_cbf[gy, gx] = 0
        cdc, cac = self._parse_chroma(dec, nb, mb, chroma_cbp)
        out = MacroblockI4x4(modes, chroma_mode, cbp | (chroma_cbp << 4),
                             cur_qp, levels, cdc, cac,
                             transform_8x8=t8, levels8=levels8)
        return cur_qp, out

    # -------------------------------------------------- P inter parse
    def _parse_sub_type(self, dec: CabacDecoder) -> int:
        """P sub_mb_type binarization (Table 9-34, ctx 21-23)."""
        if dec.decision(21):
            return 0                     # P_L0_8x8
        if not dec.decision(22):
            return 1                     # P_L0_8x4
        return 2 if dec.decision(23) else 3

    def _write_sub_type(self, enc: CabacEncoder, st: int) -> None:
        enc.decision(21, 1 if st == 0 else 0)
        if st == 0:
            return
        enc.decision(22, 0 if st == 1 else 1)
        if st != 1:
            enc.decision(23, 1 if st == 2 else 0)

    def _parse_ref(self, dec: CabacDecoder, nb: _NeighborState,
                   bx: int, by: int) -> int:
        ctx = 54 + nb.ref_inc(bx, by)
        ref = 0
        while dec.decision(ctx):
            ref += 1
            if ref > 31:
                raise ValueError("ref_idx overflow")
            ctx = 58 if ref == 1 else 59
        return ref

    def _write_ref_cabac(self, enc: CabacEncoder, nb: _NeighborState,
                         bx: int, by: int, ref: int) -> None:
        ctx = 54 + nb.ref_inc(bx, by)
        for i in range(ref):
            enc.decision(ctx, 1)
            ctx = 58 if i == 0 else 59
        enc.decision(ctx, 0)

    def _parse_mvd(self, dec: CabacDecoder, base: int, inc: int) -> int:
        """UEG3 mvd binarization (9.3.2.3): TU prefix cMax 9 over ctx
        base+{inc,3,4,5,6,6,...}, EG3 bypass suffix, bypass sign."""
        if not dec.decision(base + inc):
            return 0
        mag = 1
        ctxofs = 3
        while mag < 9 and dec.decision(base + ctxofs):
            mag += 1
            if ctxofs < 6:
                ctxofs += 1
        if mag == 9:
            k = 3
            while dec.bypass():
                mag += 1 << k
                k += 1
                if k > 24:
                    raise ValueError("mvd escape overflow")
            while k:
                k -= 1
                mag += dec.bypass() << k
        return -mag if dec.bypass() else mag

    def _write_mvd(self, enc: CabacEncoder, base: int, inc: int,
                   v: int) -> None:
        mag = abs(int(v))
        if mag == 0:
            enc.decision(base + inc, 0)
            return
        enc.decision(base + inc, 1)
        ctxofs = 3
        n = 1
        while n < min(mag, 9):
            enc.decision(base + ctxofs, 1)
            if ctxofs < 6:
                ctxofs += 1
            n += 1
        if mag < 9:
            enc.decision(base + ctxofs, 0)
        else:
            rem = mag - 9
            k = 3
            while rem >= (1 << k):
                enc.bypass(1)
                rem -= 1 << k
                k += 1
            enc.bypass(0)
            for i in range(k - 1, -1, -1):
                enc.bypass((rem >> i) & 1)
        enc.bypass(1 if v < 0 else 0)

    def _mvd_pair_parse(self, dec, nb, x4: int, y4: int, w4: int,
                        h4: int) -> tuple:
        mx = self._parse_mvd(dec, 40, nb.mvd_inc(0, x4, y4))
        my = self._parse_mvd(dec, 47, nb.mvd_inc(1, x4, y4))
        nb.absmvd[0, y4:y4 + h4, x4:x4 + w4] = abs(mx)
        nb.absmvd[1, y4:y4 + h4, x4:x4 + w4] = abs(my)
        return mx, my

    def _mvd_pair_write(self, enc, nb, x4: int, y4: int, w4: int,
                        h4: int, pair) -> None:
        mx, my = pair
        self._write_mvd(enc, 40, nb.mvd_inc(0, x4, y4), mx)
        self._write_mvd(enc, 47, nb.mvd_inc(1, x4, y4), my)
        nb.absmvd[0, y4:y4 + h4, x4:x4 + w4] = abs(int(mx))
        nb.absmvd[1, y4:y4 + h4, x4:x4 + w4] = abs(int(my))

    def _mvd_geometry(self, mb_type: int, sub_types,
                      mbx: int, mby: int):
        """(x4, y4, w4, h4) per coded mvd, in bitstream order."""
        if mb_type == 3:
            out = []
            for i8, st in enumerate(sub_types):
                ox, oy = (i8 & 1) * 2, (i8 >> 1) * 2
                out.extend((mbx + ox + sx, mby + oy + sy, sw, sh)
                           for sx, sy, sw, sh in _P_SUB4[st])
            return out
        return [(mbx + px * 2, mby + py * 2, pw * 2, ph * 2)
                for px, py, pw, ph in _P_PARTS8[mb_type]]

    def _parse_inter(self, dec: CabacDecoder, nb: _NeighborState,
                     mb: int, cur_qp: int, mb_type: int, n_ref: int):
        w = self.sps.width_mbs
        mbx, mby = (mb % w) * 4, (mb // w) * 4
        bx, by = (mb % w) * 2, (mb // w) * 2
        nb.mb_seen[mb] = True
        nb.is_i4x4[mb] = False
        nb.chroma_mode[mb] = 0
        sub_types = None
        if mb_type == 3:
            sub_types = [self._parse_sub_type(dec) for _ in range(4)]
            parts8 = ((0, 0, 1, 1), (1, 0, 1, 1),
                      (0, 1, 1, 1), (1, 1, 1, 1))
        else:
            parts8 = _P_PARTS8[mb_type]
        refs = []
        for px, py, pw, ph in parts8:
            if n_ref == 1:
                r = 0                    # inferred, not coded
            else:
                r = self._parse_ref(dec, nb, bx + px, by + py)
                if r >= n_ref:
                    raise ValueError("ref_idx out of range")
            refs.append(r)
            nb.refgt0[by + py:by + py + ph,
                      bx + px:bx + px + pw] = 1 if r > 0 else 0
        mvds = [self._mvd_pair_parse(dec, nb, x4, y4, w4, h4)
                for x4, y4, w4, h4 in
                self._mvd_geometry(mb_type, sub_types, mbx, mby)]

        cbp = 0
        for b8 in range(4):
            if dec.decision(73 + nb.cbp_luma_inc(mb, b8, cbp)):
                cbp |= 1 << b8
        chroma_cbp = 0
        if dec.decision(77 + nb.cbp_chroma_inc(mb, 0)):
            chroma_cbp = 2 if dec.decision(
                81 + nb.cbp_chroma_inc(mb, 1)) else 1
        nb.cbp_luma[mb] = cbp
        nb.cbp_chroma[mb] = chroma_cbp
        t8 = False
        if (cbp and self.pps.transform_8x8_mode
                and (mb_type <= 2
                     or all(t == 0 for t in (sub_types or [])))):
            t8 = bool(dec.decision(_T8_BASE + nb.t8_inc(mb)))
        nb.t8[mb] = 1 if t8 else 0
        if cbp or chroma_cbp:
            cur_qp += self._parse_dqp(dec, nb)
            if not 0 <= cur_qp <= 51:
                raise ValueError("QPY out of range")
        else:
            nb.last_dqp_nz = False
        nb.dc_cbf[mb] = 0
        levels = np.zeros((16, 16), dtype=np.int64)
        levels8 = None
        if t8:
            levels8 = np.zeros((4, 64), dtype=np.int64)
            for b8 in range(4):
                x8, y8 = (b8 & 1) * 2, (b8 >> 1) * 2
                bit = (cbp >> b8) & 1
                if bit:
                    self._residual(dec, 5, levels8[b8], 64)
                nb.luma_cbf[mby + y8:mby + y8 + 2,
                            mbx + x8:mbx + x8 + 2] = bit
        else:
            for b in range(16):
                x4, y4 = BLK_XY[b]
                gx, gy = mbx + x4, mby + y4
                if (cbp >> (b >> 2)) & 1:
                    cbf = dec.decision(
                        _CBF_BASE + 8
                        + nb.luma_cbf_inc(gx, gy, intra=False))
                    nb.luma_cbf[gy, gx] = cbf
                    if cbf:
                        self._residual(dec, 2, levels[b], 16)
                else:
                    nb.luma_cbf[gy, gx] = 0
        cdc, cac = self._parse_chroma(dec, nb, mb, chroma_cbp,
                                      intra=False)
        out = MacroblockInter(mb_type, sub_types, refs, mvds,
                              cbp | (chroma_cbp << 4), cur_qp, levels,
                              cdc, cac, transform_8x8=t8,
                              levels8=levels8)
        return cur_qp, out

    def _parse_chroma_mode(self, dec, nb, mb) -> int:
        if not dec.decision(64 + nb.chroma_pred_inc(mb)):
            mode = 0
        elif not dec.decision(67):
            mode = 1
        else:
            mode = 2 if not dec.decision(67) else 3
        nb.chroma_mode[mb] = mode
        return mode

    def _parse_dqp(self, dec, nb) -> int:
        val = 0
        ctx = 60 + nb.dqp_inc()
        while dec.decision(ctx):
            val += 1
            if val > 104:                    # 2*52: corrupt stream
                raise ValueError("mb_qp_delta overflow")
            ctx = 62 if val == 1 else 63
        nb.last_dqp_nz = val != 0
        return (val + 1) // 2 if val & 1 else -(val // 2)

    def _parse_chroma(self, dec, nb, mb, chroma_cbp, intra: bool = True):
        w = self.sps.width_mbs
        cx, cy = (mb % w) * 2, (mb // w) * 2
        cdc = np.zeros((2, 4), dtype=np.int64)
        cac = np.zeros((2, 4, 15), dtype=np.int64)
        if chroma_cbp:
            for comp in range(2):
                cbf = dec.decision(
                    _CBF_BASE + 12 + self._cdc_inc(nb, comp, mb, intra))
                self._cdc_set(nb, comp, mb, cbf)
                if cbf:
                    self._residual(dec, 3, cdc[comp], 4)
        else:
            for comp in range(2):
                self._cdc_set(nb, comp, mb, 0)
        for comp in range(2):
            for b in range(4):
                gx, gy = cx + (b & 1), cy + (b >> 1)
                if chroma_cbp == 2:
                    cbf = dec.decision(
                        _CBF_BASE + 16
                        + nb.chroma_cbf_inc(comp, gx, gy, intra))
                    nb.chroma_cbf[comp, gy, gx] = cbf
                    if cbf:
                        self._residual(dec, 4, cac[comp, b], 15)
                else:
                    nb.chroma_cbf[comp, gy, gx] = 0
        return cdc, cac

    # chroma DC cbf neighbor state lives per component per MB
    def _cdc_inc(self, nb, comp, mb, intra: bool = True) -> int:
        inc = 0
        d = 1 if intra else 0
        for i, (dx, dy) in enumerate(((-1, 0), (0, -1))):
            n = nb._mb_ok(mb, dx, dy)
            v = d if n < 0 else int(nb.cdc_cbf[comp, n])
            if v:
                inc += 1 if i == 0 else 2
        return inc

    def _cdc_set(self, nb, comp, mb, v) -> None:
        nb.cdc_cbf[comp, mb] = v

    def _residual(self, dec: CabacDecoder, cat: int, out, maxc: int
                  ) -> None:
        """residual_block_cabac (7.3.5.3.3) with cbf already consumed;
        ``out`` is a zigzag/scan-ordered level row.  cat 5 (luma 8x8)
        selects the Table 9-43 position-mapped sig/last contexts."""
        if cat == 5:
            sigpos = []
            i = 0
            while i < 63:
                if dec.decision(_SIG8 + SIG_MAP_8X8[i]):
                    sigpos.append(i)
                    if dec.decision(_LAST8 + LAST_MAP_8X8[i]):
                        break
                i += 1
            else:
                sigpos.append(63)
            abs_base = _ABS8
        else:
            sig_base = _SIG_BASE[cat]
            last_base = _LAST_BASE[cat]
            sigpos = []
            i = 0
            while i < maxc - 1:
                if dec.decision(sig_base + i):
                    sigpos.append(i)
                    if dec.decision(last_base + i):
                        break
                i += 1
            else:
                # no last flag fired: the final scan position is
                # implicitly significant (cbf guarantees >= 1 coeff)
                sigpos.append(maxc - 1)
            abs_base = _ABS_BASE[cat]
        n_eq1 = n_gt1 = 0
        for pos in reversed(sigpos):
            ctx0 = abs_base + (0 if n_gt1 else min(4, 1 + n_eq1))
            mag = 0
            if dec.decision(ctx0):
                mag = 1
                ctxn = abs_base + 5 + min(4, n_gt1)
                while mag < 14 and dec.decision(ctxn):
                    mag += 1
                if mag == 14:                # UEG0 bypass suffix
                    k = 0
                    while dec.bypass():
                        k += 1
                        if k > 31:
                            raise ValueError("level escape overflow")
                    add = 0
                    for _ in range(k):
                        add = (add << 1) | dec.bypass()
                    mag += (1 << k) - 1 + add
            level = mag + 1
            if dec.bypass():
                level = -level
            out[pos] = level
            if mag == 0:
                n_eq1 += 1
            else:
                n_gt1 += 1

    # ------------------------------------------------------------ write
    def write_slice(self, hdr: SliceHeader, first_mb: int, mbs: list,
                    qp_out_base: int) -> bytes:
        """Serialize MBs (their .qp already holds the OUTPUT absolute
        QP) into a complete NAL with the header's QP set to
        ``qp_out_base``."""
        bw = BitWriter()
        self.inner.write_slice_header(bw, hdr, qp_out_base)
        while bw.bit_length % 8:
            bw.write_bit(1)                  # cabac_alignment_one_bit
        is_p = hdr.is_p
        table = CTX_INIT_P[hdr.cabac_init_idc] if is_p else CTX_INIT_I
        enc = CabacEncoder(qp_out_base, table)
        w = self.sps.width_mbs
        n_ref = hdr.num_ref_l0(self.pps) if is_p else 1
        nb = _NeighborState(w, self.sps.height_mbs)
        prev_qp = qp_out_base
        for idx, m in enumerate(mbs):
            mb = first_mb + idx
            if is_p:
                skip = isinstance(m, MacroblockPSkip)
                enc.decision(11 + nb.skip_inc(mb), 1 if skip else 0)
                if skip:
                    nb.mark_skip(mb)
                    enc.terminate(1 if idx == len(mbs) - 1 else 0)
                    continue
            # the QP chain advances only at MBs that CODE a delta (an
            # all-zero I_4x4 MB communicates nothing; the next coded MB
            # must delta from the last coded QP, 7.4.5)
            prev_qp = self._write_mb(enc, nb, mb, m, prev_qp, is_p,
                                     n_ref)
            enc.terminate(1 if idx == len(mbs) - 1 else 0)
        for b in enc.bits:
            bw.write_bit(b)
        while bw.bit_length % 8:
            bw.write_bit(0)                  # rbsp_alignment_zero_bit
        nal_byte = (hdr.nal_ref_idc << 5) | hdr.nal_type
        return bytes([nal_byte]) + rbsp_to_nal(bw.to_bytes())

    def _write_mb(self, enc: CabacEncoder, nb: _NeighborState, mb: int,
                  m, prev_qp: int, is_p: bool = False,
                  n_ref: int = 1) -> int:
        w = self.sps.width_mbs
        mbx, mby = (mb % w) * 4, (mb // w) * 4
        cx, cy = (mb % w) * 2, (mb // w) * 2
        if isinstance(m, MacroblockInter):
            return self._write_inter(enc, nb, mb, m, prev_qp, n_ref)
        if isinstance(m, MacroblockI4x4):
            if is_p:
                enc.decision(14, 1)          # intra prefix in P
                enc.decision(17, 0)          # I_NxN
            else:
                enc.decision(3 + nb.mb_type_inc(mb), 0)
            nb.mb_seen[mb] = True
            nb.is_i4x4[mb] = True
            if self.pps.transform_8x8_mode:
                enc.decision(_T8_BASE + nb.t8_inc(mb),
                             1 if m.transform_8x8 else 0)
            nb.t8[mb] = 1 if m.transform_8x8 else 0
            for flag, rem in m.pred_modes:
                enc.decision(68, flag)
                if not flag:
                    enc.decision(69, rem & 1)
                    enc.decision(69, (rem >> 1) & 1)
                    enc.decision(69, (rem >> 2) & 1)
            self._write_chroma_mode(enc, nb, mb, m.chroma_mode)
            cbp = m.cbp & 15
            chroma_cbp = m.chroma_cbp
            built = 0
            for b8 in range(4):
                bit = (cbp >> b8) & 1
                enc.decision(73 + nb.cbp_luma_inc(mb, b8, built), bit)
                built |= bit << b8
            enc.decision(77 + nb.cbp_chroma_inc(mb, 0),
                         1 if chroma_cbp else 0)
            if chroma_cbp:
                enc.decision(81 + nb.cbp_chroma_inc(mb, 1),
                             1 if chroma_cbp == 2 else 0)
            nb.cbp_luma[mb] = cbp
            nb.cbp_chroma[mb] = chroma_cbp
            coded_qp = prev_qp
            if cbp or chroma_cbp:
                self._write_dqp(enc, nb, m.qp - prev_qp)
                coded_qp = m.qp
            else:
                nb.last_dqp_nz = False
            nb.dc_cbf[mb] = 0
            if m.transform_8x8:
                for b8 in range(4):
                    x8, y8 = (b8 & 1) * 2, (b8 >> 1) * 2
                    bit = (cbp >> b8) & 1
                    if bit:
                        self._write_residual(enc, 5, m.levels8[b8], 64)
                    nb.luma_cbf[mby + y8:mby + y8 + 2,
                                mbx + x8:mbx + x8 + 2] = bit
            else:
                for b in range(16):
                    x4, y4 = BLK_XY[b]
                    gx, gy = mbx + x4, mby + y4
                    if (cbp >> (b >> 2)) & 1:
                        row = m.levels[b]
                        cbf = 1 if np.any(row) else 0
                        enc.decision(
                            _CBF_BASE + 8 + nb.luma_cbf_inc(gx, gy), cbf)
                        nb.luma_cbf[gy, gx] = cbf
                        if cbf:
                            self._write_residual(enc, 2, row, 16)
                    else:
                        nb.luma_cbf[gy, gx] = 0
            self._write_chroma(enc, nb, mb, chroma_cbp, m.chroma_dc,
                               m.chroma_ac, cx, cy)
            return coded_qp
        # I_16x16
        if is_p:
            enc.decision(14, 1)              # intra prefix in P
            enc.decision(17, 1)              # not I_4x4
            ctxs = (18, 19, 19, 20, 20)
        else:
            enc.decision(3 + nb.mb_type_inc(mb), 1)
            ctxs = (6, 7, 8, 9, 10)
        nb.mb_seen[mb] = True
        nb.is_i4x4[mb] = False
        nb.t8[mb] = 0
        enc.terminate(0)
        enc.decision(ctxs[0], 1 if m.luma_cbp15 else 0)
        enc.decision(ctxs[1], 1 if m.chroma_cbp else 0)
        if m.chroma_cbp:
            enc.decision(ctxs[2], 1 if m.chroma_cbp == 2 else 0)
        enc.decision(ctxs[3], (m.pred_mode >> 1) & 1)
        enc.decision(ctxs[4], m.pred_mode & 1)
        nb.cbp_luma[mb] = 15 if m.luma_cbp15 else 0
        nb.cbp_chroma[mb] = m.chroma_cbp
        self._write_chroma_mode(enc, nb, mb, m.chroma_mode)
        self._write_dqp(enc, nb, m.qp - prev_qp)
        cbf = 1 if np.any(m.dc_levels) else 0
        enc.decision(_CBF_BASE + 0 + nb.dc_cbf_inc(mb), cbf)
        nb.dc_cbf[mb] = cbf
        if cbf:
            self._write_residual(enc, 0, m.dc_levels, 16)
        for b in range(16):
            x4, y4 = BLK_XY[b]
            gx, gy = mbx + x4, mby + y4
            if m.luma_cbp15:
                row = m.ac_levels[b]
                cbf = 1 if np.any(row) else 0
                enc.decision(_CBF_BASE + 4 + nb.luma_cbf_inc(gx, gy), cbf)
                nb.luma_cbf[gy, gx] = cbf
                if cbf:
                    self._write_residual(enc, 1, row, 15)
            else:
                nb.luma_cbf[gy, gx] = 0
        self._write_chroma(enc, nb, mb, m.chroma_cbp, m.chroma_dc,
                           m.chroma_ac, cx, cy)
        return m.qp                          # I_16x16 always codes dqp

    def _write_inter(self, enc: CabacEncoder, nb: _NeighborState,
                     mb: int, m: MacroblockInter, prev_qp: int,
                     n_ref: int) -> int:
        w = self.sps.width_mbs
        mbx, mby = (mb % w) * 4, (mb // w) * 4
        bx, by = (mb % w) * 2, (mb // w) * 2
        cx, cy = bx, by
        nb.mb_seen[mb] = True
        nb.is_i4x4[mb] = False
        nb.chroma_mode[mb] = 0
        if m.mb_type == 4:
            raise ValueError("P_8x8ref0 is CAVLC-only")
        enc.decision(14, 0)
        if m.mb_type in (0, 3):
            enc.decision(15, 0)
            enc.decision(16, 1 if m.mb_type == 3 else 0)
        else:
            enc.decision(15, 1)
            enc.decision(17, 1 if m.mb_type == 1 else 0)
        if m.mb_type == 3:
            for st in m.sub_types:
                self._write_sub_type(enc, st)
            parts8 = ((0, 0, 1, 1), (1, 0, 1, 1),
                      (0, 1, 1, 1), (1, 1, 1, 1))
        else:
            parts8 = _P_PARTS8[m.mb_type]
        for (px, py, pw, ph), r in zip(parts8, m.refs or
                                       [0] * len(parts8)):
            if n_ref > 1:
                self._write_ref_cabac(enc, nb, bx + px, by + py, r)
            nb.refgt0[by + py:by + py + ph,
                      bx + px:bx + px + pw] = 1 if r > 0 else 0
        for (x4, y4, w4, h4), pair in zip(
                self._mvd_geometry(m.mb_type, m.sub_types, mbx, mby),
                m.mvds):
            self._mvd_pair_write(enc, nb, x4, y4, w4, h4, pair)

        cbp = m.cbp & 15
        chroma_cbp = m.chroma_cbp
        built = 0
        for b8 in range(4):
            bit = (cbp >> b8) & 1
            enc.decision(73 + nb.cbp_luma_inc(mb, b8, built), bit)
            built |= bit << b8
        enc.decision(77 + nb.cbp_chroma_inc(mb, 0),
                     1 if chroma_cbp else 0)
        if chroma_cbp:
            enc.decision(81 + nb.cbp_chroma_inc(mb, 1),
                         1 if chroma_cbp == 2 else 0)
        nb.cbp_luma[mb] = cbp
        nb.cbp_chroma[mb] = chroma_cbp
        t8 = bool(m.transform_8x8) and cbp != 0
        if (cbp and self.pps.transform_8x8_mode and m.allows_8x8):
            enc.decision(_T8_BASE + nb.t8_inc(mb), 1 if t8 else 0)
        nb.t8[mb] = 1 if t8 else 0
        coded_qp = prev_qp
        if cbp or chroma_cbp:
            self._write_dqp(enc, nb, m.qp - prev_qp)
            coded_qp = m.qp
        else:
            nb.last_dqp_nz = False
        nb.dc_cbf[mb] = 0
        if t8:
            for b8 in range(4):
                x8, y8 = (b8 & 1) * 2, (b8 >> 1) * 2
                bit = (cbp >> b8) & 1
                if bit:
                    self._write_residual(enc, 5, m.levels8[b8], 64)
                nb.luma_cbf[mby + y8:mby + y8 + 2,
                            mbx + x8:mbx + x8 + 2] = bit
        else:
            for b in range(16):
                x4, y4 = BLK_XY[b]
                gx, gy = mbx + x4, mby + y4
                if (cbp >> (b >> 2)) & 1:
                    row = m.levels[b]
                    cbf = 1 if np.any(row) else 0
                    enc.decision(
                        _CBF_BASE + 8
                        + nb.luma_cbf_inc(gx, gy, intra=False), cbf)
                    nb.luma_cbf[gy, gx] = cbf
                    if cbf:
                        self._write_residual(enc, 2, row, 16)
                else:
                    nb.luma_cbf[gy, gx] = 0
        self._write_chroma(enc, nb, mb, chroma_cbp, m.chroma_dc,
                           m.chroma_ac, cx, cy, intra=False)
        return coded_qp

    def _write_chroma_mode(self, enc, nb, mb, mode) -> None:
        enc.decision(64 + nb.chroma_pred_inc(mb), 0 if mode == 0 else 1)
        if mode > 0:
            enc.decision(67, 0 if mode == 1 else 1)
            if mode > 1:
                enc.decision(67, 0 if mode == 2 else 1)
        nb.chroma_mode[mb] = mode

    def _write_dqp(self, enc, nb, delta: int) -> None:
        if not -26 <= delta <= 25:
            # 7.4.5 bound; requant can fold an uncoded MB's delta into
            # the next coded one — out of range must pass through, not
            # emit a nonconforming slice (caller catches ValueError)
            raise ValueError("mb_qp_delta out of range")
        val = 2 * delta - 1 if delta > 0 else -2 * delta
        ctx = 60 + nb.dqp_inc()
        for i in range(val):
            enc.decision(ctx, 1)
            ctx = 62 if i == 0 else 63
        enc.decision(ctx, 0)
        nb.last_dqp_nz = delta != 0

    def _write_chroma(self, enc, nb, mb, chroma_cbp, cdc, cac, cx, cy,
                      intra: bool = True) -> None:
        if chroma_cbp:
            for comp in range(2):
                cbf = 1 if np.any(cdc[comp]) else 0
                enc.decision(
                    _CBF_BASE + 12 + self._cdc_inc(nb, comp, mb, intra),
                    cbf)
                self._cdc_set(nb, comp, mb, cbf)
                if cbf:
                    self._write_residual(enc, 3, cdc[comp], 4)
        else:
            for comp in range(2):
                self._cdc_set(nb, comp, mb, 0)
        for comp in range(2):
            for b in range(4):
                gx, gy = cx + (b & 1), cy + (b >> 1)
                if chroma_cbp == 2:
                    row = cac[comp, b]
                    cbf = 1 if np.any(row) else 0
                    enc.decision(
                        _CBF_BASE + 16
                        + nb.chroma_cbf_inc(comp, gx, gy, intra),
                        cbf)
                    nb.chroma_cbf[comp, gy, gx] = cbf
                    if cbf:
                        self._write_residual(enc, 4, row, 15)
                else:
                    nb.chroma_cbf[comp, gy, gx] = 0

    def _write_residual(self, enc: CabacEncoder, cat: int, row, maxc: int
                        ) -> None:
        sigpos = [i for i in range(maxc) if row[i]]
        assert sigpos
        last = sigpos[-1]
        if cat == 5:
            for i in range(63):
                if i > last:
                    break
                sig = 1 if row[i] else 0
                enc.decision(_SIG8 + SIG_MAP_8X8[i], sig)
                if sig:
                    enc.decision(_LAST8 + LAST_MAP_8X8[i],
                                 1 if i == last else 0)
            abs_base = _ABS8
        else:
            sig_base = _SIG_BASE[cat]
            last_base = _LAST_BASE[cat]
            for i in range(maxc - 1):
                if i > last:
                    break
                sig = 1 if row[i] else 0
                enc.decision(sig_base + i, sig)
                if sig:
                    enc.decision(last_base + i, 1 if i == last else 0)
            abs_base = _ABS_BASE[cat]
        n_eq1 = n_gt1 = 0
        for pos in reversed(sigpos):
            level = int(row[pos])
            mag = abs(level) - 1
            ctx0 = abs_base + (0 if n_gt1 else min(4, 1 + n_eq1))
            if mag == 0:
                enc.decision(ctx0, 0)
            else:
                enc.decision(ctx0, 1)
                ctxn = abs_base + 5 + min(4, n_gt1)
                for _ in range(min(mag, 14) - 1):
                    enc.decision(ctxn, 1)
                if mag < 14:
                    enc.decision(ctxn, 0)
                else:                        # UEG0 bypass suffix:
                    # value v → k = floor(log2(v+1)): k one-bits, a
                    # zero, then k suffix bits of (v+1-2^k)
                    rem = mag - 14
                    k = (rem + 1).bit_length() - 1
                    for _ in range(k):
                        enc.bypass(1)
                    enc.bypass(0)
                    suffix = rem + 1 - (1 << k)
                    for i in range(k - 1, -1, -1):
                        enc.bypass((suffix >> i) & 1)
            enc.bypass(1 if level < 0 else 0)
            if mag == 0:
                n_eq1 += 1
            else:
                n_gt1 += 1
