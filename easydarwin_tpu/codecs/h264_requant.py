"""Transform-domain H.264 requantization: the HLS bitrate rung's core.

Open-loop CAVLC transcoding (the classic transform-domain design): parse
every macroblock's residual levels, requantize them at a higher QP
— batched on the device (``ops.transform.h264_requant`` /
``h264_requant_chroma``) or through the scalar oracles — and re-encode
the slice with the new QP and recomputed CBP/nC contexts.  SPS/PPS pass
through untouched (QP lives in the slice header).  Prediction drift is
accepted and resets at every IDR, which in the all-intra camera configs
this ladder targets means every frame.

Scope: I AND P slices in BOTH entropy layers — CAVLC and CABAC
(``h264_cabac``, dispatched on the PPS's entropy_coding_mode_flag) —
including multi-slice pictures (each slice requants independently from
its ``first_mb_in_slice``, contexts slice-scoped) — with luma AND
4:2:0 chroma residuals (luma steps by the exact +6k shift; chroma
follows the Table 8-15 QPc mapping with a three-way identity /
exact-shift / integer-round-trip dispatch — see
``h264_transform.requant_chroma_scalar``).  P slices requant their
residuals only: motion syntax (mb_type, sub-types, ref_idx, mvd) and
the skip map ride through verbatim, so prediction is untouched and
drift stays open-loop (resets at each IDR).  I_16x16 needs QPY ≥ 12
(the exact-shift DC dequant window).  High-profile 8x8-transform
streams requant too — CAVLC fully (byte-exact vs x264); CABAC with a
conservative gate that passes through any 8x8 slice whose parse stops
before the picture end (an open sparse-content margin case).  Streams
outside the profile (B slices, weighted prediction, scaling matrices,
low-QP I_16x16) PASS THROUGH unchanged and are counted — the rung
never corrupts what it cannot parse."""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .h264_bits import BitReader, BitWriter, nal_to_rbsp, rbsp_to_nal
from .h264_intra import (MacroblockI16x16, MacroblockPSkip, Pps,
                         SliceCodec, Sps)
from .h264_transform import (chroma_qp, requant_chroma_scalar,
                             requant_levels_scalar)


@dataclass
class RequantStats:
    slices_requantized: int = 0
    slices_passed_through: int = 0
    native_slices: int = 0              # served by csrc, not Python
    blocks: int = 0
    bytes_in: int = 0
    bytes_out: int = 0

    def merge(self, d: "RequantStats") -> None:
        """Fold a worker's per-AU delta in (pool path: workers requant
        against snapshot parameter sets and never touch shared stats;
        the owner thread merges at emit time)."""
        self.slices_requantized += d.slices_requantized
        self.slices_passed_through += d.slices_passed_through
        self.native_slices += d.native_slices
        self.blocks += d.blocks
        self.bytes_in += d.bytes_in
        self.bytes_out += d.bytes_out


def _peek_is_p(nal: bytes) -> bool:
    """slice_type of a coded-slice NAL (2nd ue of the header) % 5 == 0."""
    try:
        br = BitReader(nal_to_rbsp(nal[1:9]))
        br.ue()                          # first_mb_in_slice
        return br.ue() % 5 == 0
    except (ValueError, EOFError, IndexError):
        return False


def _scalar_batch(levels: np.ndarray, qp_in: np.ndarray,
                  qp_out: np.ndarray) -> np.ndarray:
    out = np.empty_like(levels)
    for i in range(levels.shape[0]):
        out[i] = requant_levels_scalar(levels[i], int(qp_in[i]),
                                       int(qp_out[i]))
    return out


def device_batch(levels: np.ndarray, qp_in: np.ndarray,
                 qp_out: np.ndarray) -> np.ndarray:
    """Batch requant on the accelerator (bit-exact vs the scalar path)."""
    import numpy as _np

    from ..ops.transform import h264_requant
    return _np.asarray(h264_requant(levels.astype(_np.int32),
                                    qp_in.astype(_np.int32),
                                    qp_out.astype(_np.int32))
                       ).astype(_np.int64)


def _scalar_batch_chroma(dc: np.ndarray, ac: np.ndarray,
                         qpc_in: np.ndarray, qpc_out: np.ndarray
                         ) -> tuple[np.ndarray, np.ndarray]:
    out_dc = np.empty_like(dc)
    out_ac = np.empty_like(ac)
    for i in range(dc.shape[0]):
        out_dc[i], out_ac[i] = requant_chroma_scalar(
            dc[i], ac[i], int(qpc_in[i]), int(qpc_out[i]))
    return out_dc, out_ac


def device_batch_chroma(dc: np.ndarray, ac: np.ndarray,
                        qpc_in: np.ndarray, qpc_out: np.ndarray
                        ) -> tuple[np.ndarray, np.ndarray]:
    """Chroma batch requant on the accelerator (bit-exact vs scalar)."""
    import numpy as _np

    from ..ops.transform import h264_requant_chroma
    d, a = h264_requant_chroma(dc.astype(_np.int32), ac.astype(_np.int32),
                               qpc_in.astype(_np.int32),
                               qpc_out.astype(_np.int32))
    return (_np.asarray(d).astype(_np.int64),
            _np.asarray(a).astype(_np.int64))


class SliceRequantizer:
    """Per-stream requantizer: latches SPS/PPS from the NAL flow and
    rewrites coded slices ``delta_qp`` steps coarser.

    Engine selection: the native CAVLC walk (``csrc ed_h264_requant_slice``,
    bit-exact vs this module's Python path — differential-tested byte for
    byte) runs by default when the C core is loaded; pure-Python CAVLC
    costs ~0.5 ms per macroblock, the native walk ~100× less, which is
    what makes HD pictures fit a real-time budget.  An explicit
    ``requant_fn`` (the device batch, the scalar oracle) pins the Python
    path — that is how the differential tests and the TPU-batched
    variant run."""

    def __init__(self, delta_qp: int, *, requant_fn=None, chroma_fn=None,
                 prefer_native: bool = True, closed_loop: bool = False):
        if delta_qp < 6 or delta_qp % 6:
            # +6k steps are EXACT level shifts (table periodicity); other
            # deltas would need transform-normalization terms
            raise ValueError("delta_qp must be a positive multiple of 6")
        self.delta_qp = delta_qp
        self.requant_fn = requant_fn or _scalar_batch
        self.chroma_fn = chroma_fn or _scalar_batch_chroma
        self._native = (prefer_native and requant_fn is None
                        and chroma_fn is None)
        # closed_loop: I slices re-derive residuals against the OUTPUT
        # reconstruction (codecs.h264_closed_loop) instead of the
        # open-loop level shift — kills intra drift at a CPU cost.
        # Reconstruction state spans the slices of one picture, so a
        # closed-loop rung must see its AUs IN ORDER (single worker).
        self.closed_loop = closed_loop
        self._cl_orig = None
        self._cl_out = None
        self.sps: Sps | None = None
        self.pps: Pps | None = None
        self.stats = RequantStats()

    # -- per-NAL entry -----------------------------------------------------
    def transform_nal(self, nal: bytes) -> bytes:
        t = nal[0] & 0x1F
        if t == 7:
            try:
                self.sps = Sps.parse(nal)
            except (ValueError, EOFError, IndexError):
                self.sps = None
            return nal
        if t == 8:
            try:
                self.pps = Pps.parse(nal)
            except (ValueError, EOFError, IndexError):
                self.pps = None
            return nal
        out, delta = self.requant_with(nal, self.sps, self.pps)
        self.stats.merge(delta)
        return out

    def requant_with(self, nal: bytes, sps: Sps | None, pps: Pps | None
                     ) -> tuple[bytes, RequantStats]:
        """Requant one slice NAL against EXPLICIT parameter sets,
        returning the output and a stats delta — no instance state is
        read or written, so pool workers can run AUs from the same
        stream concurrently (each AU snapshot-captures the sets it was
        coded against at enqueue time)."""
        delta = RequantStats()
        t = nal[0] & 0x1F
        if t not in (1, 5) or sps is None or pps is None:
            return nal, delta
        delta.bytes_in += len(nal)
        out = None
        use_native = self._native
        if self.closed_loop and use_native and not _peek_is_p(nal):
            use_native = False           # I slices take the closed loop
        if use_native:
            res = self._requant_native(nal, sps, pps)
            if res is not None:
                out, _n_slice_mbs, n_blocks = res
                delta.slices_requantized += 1
                delta.native_slices += 1
                delta.blocks += n_blocks
        if out is None:
            try:
                out, n_blocks = self._requant_slice(nal, sps, pps)
                delta.slices_requantized += 1
                delta.blocks += n_blocks
            except (ValueError, EOFError, KeyError, IndexError):
                out = nal
                delta.slices_passed_through += 1
        delta.bytes_out += len(out)
        return out, delta

    def _requant_native(self, nal: bytes, s: Sps, p: Pps
                        ) -> "tuple[bytes, int, int] | None":
        from .. import native
        if p.transform_8x8_mode:
            return None                # High 8x8: Python oracle path
        if not native.available():
            return None
        return native.h264_requant_slice(
            nal, width_mbs=s.width_mbs, height_mbs=s.height_mbs,
            log2_max_frame_num=s.log2_max_frame_num, poc_type=s.poc_type,
            log2_max_poc_lsb=s.log2_max_poc_lsb,
            pic_init_qp=p.pic_init_qp, pps_id=p.pps_id,
            deblocking_control=p.deblocking_control,
            bottom_field_poc=p.bottom_field_poc, delta_qp=self.delta_qp,
            chroma_qp_offset=p.chroma_qp_offset, cabac=p.entropy_cabac,
            num_ref_l0_default=p.num_ref_l0_default,
            weighted_pred=p.weighted_pred)

    def _requant_slice(self, nal: bytes, sps: Sps, pps: Pps
                       ) -> tuple[bytes, int]:
        n_blocks = 0
        cabac_codec = None
        if pps.entropy_cabac:
            from .h264_cabac import CabacSliceCodec
            cabac_codec = CabacSliceCodec(sps, pps)
            hdr, _first, mbs, _qps = cabac_codec.parse_slice(nal)
            qp_in_base = hdr.qp
        else:
            codec = SliceCodec(sps, pps)
            br = BitReader(nal_to_rbsp(nal[1:]))
            hdr = codec.parse_slice_header(br, nal[0])
            qp_in_base = hdr.qp
            mbs = codec.parse_mbs(br, qp_in_base, hdr.first_mb, hdr)
        qp_out_base = qp_in_base + self.delta_qp
        # mb.qp is ABSOLUTE (parse accumulates mb_qp_delta per 7.4.5):
        # the ceiling check covers the true per-MB maxima; P_Skip MBs
        # carry no QP
        if max((mb.qp for mb in mbs
                if not isinstance(mb, MacroblockPSkip)),
               default=qp_in_base) + self.delta_qp > 51:
            raise ValueError("qp already at ladder ceiling")

        if pps.entropy_cabac and pps.transform_8x8_mode \
                and hdr.first_mb + len(mbs) < sps.width_mbs \
                * sps.height_mbs:
            # CABAC + 8x8: a slice whose parse ends before the picture
            # does is either a genuine multi-slice picture or a sparse-
            # content context desync this engine still has on cat-5
            # streams (dense intra is byte-exact vs x264; the sparse
            # margin case is under investigation) — both must PASS
            # THROUGH rather than emit a truncated-but-plausible slice
            raise ValueError("CABAC 8x8 slice ended before picture end")

        if self.closed_loop and not hdr.is_p:
            n_blocks = self._closed_loop_slice(sps, pps, hdr, mbs)
        else:
            n_blocks = self._open_loop_levels(pps, mbs, n_blocks)
        for mb in mbs:
            if isinstance(mb, MacroblockPSkip):
                continue
            ccbp = (2 if np.any(mb.chroma_ac) else
                    1 if np.any(mb.chroma_dc) else 0)
            if isinstance(mb, MacroblockI16x16):
                mb.luma_cbp15 = bool(np.any(mb.ac_levels))
                mb.chroma_cbp = ccbp
            elif getattr(mb, "transform_8x8", False):
                cbp = 0
                for g in range(4):
                    if np.any(mb.levels8[g]):
                        cbp |= 1 << g
                mb.cbp = cbp | (ccbp << 4)
            else:                      # I_NxN and inter share the CBP
                cbp = 0                # recompute shape
                for g in range(4):
                    if np.any(mb.levels[4 * g:4 * g + 4]):
                        cbp |= 1 << g
                mb.cbp = cbp | (ccbp << 4)
            mb.qp = mb.qp + self.delta_qp
        if cabac_codec is not None:
            return cabac_codec.write_slice(hdr, hdr.first_mb, mbs,
                                           qp_out_base), n_blocks
        bw = BitWriter()
        codec.write_slice_header(bw, hdr, qp_out_base)
        codec.write_mbs(bw, mbs, qp_out_base, hdr.first_mb, hdr)
        bw.rbsp_trailing()
        return bytes([nal[0]]) + rbsp_to_nal(bw.to_bytes()), n_blocks

    def _closed_loop_slice(self, sps: Sps, pps: Pps, hdr, mbs) -> int:
        """Closed-loop intra requant of one slice's MBs (mutates their
        levels in place); returns the block count for stats parity."""
        from .h264_closed_loop import PictureRecon, requant_mb_closed
        if hdr.first_mb % sps.width_mbs:
            raise ValueError("closed loop needs MB-row-aligned slices")
        if hdr.first_mb == 0 or self._cl_orig is None:
            self._cl_orig = PictureRecon(sps.width_mbs, sps.height_mbs)
            self._cl_out = PictureRecon(sps.width_mbs, sps.height_mbs)
        n_blocks = 0
        for i, mb in enumerate(mbs, start=hdr.first_mb):
            requant_mb_closed(self._cl_orig, self._cl_out, sps, pps, i,
                              mb, hdr.first_mb, self.delta_qp)
            n_blocks += (17 if isinstance(mb, MacroblockI16x16) else 16)
            n_blocks += 8 if mb.chroma_cbp else 0
        return n_blocks

    def _open_loop_levels(self, pps: Pps, mbs, n_blocks: int) -> int:
        # gather every block with its per-MB source/target QP; the +6k
        # step is uniform so every MB shifts by the same k.  I_16x16 MBs
        # contribute a DC row + 16 zero-padded 15-coeff AC rows (the op
        # is elementwise, padding stays zero); a row map routes results
        # back to the right structure
        all_levels = []
        qps = []
        row_map = []                   # (mb_index, kind, blk)
        for i, mb in enumerate(mbs):
            if isinstance(mb, MacroblockPSkip):
                continue               # no residual, nothing to shift
            if getattr(mb, "transform_8x8", False):
                # 8x8 levels shift by the same exact +6k step (the 8x8
                # tables share the qp%6 periodicity); batch as 16 rows
                all_levels.append(mb.levels8.reshape(16, 16))
                row_map.extend((i, "l8", b) for b in range(16))
                qps.extend([mb.qp] * 16)
                continue
            if isinstance(mb, MacroblockI16x16):
                all_levels.append(mb.dc_levels[None, :])
                row_map.append((i, "dc", 0))
                qps.append(mb.qp)
                ac = np.zeros((16, 16), dtype=np.int64)
                ac[:, :15] = mb.ac_levels
                all_levels.append(ac)
                row_map.extend((i, "ac", b) for b in range(16))
                qps.extend([mb.qp] * 16)
            else:
                all_levels.append(mb.levels)
                row_map.extend((i, "l4", b) for b in range(16))
                qps.extend([mb.qp] * 16)
        if all_levels:                 # an all-skip P slice has no rows;
            # its header QP still shifts (deblocking strength follows
            # the slice QP even for skipped MBs)
            batch = np.concatenate(all_levels, axis=0)
            qps = np.asarray(qps)
            n_blocks += batch.shape[0]
            requanted = self.requant_fn(batch, qps, qps + self.delta_qp)
        else:
            requanted = np.zeros((0, 16), dtype=np.int64)

        # write back + recompute CBP and the shifted absolute QP per MB;
        # the writer re-derives deltas vs the previous CODED MB, so a
        # cleared-CBP MB's QP correctly stops influencing the chain
        for r, (i, kind, b) in enumerate(row_map):
            mb = mbs[i]
            if kind == "dc":
                mb.dc_levels = requanted[r]
            elif kind == "ac":
                mb.ac_levels[b] = requanted[r, :15]
            elif kind == "l8":
                mb.levels8[b >> 2, (b & 3) * 16:(b & 3) * 16 + 16] = \
                    requanted[r]
            else:
                mb.levels[b] = requanted[r]

        # chroma: per-MB QPc pairs (Table 8-15 over the shifted QPY)
        # through the three-way identity/shift/round-trip requant, both
        # components batched as independent rows
        centries = [i for i, mb in enumerate(mbs) if mb.chroma_cbp]
        if centries:
            off = pps.chroma_qp_offset
            cdc = np.stack([mbs[i].chroma_dc for i in centries])
            cac = np.stack([mbs[i].chroma_ac for i in centries])
            qin = np.array([chroma_qp(mbs[i].qp, off) for i in centries],
                           dtype=np.int64)
            qout = np.array(
                [chroma_qp(mbs[i].qp + self.delta_qp, off)
                 for i in centries], dtype=np.int64)
            n_blocks += 8 * len(centries)
            d2, a2 = self.chroma_fn(
                cdc.reshape(-1, 4), cac.reshape(-1, 4, 15),
                np.repeat(qin, 2), np.repeat(qout, 2))
            d2 = d2.reshape(-1, 2, 4)
            a2 = a2.reshape(-1, 2, 4, 15)
            for j, i in enumerate(centries):
                mbs[i].chroma_dc = d2[j]
                mbs[i].chroma_ac = a2[j]
        return n_blocks
