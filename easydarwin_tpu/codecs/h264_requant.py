"""Transform-domain H.264 requantization: the HLS bitrate rung's core.

Open-loop CAVLC transcoding (the classic transform-domain design): parse
every macroblock's residual levels, requantize them at a higher QP
— batched on the device (``ops.transform.h264_requant`` /
``h264_requant_chroma``) or through the scalar oracles — and re-encode
the slice with the new QP and recomputed CBP/nC contexts.  SPS/PPS pass
through untouched (QP lives in the slice header).  Prediction drift is
accepted and resets at every IDR, which in the all-intra camera configs
this ladder targets means every frame.

Scope: I AND P slices in BOTH entropy layers — CAVLC and CABAC
(``h264_cabac``, dispatched on the PPS's entropy_coding_mode_flag) —
including multi-slice pictures (each slice requants independently from
its ``first_mb_in_slice``, contexts slice-scoped) — with luma AND
4:2:0 chroma residuals (luma steps by the exact +6k shift; chroma
follows the Table 8-15 QPc mapping with a three-way identity /
exact-shift / integer-round-trip dispatch — see
``h264_transform.requant_chroma_scalar``).  P slices requant their
residuals only: motion syntax (mb_type, sub-types, ref_idx, mvd) and
the skip map ride through verbatim, so prediction is untouched and
drift stays open-loop (resets at each IDR).  I_16x16 needs QPY ≥ 12
(the exact-shift DC dequant window).  High-profile 8x8-transform
streams requant too — CAVLC fully (byte-exact vs x264); CABAC with a
conservative gate that passes through any 8x8 slice whose parse stops
before the picture end (an open sparse-content margin case).  Streams
outside the profile (B slices, weighted prediction, scaling matrices,
low-QP I_16x16) PASS THROUGH unchanged and are counted — the rung
never corrupts what it cannot parse."""

from __future__ import annotations

import copy
import threading
from dataclasses import dataclass, field

import numpy as np

from .h264_bits import BitReader, BitWriter, nal_to_rbsp, rbsp_to_nal
from .h264_intra import (MacroblockI16x16, MacroblockPSkip, Pps,
                         SliceCodec, SliceHeader, Sps)
from .h264_transform import (chroma_qp, requant_chroma_scalar,
                             requant_levels_scalar)


@dataclass
class RequantStats:
    slices_requantized: int = 0
    slices_passed_through: int = 0
    native_slices: int = 0              # served by csrc, not Python
    blocks: int = 0
    bytes_in: int = 0
    bytes_out: int = 0
    # merge() must be safe under the requant worker pool: slice jobs of
    # one AU complete on different workers, and two lock-free read-
    # modify-write merges into the same target can drop counts.  The
    # discipline stays "accumulate per-worker deltas locally, merge once
    # at AU completion", but the fold itself now holds a lock so ANY
    # caller topology is correct, not just the loop-thread one.
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)

    def merge(self, d: "RequantStats") -> None:
        """Fold a worker's per-AU delta in (pool path: workers requant
        against snapshot parameter sets and accumulate into LOCAL delta
        objects; the deltas are merged into the shared stats once per
        AU).  Thread-safe: concurrent merges into the same target
        serialize on the target's lock."""
        with self._lock:
            self.slices_requantized += d.slices_requantized
            self.slices_passed_through += d.slices_passed_through
            self.native_slices += d.native_slices
            self.blocks += d.blocks
            self.bytes_in += d.bytes_in
            self.bytes_out += d.bytes_out


def _peek_is_p(nal: bytes) -> bool:
    """slice_type of a coded-slice NAL (2nd ue of the header) % 5 == 0."""
    try:
        br = BitReader(nal_to_rbsp(nal[1:9]))
        br.ue()                          # first_mb_in_slice
        return br.ue() % 5 == 0
    except (ValueError, EOFError, IndexError):
        return False


def _scalar_batch(levels: np.ndarray, qp_in: np.ndarray,
                  qp_out: np.ndarray) -> np.ndarray:
    out = np.empty_like(levels)
    for i in range(levels.shape[0]):
        out[i] = requant_levels_scalar(levels[i], int(qp_in[i]),
                                       int(qp_out[i]))
    return out


def device_batch(levels: np.ndarray, qp_in: np.ndarray,
                 qp_out: np.ndarray) -> np.ndarray:
    """Batch requant on the accelerator (bit-exact vs the scalar path)."""
    import numpy as _np

    from ..ops.transform import h264_requant
    return _np.asarray(h264_requant(levels.astype(_np.int32),
                                    qp_in.astype(_np.int32),
                                    qp_out.astype(_np.int32))
                       ).astype(_np.int64)


def _scalar_batch_chroma(dc: np.ndarray, ac: np.ndarray,
                         qpc_in: np.ndarray, qpc_out: np.ndarray
                         ) -> tuple[np.ndarray, np.ndarray]:
    out_dc = np.empty_like(dc)
    out_ac = np.empty_like(ac)
    for i in range(dc.shape[0]):
        out_dc[i], out_ac[i] = requant_chroma_scalar(
            dc[i], ac[i], int(qpc_in[i]), int(qpc_out[i]))
    return out_dc, out_ac


def device_batch_chroma(dc: np.ndarray, ac: np.ndarray,
                        qpc_in: np.ndarray, qpc_out: np.ndarray
                        ) -> tuple[np.ndarray, np.ndarray]:
    """Chroma batch requant on the accelerator (bit-exact vs scalar)."""
    import numpy as _np

    from ..ops.transform import h264_requant_chroma
    d, a = h264_requant_chroma(dc.astype(_np.int32), ac.astype(_np.int32),
                               qpc_in.astype(_np.int32),
                               qpc_out.astype(_np.int32))
    return (_np.asarray(d).astype(_np.int64),
            _np.asarray(a).astype(_np.int64))


# ===================================================== shared-parse fan-out
# The ABR-ladder cost model (ISSUE 9 tentpole): parse/entropy-decode a
# slice ONCE, requantize the same parsed MB arrays to N ``delta_qp``
# targets, and re-encode N slices — the parse (the dominant CAVLC/CABAC
# read on the Python engines) is amortized across the whole rendition
# ladder instead of paid per rendition.  The pieces compose:
#
#   parse_slice_nal()  →  ParsedSlice     (one per slice, shared)
#   gather_slice()     →  SliceGather     (level rows + QPs, shared)
#   FusedRequantDispatch(gathers × deltas) — ONE transform dispatch for
#       every (slice, rendition) of an AU; with ``use_device`` the JAX
#       call is asynchronous, so the device computes while pool workers
#       entropy-decode the NEXT AU (the PR 4 double-buffered staging
#       pattern at AU scale)
#   recode_parsed()    →  bytes           (per rendition, clones the MBs)
#
# ``SliceRequantizer._requant_slice`` runs the SAME pipeline with a
# single delta and no clone, so the serial path and the fan-out path are
# one code path — byte-identity between them is structural, and the
# differential tests pin it.


@dataclass
class ParsedSlice:
    """One entropy-decoded slice: everything recode needs, engine-agnostic
    (the MB model is shared by the CAVLC and CABAC layers)."""

    nal0: int                           # original NAL header byte
    hdr: SliceHeader
    mbs: list
    qp_in_base: int                     # slice-header QP (pre-shift)
    cabac: bool
    sps: Sps
    pps: Pps


@dataclass
class SliceGather:
    """The batched-requant surface of one parsed slice: every residual
    row with its per-row QP, plus the write-back routing map.  Built
    once per slice and shared read-only across renditions."""

    rows: np.ndarray                    # [R, 16] luma/8x8 level rows
    qps: np.ndarray                     # [R] absolute source QPY per row
    row_map: list                       # (mb_index, kind, blk) per row
    centries: list                      # mb indices with chroma residual
    cqp: np.ndarray                     # [C] source QPY of those MBs
    cdc: np.ndarray                     # [C*2, 4] chroma DC rows
    cac: np.ndarray                     # [C*2, 4, 15] chroma AC rows
    n_blocks: int                       # luma + chroma block count
    max_qp: int                         # slice ceiling input (7.4.5 max)


def parse_slice_nal(nal: bytes, sps: Sps, pps: Pps) -> ParsedSlice:
    """Entropy-decode one coded-slice NAL into the shared MB model
    (CAVLC or CABAC per the PPS).  Raises ValueError on anything outside
    the requant profile — the caller passes the slice through."""
    if pps.entropy_cabac:
        from .h264_cabac import CabacSliceCodec
        hdr, _first, mbs, _qps = CabacSliceCodec(sps, pps).parse_slice(nal)
    else:
        codec = SliceCodec(sps, pps)
        br = BitReader(nal_to_rbsp(nal[1:]))
        hdr = codec.parse_slice_header(br, nal[0])
        mbs = codec.parse_mbs(br, hdr.qp, hdr.first_mb, hdr)
    if pps.entropy_cabac and pps.transform_8x8_mode \
            and hdr.first_mb + len(mbs) < sps.width_mbs * sps.height_mbs:
        # CABAC + 8x8: a slice whose parse ends before the picture does
        # is either a genuine multi-slice picture or a sparse-content
        # context desync this engine still has on cat-5 streams — both
        # must PASS THROUGH rather than emit a truncated slice
        raise ValueError("CABAC 8x8 slice ended before picture end")
    return ParsedSlice(nal[0], hdr, mbs, hdr.qp, pps.entropy_cabac,
                       sps, pps)


def gather_slice(parsed: ParsedSlice) -> SliceGather:
    """Collect every residual row of a parsed slice with its per-MB
    source QP (the +6k step is uniform, so the TARGET QP is derived per
    rendition at dispatch time).  I_16x16 MBs contribute a DC row + 16
    zero-padded 15-coeff AC rows (the op is elementwise, padding stays
    zero); a row map routes results back to the right structure."""
    mbs = parsed.mbs
    all_levels = []
    qps: list[int] = []
    row_map: list[tuple[int, str, int]] = []
    for i, mb in enumerate(mbs):
        if isinstance(mb, MacroblockPSkip):
            continue                   # no residual, nothing to shift
        if getattr(mb, "transform_8x8", False):
            # 8x8 levels shift by the same exact +6k step (the 8x8
            # tables share the qp%6 periodicity); batch as 16 rows
            all_levels.append(mb.levels8.reshape(16, 16))
            row_map.extend((i, "l8", b) for b in range(16))
            qps.extend([mb.qp] * 16)
            continue
        if isinstance(mb, MacroblockI16x16):
            all_levels.append(mb.dc_levels[None, :])
            row_map.append((i, "dc", 0))
            qps.append(mb.qp)
            ac = np.zeros((16, 16), dtype=np.int64)
            ac[:, :15] = mb.ac_levels
            all_levels.append(ac)
            row_map.extend((i, "ac", b) for b in range(16))
            qps.extend([mb.qp] * 16)
        else:
            all_levels.append(mb.levels)
            row_map.extend((i, "l4", b) for b in range(16))
            qps.extend([mb.qp] * 16)
    if all_levels:                     # an all-skip P slice has no rows;
        # its header QP still shifts (deblocking strength follows the
        # slice QP even for skipped MBs)
        rows = np.concatenate(all_levels, axis=0)
    else:
        rows = np.zeros((0, 16), dtype=np.int64)
    n_blocks = rows.shape[0]

    centries = [i for i, mb in enumerate(mbs) if mb.chroma_cbp]
    if centries:
        cdc = np.stack([mbs[i].chroma_dc for i in centries]).reshape(-1, 4)
        cac = np.stack([mbs[i].chroma_ac
                        for i in centries]).reshape(-1, 4, 15)
        cqp = np.array([mbs[i].qp for i in centries], dtype=np.int64)
        n_blocks += 8 * len(centries)
    else:
        cdc = np.zeros((0, 4), dtype=np.int64)
        cac = np.zeros((0, 4, 15), dtype=np.int64)
        cqp = np.zeros((0,), dtype=np.int64)
    return SliceGather(rows, np.asarray(qps, dtype=np.int64), row_map,
                       centries, cqp, cdc, cac, n_blocks,
                       max((mb.qp for mb in mbs
                            if not isinstance(mb, MacroblockPSkip)),
                           default=parsed.qp_in_base))


def _device_rows_async(levels: np.ndarray, qp_in: np.ndarray,
                       qp_out: np.ndarray):
    """Luma dispatch WITHOUT the host sync: returns the JAX array so the
    device computes behind the caller (harvest converts)."""
    from ..ops.transform import h264_requant
    return h264_requant(levels.astype(np.int32), qp_in.astype(np.int32),
                        qp_out.astype(np.int32))


def _device_chroma_async(dc: np.ndarray, ac: np.ndarray,
                         qpc_in: np.ndarray, qpc_out: np.ndarray):
    from ..ops.transform import h264_requant_chroma
    return h264_requant_chroma(dc.astype(np.int32), ac.astype(np.int32),
                               qpc_in.astype(np.int32),
                               qpc_out.astype(np.int32))


class FusedRequantDispatch:
    """ONE transform dispatch covering every (slice, rendition) pair of
    an access unit (tentpole c): the luma rows and chroma rows of all
    gathers are tiled across the delta axis and requantized in a single
    fused call.  With ``use_device=True`` the dispatch goes through the
    asynchronous JAX op — the device computes while the pool's other
    workers entropy-decode the next slices/AU, and ``harvest`` blocks
    only on arrival (PR 4's dispatch-ahead/harvest-behind staging shape,
    here at AU scale).  Bit-exact vs per-slice-per-delta calls: the op
    is elementwise per row, so tiling never changes values."""

    def __init__(self, gathers: "list[SliceGather]",
                 deltas: "tuple[int, ...]", *, requant_fn=None,
                 chroma_fn=None, chroma_qp_offset: int = 0,
                 use_device: bool = False):
        self.deltas = tuple(deltas)
        self._lock = threading.Lock()
        self._np_rows = None
        self._np_chroma = None
        # a delta every slice of this batch would reject at the QP-51
        # ceiling is excluded from the tile entirely — a permanently
        # over-ceiling rung must not tax every AU of the stream with
        # transform work recode_parsed then discards.  (A delta only
        # SOME slices reject stays tiled: its under-ceiling slices
        # still consume their rows.)
        floor_qp = min((g.max_qp for g in gathers), default=0)
        self._tile_pos = {}
        for i, d in enumerate(self.deltas):
            if floor_qp + d <= 51:
                self._tile_pos[i] = len(self._tile_pos)
        active = [self.deltas[i] for i in sorted(self._tile_pos)]
        nd = len(active)
        self._offsets = np.cumsum([0] + [g.rows.shape[0]
                                         for g in gathers])
        self._coffsets = np.cumsum([0] + [len(g.centries)
                                          for g in gathers])
        r_total = int(self._offsets[-1])
        c_total = int(self._coffsets[-1])
        self._r_total, self._c_total = r_total, c_total
        self._pending_rows = None
        self._pending_chroma = None
        if r_total and nd:
            rows = np.concatenate([g.rows for g in gathers], axis=0)
            qps = np.concatenate([g.qps for g in gathers])
            batch = np.tile(rows, (nd, 1))
            qp_in = np.tile(qps, nd)
            qp_out = np.concatenate([qps + d for d in active])
            fn = _device_rows_async if use_device \
                else (requant_fn or _scalar_batch)
            self._pending_rows = fn(batch, qp_in, qp_out)
        if c_total and nd:
            cdc = np.concatenate([g.cdc for g in gathers], axis=0)
            cac = np.concatenate([g.cac for g in gathers], axis=0)
            cqp = np.concatenate([g.cqp for g in gathers])
            qin = np.array([chroma_qp(int(q), chroma_qp_offset)
                            for q in cqp], dtype=np.int64)
            dc_t = np.tile(cdc, (nd, 1))
            ac_t = np.tile(cac, (nd, 1, 1))
            qin_t = np.repeat(np.tile(qin, nd), 2)
            qout_t = np.repeat(np.concatenate(
                [np.array([chroma_qp(int(q) + d, chroma_qp_offset)
                           for q in cqp], dtype=np.int64)
                 for d in active]), 2)
            cfn = _device_chroma_async if use_device \
                else (chroma_fn or _scalar_batch_chroma)
            self._pending_chroma = cfn(dc_t, ac_t, qin_t, qout_t)

    def _harvested(self):
        """Block (once) on the fused results and cache the numpy views."""
        with self._lock:
            if self._np_rows is None:
                if self._pending_rows is not None:
                    self._np_rows = np.asarray(
                        self._pending_rows).astype(np.int64)
                else:
                    self._np_rows = np.zeros((0, 16), dtype=np.int64)
                if self._pending_chroma is not None:
                    d, a = self._pending_chroma
                    self._np_chroma = (np.asarray(d).astype(np.int64),
                                       np.asarray(a).astype(np.int64))
                else:
                    self._np_chroma = (
                        np.zeros((0, 4), dtype=np.int64),
                        np.zeros((0, 4, 15), dtype=np.int64))
                self._pending_rows = self._pending_chroma = None
        return self._np_rows, self._np_chroma

    def _pos(self, delta_idx: int) -> int:
        pos = self._tile_pos.get(delta_idx)
        if pos is None:
            # unreachable through recode_parsed (its ceiling check
            # raises first), kept as the same contract for any caller
            raise ValueError("qp already at ladder ceiling")
        return pos

    def luma_rows(self, slice_idx: int, delta_idx: int) -> np.ndarray:
        rows, _ = self._harvested()
        base = self._pos(delta_idx) * self._r_total
        return rows[base + int(self._offsets[slice_idx]):
                    base + int(self._offsets[slice_idx + 1])]

    def chroma_rows(self, slice_idx: int, delta_idx: int
                    ) -> tuple[np.ndarray, np.ndarray]:
        _, (d, a) = self._harvested()
        lo = 2 * (self._pos(delta_idx) * self._c_total
                  + int(self._coffsets[slice_idx]))
        hi = 2 * (self._pos(delta_idx) * self._c_total
                  + int(self._coffsets[slice_idx + 1]))
        return (d[lo:hi].reshape(-1, 2, 4),
                a[lo:hi].reshape(-1, 2, 4, 15))


def _clone_mb(mb):
    """Copy one parsed MB so a rendition's requant write-back never
    touches the shared parse (arrays the recode mutates are copied;
    verbatim-carried syntax — pred modes, motion — is shared)."""
    if isinstance(mb, MacroblockPSkip):
        return mb                       # stateless marker
    c = copy.copy(mb)
    for f in ("levels", "levels8", "dc_levels", "ac_levels",
              "chroma_dc", "chroma_ac"):
        v = getattr(c, f, None)
        if isinstance(v, np.ndarray):
            setattr(c, f, v.copy())
    return c


def _writeback_rows(mbs: list, gather: SliceGather,
                    requanted: np.ndarray,
                    cdc2: np.ndarray, cac2: np.ndarray) -> None:
    """Route fused-requant rows back into the MB structures (the inverse
    of ``gather_slice``'s flattening)."""
    for r, (i, kind, b) in enumerate(gather.row_map):
        mb = mbs[i]
        if kind == "dc":
            mb.dc_levels = requanted[r]
        elif kind == "ac":
            mb.ac_levels[b] = requanted[r, :15]
        elif kind == "l8":
            mb.levels8[b >> 2, (b & 3) * 16:(b & 3) * 16 + 16] = \
                requanted[r]
        else:
            mb.levels[b] = requanted[r]
    for j, i in enumerate(gather.centries):
        mbs[i].chroma_dc = cdc2[j]
        mbs[i].chroma_ac = cac2[j]


def _finalize_mbs(mbs: list, delta_qp: int) -> None:
    """Recompute CBP/CBP-equivalents from the requanted levels and shift
    every MB's absolute QP; the writer re-derives deltas vs the previous
    CODED MB, so a cleared-CBP MB's QP correctly stops influencing the
    chain."""
    for mb in mbs:
        if isinstance(mb, MacroblockPSkip):
            continue
        ccbp = (2 if np.any(mb.chroma_ac) else
                1 if np.any(mb.chroma_dc) else 0)
        if isinstance(mb, MacroblockI16x16):
            mb.luma_cbp15 = bool(np.any(mb.ac_levels))
            mb.chroma_cbp = ccbp
        elif getattr(mb, "transform_8x8", False):
            cbp = 0
            for g in range(4):
                if np.any(mb.levels8[g]):
                    cbp |= 1 << g
            mb.cbp = cbp | (ccbp << 4)
        else:                          # I_NxN and inter share the CBP
            cbp = 0                    # recompute shape
            for g in range(4):
                if np.any(mb.levels[4 * g:4 * g + 4]):
                    cbp |= 1 << g
            mb.cbp = cbp | (ccbp << 4)
        mb.qp = mb.qp + delta_qp


def _write_slice_bytes(parsed: ParsedSlice, mbs: list,
                       qp_out_base: int) -> bytes:
    """Serialize the requanted MBs back into a slice NAL (fresh codec
    per call: the writers are stateless beyond SPS/PPS, so renditions
    recode concurrently)."""
    if parsed.cabac:
        from .h264_cabac import CabacSliceCodec
        return CabacSliceCodec(parsed.sps, parsed.pps).write_slice(
            parsed.hdr, parsed.hdr.first_mb, mbs, qp_out_base)
    codec = SliceCodec(parsed.sps, parsed.pps)
    bw = BitWriter()
    codec.write_slice_header(bw, parsed.hdr, qp_out_base)
    codec.write_mbs(bw, mbs, qp_out_base, parsed.hdr.first_mb,
                    parsed.hdr)
    bw.rbsp_trailing()
    return bytes([parsed.nal0]) + rbsp_to_nal(bw.to_bytes())


def _check_ceiling(parsed: ParsedSlice, delta_qp: int) -> None:
    # mb.qp is ABSOLUTE (parse accumulates mb_qp_delta per 7.4.5): the
    # ceiling check covers the true per-MB maxima; P_Skip MBs carry no QP
    if max((mb.qp for mb in parsed.mbs
            if not isinstance(mb, MacroblockPSkip)),
           default=parsed.qp_in_base) + delta_qp > 51:
        raise ValueError("qp already at ladder ceiling")


def recode_parsed(parsed: ParsedSlice, gather: SliceGather,
                  dispatch: FusedRequantDispatch, slice_idx: int,
                  delta_idx: int, *, clone: bool = True
                  ) -> tuple[bytes, int]:
    """One rendition's serial entropy re-encode over the shared parse:
    clone the MB arrays, write the fused-requant rows back, recompute
    CBP + the shifted QP chain, and serialize.  Raises ValueError when
    this rendition's target QP would pass the ladder ceiling (the caller
    passes the slice through for THAT rendition only)."""
    delta_qp = dispatch.deltas[delta_idx]
    if gather.max_qp + delta_qp > 51:    # == _check_ceiling, O(1): the
        # gather already carries the slice's per-MB QP maximum
        raise ValueError("qp already at ladder ceiling")
    mbs = [_clone_mb(mb) for mb in parsed.mbs] if clone else parsed.mbs
    requanted = dispatch.luma_rows(slice_idx, delta_idx)
    cdc2, cac2 = dispatch.chroma_rows(slice_idx, delta_idx)
    _writeback_rows(mbs, gather, requanted, cdc2, cac2)
    _finalize_mbs(mbs, delta_qp)
    return (_write_slice_bytes(parsed, mbs,
                               parsed.qp_in_base + delta_qp),
            gather.n_blocks)


def requant_multi(nal: bytes, sps: Sps | None, pps: Pps | None,
                  deltas: "tuple[int, ...]", *, requant_fn=None,
                  chroma_fn=None, use_device: bool = False
                  ) -> "list[tuple[bytes, RequantStats]]":
    """Shared-parse rendition fan-out over one NAL: parse once, requant
    + recode to every ``delta_qp`` in ``deltas`` with ONE fused
    transform dispatch.  Returns (output, stats delta) per rendition —
    stateless, so pool workers run slices of the same stream
    concurrently.  Output is byte-identical to N independent
    ``SliceRequantizer``s with the same engine config (pinned by
    tests/test_requant_ladder.py)."""
    t = nal[0] & 0x1F
    if t not in (1, 5) or sps is None or pps is None:
        return [(nal, RequantStats()) for _ in deltas]
    try:
        parsed = parse_slice_nal(nal, sps, pps)
        gather = gather_slice(parsed)
    except (ValueError, EOFError, KeyError, IndexError):
        out = []
        for _ in deltas:
            d = RequantStats()
            d.bytes_in += len(nal)
            d.slices_passed_through += 1
            d.bytes_out += len(nal)
            out.append((nal, d))
        return out
    dispatch = FusedRequantDispatch(
        [gather], tuple(deltas), requant_fn=requant_fn,
        chroma_fn=chroma_fn, chroma_qp_offset=pps.chroma_qp_offset,
        use_device=use_device)
    out = []
    for i in range(len(dispatch.deltas)):
        d = RequantStats()
        d.bytes_in += len(nal)
        try:
            out_nal, n_blocks = recode_parsed(parsed, gather, dispatch,
                                              0, i)
            d.slices_requantized += 1
            d.blocks += n_blocks
        except (ValueError, EOFError, KeyError, IndexError):
            out_nal = nal
            d.slices_passed_through += 1
        d.bytes_out += len(out_nal)
        out.append((out_nal, d))
    return out


class SliceRequantizer:
    """Per-stream requantizer: latches SPS/PPS from the NAL flow and
    rewrites coded slices ``delta_qp`` steps coarser.

    Engine selection: the native CAVLC walk (``csrc ed_h264_requant_slice``,
    bit-exact vs this module's Python path — differential-tested byte for
    byte) runs by default when the C core is loaded; pure-Python CAVLC
    costs ~0.5 ms per macroblock, the native walk ~100× less, which is
    what makes HD pictures fit a real-time budget.  An explicit
    ``requant_fn`` (the device batch, the scalar oracle) pins the Python
    path — that is how the differential tests and the TPU-batched
    variant run."""

    def __init__(self, delta_qp: int, *, requant_fn=None, chroma_fn=None,
                 prefer_native: bool = True, closed_loop: bool = False):
        if delta_qp < 6 or delta_qp % 6:
            # +6k steps are EXACT level shifts (table periodicity); other
            # deltas would need transform-normalization terms
            raise ValueError("delta_qp must be a positive multiple of 6")
        self.delta_qp = delta_qp
        self.requant_fn = requant_fn or _scalar_batch
        self.chroma_fn = chroma_fn or _scalar_batch_chroma
        self._native = (prefer_native and requant_fn is None
                        and chroma_fn is None)
        # closed_loop: I slices re-derive residuals against the OUTPUT
        # reconstruction (codecs.h264_closed_loop) instead of the
        # open-loop level shift — kills intra drift at a CPU cost.
        # Reconstruction state spans the slices of one picture, so a
        # closed-loop rung must see its AUs IN ORDER (single worker).
        self.closed_loop = closed_loop
        self._cl_orig = None
        self._cl_out = None
        self.sps: Sps | None = None
        self.pps: Pps | None = None
        self.stats = RequantStats()

    # -- per-NAL entry -----------------------------------------------------
    def transform_nal(self, nal: bytes) -> bytes:
        t = nal[0] & 0x1F
        if t == 7:
            try:
                self.sps = Sps.parse(nal)
            except (ValueError, EOFError, IndexError):
                self.sps = None
            return nal
        if t == 8:
            try:
                self.pps = Pps.parse(nal)
            except (ValueError, EOFError, IndexError):
                self.pps = None
            return nal
        out, delta = self.requant_with(nal, self.sps, self.pps)
        self.stats.merge(delta)
        return out

    def requant_with(self, nal: bytes, sps: Sps | None, pps: Pps | None
                     ) -> tuple[bytes, RequantStats]:
        """Requant one slice NAL against EXPLICIT parameter sets,
        returning the output and a stats delta — no instance state is
        read or written, so pool workers can run AUs from the same
        stream concurrently (each AU snapshot-captures the sets it was
        coded against at enqueue time)."""
        delta = RequantStats()
        t = nal[0] & 0x1F
        if t not in (1, 5) or sps is None or pps is None:
            return nal, delta
        delta.bytes_in += len(nal)
        out = None
        use_native = self._native
        if self.closed_loop and use_native and not _peek_is_p(nal):
            use_native = False           # I slices take the closed loop
        if use_native:
            res = self._requant_native(nal, sps, pps)
            if res is not None:
                out, _n_slice_mbs, n_blocks = res
                delta.slices_requantized += 1
                delta.native_slices += 1
                delta.blocks += n_blocks
        if out is None:
            try:
                out, n_blocks = self._requant_slice(nal, sps, pps)
                delta.slices_requantized += 1
                delta.blocks += n_blocks
            except (ValueError, EOFError, KeyError, IndexError):
                out = nal
                delta.slices_passed_through += 1
        delta.bytes_out += len(out)
        return out, delta

    def _requant_native(self, nal: bytes, s: Sps, p: Pps
                        ) -> "tuple[bytes, int, int] | None":
        from .. import native
        if p.transform_8x8_mode:
            return None                # High 8x8: Python oracle path
        if not native.available():
            return None
        return native.h264_requant_slice(
            nal, width_mbs=s.width_mbs, height_mbs=s.height_mbs,
            log2_max_frame_num=s.log2_max_frame_num, poc_type=s.poc_type,
            log2_max_poc_lsb=s.log2_max_poc_lsb,
            pic_init_qp=p.pic_init_qp, pps_id=p.pps_id,
            deblocking_control=p.deblocking_control,
            bottom_field_poc=p.bottom_field_poc, delta_qp=self.delta_qp,
            chroma_qp_offset=p.chroma_qp_offset, cabac=p.entropy_cabac,
            num_ref_l0_default=p.num_ref_l0_default,
            weighted_pred=p.weighted_pred)

    def _requant_slice(self, nal: bytes, sps: Sps, pps: Pps
                       ) -> tuple[bytes, int]:
        """Single-rendition requant: the SAME parse → gather → fused
        dispatch → recode pipeline the ladder fan-out runs, with one
        delta and no MB clone — serial/fan-out byte-identity is
        structural, not coincidental."""
        parsed = parse_slice_nal(nal, sps, pps)
        _check_ceiling(parsed, self.delta_qp)
        if self.closed_loop and not parsed.hdr.is_p:
            n_blocks = self._closed_loop_slice(sps, pps, parsed.hdr,
                                               parsed.mbs)
            _finalize_mbs(parsed.mbs, self.delta_qp)
            return (_write_slice_bytes(
                parsed, parsed.mbs,
                parsed.qp_in_base + self.delta_qp), n_blocks)
        gather = gather_slice(parsed)
        dispatch = FusedRequantDispatch(
            [gather], (self.delta_qp,), requant_fn=self.requant_fn,
            chroma_fn=self.chroma_fn,
            chroma_qp_offset=pps.chroma_qp_offset)
        return recode_parsed(parsed, gather, dispatch, 0, 0,
                             clone=False)

    def _closed_loop_slice(self, sps: Sps, pps: Pps, hdr, mbs) -> int:
        """Closed-loop intra requant of one slice's MBs (mutates their
        levels in place); returns the block count for stats parity."""
        from .h264_closed_loop import PictureRecon, requant_mb_closed
        if hdr.first_mb % sps.width_mbs:
            raise ValueError("closed loop needs MB-row-aligned slices")
        if hdr.first_mb == 0 or self._cl_orig is None:
            self._cl_orig = PictureRecon(sps.width_mbs, sps.height_mbs)
            self._cl_out = PictureRecon(sps.width_mbs, sps.height_mbs)
        n_blocks = 0
        for i, mb in enumerate(mbs, start=hdr.first_mb):
            requant_mb_closed(self._cl_orig, self._cl_out, sps, pps, i,
                              mb, hdr.first_mb, self.delta_qp)
            n_blocks += (17 if isinstance(mb, MacroblockI16x16) else 16)
            n_blocks += 8 if mb.chroma_cbp else 0
        return n_blocks

