"""H.264 CAVLC residual coding (baseline profile, 4×4 luma blocks).

Both directions — the HLS requant rung decodes every residual block,
requantizes the levels on the device, and re-encodes at the new QP.
Tables are the spec's (ITU-T H.264 Tables 9-5, 9-7/9-8, 9-10); the test
suite checks them for prefix-freeness and against the published worked
example (Richardson, *H.264 and MPEG-4 Video Compression*, the classic
TotalCoeff=5/T1s=3 block).  ``nC == -1`` selects the 4:2:0 chroma-DC
column of Table 9-5 (with Table 9-9(a) total_zeros) so the transcode
tier covers chroma residuals too."""

from __future__ import annotations

from .h264_bits import BitReader, BitWriter

# --------------------------------------------------------------- coeff_token
# {(total_coeff, trailing_ones): (n_bits, value)} per nC class.
_CT_NC0 = {   # 0 <= nC < 2
    (0, 0): (1, 0b1),
    (1, 0): (6, 0b000101), (1, 1): (2, 0b01),
    (2, 0): (8, 0b00000111), (2, 1): (6, 0b000100), (2, 2): (3, 0b001),
    (3, 0): (9, 0b000000111), (3, 1): (8, 0b00000110),
    (3, 2): (7, 0b0000101), (3, 3): (5, 0b00011),
    (4, 0): (10, 0b0000000111), (4, 1): (9, 0b000000110),
    (4, 2): (8, 0b00000101), (4, 3): (6, 0b000011),
    (5, 0): (11, 0b00000000111), (5, 1): (10, 0b0000000110),
    (5, 2): (9, 0b000000101), (5, 3): (7, 0b0000100),
    (6, 0): (13, 0b0000000001111), (6, 1): (11, 0b00000000110),
    (6, 2): (10, 0b0000000101), (6, 3): (8, 0b00000100),
    (7, 0): (13, 0b0000000001011), (7, 1): (13, 0b0000000001110),
    (7, 2): (11, 0b00000000101), (7, 3): (9, 0b000000100),
    (8, 0): (13, 0b0000000001000), (8, 1): (13, 0b0000000001010),
    (8, 2): (13, 0b0000000001101), (8, 3): (10, 0b0000000100),
    (9, 0): (14, 0b00000000001111), (9, 1): (14, 0b00000000001110),
    (9, 2): (13, 0b0000000001001), (9, 3): (11, 0b00000000100),
    (10, 0): (14, 0b00000000001011), (10, 1): (14, 0b00000000001010),
    (10, 2): (14, 0b00000000001101), (10, 3): (13, 0b0000000001100),
    (11, 0): (15, 0b000000000001111), (11, 1): (15, 0b000000000001110),
    (11, 2): (14, 0b00000000001001), (11, 3): (14, 0b00000000001100),
    (12, 0): (15, 0b000000000001011), (12, 1): (15, 0b000000000001010),
    (12, 2): (15, 0b000000000001101), (12, 3): (14, 0b00000000001000),
    (13, 0): (16, 0b0000000000001111), (13, 1): (15, 0b000000000000001),
    (13, 2): (15, 0b000000000001001), (13, 3): (15, 0b000000000001100),
    (14, 0): (16, 0b0000000000001011), (14, 1): (16, 0b0000000000001110),
    (14, 2): (16, 0b0000000000001101), (14, 3): (15, 0b000000000001000),
    (15, 0): (16, 0b0000000000000111), (15, 1): (16, 0b0000000000001010),
    (15, 2): (16, 0b0000000000001001), (15, 3): (16, 0b0000000000001100),
    (16, 0): (16, 0b0000000000000100), (16, 1): (16, 0b0000000000000110),
    (16, 2): (16, 0b0000000000000101), (16, 3): (16, 0b0000000000001000),
}
_CT_NC2 = {   # 2 <= nC < 4
    (0, 0): (2, 0b11),
    (1, 0): (6, 0b001011), (1, 1): (2, 0b10),
    (2, 0): (6, 0b000111), (2, 1): (5, 0b00111), (2, 2): (3, 0b011),
    (3, 0): (7, 0b0000111), (3, 1): (6, 0b001010),
    (3, 2): (6, 0b001001), (3, 3): (4, 0b0101),
    (4, 0): (8, 0b00000111), (4, 1): (6, 0b000110),
    (4, 2): (6, 0b000101), (4, 3): (4, 0b0100),
    (5, 0): (8, 0b00000100), (5, 1): (7, 0b0000110),
    (5, 2): (7, 0b0000101), (5, 3): (5, 0b00110),
    (6, 0): (9, 0b000000111), (6, 1): (8, 0b00000110),
    (6, 2): (8, 0b00000101), (6, 3): (6, 0b001000),
    (7, 0): (11, 0b00000001111), (7, 1): (9, 0b000000110),
    (7, 2): (9, 0b000000101), (7, 3): (6, 0b000100),
    (8, 0): (11, 0b00000001011), (8, 1): (11, 0b00000001110),
    (8, 2): (11, 0b00000001101), (8, 3): (7, 0b0000100),
    (9, 0): (12, 0b000000001111), (9, 1): (11, 0b00000001010),
    (9, 2): (11, 0b00000001001), (9, 3): (9, 0b000000100),
    (10, 0): (12, 0b000000001011), (10, 1): (12, 0b000000001110),
    (10, 2): (12, 0b000000001101), (10, 3): (11, 0b00000001100),
    (11, 0): (12, 0b000000001000), (11, 1): (12, 0b000000001010),
    (11, 2): (12, 0b000000001001), (11, 3): (11, 0b00000001000),
    (12, 0): (13, 0b0000000001111), (12, 1): (13, 0b0000000001110),
    (12, 2): (13, 0b0000000001101), (12, 3): (12, 0b000000001100),
    (13, 0): (13, 0b0000000001011), (13, 1): (13, 0b0000000001010),
    (13, 2): (13, 0b0000000001001), (13, 3): (13, 0b0000000001100),
    (14, 0): (13, 0b0000000000111), (14, 1): (14, 0b00000000001011),
    (14, 2): (13, 0b0000000000110), (14, 3): (13, 0b0000000001000),
    (15, 0): (14, 0b00000000001001), (15, 1): (14, 0b00000000001000),
    (15, 2): (14, 0b00000000001010), (15, 3): (13, 0b0000000000001),
    (16, 0): (14, 0b00000000000111), (16, 1): (14, 0b00000000000110),
    (16, 2): (14, 0b00000000000101), (16, 3): (14, 0b00000000000100),
}
_CT_NC4 = {   # 4 <= nC < 8
    (0, 0): (4, 0b1111),
    (1, 0): (6, 0b001111), (1, 1): (4, 0b1110),
    (2, 0): (6, 0b001011), (2, 1): (5, 0b01111), (2, 2): (4, 0b1101),
    (3, 0): (6, 0b001000), (3, 1): (5, 0b01100),
    (3, 2): (5, 0b01110), (3, 3): (4, 0b1100),
    (4, 0): (7, 0b0001111), (4, 1): (5, 0b01010),
    (4, 2): (5, 0b01011), (4, 3): (4, 0b1011),
    (5, 0): (7, 0b0001011), (5, 1): (5, 0b01000),
    (5, 2): (5, 0b01001), (5, 3): (4, 0b1010),
    (6, 0): (7, 0b0001001), (6, 1): (6, 0b001110),
    (6, 2): (6, 0b001101), (6, 3): (4, 0b1001),
    (7, 0): (7, 0b0001000), (7, 1): (6, 0b001010),
    (7, 2): (6, 0b001001), (7, 3): (4, 0b1000),
    (8, 0): (8, 0b00001111), (8, 1): (7, 0b0001110),
    (8, 2): (7, 0b0001101), (8, 3): (5, 0b01101),
    (9, 0): (8, 0b00001011), (9, 1): (8, 0b00001110),
    (9, 2): (7, 0b0001010), (9, 3): (6, 0b001100),
    (10, 0): (9, 0b000001111), (10, 1): (8, 0b00001010),
    (10, 2): (8, 0b00001101), (10, 3): (7, 0b0001100),
    (11, 0): (9, 0b000001011), (11, 1): (9, 0b000001110),
    (11, 2): (8, 0b00001001), (11, 3): (8, 0b00001100),
    (12, 0): (9, 0b000001000), (12, 1): (9, 0b000001010),
    (12, 2): (9, 0b000001101), (12, 3): (8, 0b00001000),
    (13, 0): (10, 0b0000001101), (13, 1): (9, 0b000000111),
    (13, 2): (9, 0b000001001), (13, 3): (9, 0b000001100),
    (14, 0): (10, 0b0000001001), (14, 1): (10, 0b0000001100),
    (14, 2): (10, 0b0000001011), (14, 3): (10, 0b0000001010),
    (15, 0): (10, 0b0000000101), (15, 1): (10, 0b0000001000),
    (15, 2): (10, 0b0000000111), (15, 3): (10, 0b0000000110),
    (16, 0): (10, 0b0000000001), (16, 1): (10, 0b0000000100),
    (16, 2): (10, 0b0000000011), (16, 3): (10, 0b0000000010),
}


#: Table 9-5's nC == −1 column: chroma DC (4:2:0, maxNumCoeff 4).
_CT_CDC = {
    (0, 0): (2, 0b01),
    (1, 0): (6, 0b000111), (1, 1): (1, 0b1),
    (2, 0): (6, 0b000100), (2, 1): (6, 0b000110), (2, 2): (3, 0b001),
    (3, 0): (6, 0b000011), (3, 1): (7, 0b0000011),
    (3, 2): (7, 0b0000010), (3, 3): (6, 0b000101),
    (4, 0): (6, 0b000010), (4, 1): (8, 0b00000011),
    (4, 2): (8, 0b00000010), (4, 3): (7, 0b0000000),
}


def _invert(table):
    return {(n, v): key for key, (n, v) in table.items()}


_CT_TABLES = (_CT_NC0, _CT_NC2, _CT_NC4)
_CT_DECODE = tuple(_invert(t) for t in _CT_TABLES)
_CT_CDC_DECODE = _invert(_CT_CDC)


def _ct_class(nC: int) -> int:
    if nC < 2:
        return 0
    if nC < 4:
        return 1
    if nC < 8:
        return 2
    return 3          # 6-bit FLC


def write_coeff_token(bw: BitWriter, nC: int, total: int, t1s: int) -> None:
    if nC < 0:                          # chroma DC (4:2:0)
        n, v = _CT_CDC[(total, t1s)]
        bw.write_bits(v, n)
        return
    cls = _ct_class(nC)
    if cls == 3:
        v = 0b000011 if total == 0 else (((total - 1) << 2) | t1s)
        bw.write_bits(v, 6)
        return
    n, v = _CT_TABLES[cls][(total, t1s)]
    bw.write_bits(v, n)


def read_coeff_token(br: BitReader, nC: int) -> tuple[int, int]:
    if nC < 0:
        table = _CT_CDC_DECODE
        max_bits = 8
    else:
        cls = _ct_class(nC)
        if cls == 3:
            v = br.read_bits(6)
            if v == 0b000011:
                return 0, 0
            return (v >> 2) + 1, v & 3
        table = _CT_DECODE[cls]
        max_bits = 17
    n = 0
    v = 0
    while n < max_bits:
        v = (v << 1) | br.read_bit()
        n += 1
        hit = table.get((n, v))
        if hit is not None:
            return hit
    raise ValueError("bad coeff_token")


# --------------------------------------------------------------- total_zeros
# Table 9-7/9-8: _TZ[total_coeff-1][total_zeros] = (bits, value)
_TZ = [
    # tc=1
    [(1, 1), (3, 0b011), (3, 0b010), (4, 0b0011), (4, 0b0010),
     (5, 0b00011), (5, 0b00010), (6, 0b000011), (6, 0b000010),
     (7, 0b0000011), (7, 0b0000010), (8, 0b00000011), (8, 0b00000010),
     (9, 0b000000011), (9, 0b000000010), (9, 0b000000001)],
    # tc=2
    [(3, 0b111), (3, 0b110), (3, 0b101), (3, 0b100), (3, 0b011),
     (4, 0b0101), (4, 0b0100), (4, 0b0011), (4, 0b0010), (5, 0b00011),
     (5, 0b00010), (6, 0b000011), (6, 0b000010), (6, 0b000001),
     (6, 0b000000)],
    # tc=3
    [(4, 0b0101), (3, 0b111), (3, 0b110), (3, 0b101), (4, 0b0100),
     (4, 0b0011), (3, 0b100), (3, 0b011), (4, 0b0010), (5, 0b00011),
     (5, 0b00010), (6, 0b000001), (5, 0b00001), (6, 0b000000)],
    # tc=4
    [(5, 0b00011), (3, 0b111), (4, 0b0101), (4, 0b0100), (3, 0b110),
     (3, 0b101), (3, 0b100), (4, 0b0011), (3, 0b011), (4, 0b0010),
     (5, 0b00010), (5, 0b00001), (5, 0b00000)],
    # tc=5
    [(4, 0b0101), (4, 0b0100), (4, 0b0011), (3, 0b111), (3, 0b110),
     (3, 0b101), (3, 0b100), (3, 0b011), (4, 0b0010), (5, 0b00001),
     (4, 0b0001), (5, 0b00000)],
    # tc=6
    [(6, 0b000001), (5, 0b00001), (3, 0b111), (3, 0b110), (3, 0b101),
     (3, 0b100), (3, 0b011), (3, 0b010), (4, 0b0001), (3, 0b001),
     (6, 0b000000)],
    # tc=7
    [(6, 0b000001), (5, 0b00001), (3, 0b101), (3, 0b100), (3, 0b011),
     (2, 0b11), (3, 0b010), (4, 0b0001), (3, 0b001), (6, 0b000000)],
    # tc=8
    [(6, 0b000001), (4, 0b0001), (5, 0b00001), (3, 0b011), (2, 0b11),
     (2, 0b10), (3, 0b010), (3, 0b001), (6, 0b000000)],
    # tc=9
    [(6, 0b000001), (6, 0b000000), (4, 0b0001), (2, 0b11), (2, 0b10),
     (3, 0b001), (2, 0b01), (5, 0b00001)],
    # tc=10
    [(5, 0b00001), (5, 0b00000), (3, 0b001), (2, 0b11), (2, 0b10),
     (2, 0b01), (4, 0b0001)],
    # tc=11
    [(4, 0b0000), (4, 0b0001), (3, 0b001), (3, 0b010), (1, 0b1),
     (3, 0b011)],
    # tc=12
    [(4, 0b0000), (4, 0b0001), (2, 0b01), (1, 0b1), (3, 0b001)],
    # tc=13
    [(3, 0b000), (3, 0b001), (1, 0b1), (2, 0b01)],
    # tc=14
    [(2, 0b00), (2, 0b01), (1, 0b1)],
    # tc=15
    [(1, 0b0), (1, 0b1)],
]
_TZ_DECODE = [{(n, v): tz for tz, (n, v) in enumerate(row)} for row in _TZ]

#: Table 9-9(a): total_zeros for chroma DC (4:2:0, maxNumCoeff 4);
#: rows are TotalCoeff 1..3 (TotalCoeff 4 ⇒ no zeros, nothing coded).
_TZ_CDC = [
    [(1, 1), (2, 0b01), (3, 0b001), (3, 0b000)],
    [(1, 1), (2, 0b01), (2, 0b00)],
    [(1, 1), (1, 0b0)],
]
_TZ_CDC_DECODE = [{(n, v): tz for tz, (n, v) in enumerate(row)}
                  for row in _TZ_CDC]


def write_total_zeros(bw: BitWriter, total_coeff: int, tz: int,
                      max_coeff: int = 16) -> None:
    row = (_TZ_CDC if max_coeff == 4 else _TZ)[total_coeff - 1]
    n, v = row[tz]
    bw.write_bits(v, n)


def read_total_zeros(br: BitReader, total_coeff: int,
                     max_coeff: int = 16) -> int:
    table = (_TZ_CDC_DECODE if max_coeff == 4
             else _TZ_DECODE)[total_coeff - 1]
    n = 0
    v = 0
    while n < 10:
        v = (v << 1) | br.read_bit()
        n += 1
        hit = table.get((n, v))
        if hit is not None:
            return hit
    raise ValueError("bad total_zeros")


# ---------------------------------------------------------------- run_before
# Table 9-10: _RB[min(zeros_left,7)-1][run] = (bits, value); zeros_left>6
# extends with unary runs 7..14.
_RB = [
    [(1, 1), (1, 0)],
    [(1, 1), (2, 0b01), (2, 0b00)],
    [(2, 0b11), (2, 0b10), (2, 0b01), (2, 0b00)],
    [(2, 0b11), (2, 0b10), (2, 0b01), (3, 0b001), (3, 0b000)],
    [(2, 0b11), (2, 0b10), (3, 0b011), (3, 0b010), (3, 0b001),
     (3, 0b000)],
    [(2, 0b11), (3, 0b000), (3, 0b001), (3, 0b011), (3, 0b010),
     (3, 0b101), (3, 0b100)],
    [(3, 0b111), (3, 0b110), (3, 0b101), (3, 0b100), (3, 0b011),
     (3, 0b010), (3, 0b001)],
]
_RB_DECODE = [{(n, v): r for r, (n, v) in enumerate(row)} for row in _RB]


def write_run_before(bw: BitWriter, zeros_left: int, run: int) -> None:
    idx = min(zeros_left, 7) - 1
    if zeros_left > 6 and run > 6:
        # unary extension: run 7 → 0001, 8 → 00001, ...
        bw.write_bits(1, run - 3)
        return
    n, v = _RB[idx][run]
    bw.write_bits(v, n)


def read_run_before(br: BitReader, zeros_left: int) -> int:
    idx = min(zeros_left, 7) - 1
    table = _RB_DECODE[idx]
    n = 0
    v = 0
    while n < 3:
        v = (v << 1) | br.read_bit()
        n += 1
        hit = table.get((n, v))
        if hit is not None:
            return hit
    if zeros_left > 6 and v == 0:
        # unary extension
        run = 6
        while br.read_bit() == 0:
            run += 1
            if run > 14:
                raise ValueError("bad run_before")
        return run + 1
    raise ValueError("bad run_before")


# ----------------------------------------------------------- residual block

def decode_residual(br: BitReader, nC: int, max_coeff: int = 16
                    ) -> list[int]:
    """One CAVLC residual block → levels in ZIGZAG order [max_coeff]."""
    total, t1s = read_coeff_token(br, nC)
    if total > max_coeff:
        raise ValueError("TotalCoeff exceeds block size")
    levels = [0] * max_coeff
    if total == 0:
        return levels
    # trailing-one signs, highest frequency first
    vals: list[int] = []
    for _ in range(t1s):
        vals.append(-1 if br.read_bit() else 1)
    suffix_len = 1 if total > 10 and t1s < 3 else 0
    for i in range(total - t1s):
        prefix = 0
        while br.read_bit() == 0:
            prefix += 1
            if prefix > 32:
                raise ValueError("bad level_prefix")
        if prefix <= 14:
            suffix_size = suffix_len
            if prefix == 14 and suffix_len == 0:
                suffix_size = 4
            level_code = (min(prefix, 15) << suffix_len) \
                + (br.read_bits(suffix_size) if suffix_size else 0)
        else:
            suffix_size = prefix - 3
            level_code = (15 << suffix_len) + br.read_bits(suffix_size)
            if suffix_len == 0:
                level_code += 15
            if prefix >= 16:
                level_code += (1 << (prefix - 3)) - 4096
        if i == 0 and t1s < 3:
            level_code += 2
        if level_code % 2 == 0:
            vals.append((level_code + 2) >> 1)
        else:
            vals.append(-((level_code + 1) >> 1))
        if suffix_len == 0:
            suffix_len = 1
        if abs(vals[-1]) > (3 << (suffix_len - 1)) and suffix_len < 6:
            suffix_len += 1
    total_zeros = 0
    if total < max_coeff:
        total_zeros = read_total_zeros(br, total, max_coeff)
    # place coefficients, highest scan position first
    zeros_left = total_zeros
    pos = total + total_zeros - 1
    for i, v in enumerate(vals):
        levels[pos] = v
        if i == len(vals) - 1:
            break
        run = read_run_before(br, zeros_left) if zeros_left > 0 else 0
        zeros_left -= run
        pos -= 1 + run
    return levels


def encode_residual(bw: BitWriter, levels: list[int], nC: int,
                    max_coeff: int = 16) -> None:
    """Levels in ZIGZAG order [max_coeff] → CAVLC bits (inverse of
    ``decode_residual``; fuzz-tested as a bijection)."""
    nz = [(i, v) for i, v in enumerate(levels[:max_coeff]) if v != 0]
    total = len(nz)
    if total == 0:
        write_coeff_token(bw, nC, 0, 0)
        return
    # trailing ones: up to 3 |v|==1 at the end of the scan
    t1s = 0
    for _, v in reversed(nz):
        if abs(v) == 1 and t1s < 3:
            t1s += 1
        else:
            break
    write_coeff_token(bw, nC, total, t1s)
    rev = list(reversed(nz))              # highest frequency first
    for _, v in rev[:t1s]:
        bw.write_bit(1 if v < 0 else 0)
    suffix_len = 1 if total > 10 and t1s < 3 else 0
    for i, (_, v) in enumerate(rev[t1s:]):
        level_code = (abs(v) - 1) * 2 + (1 if v < 0 else 0)
        if i == 0 and t1s < 3:
            level_code -= 2
        if suffix_len == 0:
            if level_code < 14:
                bw.write_bits(1, level_code + 1)          # prefix, stop 1
            elif level_code < 30:
                bw.write_bits(1, 15)                      # prefix 14
                bw.write_bits(level_code - 14, 4)
            else:
                lc = level_code - 30
                size = 12
                prefix = 15
                while lc >= (1 << size):
                    lc -= (1 << size)
                    prefix += 1
                    size += 1
                bw.write_bits(0, prefix)
                bw.write_bit(1)
                bw.write_bits(lc, size)
        else:
            if level_code < (15 << suffix_len):
                prefix = level_code >> suffix_len
                bw.write_bits(1, prefix + 1)
                bw.write_bits(level_code & ((1 << suffix_len) - 1),
                              suffix_len)
            else:
                lc = level_code - (15 << suffix_len)
                size = 12
                prefix = 15
                while lc >= (1 << size):
                    lc -= (1 << size)
                    prefix += 1
                    size += 1
                bw.write_bits(0, prefix)
                bw.write_bit(1)
                bw.write_bits(lc, size)
        if suffix_len == 0:
            suffix_len = 1
        if abs(v) > (3 << (suffix_len - 1)) and suffix_len < 6:
            suffix_len += 1
    highest = nz[-1][0]
    total_zeros = highest + 1 - total
    if total < max_coeff:
        write_total_zeros(bw, total, total_zeros, max_coeff)
    zeros_left = total_zeros
    for i in range(len(rev) - 1):
        pos = rev[i][0]
        nxt = rev[i + 1][0]
        run = pos - nxt - 1
        if zeros_left > 0:
            write_run_before(bw, zeros_left, run)
            zeros_left -= run
        # zeros_left == 0: nothing coded, runs are implicitly 0


def total_coeffs(levels: list[int]) -> int:
    return sum(1 for v in levels if v != 0)
