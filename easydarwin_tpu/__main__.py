"""CLI entry: ``python -m easydarwin_tpu [-c config.toml] [options]``.

The ``main.cpp`` equivalent (CLI parse ``main.cpp:323-385``) minus the fork
watchdog (see ``server.supervisor`` for the restart loop).
"""

from __future__ import annotations

import argparse
import asyncio
import signal

from .server import ServerConfig, StreamingServer


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="easydarwin_tpu",
        description="TPU-native RTSP streaming/relay server")
    p.add_argument("-c", "--config", help="TOML config file")
    p.add_argument("-p", "--rtsp-port", type=int, help="RTSP listen port")
    p.add_argument("--service-port", type=int, help="REST API port")
    p.add_argument("--bind-ip", help="bind address")
    p.add_argument("--movie-folder", help="VOD media directory")
    p.add_argument("--module-folder",
                   help="directory of plugin .py modules (LoadModules)")
    p.add_argument("--tpu-fanout", action="store_true",
                   help="enable the TPU batch fan-out engine")
    p.add_argument("-S", "--stats-interval", type=int, metavar="N",
                   help="print status columns every N seconds (-S display)")
    p.add_argument("--status-file", help="write a JSON status snapshot here "
                   "on an interval (server_status equivalent)")
    p.add_argument("-x", "--exit-after-boot", action="store_true",
                   help="boot, print status, exit (config check)")
    p.add_argument("-w", "--watchdog", action="store_true",
                   help="run under the auto-restart supervisor")
    return p


def config_from_args(args) -> ServerConfig:
    is_xml = False
    if args.config:
        with open(args.config, "rb") as f:
            head = f.read(256).lstrip()
        # sniff content, not filename: reference configs travel under
        # arbitrary names (easydarwin.conf, EASYDARWIN.XML, ...)
        is_xml = head.startswith((b"<?xml", b"<!DOCTYPE", b"<CONFIGURATION"))
    if is_xml:
        # reference easydarwin.xml migration path
        from .server.config import load_reference_xml
        cfg, unmapped = load_reference_xml(args.config)
        if unmapped:
            print(f"note: {len(unmapped)} reference prefs have no "
                  f"counterpart here (first few: {unmapped[:5]})",
                  flush=True)
    elif args.config:
        cfg = ServerConfig.from_toml(args.config)
    else:
        cfg = ServerConfig()
    for k in ("rtsp_port", "service_port", "bind_ip", "movie_folder",
              "module_folder"):
        v = getattr(args, k)
        if v is not None:
            setattr(cfg, k, v)
    if args.tpu_fanout:
        cfg.tpu_fanout = True
    if args.stats_interval is not None:
        cfg.stats_interval_sec = args.stats_interval
    if args.status_file is not None:
        cfg.status_file_path = args.status_file
    return cfg


async def amain(cfg: ServerConfig, exit_after_boot: bool = False) -> int:
    app = StreamingServer(cfg)
    await app.start()
    print(f"easydarwin-tpu listening: rtsp://{cfg.bind_ip}:{app.rtsp.port} "
          f"service http://{cfg.bind_ip}:{app.rest.port}/api/v1 "
          f"tpu_fanout={'on' if cfg.tpu_fanout else 'off'}", flush=True)
    if exit_after_boot:
        await app.stop()
        return 0
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    loop.add_signal_handler(signal.SIGHUP,
                            lambda: cfg.update())   # RereadPrefs rebroadcast
    done, _ = await asyncio.wait(
        [asyncio.create_task(stop.wait()),
         asyncio.create_task(app.restart_event.wait())],
        return_when=asyncio.FIRST_COMPLETED)
    restarting = app.restart_event.is_set() and not stop.is_set()
    print("restarting..." if restarting else "shutting down...", flush=True)
    await app.stop()
    from .server.supervisor import EXIT_RESTART
    return EXIT_RESTART if restarting else 0


def main(argv=None) -> int:
    import sys
    args = build_parser().parse_args(argv)
    if args.watchdog:
        from .server.supervisor import run_supervised
        child = [sys.executable, "-m", "easydarwin_tpu"] + [
            a for a in (sys.argv[1:] if argv is None else argv)
            if a not in ("-w", "--watchdog")]
        return run_supervised(child)
    cfg = config_from_args(args)
    try:
        return asyncio.run(amain(cfg, args.exit_after_boot))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
