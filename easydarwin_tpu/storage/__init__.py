"""Erasure-coded fleet storage: finalized DVR/VOD assets sharded into
k data + m parity window shards striped across the cluster (ISSUE 20).

:mod:`.codec` holds the GF(256) stripe math (device matmul + host
oracle, receiver-path Gaussian reconstruct); :mod:`.service` holds the
node-local shard store, placement/push, fenced claims, scrub and repair.
"""

from .codec import StorageError, StripeCodec
from .service import (MANIFEST_VERSION, SHARD_KEY_PREFIX, StorageService,
                      shard_key, shard_name)

__all__ = ["StorageError", "StripeCodec", "StorageService",
           "SHARD_KEY_PREFIX", "MANIFEST_VERSION", "shard_key",
           "shard_name"]
