"""Erasure-coded fleet storage: the durable CDN-origin tier (ISSUE 20).

Every finalized DVR asset is sharded into ``k`` data + ``m`` parity
*window shards* per track: data shard ``j`` of stripe ``s`` is the raw
spill blob of the stripe's ``j``-th window (byte-identical to what
``/api/v1/dvrwindow`` serves), parity shards are the
:class:`~..storage.codec.StripeCodec` device matmuls.  Placement rides
the capacity-weighted HashRing over the live lease set — shard key
``{asset}/t{track}/s{stripe}.{idx}`` — and ownership is materialized as
fenced ``Shard:{asset}/...`` records written through the cluster tick
(the claim drain), so a zombie ex-holder's stale writes lose exactly
like stream claims do.

Reads are transparent: the spill read chain (local file → live peer →
``restore``) ends here — a window blob is served from the local shard
file when this node holds it, otherwise the stripe is gathered from any
``k`` survivors and the missing rows are solved back byte-exactly
(``storage_reconstructs_total``).  Background **scrub** re-verifies
local shards against the manifest crc32s and — when a stripe's data
shards are all local — re-derives parity through the host GF oracle;
**repair** watches the fenced shard records for dead holders and
re-materializes orphaned shards onto the ring successor as a re-keyed
matmul/solve over survivors (``storage_repairs_total`` +
``storage_repair_bytes_total``), not a byte copy.

The manifest (``manifest.json`` per asset, replicated alongside every
pushed shard) carries the stripe geometry, per-shard lengths + crc32s,
the store-time holder map, and the asset's full DVR meta/index document
— which is what lets ``/api/v1/dvrmeta`` answer for an asset whose
recording node is already dead: any shard holder can bootstrap a
replay.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
import zlib

from .. import obs
from ..cluster.placement import SHARD_KEY_PREFIX, shard_key
from ..protocol.sdp import _norm
from ..utils.paths import confined_subpath
from .codec import StorageError, StripeCodec

MANIFEST_VERSION = 1


def shard_name(track: int, stripe: int, idx: int) -> str:
    return f"t{int(track)}/s{int(stripe)}.{int(idx)}"


class StorageService:
    """One node's shard store + scrub/repair workers + restore reads."""

    #: local shards crc-verified per scrub tick (incremental cursor —
    #: a big store must not stall the sweep loop)
    SCRUB_BATCH = 32

    def __init__(self, root: str, node_id: str, *, k: int = 4,
                 m: int = 2, use_device: bool = True,
                 error_log=None):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.node_id = str(node_id)
        self.codec = StripeCodec(k, m, use_device=use_device)
        self.k, self.m = self.codec.k, self.codec.m
        self.error_log = error_log
        # -- cluster hooks (all optional: None = single-node store) --
        #: callable() -> dict[node_id, lease_meta] of LIVE nodes
        self.peer_nodes = None
        #: callable(nodes_dict) -> HashRing (capacity-weighted when the
        #: fleet publishes capacities — cluster.placement ring())
        self.ring_for = None
        #: callable(node_meta, asset, name, payload, manifest_json)
        #: -> bool — blocking HTTP push of one shard to a peer
        self.push_shard = None
        #: callable(node_meta, asset, name) -> bytes | None — blocking
        #: HTTP fetch of one shard from a peer
        self.fetch_shard = None
        #: callable(node_meta, asset) -> dict | None — blocking HTTP
        #: fetch of a peer's manifest
        self.fetch_manifest = None
        # -- state --
        self._lock = threading.Lock()
        self._manifests: dict[str, dict] = {}
        #: fenced claims awaiting the cluster tick's drain:
        #: [(redis key, record dict)]
        self._pending_claims: list[tuple[str, dict]] = []
        #: repair jobs awaiting a worker: {(asset, name)}
        self._repair_queue: list[tuple[str, str]] = []
        self._repair_inflight: set[tuple[str, str]] = set()
        self._pool = None
        self._scrub_cursor: list[tuple[str, str]] = []
        self._closed = False
        #: one solve serves the whole stripe: {(asset, tid, s, gen):
        #: {data_idx: blob}} — a replay walking a timeline hits every
        #: missing window of a stripe back-to-back, so the sibling
        #: windows ride the first reconstruct instead of re-gathering
        #: and re-solving (FIFO-bounded; gen key retires stale entries)
        self._stripe_cache: dict[tuple, dict[int, bytes]] = {}
        self._stripe_cache_max = 8
        #: confined_subpath → realpath() is measurably hot on the
        #: reconstruct read path; path confinement is stable, so cache
        #: both asset→dir and (dir, shard name)→file resolutions
        self._dir_cache: dict = {}
        # -- stats (bench/tests read these; metrics are the fleet view)
        self.stored_assets = 0
        self.shards_local = 0
        self.shards_pushed = 0
        self.push_failures = 0
        self.reconstructs = 0
        self.reconstruct_failures = 0
        self.repairs = 0
        self.repair_bytes = 0
        self.scrub_errors = 0
        self.scrubbed = 0

    # ------------------------------------------------------------ geometry
    def _dir_for(self, asset: str) -> str | None:
        key = _norm(asset)
        try:
            return self._dir_cache[key]
        except KeyError:
            pass
        p = confined_subpath(self.root, key)
        if len(self._dir_cache) >= 1024:
            self._dir_cache.clear()
        self._dir_cache[key] = p
        return p

    def _placement_target(self, ring, key: str, name: str) -> str:
        """Distinct-node-per-stripe placement: rank the STRIPE on the
        capacity-weighted ring and deal shard ``idx`` round-robin down
        the candidate list — a fleet at least ``k+m`` wide then loses
        at most ONE shard of any stripe per node death, which is
        exactly what ``m`` parity rows insure against."""
        stem, _, idx_s = name.rpartition(".")
        try:
            idx = int(idx_s)
        except ValueError:
            idx = 0
        rank = ring.rank(f"{key}/{stem}")
        if not rank:
            return self.node_id
        return rank[idx % len(rank)]

    def _shard_path(self, asset: str, name: str) -> str | None:
        adir = self._dir_for(asset)
        if adir is None:
            return None
        ck = (adir, name)
        try:
            return self._dir_cache[ck]      # type: ignore[index]
        except KeyError:
            pass
        p = confined_subpath(adir, name)
        if len(self._dir_cache) >= 1024:
            self._dir_cache.clear()
        self._dir_cache[ck] = p             # type: ignore[index]
        return p

    # ------------------------------------------------------------ manifest
    def manifest(self, asset: str) -> dict | None:
        """The asset's manifest — memory cache, then disk."""
        key = _norm(asset)
        with self._lock:
            doc = self._manifests.get(key)
        if doc is not None:
            return doc
        adir = self._dir_for(asset)
        if adir is None:
            return None
        try:
            with open(os.path.join(adir, "manifest.json"),
                      encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return None
        if not isinstance(doc, dict) \
                or doc.get("version") != MANIFEST_VERSION:
            return None
        with self._lock:
            self._manifests[key] = doc
        return doc

    def _write_manifest(self, asset: str, doc: dict) -> bool:
        adir = self._dir_for(asset)
        if adir is None:
            return False
        os.makedirs(adir, exist_ok=True)
        tmp = os.path.join(adir, "manifest.json.tmp")
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, separators=(",", ":"))
            os.replace(tmp, os.path.join(adir, "manifest.json"))
        except OSError:
            return False
        with self._lock:
            self._manifests[_norm(asset)] = doc
        return True

    def meta_doc(self, asset: str) -> dict | None:
        """The asset's DVR meta/index document carried by the manifest —
        the ``/api/v1/dvrmeta`` fallback that answers for a DEAD
        recording node (ISSUE 20 satellite: any shard holder can
        bootstrap a fully-remote replay)."""
        man = self.manifest(asset)
        if man is None:
            man = self._sync_manifest(asset)
        doc = (man or {}).get("dvr")
        return doc if isinstance(doc, dict) else None

    # --------------------------------------------------------------- store
    def store_asset(self, path: str, dvr) -> dict | None:
        """Shard one finalized asset (the ``DvrManager.on_finalize``
        hook): encode every track's windows into k+m stripes, keep the
        ring-assigned local shards, push the rest to their holders, and
        queue one fenced ``Shard:`` claim per shard.  A push failure
        keeps the shard local (the manifest holder map records reality,
        and repair re-places it later) — finalize never loses bytes."""
        key = _norm(path)
        doc = dvr.meta_doc(key)
        if doc is None or not isinstance(doc.get("tracks"), dict):
            return None
        adir = self._dir_for(key)
        if adir is None:
            return None
        nodes = {}
        if self.peer_nodes is not None:
            try:
                nodes = dict(self.peer_nodes() or {})
            except Exception:
                nodes = {}
        ring_nodes = nodes if nodes else {self.node_id: {}}
        ring = (self.ring_for(ring_nodes) if self.ring_for is not None
                else None)
        try:
            gen = int((doc.get("meta") or {}).get("gen", 0))
        except (TypeError, ValueError):
            gen = 0
        # fresh tree per generation: a re-recorded asset's stale shards
        # must never mix with the new stripes
        if os.path.isdir(adir):
            shutil.rmtree(adir, ignore_errors=True)
        man = {"version": MANIFEST_VERSION, "path": key, "gen": gen,
               "k": self.k, "m": self.m, "tracks": {},
               "holders": {}, "dvr": doc}
        shards: list[tuple[str, int, bytes]] = []   # (name, idx, payload)
        for tid_s, idx_doc in doc["tracks"].items():
            try:
                tid = int(tid_s)
            except (TypeError, ValueError):
                continue
            wins = sorted(int(r["win"]) for r in
                          (idx_doc.get("windows") or ())
                          if isinstance(r, dict) and "win" in r)
            if not wins:
                continue
            trec = {"wins": wins, "stripes": []}
            for s in range(0, (len(wins) + self.k - 1) // self.k):
                grp = wins[s * self.k:(s + 1) * self.k]
                blobs = []
                for w in grp:
                    b = dvr.window_blob(key, tid, w)
                    blobs.append(b or b"")
                blobs += [b""] * (self.k - len(blobs))
                parity = self.codec.parity(blobs)
                srec = {"lens": [len(b) for b in blobs],
                        "crcs": [zlib.crc32(b) & 0xFFFFFFFF
                                 for b in blobs],
                        "pcrcs": [zlib.crc32(p) & 0xFFFFFFFF
                                  for p in parity],
                        "width": max([len(b) for b in blobs] + [1])}
                trec["stripes"].append(srec)
                for j, b in enumerate(blobs):
                    if b:
                        shards.append((shard_name(tid, s, j), j, b))
                for p, pb in enumerate(parity):
                    shards.append(
                        (shard_name(tid, s, self.k + p), self.k + p, pb))
            man["tracks"][str(tid)] = trec
        if not shards:
            return None
        man_json = json.dumps(man, separators=(",", ":"))
        placed = {"data": 0, "parity": 0}
        for name, idx, payload in shards:
            target = self.node_id
            if ring is not None and len(ring_nodes) > 1:
                target = self._placement_target(ring, key, name)
            kind = "data" if idx < self.k else "parity"
            if target != self.node_id and self.push_shard is not None:
                ok = False
                try:
                    ok = bool(self.push_shard(
                        ring_nodes.get(target) or {}, key, name,
                        payload, man_json))
                except Exception:
                    ok = False
                if not ok:
                    self.push_failures += 1
                    target = self.node_id       # keep it: never lose bytes
            if target == self.node_id:
                if not self._write_shard(key, name, payload):
                    continue
                self.shards_local += 1
            else:
                self.shards_pushed += 1
            obs.STORAGE_SHARDS.inc(kind=kind)
            placed[kind] += 1
            man["holders"][name] = target
            self._queue_claim(key, name, target)
        self._write_manifest(key, man)
        self.stored_assets += 1
        obs.EVENTS.emit("storage.store", stream=key, asset=key,
                        shards=placed["data"] + placed["parity"],
                        parity=placed["parity"])
        return man

    def _write_shard(self, asset: str, name: str, payload: bytes) -> bool:
        p = self._shard_path(asset, name)
        if p is None:
            return False
        try:
            os.makedirs(os.path.dirname(p), exist_ok=True)
            tmp = p + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(payload)
            os.replace(tmp, p)
        except OSError:
            return False
        return True

    def _queue_claim(self, asset: str, name: str, holder: str) -> None:
        with self._lock:
            self._pending_claims.append(
                (shard_key(asset, name), {"node": holder}))

    def pending_claims(self) -> list[tuple[str, dict]]:
        """Drain the fenced-claim queue (the cluster tick writes these
        with freshly minted tokens — storage itself never touches
        redis)."""
        with self._lock:
            out, self._pending_claims = self._pending_claims, []
        return out

    # ---------------------------------------------------------- peer faces
    def serve_shard(self, asset: str, name: str) -> bytes | None:
        """One local shard's payload (the REST ``/api/v1/shard`` body),
        crc-verified against the manifest — corrupt bytes are counted,
        quarantined and never shipped."""
        payload = self._read_local(asset, name)
        return payload

    def receive_shard(self, asset: str, name: str, payload: bytes,
                      manifest_doc: dict | None) -> bool:
        """A peer pushed one shard at store/repair time: adopt the
        manifest (first write wins per gen; a newer gen replaces), crc-
        verify the payload against it, persist, queue our claim."""
        key = _norm(asset)
        if manifest_doc is not None:
            cur = self.manifest(key)
            try:
                new_gen = int(manifest_doc.get("gen", 0))
            except (TypeError, ValueError):
                return False
            if cur is None or int(cur.get("gen", -1)) != new_gen:
                adir = self._dir_for(key)
                if adir is not None and os.path.isdir(adir) \
                        and cur is not None \
                        and int(cur.get("gen", -1)) < new_gen:
                    shutil.rmtree(adir, ignore_errors=True)
                    with self._lock:
                        self._manifests.pop(key, None)
                if not self._write_manifest(key, manifest_doc):
                    return False
        man = self.manifest(key)
        if man is None:
            return False
        want = self._expected_crc(man, name)
        if want is None \
                or (zlib.crc32(payload) & 0xFFFFFFFF) != want:
            return False
        if not self._write_shard(key, name, payload):
            return False
        self.shards_local += 1
        self._queue_claim(key, name, self.node_id)
        return True

    @staticmethod
    def _parse_name(name: str) -> tuple[int, int, int] | None:
        try:
            tpart, spart = name.split("/", 1)
            tid = int(tpart[1:])
            stripe_s, idx_s = spart[1:].split(".", 1)
            return tid, int(stripe_s), int(idx_s)
        except (ValueError, IndexError):
            return None

    def _expected_crc(self, man: dict, name: str) -> int | None:
        parsed = self._parse_name(name)
        if parsed is None:
            return None
        tid, stripe, idx = parsed
        trec = (man.get("tracks") or {}).get(str(tid))
        if not isinstance(trec, dict):
            return None
        stripes = trec.get("stripes") or []
        if not 0 <= stripe < len(stripes):
            return None
        srec = stripes[stripe]
        try:
            if idx < int(man.get("k", self.k)):
                return int(srec["crcs"][idx])
            return int(srec["pcrcs"][idx - int(man.get("k", self.k))])
        except (KeyError, IndexError, TypeError, ValueError):
            return None

    def _read_local(self, asset: str, name: str) -> bytes | None:
        """Local shard bytes, crc-verified.  A mismatch counts a scrub
        error, quarantines the file and queues repair — today's
        truncated read is tomorrow's background fix."""
        p = self._shard_path(asset, name)
        if p is None or not os.path.isfile(p):
            return None
        try:
            with open(p, "rb") as fh:
                payload = fh.read()
        except OSError:
            return None
        man = self.manifest(asset)
        want = self._expected_crc(man, name) if man else None
        if want is not None \
                and (zlib.crc32(payload) & 0xFFFFFFFF) != want:
            self._note_corrupt(asset, name, p)
            return None
        return payload

    def _note_corrupt(self, asset: str, name: str, path: str) -> None:
        self.scrub_errors += 1
        obs.STORAGE_SCRUB_ERRORS.inc()
        obs.EVENTS.emit("storage.scrub_error", level="error",
                        stream=asset, asset=asset, shard=name)
        try:
            os.unlink(path)
        except OSError:
            pass
        with self._lock:
            if (asset, name) not in self._repair_inflight:
                self._repair_queue.append((_norm(asset), name))

    # -------------------------------------------------------------- restore
    def restore_window(self, path: str, track: int,
                       win: int) -> bytes | None:
        """The spill chain's last resort (BLOCKING — helper threads
        only): the raw window blob from the local shard file, or a
        byte-exact reconstruct from any k surviving shards of its
        stripe.  None = beyond the parity budget (the failure already
        counted loudly)."""
        key = _norm(path)
        man = self.manifest(key) or self._sync_manifest(key)
        if man is None:
            return None
        trec = (man.get("tracks") or {}).get(str(int(track)))
        if not isinstance(trec, dict):
            return None
        wins = trec.get("wins") or []
        try:
            pos = wins.index(int(win))
        except ValueError:
            return None
        k = int(man.get("k", self.k))
        s, j = divmod(pos, k)
        name = shard_name(int(track), s, j)
        # stripe cache first: one gather+solve serves the WHOLE stripe
        # (solved rows AND the survivors it read), so a degraded replay
        # touches each shard once, like a healthy one
        ck = (key, int(track), s, int(man.get("gen", 0)))
        with self._lock:
            cached = self._stripe_cache.get(ck)
        if cached is not None and j in cached:
            self.reconstructs += 1
            return cached[j]
        local = self._read_local(key, name)
        if local is not None:
            return local
        try:
            srec = (trec.get("stripes") or [])[s]
            lens = [int(x) for x in srec["lens"]]
        except (IndexError, KeyError, TypeError, ValueError):
            return None
        present = self._gather_stripe(key, man, int(track), s, lens,
                                      skip=j)
        try:
            out = self.codec.reconstruct(
                present, lens, asset=f"{key}/{name}",
                crcs=[int(x) for x in srec.get("crcs") or ()] or None)
        except StorageError as e:
            self.reconstruct_failures += 1
            if self.error_log:
                self.error_log.error(f"storage restore failed: {e}")
            return None
        self.reconstructs += 1
        entry = dict(out)
        for i, blob in present.items():
            if i < k:                   # survivors ride along (exact
                entry[i] = blob         # blob bytes, crc-verified)
        with self._lock:
            while len(self._stripe_cache) >= self._stripe_cache_max:
                self._stripe_cache.pop(next(iter(self._stripe_cache)))
            self._stripe_cache[ck] = entry
        return out.get(j)

    def _gather_stripe(self, asset: str, man: dict, tid: int, s: int,
                       lens: list[int], *, skip: int) -> dict[int, bytes]:
        """Every shard of one stripe this node can lay hands on: local
        files first, then the manifest's holders, then a live-peer
        sweep.  Stops fetching parity once enough rows survive."""
        k, m = int(man.get("k", self.k)), int(man.get("m", self.m))
        present: dict[int, bytes] = {}
        nodes = {}
        if self.peer_nodes is not None:
            try:
                nodes = dict(self.peer_nodes() or {})
            except Exception:
                nodes = {}
        holders = man.get("holders") or {}
        missing_data = 0
        for idx in range(k):
            if idx == skip and idx < k and lens[idx] > 0:
                missing_data += 1
                continue                   # the one we are rebuilding
            if idx < len(lens) and lens[idx] == 0:
                continue                   # tail padding: known-zero
            payload = self._fetch_any(asset, shard_name(tid, s, idx),
                                      nodes, holders)
            if payload is not None:
                present[idx] = payload
            else:
                missing_data += 1
        got_parity = 0
        for p in range(m):
            if got_parity >= missing_data:
                break
            payload = self._fetch_any(asset, shard_name(tid, s, k + p),
                                      nodes, holders)
            if payload is not None:
                present[k + p] = payload
                got_parity += 1
        return present

    def _fetch_any(self, asset: str, name: str, nodes: dict,
                   holders: dict) -> bytes | None:
        local = self._read_local(asset, name)
        if local is not None:
            return local
        if self.fetch_shard is None:
            return None
        man = self.manifest(asset)
        order = []
        h = holders.get(name)
        if h and h in nodes and h != self.node_id:
            order.append(h)
        order += [n for n in nodes
                  if n != self.node_id and n not in order]
        for node in order:
            try:
                payload = self.fetch_shard(nodes.get(node) or {},
                                           asset, name)
            except Exception:
                payload = None
            if not payload:
                continue
            want = self._expected_crc(man, name) if man else None
            if want is not None \
                    and (zlib.crc32(payload) & 0xFFFFFFFF) != want:
                continue                   # corrupt peer copy: keep looking
            return payload
        return None

    def _sync_manifest(self, asset: str) -> dict | None:
        """No local manifest: sweep live peers for one (BLOCKING)."""
        if self.fetch_manifest is None or self.peer_nodes is None:
            return None
        try:
            nodes = dict(self.peer_nodes() or {})
        except Exception:
            return None
        for node, meta in nodes.items():
            if node == self.node_id:
                continue
            try:
                doc = self.fetch_manifest(meta or {}, asset)
            except Exception:
                doc = None
            if isinstance(doc, dict) \
                    and doc.get("version") == MANIFEST_VERSION:
                self._write_manifest(_norm(asset), doc)
                return doc
        return None

    # ----------------------------------------------------------- scrubbing
    def scrub_tick(self, *, batch: int | None = None) -> int:
        """Verify up to ``batch`` local shards against the manifest
        crc32s; for parity shards whose stripe's data shards are ALL
        local, also re-derive the row through the host GF oracle.
        Corruption counts ``storage_scrub_errors_total``, quarantines
        the file and queues repair.  Returns shards verified."""
        if self._closed:
            return 0
        n = batch or self.SCRUB_BATCH
        if not self._scrub_cursor:
            self._scrub_cursor = self._walk_shards()
        done = 0
        while self._scrub_cursor and done < n:
            asset, name = self._scrub_cursor.pop()
            man = self.manifest(asset)
            if man is None:
                continue
            payload = self._read_local(asset, name)   # counts crc errors
            done += 1
            self.scrubbed += 1
            if payload is None:
                continue
            parsed = self._parse_name(name)
            if parsed is None:
                continue
            tid, s, idx = parsed
            k = int(man.get("k", self.k))
            if idx < k:
                continue
            # host-oracle parity verify when the whole stripe is local
            try:
                srec = man["tracks"][str(tid)]["stripes"][s]
                lens = [int(x) for x in srec["lens"]]
            except (KeyError, IndexError, TypeError, ValueError):
                continue
            blobs = []
            for j in range(k):
                if lens[j] == 0:
                    blobs.append(b"")
                    continue
                b = self._read_local(asset, shard_name(tid, s, j))
                if b is None:
                    blobs = None
                    break
                blobs.append(b)
            if blobs is None:
                continue
            from ..relay.fec import coeff_rows, gf_matmul
            import numpy as np
            width = max([len(b) for b in blobs] + [1])
            rows = np.zeros((k, width), np.uint8)
            for j, b in enumerate(blobs):
                if b:
                    rows[j, :len(b)] = np.frombuffer(b, np.uint8)
            host = gf_matmul(coeff_rows(range(k), idx - k + 1), rows)
            if host[idx - k, :len(payload)].tobytes() != payload:
                p = self._shard_path(asset, name)
                self._note_corrupt(asset, name, p or "")
        return done

    def _walk_shards(self) -> list[tuple[str, str]]:
        out = []
        for dirpath, _dirs, files in os.walk(self.root):
            for f in files:
                if not f.startswith("s") or "." not in f:
                    continue
                full = os.path.join(dirpath, f)
                rel = os.path.relpath(full, self.root)
                parts = rel.split(os.sep)
                if len(parts) < 2 or not parts[-2].startswith("t"):
                    continue
                asset = "/" + "/".join(parts[:-2])
                out.append((asset, f"{parts[-2]}/{f}"))
        return out

    # -------------------------------------------------------------- repair
    def repair_scan(self, live_nodes: dict,
                    shard_records: dict[str, dict]) -> int:
        """The cluster tick hands us the live lease set and the parsed
        fenced ``Shard:`` records: every shard whose recorded holder is
        DEAD and whose ring successor over the survivors is THIS node
        gets queued for re-materialization.  Returns jobs queued."""
        if self._closed or not shard_records:
            return 0
        ring = (self.ring_for(live_nodes) if self.ring_for is not None
                else None)
        queued = 0
        for key, rec in shard_records.items():
            holder = rec.get("node") if isinstance(rec, dict) else None
            if holder in live_nodes:
                continue
            rel = key[len(SHARD_KEY_PREFIX):]
            asset, _, name = rel.rpartition("/t")
            if not asset or not name:
                continue
            asset, name = "/" + asset, "t" + name
            if ring is not None:
                # same stripe-ranked placement store_asset used, over
                # the survivor ring: the shard's new home elects itself
                if self._placement_target(ring, asset, name) \
                        != self.node_id:
                    continue
            p = self._shard_path(asset, name)
            if p is not None and os.path.isfile(p):
                # already local (e.g. the push failed at store time and
                # the finalizer kept it): just re-claim under our name
                self._queue_claim(asset, name, self.node_id)
                continue
            job = (_norm(asset), name)
            with self._lock:
                if job in self._repair_inflight:
                    continue
                self._repair_inflight.add(job)
            self._executor().submit(self._repair_job, *job)
            queued += 1
        return queued

    def _executor(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                2, thread_name_prefix="storage")
        return self._pool

    def store_async(self, path: str, dvr):
        """Submit :meth:`store_asset` to the worker pool (the finalize
        hook runs on the event loop; sharding + pushes are blocking)."""
        return self._executor().submit(self.store_asset, path, dvr)

    def restore_async(self, path: str, track: int, win: int):
        """Submit :meth:`restore_window` to the worker pool (the spill
        read chain calls inline on the pump and polls the future)."""
        return self._executor().submit(self.restore_window, path,
                                       int(track), int(win))

    def repair_now(self, asset: str, name: str) -> int | None:
        """Synchronously re-materialize one shard, with full repair
        accounting (bench/tests; the background path is
        :meth:`repair_scan` → worker).  Returns bytes written, or None
        when the stripe cannot be repaired yet."""
        nbytes = self._repair_one(asset, name)
        if nbytes is None:
            return None
        self.repairs += 1
        self.repair_bytes += nbytes
        parsed = self._parse_name(name)
        kind = "parity" if parsed and parsed[2] >= self.k else "data"
        obs.STORAGE_REPAIRS.inc(kind=kind)
        obs.STORAGE_REPAIR_BYTES.inc(nbytes)
        obs.STORAGE_SHARDS.inc(kind=kind)
        obs.EVENTS.emit("storage.repair", stream=asset, asset=asset,
                        shards=1, shard=name)
        return nbytes

    def _repair_job(self, asset: str, name: str) -> None:
        try:
            self.repair_now(asset, name)
        except Exception as e:
            if self.error_log:
                self.error_log.error(f"storage repair {asset}/{name}: "
                                     f"{e!r}")
        finally:
            with self._lock:
                self._repair_inflight.discard((asset, name))

    def _repair_one(self, asset: str, name: str) -> int | None:
        """Re-materialize one shard from survivors: a missing DATA shard
        is a gf_solve reconstruct; a missing PARITY shard is the
        Vandermonde matmul re-run over the k data blobs — math, not a
        byte copy."""
        man = self.manifest(asset) or self._sync_manifest(asset)
        if man is None:
            return None
        parsed = self._parse_name(name)
        if parsed is None:
            return None
        tid, s, idx = parsed
        k = int(man.get("k", self.k))
        try:
            srec = man["tracks"][str(tid)]["stripes"][s]
            lens = [int(x) for x in srec["lens"]]
        except (KeyError, IndexError, TypeError, ValueError):
            return None
        if idx < k:
            if lens[idx] == 0:
                return None                # tail padding: nothing to fix
            present = self._gather_stripe(asset, man, tid, s, lens,
                                          skip=idx)
            out = self.codec.reconstruct(
                present, lens, asset=f"{asset}/{name}",
                crcs=[int(x) for x in srec.get("crcs") or ()] or None)
            self.reconstructs += 1
            payload = out.get(idx)
        else:
            nodes = {}
            if self.peer_nodes is not None:
                try:
                    nodes = dict(self.peer_nodes() or {})
                except Exception:
                    nodes = {}
            blobs = []
            for j in range(k):
                if lens[j] == 0:
                    blobs.append(b"")
                    continue
                b = self._fetch_any(asset, shard_name(tid, s, j), nodes,
                                    man.get("holders") or {})
                if b is None:
                    return None            # data gone too: repair later
                blobs.append(b)
            payload = self.codec.parity(blobs)[idx - k]
        if not payload:
            return None
        if not self._write_shard(asset, name, payload):
            return None
        self.shards_local += 1
        self._queue_claim(asset, name, self.node_id)
        return len(payload)

    # ----------------------------------------------------------------- misc
    def stats(self) -> dict:
        return {
            "assets": self.stored_assets,
            "shards_local": self.shards_local,
            "shards_pushed": self.shards_pushed,
            "push_failures": self.push_failures,
            "reconstructs": self.reconstructs,
            "reconstruct_failures": self.reconstruct_failures,
            "repairs": self.repairs,
            "repair_bytes": self.repair_bytes,
            "scrub_errors": self.scrub_errors,
            "scrubbed": self.scrubbed,
            "oracle_mismatches": self.codec.oracle_mismatches,
            "host_fallback": self.codec.host_fallback,
            "device_passes": self.codec.device_passes,
        }

    def close(self) -> None:
        self._closed = True
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._pool = None


__all__ = ["StorageService", "SHARD_KEY_PREFIX", "shard_key",
           "shard_name", "MANIFEST_VERSION"]
