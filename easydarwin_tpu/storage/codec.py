"""GF(256) stripe codec: spill window blobs → k data + m parity shards.

The erasure math is the PR 11 reliability tier's, reused verbatim: a
stripe is ``k`` consecutive spill-window blobs of one track, zero-padded
on the byte axis to the widest blob, and the ``m`` parity shards are the
Vandermonde rows ``C[p, i] = α^(i·p)`` (``relay.fec.coeff_rows`` over
deltas ``0..k-1``) matmul'd against that ``[k, B]`` matrix.  The matmul
runs on the device (``models.relay_pipeline.fec_parity_window_step`` —
the SAME jitted kernel that computes wire FEC parity) and every row is
compared against the independent host oracle ``relay.fec.gf_matmul``
through the ``_install_segment`` discipline: a mismatch counts
``fec_parity_oracle_mismatch_total``, latches this codec onto host
parity and emits one ``storage.host_fallback`` — a kernel bug degrades
the tier to host math, it never persists an unchecked byte.

Reconstruction is the receiver path's Gaussian solve: XOR the surviving
data rows' contributions out of the surviving parity rows (syndromes),
then ``gf_solve`` the Vandermonde subsystem for the missing rows —
preferring the LOWEST parity indices, which form a true Vandermonde
system and always solve.  More than ``m`` missing shards, or a singular
arbitrary-index subset, raises :class:`StorageError` and counts
``storage_reconstructs_total{result="failed"}`` — a read that cannot be
byte-exact fails loudly, never silently partial.
"""

from __future__ import annotations

import time

import numpy as np

from .. import obs
from ..relay.fec import coeff_for_indices, coeff_rows, gf_matmul, gf_solve


class StorageError(RuntimeError):
    """A stripe that cannot be encoded or byte-exactly reconstructed."""


class StripeCodec:
    """Encode/reconstruct one ``k + m`` stripe of window blobs."""

    def __init__(self, k: int, m: int, *, use_device: bool = True):
        if not (1 <= k and 1 <= m <= 8):
            raise ValueError(f"bad stripe geometry k={k} m={m}")
        self.k = int(k)
        self.m = int(m)
        self.use_device = bool(use_device)
        #: latched on the first device/oracle divergence: host parity
        #: from then on (same semantics as StreamFec.host_fallback)
        self.host_fallback = False
        self.oracle_mismatches = 0
        self.device_passes = 0

    # ------------------------------------------------------------- encode
    def parity(self, blobs: list[bytes]) -> list[bytes]:
        """The ``m`` parity shard payloads over ``k`` data blobs (short
        stripes pad with ``b""`` entries).  Each payload is the stripe
        width ``B = max(len(blob))`` — the padded region's parity is
        zero by construction (gf_mul(0, ·) = 0), so trimming is free."""
        if len(blobs) != self.k:
            raise StorageError(
                f"stripe wants {self.k} blobs, got {len(blobs)}")
        from ..ops.staging import pow2
        width = max([len(b) for b in blobs] + [1])
        b_pad = pow2(width, 256)
        rows = np.zeros((self.k, b_pad), np.uint8)
        for i, b in enumerate(blobs):
            if b:
                rows[i, :len(b)] = np.frombuffer(b, np.uint8)
        r_pad = pow2(self.m, 1)
        coeff = coeff_rows(range(self.k), r_pad)
        host = gf_matmul(coeff, rows)
        parity = host
        if self.use_device and not self.host_fallback:
            dev = None
            try:
                from ..models.relay_pipeline import fec_parity_window_step
                t0 = time.perf_counter_ns()
                dev = np.asarray(fec_parity_window_step(rows, coeff))
                obs.TPU_PASS_SECONDS.observe(
                    (time.perf_counter_ns() - t0) / 1e9,
                    stage="storage_parity")
                obs.TPU_H2D_BYTES.inc(rows.nbytes + coeff.nbytes)
                obs.TPU_D2H_BYTES.inc(dev.nbytes)
                self.device_passes += 1
            except Exception:
                dev = None               # no backend: host parity serves
            if dev is not None and not np.array_equal(dev, host):
                # the _install_segment discipline: count, discard the
                # device result, latch host parity — never persist an
                # unchecked row
                self.oracle_mismatches += 1
                obs.FEC_PARITY_ORACLE_MISMATCH.inc()
                if not self.host_fallback:
                    self.host_fallback = True
                    obs.EVENTS.emit("storage.host_fallback", level="warn",
                                    mismatches=self.oracle_mismatches)
            elif dev is not None:
                parity = dev
        return [parity[p, :width].tobytes() for p in range(self.m)]

    # -------------------------------------------------------- reconstruct
    def reconstruct(self, present: dict[int, bytes], lens: list[int], *,
                    asset: str = "?",
                    crcs: list[int] | None = None) -> dict[int, bytes]:
        """Byte-exact blobs for every MISSING data index of one stripe.

        ``present`` maps shard index → payload: every surviving data
        shard (``idx < k``, exact blob bytes) plus surviving parity rows
        (``idx >= k``, stripe-width bytes).  ``lens`` are the k data
        blob lengths from the manifest.  Returns ``{data_idx: blob}``
        for each missing index; raises :class:`StorageError` (and
        counts the failure) when more than the surviving parity can
        solve, or the chosen coefficient subset is singular.

        The wide math is ONE matmul: invert the tiny ``[n, n]``
        Vandermonde subsystem (``gf_solve`` against I — eliminating the
        stripe-width rows directly costs ~2·n² scalar row ops over B
        bytes each), fold the inverse into a combined coefficient
        matrix over the stacked survivor rows, and apply it.  When
        ``crcs`` (the manifest's per-window crc32s) are given and the
        device is healthy, that matmul runs on the SAME jitted kernel
        that writes parity, oracle-checked end-to-end against the
        manifest crc32s: a mismatch counts, latches host fallback and
        recomputes with host math — the exact ``parity()`` discipline
        with the crc as the independent check."""
        k = self.k
        if len(lens) != k:
            raise StorageError(f"{asset}: manifest lens {len(lens)} != k")
        missing = [i for i in range(k) if i not in present]
        need = [i for i in missing if lens[i] > 0]
        out = {i: b"" for i in missing if lens[i] == 0}
        if not need:
            return out
        pav = sorted(i - k for i in present if i >= k)
        if len(need) > len(pav):
            obs.STORAGE_RECONSTRUCTS.inc(result="failed")
            obs.EVENTS.emit("storage.reconstruct", level="error",
                            asset=asset, missing=len(need),
                            parity=len(pav))
            raise StorageError(
                f"{asset}: {len(need)} data shards missing, only "
                f"{len(pav)} parity rows survive")
        # LOWEST surviving parity indices first: consecutive-from-0 rows
        # form a true Vandermonde system (always solvable); an arbitrary
        # subset can be singular, which gf_solve counts and reports
        n = len(need)
        idxs = pav[:n]
        ainv = gf_solve(coeff_for_indices(need, idxs),
                        np.eye(n, dtype=np.uint8), caller="storage")
        if ainv is None:
            obs.STORAGE_RECONSTRUCTS.inc(result="failed")
            obs.EVENTS.emit("storage.solve_singular", level="error",
                            asset=asset, missing=len(need))
            raise StorageError(
                f"{asset}: singular parity subset {idxs} for {need}")
        # stacked survivors [chosen parity rows ∥ surviving data rows];
        # D_need = A⁻¹·P ⊕ A⁻¹·C_known·D_known = [A⁻¹ | A⁻¹·C_k]·stack
        width = max([len(v) for i, v in present.items() if i >= k]
                    + [max(lens)])
        known = [i for i in range(k) if i in present and lens[i] > 0]
        ccomb = ainv
        if known:
            ccomb = np.concatenate(
                [ainv, gf_matmul(ainv, coeff_for_indices(known, idxs))],
                axis=1)
        bufs = [present[p + k] for p in idxs] \
            + [present[i] for i in known]
        if int(ccomb.max(initial=0)) <= 1:
            # single-loss stripes solve through parity row 0 — the XOR
            # row — so every combined coefficient is 0/1 and the apply
            # is pure XOR straight over the survivor buffers (RAID-5's
            # fast path): no stacked matrix, no table gathers
            solved = np.zeros((n, width), np.uint8)
            for r in range(n):
                for i in np.flatnonzero(ccomb[r]):
                    b = bufs[i]
                    solved[r, :len(b)] ^= np.frombuffer(b, np.uint8)
        else:
            surv = np.zeros((len(bufs), width), np.uint8)
            for j, b in enumerate(bufs):
                surv[j, :len(b)] = np.frombuffer(b, np.uint8)
            solved = self._wide_matmul(ccomb, surv, need, lens, crcs)
        for j, i in enumerate(need):
            out[i] = solved[j, :lens[i]].tobytes()
        obs.STORAGE_RECONSTRUCTS.inc(result="ok")
        obs.EVENTS.emit("storage.reconstruct", asset=asset,
                        missing=len(need))
        return out

    def _wide_matmul(self, ccomb: np.ndarray, surv: np.ndarray,
                     need: list[int], lens: list[int],
                     crcs: list[int] | None) -> np.ndarray:
        """``ccomb × surv`` on the device when the manifest crc32s can
        oracle-check the result; host ``gf_matmul`` otherwise (and on
        any divergence, with the parity-path mismatch accounting)."""
        if not (self.use_device and not self.host_fallback and crcs):
            return gf_matmul(ccomb, surv)
        import zlib
        from ..ops.staging import pow2
        dev = None
        try:
            from ..models.relay_pipeline import fec_parity_window_step
            rows = np.zeros((pow2(surv.shape[0], 1),
                             pow2(surv.shape[1], 256)), np.uint8)
            rows[:surv.shape[0], :surv.shape[1]] = surv
            coeff = np.zeros((pow2(ccomb.shape[0], 1), rows.shape[0]),
                             np.uint8)
            coeff[:ccomb.shape[0], :ccomb.shape[1]] = ccomb
            t0 = time.perf_counter_ns()
            dev = np.asarray(fec_parity_window_step(rows, coeff))
            obs.TPU_PASS_SECONDS.observe(
                (time.perf_counter_ns() - t0) / 1e9,
                stage="storage_reconstruct")
            obs.TPU_H2D_BYTES.inc(rows.nbytes + coeff.nbytes)
            obs.TPU_D2H_BYTES.inc(dev.nbytes)
        except Exception:
            dev = None                   # no backend: host math serves
        if dev is not None:
            ok = all((zlib.crc32(dev[j, :lens[i]].tobytes())
                      & 0xFFFFFFFF) == int(crcs[i])
                     for j, i in enumerate(need))
            if ok:
                self.device_passes += 1
                return dev[:, :surv.shape[1]]
            self.oracle_mismatches += 1
            obs.FEC_PARITY_ORACLE_MISMATCH.inc()
            if not self.host_fallback:
                self.host_fallback = True
                obs.EVENTS.emit("storage.host_fallback", level="warn",
                                mismatches=self.oracle_mismatches)
        return gf_matmul(ccomb, surv)


__all__ = ["StripeCodec", "StorageError"]
