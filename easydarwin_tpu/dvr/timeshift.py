"""Time-shift sessions: pause/rewind on live streams + spilled replay.

A ``TimeShiftSession`` is a ``PacedVodSession``-shaped citizen of the
shared VOD pacer (``VodPacerGroup.adopt``): each subscriber-track gets
its own ``StagedPacketRing``-backed relay stream that the pacer
block-fills from **spilled windows** — rows preserved verbatim from the
live ring, original src seq/ts/ssrc header bytes intact — while the
live head keeps relaying to everyone else.  The subscriber's existing
affine rewrite (ssrc / seq / ts rebase, latched when it joined live)
therefore produces wire bytes identical to what a live subscriber with
the same rewrite saw for the same ids.

The live ring is the HOT tail and the spill file the COLD tail of one
continuous absolute-id space: a window still inside the ring is sliced
straight out of it; an older one loads through ``SegmentCache.
get_packed`` (zero repack — a spill-file memcpy, LRU'd and HBM-eligible
like any VOD window).  **Catch-up**: when the time-shift cursor reaches
the live head and the backlog has drained to the player, the output
re-attaches to the live stream with ``bookmark = cursor`` — same ssrc,
contiguous seq, because src ids and the rewrite are both continuous
across the join (``dvr_catchup_joins_total``).

Finalized assets (instant stream-to-VOD) replay through the same class
with no live stream: the session is done when the spilled range has
been delivered.
"""

from __future__ import annotations

import time

import numpy as np

from .. import obs
from ..obs import EVENTS
from ..relay.stream import StreamSettings
from ..vod.session import VodStream
from ..vod.cache import CachedWindow, StagedPacketRing
from .spill import SpilledTrack, WindowRows, snapshot_window

#: ring slots per time-shift subscriber track (the VOD pacer's sizing
#: rationale: lookahead depth, not a live burst absorber)
SHIFT_RING_CAPACITY = 1024


class _ShiftTrack:
    """One subscriber-track of a time-shift session: spill/ring-fed
    paced ring + the catch-up join state machine."""

    def __init__(self, sess: "TimeShiftSession", track_id: int,
                 spilled: SpilledTrack, out, settings: StreamSettings,
                 start_id: int, live_stream=None):
        import dataclasses
        self.track_id = track_id
        self.spilled = spilled
        self.out = out
        self.live_stream = live_stream
        self.k = spilled.k
        self.cursor = int(start_id)
        if settings.ring_capacity > SHIFT_RING_CAPACITY:
            settings = dataclasses.replace(
                settings, ring_capacity=SHIFT_RING_CAPACITY)
        ring = StagedPacketRing(
            settings.ring_capacity,
            is_video=spilled.info.media_type == "video",
            codec=spilled.info.codec or None)
        self.stream = VodStream(spilled.info, settings, ring)
        self.stream.session_path = sess.path
        self.stream.audience_tier = "dvr"
        # the output's rewrite is PRESERVED: a live subscriber keeps its
        # latched base (seq/ts continuity through the shift and back); a
        # fresh subscriber latches from the first replayed packet
        out.bookmark = 0                 # shift ring ids start at 0
        self.stream.add_output(out)
        self.window: CachedWindow | None = None   # pinned cold window
        self.window_idx = -1
        self.joined = False
        self.done = spilled.win_lo is None and live_stream is None
        self.released = False
        self.gaps = 0                    # id hops over unspilled ranges
        self.last_arr = None             # newest served original arrival

    # ------------------------------------------------------------- helpers
    def _room(self) -> int:
        ring = self.stream.rtp_ring
        bm = self.out.bookmark
        base = ring.tail if bm is None else max(min(bm, ring.head),
                                                ring.tail)
        return ring.capacity - (ring.head - base) - 8

    def _delivered(self) -> bool:
        ring = self.stream.rtp_ring
        bm = self.out.bookmark
        return bm is not None and bm >= ring.head

    def _load_cold(self, win: int):
        rows = self.spilled.read_window(win)
        if rows is None:
            return None
        return CachedWindow.from_packed(
            None, rows.id_lo, rows.data, rows.length, rows.flags,
            rows.ts, seq=rows.seq, arrival=rows.arrival,
            restored=getattr(rows, "restored", False))

    def _rows_for(self, sess: "TimeShiftSession",
                  win: int) -> WindowRows | None:
        """Window ``win`` as parallel arrays: live-ring hot tail (ids
        still in the ring, sliced in place) or the cold spill via the
        segment cache (zero repack)."""
        lr = (self.live_stream.rtp_ring
              if self.live_stream is not None else None)
        if lr is not None and win * self.k >= lr.tail:
            hi = min((win + 1) * self.k, lr.head)
            if hi <= win * self.k:
                return None
            return snapshot_window(lr, win * self.k, hi)
        if self.window is not None and self.window_idx == win:
            w = self.window
        else:
            if self.window is not None:
                sess.pacer.cache.unpin(self.window)
                self.window = None
            w = sess.pacer.cache.get_packed(
                sess.asset_key, self.track_id, win, self._load_cold)
            if w is None:
                return None
            self.window = sess.pacer.cache.pin(w)
            self.window_idx = win
        if w.arrival is None:
            return None                  # not a spilled window (corrupt)
        return WindowRows(w.lo, w.data, w.length, w.flags, w.ts,
                          w.seq if w.seq is not None
                          else np.zeros(len(w.length), np.int32),
                          w.arrival)

    def _next_available(self, cur: int) -> int | None:
        """The next absolute id >= ``cur`` backed by data: the first
        indexed spill window past it, else the live ring tail."""
        cand = None
        for win in sorted(self.spilled.windows):
            rec = self.spilled.windows[win]
            if rec["id_lo"] + rec["n"] > cur:
                cand = max(rec["id_lo"], cur)
                break
        if cand is None and self.live_stream is not None:
            lr = self.live_stream.rtp_ring
            if lr.head > cur:
                cand = max(lr.tail, cur)
        return cand

    # ---------------------------------------------------------------- fill
    def fill(self, sess: "TimeShiftSession", now_ms: int,
             horizon_ms: float) -> None:
        while not self.joined and not self.done:
            lr = (self.live_stream.rtp_ring
                  if self.live_stream is not None else None)
            if lr is not None and self._delivered() \
                    and self._caught_up(sess, lr):
                # the replay clock has caught the real clock AND the
                # shift backlog has drained to the player: rejoin live
                # — under continuous ingest the cursor never literally
                # equals a still-advancing head, so the join condition
                # is schedule-based, not head-equality
                self._maybe_join(sess)
                return
            if self._room() < 96:
                return                   # wait for the player to drain
            end_id = lr.head if lr is not None else self.spilled_end()
            if self.cursor >= end_id:
                if lr is None:
                    self.done = self._delivered()
                return
            if (lr is not None and self.cursor >= lr.tail
                    and not sess.anchor_pending):
                # hot-tail cheap gate: peek the cursor packet's due
                # time BEFORE snapshotting the window — a cursor pacing
                # slower than the wake rate would otherwise copy and
                # discard up to k rows every single pump wake
                arr0 = float(lr.arrival[lr.slot(self.cursor)])
                if (sess.t0_ms + (arr0 - sess.anchor_arr) / sess.speed
                        > horizon_ms):
                    return
            rows = self._rows_for(sess, self.cursor // self.k)
            if rows is None or self.cursor >= rows.id_lo + rows.n:
                if rows is None and self.spilled.fetch_pending:
                    return               # peer fetch in flight: HOLD —
                    #                      hopping would skip a window
                    #                      that arrives next tick
                nxt = self._next_available(
                    max(self.cursor,
                        (self.cursor // self.k + 1) * self.k))
                if nxt is None or nxt <= self.cursor:
                    return               # nothing to serve yet
                self.gaps += 1
                self.cursor = nxt
                continue
            if self.cursor < rows.id_lo:
                # tail-clamped window (snapshot started above the grid
                # line): snap forward FIRST — filling from rel 0 while
                # advancing the cursor from below id_lo would re-serve
                # the same rows next iteration as fresh out-seqs
                self.gaps += 1
                self.cursor = rows.id_lo
            rel_lo = self.cursor - rows.id_lo
            if sess.anchor_pending:
                # resume whose pause-point arrival was unresolvable
                # (window evicted / audio-only): anchor on the first
                # packet actually served, so replay starts NOW instead
                # of an elapsed-recording-time silence
                sess.anchor_arr = float(rows.arrival[rel_lo])
                sess.anchor_pending = False
            dues = (sess.t0_ms
                    + (rows.arrival[rel_lo:] - sess.anchor_arr)
                    / sess.speed)
            n_due = int(np.searchsorted(dues, horizon_ms, side="right"))
            n_due = min(n_due, rows.n - rel_lo, self._room())
            if n_due <= 0:
                return
            sel = slice(rel_lo, rel_lo + n_due)
            due_ms = dues[:n_due]
            now_ns = time.perf_counter_ns()
            now_mono = time.monotonic() * 1000.0
            due_ns = (now_ns + np.maximum(due_ms - now_mono, 0.0)
                      * 1e6).astype(np.int64)
            ring = self.stream.rtp_ring
            ring.push_block(rows.data[sel], rows.length[sel],
                            due_ms.astype(np.int64), rows.flags[sel],
                            rows.seq[sel], rows.ts[sel],
                            arrival_ns=due_ns)
            self.cursor += n_due
            if n_due:
                self.last_arr = int(rows.arrival[rel_lo + n_due - 1])
            obs.VOD_PACKETS.inc(n_due, path="hot")
            sess.pacer.hot_pkts += n_due

    # ---------------------------------------------------------------- join
    def _caught_up(self, sess: "TimeShiftSession", lr) -> bool:
        """True when replaying the cursor packet would happen no later
        than live delivery would: ``due(cursor) <= arrival(cursor)``.
        A Speed>1 catch-up crosses this point; a deliberate 1× time
        shift (pause offset) never does and stays shifted — exactly
        the semantics the viewer asked for."""
        if self.cursor >= lr.head:
            return True                  # nothing left to replay at all
        if self.cursor < lr.tail:
            return False                 # still deep in the cold tail
        arr = float(lr.arrival[lr.slot(self.cursor)])
        due = sess.t0_ms + (arr - sess.anchor_arr) / sess.speed
        return due <= arr + 1.0

    def _maybe_join(self, sess: "TimeShiftSession") -> None:
        """Cursor reached the live head: once the shift backlog has
        drained to the player, re-attach to the live stream with
        ``bookmark = cursor``.  Ids and the affine rewrite are both
        continuous across the join, so the player sees the same ssrc
        and a contiguous seq — the gapless catch-up the acceptance
        pins."""
        if not self._delivered():
            return
        live = self.live_stream
        self.stream.remove_output(self.out)
        if self.cursor < live.rtp_ring.tail:
            # pathological: the ring evicted past us while we stalled —
            # rejoin at the tail (a seq jump the player sees as loss;
            # counted as a gap, never silent)
            self.gaps += 1
            self.cursor = live.rtp_ring.tail
        self.out.bookmark = self.cursor
        live.add_output(self.out)
        self.joined = True
        obs.DVR_CATCHUP_JOINS.inc()
        EVENTS.emit("dvr.catchup", stream=sess.path,
                    trace_id=self.stream.trace_id,
                    track=self.track_id, join_id=self.cursor)

    # ------------------------------------------------------------- retire
    def release(self, pacer) -> None:
        if self.released:
            return
        self.released = True
        if self.window is not None:
            pacer.cache.unpin(self.window)
            self.window = None
        if not self.joined:
            self.stream.remove_output(self.out)
        pacer.engine_drop(self.stream)

    def spilled_end(self) -> int:
        hi = self.spilled.win_hi
        if hi is None:
            return 0
        rec = self.spilled.windows[hi]
        return rec["id_lo"] + rec["n"]

    def position_arr(self) -> int | None:
        """Original arrival ms of the newest packet served (the pause
        bookmark a resume re-enters at)."""
        return self.last_arr


class TimeShiftSession:
    """Pause/rewind/replay session under the shared VOD pacer (see
    module docstring).  Duck-types the ``PacedVodSession`` surface the
    pacer's tick/retire consume."""

    ts_scale = 1.0

    def __init__(self, pacer, asset, outputs: dict[int, object], *,
                 live_session=None, start_npt: float | None = None,
                 start_ids: dict[int, int] | None = None,
                 speed: float = 1.0, path: str = "",
                 now_ms: int | None = None):
        """``asset`` is a DvrAsset (per-track ``SpilledTrack`` map +
        ``asset_key``); ``start_ids`` (absolute ids per track — the
        PAUSE-resume path) wins over ``start_npt`` (seek: the video
        track snaps to a keyframe, audio aligns on arrival time)."""
        self.pacer = pacer
        self.asset = asset
        self.asset_key = asset.asset_key
        self.file = asset                # pacer.retire closes this
        self.speed = max(speed, 0.01)
        self.path = path or asset.path
        self.done = False
        self.stopped = False
        self.frames_thinned = 0
        self.start_npt = start_npt or 0.0
        t = int(time.monotonic() * 1000) if now_ms is None else now_ms
        self.t0_ms = float(t)
        self._pkts_base = {id(o): o.packets_sent
                           for o in outputs.values()}
        self.tracks: list[_ShiftTrack] = []
        # -- resolve per-track start cursors + the arrival anchor ------
        cursors: dict[int, int] = {}
        anchor = None
        video_tid = None
        for tid, sp in asset.tracks.items():
            if tid in outputs and sp.info.media_type == "video":
                video_tid = tid
                break
        if start_ids:
            cursors = {tid: int(i) for tid, i in start_ids.items()}
            if video_tid in cursors:
                anchor = self._arrival_of(asset.tracks[video_tid],
                                          cursors[video_tid],
                                          live_session)
        else:
            npt = self.start_npt
            if video_tid is not None:
                sp = asset.tracks[video_tid]
                vid = sp.seek_id(npt, keyframe=True)
                cursors[video_tid] = vid
                anchor = self._arrival_of(sp, vid, live_session)
        #: a PAUSE-resume (start_ids) whose anchor packet could not be
        #: resolved (retention-evicted window, audio-only stream) must
        #: NOT fall back to the recording start — the elapsed offset
        #: would push every due time that far into the future.  The
        #: first fill() resolves the anchor from the first row served.
        self.anchor_pending = bool(start_ids) and anchor is None
        if anchor is None:
            bases = [sp.base_arrival_ms
                     for sp in asset.tracks.values()
                     if sp.base_arrival_ms is not None]
            anchor = ((min(bases) if bases else 0)
                      + self.start_npt * 1000.0)
        self.anchor_arr = float(anchor)
        for tid, out in outputs.items():
            sp = asset.tracks.get(tid)
            if sp is None:
                continue
            if tid not in cursors:
                cursors[tid] = self._seek_arrival(sp, self.anchor_arr)
            live_stream = (live_session.streams.get(tid)
                           if live_session is not None else None)
            self.tracks.append(_ShiftTrack(
                self, tid, sp, out, pacer.settings, cursors[tid],
                live_stream=live_stream))
        self._gauge(+1)

    _live = 0

    @classmethod
    def _gauge(cls, d: int) -> None:
        cls._live = max(cls._live + d, 0)
        obs.DVR_TIMESHIFT_SESSIONS.set(cls._live)

    def on_retire(self) -> None:
        self._gauge(-1)

    # ------------------------------------------------------------ seek aux
    @staticmethod
    def _seek_arrival(sp: SpilledTrack, arr_ms: float) -> int:
        """Exact arrival-time seek on a non-anchor track (A/V sync:
        audio enters at the video keyframe's wall instant)."""
        base = sp.base_arrival_ms
        if base is None:
            return 0
        return sp.seek_id(max(arr_ms - base, 0.0) / 1000.0,
                          keyframe=False)

    @staticmethod
    def _arrival_of(sp: SpilledTrack, pkt_id: int,
                    live_session) -> float | None:
        """Original arrival ms of one absolute id — spill window if
        indexed, else the live ring."""
        rows = sp.read_window(pkt_id // sp.k)
        if rows is not None and rows.id_lo <= pkt_id < rows.id_lo + rows.n:
            return float(rows.arrival[pkt_id - rows.id_lo])
        if live_session is not None:
            st = live_session.streams.get(sp.info.track_id)
            if st is not None and st.rtp_ring.valid(pkt_id):
                return float(st.rtp_ring.arrival[
                    st.rtp_ring.slot(pkt_id)])
        return None

    # -------------------------------------------------------------- pacer
    @property
    def packets_sent(self) -> int:
        return sum(tr.out.packets_sent
                   - self._pkts_base.get(id(tr.out), 0)
                   for tr in self.tracks)

    @property
    def catchup_pending(self) -> bool:
        return any(not tr.joined and tr.live_stream is not None
                   for tr in self.tracks)

    def position_npt(self) -> float:
        """Seconds past recording start of the newest packet served —
        what a PAUSE on this session bookmarks."""
        arrs = [tr.position_arr() for tr in self.tracks
                if tr.position_arr() is not None]
        bases = [sp.base_arrival_ms
                 for sp in self.asset.tracks.values()
                 if sp.base_arrival_ms is not None]
        if not arrs or not bases:
            return self.start_npt
        return max(max(arrs) - min(bases), 0) / 1000.0

    def cursor_ids(self) -> dict[int, int]:
        """Per-track absolute cursor ids (the PAUSE bookmark)."""
        return {tr.track_id: tr.cursor for tr in self.tracks}

    def pause_ids(self) -> dict[int, int]:
        """Per-track resume cursors for a PAUSE on this session: the
        next absolute id the PLAYER has not received — the fill cursor
        minus the shift ring's filled-but-unsent backlog — so a resume
        neither skips content nor re-sends what was delivered.  (If an
        unspilled gap was hopped inside the backlog this errs toward a
        small overlap, which the affine rewrite turns into duplicate
        out-seqs a player drops; skipping would be silent loss.)  A
        joined track's live bookmark is already that id."""
        out: dict[int, int] = {}
        for tr in self.tracks:
            if tr.joined:
                bm = tr.out.bookmark
                out[tr.track_id] = (int(bm) if bm is not None
                                    else tr.cursor)
            else:
                ring = tr.stream.rtp_ring
                bm = tr.out.bookmark
                base = (ring.tail if bm is None
                        else max(min(bm, ring.head), ring.tail))
                out[tr.track_id] = max(tr.cursor - (ring.head - base), 0)
        return out

    def tick(self, now_ms: int) -> None:
        if self.stopped or self.done:
            return
        horizon = now_ms + self.pacer.lookahead_ms
        done = True
        for tr in self.tracks:
            tr.fill(self, now_ms, horizon)
            if not (tr.joined or tr.done):
                done = False
        self.done = done

    def start(self) -> None:             # FileSession API parity
        pass

    def stop(self) -> None:
        self.pacer.retire(self)


__all__ = ["TimeShiftSession", "SHIFT_RING_CAPACITY"]
