"""DVR window spill: live ring windows → on-disk packed-window store.

The reference is a recorder as much as a relay (DSS file serving +
``RtspRecordModule``), but a live stream's past was gone the moment the
ring head advanced.  Here completed ring windows — the same absolute-id
grid ``[w·k, (w+1)·k)`` the FEC tier protects — are snapshot **already
in the fixed-slot packed format** (the ``CachedWindow`` parallel-array
layout from ``vod/cache.py``: payload bytes + length/flags/ts/seq/
arrival per packet) and appended to a per-(asset, track) spill file
with an index record per window.  The PR 10 pack-at-open cost is paid
once, at record time; a re-open is a plain memcpy — ``pack_window`` is
never invoked for a spilled asset (counter-pinned by the tests).

Layout per ``<dvr_root>/<path>/track<id>/``:

* ``spill.bin``   — append-only window blobs (magic ∥ u32 n ∥ int32
  length[n] ∥ int32 flags[n] ∥ int32 seq[n] ∥ int64 ts[n] ∥ int64
  arrival_ms[n] ∥ payload bytes, tightly packed)
* ``index.json``  — atomic tmp+rename per update: window → file offset,
  packet count, ts/arrival ranges, keyframe ids, plus the track's
  ``StreamInfo`` snapshot and a ``complete`` flag set at finalize.

Retention is a per-track byte + duration budget: oldest windows drop
from the index first (``dvr_retention_evictions_total``); when dead
bytes exceed live bytes the bin file is compacted (copy live blobs,
tmp+rename).  The index is the source of truth — a crash between a
blob append and its index write loses only that window.
"""

from __future__ import annotations

import json
import os
import struct
import time
import zlib

import numpy as np

from .. import obs
from ..obs import PROFILER
from ..protocol.sdp import StreamInfo
from ..relay.ring import SLOT_SIZE

BLOB_MAGIC = b"EDWN"
INDEX_VERSION = 1
#: per-packet metadata row in the blob: i32 length/flags/seq + i64 ts/arr
_META = struct.Struct("<4sI")


class SpillError(RuntimeError):
    """A spill file/index that cannot be read (corrupt, version skew)."""


class WindowRows:
    """One window's packets as the fixed-slot parallel arrays — the
    exchange format between the live ring, the spill file and the
    segment cache.  ``id_lo`` is the absolute ring id of row 0, so the
    live ring IS the hot tail and the spill the cold tail of one
    continuous id space."""

    __slots__ = ("id_lo", "data", "length", "flags", "ts", "seq",
                 "arrival", "restored")

    def __init__(self, id_lo: int, data, length, flags, ts, seq,
                 arrival):
        self.id_lo = id_lo
        #: True when these rows were erasure-RECONSTRUCTED from fleet
        #: shards rather than read from a spill file / live peer
        self.restored = False
        self.data = data                # [n, SLOT_SIZE] uint8
        self.length = length            # int32 [n]
        self.flags = flags              # int32 [n]
        self.ts = ts                    # int64 [n]
        self.seq = seq                  # int32 [n]
        self.arrival = arrival          # int64 [n], relay arrival ms

    @property
    def n(self) -> int:
        return len(self.length)

    def keyframe_rels(self) -> list[int]:
        from ..relay.ring import PacketFlags
        return [int(i) for i in
                np.nonzero(self.flags & PacketFlags.KEYFRAME_FIRST)[0]]


def snapshot_window(ring, lo: int, hi: int) -> WindowRows:
    """Copy ring ids ``[lo, hi)`` out as a :class:`WindowRows` — one
    fancy-index pass per parallel array, no per-packet Python."""
    lo = max(lo, ring.tail)
    hi = min(hi, ring.head)
    idx = (np.arange(lo, hi) % ring.capacity).astype(np.int64)
    return WindowRows(
        lo, ring.data[idx].copy(), ring.length[idx].copy(),
        ring.flags[idx].copy(), ring.timestamp[idx].copy(),
        ring.seq[idx].copy(), ring.arrival[idx].copy())


def encode_blob(rows: WindowRows) -> bytes:
    """Tightly-packed window blob: metadata arrays + concatenated
    payload bytes (no slot padding on disk)."""
    n = rows.n
    out = bytearray(_META.pack(BLOB_MAGIC, n))
    out += rows.length.astype("<i4").tobytes()
    out += rows.flags.astype("<i4").tobytes()
    out += rows.seq.astype("<i4").tobytes()
    out += rows.ts.astype("<i8").tobytes()
    out += rows.arrival.astype("<i8").tobytes()
    for i in range(n):
        out += rows.data[i, :int(rows.length[i])].tobytes()
    return bytes(out)


def decode_blob(blob: bytes, id_lo: int) -> WindowRows:
    """Inverse of :func:`encode_blob`: a memcpy scatter back into
    fixed-slot rows.  This is NOT a repack — no packetizer, no
    classification; the rows were born packed at record time."""
    magic, n = _META.unpack_from(blob, 0)
    if magic != BLOB_MAGIC:
        raise SpillError("bad window blob magic")
    off = _META.size
    length = np.frombuffer(blob, "<i4", n, off).astype(np.int32)
    off += 4 * n
    flags = np.frombuffer(blob, "<i4", n, off).astype(np.int32)
    off += 4 * n
    seq = np.frombuffer(blob, "<i4", n, off).astype(np.int32)
    off += 4 * n
    ts = np.frombuffer(blob, "<i8", n, off).astype(np.int64)
    off += 8 * n
    arrival = np.frombuffer(blob, "<i8", n, off).astype(np.int64)
    off += 8 * n
    data = np.zeros((n, SLOT_SIZE), np.uint8)
    for i in range(n):
        ln = int(length[i])
        if off + ln > len(blob):
            raise SpillError("truncated window blob")
        data[i, :ln] = np.frombuffer(blob, np.uint8, ln, off)
        off += ln
    return WindowRows(id_lo, data, length, flags, ts, seq, arrival)


def _info_to_meta(info: StreamInfo) -> dict:
    return {"media_type": info.media_type,
            "payload_type": info.payload_type,
            "payload_name": info.payload_name, "codec": info.codec,
            "clock_rate": info.clock_rate, "track_id": info.track_id,
            "fmtp": info.fmtp}


def _meta_to_info(meta: dict) -> StreamInfo:
    return StreamInfo(
        media_type=meta.get("media_type", "video"),
        payload_type=int(meta.get("payload_type", 96)),
        payload_name=meta.get("payload_name", ""),
        codec=meta.get("codec", ""),
        clock_rate=int(meta.get("clock_rate", 90000)),
        track_id=int(meta.get("track_id", 1)),
        fmtp=meta.get("fmtp", ""))


class SpillWriter:
    """Append-only per-track spill file + atomically-updated index."""

    def __init__(self, dir_path: str, info: StreamInfo, *,
                 window_pkts: int, retention_bytes: int = 64 << 20,
                 retention_sec: float = 300.0,
                 compact_floor_bytes: int = 1 << 20, gen: int = 0):
        self.dir = dir_path
        os.makedirs(dir_path, exist_ok=True)
        self.bin_path = os.path.join(dir_path, "spill.bin")
        self.index_path = os.path.join(dir_path, "index.json")
        self.k = int(window_pkts)
        self.retention_bytes = int(retention_bytes)
        self.retention_sec = float(retention_sec)
        #: dead bytes below this never trigger a copy (compaction is
        #: amortization, not tidiness)
        self.compact_floor_bytes = int(compact_floor_bytes)
        self.info = info
        #: recording generation (DvrManager meta): a reader of the
        #: PREVIOUS generation must not adopt this index on reload
        self.gen = int(gen)
        self.windows: list[dict] = []
        self.live_bytes = 0
        self.dead_bytes = 0
        self.evictions = 0
        self.compactions = 0
        self.complete = False
        # a writer always starts a FRESH asset (arm after finalize):
        # truncate — appending after a previous asset's blobs would
        # leave an unaccounted dead prefix no retention/compaction
        # budget ever reclaims (the index is overwritten regardless,
        # so those bytes were unreachable anyway)
        self._f = open(self.bin_path, "wb")

    # ------------------------------------------------------------- append
    def append_window(self, win: int, rows: WindowRows) -> dict:
        blob = encode_blob(rows)
        off = self._f.tell()
        self._f.write(blob)
        self._f.flush()
        rec = {"win": int(win), "off": off, "nbytes": len(blob),
               "crc": zlib.crc32(blob) & 0xFFFFFFFF,
               "n": rows.n, "id_lo": int(rows.id_lo),
               "ts_lo": int(rows.ts[0]) if rows.n else 0,
               "ts_hi": int(rows.ts[-1]) if rows.n else 0,
               "arr_lo": int(rows.arrival[0]) if rows.n else 0,
               "arr_hi": int(rows.arrival[-1]) if rows.n else 0,
               "kf": rows.keyframe_rels()}
        self.windows.append(rec)
        self.live_bytes += len(blob)
        self._retain()
        self._write_index()
        return rec

    def _retain(self) -> None:
        """Oldest-first retention by bytes and duration; compaction when
        the dead prefix outweighs the live tail."""
        if not self.windows:
            return
        newest_arr = self.windows[-1]["arr_hi"]
        horizon = newest_arr - self.retention_sec * 1000.0
        while len(self.windows) > 1 and (
                self.live_bytes > self.retention_bytes
                or self.windows[0]["arr_hi"] < horizon):
            rec = self.windows.pop(0)
            self.live_bytes -= rec["nbytes"]
            self.dead_bytes += rec["nbytes"]
            self.evictions += 1
            obs.DVR_RETENTION_EVICTIONS.inc()
        if self.dead_bytes > max(self.live_bytes,
                                 self.compact_floor_bytes):
            self._compact()

    def _compact(self) -> None:
        """Rewrite the bin file with only the live windows (tmp+rename);
        offsets in the index records are rebuilt."""
        tmp = self.bin_path + ".tmp"
        self._f.flush()
        with open(self.bin_path, "rb") as src, open(tmp, "wb") as dst:
            for rec in self.windows:
                src.seek(rec["off"])
                rec["off"] = dst.tell()
                dst.write(src.read(rec["nbytes"]))
        self._f.close()
        os.replace(tmp, self.bin_path)
        self._f = open(self.bin_path, "ab")
        self.dead_bytes = 0
        self.compactions += 1
        self._write_index()

    # -------------------------------------------------------------- index
    def _doc(self) -> dict:
        return {"version": INDEX_VERSION, "k": self.k,
                "complete": self.complete, "gen": self.gen,
                "media": _info_to_meta(self.info),
                "windows": self.windows}

    def _write_index(self) -> None:
        tmp = self.index_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(self._doc(), fh, separators=(",", ":"))
        os.replace(tmp, self.index_path)

    def finalize(self) -> int:
        """Mark the asset complete (instant stream-to-VOD: the windows
        are already in the packed serving format).  Returns the live
        window count."""
        self.complete = True
        self._write_index()
        self._f.close()
        return len(self.windows)

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:
            pass


class SpilledTrack:
    """Read side of one track's spill directory.  ``fetch`` is the
    cluster peer-fill hook: a window absent from the LOCAL index (this
    node never recorded it) may still be served by the recording node's
    spill file — the fetcher returns the raw blob bytes or None.
    ``restore`` is the erasure-coded storage tier's last-resort hook
    (ISSUE 20): when the local file AND the live peer both miss, the
    window blob may still be reconstructable from k surviving fleet
    shards — same ``bytes | b"" (in flight) | None`` protocol."""

    def __init__(self, dir_path: str, *, fetch=None, restore=None):
        self.dir = dir_path
        self.bin_path = os.path.join(dir_path, "spill.bin")
        self.index_path = os.path.join(dir_path, "index.json")
        self.fetch = fetch
        self.restore = restore
        #: latched by read_window: the last miss had a peer fetch IN
        #: FLIGHT (fetcher returned b"") — the caller should hold its
        #: cursor and retry, not hop the window as unavailable
        self.fetch_pending = False
        #: windows whose on-disk bytes failed the index crc32 (ISSUE 20
        #: satellite: truncated/compacted-under-us reads surface here
        #: instead of as decode errors — and the storage scrub leans on
        #: the same checksum)
        self.crc_errors = 0
        #: the asset was re-recorded under this reader (generation
        #: changed on reload): local windows are gone, offsets invalid
        self.superseded = False
        self.gen: int | None = None
        self.reload()

    def reload(self) -> None:
        try:
            with open(self.index_path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as e:
            raise SpillError(f"unreadable index {self.index_path}: {e}")
        if doc.get("version") != INDEX_VERSION:
            raise SpillError(f"index version {doc.get('version')}")
        gen = int(doc.get("gen", 0))
        if self.gen is not None and gen != self.gen:
            # a re-arm truncated spill.bin and restarted the window
            # grid in a NEW ring id space while we were reading the old
            # asset: adopting this index would mix generations (stale
            # cursors against new id_lo values, offsets into a
            # truncated file).  The old asset is simply gone.
            self.superseded = True
            self.windows = {}
            return
        self.gen = gen
        self.k = int(doc["k"])
        self.complete = bool(doc.get("complete"))
        self.info = _meta_to_info(doc.get("media", {}))
        self.windows = {int(r["win"]): r for r in doc.get("windows", ())}

    # ------------------------------------------------------------- ranges
    @property
    def win_lo(self) -> int | None:
        return min(self.windows) if self.windows else None

    @property
    def win_hi(self) -> int | None:
        return max(self.windows) if self.windows else None

    @property
    def base_arrival_ms(self) -> int | None:
        w = self.win_lo
        return self.windows[w]["arr_lo"] if w is not None else None

    def duration_sec(self) -> float:
        if not self.windows:
            return 0.0
        lo, hi = self.win_lo, self.win_hi
        return max(self.windows[hi]["arr_hi"]
                   - self.windows[lo]["arr_lo"], 0) / 1000.0

    def window_blob(self, win: int) -> bytes | None:
        """Raw blob bytes of one indexed window (the REST peer-fill
        endpoint serves exactly this), verified against the index's
        per-window crc32 — a truncated or compacted-under-us read
        returns None (a local miss) instead of bytes that decode into
        garbage or ship corrupt to a peer.  Pre-crc indexes (no ``crc``
        key) read unverified, so old assets stay servable."""
        rec = self.windows.get(int(win))
        if rec is None:
            return None
        try:
            with open(self.bin_path, "rb") as fh:
                fh.seek(rec["off"])
                blob = fh.read(rec["nbytes"])
        except OSError:
            # spill bytes evicted or lost out from under the index: a
            # local miss, so read_window falls through to peer/storage
            return None
        crc = rec.get("crc")
        if crc is not None and (zlib.crc32(blob) & 0xFFFFFFFF) != int(crc):
            self.crc_errors += 1
            return None
        return blob

    def read_window(self, win: int) -> WindowRows | None:
        """Window ``win``'s rows — local spill file first, then the
        peer-fill fetcher.  A miss re-reads the index once: an ARMED
        asset's writer keeps appending after this reader opened (the
        live time-shift case), so staleness is normal, not an error.
        A fetcher returning ``b""`` means the peer round-trip is still
        in flight: ``fetch_pending`` latches and the caller retries.
        When both local file and peer miss, the storage tier's
        ``restore`` hook gets the last word — an erasure reconstruct
        from surviving fleet shards, same in-flight protocol."""
        self.fetch_pending = False
        rec = self.windows.get(int(win))
        if rec is None:
            try:
                self.reload()
            except SpillError:
                pass
            rec = self.windows.get(int(win))
        if rec is not None:
            blob = self.window_blob(win)
            if blob:
                try:
                    return decode_blob(blob, rec["id_lo"])
                except (SpillError, struct.error, ValueError):
                    # truncated/compacted-under-us local read (a bad n
                    # raises ValueError from np.frombuffer, an oversize
                    # length from the row assign): degrade to the
                    # fetcher (or a plain miss), never raise
                    pass
        if self.fetch is not None:
            blob = self.fetch(int(win))
            if blob:
                try:
                    return decode_blob(blob, int(win) * self.k)
                except (SpillError, struct.error, ValueError):
                    return None          # malformed peer blob = a miss
            if blob == b"":
                self.fetch_pending = True
        if self.restore is not None:
            blob = self.restore(int(win))
            if blob:
                try:
                    rows = decode_blob(blob, int(win) * self.k)
                except (SpillError, struct.error, ValueError):
                    return None      # malformed reconstruct = a miss
                rows.restored = True
                return rows
            if blob == b"":
                self.fetch_pending = True
        return None

    def seek_id(self, npt_sec: float, *, keyframe: bool = True) -> int:
        """Absolute packet id for ``npt`` seconds past the recording
        start, snapped back to the nearest keyframe-first packet at or
        before it (video fast-start semantics; ``keyframe=False`` =
        exact).  One window read at most — the keyframe snap works off
        index metadata alone (per-window ``kf`` rel ids + ``id_lo``)."""
        base = self.base_arrival_ms
        if base is None:
            return 0
        target = base + max(npt_sec, 0.0) * 1000.0
        wins = sorted(self.windows)
        cand = wins[0]
        for w in wins:
            if self.windows[w]["arr_lo"] <= target:
                cand = w
            else:
                break
        rec = self.windows[cand]
        rows = self.read_window(cand)
        if rows is None or rows.n == 0:
            exact = rec["id_lo"]
        else:
            rel = int(np.searchsorted(rows.arrival, target,
                                      side="right"))
            exact = rows.id_lo + min(max(rel - 1, 0), rows.n - 1)
        if not keyframe:
            return exact
        for w in reversed([x for x in wins if x <= cand]):
            r = self.windows[w]
            kfset = set(r.get("kf", ()))
            kfs = sorted(k for k in kfset if r["id_lo"] + k <= exact)
            if kfs:
                # SPS/PPS/IDR are EACH keyframe-first (the reference's
                # ReflectorStream classification): snap to the start of
                # the contiguous run, so a replay fast-starts with the
                # parameter sets exactly like a live late-joiner
                k = kfs[-1]
                while k - 1 in kfset:
                    k -= 1
                return r["id_lo"] + k
        return exact

    def close(self) -> None:
        pass


class WindowSpiller:
    """Rides the relay tick for ONE (stream, writer) pair: every time
    the ring head crosses a ``[w·k,(w+1)·k)`` boundary the completed
    window is snapshot and appended.  The per-wake cost when nothing
    completed is one integer compare."""

    def __init__(self, stream, writer: SpillWriter):
        self.stream = stream
        self.writer = writer
        self.k = writer.k
        # the first FULL window at or after arm time — partial windows
        # before arm were never fully observed
        self.next_win = (stream.rtp_ring.head + self.k - 1) // self.k
        self.skipped = 0                 # windows lost to ring eviction
        self.spilled = 0

    def tick(self, now_ms: int, *, max_windows: int = 8) -> int:
        ring = self.stream.rtp_ring
        k = self.k
        done = 0
        while (self.next_win + 1) * k <= ring.head \
                and done < max_windows:
            w = self.next_win
            self.next_win += 1
            if w * k < ring.tail:
                # the pump fell behind the ring's eviction horizon;
                # the window is gone — a retention-shaped loss, not
                # an error (counted so soak can bound it)
                self.skipped += 1
                continue
            t0 = time.perf_counter_ns()
            rows = snapshot_window(ring, w * k, (w + 1) * k)
            self.writer.append_window(w, rows)
            self.spilled += 1
            done += 1
            obs.DVR_WINDOWS_SPILLED.inc()
            dur = time.perf_counter_ns() - t0
            PROFILER.account_pass("dvr", dur, {"spill": dur},
                                  path=self.stream.session_path)
        return done


__all__ = ["SpillWriter", "SpilledTrack", "WindowSpiller", "WindowRows",
           "snapshot_window", "encode_blob", "decode_blob", "SpillError",
           "INDEX_VERSION"]
