"""DVR / time-shift subsystem (ISSUE 12).

Live relay rings spill completed absolute-id windows to disk already in
the fixed-slot packed serving format (``spill``); pause/rewind/catch-up
on live streams and instant stream-to-VOD replay are served by the
shared VOD pacer against those windows (``timeshift``), managed and
wired into the server by ``service``.  See ARCHITECTURE.md §9c.
"""

from .service import DVR_SUFFIX, DvrAsset, DvrManager  # noqa: F401
from .spill import (SpilledTrack, SpillWriter,  # noqa: F401
                    WindowRows, WindowSpiller, decode_blob, encode_blob,
                    snapshot_window)
from .timeshift import TimeShiftSession  # noqa: F401

__all__ = ["DvrManager", "DvrAsset", "DVR_SUFFIX", "SpillWriter",
           "SpilledTrack", "WindowSpiller", "WindowRows",
           "TimeShiftSession", "snapshot_window", "encode_blob",
           "decode_blob"]
